"""
Headline benchmark: autoencoders trained per hour (BASELINE.json metric).

Four stages, each with its own timeout, transient-error retry, and a
partial-result artifact written after every stage so an environment flake
can never zero the whole run:

1. **fleet-train** — the bare fused training program: BENCH_MODELS
   hourglass feedforward autoencoders (the reference's production
   architecture — 20 sensor tags, 10 days of 10-minute data, the
   `examples/config.yaml` shape) trained as ONE vmapped device program.
   Reports models/hour, seconds per training step, achieved FLOP/s and
   MFU (with the arithmetic printed to stderr).
2. **fleet-build-e2e** — the real product path, `FleetBuilder.build` from
   a NormalizedConfig: machine validation, data staging, CV folds +
   DiffBased threshold math, final fit, artifact dump
   (parallel/fleet_build.py). This is the `build-fleet` CLI path the
   north-star target is defined on (BASELINE.md: 1000 AEs < 10 min).
3. **lstm-fleet-train** — BASELINE.json parity configs #3/#4: 50-tag
   sliding-window LSTM autoencoder and forecast fleets with on-device
   window gathering. Rates land in the final line's extras. A separate
   last-priority **lstm-experiments** stage (TPU only) measures the
   segmented stateful-scan path and a recurrence unroll sweep against
   the window-restart baseline.
4. **parity** — the north star's correctness half: the same hourglass AE
   trained on identical data by the reference's Keras/TF2 engine and by
   the JAX engine, both wrapped in DiffBasedAnomalyDetector with the same
   CV + threshold math; reports the anomaly-score MAE / correlation /
   threshold deltas against the reference AND the reference's own
   seed-to-seed envelope (gordo_tpu/compat/tf_parity.py).
5. **reference baseline** — the reference engine's cost measured
   directly: the same architecture / optimizer / batch size / epochs
   trained with Keras/TF2 on CPU (the reference trains every model with
   CPU Keras inside its per-model k8s pod — SURVEY.md §2.9, BASELINE.md).

Prints ONE JSON line:
  {"metric": "autoencoders_trained_per_hour", "value": ..., "unit":
   "models/hour", "vs_baseline": ..., "extra": {...}}

Env knobs: BENCH_MODELS (default 1024), BENCH_E2E_MODELS (default 1000),
BENCH_EPOCHS (20), BENCH_SAMPLES (1440), BENCH_TAGS (20),
BENCH_LSTM_MODELS (256), BENCH_LSTM_TAGS (50), BENCH_LSTM_LOOKBACK (60),
BENCH_LSTM_EPOCHS (5), BENCH_STAGE_TIMEOUT seconds (default 1500),
BENCH_BUDGET total wall-clock seconds (default 460 — stages are clamped
to it and skipped once it runs out), BENCH_TIMED_RUNS best-of-n count,
BENCH_REFRESH_BASELINE=1 to re-measure the Keras baseline instead of
using .bench_baseline.json, BENCH_SKIP_E2E=1 / BENCH_SKIP_LSTM=1 /
BENCH_SKIP_PARITY=1 to skip those stages, BENCH_PARITY_EPOCHS (150) /
BENCH_PARITY_ENVELOPE (1; the TF-vs-TF envelope is cached in
.bench_envelope.json).
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import traceback

from typing import Optional

import numpy as np

# -- global wall-clock budget ----------------------------------------------
#
# The driver runs `python bench.py` under its own hard timeout (round 4
# died at rc=124 with no JSON line). The bench therefore keeps its OWN
# deadline, strictly inside the driver's: every stage timeout is clamped
# to the time remaining, stages that no longer fit are skipped with a
# recorded reason, and SIGTERM/SIGINT emit the final JSON line from
# whatever completed before exiting. The bench must be constitutionally
# unable to end a round without an artifact.
_T0 = time.time()
# 780s: round 4's driver kill landed only after ~675s of stages had run,
# so the external budget is comfortably larger; a too-small internal
# budget would skip stages a live TPU had time for. Overshoot is safe —
# the SIGTERM handler emits the final JSON from completed stages if the
# driver's own timeout fires first. The worst-case CPU-fallback run
# (every stage shrunk) finishes well under this regardless.
BUDGET = int(os.environ.get("BENCH_BUDGET", 780))
_EMIT_RESERVE = 10  # seconds kept back for writing the final JSON line


def _remaining() -> float:
    return BUDGET - (time.time() - _T0)

# 1024 models per fused program: the fleet regime is per-scan-step
# overhead-bound (docs/architecture.md roofline), so per-step cost is
# amortized over the model axis and models/hour scales ~linearly with
# fleet size — the bench measures the design at its intended scale.
N_MODELS = int(os.environ.get("BENCH_MODELS", 1024))
# The north-star scale (BASELINE.md: 1000 AEs from one YAML in <10 min) is
# the DEFAULT e2e demonstration, not an extrapolation from 256.
N_E2E_MODELS = int(os.environ.get("BENCH_E2E_MODELS", 1000))
N_EPOCHS = int(os.environ.get("BENCH_EPOCHS", 20))
N_SAMPLES = int(os.environ.get("BENCH_SAMPLES", 1440))  # 10 days @ 10min
N_TAGS = int(os.environ.get("BENCH_TAGS", 20))
BATCH = 64
# LSTM stage (BASELINE.json parity configs #3/#4: 50-tag sliding window).
# 256 members: the recurrence is per-scan-step overhead-bound like the
# dense fleet, so per-step cost amortizes across the vmapped member axis.
N_LSTM_MODELS = int(os.environ.get("BENCH_LSTM_MODELS", 256))
LSTM_TAGS = int(os.environ.get("BENCH_LSTM_TAGS", 50))
LSTM_LOOKBACK = int(os.environ.get("BENCH_LSTM_LOOKBACK", 60))
LSTM_EPOCHS = int(os.environ.get("BENCH_LSTM_EPOCHS", 5))
STAGE_TIMEOUT = int(os.environ.get("BENCH_STAGE_TIMEOUT", 1500))
_HERE = os.path.dirname(os.path.abspath(__file__))
BASELINE_CACHE = os.path.join(_HERE, ".bench_baseline.json")
ENVELOPE_CACHE = os.path.join(_HERE, ".bench_envelope.json")
PARTIAL_PATH = os.environ.get(
    "BENCH_PARTIAL_PATH", os.path.join(_HERE, ".bench_partial.json")
)

# MXU peak FLOP/s by device kind (dense matmul, bf16 — JAX's default f32
# matmul precision on TPU lowers to bf16 MXU passes). Used only for the
# reported MFU; absent kinds report mfu=null.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e bf16 (394e12 is the int8 peak)
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}

# HBM peak bandwidth by device kind (bytes/s). The tiny-model fleet
# regime is NOT MXU-bound (docs/architecture.md roofline): the relevant
# ceiling is per-step HBM traffic and the per-scan-iteration dispatch
# floor, so the bench reports achieved GB/s against this peak alongside
# the (tiny, expected) MFU.
PEAK_HBM_BPS = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v3": 900e9,
    "TPU v2": 700e9,
}


def log(msg: str):
    print(f"# {msg}", file=sys.stderr, flush=True)


# -- stage harness: subprocess isolation + timeout + retry ------------------
#
# Each stage runs in its own subprocess (`bench.py --stage <name> <out>`).
# A hang inside the JAX/TPU C++ runtime (compile or execute over a dead
# axon tunnel — the exact failure that zeroed round 1) is uninterruptible
# by Python signals in-process, but a subprocess can simply be killed; the
# parent never touches JAX, so later stages still run. When a stage times
# out on the default (TPU) backend, one labeled CPU retry runs so the
# round still gets a number — `extra.device` shows which backend scored.

_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "RESOURCE_EXHAUSTED",
    "ABORTED",
    "Socket closed",
    "Connection reset",
    "failed to connect",
)

STAGES = {}


def stage(fn):
    STAGES[fn.__name__] = fn
    return fn


# Stage sizes for the CPU-fallback regime (dead/wedged accelerator).
# Full-size CPU runs blew round 4's driver budget (1000-machine e2e alone
# was 357s on this 1-core host); these sizes keep the WHOLE bench under
# ~6 minutes worst-case while still exercising every stage. setdefault
# semantics: an explicit BENCH_* env from the operator wins.
_CPU_SHRINK = {
    "BENCH_MODELS": "128",
    "BENCH_E2E_MODELS": "128",
    # The production LSTM geometry (50 tags, lookback 60, 6 stacked
    # 256-wide layers) is ~minutes of FLOPs per epoch on one CPU core —
    # the labeled CPU number only proves the stage executes, so it runs
    # a scaled-down geometry.
    "BENCH_LSTM_MODELS": "8",
    "BENCH_LSTM_TAGS": "10",
    "BENCH_LSTM_LOOKBACK": "12",
    "BENCH_LSTM_DIMS": "32",  # production stack is (256,128,64)×2
    "BENCH_TIMED_RUNS": "1",  # no tunnel jitter on CPU; one timed run
}


def _apply_cpu_shrink(env: dict) -> dict:
    for key, value in _CPU_SHRINK.items():
        env.setdefault(key, value)
    return env


def _run_stage_subprocess(name: str, timeout: int, force_cpu: bool):
    """One attempt: returns (result dict | None, error string | None)."""
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as f:
        out_path = f.name
    env = dict(os.environ)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
        _apply_cpu_shrink(env)
    timed_out = False
    proc = None
    # stages that run optional second passes (e2e steady-state) read this
    # wall-clock deadline to decide whether the extra pass still fits —
    # a duration would ignore the stage's own setup time before the
    # check (imports, machine construction)
    env["BENCH_STAGE_DEADLINE"] = str(time.time() + timeout)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", name, out_path],
            timeout=timeout,
            env=env,
        )
    except subprocess.TimeoutExpired:
        timed_out = True
    payload = None
    try:
        with open(out_path) as f:
            content = f.read()
        os.unlink(out_path)
        payload = json.loads(content) if content else None
    except (OSError, ValueError):
        pass
    if timed_out:
        # a long multi-measurement stage flushes interim results as it
        # goes (_flush_stage); a timeout salvages those instead of
        # discarding completed measurements
        if payload is not None and "error" not in payload:
            payload["timeout_note"] = f"killed at {timeout}s; interim results"
            return payload, None
        return None, f"timeout after {timeout}s (stage subprocess killed)"
    if payload is None or payload.get("interim"):
        # no result — or only an interim flush left behind by a CRASHED
        # process (OOM kill, segfault). Unlike a timeout, a crash is
        # worth the normal retry/CPU-fallback path, which can still
        # produce complete results; accepting the partial here would
        # silently skip both.
        return None, f"stage subprocess died (rc={proc.returncode}) without a result"
    if "error" in payload:
        return None, payload["error"]
    return payload, None


def _stage_budget(timeout: int) -> int:
    """Clamp a stage timeout to the global deadline; <=0 means skip."""
    return int(min(timeout, _remaining() - _EMIT_RESERVE))


def run_stage(partial: dict, name: str, timeout: int = STAGE_TIMEOUT, retries: int = 1):
    """
    Run one bench stage with subprocess isolation, transient-error retry,
    and a final labeled CPU-backend attempt if the accelerator path hung.
    Every attempt's timeout is clamped to the global deadline; a stage
    that no longer fits is skipped with a recorded reason instead of
    running past the driver's budget. Results/failures are recorded into
    ``partial`` and flushed either way.
    """

    def record(error):
        partial[f"{name}_error"] = error
        _flush_partial(partial)

    def accept(result):
        partial[name] = result
        partial.pop(f"{name}_error", None)  # earlier attempts' failures
        _flush_partial(partial)
        return result

    last_error = None
    for attempt in range(retries + 1):
        if _remaining() - _EMIT_RESERVE < 20:
            # Budget exhausted — distinct from a small configured stage
            # timeout, and never allowed to mask a real first-attempt
            # error with a "skipped" message.
            if last_error is None:
                record(f"skipped: {_remaining():.0f}s left of {BUDGET}s budget")
            else:
                record(f"{last_error}; no budget left for a retry")
            log(f"stage {name}: stopping (budget exhausted)")
            return None
        result, error = _run_stage_subprocess(
            name, _stage_budget(timeout), force_cpu=False
        )
        if result is not None:
            return accept(result)
        last_error = error
        record(error)
        log(f"stage {name}: attempt {attempt + 1} failed: {error}")
        # Only the harness's OWN kill sentinel means "wedged backend, stop
        # retrying" — a backend error that merely mentions a timeout (e.g.
        # 'UNAVAILABLE: connection timeout') is still transient-retryable.
        if "(stage subprocess killed)" in error:
            break  # wedged backend stays wedged — don't burn more timeouts
        if not any(marker in error for marker in _TRANSIENT_MARKERS):
            break  # deterministic failure; identical retries won't help
        time.sleep(2 * (attempt + 1))

    backend_shaped = last_error and (
        "timeout" in last_error
        or any(marker in last_error for marker in _TRANSIENT_MARKERS)
    )
    # Only the JAX stages have an accelerator to fall back FROM; re-running
    # the pure-TF reference stage with BENCH_FORCE_CPU would change nothing.
    if backend_shaped and name in (
        "fleet_train",
        "fleet_build_e2e",
        "lstm_fleet_train",
    ):
        if _remaining() - _EMIT_RESERVE < 20:
            record(f"{last_error}; cpu fallback skipped (budget exhausted)")
            return None
        fallback_timeout = _stage_budget(timeout)
        log(f"stage {name}: accelerator path failed; labeled CPU fallback")
        result, error = _run_stage_subprocess(name, fallback_timeout, force_cpu=True)
        if result is not None:
            # keep the accelerator failure visible next to the CPU number
            partial[f"{name}_note"] = f"cpu fallback after: {last_error}"
            return accept(result)
        record(f"{last_error}; cpu fallback: {error}")
        log(f"stage {name}: cpu fallback failed: {error}")
    return None


#: Set by _stage_entry: long multi-measurement stages flush interim
#: results here (via _flush_stage) so a timeout kill salvages completed
#: measurements — the parent reads whatever was last written.
_STAGE_OUT_PATH: Optional[str] = None


def _write_json_atomic(path: str, payload: dict):
    """tmp + os.replace so a kill mid-write can never leave truncated
    JSON — every observable file state is a complete document."""
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, default=str)
    os.replace(tmp, path)


def _flush_stage(payload: dict):
    """Write a stage's in-progress results; marked interim until the
    stage returns normally (the final write overwrites)."""
    if _STAGE_OUT_PATH:
        _write_json_atomic(_STAGE_OUT_PATH, {**payload, "interim": True})


def _stage_entry(name: str, out_path: str) -> int:
    """Subprocess side: run one stage, write its JSON result or error."""
    global _STAGE_OUT_PATH
    _STAGE_OUT_PATH = out_path
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax

        # Env vars are not enough: the axon plugin overrides platform
        # selection through jax.config, so set it explicitly.
        jax.config.update("jax_platforms", "cpu")
        log(f"stage {name}: forced CPU backend")
    try:
        result = STAGES[name]()
        payload = result
    except Exception as exc:  # noqa: BLE001 - report, don't crash silently
        traceback.print_exc(file=sys.stderr)
        error = f"{type(exc).__name__}: {exc}"
        # A late failure must not clobber measurements already flushed:
        # keep them and note the error under a non-"error" key so the
        # parent accepts the partials (the error key would discard them).
        prior = None
        try:
            with open(out_path) as f:
                prior = json.loads(f.read() or "null")
        except (OSError, ValueError):
            pass
        if isinstance(prior, dict) and prior.get("interim"):
            payload = {**prior, "stage_error": error}
            payload.pop("interim", None)
        else:
            payload = {"error": error}
    _write_json_atomic(out_path, payload)
    return 0


def _flush_partial(partial: dict):
    try:
        with open(PARTIAL_PATH, "w") as f:
            json.dump(partial, f, indent=2, default=str)
    except OSError as exc:
        log(f"could not write partial artifact: {exc}")


# -- data -------------------------------------------------------------------


def _timed_best(trainer, members, config, n=None):
    """Best of n timed training runs: tunneled-accelerator transfer latency
    varies ±50% run to run, so a single sample misreports the engine.
    (The CPU-fallback regime sets BENCH_TIMED_RUNS=1 — no tunnel, no
    jitter, and repeat runs there only burn the driver's budget.)"""
    if n is None:
        n = int(os.environ.get("BENCH_TIMED_RUNS", 3))
    best, results = None, None
    for _ in range(n):
        start = time.time()
        r = trainer.train(members, config)
        dt = time.time() - start
        if best is None or dt < best:
            best, results = dt, r
    return best, results


def make_data(n_models: int):
    rng = np.random.RandomState(42)
    t = np.linspace(0, 12 * np.pi, N_SAMPLES, dtype=np.float32)
    data = []
    for i in range(n_models):
        phases = rng.uniform(0, 2 * np.pi, N_TAGS).astype(np.float32)
        amp = rng.uniform(0.5, 2.0, N_TAGS).astype(np.float32)
        X = amp * np.sin(t[:, None] + phases) + 0.05 * rng.standard_normal(
            (N_SAMPLES, N_TAGS)
        ).astype(np.float32)
        data.append(X)
    return data


def _device_desc() -> str:
    import jax

    d = jax.devices()
    return f"{len(d)}x {d[0].device_kind}"


def _setup_jax_cache():
    # CPU runs skip the persistent cache: XLA:CPU AOT entries embed the
    # compile host's machine features, and loading them on a different
    # host spams feature-mismatch errors (and risks SIGILL) — exactly the
    # noise in round 4's rc=124 tail. TPU programs have no such coupling.
    if os.environ.get("BENCH_FORCE_CPU"):
        return
    import jax

    # Persistent compilation cache: the fleet program for a (spec, shape)
    # compiles once per machine ever, not once per process.
    jax.config.update("jax_compilation_cache_dir", os.path.join(_HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# -- stage 0: backend pre-flight -------------------------------------------


@stage
def backend_probe() -> dict:
    """A pure host↔device transfer round trip — deliberately NO XLA
    compile, so a live-but-cold accelerator answers in well under a
    second (~2×67ms on the axon tunnel) and the probe timeout can be
    short. If even this hangs, the tunnel is wedged and every later
    stage should go straight to CPU instead of burning a full stage
    timeout each first."""
    import jax

    x = jax.device_put(np.arange(8, dtype=np.float32))
    value = float(np.asarray(x).sum())
    return {"device": _device_desc(), "checksum": value}


# -- stage 1: bare fleet training ------------------------------------------


@stage
def fleet_train() -> dict:
    """Bare fused-training throughput on the available accelerator."""
    from gordo_tpu.models.factories import feedforward_hourglass
    from gordo_tpu.models.training import FitConfig
    from gordo_tpu.parallel import FleetMember, FleetTrainer
    from gordo_tpu.parallel.fleet import _round_up_pow2

    import jax

    _setup_jax_cache()

    spec = feedforward_hourglass(N_TAGS)
    config = FitConfig(epochs=N_EPOCHS, batch_size=BATCH, shuffle=True)
    data = make_data(N_MODELS)
    members = [
        FleetMember(name=f"m{i}", spec=spec, X=X, y=X, seed=i)
        for i, X in enumerate(data)
    ]
    trainer = FleetTrainer()

    # Warmup with the SAME member count and shapes: the vmapped program's
    # model axis is part of the compiled shape, so a smaller warmup fleet
    # would leave XLA compilation inside the measured section.
    trainer.train(members, config)

    elapsed, results = _timed_best(trainer, members, config)

    losses = [r.history.history["loss"][-1] for r in results]
    assert all(np.isfinite(losses)), "non-finite training losses"

    # Block-diagonal packing (models/packing.py): same fleet, MXU tiles
    # filled laterally with G models per matmul. Reported alongside the
    # baseline so the headroom is visible, per-seat.
    packed_elapsed = None
    packing = os.environ.get("BENCH_PACKING", "auto")
    # MXU/HBM experiments only make sense on a TPU: packing measurably
    # loses on CPU (real extra FLOPs, no tiles) and bf16 is emulated
    # there — on the CPU-fallback path they would only burn the stage
    # timeout, so they are skipped and reported as null.
    on_tpu = jax.default_backend() == "tpu"
    # "0"/"1" both mean "no packing" — a factor of 1 IS the unpacked
    # program, and timing it twice would just report jitter as speedup.
    if on_tpu and packing not in ("0", "1"):
        packed_trainer = FleetTrainer(
            packing=packing if packing == "auto" else int(packing)
        )
        packed_trainer.train(members, config)  # warmup/compile
        packed_elapsed, packed_results = _timed_best(packed_trainer, members, config)
        packed_losses = [r.history.history["loss"][-1] for r in packed_results]
        assert all(np.isfinite(packed_losses)), "non-finite packed losses"

    # Mixed-precision (bf16 compute, f32 master params): same fleet with
    # compute_dtype=bfloat16 — in the HBM-bound regime the win is bounded
    # by how much of the per-step traffic is activations/data vs the f32
    # param+moment state (docs/architecture.md roofline).
    bf16_elapsed = None
    if on_tpu and os.environ.get("BENCH_BF16", "1") == "1":
        bf16_spec = feedforward_hourglass(N_TAGS, compute_dtype="bfloat16")
        bf16_members = [
            FleetMember(name=f"m{i}", spec=bf16_spec, X=X, y=X, seed=i)
            for i, X in enumerate(data)
        ]
        trainer.train(bf16_members, config)  # warmup/compile
        bf16_elapsed, bf16_results = _timed_best(trainer, bf16_members, config)
        bf16_losses = [r.history.history["loss"][-1] for r in bf16_results]
        assert all(np.isfinite(bf16_losses)), "non-finite bf16 losses"

    # -- MFU arithmetic (all counted, none assumed; ADVICE.md r2) ----------
    # Dense-weight parameter count of one model:
    weight_elems = sum(
        int(np.asarray(leaf).size)
        for leaf in jax.tree_util.tree_leaves(results[0].params)
        if np.asarray(leaf).ndim == 2
    )
    # The compiled program trains the PADDED sample axis (zero-weight rows
    # still run through the MXU), so executed FLOPs use n_padded:
    n_padded = _round_up_pow2(N_SAMPLES, BATCH)
    steps_per_epoch = n_padded // BATCH
    # fwd = 2*W FLOPs/sample; backward ≈ 2×fwd; + one val forward pass
    # over the padded set per epoch = 2*W*n_padded. These are USEFUL
    # per-model FLOPs — packing executes extra zero-block FLOPs that are
    # deliberately not counted as achieved work.
    flops_per_model = N_EPOCHS * (6 * weight_elems * n_padded + 2 * weight_elems * n_padded)
    total_flops = flops_per_model * N_MODELS

    # The headline (and its derived step/FLOP/MFU figures) describe the
    # BEST of the unpacked and packed runs, labeled via `mode`.
    best_elapsed = min(elapsed, packed_elapsed or elapsed)
    mode = "packed" if packed_elapsed is not None and packed_elapsed < elapsed else "unpacked"
    achieved = total_flops / best_elapsed
    device_kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(device_kind)
    mfu = achieved / (peak * len(jax.devices())) if peak else None
    step_time_s = best_elapsed / (N_EPOCHS * steps_per_epoch)

    # -- HBM roofline (the bound the architecture targets; VERDICT r4) -----
    # Per training step per member, counted analytically: f32 params and
    # both Adam moments are read and written (optimizer update), and the
    # batch (X, y) is read. The per-epoch shuffle rewrite of the staged
    # arrays amortizes over the epoch's steps. Fused activations stay
    # on-chip and are deliberately not counted — this is the *traffic
    # floor*, so achieved-GB/s is a lower bound.
    param_elems = sum(
        int(np.asarray(leaf).size)
        for leaf in jax.tree_util.tree_leaves(results[0].params)
    )
    bytes_step_member = (
        4 * param_elems * (2 + 4)  # params r+w, two moments r+w
        + 2 * 4 * BATCH * N_TAGS  # batch X and y read
        + (4 * 4 * n_padded * N_TAGS) / steps_per_epoch  # shuffle gather r+w
    )
    bytes_per_step = N_MODELS * bytes_step_member
    hbm_peak = PEAK_HBM_BPS.get(device_kind)
    achieved_hbm = bytes_per_step / step_time_s
    hbm_pct = achieved_hbm / (hbm_peak * len(jax.devices())) if hbm_peak else None
    # dispatch floor = what the step would cost if HBM were the only
    # limit; the residual is per-scan-iteration overhead (the measured
    # bound of this regime — docs/architecture.md)
    hbm_floor_ms = (
        bytes_per_step / (hbm_peak * len(jax.devices())) * 1e3 if hbm_peak else None
    )
    log(
        f"roofline ({mode}): {bytes_per_step / 1e6:.2f} MB/step analytic floor "
        f"-> {achieved_hbm / 1e9:.1f} GB/s achieved"
        + (
            f" = {hbm_pct * 100:.1f}% of {hbm_peak / 1e9:.0f} GB/s peak; "
            f"HBM-floor step {hbm_floor_ms:.3f} ms vs measured "
            f"{step_time_s * 1e3:.3f} ms -> per-step overhead "
            f"{step_time_s * 1e3 - hbm_floor_ms:.3f} ms"
            if hbm_peak
            else " (no HBM peak table entry for this device)"
        )
    )

    log(
        f"fleet: {N_MODELS} AEs x {N_EPOCHS} epochs in {elapsed:.2f}s "
        f"(final loss mean {np.mean(losses):.5f}) on {_device_desc()}"
    )
    if packed_elapsed is not None:
        log(
            f"packed fleet: same workload in {packed_elapsed:.2f}s "
            f"({elapsed / packed_elapsed:.2f}x vs unpacked)"
        )
    if bf16_elapsed is not None:
        log(
            f"bf16 fleet: same workload in {bf16_elapsed:.2f}s "
            f"({elapsed / bf16_elapsed:.2f}x vs f32)"
        )
    log(
        f"mfu arithmetic ({mode} run): W={weight_elems} dense weights/model, "
        f"n_padded={n_padded} (from {N_SAMPLES}), steps/epoch={steps_per_epoch}, "
        f"useful flops/model = {N_EPOCHS}*(6+2)*{weight_elems}*{n_padded} = {flops_per_model:.3e}, "
        f"achieved {achieved / 1e9:.1f} GFLOP/s vs peak "
        f"{peak / 1e12 if peak else float('nan'):.0f} TFLOP/s ({device_kind}) "
        f"-> MFU {mfu * 100 if mfu else float('nan'):.4f}%"
    )
    return {
        "models_per_hour": N_MODELS / (best_elapsed / 3600.0),
        "mode": mode,
        "elapsed_s": round(best_elapsed, 3),
        "unpacked_elapsed_s": round(elapsed, 3),
        "unpacked_models_per_hour": round(N_MODELS / (elapsed / 3600.0), 1),
        "packed_elapsed_s": (
            round(packed_elapsed, 3) if packed_elapsed is not None else None
        ),
        "packed_speedup": (
            round(elapsed / packed_elapsed, 3) if packed_elapsed else None
        ),
        "bf16_elapsed_s": (
            round(bf16_elapsed, 3) if bf16_elapsed is not None else None
        ),
        "bf16_speedup": (
            round(elapsed / bf16_elapsed, 3) if bf16_elapsed else None
        ),
        "step_time_ms": round(step_time_s * 1e3, 4),
        "achieved_gflops": round(achieved / 1e9, 2),
        "mfu": round(mfu, 6) if mfu is not None else None,
        "roofline": {
            "bytes_per_step": int(bytes_per_step),
            "achieved_hbm_gbps": round(achieved_hbm / 1e9, 2),
            "hbm_roofline_pct": (
                round(hbm_pct * 100, 2) if hbm_pct is not None else None
            ),
            "hbm_floor_step_ms": (
                round(hbm_floor_ms, 4) if hbm_floor_ms is not None else None
            ),
            "overhead_step_ms": (
                round(step_time_s * 1e3 - hbm_floor_ms, 4)
                if hbm_floor_ms is not None
                else None
            ),
            "steps_per_second": round(1.0 / step_time_s, 1),
        },
        "device": _device_desc(),
        "flops_per_model": flops_per_model,
        "weight_elems": weight_elems,
        "n_padded": n_padded,
    }


# -- stage 2: end-to-end fleet build ---------------------------------------


@stage
def fleet_build_e2e() -> dict:
    """
    The product path from config to artifacts: NormalizedConfig machine
    validation -> data staging -> CV folds + thresholds -> final fit ->
    artifact dump, timed end to end (parallel/fleet_build.py).
    """
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import FleetBuilder

    _setup_jax_cache()

    # The reference production shape: DiffBased detector over an hourglass
    # AE, 3-fold TimeSeriesSplit CV + final fit (SURVEY.md §2.1/§2.3).
    model_def = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "epochs": N_EPOCHS,
                    "batch_size": BATCH,
                }
            }
        }
    }
    machines = [
        Machine.from_config(
            {
                "name": f"bench-machine-{i:04d}",
                "model": model_def,
                "dataset": {
                    "type": "RandomDataset",
                    "train_start_date": "2020-01-01T00:00:00+00:00",
                    "train_end_date": "2020-01-11T00:00:00+00:00",
                    "tag_list": [f"bench-tag-{i:04d}-{j}" for j in range(N_TAGS)],
                },
            },
            project_name="bench",
        )
        for i in range(N_E2E_MODELS)
    ]

    with tempfile.TemporaryDirectory() as output_dir:
        start = time.time()
        builder = FleetBuilder(machines)
        results = builder.build(output_dir=output_dir)
        elapsed = time.time() - start
        n_artifacts = sum(
            os.path.isfile(os.path.join(output_dir, m.name, "model.pkl"))
            for _, m in results
        )

    if builder.build_errors:
        raise RuntimeError(f"e2e build errors: {builder.build_errors}")
    if n_artifacts != N_E2E_MODELS:
        raise RuntimeError(f"expected {N_E2E_MODELS} artifacts, found {n_artifacts}")

    # Steady-state second run (TPU only — doubling the CPU-fallback run
    # would blow the stage timeout): the first run pays one-time XLA
    # compiles that a long-lived build service amortizes; the second run
    # is the engine's recurring cost. Machines are rebuilt so no staged
    # data is reused.
    import jax

    steady_elapsed = None
    # cold-result record, shared between the interim flush and the final
    # return so a salvaged artifact can never disagree with a normal one
    cold_result = {
        "models_per_hour": N_E2E_MODELS / (elapsed / 3600.0),
        "elapsed_s": round(elapsed, 3),
        "cold_elapsed_s": round(elapsed, 3),
        "steady_elapsed_s": None,
        "n_machines": N_E2E_MODELS,
        "device": _device_desc(),
    }
    # the cold number is salvageable from here on even if the steady
    # pass is killed mid-run (interim flush; see _flush_stage)
    _flush_stage(cold_result)
    steady_wanted = jax.default_backend() == "tpu" and not os.environ.get(
        "BENCH_E2E_COLD_ONLY"
    )
    # the steady pass re-runs the whole build; skip it when it no longer
    # fits the wall-clock deadline — a half-finished steady run would be
    # killed and lose its number (the cold one survives via the flush)
    stage_remaining = (
        float(os.environ.get("BENCH_STAGE_DEADLINE", "inf")) - time.time()
    )
    steady_fits = elapsed < 0.7 * stage_remaining
    if steady_wanted and not steady_fits:
        log(
            f"e2e steady-state skipped: cold took {elapsed:.0f}s with only "
            f"{stage_remaining:.0f}s of the stage window left"
        )
    if steady_wanted and steady_fits:
        machines = [machine.copy() for machine in machines]
        with tempfile.TemporaryDirectory() as output_dir:
            start = time.time()
            builder = FleetBuilder(machines)
            builder.build(output_dir=output_dir)
            steady_elapsed = time.time() - start
        if builder.build_errors:
            raise RuntimeError(f"steady e2e build errors: {builder.build_errors}")
        log(
            f"e2e steady-state (warm compile caches): {N_E2E_MODELS} machines "
            f"in {steady_elapsed:.2f}s "
            f"-> {N_E2E_MODELS / (steady_elapsed / 3600.0):.0f} models/hour"
        )

    # phases describe the LAST build that ran (the steady-state one on
    # TPU) — pair the host/device split with that run's wall time
    phase_elapsed = steady_elapsed if steady_elapsed is not None else elapsed
    phases = {k: round(v, 3) for k, v in sorted(builder.phase_seconds.items())}
    device_s = sum(
        phases.get(k, 0.0) for k in ("cv_train", "cv_predict", "final_fit")
    )
    host_s = max(phase_elapsed - device_s, 0.0)
    log(
        f"e2e fleet build: {N_E2E_MODELS} machines (CV 3 folds + final fit "
        f"+ artifacts) in {elapsed:.2f}s cold on {_device_desc()}"
    )
    log(
        f"e2e phases ({phase_elapsed:.2f}s run): {phases} -> device-program "
        f"{device_s:.1f}s, host {host_s:.1f}s "
        f"({100 * host_s / max(phase_elapsed, 1e-9):.0f}%)"
    )
    best_elapsed = min(elapsed, steady_elapsed or elapsed)
    return {
        **cold_result,
        "models_per_hour": N_E2E_MODELS / (best_elapsed / 3600.0),
        "elapsed_s": round(best_elapsed, 3),
        "steady_elapsed_s": (
            round(steady_elapsed, 3) if steady_elapsed is not None else None
        ),
        "phases": phases,
        "device_program_s": round(device_s, 3),
        "host_s": round(host_s, 3),
    }


# -- stage 2b: LSTM fleet (parity configs #3/#4) ----------------------------


def _lstm_fleet_setup():
    """
    The ONE LSTM fleet definition both LSTM stages measure — the
    experiments stage's restart baseline is only comparable to the core
    `lstm_ae` rate because they share this geometry verbatim.

    Returns ``(members, config, n_lstm, lstm_kwargs)`` where ``members``
    is a ``members(lookahead)`` factory.
    """
    from gordo_tpu.models.factories import lstm_model
    from gordo_tpu.models.training import FitConfig
    from gordo_tpu.ops.windows import window_targets
    from gordo_tpu.parallel import WindowedFleetMember

    import jax

    # The 256-member default is sized for a TPU; the CPU-fallback path
    # (dead accelerator tunnel) caps the fleet so the labeled CPU number
    # lands inside the stage timeout instead of zeroing the stage.
    n_lstm = N_LSTM_MODELS
    if jax.default_backend() != "tpu":
        n_lstm = min(n_lstm, 8)
        log(f"lstm setup: CPU backend, capping fleet at {n_lstm} members")

    # shuffle=False: the product LSTM path pins it (estimators.py — the
    # reference fits its timeseries generator unshuffled), so the bench
    # must time the same compiled program the product runs.
    config = FitConfig(epochs=LSTM_EPOCHS, batch_size=BATCH, shuffle=False)
    rng = np.random.RandomState(0)
    series = [
        rng.rand(N_SAMPLES, LSTM_TAGS).astype(np.float32)
        for _ in range(n_lstm)
    ]

    # Layer widths (production default (256,128,64) mirrored); the CPU
    # fallback shrinks them — a 6×256-wide stack is minutes of FLOPs per
    # epoch on one core.
    dims = tuple(
        int(d)
        for d in os.environ.get("BENCH_LSTM_DIMS", "256,128,64").split(",")
    )
    lstm_kwargs = dict(
        lookback_window=LSTM_LOOKBACK,
        encoding_dim=dims,
        encoding_func=("tanh",) * len(dims),
        decoding_dim=dims[::-1],
        decoding_func=("tanh",) * len(dims),
    )

    def members(lookahead: int):
        # the spec carries lookback only; lookahead lives in the targets
        # alignment (ops.windows.window_targets)
        spec = lstm_model(LSTM_TAGS, **lstm_kwargs)
        return [
            WindowedFleetMember(
                name=f"lstm{i}",
                spec=spec,
                series=X,
                targets=window_targets(X, LSTM_LOOKBACK, lookahead),
                seed=i,
            )
            for i, X in enumerate(series)
        ]

    return members, config, n_lstm, lstm_kwargs


@stage
def lstm_fleet_train() -> dict:
    """
    BASELINE.json parity configs #3 (LSTM AE) and #4 (LSTM forecast):
    50-tag sliding-window fleets trained with on-device window gathering
    (WindowedFleetMember — the raw series stays device-resident; windows
    are gathered per batch inside the fused program).
    """
    from gordo_tpu.models.factories import lstm_model
    from gordo_tpu.parallel import FleetTrainer

    _setup_jax_cache()
    members, config, n_lstm, lstm_kwargs = _lstm_fleet_setup()

    trainer = FleetTrainer()
    rates = {}
    elapsed_by_key = {}
    for key, lookahead in (("lstm_ae", 0), ("lstm_forecast", 1)):
        fleet = members(lookahead)
        trainer.train(fleet, config)  # warmup/compile
        # n=2: a ~30s program amortizes per-transfer jitter far better
        # than the millisecond feedforward runs, and best-of-3 here would
        # push the whole bench past a 10-minute budget
        n_runs = min(2, int(os.environ.get("BENCH_TIMED_RUNS", 2)))
        elapsed, results = _timed_best(trainer, fleet, config, n=n_runs)
        losses = [r.history.history["loss"][-1] for r in results]
        assert all(np.isfinite(losses)), f"non-finite {key} losses"
        rates[key] = n_lstm / (elapsed / 3600.0)
        elapsed_by_key[key] = elapsed
        log(
            f"{key}: {n_lstm} x {LSTM_TAGS}-tag lookback-"
            f"{LSTM_LOOKBACK} models, {LSTM_EPOCHS} epochs in {elapsed:.2f}s "
            f"-> {rates[key]:.0f} models/hour"
        )

    # -- LSTM roofline: the recurrence is a sequential scan; report the
    # loop-iteration arithmetic so "at the sequential bound" is checkable
    # from the artifact (VERDICT r4 weak #3).
    from gordo_tpu.models.nn import _lstm_unroll

    nw = N_SAMPLES - LSTM_LOOKBACK + 1
    nv = -(-nw // BATCH) * BATCH
    updates_per_epoch = nv // BATCH
    unroll = _lstm_unroll()
    # fwd scan + bwd scan (recompute+grad) per update, each
    # ceil(lookback/unroll) XLA loop iterations, plus the update step
    loop_iters_per_epoch = updates_per_epoch * (
        2 * -(-LSTM_LOOKBACK // unroll) + 1
    )
    total_iters = LSTM_EPOCHS * loop_iters_per_epoch
    ms_per_iter = elapsed_by_key["lstm_ae"] / total_iters * 1e3
    # Recurrent weights re-read per cell step across the vmapped member
    # axis, plus each layer's (h, c) carry read+written — the input
    # projection (Wx) is hoisted out of the scan (models/nn.py) and so is
    # NOT per-step traffic.
    spec = lstm_model(LSTM_TAGS, **lstm_kwargs)
    recurrent_weight_bytes = 4 * sum(u * 4 * u for u in spec.dims)
    carry_bytes = 4 * sum(2 * 2 * BATCH * u for u in spec.dims)
    cell_bytes = n_lstm * (recurrent_weight_bytes + carry_bytes)
    import jax as _jax

    kind = _jax.devices()[0].device_kind
    hbm_peak = PEAK_HBM_BPS.get(kind)
    hbm_floor_iter_ms = (
        cell_bytes * unroll / hbm_peak * 1e3 if hbm_peak else None
    )
    log(
        f"lstm roofline: {updates_per_epoch} updates x "
        f"2*ceil({LSTM_LOOKBACK}/{unroll}) iters -> {total_iters} loop "
        f"iterations; {ms_per_iter:.3f} ms/iter measured"
        + (
            f" vs {hbm_floor_iter_ms:.4f} ms HBM floor/iter "
            f"({cell_bytes * unroll / 1e6:.2f} MB)"
            if hbm_peak
            else ""
        )
    )

    return {
        "lstm_ae_models_per_hour": round(rates["lstm_ae"], 1),
        "lstm_forecast_models_per_hour": round(rates["lstm_forecast"], 1),
        "roofline": {
            "loop_iters_per_epoch": loop_iters_per_epoch,
            "unroll": unroll,
            "ms_per_loop_iter": round(ms_per_iter, 4),
            "hbm_floor_iter_ms": (
                round(hbm_floor_iter_ms, 4)
                if hbm_floor_iter_ms is not None
                else None
            ),
            "cell_bytes": int(cell_bytes),
        },
        "n_models": n_lstm,
        "tags": LSTM_TAGS,
        "lookback": LSTM_LOOKBACK,
        "epochs": LSTM_EPOCHS,
        "device": _device_desc(),
    }


# -- stage 2b': LSTM experiments (segmented path, unroll sweep) -------------


@stage
def lstm_experiments() -> dict:
    """
    The measured answers to the LSTM 100× question, isolated in their own
    stage so a budget clamp can never take the core LSTM rates down with
    them (they run LAST):

    - **segmented (stateful-scan) training** at BENCH_LSTM_SEGMENTED
      segments/update — the ~lookback× FLOP/HBM cut vs window-restart;
    - **scan-unroll sweep** — the per-scan-iteration-overhead killer:
      the same window-restart fleet at GORDO_TPU_LSTM_UNROLL 4 (the
      default), 15, and 60 (= fully unrolled recurrence, no inner loop).
      The unroll knob is read at trace time, so each sweep point clears
      the (spec, config)-keyed program caches to force a rebuild.

    TPU-only: on the CPU fallback these would only burn budget.
    """
    from gordo_tpu.models import training as training_mod
    from gordo_tpu.parallel import FleetTrainer
    from gordo_tpu.parallel import fleet as fleet_mod

    _setup_jax_cache()

    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": "accelerator-only experiments (CPU backend)"}

    members, config, n_lstm, _ = _lstm_fleet_setup()

    def clear_program_caches():
        # the unroll env var is read at trace time; cached programs for
        # the same (spec, config) must be rebuilt to pick it up
        fleet_mod._fleet_windowed_fit_program.cache_clear()
        fleet_mod._fleet_segmented_fit_program.cache_clear()
        training_mod.build_raw_windowed_fit_fn.cache_clear()
        training_mod.build_raw_segmented_fit_fn.cache_clear()

    trainer = FleetTrainer()
    n_runs = min(2, int(os.environ.get("BENCH_TIMED_RUNS", 2)))

    def measure(label: str) -> float:
        fleet = members(0)
        trainer.train(fleet, config)  # warmup/compile
        # best-of-2 like the core LSTM stage: tunneled-transfer latency
        # varies ±50% run to run, and these speedup ratios are the
        # round's headline experiment evidence
        elapsed, results = _timed_best(trainer, fleet, config, n=n_runs)
        losses = [r.history.history["loss"][-1] for r in results]
        assert all(np.isfinite(losses)), f"non-finite {label} losses"
        rate = n_lstm / (elapsed / 3600.0)
        log(f"lstm experiment {label}: {elapsed:.2f}s -> {rate:.0f} models/hour")
        return rate

    result: dict = {"n_models": n_lstm, "device": _device_desc()}

    # Baseline PINNED to unroll=4 (the shipped default) regardless of any
    # operator GORDO_TPU_LSTM_UNROLL in the environment — every speedup
    # ratio below is "vs the default configuration", so the baseline must
    # actually run it.
    prior_unroll = os.environ.get("GORDO_TPU_LSTM_UNROLL")
    try:
        os.environ["GORDO_TPU_LSTM_UNROLL"] = "4"
        clear_program_caches()
        base_rate = measure("restart@unroll=4 (baseline)")
        result["restart_models_per_hour"] = round(base_rate, 1)
        result["baseline_unroll"] = 4
        _flush_stage(result)

        seg = os.environ.get("BENCH_LSTM_SEGMENTED", "4")
        if seg.isdigit() and int(seg) > 0 and BATCH % int(seg) == 0:
            # per-point isolation: one failed experiment records its
            # error and the remaining points still run
            os.environ["GORDO_TPU_LSTM_SEGMENTED"] = seg
            try:
                seg_rate = measure(f"segmented G={seg}")
                result["segmented_models_per_hour"] = round(seg_rate, 1)
                result["segmented_speedup"] = round(seg_rate / base_rate, 3)
            except Exception as exc:  # noqa: BLE001 - isolate the point
                log(f"segmented measurement failed: {exc}")
                result["segmented_error"] = f"{type(exc).__name__}: {exc}"
            finally:
                os.environ.pop("GORDO_TPU_LSTM_SEGMENTED", None)
            _flush_stage(result)
        elif seg not in ("", "0"):
            log(f"segmented skipped: G={seg!r} invalid for batch {BATCH}")

        for unroll_raw in os.environ.get("BENCH_LSTM_UNROLL_SWEEP", "15,60").split(","):
            unroll = unroll_raw.strip()
            if not unroll:
                continue
            if not unroll.isdigit():
                log(f"unroll sweep: skipping non-numeric entry {unroll_raw!r}")
                continue
            os.environ["GORDO_TPU_LSTM_UNROLL"] = unroll
            clear_program_caches()
            try:
                rate = measure(f"restart@unroll={unroll}")
                result[f"unroll_{unroll}_models_per_hour"] = round(rate, 1)
                result[f"unroll_{unroll}_speedup"] = round(rate / base_rate, 3)
            except Exception as exc:  # noqa: BLE001 - isolate the point
                log(f"unroll={unroll} measurement failed: {exc}")
                result[f"unroll_{unroll}_error"] = f"{type(exc).__name__}: {exc}"
            _flush_stage(result)
    finally:
        if prior_unroll is None:
            os.environ.pop("GORDO_TPU_LSTM_UNROLL", None)
        else:
            os.environ["GORDO_TPU_LSTM_UNROLL"] = prior_unroll
        clear_program_caches()
    return result


# -- stage 2c: anomaly-score parity vs TF2 ---------------------------------


@stage
def parity() -> dict:
    """
    North-star correctness: train the same architecture with the
    reference Keras engine and the JAX engine on identical data, same CV
    and threshold math, and quantify anomaly-surface agreement. The
    ``tf_envelope`` sub-record is the reference engine's own seed-to-seed
    delta — the yardstick the tolerances were calibrated against
    (gordo_tpu/compat/tf_parity.py).
    """
    from gordo_tpu.compat import tf_parity

    _setup_jax_cache()
    epochs = int(os.environ.get("BENCH_PARITY_EPOCHS", 150))
    # The envelope (TF-seed1-vs-TF-seed0) involves no JAX at all — it is a
    # deterministic property of the reference engine, so measuring it once
    # per parameter set and caching saves ~half the stage's TF training
    # time on every later run.
    want_envelope = os.environ.get("BENCH_PARITY_ENVELOPE", "1") == "1"
    cached_envelope = None
    if want_envelope:
        try:
            with open(ENVELOPE_CACHE) as f:
                cached = json.load(f)
            if cached.get("epochs") == epochs:
                cached_envelope = cached["tf_envelope"]
        except (OSError, ValueError, KeyError):
            pass
    record = tf_parity.run_parity(
        epochs=epochs,
        measure_envelope=want_envelope and cached_envelope is None,
    )
    if cached_envelope is not None:
        record["tf_envelope"] = {**cached_envelope, "from_cache": True}
    elif want_envelope and record.get("tf_envelope"):
        try:
            with open(ENVELOPE_CACHE, "w") as f:
                json.dump(
                    {"epochs": epochs, "tf_envelope": record["tf_envelope"]}, f
                )
        except OSError:
            pass
    log(
        "parity: score rel MAE {:.3f} (corr {:.4f}), agg-threshold delta "
        "{:.3f}, tag-threshold delta {:.3f} -> {}".format(
            record["score_rel_mae"],
            record["score_corr"],
            record["agg_threshold_rel_delta"],
            record["tag_threshold_mean_rel_delta"],
            "PASS" if record["passes"] else "FAIL",
        )
    )
    envelope = record.get("tf_envelope")
    if envelope:
        log(
            "parity envelope (TF seed-to-seed): rel MAE {:.3f}, corr {:.4f}, "
            "agg delta {:.3f}, tag delta {:.3f}".format(
                envelope["score_rel_mae"],
                envelope["score_corr"],
                envelope["agg_threshold_rel_delta"],
                envelope["tag_threshold_mean_rel_delta"],
            )
        )
    return record


# -- stage 3: reference Keras baseline -------------------------------------


@stage
def reference_keras() -> dict:
    """
    Reference-engine cost: Keras/TF2 CPU fit of the same architecture,
    measured over a few epochs and scaled to N_EPOCHS. Returns models/hour
    for one reference builder pod (1 CPU core pod in the reference's spec;
    we grant it the whole host CPU — a conservative baseline).
    """
    # The baseline is the reference engine's CPU cost — independent of the
    # accelerator under test, so a cached measurement from an earlier run
    # on this host is as good as a fresh one and costs zero budget.
    # BENCH_REFRESH_BASELINE=1 forces a re-measure.
    if not os.environ.get("BENCH_REFRESH_BASELINE") and os.path.exists(
        BASELINE_CACHE
    ):
        with open(BASELINE_CACHE) as f:
            cached = json.load(f)
        return {**cached, "from_cache": True}

    import tensorflow as tf

    tf.get_logger().setLevel("ERROR")
    from gordo_tpu.models.factories.utils import hourglass_calc_dims

    dims = hourglass_calc_dims(0.5, 3, N_TAGS)
    layers = [tf.keras.layers.Input(shape=(N_TAGS,))]
    for units in tuple(dims) + tuple(dims[::-1]):
        layers.append(tf.keras.layers.Dense(units, activation="tanh"))
    layers.append(tf.keras.layers.Dense(N_TAGS, activation="linear"))
    model = tf.keras.Sequential(layers)
    model.compile(optimizer="adam", loss="mse")

    X = make_data(1)[0]
    measure_epochs = max(2, min(5, N_EPOCHS))
    model.fit(X, X, epochs=1, batch_size=BATCH, verbose=0)  # warmup
    start = time.time()
    model.fit(X, X, epochs=measure_epochs, batch_size=BATCH, verbose=0, shuffle=True)
    per_epoch = (time.time() - start) / measure_epochs
    seconds_per_model = per_epoch * N_EPOCHS
    models_per_hour = 3600.0 / seconds_per_model
    log(
        f"reference: keras CPU {per_epoch:.3f}s/epoch -> "
        f"{seconds_per_model:.2f}s/model -> {models_per_hour:.1f} models/hour"
    )
    result = {"models_per_hour": models_per_hour}
    with open(BASELINE_CACHE, "w") as f:
        json.dump(result, f)
    return result


def _emit_result(partial: dict) -> int:
    """Derive the one-line JSON from whatever stages completed, print it,
    flush the partial artifact, and return the exit code."""
    fleet = partial.get("fleet_train")
    e2e = partial.get("fleet_build_e2e")
    lstm = partial.get("lstm_fleet_train")
    experiments = partial.get("lstm_experiments")
    reference = partial.get("reference_keras")
    parity_rec = partial.get("parity")

    # Headline = bare fleet throughput; fall back to the e2e number rather
    # than zeroing the round if only the bare stage flaked.
    headline = fleet or e2e
    ref_mph = reference["models_per_hour"] if reference else None
    result = {
        "metric": "autoencoders_trained_per_hour",
        "value": round(headline["models_per_hour"], 1) if headline else None,
        "unit": "models/hour",
        "vs_baseline": (
            round(headline["models_per_hour"] / ref_mph, 2)
            if headline and ref_mph
            else None
        ),
        "extra": {
            "step_time_ms": fleet["step_time_ms"] if fleet else None,
            "achieved_gflops": fleet["achieved_gflops"] if fleet else None,
            "mfu": fleet["mfu"] if fleet else None,
            "packed_speedup": fleet.get("packed_speedup") if fleet else None,
            "bf16_speedup": fleet.get("bf16_speedup") if fleet else None,
            "e2e_models_per_hour": (
                round(e2e["models_per_hour"], 1) if e2e else None
            ),
            "e2e_elapsed_s": e2e["elapsed_s"] if e2e else None,
            "e2e_n_machines": e2e["n_machines"] if e2e else None,
            "lstm_ae_models_per_hour": (
                lstm["lstm_ae_models_per_hour"] if lstm else None
            ),
            "lstm_forecast_models_per_hour": (
                lstm["lstm_forecast_models_per_hour"] if lstm else None
            ),
            "lstm_experiments": (
                experiments if experiments and "skipped" not in experiments else None
            ),
            "roofline": fleet.get("roofline") if fleet else None,
            "lstm_roofline": lstm.get("roofline") if lstm else None,
            "parity": (
                {
                    "score_rel_mae": round(parity_rec["score_rel_mae"], 4),
                    "score_corr": round(parity_rec["score_corr"], 4),
                    "agg_threshold_rel_delta": round(
                        parity_rec["agg_threshold_rel_delta"], 4
                    ),
                    "tag_threshold_mean_rel_delta": round(
                        parity_rec["tag_threshold_mean_rel_delta"], 4
                    ),
                    "passes": parity_rec["passes"],
                    "tf_envelope": (
                        {
                            k: round(v, 4) if isinstance(v, float) else v
                            for k, v in parity_rec["tf_envelope"].items()
                        }
                        if parity_rec.get("tf_envelope")
                        else None
                    ),
                }
                if parity_rec
                else None
            ),
            "device": (fleet or e2e or lstm or {}).get("device"),
            "errors": {
                k: v
                for k, v in partial.items()
                if k.endswith("_error") or k.endswith("_note")
            }
            or None,
        },
    }
    partial["result"] = result
    _flush_partial(partial)
    print(json.dumps(result), flush=True)
    # rc 0 whenever any stage produced a usable number; a completely dead
    # environment still leaves the partial artifact behind.
    return 0 if headline else 1


def main():
    if len(sys.argv) >= 4 and sys.argv[1] == "--stage":
        sys.exit(_stage_entry(sys.argv[2], sys.argv[3]))

    partial: dict = {"n_models": N_MODELS, "epochs": N_EPOCHS, "budget_s": BUDGET}

    # Backstop: if the driver's own timeout fires anyway (SIGTERM, or ^C
    # interactively), emit the final JSON line from whatever stages
    # completed instead of dying silently — round 4 ended rc=124 with no
    # artifact precisely because nothing caught the kill.
    def _on_signal(signum, frame):
        log(f"signal {signum}: emitting result from completed stages")
        partial["interrupted"] = f"signal {signum} at {time.time() - _T0:.0f}s"
        _emit_result(partial)
        os._exit(0)  # noqa: SLF001 - skip atexit; the JSON line is out

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    # Pre-flight: a wedged accelerator tunnel hangs even trivial device
    # work. The probe is a pure transfer round trip (no XLA compile), so
    # 30s is generous for a live tunnel; on failure the whole run pins to
    # CPU with every stage auto-shrunk to fallback sizes.
    if os.environ.get("BENCH_FORCE_CPU"):
        # Operator-pinned CPU run: same budget math as the fallback path.
        _apply_cpu_shrink(os.environ)
    else:
        probe = run_stage(partial, "backend_probe", timeout=30, retries=0)
        if probe is None:
            log("backend probe failed; forcing CPU + shrunk stages")
            os.environ["BENCH_FORCE_CPU"] = "1"
            _apply_cpu_shrink(os.environ)
            partial["backend_note"] = "accelerator unresponsive; ran on CPU"
        elif "tpu" not in probe.get("device", "").lower():
            # A healthy host with no accelerator (CI, laptops): the JAX
            # CPU backend answers the probe fine, but full-size stages
            # can no more fit the budget here than on the fallback path.
            log(f"no accelerator ({probe.get('device')}); shrunk CPU stages")
            _apply_cpu_shrink(os.environ)
            os.environ["BENCH_FORCE_CPU"] = "1"
            partial["backend_note"] = f"no accelerator; ran on {probe.get('device')}"
    # Sizes may have been shrunk above — the artifact must describe the
    # run that actually happened, not the import-time defaults.
    partial["n_models"] = int(os.environ.get("BENCH_MODELS", N_MODELS))

    # Stage order = audit priority: the headline number and the parity
    # record land first so a budget squeeze costs the auxiliary rates,
    # never the round's primary evidence.
    run_stage(partial, "fleet_train")
    if not os.environ.get("BENCH_SKIP_PARITY"):
        run_stage(partial, "parity", retries=0)
    reference = run_stage(partial, "reference_keras", retries=0)
    if reference is None and os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            partial["reference_keras"] = {**json.load(f), "from_cache": True}
    if not os.environ.get("BENCH_SKIP_E2E"):
        run_stage(partial, "fleet_build_e2e")
    if not os.environ.get("BENCH_SKIP_LSTM"):
        run_stage(partial, "lstm_fleet_train", retries=1)
        # experiments (segmented path, unroll sweep) run LAST: if the
        # budget clamps anything, it is these, never the core rates
        run_stage(partial, "lstm_experiments", retries=0)

    sys.exit(_emit_result(partial))


if __name__ == "__main__":
    main()
