"""
Headline benchmark: autoencoders trained per hour (BASELINE.json metric).

Trains a fleet of hourglass feedforward autoencoders (the reference's
production architecture — 20 sensor tags, 10 days of 10-minute data, the
`examples/config.yaml` shape) as ONE fused vmapped program on whatever
accelerator `jax.devices()` provides, and compares against the reference
engine's cost measured directly: the same architecture / optimizer / batch
size / epochs trained with Keras/TF2 on CPU (the reference trains every
model with CPU Keras inside its per-model k8s pod —
SURVEY.md §2.9, BASELINE.md).

Prints ONE JSON line:
  {"metric": "autoencoders_trained_per_hour", "value": ..., "unit":
   "models/hour", "vs_baseline": ...}

Env knobs: BENCH_MODELS (default 256), BENCH_EPOCHS (20), BENCH_SAMPLES
(1440), BENCH_TAGS (20), BENCH_SKIP_TF_BASELINE=1 to reuse/skip the Keras
measurement (cached in .bench_baseline.json).
"""

import json
import os
import sys
import time

import numpy as np

N_MODELS = int(os.environ.get("BENCH_MODELS", 256))
N_EPOCHS = int(os.environ.get("BENCH_EPOCHS", 20))
N_SAMPLES = int(os.environ.get("BENCH_SAMPLES", 1440))  # 10 days @ 10min
N_TAGS = int(os.environ.get("BENCH_TAGS", 20))
BATCH = 64
BASELINE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".bench_baseline.json")


def make_data(n_models: int):
    rng = np.random.RandomState(42)
    t = np.linspace(0, 12 * np.pi, N_SAMPLES, dtype=np.float32)
    data = []
    for i in range(n_models):
        phases = rng.uniform(0, 2 * np.pi, N_TAGS).astype(np.float32)
        amp = rng.uniform(0.5, 2.0, N_TAGS).astype(np.float32)
        X = amp * np.sin(t[:, None] + phases) + 0.05 * rng.standard_normal(
            (N_SAMPLES, N_TAGS)
        ).astype(np.float32)
        data.append(X)
    return data


def bench_fleet() -> float:
    """Our throughput: models/hour on the available accelerator."""
    from gordo_tpu.models.factories import feedforward_hourglass
    from gordo_tpu.models.training import FitConfig
    from gordo_tpu.parallel import FleetMember, FleetTrainer

    import jax

    # Persistent compilation cache: the fleet program for a (spec, shape)
    # compiles once per machine ever, not once per process.
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    spec = feedforward_hourglass(N_TAGS)
    config = FitConfig(epochs=N_EPOCHS, batch_size=BATCH, shuffle=True)
    data = make_data(N_MODELS)
    members = [
        FleetMember(name=f"m{i}", spec=spec, X=X, y=X, seed=i)
        for i, X in enumerate(data)
    ]
    trainer = FleetTrainer()

    # Warmup with the SAME member count and shapes: the vmapped program's
    # model axis is part of the compiled shape, so a smaller warmup fleet
    # would leave XLA compilation inside the measured section.
    trainer.train(members, config)

    start = time.time()
    results = trainer.train(members, config)
    elapsed = time.time() - start

    losses = [r.history.history["loss"][-1] for r in results]
    assert all(np.isfinite(losses)), "non-finite training losses"
    print(
        f"# fleet: {N_MODELS} AEs x {N_EPOCHS} epochs in {elapsed:.2f}s "
        f"(final loss mean {np.mean(losses):.5f}) on {_device_desc()}",
        file=sys.stderr,
    )
    return N_MODELS / (elapsed / 3600.0)


def _device_desc() -> str:
    import jax

    d = jax.devices()
    return f"{len(d)}x {d[0].device_kind}"


def bench_reference_keras() -> float:
    """
    Reference-engine cost: Keras/TF2 CPU fit of the same architecture,
    measured over a few epochs and scaled to N_EPOCHS. Returns models/hour
    for one reference builder pod (1 CPU core pod in the reference's spec;
    we grant it the whole host CPU — a conservative baseline).
    """
    if os.environ.get("BENCH_SKIP_TF_BASELINE") and os.path.exists(BASELINE_CACHE):
        with open(BASELINE_CACHE) as f:
            return json.load(f)["models_per_hour"]

    import tensorflow as tf

    tf.get_logger().setLevel("ERROR")
    from gordo_tpu.models.factories.utils import hourglass_calc_dims

    dims = hourglass_calc_dims(0.5, 3, N_TAGS)
    layers = [tf.keras.layers.Input(shape=(N_TAGS,))]
    for units in tuple(dims) + tuple(dims[::-1]):
        layers.append(tf.keras.layers.Dense(units, activation="tanh"))
    layers.append(tf.keras.layers.Dense(N_TAGS, activation="linear"))
    model = tf.keras.Sequential(layers)
    model.compile(optimizer="adam", loss="mse")

    X = make_data(1)[0]
    measure_epochs = max(2, min(5, N_EPOCHS))
    model.fit(X, X, epochs=1, batch_size=BATCH, verbose=0)  # warmup
    start = time.time()
    model.fit(X, X, epochs=measure_epochs, batch_size=BATCH, verbose=0, shuffle=True)
    per_epoch = (time.time() - start) / measure_epochs
    seconds_per_model = per_epoch * N_EPOCHS
    models_per_hour = 3600.0 / seconds_per_model
    print(
        f"# reference: keras CPU {per_epoch:.3f}s/epoch -> "
        f"{seconds_per_model:.2f}s/model -> {models_per_hour:.1f} models/hour",
        file=sys.stderr,
    )
    with open(BASELINE_CACHE, "w") as f:
        json.dump({"models_per_hour": models_per_hour}, f)
    return models_per_hour


def main():
    ours = bench_fleet()
    try:
        reference = bench_reference_keras()
    except Exception as e:  # TF unavailable: fall back to cached/derived
        print(f"# reference baseline unavailable ({e})", file=sys.stderr)
        if os.path.exists(BASELINE_CACHE):
            with open(BASELINE_CACHE) as f:
                reference = json.load(f)["models_per_hour"]
        else:
            reference = None
    result = {
        "metric": "autoencoders_trained_per_hour",
        "value": round(ours, 1),
        "unit": "models/hour",
        "vs_baseline": round(ours / reference, 2) if reference else None,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
