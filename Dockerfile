# gordo-tpu-base — the image every manifest in
# gordo_tpu/workflow/workflow_generator/resources/tpu-workflow.yml.template
# pins as {{ docker_registry }}/{{ docker_repository }}/gordo-tpu-base:
# {{ gordo_version }} (fleet-shard Jobs, server Deployment, client replay,
# cleanup Job). Reference analog: /root/reference/Dockerfile (python-slim
# two-stage sdist build, non-root user, build.sh default command); the
# TPU specifics — libtpu wheel, no CUDA, no argo binary — are this
# image's own.
#
#   docker build -t gordo-tpu-base:$(python -c 'import gordo_tpu; print(gordo_tpu.__version__)') .

# -- stage 1: pack the sdist ------------------------------------------------
FROM python:3.12-slim-bookworm AS builder

COPY . /code
WORKDIR /code

RUN pip install --no-cache-dir build \
    && rm -rf /code/dist \
    && python -m build --sdist \
    && mv /code/dist/$(ls /code/dist | head -1) /code/dist/gordo-tpu-packed.tar.gz

# -- stage 2: runtime -------------------------------------------------------
FROM python:3.12-slim-bookworm

# Non-root runtime user (pods run with runAsNonRoot; uid is what the
# manifests' securityContext expects).
RUN groupadd -g 999 gordo && useradd -r -u 999 -g gordo -m gordo
ENV HOME=/home/gordo
ENV PATH="${HOME}/.local/bin:${PATH}"

# The heavy, slow-moving dependencies install in their own layer so a
# source-only change rebuilds in seconds. jax[tpu] pulls libtpu from the
# Google releases index — this is the only TPU-specific install step; the
# same image runs CPU-only (tests, workflow generation, server) when no
# TPU is attached, because JAX falls back to the CPU backend at runtime.
RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir \
    numpy pandas scikit-learn optax pyarrow gunicorn prometheus_client

COPY --from=builder /code/dist/gordo-tpu-packed.tar.gz .
RUN pip install --no-cache-dir "gordo-tpu-packed.tar.gz[server,reporters]" \
    && rm gordo-tpu-packed.tar.gz

# Example configs ride along for smoke tests (reference bakes its
# examples/ and resources/ the same way).
COPY ./examples ${HOME}/examples

# `build` as the default command: the fleet-shard Jobs in the rendered
# workflow run the image with no args and expect a model build, exactly
# like the reference's build.sh. Every other entrypoint (run-server,
# workflow generate, client) is an explicit `gordo-tpu <subcommand>`
# in its manifest.
RUN printf '#!/bin/sh\nexec gordo-tpu build "$@"\n' > /usr/bin/build \
    && chmod a+x /usr/bin/build

WORKDIR ${HOME}
RUN chown -R gordo:gordo ${HOME}
USER 999

CMD ["build"]
