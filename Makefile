# Convenience targets (CI runs scripts/tests.sh per matrix component)

.PHONY: test test-fast test-faults docs bench lint image

test:
	python -m pytest tests/ -q

# The deterministic fault-injection robustness suite (crash+resume,
# bucket bisection, data-fetch retry) — CPU-only and not slow-marked,
# so the same tests also run inside the tier-1 `-m 'not slow'` budget.
test-faults:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults

# The sub-5-minute tier: everything except the compile-heavy JAX suites
# (tests/parallel, tests/models) and slow-marked tests.
test-fast:
	bash scripts/tests.sh fast

image:
	docker build -t gordo-tpu-base:latest .

docs:
	python docs/generate_api.py docs/api

bench:
	python bench.py

lint:
	python -m pytest tests/test_codestyle.py -q
