# Convenience targets (CI runs scripts/tests.sh per matrix component)

.PHONY: test test-fast test-faults test-observability test-serve test-wire test-planner test-lifecycle test-lifecycle-faults test-analysis test-concurrency test-fleet-health test-slo test-precision test-chaos test-scale test-stream test-ingest test-perfmodel docs bench bench-telemetry bench-serve bench-planner bench-lifecycle bench-route bench-fleet-health bench-slo bench-precision bench-chaos bench-scale bench-stream bench-ingest bench-perfmodel bench-check lint lint-gordo lockgraph-check image

test:
	python -m pytest tests/ -q

# The deterministic fault-injection robustness suite (crash+resume,
# bucket bisection, data-fetch retry) — CPU-only and not slow-marked,
# so the same tests also run inside the tier-1 `-m 'not slow'` budget.
test-faults:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults

# The build-telemetry suite: span recorder, live progress surface,
# compile/run attribution, Prometheus build metrics — CPU-only and not
# slow-marked, so the same tests also run inside the tier-1 budget.
test-observability:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m observability

# The micro-batching serving suite: flush policy, shape ladder, warmup,
# admission control, batched-vs-unbatched equivalence — CPU-only and not
# slow-marked, so the same tests also run inside the tier-1 budget.
test-serve:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve

# The columnar wire-format suite: content negotiation, JSON/Arrow
# codec parity (byte-identical JSON, numerically identical Arrow),
# malformed-body/406 contracts, mixed-format concurrency — CPU-only and
# not slow-marked, so the same tests also run inside the tier-1 budget.
test-wire:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m wire

# The build-planner suite: cost model + calibration, bucket packing,
# FleetPlan determinism/replay, plan-aware resume — CPU-only and not
# slow-marked, so the same tests also run inside the tier-1 budget.
test-planner:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m planner

# The self-healing lifecycle suite: drift statistics, canary
# publish/gates, promotion hot-swap, rollback + quarantine — CPU-only
# and not slow-marked, so the same tests also run inside tier-1.
test-lifecycle:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m lifecycle

# The deterministic lifecycle chaos drill: a crash injected at each
# lifecycle/serve fault site (drift_eval, canary_build, promote_swap,
# rollback) must leave serving on the last-good revision and the loop
# resumable.
test-lifecycle-faults:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "lifecycle and faults"

# Lifecycle hot-swap benchmark: concurrent clients through N canary
# promote/rollback swaps; writes BENCH_LIFECYCLE.json (swap latency,
# dropped requests — target: zero).
bench-lifecycle:
	JAX_PLATFORMS=cpu python benchmarks/bench_lifecycle.py

# Serving micro-batching benchmark: concurrent single-model requests
# with batching off vs on; writes BENCH_SERVE.json.
bench-serve:
	JAX_PLATFORMS=cpu python benchmarks/bench_serve.py

# Bucket-planner benchmark: a heterogeneous synthetic fleet built with
# the naive vs packed strategies; writes BENCH_PLAN.json.
bench-planner:
	JAX_PLATFORMS=cpu python benchmarks/bench_planner.py

# Telemetry-overhead microbench: a small CPU fleet build with telemetry
# off vs on; writes BENCH_TELEMETRY.json for the bench trajectory.
bench-telemetry:
	JAX_PLATFORMS=cpu python benchmarks/bench_telemetry.py

# The fleet console suite: per-member health ledger, device-utilization
# telemetry, the joined fleet-status CLI/route surface — CPU-only and
# not slow-marked, so the same tests also run inside the tier-1 budget.
test-fleet-health:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fleet_health

# Fleet-health overhead microbench: the same build with all telemetry
# (ledger + device sampler included) off vs on; writes
# BENCH_FLEET_HEALTH.json (<=2% overhead is the gate).
bench-fleet-health:
	JAX_PLATFORMS=cpu python benchmarks/bench_fleet_health.py

# The fleet SLO suite: cross-worker rollup reducer, burn-rate alert
# state machine, worker-sink merge, slo CLI/route/gauges — CPU-only and
# not slow-marked, so the same tests also run inside the tier-1 budget.
test-slo:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slo

# The mixed-precision serving-ladder suite: precision vocabulary /
# casting / quantization units, engine e2e (f32 byte-parity, bf16
# verdict parity, degrade drill, mixed-precision hot swap), the
# precision-parity gate drills, and the cost-model precision features.
test-precision:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m precision

# Precision-ladder bench: per-precision fused scoring throughput +
# verdict-agreement rate; writes BENCH_PRECISION.json.
bench-precision:
	JAX_PLATFORMS=cpu python benchmarks/bench_precision.py

# The serving fault-containment suite: circuit-breaker state machine,
# batch bisection under injected device faults, NaN-poison detection,
# OOM rung demotion, the route-level chaos drills, and the
# breaker->lifecycle rebuild feed — CPU-only and not slow-marked, so
# the same tests also run inside the tier-1 budget.
test-chaos:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m chaos

# Route-level chaos drill: >=8 concurrent clients + device faults
# against one coalesced member + a hot-swap mid-drill; asserts zero
# innocent-rider 5xx, breaker trip/recovery, ledger narration; writes
# BENCH_CHAOS.json (gated by `gordo-tpu bench-check`).
bench-chaos:
	JAX_PLATFORMS=cpu python benchmarks/bench_chaos.py

# The streaming scoring-plane suite: row/event rings, SSE session
# replay + cursor resume, watermark scoring with breaker quarantine,
# backpressure shedding, hot-swap pinning, drain terminals, the three
# stream_* fault-site drills — CPU-only and not slow-marked, so the
# same tests also run inside the tier-1 budget.
test-stream:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m stream

# Streaming soak harness: N long-lived sessions under sustained Arrow
# ingest with >=5 mid-stream hot-swaps, a poisoned member (quarantine +
# half-open recovery), and a drain audit; writes BENCH_STREAM.json
# (gated by `gordo-tpu bench-check`).
bench-stream:
	JAX_PLATFORMS=cpu python benchmarks/bench_stream.py

# The device-resident ingest suite: compiled preprocessing plans,
# raw-column dlpack transfer with host fallback, compiled-vs-host
# parity across wire formats / batching modes / routes, ladder-snapped
# stream cuts — CPU-only and not slow-marked, so the same tests also
# run inside the tier-1 budget.
test-ingest:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m ingest

# Device-ingest microbench: host preprocessing pipeline vs the compiled
# plan + raw-column transfer on the same payloads; writes
# BENCH_INGEST.json (gated by `gordo-tpu bench-check`).
bench-ingest:
	JAX_PLATFORMS=cpu python benchmarks/bench_ingest.py

# The learned performance-model suite: trace harvesting, closed-form
# ridge fit + deterministic holdout, accuracy-gated promotion,
# cold-start/corrupt-table fallback, knob-off plan byte-parity, and the
# model-informed serving consumers — CPU-only and not slow-marked, so
# the same tests also run inside the tier-1 budget.
test-perfmodel:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m perfmodel

# Learned-cost-model bench: measure a real fleet_forward shape grid,
# fit + promote through the accuracy gate, score predicted-vs-actual on
# the deterministic holdout (learned must beat analytic), and replay a
# ragged request stream through the static vs model-informed row
# ladder; writes BENCH_PERFMODEL.json (gated by `gordo-tpu
# bench-check`).
bench-perfmodel:
	JAX_PLATFORMS=cpu python benchmarks/bench_perfmodel.py

# The fleet-scale observability suite: sharded ledger layout/migration/
# dirty-flush contracts, rollup-manifest counting-open reads, bounded
# fleet-status selection/paging, the 5k-member breaker-summary guard —
# CPU-only and not slow-marked, so the same tests also run inside the
# tier-1 budget.
test-scale:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m scale

# Fleet-scale observability harness: the synthetic-fleet generator
# (benchmarks/fleetgen.py) drives build-plan, sharded health ledger,
# rollup manifest, bounded fleet-status, breaker board and prometheus
# scrape at N in {100, 1k, 10k}; writes BENCH_SCALE.json (gated by
# `gordo-tpu bench-check`).
bench-scale:
	JAX_PLATFORMS=cpu python benchmarks/bench_scale.py

# SLO-engine bench: aggregation throughput (spans/s), steady-state
# evaluation overhead vs the telemetry-on floor (<=2% is the gate), and
# the scripted burn drill; writes BENCH_SLO.json.
bench-slo:
	JAX_PLATFORMS=cpu python benchmarks/bench_slo.py

# Full-route serving benchmark + observability acceptance surface:
# per-stage attribution from serve_trace.jsonl (coverage >= 90% of p50
# walltime) and the tracing/histogram overhead floor; writes
# BENCH_ROUTE.json (override the path with BENCH_ROUTE_OUT).
bench-route:
	JAX_PLATFORMS=cpu python benchmarks/bench_route.py

# The perf-regression gate: re-run the route bench into a scratch file
# and compare it against the committed BENCH_ROUTE.json. Exits non-zero
# on regression; CI runs the same comparison with --report-only.
bench-check:
	JAX_PLATFORMS=cpu BENCH_ROUTE_OUT=/tmp/bench_route_fresh.json \
		python benchmarks/bench_route.py
	python -m gordo_tpu bench-check /tmp/bench_route_fresh.json \
		--baseline BENCH_ROUTE.json

# The sub-5-minute tier: everything except the compile-heavy JAX suites
# (tests/parallel, tests/models) and slow-marked tests.
test-fast:
	bash scripts/tests.sh fast

image:
	docker build -t gordo-tpu-base:latest .

docs:
	python docs/generate_api.py docs/api
	python docs/generate_env_docs.py

# The invariant gate (gordo_tpu/analysis/): layering arrows, JAX
# hazards, env-knob registry, atomic writes, clock discipline,
# Prometheus cardinality, and the concurrency contracts (lock-guard
# inference, COW-publish discipline, fork-safety, thread lifecycle)
# over gordo_tpu/ itself — non-zero exit on any finding that is neither
# suppressed in-file nor justified in lint_baseline.json. CI's `lint`
# job runs exactly this (plus `--sarif` for the annotation artifact).
lint-gordo:
	python -m gordo_tpu lint

# The runtime half of the concurrency gate: run the threaded suites
# (serve, telemetry, lifecycle) with every lock instrumented
# (GORDO_TPU_LOCK_TRACE), then fail on any acquisition-ordering cycle —
# a cycle is two threads ordering the same locks differently, i.e. a
# deadlock waiting for the right interleaving. CI's `lint` job runs
# the same pair of steps.
lockgraph-check:
	rm -f lock_trace-*.jsonl
	JAX_PLATFORMS=cpu GORDO_TPU_LOCK_TRACE=lock_trace.jsonl \
		python -m pytest tests/serve tests/telemetry tests/lifecycle \
		-q -m 'not slow' -p no:cacheprovider
	python -m gordo_tpu lockgraph 'lock_trace-*.jsonl'

# The concurrency-contract suite: rule fixtures (lock-guard/COW/fork/
# thread-lifecycle), the lock-order harness unit tests, the COW
# hot-swap stress drill, the ledger/recorder fork drills, and the
# shutdown thread audit — CPU-only and not slow-marked, so the same
# tests also run inside the tier-1 budget.
test-concurrency:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m concurrency

# The static-analysis test suite: per-rule fixture trees, suppression/
# baseline semantics, and the tier-1 self-run asserting gordo_tpu/ is
# clean against the committed baseline.
test-analysis:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m analysis

bench:
	python bench.py

lint:
	python -m pytest tests/test_codestyle.py -q
