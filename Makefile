# Convenience targets (CI runs scripts/tests.sh per matrix component)

.PHONY: test test-fast docs bench lint image

test:
	python -m pytest tests/ -q

# The sub-5-minute tier: everything except the compile-heavy JAX suites
# (tests/parallel, tests/models) and slow-marked tests.
test-fast:
	bash scripts/tests.sh fast

image:
	docker build -t gordo-tpu-base:latest .

docs:
	python docs/generate_api.py docs/api

bench:
	python bench.py

lint:
	python -m pytest tests/test_codestyle.py -q
