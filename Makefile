# Convenience targets (CI runs the same commands directly)

.PHONY: test docs bench lint

test:
	python -m pytest tests/ -q

docs:
	python docs/generate_api.py docs/api

bench:
	python bench.py

lint:
	python -m pytest tests/test_codestyle.py -q
