"""
Env-knob reference generator: renders the knob registry
(``gordo_tpu.utils.env.KNOBS``) into the section of
``docs/configuration.md`` between the ``<!-- env-knobs:begin -->`` /
``<!-- env-knobs:end -->`` markers, one table per registry section.

Usage:  python docs/generate_env_docs.py          (rewrite in place)
        python docs/generate_env_docs.py --check  (exit 1 when stale)

The emitted block is committed; tests/analysis/test_env_docs.py runs the
``--check`` mode, so adding a knob to the registry without regenerating
fails the suite — the table cannot drift from the code.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from gordo_tpu.utils.env import KNOBS, knob_sections  # noqa: E402

CONFIG_MD = Path(__file__).resolve().parent / "configuration.md"
BEGIN = "<!-- env-knobs:begin -->"
END = "<!-- env-knobs:end -->"


def _default_cell(knob) -> str:
    if knob.default is None:
        return "_(unset)_"
    if knob.type == "bool":
        return "`1`" if knob.default else "`0`"
    return f"`{knob.default}`"


def render_block() -> str:
    lines = [
        BEGIN,
        "",
        "_Generated from the knob registry in `gordo_tpu/utils/env.py` by "
        "`python docs/generate_env_docs.py` — edit the registry, not this "
        "block. Every knob is read through the typed accessors there "
        "(malformed values warn once and fall back to the default), and "
        "`gordo-tpu lint` fails on reads of undeclared knobs._",
        "",
    ]
    for section in knob_sections():
        knobs = [k for k in KNOBS.values() if k.section == section]
        lines.append(f"**{section} knobs**:")
        lines.append("")
        lines.append("| Variable | Type | Default | Effect |")
        lines.append("|---|---|---|---|")
        for knob in knobs:
            doc = " ".join(knob.doc.split())
            lines.append(
                f"| `{knob.name}` | {knob.type} | {_default_cell(knob)} | {doc} |"
            )
        lines.append("")
    lines.append(END)
    return "\n".join(lines)


def spliced_document() -> str:
    text = CONFIG_MD.read_text(encoding="utf-8")
    if BEGIN not in text or END not in text:
        raise SystemExit(
            f"{CONFIG_MD} is missing the {BEGIN} / {END} markers"
        )
    head, rest = text.split(BEGIN, 1)
    _, tail = rest.split(END, 1)
    return head + render_block() + tail


def main() -> int:
    fresh = spliced_document()
    if "--check" in sys.argv[1:]:
        if fresh != CONFIG_MD.read_text(encoding="utf-8"):
            print(
                "docs/configuration.md env-knob block is stale — run "
                "`python docs/generate_env_docs.py` (or `make docs`)",
                file=sys.stderr,
            )
            return 1
        print("env-knob block is up to date")
        return 0
    CONFIG_MD.write_text(fresh, encoding="utf-8")
    print(f"regenerated env-knob block in {CONFIG_MD}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
