"""
API-reference generator: walks ``gordo_tpu`` and emits one markdown page
per public module from the live docstrings/signatures (the reference
ships a sphinx tree with per-module pages under docs/api/; this is the
same coverage without a sphinx dependency in the image).

Usage:  python docs/generate_api.py [output_dir]   (default: docs/api)

The emitted tree is committed; tests/test_docs.py regenerates into a temp
dir and asserts the committed pages cover every public module.
"""

import importlib
import inspect
import pkgutil
import sys
from pathlib import Path
from typing import List, Optional

# runnable from anywhere: the package lives next to docs/
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

def public_modules(package_name: str = "gordo_tpu") -> List[str]:
    package = importlib.import_module(package_name)
    names = [package_name]
    for info in pkgutil.walk_packages(package.__path__, prefix=f"{package_name}."):
        tail = info.name.rsplit(".", 1)[-1]
        if tail.startswith("_"):
            continue
        names.append(info.name)
    return sorted(names)


def _first_paragraph(doc: Optional[str]) -> str:
    if not doc:
        return ""
    return inspect.cleandoc(doc).split("\n\n")[0]


def _signature(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"


def _document_class(cls) -> List[str]:
    lines = [f"### `{cls.__name__}{_signature(cls)}`", ""]
    doc = _first_paragraph(cls.__doc__)
    if doc:
        lines += [doc, ""]
    for name, member in sorted(vars(cls).items()):
        if name.startswith("_"):
            continue
        if callable(member) or isinstance(member, (classmethod, staticmethod)):
            fn = member.__func__ if isinstance(member, (classmethod, staticmethod)) else member
            if not callable(fn):
                continue
            summary = _first_paragraph(getattr(fn, "__doc__", "")).split("\n")[0]
            try:
                sig = _signature(fn)
            except Exception:  # noqa: BLE001 - descriptors vary
                sig = "(...)"
            lines.append(f"- `{name}{sig}`" + (f" — {summary}" if summary else ""))
        elif isinstance(member, property):
            summary = _first_paragraph(member.__doc__).split("\n")[0]
            lines.append(f"- `{name}` (property)" + (f" — {summary}" if summary else ""))
    if lines[-1] != "":
        lines.append("")
    return lines


def document_module(module_name: str) -> str:
    lines = [f"# `{module_name}`", ""]
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        lines += [
            f"*(optional dependency not installed: `{exc}` — see the module "
            "source for its docstring)*",
            "",
        ]
        return "\n".join(lines)
    doc = inspect.cleandoc(module.__doc__ or "")
    if doc:
        lines += [doc, ""]
    if hasattr(module, "__path__"):  # package: document its public surface
        exported = []
        for name in getattr(module, "__all__", []) or sorted(
            n for n in vars(module) if not n.startswith("_")
        ):
            member = getattr(module, name, None)
            home = getattr(member, "__module__", None)
            if home and home.startswith(module_name):
                exported.append(f"- `{name}` (from [`{home}`]({home}.md))")
            elif inspect.ismodule(member):
                continue
            elif member is not None:
                exported.append(f"- `{name}`")
        if exported:
            lines += ["## Public surface", ""] + exported + [""]
        submodules = sorted(
            info.name
            for info in pkgutil.iter_modules(module.__path__)
            if not info.name.startswith("_")
        )
        if submodules:
            lines += ["## Submodules", ""] + [
                f"- [`{module_name}.{sub}`]({module_name}.{sub}.md)"
                for sub in submodules
            ] + [""]
    members = [
        (name, member)
        for name, member in inspect.getmembers(module)
        if not name.startswith("_") and getattr(member, "__module__", None) == module_name
    ]
    classes = [(n, m) for n, m in members if inspect.isclass(m)]
    functions = [(n, m) for n, m in members if inspect.isfunction(m)]
    if classes:
        lines += ["## Classes", ""]
        for _, cls in sorted(classes):
            lines += _document_class(cls)
    if functions:
        lines += ["## Functions", ""]
        for name, fn in sorted(functions):
            lines.append(f"### `{name}{_signature(fn)}`")
            lines.append("")
            doc = _first_paragraph(fn.__doc__)
            if doc:
                lines += [doc, ""]
    return "\n".join(lines)


def generate(output_dir: str) -> List[str]:
    out = Path(output_dir)
    out.mkdir(parents=True, exist_ok=True)
    modules = public_modules()
    # prune pages of deleted/renamed modules so the committed reference
    # never documents modules that no longer exist
    expected = {f"{name}.md" for name in modules} | {"index.md"}
    for stale in out.glob("*.md"):
        if stale.name not in expected:
            stale.unlink()
    index = [
        "# gordo-tpu API reference",
        "",
        "Generated from live docstrings by `docs/generate_api.py` "
        "(`make docs` regenerates).",
        "",
    ]
    for module_name in modules:
        page = f"{module_name}.md"
        (out / page).write_text(document_module(module_name) + "\n")
        module = sys.modules.get(module_name)
        summary = _first_paragraph(getattr(module, "__doc__", "")).split("\n")[0]
        index.append(f"- [`{module_name}`]({page})" + (f" — {summary}" if summary else ""))
    (out / "index.md").write_text("\n".join(index) + "\n")
    return modules


if __name__ == "__main__":
    target = sys.argv[1] if len(sys.argv) > 1 else str(
        Path(__file__).parent / "api"
    )
    modules = generate(target)
    print(f"Documented {len(modules)} modules into {target}")
