"""
ServeEngine end-to-end over the WSGI routes: batched and unbatched
scoring are numerically equivalent under concurrent clients, coalescing
actually happens, the compiled-program count stays inside the shape
ladder, warmup precompiles it, and admission control maps to 429/504.
"""

import json

import numpy as np
import pytest
from werkzeug.test import Client

from gordo_tpu import serve
from gordo_tpu.serve import DeadlineExceeded, QueueFullError
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
    warm_store,
)

pytestmark = pytest.mark.serve


def _frames_close(got, want, rtol=1e-4, atol=1e-5, path=""):
    """dataframe_to_dict payloads (nested {column: {row: value}}, one
    level deeper for MultiIndex anomaly frames) numerically equal within
    float32 tolerance; non-numeric leaves (timestamps) exactly equal."""
    if isinstance(want, dict):
        assert isinstance(got, dict) and got.keys() == want.keys(), path
        for key in want:
            _frames_close(got[key], want[key], rtol, atol, f"{path}/{key}")
    elif isinstance(want, (int, float)) and not isinstance(want, bool):
        np.testing.assert_allclose(got, want, rtol=rtol, atol=atol, err_msg=path)
    else:
        assert got == want, path


def test_concurrent_clients_batched_matches_unbatched(
    serve_collection_dir, batch_payload
):
    """The acceptance-criteria test: N concurrent single-model requests
    with batching on answer exactly what the unbatched path answers, and
    they coalesce into fewer fused programs than requests."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        reference = {}
        client = Client(app)
        assert serve.get_engine() is None  # the reference runs unbatched
        for name in BATCH_NAMES:
            resp = client.post(
                f"/gordo/v0/{PROJECT}/{name}/prediction", json=batch_payload
            )
            assert resp.status_code == 200
            reference[name] = json.loads(resp.data)["data"]["model-output"]

        # a longer flush window than thread-spawn jitter so the burst
        # lands in one or two fused programs, never nine
        with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
            engine.warmup_collection(serve_collection_dir)
            results = {}

            def hit(i):
                name = BATCH_NAMES[i % len(BATCH_NAMES)]
                resp = Client(app).post(
                    f"/gordo/v0/{PROJECT}/{name}/prediction", json=batch_payload
                )
                assert resp.status_code == 200, resp.data
                results[i] = (name, json.loads(resp.data)["data"]["model-output"])

            errors = run_threads(9, hit)
            assert not errors
            stats = engine.stats()
            assert stats["coalesced"] == 9
            assert stats["batches"] < 9  # requests actually fused

        assert len(results) == 9
        for name, frame in results.values():
            _frames_close(frame, reference[name])


def test_anomaly_route_batched_matches_unbatched(
    serve_collection_dir, batch_payload
):
    """The detector's threshold/confidence math over a micro-batched
    reconstruction answers the same anomaly frame as the unbatched
    route (the detector accepts model_output, so only predict fused)."""
    payload = dict(batch_payload, y=batch_payload["X"])
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        url = f"/gordo/v0/{PROJECT}/batch-a/anomaly/prediction"
        resp = Client(app).post(url, json=payload)
        assert resp.status_code == 200, resp.data
        reference = json.loads(resp.data)["data"]

        with installed_engine() as engine:
            resp = Client(app).post(url, json=payload)
            assert resp.status_code == 200, resp.data
            batched = json.loads(resp.data)["data"]
            assert engine.stats()["coalesced"] == 1

    _frames_close(batched, reference)


def test_program_count_bounded_by_ladder(serve_collection_dir):
    """Arbitrary client row counts mint at most |member ladder| x
    |row ladder| fused programs per spec bucket."""
    fleet = warm_store(serve_collection_dir, BATCH_NAMES)
    config = tiny_config(max_size=8, row_ladder=(8, 32), max_delay_ms=20.0)
    bound = len(serve.member_ladder(8)) * 2
    with installed_engine(config) as engine:
        model = STORE.get_model(serve_collection_dir, "batch-a")

        def hit(i):
            rows = 1 + (i * 7) % 30  # 1..29: spans both rungs
            X = np.random.RandomState(i).rand(rows, 4).astype(np.float32)
            recon = engine.batched_predict(
                serve_collection_dir, "batch-a", model, X
            )
            assert recon is not None and recon.shape == (rows, 4)

        errors = run_threads(12, hit)
        assert not errors
        stats = engine.stats()
        assert stats["requests"] == 12
        assert 0 < stats["programs"] <= bound
        for _, _, members, rows, precision, _ in engine.program_shapes():
            assert members in serve.member_ladder(8)
            assert rows in (8, 32)
            assert precision == "f32"  # the default ladder is pure f32
    del fleet


def test_oversized_and_empty_requests_fall_back(serve_collection_dir):
    """Rows above the top rung (an unbounded shape) and empty inputs
    answer None — the caller's cue to run the model's own predict."""
    warm_store(serve_collection_dir, ["batch-a"])
    model = STORE.get_model(serve_collection_dir, "batch-a")
    with installed_engine(tiny_config(row_ladder=(8, 32))) as engine:
        tall = np.zeros((64, 4), np.float32)
        assert (
            engine.batched_predict(serve_collection_dir, "batch-a", model, tall)
            is None
        )
        empty = np.zeros((0, 4), np.float32)
        assert (
            engine.batched_predict(serve_collection_dir, "batch-a", model, empty)
            is None
        )
        assert engine.stats()["fallback"] == 2


def test_unknown_model_falls_back(serve_collection_dir):
    warm_store(serve_collection_dir, ["batch-a"])
    model = STORE.get_model(serve_collection_dir, "batch-a")
    with installed_engine() as engine:
        assert (
            engine.batched_predict(
                serve_collection_dir, "never-loaded", model, np.zeros((4, 4))
            )
            is None
        )
        assert engine.stats()["fallback"] == 1


def test_warmup_precompiles_every_ladder_shape(serve_collection_dir):
    """Warmup mints exactly |specs| x |member ladder| x |warm rows|
    programs, and is idempotent — the first real request after boot
    hits a compiled program."""
    with installed_engine(tiny_config()) as engine:
        report = engine.warmup_collection(serve_collection_dir)
        # two spec buckets: the shared 4-feature detector spec + odd-one
        assert report["specs"] == 2
        member_rungs = len(serve.member_ladder(engine.config.max_size))
        assert report["programs"] == 2 * member_rungs * 2  # warm rows (8, 32)
        assert engine.stats()["programs"] == report["programs"]

        again = engine.warmup_fleet(STORE.fleet(serve_collection_dir))
        assert again["programs"] == 0

        # a ladder-shaped request adds no new program
        model = STORE.get_model(serve_collection_dir, "batch-a")
        recon = engine.batched_predict(
            serve_collection_dir, "batch-a", model, np.zeros((6, 4), np.float32)
        )
        assert recon is not None
        assert engine.stats()["programs"] == report["programs"]


def test_request_deadline_maps_to_504(client, batch_payload):
    """A request whose batch never flushes inside its deadline answers
    504, not a hang: deadline 50ms versus a 400ms flush window."""
    with installed_engine(tiny_config(max_delay_ms=400.0, deadline_ms=50.0)):
        resp = client.post(
            f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
        )
        assert resp.status_code == 504
        assert "error" in json.loads(resp.data)


class _ShedStub:
    """An engine stand-in whose batched_predict always sheds."""

    def __init__(self, exc):
        self.exc = exc

    def batched_predict(self, *args, **kwargs):
        raise self.exc


@pytest.fixture
def stub_engine():
    def install(exc):
        serve.install_engine(_ShedStub(exc))

    yield install
    serve.install_engine(None)


def test_queue_full_maps_to_429_with_retry_after(
    client, batch_payload, stub_engine
):
    stub_engine(QueueFullError(7, 1.6))
    for url in (
        f"/gordo/v0/{PROJECT}/batch-a/prediction",
        f"/gordo/v0/{PROJECT}/batch-a/anomaly/prediction",
    ):
        payload = dict(batch_payload, y=batch_payload["X"])
        resp = client.post(url, json=payload)
        assert resp.status_code == 429
        assert resp.headers["Retry-After"] == "2"
        assert "retry" in json.loads(resp.data)["error"].lower()


def test_deadline_exceeded_maps_to_504(client, batch_payload, stub_engine):
    stub_engine(DeadlineExceeded("missed"))
    resp = client.post(
        f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
    )
    assert resp.status_code == 504


def test_batching_disabled_is_the_default(client, batch_payload):
    """Without the master switch nothing is installed and the routes
    serve exactly as before (the fallback IS the default)."""
    assert serve.get_engine() is None
    assert not serve.batching_enabled()
    resp = client.post(
        f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
    )
    assert resp.status_code == 200
