"""
Precision-ladder units: the precision vocabulary and its resolution
order, payload dtypes, bucket casting/quantization, the shared parity
math, and the program-cache bound now that programs are keyed by
``|members| × |rows| × |precisions|``.
"""

import numpy as np
import pytest

from gordo_tpu import serve
from gordo_tpu.models.factories import feedforward_hourglass
from gordo_tpu.serve import precision as P
from gordo_tpu.server.fleet_store import STORE

from tests.serve.conftest import (
    BATCH_NAMES,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
    warm_store,
)

pytestmark = [pytest.mark.serve, pytest.mark.precision]


# -- vocabulary ---------------------------------------------------------------


def test_normalize_aliases_and_fallback():
    assert P.normalize("f32") == "f32"
    assert P.normalize("float32") == "f32"
    assert P.normalize("bfloat16") == "bf16"
    assert P.normalize("BF16") == "bf16"
    assert P.normalize("int8") == "int8"
    assert P.normalize("i8") == "int8"
    # unset inherits the default; garbage degrades to it (warn-once)
    assert P.normalize(None) == "f32"
    assert P.normalize("") == "f32"
    assert P.normalize("float8000") == "f32"
    assert P.normalize("garbage", default="bf16") == "bf16"


def test_resolution_order_spec_field_wins_over_env():
    plain = feedforward_hourglass(4)
    declared = feedforward_hourglass(4, precision="int8")
    with temp_env_vars(GORDO_TPU_SERVE_PRECISION="bf16"):
        assert P.serve_precision() == "bf16"
        assert P.resolve_precision(plain) == "bf16"
        assert P.resolve_precision(declared) == "int8"
    # default default: f32
    with temp_env_vars(GORDO_TPU_SERVE_PRECISION=""):
        assert P.resolve_precision(plain) == "f32"
        assert P.resolve_precision(declared) == "int8"
    # an explicit engine-config default beats the env too
    assert P.resolve_precision(plain, "bf16") == "bf16"


def test_spec_precision_field_rides_the_config_surface():
    """The factory kwarg lands on the spec (how a machine config's
    ``precision: bf16`` declares its serving precision), defaults
    unchanged, and two specs differing only in precision are distinct
    (they must never share a fused-program cache entry)."""
    spec = feedforward_hourglass(6)
    assert spec.precision == ""
    bf16 = feedforward_hourglass(6, precision="bf16")
    assert bf16.precision == "bf16"
    assert spec != bf16
    assert hash(spec) != hash(bf16)
    assert bf16.to_dict()["precision"] == "bf16"


def test_payload_dtype_mapping():
    assert P.payload_dtype("f32") == np.float32
    bf16 = P.payload_dtype("bf16")
    # jax ships ml_dtypes, so the reduced payload dtype is bfloat16
    # (2 bytes on the wire to the device) both for bf16 and for int8
    # weight-only serving (activations run bf16)
    assert np.dtype(bf16).itemsize == 2
    assert P.payload_dtype("int8") == bf16


# -- casting / quantization ---------------------------------------------------


@pytest.fixture(scope="module")
def stacked_params():
    import jax

    from gordo_tpu.models.nn import init_feedforward
    from gordo_tpu.parallel.fleet import stack_member_params

    spec = feedforward_hourglass(6)

    class _P:
        def __init__(self, params):
            self.params = params

    members = [
        _P(init_feedforward(jax.random.PRNGKey(i), spec)) for i in range(3)
    ]
    return spec, stack_member_params(members)


def test_cast_bucket_bf16(stacked_params):
    import jax.numpy as jnp

    _, stacked = stacked_params
    cast = P.cast_bucket_params(stacked, "bf16")
    for layer in cast.values():
        assert layer["W"].dtype == jnp.bfloat16
        assert layer["b"].dtype == jnp.bfloat16
    # f32 passes through untouched (identity, not a copy)
    assert P.cast_bucket_params(stacked, "f32") is stacked


def test_quantize_bucket_int8_per_channel(stacked_params):
    import jax.numpy as jnp

    _, stacked = stacked_params
    q = P.cast_bucket_params(stacked, "int8")
    for name, layer in q.items():
        W32 = np.asarray(stacked[name]["W"], np.float32)
        assert layer["W"].dtype == jnp.int8
        # one scale per member per output channel
        assert layer["scale"].shape == (W32.shape[0], 1, W32.shape[-1])
        assert np.asarray(layer["W"]).min() >= -127
        assert np.asarray(layer["W"]).max() <= 127
        # dequantization error bounded by half a quantization step
        dequant = np.asarray(layer["W"], np.float32) * np.asarray(
            layer["scale"], np.float32
        )
        step = np.asarray(layer["scale"], np.float32)
        assert np.all(np.abs(dequant - W32) <= 0.51 * step)


def test_unknown_precision_raises(stacked_params):
    _, stacked = stacked_params
    with pytest.raises(ValueError):
        P.cast_bucket_params(stacked, "fp4")


# -- parity math --------------------------------------------------------------


def test_recon_agreement_identical_and_corrupted():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((64, 4)).astype(np.float32)
    assert P.recon_agreement(a, a)["agreement"] == 1.0
    # a bf16-magnitude perturbation stays inside tolerance
    near = a * (1.0 + 0.004)
    assert P.recon_agreement(a, near, rtol=0.05)["agreement"] == 1.0
    # zeroed weights (the degrade drill's corruption) do not
    corrupt = np.zeros_like(a)
    report = P.recon_agreement(a, corrupt, rtol=0.05)
    assert report["agreement"] < 0.5
    # stacked [members, rows, features] batches count feature-vector rows
    stacked = np.stack([a, a])
    assert P.recon_agreement(stacked, stacked)["rows"] == 128
    # shape mismatch is disagreement, not a crash
    assert P.recon_agreement(a, a[:10])["agreement"] == 0.0


def test_verdict_agreement_threshold_math():
    from sklearn.preprocessing import MinMaxScaler

    rng = np.random.default_rng(1)
    y = rng.random((100, 4)).astype(np.float32)
    scaler = MinMaxScaler().fit(y)
    # recon_a reconstructs half the rows well and half badly → verdicts
    # split around a mid threshold
    recon_a = y.copy()
    recon_a[50:] += 1.0
    report = P.verdict_agreement(recon_a, recon_a.copy(), y, scaler, 0.5)
    assert report["mode"] == "verdict"
    assert report["agreement"] == 1.0
    assert report["flagged_f32"] == report["flagged_reduced"] == 50
    # flipping the reduced copy's verdicts tanks agreement
    flipped = y.copy()
    flipped[:50] += 1.0
    report = P.verdict_agreement(recon_a, flipped, y, scaler, 0.5)
    assert report["agreement"] == 0.0
    # no threshold → falls back to the closeness mode
    report = P.verdict_agreement(recon_a, recon_a, y, None, None)
    assert report["mode"] == "recon"


# -- program-cache bound with the precision axis ------------------------------


def test_program_cache_bound_covers_precisions(serve_collection_dir):
    """Mixed f32/bf16 traffic mints at most |member ladder| × |row
    ladder| × |precisions| programs, and the shapes report carries the
    precision axis."""
    warm_store(serve_collection_dir, BATCH_NAMES)
    model = STORE.get_model(serve_collection_dir, "batch-a")
    config = tiny_config(max_size=8, row_ladder=(8, 32), max_delay_ms=20.0)
    bound = len(serve.member_ladder(8)) * 2 * 2  # two precisions in play
    # gate off: this test bounds the cache, the gate has its own tests
    with temp_env_vars(GORDO_TPU_PRECISION_GATE="0"):
        with installed_engine(config) as engine:

            def hit(i):
                rows = 1 + (i * 7) % 30
                X = np.random.RandomState(i).rand(rows, 4).astype(np.float32)
                engine.config.precision = "bf16" if i % 2 else "f32"
                recon = engine.batched_predict(
                    serve_collection_dir, "batch-a", model, X
                )
                assert recon is not None and recon.shape == (rows, 4)

            # sequential on purpose: the per-request precision flips
            # through the shared engine config, which is only
            # deterministic single-threaded
            for i in range(12):
                hit(i)
            stats = engine.stats()
            assert 0 < stats["programs"] <= bound
            precisions = {p for (_, _, _, _, p, _) in engine.program_shapes()}
            assert precisions == {"f32", "bf16"}
            coalesced = stats["precision"]["coalesced"]
            assert coalesced.get("f32", 0) > 0
            assert coalesced.get("bf16", 0) > 0


def test_gate_verdict_invalidated_by_bucket_membership_growth(
    serve_collection_dir,
):
    """Review fix: a PASS verdict gated on the old membership must not
    let a later-loaded member of the same spec serve reduced unverified
    — verdicts are epoch-stamped like the cast buckets and read as
    absent (→ re-gate) once the bucket grows."""
    from gordo_tpu.server.fleet_store import RevisionFleet

    fleet = RevisionFleet(serve_collection_dir)
    fleet.warm(["batch-a", "batch-b"])  # two of the three spec members
    spec = fleet.loaded_specs()["batch-a"]
    governor = P.PrecisionGovernor()
    assert governor.effective_precision(fleet, spec, "bf16") == "bf16"
    state = fleet.precision_state(spec, "bf16")
    assert state is not None and set(state["members"]) == {"batch-a", "batch-b"}
    assert len(fleet.precision_reports()) == 1

    fleet.model("batch-c")  # the bucket grows: epoch bumps
    assert fleet.precision_state(spec, "bf16") is None
    assert fleet.precision_reports() == []
    # the next request re-gates over the FULL membership
    assert governor.effective_precision(fleet, spec, "bf16") == "bf16"
    state = fleet.precision_state(spec, "bf16")
    assert set(state["members"]) == {"batch-a", "batch-b", "batch-c"}
