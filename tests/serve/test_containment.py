"""
Engine-level fault containment: injected device faults against the
fused serving programs must bisect down to the poisonous member (the
serving twin of PR 2's `_run_bucket_degraded` ladder), innocents must
keep scoring, non-finite poison must be caught, OOM must demote its
ladder rung, and repeated isolated failures must trip the member's
circuit breaker.
"""

import numpy as np
import pytest

from gordo_tpu.serve import MemberQuarantined, ServeDeviceError
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.utils.faults import FaultRule, InjectedDeviceError, inject

from tests.serve.conftest import (
    BATCH_NAMES,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
    warm_store,
)

pytestmark = [pytest.mark.serve, pytest.mark.chaos]

ROWS = 6
FEATURES = 4


def payload_rows(seed=0):
    rng = np.random.default_rng(seed)
    return rng.random((ROWS, FEATURES)).astype(np.float32)


def concurrent_predict(engine, collection_dir, names, X=None):
    """Score `names` concurrently through the engine (one thread per
    name, coalescing window >> spawn jitter); returns name -> result
    array or the raised exception."""
    fleet = warm_store(collection_dir)
    outcomes = {}

    def hit(i):
        name = names[i]
        try:
            outcomes[name] = engine.batched_predict(
                collection_dir, name, fleet.model(name),
                payload_rows() if X is None else X,
            )
        except Exception as exc:  # noqa: BLE001 - the assertion target
            outcomes[name] = exc

    errors = run_threads(len(names), hit)
    assert not errors
    return outcomes


def test_transient_device_fault_bisects_and_everyone_scores(
    serve_collection_dir,
):
    """One injected device error against a coalesced batch: bisection
    retries the halves and every rider still gets its reconstruction."""
    with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
        reference = concurrent_predict(
            engine, serve_collection_dir, BATCH_NAMES
        )
        rule = FaultRule(
            "serve_device_program",
            match="*:f32:batch-a",
            times=1,
            exc=InjectedDeviceError,
        )
        with inject(rule):
            outcomes = concurrent_predict(
                engine, serve_collection_dir, BATCH_NAMES
            )
        assert rule.fired == 1
        for name in BATCH_NAMES:
            assert isinstance(outcomes[name], np.ndarray), outcomes[name]
            np.testing.assert_allclose(
                outcomes[name], reference[name], rtol=1e-5, atol=1e-6
            )
        stats = engine.stats()
        assert stats["device_errors"] >= 1
        assert stats["batch_bisects"] >= 1
        assert stats["members_isolated"] == 0
        assert stats["breaker"]["tracked"] == 0 or (
            stats["breaker"]["open"] == 0
        )


def test_poison_member_fails_alone_and_breaker_trips(serve_collection_dir):
    """A persistently-poisonous member: innocents answer normally on
    every batch, only the poison rider errors, and past the threshold
    the breaker quarantines it (503 material) instead of re-bisecting
    every batch it touches."""
    with temp_env_vars(
        GORDO_TPU_BREAKER_THRESHOLD="2",
        GORDO_TPU_BREAKER_COOLDOWN_S="30",
    ):
        with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
            rule = FaultRule(
                "serve_device_program",
                match="*:f32:batch-a",
                times=None,
                exc=InjectedDeviceError,
            )
            with inject(rule):
                first = concurrent_predict(
                    engine, serve_collection_dir, BATCH_NAMES
                )
                assert isinstance(first["batch-a"], ServeDeviceError)
                assert isinstance(first["batch-b"], np.ndarray)
                assert isinstance(first["batch-c"], np.ndarray)
                second = concurrent_predict(
                    engine, serve_collection_dir, BATCH_NAMES
                )
                # second isolated failure crossed the threshold: tripped
                assert isinstance(second["batch-a"], ServeDeviceError)
                third = concurrent_predict(
                    engine, serve_collection_dir, BATCH_NAMES
                )
                # quarantined: rejected BEFORE riding a batch, with a
                # Retry-After; innocents still score
                assert isinstance(third["batch-a"], MemberQuarantined)
                assert third["batch-a"].retry_after_s > 0
                assert isinstance(third["batch-b"], np.ndarray)
            stats = engine.stats()
            assert stats["members_isolated"] >= 2
            assert stats["breaker_trips"] == 1
            assert stats["breaker_rejects"] >= 1
            snap = stats["breaker"]
            assert snap["open"] == 1
            assert snap["members"][0]["member"] == "batch-a"


def test_breaker_recovers_via_half_open_probe(serve_collection_dir):
    """Faults stop; after the cooldown the next request probes the
    member through a real fused program and recovery closes the
    breaker."""
    import threading

    with temp_env_vars(
        GORDO_TPU_BREAKER_THRESHOLD="1",
        GORDO_TPU_BREAKER_COOLDOWN_S="0.2",
    ):
        with installed_engine(tiny_config(max_delay_ms=30.0)) as engine:
            fleet = warm_store(serve_collection_dir)
            model = fleet.model("batch-a")
            rule = FaultRule(
                "serve_device_program",
                match="*:f32:batch-a",
                times=1,
                exc=InjectedDeviceError,
            )
            with inject(rule):
                with pytest.raises(ServeDeviceError):
                    engine.batched_predict(
                        serve_collection_dir, "batch-a", model, payload_rows()
                    )
            with pytest.raises(MemberQuarantined):
                engine.batched_predict(
                    serve_collection_dir, "batch-a", model, payload_rows()
                )
            threading.Event().wait(0.3)
            # the probe request: admitted, scores cleanly, closes the
            # breaker — and everything after flows freely
            recon = engine.batched_predict(
                serve_collection_dir, "batch-a", model, payload_rows()
            )
            assert isinstance(recon, np.ndarray)
            assert engine.stats()["breaker"]["open"] == 0
            recon = engine.batched_predict(
                serve_collection_dir, "batch-a", model, payload_rows()
            )
            assert isinstance(recon, np.ndarray)


def test_nonfinite_output_is_member_poison(serve_collection_dir):
    """A member answering NaN rows for FINITE input fails alone (and
    feeds its breaker) — NaN poison must not ride the wire as a silent
    verdict corruption, and must not touch innocent riders."""
    with temp_env_vars(GORDO_TPU_BREAKER_THRESHOLD="10"):
        with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
            rule = FaultRule(
                "serve_member_poison", match="*:f32:batch-b", times=None
            )
            with inject(rule):
                outcomes = concurrent_predict(
                    engine, serve_collection_dir, BATCH_NAMES
                )
            assert isinstance(outcomes["batch-b"], ServeDeviceError)
            assert isinstance(outcomes["batch-a"], np.ndarray)
            assert np.isfinite(outcomes["batch-a"]).all()
            assert isinstance(outcomes["batch-c"], np.ndarray)
            stats = engine.stats()
            assert stats["nonfinite_outputs"] >= 1
            assert stats["members_isolated"] >= 1


def test_nonfinite_input_stays_the_clients_problem(serve_collection_dir):
    """NaN rows IN mean NaN rows OUT — exactly what the unbatched path
    answers; the member is not blamed and the breaker stays untouched."""
    with installed_engine(tiny_config(max_delay_ms=30.0)) as engine:
        fleet = warm_store(serve_collection_dir)
        X = payload_rows()
        X[2, 1] = np.nan
        recon = engine.batched_predict(
            serve_collection_dir, "batch-a", fleet.model("batch-a"), X
        )
        assert isinstance(recon, np.ndarray)
        stats = engine.stats()
        assert stats["nonfinite_outputs"] == 0
        assert stats["breaker"]["tracked"] == 0


def test_single_member_oom_demotes_rung_and_falls_back(serve_collection_dir):
    """RESOURCE_EXHAUSTED with nothing left to bisect is a SHAPE
    problem: the request hands back to the unbatched path (no error, no
    breaker penalty) and the rung is demoted so the engine never
    retries it."""
    with installed_engine(tiny_config(max_delay_ms=30.0)) as engine:
        fleet = warm_store(serve_collection_dir)
        model = fleet.model("batch-a")
        # default serve_device_program exception message carries
        # RESOURCE_EXHAUSTED — the OOM-shaped fault
        rule = FaultRule(
            "serve_device_program", match="*:f32:batch-a", times=1
        )
        with inject(rule):
            recon = engine.batched_predict(
                serve_collection_dir, "batch-a", model, payload_rows()
            )
        assert recon is None  # unbatched fallback, not a 500
        stats = engine.stats()
        assert stats["oom_fallbacks"] == 1
        assert stats["rung_demotions"] == 1
        assert stats["breaker"]["tracked"] == 0  # OOM never blames the member
        # the demoted rung is remembered: the same request shape now
        # falls back WITHOUT riding a batch (no fused program retry)
        assert (
            engine.batched_predict(
                serve_collection_dir, "batch-a", model, payload_rows()
            )
            is None
        )


def test_coalesced_oom_demotes_member_axis(serve_collection_dir):
    """A multi-member RESOURCE_EXHAUSTED halves the member-axis cap for
    that program key while bisection rescues the in-flight batch."""
    with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
        rule = FaultRule("serve_device_program", match="*:f32:*", times=1)
        with inject(rule):
            outcomes = concurrent_predict(
                engine, serve_collection_dir, BATCH_NAMES
            )
        for name in BATCH_NAMES:
            assert isinstance(outcomes[name], np.ndarray)
        stats = engine.stats()
        assert stats["rung_demotions"] >= 1
        assert list(stats["demoted_rungs"]["members"].values()) == [2]


def test_scatter_fault_is_isolated_to_its_rider(serve_collection_dir):
    with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
        rule = FaultRule("serve_scatter", match="*:f32:batch-c", times=1)
        with inject(rule):
            outcomes = concurrent_predict(
                engine, serve_collection_dir, BATCH_NAMES
            )
        assert isinstance(outcomes["batch-c"], ServeDeviceError)
        assert isinstance(outcomes["batch-a"], np.ndarray)
        assert isinstance(outcomes["batch-b"], np.ndarray)


def test_reduced_precision_faults_degrade_to_f32_before_breaker(
    serve_collection_dir,
):
    """The precision-degradation ladder (the PR 14 path under device
    errors): a bf16 program that starts faulting degrades that bucket to
    f32 — requests keep answering, the breaker is NOT charged — and only
    when f32 fails too does the member trip."""
    with temp_env_vars(
        GORDO_TPU_SERVE_PRECISION="bf16",
        GORDO_TPU_PRECISION_GATE="0",
        GORDO_TPU_BREAKER_THRESHOLD="2",
        GORDO_TPU_BREAKER_COOLDOWN_S="30",
    ):
        with installed_engine(
            tiny_config(serve_precision="bf16")
        ) as engine:
            fleet = warm_store(serve_collection_dir)
            model = fleet.model("batch-a")
            bf16_rule = FaultRule(
                "serve_device_program",
                match="*:bf16:*",
                times=None,
                exc=InjectedDeviceError,
            )
            with inject(bf16_rule):
                recon = engine.batched_predict(
                    serve_collection_dir, "batch-a", model, payload_rows()
                )
                # served — at f32, after the bucket degraded
                assert isinstance(recon, np.ndarray)
                stats = engine.stats()
                assert stats["precision_degraded"] >= 1
                assert stats["breaker"]["tracked"] == 0
                assert stats["breaker"]["degraded_buckets"] == 1
                # the degrade is sticky: the next request goes straight
                # to f32 (the bf16 rule never fires again)
                fired = bf16_rule.fired
                recon = engine.batched_predict(
                    serve_collection_dir, "batch-a", model, payload_rows()
                )
                assert isinstance(recon, np.ndarray)
                assert bf16_rule.fired == fired
            # the fleet's gate verdict narrates the degrade too
            reports = fleet.precision_reports()
            assert any(
                r.get("precision") == "bf16" and r.get("passed") is False
                for r in reports
            )
            # phase two: f32 faults as well -> the breaker takes over
            f32_rule = FaultRule(
                "serve_device_program",
                match="*:f32:batch-a",
                times=None,
                exc=InjectedDeviceError,
            )
            with inject(f32_rule):
                with pytest.raises(ServeDeviceError):
                    engine.batched_predict(
                        serve_collection_dir, "batch-a", model, payload_rows()
                    )
                with pytest.raises(ServeDeviceError):
                    engine.batched_predict(
                        serve_collection_dir, "batch-a", model, payload_rows()
                    )
                with pytest.raises(MemberQuarantined):
                    engine.batched_predict(
                        serve_collection_dir, "batch-a", model, payload_rows()
                    )
            assert engine.stats()["breaker"]["open"] == 1


def test_reduced_precision_oom_falls_back_without_degrading_the_bucket(
    serve_collection_dir,
):
    """An isolated RESOURCE_EXHAUSTED on a bf16 program is a SHAPE
    problem: unbatched fallback, rung demoted — but the bucket's parity
    verdict must NOT fail (OOM says nothing about bf16 correctness, and
    a double-width f32 retry would only OOM harder)."""
    with temp_env_vars(
        GORDO_TPU_SERVE_PRECISION="bf16", GORDO_TPU_PRECISION_GATE="0"
    ):
        with installed_engine(
            tiny_config(serve_precision="bf16")
        ) as engine:
            fleet = warm_store(serve_collection_dir)
            model = fleet.model("batch-a")
            # the session fleet may carry verdicts from earlier tests:
            # what matters is that THIS drill adds none
            reports_before = fleet.precision_reports()
            # default exception message carries RESOURCE_EXHAUSTED
            rule = FaultRule(
                "serve_device_program", match="*:bf16:batch-a", times=1
            )
            with inject(rule):
                recon = engine.batched_predict(
                    serve_collection_dir, "batch-a", model, payload_rows()
                )
            assert recon is None  # unbatched fallback, not an error
            stats = engine.stats()
            assert stats["oom_fallbacks"] == 1
            assert stats["rung_demotions"] == 1
            assert stats["breaker"]["degraded_buckets"] == 0
            assert stats["breaker"]["tracked"] == 0
            # no NEW failed verdict: OOM says nothing about parity
            assert fleet.precision_reports() == reports_before


def test_breaker_ledger_feed_uses_the_wired_anchor(
    serve_collection_dir, tmp_path
):
    """build_app wires engine.ledger_anchor through the app's
    configurable collection-dir env var; the transition feed must honor
    it instead of hardcoding MODEL_COLLECTION_DIR."""
    from gordo_tpu import telemetry
    from gordo_tpu.telemetry.fleet_health import reset_ledgers

    reset_ledgers()
    try:
        with temp_env_vars(GORDO_TPU_BREAKER_THRESHOLD="1"):
            with installed_engine(tiny_config()) as engine:
                engine.ledger_anchor = str(tmp_path)
                fleet = warm_store(serve_collection_dir)
                spec = fleet.loaded_specs()["batch-a"]
                engine.breakers.record_failure(
                    fleet, spec, "batch-a", RuntimeError("boom")
                )
                doc = telemetry.ledger_for(str(tmp_path)).document()
                assert doc["machines"]["batch-a"]["breaker"]["state"] == "open"
    finally:
        reset_ledgers()
