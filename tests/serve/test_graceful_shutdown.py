"""
Graceful shutdown: queued micro-batcher futures must RESOLVE on
SIGTERM-driven drains — concurrent clients get real responses, never a
dead future — while the healthcheck flips to 503 so load balancers
stop routing here.
"""

import json
import signal
import threading
import time

import pytest
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server.app import drain_and_stop, install_graceful_shutdown

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    installed_engine,
    temp_env_vars,
    tiny_config,
)

pytestmark = pytest.mark.serve


def test_drain_resolves_queued_batches_with_concurrent_clients(
    serve_collection_dir, batch_payload
):
    """Clients whose requests are QUEUED in the batcher when the drain
    starts still get 200s (today's failure mode: their futures die with
    the process); post-drain requests serve unbatched."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        # a flush window long enough that the drain lands mid-queue
        with installed_engine(tiny_config(max_delay_ms=5000.0)) as engine:
            statuses = [None] * 4

            def hit(i):
                resp = Client(app).post(
                    f"/gordo/v0/{PROJECT}/{BATCH_NAMES[i % 3]}/prediction",
                    json=batch_payload,
                )
                statuses[i] = resp.status_code

            threads = [
                threading.Thread(target=hit, args=(i,)) for i in range(4)
            ]
            for thread in threads:
                thread.start()
            deadline = time.monotonic() + 10.0
            while engine._batcher.pending() < 4:
                assert time.monotonic() < deadline, engine.stats()
                time.sleep(0.005)

            # SIGTERM path: drain flushes everything queued
            drain_and_stop(app, server=None, engine=engine)
            for thread in threads:
                thread.join(timeout=30)
            assert statuses == [200, 200, 200, 200], statuses
            assert engine._batcher.pending() == 0

            # draining server: healthcheck 503 (LBs stop sending) but
            # already-connected clients still get served, unbatched
            assert Client(app).get("/healthcheck").status_code == 503
            resp = Client(app).post(
                f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
            )
            assert resp.status_code == 200, resp.data
            assert "model-output" in json.loads(resp.data)["data"]


def test_drain_without_engine_still_flips_healthcheck(serve_collection_dir):
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        assert Client(app).get("/healthcheck").status_code == 200
        drain_and_stop(app, server=None, engine=None)
        assert Client(app).get("/healthcheck").status_code == 503


def test_install_graceful_shutdown_registers_sigterm(serve_collection_dir):
    """The werkzeug fallback path wires SIGTERM/SIGINT to the drain
    (restored afterwards so the test process keeps its handlers)."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        previous_term = signal.getsignal(signal.SIGTERM)
        previous_int = signal.getsignal(signal.SIGINT)
        try:
            handler = install_graceful_shutdown(app, server=None)
            assert handler is not None
            assert signal.getsignal(signal.SIGTERM) is handler
            handler(signal.SIGTERM, None)
            deadline = time.monotonic() + 10.0
            while not app.draining:
                assert time.monotonic() < deadline
                time.sleep(0.01)
        finally:
            signal.signal(signal.SIGTERM, previous_term)
            signal.signal(signal.SIGINT, previous_int)
