"""Shape-ladder semantics: rung selection, env override, overflow."""

import pytest

from gordo_tpu.serve import ladder

from tests.server.conftest import temp_env_vars

pytestmark = pytest.mark.serve


def test_parse_ladder_sorts_and_dedups():
    assert ladder.parse_ladder("128, 32,32,512") == (32, 128, 512)


@pytest.mark.parametrize("bad", ["", "0,32", "-4", "a,b"])
def test_parse_ladder_rejects(bad):
    with pytest.raises(ValueError):
        ladder.parse_ladder(bad)


def test_row_ladder_env_override_and_fallback():
    with temp_env_vars(GORDO_TPU_BATCH_ROW_LADDER="16,64"):
        assert ladder.row_ladder() == (16, 64)
    with temp_env_vars(GORDO_TPU_BATCH_ROW_LADDER="not-a-ladder"):
        # malformed env degrades to the default, never crashes serving
        assert ladder.row_ladder() == ladder.DEFAULT_ROW_LADDER
    assert ladder.row_ladder() == ladder.DEFAULT_ROW_LADDER


def test_member_ladder_covers_max_size():
    assert ladder.member_ladder(1) == (1,)
    assert ladder.member_ladder(8) == (1, 2, 4, 8)
    # non-power max still gets a covering top rung
    assert ladder.member_ladder(6) == (1, 2, 4, 8)


@pytest.mark.parametrize(
    "n,expected", [(1, 8), (8, 8), (9, 32), (32, 32), (33, None)]
)
def test_pad_to_first_covering_rung(n, expected):
    assert ladder.pad_to(n, (8, 32)) == expected
