"""
MicroBatcher scheduling semantics, device-free: flush triggers (size,
age, pressure), admission control (queue depth, deadlines, cancels),
key isolation, and shutdown draining.
"""

import threading
import time

import pytest

from gordo_tpu.serve.batcher import (
    BatcherStopped,
    BatchItem,
    DeadlineExceeded,
    MicroBatcher,
    QueueFullError,
)

pytestmark = pytest.mark.serve


class Collector:
    """Runner stub: records batches and resolves futures with the key."""

    def __init__(self, delay_s: float = 0.0):
        self.batches = []
        self.delay_s = delay_s
        self._lock = threading.Lock()

    def __call__(self, key, items):
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.batches.append((key, [item.name for item in items]))
        for item in items:
            item.future.set_result(item.name)


def make(runner, **kwargs):
    defaults = dict(max_size=4, max_delay_s=0.02, queue_depth=32, dispatchers=1)
    defaults.update(kwargs)
    return MicroBatcher(runner, **defaults)


def test_flush_on_size_before_delay():
    runner = Collector()
    batcher = make(runner, max_size=3, max_delay_s=5.0)
    try:
        futures = [batcher.submit("k", BatchItem(f"r{i}", None)) for i in range(3)]
        assert [f.result(timeout=5) for f in futures] == ["r0", "r1", "r2"]
        assert runner.batches == [("k", ["r0", "r1", "r2"])]
    finally:
        batcher.shutdown()


def test_flush_on_age_when_batch_never_fills():
    runner = Collector()
    batcher = make(runner, max_size=100, max_delay_s=0.02)
    try:
        future = batcher.submit("k", BatchItem("lonely", None))
        assert future.result(timeout=5) == "lonely"
    finally:
        batcher.shutdown()


def test_flush_on_pressure_across_keys():
    runner = Collector()
    # neither key fills max_size, but total pressure forces a flush well
    # before the (deliberately huge) age trigger
    batcher = make(
        runner, max_size=100, max_delay_s=60.0, queue_depth=32, pressure_depth=4
    )
    try:
        futures = [
            batcher.submit(f"k{i % 2}", BatchItem(f"r{i}", None)) for i in range(4)
        ]
        for future in futures:
            future.result(timeout=5)
        assert len(runner.batches) >= 1
    finally:
        batcher.shutdown()


def test_keys_never_share_a_batch():
    runner = Collector()
    batcher = make(runner, max_size=8, max_delay_s=0.02)
    try:
        futures = [
            batcher.submit(f"spec-{i % 2}", BatchItem(f"r{i}", None))
            for i in range(6)
        ]
        for future in futures:
            future.result(timeout=5)
        for key, names in runner.batches:
            assert {n for n in names} <= {f"r{i}" for i in range(6) if f"spec-{i % 2}" == key}
    finally:
        batcher.shutdown()


def test_queue_full_rejects_with_retry_after():
    block = threading.Event()

    def stuck(key, items):
        block.wait(timeout=10)
        for item in items:
            item.future.set_result(None)

    batcher = MicroBatcher(
        stuck, max_size=1, max_delay_s=0.0, queue_depth=2, dispatchers=1,
        retry_after_s=3.0,
    )
    try:
        batcher.submit("k", BatchItem("r0", None))  # occupies the dispatcher
        time.sleep(0.05)
        batcher.submit("k", BatchItem("r1", None))
        batcher.submit("k", BatchItem("r2", None))
        with pytest.raises(QueueFullError) as excinfo:
            batcher.submit("k", BatchItem("r3", None))
        assert excinfo.value.retry_after_s == 3.0
    finally:
        block.set()
        batcher.shutdown()


def test_expired_item_is_shed_not_scored():
    runner = Collector()
    shed = []
    batcher = MicroBatcher(
        runner, max_size=4, max_delay_s=0.05, queue_depth=8,
        on_shed=lambda reason, n: shed.append(reason),
    )
    try:
        expired = BatchItem("late", None, deadline=time.monotonic() - 1.0)
        future = batcher.submit("k", expired)
        with pytest.raises((DeadlineExceeded, Exception)):
            future.result(timeout=5)
        assert all("late" not in names for _, names in runner.batches)
        assert "deadline" in shed
    finally:
        batcher.shutdown()


def test_cancelled_future_skips_execution():
    runner = Collector()
    batcher = make(runner, max_size=4, max_delay_s=0.05)
    try:
        item = BatchItem("gone", None)
        future = batcher.submit("k", item)
        assert future.cancel()  # waiter gave up before the flush
        time.sleep(0.15)
        assert all("gone" not in names for _, names in runner.batches)
    finally:
        batcher.shutdown()


def test_shutdown_drains_queued_work():
    runner = Collector(delay_s=0.01)
    # age/size triggers deliberately unreachable: only the drain flushes
    batcher = make(runner, max_size=100, max_delay_s=60.0)
    futures = [batcher.submit("k", BatchItem(f"r{i}", None)) for i in range(5)]
    batcher.shutdown(drain=True)
    assert [f.result(timeout=1) for f in futures] == [f"r{i}" for i in range(5)]


def test_shutdown_without_drain_resolves_waiters():
    runner = Collector()
    batcher = make(runner, max_size=100, max_delay_s=60.0)
    future = batcher.submit("k", BatchItem("r0", None))
    batcher.shutdown(drain=False)
    with pytest.raises(Exception):  # cancelled or BatcherStopped
        future.result(timeout=1)
    with pytest.raises(BatcherStopped):
        batcher.submit("k", BatchItem("r1", None))


def test_inline_flush_runs_size_batch_on_submitting_thread():
    ran_on = []

    def runner(key, items):
        ran_on.append(threading.current_thread())
        for item in items:
            item.future.set_result(item.name)

    batcher = make(runner, max_size=3, max_delay_s=60.0, inline_flush=True)
    try:
        futures = [batcher.submit("k", BatchItem(f"r{i}", None)) for i in range(3)]
        # the third submit filled the batch and ran it inline — no
        # dispatcher handoff, so results exist before any wait
        assert [f.result(timeout=0) for f in futures] == ["r0", "r1", "r2"]
        assert ran_on == [threading.current_thread()]
    finally:
        batcher.shutdown()


def test_inline_flush_partial_batches_still_age_out():
    runner = Collector()
    batcher = make(runner, max_size=100, max_delay_s=0.02, inline_flush=True)
    try:
        future = batcher.submit("k", BatchItem("lonely", None))
        assert future.result(timeout=5) == "lonely"  # dispatcher age flush
    finally:
        batcher.shutdown()


def test_oversize_queue_splits_into_max_size_batches():
    runner = Collector()
    batcher = make(runner, max_size=2, max_delay_s=5.0)
    try:
        futures = [batcher.submit("k", BatchItem(f"r{i}", None)) for i in range(6)]
        for future in futures:
            future.result(timeout=5)
        assert sorted(len(names) for _, names in runner.batches) == [2, 2, 2]
    finally:
        batcher.shutdown()


def test_runner_crash_gives_each_rider_its_own_exception_instance():
    """The shared-exception fan-out fix: one exception object handed to
    N request-handler threads is re-raised (and its traceback mutated)
    concurrently — every rider must get a distinct clone instead."""

    class WeirdError(Exception):
        pass

    def crash(key, items):
        raise WeirdError("program exploded")

    batcher = make(crash, max_size=4, max_delay_s=0.01)
    try:
        futures = [
            batcher.submit("k", BatchItem(f"r{i}", None)) for i in range(3)
        ]
        raised = []
        for future in futures:
            with pytest.raises(WeirdError) as excinfo:
                future.result(timeout=5)
            raised.append(excinfo.value)
        assert len({id(exc) for exc in raised}) == 3  # three instances
        assert {str(exc) for exc in raised} == {"program exploded"}
        # the original crash rides along as the cause for the log
        assert all(type(exc.__cause__) is WeirdError for exc in raised)
    finally:
        batcher.shutdown()


def test_runner_crash_clone_degrades_for_odd_constructors():
    from gordo_tpu.serve.batcher import clone_exception

    class Odd(Exception):
        def __init__(self, a, b):  # can't rebuild from args=() spellings
            super().__init__(f"{a}/{b}")
            self.args = ()

    original = Odd("x", "y")
    clone = clone_exception(original)
    assert isinstance(clone, RuntimeError)
    assert clone.__cause__ is original
