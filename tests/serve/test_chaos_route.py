"""
Route-level chaos drills: concurrent WSGI clients + injected device
faults against one member of a coalesced fleet. The contract under
test is the PR's acceptance criterion — innocent riders see ZERO 5xx,
the poison member walks the documented error ladder (500 isolated →
503 + Retry-After quarantined → 200 after the half-open probe), the
health ledger narrates the trip/recovery, and a hot-swap mid-drill
drops nothing.
"""

import json
import os
import threading

import numpy as np
import pytest
from werkzeug.test import Client

from gordo_tpu import telemetry
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.telemetry.fleet_health import (
    breaker_tripped_machines,
    reset_ledgers,
)
from gordo_tpu.utils.faults import FaultRule, InjectedDeviceError, inject

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
    warm_store,
)

pytestmark = [pytest.mark.serve, pytest.mark.chaos]

POISON = "batch-a"
INNOCENTS = [n for n in BATCH_NAMES if n != POISON]


@pytest.fixture
def clean_ledgers(serve_collection_dir):
    """Ledger snapshots land in the session-scoped collection dir; drop
    the in-process registry and the files so drills stay independent."""
    reset_ledgers()
    yield
    reset_ledgers()
    for entry in list(os.listdir(serve_collection_dir)):
        if entry.startswith("fleet_health"):
            os.remove(os.path.join(serve_collection_dir, entry))


def post(app, name, payload):
    return Client(app).post(
        f"/gordo/v0/{PROJECT}/{name}/prediction", json=payload
    )


def test_chaos_drill_innocents_zero_5xx_breaker_trips_and_recovers(
    serve_collection_dir, batch_payload, clean_ledgers
):
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir,
        GORDO_TPU_SERVE_WARMUP="0",
        GORDO_TPU_BREAKER_THRESHOLD="2",
        GORDO_TPU_BREAKER_COOLDOWN_S="0.4",
        GORDO_TPU_HEALTH_HEARTBEAT="0",
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        with installed_engine(tiny_config(max_delay_ms=60.0)) as engine:
            warm_store(serve_collection_dir)
            statuses = {name: [] for name in BATCH_NAMES}
            lock = threading.Lock()
            stop = threading.Event()

            def hammer(i):
                # 8 concurrent route-level clients over the whole fleet
                name = BATCH_NAMES[i % len(BATCH_NAMES)]
                while not stop.is_set():
                    resp = post(app, name, batch_payload)
                    with lock:
                        statuses[name].append(resp.status_code)

            rule = FaultRule(
                "serve_device_program",
                match=f"*:f32:{POISON}",
                times=None,
                exc=InjectedDeviceError,
            )
            threads = [
                threading.Thread(target=hammer, args=(i,), daemon=True)
                for i in range(8)
            ]
            with inject(rule):
                for thread in threads:
                    thread.start()
                threading.Event().wait(2.0)
                stop.set()
                for thread in threads:
                    thread.join(timeout=30)

            # the containment contract: innocent riders NEVER 5xx
            for name in INNOCENTS:
                codes = statuses[name]
                assert codes, f"no traffic reached {name}"
                assert all(c == 200 for c in codes), {
                    name: sorted(set(codes))
                }
            # the poison member walked the ladder: isolated 500s, then
            # the breaker's 503 quarantine
            poison_codes = set(statuses[POISON])
            assert 500 in poison_codes
            assert 503 in poison_codes
            assert not poison_codes - {500, 503}
            stats = engine.stats()
            assert stats["breaker_trips"] >= 1
            assert stats["breaker"]["open"] == 1

            # 503 carries Retry-After derived from the breaker backoff
            resp = post(app, POISON, batch_payload)
            assert resp.status_code == 503
            assert int(resp.headers["Retry-After"]) >= 1
            assert "quarantined" in json.loads(resp.data)["error"]

            # the ledger narrated the trip (what the lifecycle
            # supervisor reads to nominate a rebuild)
            doc = telemetry.ledger_for(serve_collection_dir).document()
            breaker = doc["machines"][POISON]["breaker"]
            assert breaker["state"] == "open"
            assert breaker["trips"] >= 1
            assert doc["machines"][POISON]["health"]["state"] == "quarantined"
            assert POISON in breaker_tripped_machines(serve_collection_dir)
            # quarantine 503s are backpressure, not fresh error marks:
            # the error count stops growing once the breaker is open
            errors_now = doc["machines"][POISON]["serving"]["errors"]
            post(app, POISON, batch_payload)
            doc = telemetry.ledger_for(serve_collection_dir).document()
            assert doc["machines"][POISON]["serving"]["errors"] == errors_now

            # recovery: faults stopped with the inject() exit; after the
            # cooldown the half-open probe scores and the member serves
            deadline = threading.Event()
            for _ in range(20):
                deadline.wait(0.15)
                resp = post(app, POISON, batch_payload)
                if resp.status_code == 200:
                    break
            assert resp.status_code == 200, resp.data
            assert engine.stats()["breaker"]["open"] == 0
            doc = telemetry.ledger_for(serve_collection_dir).document()
            assert doc["machines"][POISON]["breaker"]["state"] == "closed"
            assert breaker_tripped_machines(serve_collection_dir) == {}


def test_hot_swap_mid_faults_drops_nothing_for_innocents(
    serve_collection_dir, batch_payload, clean_ledgers, tmp_path
):
    """A lifecycle hot-swap while device faults are firing: innocent
    riders still see zero 5xx across the swap, and the swapped-in
    revision starts with a clean breaker slate."""
    from gordo_tpu.lifecycle import publish_canary

    root = os.path.dirname(serve_collection_dir)
    base_revision = os.path.basename(serve_collection_dir)
    alt_dir = publish_canary(
        root, base_revision, serve_collection_dir, [], "9900000000001"
    )
    try:
        with temp_env_vars(
            MODEL_COLLECTION_DIR=serve_collection_dir,
            GORDO_TPU_SERVE_WARMUP="0",
            GORDO_TPU_BREAKER_THRESHOLD="2",
            GORDO_TPU_BREAKER_COOLDOWN_S="60",
        ):
            app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
            with installed_engine(tiny_config(max_delay_ms=60.0)) as engine:
                warm_store(serve_collection_dir)
                codes = {name: [] for name in BATCH_NAMES}
                lock = threading.Lock()
                stop = threading.Event()

                def hammer(i):
                    name = BATCH_NAMES[i % len(BATCH_NAMES)]
                    while not stop.is_set():
                        resp = post(app, name, batch_payload)
                        with lock:
                            codes[name].append(resp.status_code)

                rule = FaultRule(
                    "serve_device_program",
                    match=f"*:f32:{POISON}",
                    times=None,
                    exc=InjectedDeviceError,
                )
                threads = [
                    threading.Thread(target=hammer, args=(i,), daemon=True)
                    for i in range(8)
                ]
                with inject(rule):
                    for thread in threads:
                        thread.start()
                    threading.Event().wait(0.8)
                    STORE.swap(serve_collection_dir, alt_dir, warm=True)
                    threading.Event().wait(0.8)
                    stop.set()
                    for thread in threads:
                        thread.join(timeout=30)
                    for name in INNOCENTS:
                        assert codes[name]
                        assert all(c == 200 for c in codes[name]), {
                            name: sorted(set(codes[name]))
                        }
                    # the swap minted a new RevisionFleet: the poison
                    # member's breaker restarted closed (and the still-
                    # firing fault begins tripping it fresh)
                    poison_codes = set(codes[POISON])
                    assert poison_codes <= {200, 500, 503}
    finally:
        STORE.clear()


def test_batched_and_unbatched_error_contract_table(
    serve_collection_dir, batch_payload, clean_ledgers
):
    """The documented 4xx/5xx ladder stays intact around containment:
    malformed client payloads keep answering 400 even while a breaker
    is open for another member."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir,
        GORDO_TPU_SERVE_WARMUP="0",
        GORDO_TPU_BREAKER_THRESHOLD="1",
        GORDO_TPU_BREAKER_COOLDOWN_S="60",
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        with installed_engine(tiny_config(max_delay_ms=30.0)) as engine:
            warm_store(serve_collection_dir)
            rule = FaultRule(
                "serve_device_program",
                match=f"*:f32:{POISON}",
                times=None,
                exc=InjectedDeviceError,
            )
            with inject(rule):
                assert post(app, POISON, batch_payload).status_code == 500
            assert post(app, POISON, batch_payload).status_code == 503
            # a malformed body on an INNOCENT member: still the client's
            # 400, untouched by the quarantine next door
            bad = Client(app).post(
                f"/gordo/v0/{PROJECT}/batch-b/prediction",
                json={"X": {"tag-1": {"2020-01-01T00:00:00": "not-a-number"}}},
            )
            assert bad.status_code == 400
            ok = post(app, "batch-b", batch_payload)
            assert ok.status_code == 200
            assert isinstance(
                json.loads(ok.data)["data"]["model-output"], dict
            )
