"""
Precision ladder end-to-end over the WSGI routes: the f32 default is
byte-identical to the pre-precision engine, bf16 serves behind a passed
parity gate with verdict-identical anomaly answers under concurrent
clients, a failed gate degrades to f32 with zero 5xx (route-level
drill), and mixed f32-base / bf16-canary traffic survives a hot swap.
"""

import json

import numpy as np
import pytest
from werkzeug.test import Client

from gordo_tpu import serializer, serve
from gordo_tpu.builder import local_build
from gordo_tpu.serve import precision as P
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
)

pytestmark = [pytest.mark.serve, pytest.mark.precision]

#: the bf16 canary fleet: the SAME machine names as the serve collection
#: (a canary serves under the base's names) whose specs declare their
#: serving precision on the config surface (`precision: bf16`)
BF16_CONFIG = """
machines:
  - name: batch-a
    dataset: &ds
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3, tag-4]
    model: &detector
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [8, 4]
            encoding_func: [tanh, tanh]
            decoding_dim: [4, 8]
            decoding_func: [tanh, tanh]
            precision: bf16
            epochs: 1
  - name: batch-b
    dataset: *ds
    model: *detector
  - name: batch-c
    dataset: *ds
    model: *detector
"""


@pytest.fixture(scope="module")
def bf16_collection_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("bf16-canary")
    for model, machine in local_build(BF16_CONFIG, project_name=PROJECT):
        serializer.dump(
            model,
            str(root / "1700000000001" / machine.name),
            metadata=machine.to_dict(),
        )
    return str(root / "1700000000001")


@pytest.fixture(autouse=True)
def fresh_fleet(serve_collection_dir):
    """Precision gate verdicts live and die with the RevisionFleet —
    give every test a fresh fleet so one test's gate state (or
    corrupted cast) never leaks into the next."""
    STORE.invalidate(serve_collection_dir)
    yield
    STORE.invalidate(serve_collection_dir)


def _leaf_columns(frame_dict, prefix=()):
    """(path, {ts: value}) leaves of a dataframe_to_dict payload —
    MultiIndex anomaly frames nest one dict level deeper than flat
    prediction frames."""
    for key, value in frame_dict.items():
        if (
            isinstance(value, dict)
            and value
            and all(isinstance(v, dict) for v in value.values())
        ):
            yield from _leaf_columns(value, prefix + (key,))
        else:
            yield prefix + (key,), value


def _column_array(frame_dict):
    """A dataframe_to_dict payload as a dense [rows, cols] array in
    sorted column/timestamp order."""
    cols = sorted(_leaf_columns(frame_dict), key=lambda kv: kv[0])
    rows = sorted(cols[0][1])
    return np.asarray(
        [[series[r] for _, series in cols] for r in rows], np.float64
    )


def test_default_f32_is_byte_identical(serve_collection_dir, batch_payload):
    """With the knob unset (and with it explicitly f32) the batched
    response bytes are identical — the precision axis is invisible until
    asked for."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        url = f"/gordo/v0/{PROJECT}/batch-a/prediction"
        with installed_engine() as engine:
            assert engine.config.precision == "f32"
            default_bytes = Client(app).post(url, json=batch_payload).data
            stats = engine.stats()
            assert stats["precision"]["coalesced"] == {"f32": 1}
            assert stats["precision_degraded"] == 0
            assert all(p == "f32" for (_, _, _, _, p, _) in engine.program_shapes())
        # nothing was gated: f32 is the reference, not a candidate
        assert STORE.fleet(serve_collection_dir).precision_reports() == []
        with temp_env_vars(GORDO_TPU_SERVE_PRECISION="f32"):
            with installed_engine():
                explicit_bytes = Client(app).post(url, json=batch_payload).data
    assert default_bytes == explicit_bytes


def test_bf16_verdict_parity_under_concurrent_clients(
    serve_collection_dir, batch_payload
):
    """bf16 serving behind a passed gate: concurrent batched anomaly
    requests all answer 200 and their anomaly VERDICTS (confidence >= 1)
    match the unbatched f32 reference row for row."""
    payload = dict(batch_payload, y=batch_payload["X"])
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        reference = {}
        for name in BATCH_NAMES:
            resp = Client(app).post(
                f"/gordo/v0/{PROJECT}/{name}/anomaly/prediction", json=payload
            )
            assert resp.status_code == 200
            reference[name] = json.loads(resp.data)["data"]

        with temp_env_vars(GORDO_TPU_SERVE_PRECISION="bf16"):
            with installed_engine(tiny_config(max_delay_ms=250.0)) as engine:
                # warmup runs the parity gate off the request path and
                # precompiles the ACTIVE (bf16) ladder
                engine.warmup_collection(serve_collection_dir)
                fleet = STORE.fleet(serve_collection_dir)
                spec = fleet.loaded_specs()["batch-a"]
                state = fleet.precision_state(spec, "bf16")
                assert state is not None and state["passed"], state
                assert state["agreement_min"] >= 0.98

                results = {}

                def hit(i):
                    name = BATCH_NAMES[i % len(BATCH_NAMES)]
                    resp = Client(app).post(
                        f"/gordo/v0/{PROJECT}/{name}/anomaly/prediction",
                        json=payload,
                    )
                    assert resp.status_code == 200, resp.data
                    results[i] = (name, json.loads(resp.data)["data"])

                errors = run_threads(9, hit)
                assert not errors
                stats = engine.stats()
                assert stats["precision"]["coalesced"].get("bf16") == 9
                assert stats["precision_degraded"] == 0

    assert len(results) == 9
    for name, frame in results.values():
        # the reconstruction is close (bf16-magnitude error) ...
        got = _column_array(frame["model-output"])
        want = _column_array(reference[name]["model-output"])
        report = P.recon_agreement(want, got, rtol=0.02, atol=1e-2)
        assert report["agreement"] == 1.0, report
        # ... and the anomaly verdicts are identical: threshold math is
        # f32 on the output side at every precision
        got_conf = _column_array(
            {"c": frame["total-anomaly-confidence"]}
        )
        want_conf = _column_array(
            {"c": reference[name]["total-anomaly-confidence"]}
        )
        assert np.array_equal(got_conf >= 1.0, want_conf >= 1.0)


def test_parity_failure_degrades_to_f32_with_zero_5xx(
    serve_collection_dir, batch_payload, monkeypatch
):
    """The route-level degrade drill: a corrupted quantization fails the
    gate, every request still answers 200, and the answers are exactly
    the f32 answers (the degraded path IS the f32 path)."""

    def corrupt_cast(stacked, precision):
        import jax

        return jax.tree_util.tree_map(lambda a: a * 0.0, stacked)

    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        url = f"/gordo/v0/{PROJECT}/batch-a/prediction"
        with installed_engine() as engine:
            f32_bytes = Client(app).post(url, json=batch_payload).data

        monkeypatch.setattr(
            "gordo_tpu.serve.precision.cast_bucket_params", corrupt_cast
        )
        STORE.invalidate(serve_collection_dir)
        with temp_env_vars(GORDO_TPU_SERVE_PRECISION="bf16"):
            with installed_engine(tiny_config(max_delay_ms=120.0)) as engine:
                statuses = {}

                def hit(i):
                    resp = Client(app).post(url, json=batch_payload)
                    statuses[i] = (resp.status_code, resp.data)

                errors = run_threads(6, hit)
                assert not errors
                assert all(s == 200 for s, _ in statuses.values())
                # every response is the f32 response, byte for byte
                assert all(b == f32_bytes for _, b in statuses.values())
                stats = engine.stats()
                assert stats["precision_degraded"] == 6
                assert stats["precision"]["coalesced"] == {"f32": 6}
                assert all(p == "f32" for (_, _, _, _, p, _) in engine.program_shapes())
        fleet = STORE.fleet(serve_collection_dir)
        reports = fleet.precision_reports()
        assert len(reports) == 1 and not reports[0]["passed"]


def test_gate_disabled_serves_requested_precision(serve_collection_dir):
    """GORDO_TPU_PRECISION_GATE=0: the requested precision serves
    ungated (benches and tests drive this; production keeps the gate)."""
    fleet = STORE.fleet(serve_collection_dir)
    fleet.warm(BATCH_NAMES)
    model = STORE.get_model(serve_collection_dir, "batch-a")
    with temp_env_vars(
        GORDO_TPU_SERVE_PRECISION="bf16", GORDO_TPU_PRECISION_GATE="0"
    ):
        with installed_engine(tiny_config()) as engine:
            recon = engine.batched_predict(
                serve_collection_dir,
                "batch-a",
                model,
                np.zeros((6, 4), np.float32),
            )
            assert recon is not None and recon.dtype == np.float32
            assert engine.stats()["precision"]["coalesced"] == {"bf16": 1}
    assert STORE.fleet(serve_collection_dir).precision_reports() == []


def test_hot_swap_mixed_precision_traffic(
    serve_collection_dir, bf16_collection_dir, batch_payload
):
    """The hot-swap drill: base f32 and a bf16-declared canary serve
    mixed traffic (the canary's per-spec `precision: bf16` wins over the
    unset env default), then the canary promotes — zero non-200s
    throughout, and both precisions actually coalesced batches."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        url = f"/gordo/v0/{PROJECT}/batch-a/prediction"
        try:
            with installed_engine(tiny_config(max_delay_ms=60.0)) as engine:
                # every other request routes to the bf16 canary
                STORE.set_canary(serve_collection_dir, bf16_collection_dir, 0.5)
                statuses = {}

                def hit(i):
                    resp = Client(app).post(url, json=batch_payload)
                    statuses[i] = resp.status_code

                errors = run_threads(12, hit)
                assert not errors
                assert all(s == 200 for s in statuses.values()), statuses
                coalesced = engine.stats()["precision"]["coalesced"]
                assert coalesced.get("f32", 0) > 0, coalesced
                assert coalesced.get("bf16", 0) > 0, coalesced
                # the canary fleet carries a PASSED bf16 gate verdict
                canary_fleet = STORE.fleet(bf16_collection_dir)
                canary_spec = canary_fleet.loaded_specs()["batch-a"]
                state = canary_fleet.precision_state(canary_spec, "bf16")
                assert state is not None and state["passed"]
                # the base fleet was never gated (it serves f32)
                assert (
                    STORE.fleet(serve_collection_dir).precision_reports() == []
                )

                # promote: all traffic now serves the bf16 revision
                STORE.swap(serve_collection_dir, bf16_collection_dir)
                before = coalesced.get("bf16", 0)
                errors = run_threads(4, hit)
                assert not errors
                assert all(s == 200 for s in statuses.values())
                after = engine.stats()["precision"]["coalesced"]["bf16"]
                assert after >= before + 4
        finally:
            STORE.clear()


def test_fleet_status_surfaces_the_precision_ladder(
    serve_collection_dir, batch_payload
):
    """The operator surface: /fleet-health's `serving` section carries
    the engine's precision config, per-precision coalesce counts and the
    cached gate reports; the `programs` section buckets by precision."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir,
        GORDO_TPU_SERVE_WARMUP="0",
        GORDO_TPU_SERVE_PRECISION="bf16",
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        with installed_engine(tiny_config()):
            resp = Client(app).post(
                f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
            )
            assert resp.status_code == 200
            doc = Client(app).get(f"/gordo/v0/{PROJECT}/fleet-health").json
    serving = doc["serving"]
    assert serving["precision"]["config"] == "bf16"
    assert serving["precision"]["coalesced"].get("bf16") == 1
    (gate,) = serving["gates"]
    assert gate["precision"] == "bf16" and gate["passed"]
    assert doc["programs"]["by_precision"].get("bf16", 0) >= 1
    # the rendered table view carries the same story without crashing
    from gordo_tpu.telemetry import fleet_health

    rendered = fleet_health.render_fleet_status(doc)
    assert "precision=bf16" in rendered
    assert "gate bf16: PASS" in rendered
