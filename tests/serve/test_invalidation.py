"""
DELETE-revision racing in-flight scoring: ``STORE.invalidate()`` while a
fleet request is mid-batch must neither 500 later requests nor serve
parameters from the deleted revision afterwards.

The consistency contract under the race: requests already queued when the
delete lands score against the revision snapshot they were admitted under
(the engine's batch key pins the RevisionFleet OBJECT, whose params are
device-resident independent of the directory) — while every request
arriving AFTER the invalidation re-resolves through the store and either
loads fresh artifacts or answers the route's 404/410, never a 500 and
never stale params.
"""

import json
import shutil

import numpy as np
import pytest
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    REVISION,
    installed_engine,
    run_threads,
    temp_env_vars,
    tiny_config,
)

pytestmark = pytest.mark.serve

OLD_REVISION = str(int(REVISION) + 1)


@pytest.fixture
def disposable_revision(serve_collection_dir, tmp_path):
    """A throwaway copy of the serve collection the test may delete."""
    root = tmp_path / "collection"
    live = root / REVISION
    doomed = root / OLD_REVISION
    shutil.copytree(serve_collection_dir, live)
    shutil.copytree(serve_collection_dir, doomed)
    yield str(live), str(doomed)
    STORE.invalidate(str(live))
    STORE.invalidate(str(doomed))


def test_invalidate_mid_batch_keeps_inflight_and_later_requests_sane(
    disposable_revision,
):
    """Engine-level race: items queued when invalidate-and-delete lands
    still score (their key pins the old fleet's resident params); calls
    after the delete fall back cleanly instead of raising or answering
    from the deleted revision."""
    _, doomed = disposable_revision
    fleet = STORE.fleet(doomed)
    fleet.warm(BATCH_NAMES)
    model = fleet.model("batch-a")
    X = np.random.RandomState(0).rand(6, 4).astype(np.float32)
    reference = np.asarray(model.predict(X))

    # flush window long enough that every submit (and the delete) lands
    # while the batch is still queued — the "mid-batch" of the contract
    with installed_engine(tiny_config(max_delay_ms=1000.0)) as engine:
        results = [None] * 4

        def hit(i):
            results[i] = engine.batched_predict(doomed, "batch-a", model, X)

        import threading
        import time

        threads = [threading.Thread(target=hit, args=(i,)) for i in range(4)]
        for thread in threads:
            thread.start()
        # all four admitted and queued (none flushed yet) ...
        deadline = time.monotonic() + 5.0
        while engine._batcher.pending() < 4:
            assert time.monotonic() < deadline, engine.stats()
            time.sleep(0.005)
        # ... THEN the race: revision deleted from disk + store mid-batch
        STORE.invalidate(doomed)
        shutil.rmtree(doomed)
        for thread in threads:
            thread.join(timeout=30)

        for recon in results:
            assert recon is not None
            np.testing.assert_allclose(recon, reference, rtol=1e-4, atol=1e-5)

        # later calls resolve a FRESH (empty) fleet for the gone dir:
        # nothing loadable -> unbatched fallback (None), never stale rows
        later_fleet = STORE.fleet(doomed)
        assert later_fleet is not fleet
        assert later_fleet.loaded_specs() == {}
        assert engine.batched_predict(doomed, "batch-a", model, X) is None


def test_swap_and_invalidate_mid_batch_never_serves_torn_fleet(
    disposable_revision,
):
    """Hot-swap racing in-flight batches (the lifecycle promotion
    race): items queued when a swap+invalidate lands must score
    exactly against the fleet object they were admitted under (never a
    mix of old and new revisions, never an error), and requests routed
    AFTER the swap resolve the swapped-in fleet."""
    live, doomed = disposable_revision
    fleet = STORE.fleet(doomed)
    fleet.warm(BATCH_NAMES)
    model = fleet.model("batch-a")
    X = np.random.RandomState(0).rand(6, 4).astype(np.float32)
    reference = np.asarray(model.predict(X))

    with installed_engine(tiny_config(max_delay_ms=1000.0)) as engine:
        results = [None] * 4

        def hit(i):
            results[i] = engine.batched_predict(doomed, "batch-a", model, X)

        import threading
        import time

        threads = [threading.Thread(target=hit, args=(i,)) for i in range(4)]
        for thread in threads:
            thread.start()
        deadline = time.monotonic() + 5.0
        while engine._batcher.pending() < 4:
            assert time.monotonic() < deadline, engine.stats()
            time.sleep(0.005)
        # the race: a promotion swap + invalidation of the old revision
        # lands between the MRU fast-path read and the batch flush
        swapped = STORE.swap(doomed, live, warm=True)
        STORE.invalidate(doomed)
        for thread in threads:
            thread.join(timeout=30)

        # every queued request scored against its pinned snapshot —
        # bit-equal to the pre-swap reference, no errors, no tearing
        for recon in results:
            assert recon is not None
            np.testing.assert_allclose(recon, reference, rtol=1e-4, atol=1e-5)

        # post-swap traffic routes to the swapped-in revision's fleet
        routed = STORE.route(doomed)
        assert routed == live
        assert STORE.fleet(routed) is swapped
        later = engine.batched_predict(
            routed, "batch-a", swapped.model("batch-a"), X
        )
        assert later is not None
        np.testing.assert_allclose(later, reference, rtol=1e-4, atol=1e-5)


def test_delete_revision_route_mid_batch_never_500s_later_requests(
    disposable_revision, batch_payload
):
    """Route-level race: concurrent batched requests pinned to an old
    revision while DELETE removes that revision model-by-model. Every
    response is a defined status (200 for admitted work, 404/410 once
    the revision is gone) and the live revision keeps serving 200s."""
    live, doomed = disposable_revision
    with temp_env_vars(
        MODEL_COLLECTION_DIR=live, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
        with installed_engine(tiny_config(max_delay_ms=150.0)) as engine:
            statuses = [None] * 6

            def hit(i):
                name = BATCH_NAMES[i % len(BATCH_NAMES)]
                resp = Client(app).post(
                    f"/gordo/v0/{PROJECT}/{name}/prediction",
                    json=batch_payload,
                    query_string={"revision": OLD_REVISION},
                )
                statuses[i] = resp.status_code

            import threading

            threads = [threading.Thread(target=hit, args=(i,)) for i in range(6)]
            for thread in threads:
                thread.start()
            deleter = Client(app)
            for name in BATCH_NAMES + ["odd-one"]:
                resp = deleter.delete(
                    f"/gordo/v0/{PROJECT}/{name}/revision/{OLD_REVISION}"
                )
                assert resp.status_code in (200, 404), resp.data
            for thread in threads:
                thread.join(timeout=30)

            # defined outcomes only: scored, or a clean revision/model
            # miss for arrivals after their model's deletion — never 500
            assert all(code in (200, 404, 410) for code in statuses), statuses

            # the engine never errored a batch, and the live revision is
            # untouched by the old one's deletion
            assert engine.stats().get("shed_queue_full", 0) == 0
            resp = Client(app).post(
                f"/gordo/v0/{PROJECT}/batch-a/prediction", json=batch_payload
            )
            assert resp.status_code == 200, resp.data
            body = json.loads(resp.data)
            assert "model-output" in body["data"]
