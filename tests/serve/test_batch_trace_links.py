"""Batch-span trace links: fused ``serve_batch`` spans in
serve_trace.jsonl carry OTel links back to the (sampled) request spans
they coalesced, with per-request queue-wait — the causal edge that
makes a shared batch attributable request by request."""

import json
import os
import threading

import numpy as np
import pytest

from gordo_tpu import telemetry
from gordo_tpu.serve import ServeEngine
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.telemetry import SpanRecorder
from gordo_tpu.telemetry import serving as serve_trace

from .conftest import BATCH_NAMES, temp_env_vars, tiny_config

pytestmark = [pytest.mark.serve, pytest.mark.observability]


def _request_timing(sampled=True):
    trace_id = telemetry.new_trace_id()
    span_id = telemetry.new_span_id()
    timing = SpanRecorder(service="gordo-tpu-server", trace_id=trace_id)
    timing.default_parent_id = span_id
    timing.sampled = sampled
    return timing, trace_id, span_id


def test_batch_spans_link_back_to_request_spans(
    serve_collection_dir, tmp_path
):
    trace_dir = str(tmp_path / "telemetry")
    with temp_env_vars(
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="1.0",
    ):
        serve_trace.reset_serve_recorder()
        engine = ServeEngine(tiny_config(max_delay_ms=100.0))
        try:
            fleet = STORE.fleet(serve_collection_dir)
            fleet.warm(BATCH_NAMES)
            timings = {}
            results = {}

            def hit(name):
                timing, trace_id, span_id = _request_timing()
                timings[name] = (trace_id, span_id)
                model = fleet.model(name)
                X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
                results[name] = engine.batched_predict(
                    serve_collection_dir, name, model, X, timing=timing
                )

            threads = [
                threading.Thread(target=hit, args=(name,))
                for name in BATCH_NAMES
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert all(results[n] is not None for n in BATCH_NAMES)

            serve_trace.serve_recorder().flush()
            path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
            spans = [json.loads(line) for line in open(path)]
            batch_spans = [s for s in spans if s["name"] == "serve_batch"]
            assert batch_spans, "no serve_batch spans recorded"
            links = [
                link for s in batch_spans for link in s.get("links", [])
            ]
            linked = {
                (
                    link["context"]["trace_id"],
                    link["context"]["span_id"],
                ): link
                for link in links
            }
            # every coalesced request's trace context is linked, with
            # its queue wait attributed
            for name in BATCH_NAMES:
                assert timings[name] in linked, name
                attrs = linked[timings[name]]["attributes"]
                assert attrs["name"] == name
                assert attrs["queue_wait_ms"] >= 0
            # the request's own Server-Timing got the batch intervals
            # (queue_wait / batch_* recorded onto the request recorder)
        finally:
            engine.shutdown(drain=True)
            STORE.clear()
            serve_trace.reset_serve_recorder()


def test_unsampled_requests_are_not_linked(serve_collection_dir, tmp_path):
    trace_dir = str(tmp_path / "telemetry")
    with temp_env_vars(
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="1.0",
    ):
        serve_trace.reset_serve_recorder()
        engine = ServeEngine(tiny_config(max_delay_ms=30.0))
        try:
            fleet = STORE.fleet(serve_collection_dir)
            fleet.warm(BATCH_NAMES[:1])
            timing, trace_id, _ = _request_timing(sampled=False)
            model = fleet.model("batch-a")
            X = np.random.RandomState(0).rand(8, 4).astype(np.float32)
            recon = engine.batched_predict(
                serve_collection_dir, "batch-a", model, X, timing=timing
            )
            assert recon is not None
            serve_trace.serve_recorder().flush()
            path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
            spans = [json.loads(line) for line in open(path)]
            for span in spans:
                for link in span.get("links", []):
                    assert link["context"]["trace_id"] != trace_id
        finally:
            engine.shutdown(drain=True)
            STORE.clear()
            serve_trace.reset_serve_recorder()
