"""Thread-shutdown audit (the thread-lifecycle contract, runtime side):
after a SIGTERM-style drain, no gordo-owned thread may survive as
non-daemon — the batcher dispatchers join, the trace writer joins
through the recorder close, and whatever is still alive (a warmup
mid-compile) is daemon, so process exit can never hang."""

import threading

import pytest
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server.app import drain_and_stop
from gordo_tpu.telemetry import serving as serve_trace

from tests.serve.conftest import (
    BATCH_NAMES,
    PROJECT,
    installed_engine,
    temp_env_vars,
    tiny_config,
)

pytestmark = [pytest.mark.serve, pytest.mark.concurrency]


class _FakeServer:
    def __init__(self):
        self.shutdowns = 0

    def shutdown(self):
        self.shutdowns += 1


def _alive_non_daemon():
    return [
        thread
        for thread in threading.enumerate()
        if thread.is_alive()
        and not thread.daemon
        and thread is not threading.main_thread()
    ]


def test_drain_and_stop_leaves_zero_non_daemon_threads(
    serve_collection_dir, batch_payload, tmp_path
):
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir,
        GORDO_TPU_TELEMETRY_DIR=str(tmp_path),
        GORDO_TPU_TRACE_SAMPLE_RATE="1",
        GORDO_TPU_SERVE_WARMUP="0",
    ):
        serve_trace.reset_serve_recorder()
        try:
            app = build_app(config={"EXPECTED_MODELS": BATCH_NAMES})
            with installed_engine(tiny_config()) as engine:
                # traffic spawns the async trace writer + dispatcher work
                response = Client(app).post(
                    f"/gordo/v0/{PROJECT}/{BATCH_NAMES[0]}/prediction",
                    json=batch_payload,
                )
                assert response.status_code == 200
                writer = serve_trace.serve_recorder()._writer
                assert writer is not None and writer.is_alive()

                server = _FakeServer()
                drain_and_stop(app, server=server, engine=engine)

                assert server.shutdowns == 1
                # the writer thread was JOINED, not abandoned
                assert not writer.is_alive()
                # every gordo-owned thread still alive must be daemon
                # (a warmup mid-XLA-compile may linger; it cannot block
                # exit), and nothing non-daemon survives at all
                leftovers = [
                    t
                    for t in threading.enumerate()
                    if t.name.startswith("gordo-") and t.is_alive()
                ]
                assert all(t.daemon for t in leftovers), leftovers
                assert _alive_non_daemon() == []
        finally:
            serve_trace.reset_serve_recorder()


def test_serving_stack_registers_postfork_resets():
    """The fork-safety contract's runtime half: the pid-derived
    registries (serving trace recorder, fleet-health ledgers) must be
    wired into the post-fork reset registry at import time."""
    from gordo_tpu.utils.postfork import registered_resets

    names = registered_resets()
    assert "telemetry.serving.recorder" in names
    assert "telemetry.fleet_health.ledgers" in names
