"""
Unit tests for the per-member serving circuit breaker
(gordo_tpu/serve/breaker.py): the closed → open → half-open state
machine, exponential backoff, the single-probe contract, transition
hooks, and fleet-lifetime scoping. Pure stdlib — no JAX in the loop.
"""

import gc
import threading

import pytest

from gordo_tpu.serve.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    BreakerBoard,
    BreakerConfig,
    MemberQuarantined,
    ServeDeviceError,
)

pytestmark = [pytest.mark.serve, pytest.mark.chaos]


class FakeFleet:
    """Stands in for a RevisionFleet: the board only needs identity."""


SPEC = ("spec", 4)


def make_board(**overrides):
    defaults = dict(
        threshold=3, cooldown_s=0.05, backoff=2.0, max_cooldown_s=0.4,
        probe_ttl_s=0.2,
    )
    defaults.update(overrides)
    return BreakerBoard(config=BreakerConfig(**defaults))


def test_closed_until_threshold_consecutive_failures():
    board = make_board()
    fleet = FakeFleet()
    exc = RuntimeError("boom")
    assert board.quarantined(fleet, SPEC, "m-1") is None
    assert not board.record_failure(fleet, SPEC, "m-1", exc)
    assert not board.record_failure(fleet, SPEC, "m-1", exc)
    assert board.quarantined(fleet, SPEC, "m-1") is None  # still closed
    assert board.record_failure(fleet, SPEC, "m-1", exc)  # third trips
    retry = board.quarantined(fleet, SPEC, "m-1")
    assert retry is not None and retry > 0


def test_success_resets_consecutive_count():
    board = make_board()
    fleet = FakeFleet()
    exc = RuntimeError("boom")
    board.record_failure(fleet, SPEC, "m-1", exc)
    board.record_failure(fleet, SPEC, "m-1", exc)
    board.record_success(fleet, SPEC, "m-1")
    # the streak restarted: two more failures do NOT trip
    board.record_failure(fleet, SPEC, "m-1", exc)
    assert not board.record_failure(fleet, SPEC, "m-1", exc)
    assert board.quarantined(fleet, SPEC, "m-1") is None


def test_members_are_independent():
    board = make_board(threshold=1)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "poison", RuntimeError("x"))
    assert board.quarantined(fleet, SPEC, "poison") is not None
    assert board.quarantined(fleet, SPEC, "innocent") is None


def test_half_open_admits_exactly_one_probe(monkeypatch):
    board = make_board(threshold=1, cooldown_s=0.01, probe_ttl_s=30.0)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    deadline = threading.Event()
    deadline.wait(0.03)  # let the cooldown lapse
    assert board.quarantined(fleet, SPEC, "m-1") is None  # the probe
    # a second concurrent request is NOT a probe: short retry-after
    retry = board.quarantined(fleet, SPEC, "m-1")
    assert retry is not None and retry > 0


def test_probe_success_closes_and_probe_failure_reopens_with_backoff():
    board = make_board(threshold=1, cooldown_s=0.01, backoff=3.0)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    snap = board.snapshot()
    assert snap["open"] == 1 and snap["trips"] == 1
    first_cooldown = snap["members"][0]["cooldown_s"]
    threading.Event().wait(0.03)
    assert board.quarantined(fleet, SPEC, "m-1") is None  # half-open probe
    assert board.snapshot()["half_open"] == 1
    # probe fails: straight back to open, cooldown grows by backoff
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("still bad"))
    snap = board.snapshot()
    assert snap["open"] == 1 and snap["trips"] == 2
    assert snap["members"][0]["cooldown_s"] > first_cooldown
    threading.Event().wait(snap["members"][0]["cooldown_s"] + 0.02)
    assert board.quarantined(fleet, SPEC, "m-1") is None  # probe again
    board.record_success(fleet, SPEC, "m-1")  # probe came back healthy
    snap = board.snapshot()
    assert snap["open"] == 0 and snap["half_open"] == 0
    assert board.quarantined(fleet, SPEC, "m-1") is None


def test_cooldown_capped_at_max():
    board = make_board(
        threshold=1, cooldown_s=0.05, backoff=10.0, max_cooldown_s=0.2,
        probe_ttl_s=30.0,
    )
    fleet = FakeFleet()
    for _ in range(4):  # trip, probe-fail, probe-fail, probe-fail
        board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
        threading.Event().wait(0.21)
        board.quarantined(fleet, SPEC, "m-1")  # take the probe slot
    detail = board.snapshot()["members"][0]
    assert detail["cooldown_s"] <= 0.2


def test_lost_probe_expires_and_another_request_probes():
    board = make_board(threshold=1, cooldown_s=0.01, probe_ttl_s=0.02)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    threading.Event().wait(0.03)
    assert board.quarantined(fleet, SPEC, "m-1") is None  # probe admitted...
    # ...but its request was shed and never reported back
    threading.Event().wait(0.03)
    assert board.quarantined(fleet, SPEC, "m-1") is None  # fresh probe


def test_transition_hook_fires_outside_lock():
    events = []

    def hook(member, old, new, info):
        events.append((member, old, new, info["trips"]))

    board = BreakerBoard(
        config=BreakerConfig(threshold=1, cooldown_s=0.01),
        on_transition=hook,
    )
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    threading.Event().wait(0.02)
    board.quarantined(fleet, SPEC, "m-1")
    board.record_success(fleet, SPEC, "m-1")
    assert [(m, o, n) for m, o, n, _ in events] == [
        ("m-1", CLOSED, OPEN),
        ("m-1", OPEN, HALF_OPEN),
        ("m-1", HALF_OPEN, CLOSED),
    ]


def test_success_on_untracked_member_is_noop():
    board = make_board()
    board.record_success(FakeFleet(), SPEC, "never-failed")
    assert board.snapshot()["tracked"] == 0


def test_degrade_set_is_per_fleet_and_idempotent():
    board = make_board()
    fleet = FakeFleet()
    assert not board.degraded(fleet, SPEC, "bf16")
    assert board.degrade_bucket(fleet, SPEC, "bf16")
    assert not board.degrade_bucket(fleet, SPEC, "bf16")  # already
    assert board.degraded(fleet, SPEC, "bf16")
    assert not board.degraded(FakeFleet(), SPEC, "bf16")


def test_dead_fleet_state_is_purged():
    board = make_board(threshold=1)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    board.degrade_bucket(fleet, SPEC, "bf16")
    assert board.snapshot()["tracked"] == 1
    del fleet
    gc.collect()
    snap = board.snapshot()
    # a hot-swap/DELETE drops the fleet object: breaker state and the
    # degrade set die with the revision — a rebuilt member starts clean
    assert snap["tracked"] == 0
    assert snap["degraded_buckets"] == 0


def test_exception_types_carry_retry_after_and_member():
    exc = MemberQuarantined("m-9", 12.3)
    assert exc.retry_after_s == 12.3
    assert exc.member == "m-9"
    cause = RuntimeError("device text that must not echo")
    wrapped = ServeDeviceError("m-9", cause)
    assert wrapped.member == "m-9"
    assert wrapped.__cause__ is cause
    assert "device text" not in str(wrapped)


def test_fleet_finalizer_never_takes_the_board_lock():
    """The weakref finalizer runs inside the GC, which can trigger on an
    allocation made WHILE the board lock is held — a finalizer that
    locked would deadlock the serving plane. It must only enqueue."""
    board = make_board(threshold=1)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    with board._lock:  # simulate GC striking inside a locked section
        del fleet
        gc.collect()  # finalizer fires here; must not block on the lock
    snap = board.snapshot()  # first locked call drains the purge queue
    assert snap["tracked"] == 0


def test_reused_fleet_id_never_resurrects_old_state():
    """After a fleet dies, its id() can be handed to a NEW fleet; the
    deferred purge must run before any probe could alias the old
    revision's open breaker or degrade pin onto the new one."""
    board = make_board(threshold=1)
    fleet = FakeFleet()
    board.record_failure(fleet, SPEC, "m-1", RuntimeError("x"))
    board.degrade_bucket(fleet, SPEC, "bf16")
    fid = id(fleet)
    del fleet
    gc.collect()

    class Pinned(FakeFleet):
        pass

    # we can't force an id collision deterministically, but the drain
    # contract is what prevents it: both probes must drain first
    fresh = Pinned()
    assert board.quarantined(fresh, SPEC, "m-1") is None
    assert not board.degraded(fresh, SPEC, "bf16")
    assert board.snapshot()["tracked"] == 0
    assert fid is not None  # silence the linter; identity was the point
