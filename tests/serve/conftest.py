"""
Serve-suite fixtures: a model collection where three machines SHARE one
feedforward architecture (the micro-batcher's coalescing unit) plus one
odd-spec machine, an engine factory that installs/uninstalls the
process-global engine around each test, and a batched WSGI client.
"""

import contextlib
import os
import threading

import pytest
from werkzeug.test import Client

from gordo_tpu import serializer, serve
from gordo_tpu.builder import local_build
from gordo_tpu.serve import ServeConfig, ServeEngine
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.server.conftest import temp_env_vars  # noqa: F401 (re-export)

PROJECT = "serve-project"
REVISION = "1700000000000"

#: three same-architecture detector machines (one spec bucket) + one
#: two-tag machine (its own bucket) — 1 epoch keeps the build cheap
SERVE_CONFIG = """
machines:
  - name: batch-a
    dataset: &ds
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3, tag-4]
    model: &detector
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [8, 4]
            encoding_func: [tanh, tanh]
            decoding_dim: [4, 8]
            decoding_func: [tanh, tanh]
            epochs: 1
  - name: batch-b
    dataset: *ds
    model: *detector
  - name: batch-c
    dataset: *ds
    model: *detector
  - name: odd-one
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        compression_factor: 0.5
        encoding_layers: 1
        epochs: 1
"""

BATCH_NAMES = ["batch-a", "batch-b", "batch-c"]


@pytest.fixture(scope="session")
def serve_collection_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("serve-collection")
    for model, machine in local_build(SERVE_CONFIG, project_name=PROJECT):
        serializer.dump(
            model, str(root / REVISION / machine.name), metadata=machine.to_dict()
        )
    return str(root / REVISION)


#: a tiny, test-friendly engine: small ladders (fast compiles), a long
#: flush delay relative to thread-spawn jitter, generous deadline
def tiny_config(**overrides) -> ServeConfig:
    defaults = dict(
        max_size=8,
        max_delay_ms=60.0,
        queue_depth=64,
        deadline_ms=10000.0,
        dispatchers=1,
        row_ladder=(8, 32),
        warmup_max_rows=32,
    )
    defaults.update(overrides)
    return ServeConfig(**defaults)


@contextlib.contextmanager
def installed_engine(config=None):
    engine = ServeEngine(config or tiny_config())
    serve.install_engine(engine)
    try:
        yield engine
    finally:
        serve.install_engine(None)
        engine.shutdown(drain=True)


@pytest.fixture
def engine():
    with installed_engine() as installed:
        yield installed


@pytest.fixture
def client(serve_collection_dir):
    """A WSGI client over the serve collection; whether requests batch is
    decided by which engine fixture the test also pulls in."""
    with temp_env_vars(
        MODEL_COLLECTION_DIR=serve_collection_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        yield Client(build_app(config={"EXPECTED_MODELS": BATCH_NAMES}))


@pytest.fixture(scope="session")
def batch_payload():
    """A 6-row JSON X payload matching the shared four-tag spec."""
    index = [f"2020-03-01T00:{m:02d}:00+00:00" for m in range(0, 60, 10)]
    return {
        "X": {
            f"tag-{i}": {ts: 0.1 * i + 0.01 * j for j, ts in enumerate(index)}
            for i in range(1, 5)
        }
    }


def warm_store(collection_dir, names=None):
    """Load the collection's models into the process STORE (what
    require_model does per request) so engine paths see a live bucket."""
    fleet = STORE.fleet(collection_dir)
    fleet.warm(names)
    return fleet


def run_threads(n, target):
    """Run ``target(i)`` on n threads; returns per-thread exceptions."""
    errors = [None] * n

    def wrap(i):
        try:
            target(i)
        except Exception as exc:  # noqa: BLE001 - surfaced by the test
            errors[i] = exc

    threads = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=60)
    return [e for e in errors if e is not None]
