import numpy as np
import pandas as pd
import pytest

from gordo_tpu import serializer
from gordo_tpu.builder import ModelBuilder, create_model_builder, local_build
from gordo_tpu.machine import Machine

MODEL_DEF = {
    "gordo_tpu.models.JaxAutoEncoder": {
        "kind": "feedforward_model",
        "encoding_dim": [8, 4],
        "encoding_func": ["tanh", "tanh"],
        "decoding_dim": [4, 8],
        "decoding_func": ["tanh", "tanh"],
        "epochs": 1,
    }
}
DATASET_DEF = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
    "tag_list": ["tag-1", "tag-2"],
}


def make_machine(**evaluation):
    return Machine.from_config(
        {
            "name": "m1",
            "model": MODEL_DEF,
            "dataset": dict(DATASET_DEF),
            **({"evaluation": evaluation} if evaluation else {}),
        },
        project_name="proj",
    )


def test_full_build_metadata():
    model, machine = ModelBuilder(make_machine()).build()
    bm = machine.metadata.build_metadata
    assert bm.model.model_offset == 0
    assert bm.model.model_builder_version
    assert bm.model.model_training_duration_sec > 0
    assert bm.dataset.query_duration_sec > 0
    assert bm.dataset.dataset_meta["row_count"] > 0
    scores = bm.model.cross_validation.scores
    # 4 metrics x (2 tags + 1 aggregate)
    assert len(scores) == 12
    ev = scores["explained-variance-score"]
    assert {"fold-mean", "fold-std", "fold-min", "fold-max", "fold-1"} <= set(ev)
    splits = bm.model.cross_validation.splits
    assert "fold-1-train-start" in splits


def test_cross_val_only_does_not_fit():
    model, machine = ModelBuilder(make_machine(cv_mode="cross_val_only")).build()
    assert machine.metadata.build_metadata.model.cross_validation.scores
    assert machine.metadata.build_metadata.model.model_training_duration_sec is None


def test_build_only_skips_cv():
    model, machine = ModelBuilder(make_machine(cv_mode="build_only")).build()
    assert not machine.metadata.build_metadata.model.cross_validation.scores
    assert machine.metadata.build_metadata.model.model_training_duration_sec > 0


def test_output_dir_artifacts(tmp_path):
    out = tmp_path / "out"
    ModelBuilder(make_machine()).build(output_dir=out)
    assert (out / "model.pkl").is_file()
    assert (out / "metadata.json").is_file()
    assert (out / "info.json").is_file()
    metadata = serializer.load_metadata(str(out))
    assert metadata["name"] == "m1"
    model = serializer.load(str(out))
    assert hasattr(model, "predict")


def test_register_cache_hit(tmp_path):
    register = tmp_path / "register"
    builder = ModelBuilder(make_machine())
    builder.build(model_register_dir=register)
    assert builder.cached_model_path is not None

    builder2 = ModelBuilder(make_machine())
    builder2.build(model_register_dir=register)
    assert builder2.cached_model_path == builder.cached_model_path

    # replace_cache forces a rebuild
    builder3 = ModelBuilder(make_machine())
    builder3.build(model_register_dir=register, replace_cache=True)
    assert builder3.cached_model_path is not None


def test_cache_key_sensitivity():
    key1 = ModelBuilder(make_machine()).cache_key
    key2 = ModelBuilder(make_machine()).cache_key
    assert key1 == key2
    different = Machine.from_config(
        {
            "name": "m1",
            "model": MODEL_DEF,
            "dataset": {**DATASET_DEF, "tag_list": ["tag-1", "tag-3"]},
        },
        project_name="proj",
    )
    assert ModelBuilder(different).cache_key != key1


def test_metrics_from_list():
    from sklearn.metrics import r2_score

    out = ModelBuilder.metrics_from_list(None)
    assert len(out) == 4
    out = ModelBuilder.metrics_from_list(
        ["r2_score", "sklearn.metrics.mean_absolute_error"]
    )
    assert out[0] is r2_score


def test_create_model_builder():
    assert create_model_builder(None) is ModelBuilder
    with pytest.raises(ValueError):
        create_model_builder("sklearn.preprocessing.MinMaxScaler")


def test_local_build_end_to_end():
    config = """
    machines:
      - name: machine-a
        dataset:
          type: RandomDataset
          train_start_date: "2020-01-01T00:00:00+00:00"
          train_end_date: "2020-01-05T00:00:00+00:00"
          tag_list: [tag-1, tag-2]
        model:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_hourglass
            encoding_layers: 1
            epochs: 1
    """
    results = list(local_build(config))
    assert len(results) == 1
    model, machine = results[0]
    assert machine.name == "machine-a"
    X, _ = machine.dataset.get_data()
    assert model.predict(X).shape[1] == 2


def test_determine_offset():
    class FakeModel:
        def predict(self, X):
            return X[5:]

    X = np.zeros((20, 2))
    assert ModelBuilder._determine_offset(FakeModel(), X) == 5
