"""
The serve → lifecycle arrow for tripped circuit breakers: the engine
records breaker state in the fleet-health ledger (telemetry), and the
supervisor's detect phase reads it back to nominate tripped members as
rebuild candidates — without serve ever importing lifecycle.
"""

import datetime

import pytest

from gordo_tpu import telemetry
from gordo_tpu.telemetry.fleet_health import (
    breaker_tripped_machines,
    reset_ledgers,
)

from tests.lifecycle.conftest import BASE_REVISION, make_supervisor

pytestmark = [pytest.mark.lifecycle, pytest.mark.chaos]


@pytest.fixture(autouse=True)
def _clean_ledgers():
    reset_ledgers()
    yield
    reset_ledgers()


def record_trip(directory, machine, state="open", updated_at=None):
    ledger = telemetry.ledger_for(directory)
    ledger.record_breaker(
        machine, state, trips=1, cooldown_s=30.0, reason="XlaRuntimeError"
    )
    if updated_at is not None:
        # backdate the stamp (stale-record drills)
        with ledger._lock:
            ledger._machines[machine]["breaker"]["updated_at"] = updated_at
        ledger.flush()


def test_tripped_machines_read_back_from_snapshots(models_root):
    import os

    anchor = os.path.join(models_root, BASE_REVISION)
    record_trip(anchor, "lc-1")
    reset_ledgers()  # force the file path, like a separate process
    tripped = breaker_tripped_machines(anchor)
    assert list(tripped) == ["lc-1"]
    assert tripped["lc-1"]["state"] == "open"


def test_stale_trip_records_expire(models_root):
    import os

    anchor = os.path.join(models_root, BASE_REVISION)
    old = (
        datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(hours=3)
    ).isoformat()
    record_trip(anchor, "lc-1", updated_at=old)
    assert breaker_tripped_machines(anchor) == {}
    assert breaker_tripped_machines(anchor, max_age_s=0) != {}


def test_detect_nominates_tripped_member_for_rebuild(models_root):
    supervisor = make_supervisor(models_root)
    try:
        record_trip(supervisor.collection_dir, "lc-2")
        report = supervisor.run_cycle()
        assert report.details.get("breaker_tripped") == ["lc-2"]
        assert "lc-2" in report.stale
        # one cycle can ride detect all the way into a serving canary —
        # anything past idle means the trip drove a rebuild
        assert supervisor.state.phase != "idle"
    finally:
        supervisor.close()


def test_breaker_rebuild_knob_disables_the_feed(models_root):
    supervisor = make_supervisor(models_root, breaker_rebuild=False)
    try:
        record_trip(supervisor.collection_dir, "lc-2")
        report = supervisor.run_cycle()
        assert "breaker_tripped" not in report.details
        assert report.stale == []
        assert supervisor.state.phase == "idle"
    finally:
        supervisor.close()


def test_promotion_clears_breaker_state(models_root):
    import os

    anchor = os.path.join(models_root, BASE_REVISION)
    record_trip(anchor, "lc-0")
    ledger = telemetry.ledger_for(anchor)
    assert breaker_tripped_machines(anchor)
    ledger.record_promotion("101", ["lc-0"])
    assert breaker_tripped_machines(anchor) == {}
    doc = ledger.document()
    assert doc["machines"]["lc-0"]["breaker"]["state"] == "closed"
    assert doc["machines"]["lc-0"]["health"]["state"] == "healthy"
