"""SLO burn state feeds the lifecycle gate inputs: a passing canary is
NOT auto-promoted while a page-severity burn-rate alert is firing —
swapping artifacts mid-incident destroys the evidence — and the hold
releases the moment the alert resolves."""

import json
import os

import pytest

from gordo_tpu.lifecycle.gates import GateReport

from .conftest import make_supervisor

pytestmark = [pytest.mark.lifecycle, pytest.mark.slo]


def _write_alert_state(directory, state, severity="page"):
    os.makedirs(directory, exist_ok=True)
    with open(os.path.join(directory, "slo_state.json"), "w") as handle:
        json.dump(
            {
                "version": 1,
                "alerts": {
                    "availability:fast": {
                        "slo": "availability",
                        "rule": "fast",
                        "severity": severity,
                        "state": state,
                    }
                },
            },
            handle,
        )


@pytest.fixture
def telemetry_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "telemetry")
    os.makedirs(d)
    monkeypatch.setenv("GORDO_TPU_TELEMETRY_DIR", d)
    return d


def test_slo_hold_reads_firing_page_alerts(models_root, telemetry_dir):
    supervisor = make_supervisor(models_root)
    assert supervisor._slo_hold() == []
    _write_alert_state(telemetry_dir, "firing")
    assert supervisor._slo_hold() == ["availability:fast"]
    # ticket severity never holds a promotion
    _write_alert_state(telemetry_dir, "firing", severity="ticket")
    assert supervisor._slo_hold() == []
    # resolved releases the hold
    _write_alert_state(telemetry_dir, "resolved")
    assert supervisor._slo_hold() == []


def test_slo_gate_can_be_disabled(models_root, telemetry_dir):
    supervisor = make_supervisor(models_root, slo_gate=False)
    _write_alert_state(telemetry_dir, "firing")
    assert supervisor._slo_hold() == []


def test_passing_canary_held_while_page_fires(
    models_root, telemetry_dir, monkeypatch, probe_windows
):
    """The full branch: gates pass, SLO page is firing -> the canary
    keeps serving its slice (no promote, no rollback); the hold
    releases when the alert resolves."""
    from gordo_tpu.lifecycle.loop import CycleReport

    supervisor = make_supervisor(models_root)
    healthy, _ = probe_windows
    supervisor.state.transition(
        "canary_building", stale=["lc-0"], canary_revision="101"
    )
    supervisor.state.transition("canary_serving", rebuilt=["lc-0"])
    supervisor._probe_frames = {"lc-0": healthy}

    class StoreStub:
        def canary_status(self):
            return {"fraction": 0.5}

        def fleet(self, path):
            return object()

    supervisor.store = StoreStub()
    monkeypatch.setattr(
        "gordo_tpu.lifecycle.loop.evaluate_canary",
        lambda *args, **kwargs: GateReport(),
    )
    promoted = []
    monkeypatch.setattr(
        supervisor, "_promote", lambda report: promoted.append(report)
    )

    _write_alert_state(telemetry_dir, "firing")
    report = CycleReport()
    supervisor._gate_and_settle(report)
    assert report.gate["passed"]
    assert not promoted
    assert not report.rolled_back
    assert report.details["slo_hold"] == ["availability:fast"]
    assert supervisor.state.phase == "canary_serving"

    # the burn resolves -> the next cycle promotes
    _write_alert_state(telemetry_dir, "resolved")
    report = CycleReport()
    supervisor._gate_and_settle(report)
    assert promoted


def test_failing_gates_still_roll_back_during_burn(
    models_root, telemetry_dir, monkeypatch, probe_windows
):
    """A FAILING canary is never held alive by the SLO gate — rollback
    (getting the bad artifacts out) always proceeds."""
    from gordo_tpu.lifecycle.loop import CycleReport

    supervisor = make_supervisor(models_root)
    healthy, _ = probe_windows
    supervisor.state.transition(
        "canary_building", stale=["lc-0"], canary_revision="101"
    )
    supervisor.state.transition("canary_serving", rebuilt=["lc-0"])
    supervisor._probe_frames = {"lc-0": healthy}

    class StoreStub:
        def canary_status(self):
            return {"fraction": 0.5}

        def fleet(self, path):
            return object()

    supervisor.store = StoreStub()
    failing = GateReport()
    failing.fail("lc-0: canary lost its anomaly threshold")
    monkeypatch.setattr(
        "gordo_tpu.lifecycle.loop.evaluate_canary",
        lambda *args, **kwargs: failing,
    )
    rolled = []
    monkeypatch.setattr(
        supervisor,
        "_rollback",
        lambda report, reasons: rolled.append(reasons),
    )
    _write_alert_state(telemetry_dir, "firing")
    report = CycleReport()
    supervisor._gate_and_settle(report)
    assert rolled


def test_manual_promote_surfaces_hold(
    models_root, telemetry_dir, monkeypatch, probe_windows
):
    from gordo_tpu.lifecycle.loop import CycleReport  # noqa: F401

    supervisor = make_supervisor(models_root)
    healthy, _ = probe_windows
    supervisor.state.transition(
        "canary_building", stale=["lc-0"], canary_revision="101"
    )
    supervisor.state.transition("canary_serving", rebuilt=["lc-0"])
    supervisor._probe_frames = {"lc-0": healthy}

    class StoreStub:
        def canary_status(self):
            return {"fraction": 0.5}

        def fleet(self, path):
            return object()

    supervisor.store = StoreStub()
    monkeypatch.setattr(
        "gordo_tpu.lifecycle.loop.evaluate_canary",
        lambda *args, **kwargs: GateReport(),
    )
    _write_alert_state(telemetry_dir, "firing")
    with pytest.raises(RuntimeError, match="SLO page alert"):
        supervisor.promote(force=False)
    # --force bypasses the hold (and the gates)
    promoted = []
    monkeypatch.setattr(
        supervisor, "_promote", lambda report: promoted.append(report)
    )
    supervisor.promote(force=True)
    assert promoted
