"""
Lifecycle-suite fixtures: one tiny three-machine fleet built ONCE per
session into a base revision, copied per test into a throwaway models
root; probe windows drawn from the machines' own (deterministic)
RandomDataset so "healthy" traffic matches the training distribution
and "drifted" traffic is shifted by 10 training-stds.
"""

import shutil

import pytest

from gordo_tpu.dataset.datasets import RandomDataset
from gordo_tpu.lifecycle import LifecycleConfig, LifecycleSupervisor
from gordo_tpu.lifecycle.drift import DriftConfig
from gordo_tpu.lifecycle.gates import GateConfig
from gordo_tpu.machine import Machine
from gordo_tpu.parallel import FleetBuilder
from gordo_tpu.server.fleet_store import FleetModelStore
from gordo_tpu.utils import faults

PROJECT = "lifecycle-project"
BASE_REVISION = "100"
TAGS = ["tag-1", "tag-2", "tag-3"]
NAMES = ["lc-0", "lc-1", "lc-2"]

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
    "tag_list": TAGS,
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": 1,
            }
        }
    }
}


def make_machines(names=NAMES):
    return [
        Machine.from_config(
            {"name": name, "model": MODEL, "dataset": dict(DATASET)},
            project_name=PROJECT,
        )
        for name in names
    ]


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="session")
def base_build(tmp_path_factory):
    """The base revision, built once per session (plan + journal +
    artifacts, exactly what a real build leaves on the volume)."""
    root = tmp_path_factory.mktemp("lifecycle-base")
    base_dir = root / BASE_REVISION
    FleetBuilder(make_machines(), plan_strategy="packed").build(
        output_dir=str(base_dir)
    )
    return str(base_dir)


@pytest.fixture
def models_root(base_build, tmp_path):
    """A throwaway models root holding a copy of the base revision."""
    root = tmp_path / "collection"
    root.mkdir()
    shutil.copytree(base_build, root / BASE_REVISION)
    return str(root)


@pytest.fixture(scope="session")
def probe_windows():
    """(healthy, drifted) probe DataFrames: a stride sample of the
    training series (window mean ≈ training mean) and the same rows
    shifted by 10 training-stds."""
    dataset = RandomDataset(
        **{k: v for k, v in DATASET.items() if k != "type"}
    )
    X, _ = dataset.get_data()
    healthy = X.iloc[::24]
    drifted = healthy + 10.0 * X.std()
    return healthy, drifted


def lifecycle_config(**overrides) -> LifecycleConfig:
    """Test-friendly config: small windows, instant calibration, no
    cooldown (tests re-canary on purpose), half the traffic to the
    canary (deterministic alternation)."""
    defaults = dict(
        canary_fraction=0.5,
        quarantine_cooldown_s=0.0,
        drift=DriftConfig(min_samples=8, calibration_batches=1),
        gates=GateConfig(),
    )
    defaults.update(overrides)
    return LifecycleConfig(**defaults)


def make_supervisor(
    models_root, store=None, machines=None, **config_overrides
) -> LifecycleSupervisor:
    import os

    return LifecycleSupervisor(
        machines if machines is not None else make_machines(),
        os.path.join(models_root, BASE_REVISION),
        store=store if store is not None else FleetModelStore(max_revisions=4),
        config=lifecycle_config(**config_overrides),
    )


def frames_for(names, window):
    return {name: window for name in names}
