"""
Drift statistics: baselines out of build metadata, feature/residual
tests, calibration, quorum, and snapshot round-trips.
"""

import json
import os

import numpy as np
import pytest

from gordo_tpu.lifecycle.drift import (
    DriftConfig,
    DriftMonitor,
    MachineDrift,
)

from tests.lifecycle.conftest import BASE_REVISION, NAMES, TAGS

pytestmark = pytest.mark.lifecycle

BASELINE = {
    "tags": ["a", "b"],
    "feature_means": [0.0, 10.0],
    "feature_stds": [1.0, 2.0],
    "n_samples": 500,
}


def config(**kw):
    defaults = dict(min_samples=4, sigma=2.0, calibration_batches=1)
    defaults.update(kw)
    return DriftConfig(**defaults)


def test_no_drift_on_baseline_distribution():
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    rng = np.random.RandomState(0)
    X = np.stack([rng.normal(0.0, 1.0, 200), rng.normal(10.0, 2.0, 200)], 1)
    machine.observe(X)
    verdict = machine.evaluate()
    assert not verdict.drifted, verdict


def test_feature_shift_trips_with_quorum():
    # quorum 0.25 of 2 tags -> 1 shifted tag suffices
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    X = np.stack([np.full(50, 8.0), np.full(50, 10.0)], 1)  # tag a: +8σ
    machine.observe(X)
    verdict = machine.evaluate()
    assert verdict.drifted
    assert verdict.reasons[0].startswith("feature-shift a")

    # quorum 1.0 -> one shifted tag of two is NOT enough
    machine = MachineDrift(
        "m", baseline=BASELINE, config=config(feature_quorum=1.0)
    )
    machine.observe(X)
    assert not machine.evaluate().drifted


def test_residual_drift_after_calibration():
    machine = MachineDrift("m", baseline=None, config=config())
    machine.observe(np.zeros((4, 1)), residuals=[0.5] * 4)  # calibrates
    machine.observe(np.zeros((4, 1)), residuals=[2.0] * 4)  # 4x baseline
    verdict = machine.evaluate()
    assert verdict.drifted
    assert "residual-ratio" in verdict.reasons[0]
    assert verdict.stats["residual_ratio"] == pytest.approx(4.0)


def test_residual_calibration_window_never_flags():
    machine = MachineDrift(
        "m", baseline=None, config=config(calibration_batches=3)
    )
    for _ in range(3):  # all calibration, whatever the values
        machine.observe(np.zeros((4, 1)), residuals=[5.0] * 4)
    assert not machine.evaluate().drifted


def test_min_samples_gate():
    machine = MachineDrift(
        "m", baseline=BASELINE, config=config(min_samples=100)
    )
    machine.observe(np.full((10, 2), 100.0))
    assert not machine.evaluate().drifted  # huge shift, tiny window


def test_window_resets_after_evaluation():
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    machine.observe(np.full((10, 2), 100.0))
    assert machine.evaluate().drifted
    assert not machine.evaluate().drifted  # fresh (empty) window


def test_nan_rows_do_not_poison_the_feature_test():
    """One NaN in a window (routine in raw sensor frames) must neither
    disable drift detection (NaN > sigma is always False) nor trip it."""
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    X = np.stack([np.full(50, 8.0), np.full(50, 10.0)], 1)
    X[3, 0] = np.nan
    X[7, 1] = np.nan
    machine.observe(X)
    verdict = machine.evaluate()
    assert verdict.drifted, verdict  # tag a is still +8σ over baseline

    healthy = MachineDrift("m", baseline=BASELINE, config=config())
    H = np.stack([np.zeros(50), np.full(50, 10.0)], 1)
    H[0, 0] = np.nan
    healthy.observe(H)
    assert not healthy.evaluate().drifted


def test_nan_baseline_tag_is_unmeasurable_not_undrifted():
    """A tag with a NaN/null training stat (all-NaN column at build
    time) drops out of the quorum; the measurable tags still vote."""
    baseline = dict(BASELINE, feature_means=[None, 10.0])
    machine = MachineDrift(
        "m", baseline=baseline, config=config(feature_quorum=1.0)
    )
    X = np.stack([np.zeros(50), np.full(50, 30.0)], 1)  # tag b: +10σ
    machine.observe(X)
    verdict = machine.evaluate()
    assert verdict.drifted, verdict  # quorum = 1 measurable tag, shifted

    nothing = MachineDrift(
        "m", baseline=dict(BASELINE, feature_means=[None, None])
    )
    nothing.observe(X)
    assert not nothing.evaluate().drifted


def test_offline_sensor_is_unmeasurable_not_a_giant_shift():
    """An all-NaN window column (dead sensor) must not read as a huge
    shift from a nonzero baseline — zero finite rows means the tag
    cannot vote, period."""
    baseline = dict(
        BASELINE, feature_means=[500.0, 10.0], feature_stds=[10.0, 2.0]
    )
    machine = MachineDrift(
        "m", baseline=baseline, config=config(feature_quorum=0.25)
    )
    X = np.stack([np.full(50, np.nan), np.full(50, 10.0)], 1)
    machine.observe(X)
    assert not machine.evaluate().drifted


def test_sub_threshold_windows_accumulate_across_evaluations():
    """Evidence from windows too small to test must survive the cycle
    boundary — otherwise small per-cycle batches make drift permanently
    undetectable."""
    machine = MachineDrift(
        "m", baseline=BASELINE, config=config(min_samples=20)
    )
    verdicts = []
    for _ in range(3):  # 3 × 8 rows; testable once 24 ≥ 20 accumulate
        machine.observe(np.full((8, 2), 100.0))
        verdicts.append(machine.evaluate())
    assert [v.drifted for v in verdicts] == [False, False, True], verdicts
    # ... and the tested window DID reset
    machine.observe(np.full((8, 2), 100.0))
    assert not machine.evaluate().drifted


def test_baseline_shape_mismatch_disables_feature_test():
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    machine.observe(np.full((10, 3), 100.0))  # 3 cols vs 2-tag baseline
    verdict = machine.evaluate()
    assert not verdict.drifted
    assert verdict.stats["feature_baseline"] == "shape-mismatch"


def test_snapshot_restore_roundtrip_through_json():
    machine = MachineDrift("m", baseline=BASELINE, config=config())
    machine.observe(np.full((10, 2), 3.0), residuals=[1.0] * 10)
    snapshot = json.loads(json.dumps(machine.snapshot()))
    clone = MachineDrift("m", baseline=BASELINE, config=config())
    clone.restore(snapshot)
    assert clone.snapshot() == machine.snapshot()
    machine.observe(np.full((10, 2), 3.0))
    clone.observe(np.full((10, 2), 3.0))
    assert machine.evaluate().drifted == clone.evaluate().drifted


def test_monitor_from_revision_reads_persisted_baselines(models_root):
    collection = os.path.join(models_root, BASE_REVISION)
    monitor = DriftMonitor.from_revision(collection, config())
    assert monitor.machines() == sorted(NAMES)
    machine = monitor.ensure(NAMES[0])
    assert machine.baseline is not None
    assert machine.baseline["tags"] == TAGS
    assert len(machine.baseline["feature_means"]) == len(TAGS)
    assert machine.baseline["n_samples"] > 0


def test_monitor_per_machine_isolation_on_bad_frames():
    monitor = DriftMonitor(config())
    monitor.observe_scores(
        {"good": np.zeros((5, 2)), "bad": object()},
        {"good": (np.zeros((5, 2)), np.zeros(5))},
    )
    verdicts = monitor.evaluate()
    assert set(verdicts) == {"good", "bad"}
    assert not verdicts["bad"].drifted
