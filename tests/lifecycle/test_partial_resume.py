"""
Journal-driven partial rebuilds: kill the lifecycle loop mid-canary-
build, restart, and assert only unbuilt stale members replan/rebuild —
and the canary resumes to the SAME revision id.
"""

import os

import pytest

from gordo_tpu.lifecycle import LifecycleState
from gordo_tpu.parallel.journal import BuildJournal
from gordo_tpu.utils.faults import FaultRule, inject

from tests.lifecycle.conftest import NAMES, frames_for, make_supervisor

pytestmark = [pytest.mark.lifecycle, pytest.mark.faults]


def test_kill_mid_canary_build_resumes_only_unbuilt_members(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(models_root)
    supervisor.run_cycle(frames_for(NAMES, healthy))
    # TWO machines drift; the process dies while dumping the second
    # canary artifact (mid-write, inside the atomic dump — the dump
    # pool is concurrent, so the process_kill-after-N site can land
    # after BOTH dumps; dying inside the Nth dump is deterministic)
    frames = frames_for(NAMES, healthy)
    frames[NAMES[1]] = drifted
    frames[NAMES[2]] = drifted
    with inject(
        FaultRule("dump_artifact", after=1, times=None, exc=SystemExit)
    ):
        with pytest.raises(SystemExit):
            supervisor.run_cycle(frames)
    supervisor.close()

    state = LifecycleState.load(models_root)
    assert state.phase == "canary_building"
    revision = state.canary_revision
    assert sorted(state.stale) == sorted(NAMES[1:])
    build_dir = os.path.join(models_root, ".lifecycle", f"build-{revision}")
    journal = BuildJournal.load(build_dir)
    built = sorted(
        name
        for name, entry in journal.machines().items()
        if entry.get("status") == "built"
    )
    assert len(built) == 1  # exactly one artifact landed before the kill
    survivor = built[0]
    other = next(n for n in NAMES[1:] if n != survivor)
    before = os.stat(os.path.join(build_dir, survivor, "model.pkl")).st_mtime_ns

    # restart: the canary resumes — same revision id, and ONLY the
    # unbuilt member trains (the survivor's artifact is untouched)
    resumed = make_supervisor(models_root, store=supervisor.store)
    report = resumed.run_cycle(frames)
    assert report.canary_revision == revision
    assert report.details["resumed"] == [survivor]
    assert report.details["rebuilt"] == sorted(NAMES[1:])
    assert (
        os.stat(os.path.join(build_dir, survivor, "model.pkl")).st_mtime_ns
        == before
    )
    # journal evidence: both stale members built, nothing else planned
    journal = BuildJournal.load(build_dir)
    assert sorted(journal.machines()) == sorted([survivor, other])
    assert all(
        entry.get("status") == "built"
        for entry in journal.machines().values()
    )
    # the resumed canary promoted and serves
    assert report.promoted
    assert resumed.serving_revision == revision
    resumed.close()
