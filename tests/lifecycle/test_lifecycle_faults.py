"""
The lifecycle chaos drill: a deterministic crash injected at every
lifecycle fault site (``drift_eval``, ``canary_build``,
``promote_swap``, ``rollback``) must leave serving on the last-good
revision and the loop resumable — a restarted supervisor converges.
"""

import os

import pytest

from gordo_tpu.lifecycle import LifecycleState
from gordo_tpu.lifecycle.gates import GateConfig
from gordo_tpu.utils.faults import FaultRule, inject

from tests.lifecycle.conftest import (
    BASE_REVISION,
    NAMES,
    frames_for,
    make_supervisor,
)

pytestmark = [pytest.mark.lifecycle, pytest.mark.faults]


def _drifted_frames(probe_windows, name=None):
    healthy, drifted = probe_windows
    frames = frames_for(NAMES, healthy)
    frames[name or NAMES[1]] = drifted
    return frames


def _calibrated_supervisor(models_root, probe_windows, **overrides):
    healthy, _ = probe_windows
    supervisor = make_supervisor(models_root, **overrides)
    supervisor.run_cycle(frames_for(NAMES, healthy))
    return supervisor


def _assert_serving_last_good(supervisor, models_root):
    base_dir = os.path.join(models_root, BASE_REVISION)
    # no hot-swap redirect landed: steady traffic still resolves base
    assert supervisor.store._redirects == {}
    assert supervisor.store.route(base_dir) in (
        base_dir,
        supervisor.store.canary_status() and supervisor.store.canary_status()["canary"],
    )
    assert LifecycleState.load(models_root).serving_revision == BASE_REVISION


def test_crash_at_drift_eval_leaves_serving_and_loop_intact(
    models_root, probe_windows
):
    supervisor = _calibrated_supervisor(models_root, probe_windows)
    frames = _drifted_frames(probe_windows)
    with inject(FaultRule("drift_eval", match=NAMES[1], exc=SystemExit)):
        with pytest.raises(SystemExit):
            supervisor.run_cycle(frames)
    _assert_serving_last_good(supervisor, models_root)
    assert LifecycleState.load(models_root).phase == "idle"
    supervisor.close()

    # restart converges: drift detected, canary built, promoted
    resumed = make_supervisor(models_root, store=supervisor.store)
    resumed.run_cycle(frames_for(NAMES, probe_windows[0]))
    report = resumed.run_cycle(frames)
    assert report.promoted
    resumed.close()


def test_nonfatal_drift_eval_fault_is_isolated_per_machine(
    models_root, probe_windows
):
    """A drift evaluation ERROR (not a crash) must neither take the
    loop down nor trip the machine."""
    supervisor = _calibrated_supervisor(models_root, probe_windows)
    with inject(FaultRule("drift_eval", match=NAMES[0], times=None)):
        report = supervisor.run_cycle(_drifted_frames(probe_windows))
    # the faulted machine is skipped; the genuinely drifted one rebuilt
    assert NAMES[0] not in report.drifted
    assert report.details.get("rebuilt") == [NAMES[1]]
    assert report.promoted
    supervisor.close()


def test_crash_at_canary_build_resumes_same_canary(models_root, probe_windows):
    supervisor = _calibrated_supervisor(models_root, probe_windows)
    frames = _drifted_frames(probe_windows)
    with inject(FaultRule("canary_build", exc=SystemExit)):
        with pytest.raises(SystemExit):
            supervisor.run_cycle(frames)
    _assert_serving_last_good(supervisor, models_root)
    state = LifecycleState.load(models_root)
    assert state.phase == "canary_building"
    planned_revision = state.canary_revision
    assert planned_revision
    # the crash happened BEFORE any training: nothing half-published
    assert planned_revision not in os.listdir(models_root)
    supervisor.close()

    resumed = make_supervisor(models_root, store=supervisor.store)
    report = resumed.run_cycle(frames)
    assert report.canary_revision == planned_revision
    assert report.promoted
    resumed.close()


def test_crash_at_promote_swap_leaves_canary_serving_and_resumes(
    models_root, probe_windows
):
    supervisor = _calibrated_supervisor(models_root, probe_windows)
    frames = _drifted_frames(probe_windows)
    with inject(FaultRule("promote_swap", exc=SystemExit)):
        with pytest.raises(SystemExit):
            supervisor.run_cycle(frames)
    _assert_serving_last_good(supervisor, models_root)
    state = LifecycleState.load(models_root)
    assert state.phase == "canary_serving"
    supervisor.close()

    resumed = make_supervisor(models_root, store=supervisor.store)
    report = resumed.run_cycle(frames_for(NAMES, probe_windows[0]))
    assert report.promoted
    assert (
        LifecycleState.load(models_root).serving_revision
        == state.canary_revision
    )
    resumed.close()


def test_crash_at_rollback_finishes_rollback_on_restart(
    models_root, probe_windows
):
    supervisor = _calibrated_supervisor(
        models_root, probe_windows, gates=GateConfig(residual_ratio=1e-6)
    )
    frames = _drifted_frames(probe_windows, NAMES[2])
    with inject(FaultRule("rollback", exc=SystemExit)):
        with pytest.raises(SystemExit):
            supervisor.run_cycle(frames)
    _assert_serving_last_good(supervisor, models_root)
    state = LifecycleState.load(models_root)
    assert state.phase == "rolling_back"
    supervisor.close()

    resumed = make_supervisor(
        models_root,
        store=supervisor.store,
        gates=GateConfig(residual_ratio=1e-6),
    )
    report = resumed.run_cycle()
    assert report.rolled_back
    after = LifecycleState.load(models_root)
    assert after.phase == "idle"
    assert after.serving_revision == BASE_REVISION
    assert after.quarantined(), "rollback must leave the quarantine record"
    resumed.close()
