"""
Promotion gates: identical fleets pass, broken/worse canaries fail with
the reason recorded.
"""

import os
import shutil

import pytest

from gordo_tpu.lifecycle.gates import GateConfig, evaluate_canary
from gordo_tpu.server.fleet_store import RevisionFleet

from tests.lifecycle.conftest import BASE_REVISION, NAMES

pytestmark = pytest.mark.lifecycle


@pytest.fixture
def twin_fleets(models_root, probe_windows):
    """Base + an identical 'canary' copy of the same revision."""
    base_dir = os.path.join(models_root, BASE_REVISION)
    canary_dir = os.path.join(models_root, "101")
    shutil.copytree(base_dir, canary_dir)
    return RevisionFleet(base_dir), RevisionFleet(canary_dir), canary_dir


def _frames(probe_windows):
    healthy, _ = probe_windows
    return {name: healthy for name in NAMES}


def test_identical_canary_passes_all_gates(twin_fleets, probe_windows):
    base, canary, _ = twin_fleets
    report = evaluate_canary(
        base, canary, _frames(probe_windows), NAMES, GateConfig()
    )
    assert report.passed, report.failures
    assert report.checks["error_rate"] == 0.0
    assert set(report.checks["threshold_parity"]) == set(NAMES)
    for ratio in report.checks["residual_parity"].values():
        assert ratio == pytest.approx(1.0, abs=1e-3)


def test_residual_gate_rejects_worse_canary(twin_fleets, probe_windows):
    base, canary, _ = twin_fleets
    report = evaluate_canary(
        base,
        canary,
        _frames(probe_windows),
        NAMES,
        GateConfig(residual_ratio=0.5),  # identical (1.0x) now "worse"
    )
    assert not report.passed
    assert any("residual" in failure for failure in report.failures)


def test_threshold_gate_rejects_runaway_threshold(twin_fleets, probe_windows):
    base, canary, _ = twin_fleets
    poisoned = canary.model(NAMES[1])
    poisoned.aggregate_threshold_ = poisoned.aggregate_threshold_ * 1000.0
    report = evaluate_canary(
        base, canary, _frames(probe_windows), NAMES, GateConfig()
    )
    assert not report.passed
    assert any(
        failure.startswith(f"{NAMES[1]}: threshold parity")
        for failure in report.failures
    )


def test_lost_threshold_fails(twin_fleets, probe_windows):
    base, canary, _ = twin_fleets
    delattr_target = canary.model(NAMES[0])
    delattr_target.aggregate_threshold_ = None
    report = evaluate_canary(
        base, canary, _frames(probe_windows), NAMES, GateConfig()
    )
    assert not report.passed
    assert any("lost its anomaly threshold" in f for f in report.failures)


def test_unloadable_canary_artifact_fails_error_rate(
    twin_fleets, probe_windows
):
    base, canary, canary_dir = twin_fleets
    with open(os.path.join(canary_dir, NAMES[2], "model.pkl"), "wb") as f:
        f.write(b"not a pickle")
    report = evaluate_canary(
        base, canary, _frames(probe_windows), NAMES, GateConfig()
    )
    assert not report.passed
    assert report.checks["error_rate"] > 0


def test_unprobed_members_are_reported(twin_fleets, probe_windows):
    base, canary, _ = twin_fleets
    healthy, _ = probe_windows
    report = evaluate_canary(
        base, canary, {NAMES[0]: healthy}, NAMES, GateConfig()
    )
    assert report.passed
    assert report.checks["unprobed"] == sorted(NAMES[1:])
