"""
The health ledger through a live lifecycle (PR 9): a full
drift → canary → quarantine cycle and a drift → canary → promote cycle
must each leave the per-member ledger telling the story — per-machine
drift verdicts with their σ/ratio stats, residual means from the scored
windows, quarantine evidence, and promotion clearing it.
"""

import os

import pytest

from gordo_tpu.lifecycle.gates import GateConfig
from gordo_tpu.telemetry.fleet_health import (
    fleet_status_document,
    ledger_for,
    load_health,
    reset_ledgers,
)

from tests.lifecycle.conftest import (
    BASE_REVISION,
    NAMES,
    frames_for,
    make_supervisor,
)

pytestmark = [pytest.mark.lifecycle, pytest.mark.fleet_health]


@pytest.fixture(autouse=True)
def _fresh_ledgers():
    reset_ledgers()
    yield
    reset_ledgers()


def test_drift_canary_quarantine_cycle_lands_in_ledger(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    anchor = os.path.join(models_root, BASE_REVISION)
    supervisor = make_supervisor(
        models_root, gates=GateConfig(residual_ratio=1e-6)
    )
    supervisor.run_cycle(frames_for(NAMES, healthy))  # calibration

    ledger = ledger_for(anchor)
    # the observed window already fed rolling serving stats per machine
    for name in NAMES:
        machine = ledger.machine(name)
        assert machine["serving"]["rows"] > 0
        assert machine["serving"]["residual_mean"] is not None

    frames = frames_for(NAMES, healthy)
    frames[NAMES[2]] = drifted
    report = supervisor.run_cycle(frames)
    assert report.rolled_back

    # the drifted machine carries its verdict AND its quarantine record
    machine = ledger.machine(NAMES[2])
    assert machine["drift"]["drifted"] is True
    assert any("feature-shift" in r for r in machine["drift"]["reasons"])
    assert machine["drift"]["feature_shift_max"] is not None
    assert machine["quarantine"]["active"] is True
    assert machine["quarantine"]["revision"] == report.canary_revision
    assert machine["health"]["state"] == "quarantined"
    # the healthy machines did not
    assert ledger.machine(NAMES[0])["health"]["state"] in ("healthy", "drifting")
    assert ledger.machine(NAMES[0])["quarantine"]["active"] is False

    # the snapshot on disk says the same (operators read the file)
    doc = load_health(anchor)
    assert doc["summary"]["quarantined"] == 1
    assert doc["machines"][NAMES[2]]["quarantine"]["active"] is True

    # ... and the joined fleet-status surface ties it to lifecycle state
    status = fleet_status_document(anchor)
    assert status["lifecycle"]["phase"] == "idle"
    assert status["lifecycle"]["quarantine_records"] == 1
    assert status["health"]["summary"]["quarantined"] == 1
    supervisor.close()


def test_promotion_clears_quarantine_and_advances_revision(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    anchor = os.path.join(models_root, BASE_REVISION)
    supervisor = make_supervisor(models_root)
    supervisor.run_cycle(frames_for(NAMES, healthy))

    frames = frames_for(NAMES, healthy)
    frames[NAMES[1]] = drifted
    report = supervisor.run_cycle(frames)
    assert report.promoted

    ledger = ledger_for(anchor)
    machine = ledger.machine(NAMES[1])
    # promotion cleared the drift flag and stamped the new revision
    assert machine["drift"]["drifted"] is False
    assert machine["quarantine"]["active"] is False
    assert machine["build"]["revision"] == report.canary_revision
    assert machine["health"]["state"] == "healthy"
    # the incremental rebuild ran in a .lifecycle staging dir, but its
    # provenance landed HERE, in the anchor ledger the console reads
    # (the base build fed a different dir; this value can only come
    # from the rebuild's health_ledger override)
    assert machine["build"]["final_loss"] is not None

    doc = load_health(anchor)
    assert doc["summary"]["quarantined"] == 0
    supervisor.close()
