"""
The ``gordo-tpu lifecycle`` command group: dry-run observation, status
rendering, and the no-canary guard rails.
"""

import json
import os

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu.cli.cli import gordo_tpu_cli
from gordo_tpu.server.fleet_store import STORE

from tests.lifecycle.conftest import (
    BASE_REVISION,
    DATASET,
    MODEL,
    NAMES,
    PROJECT,
)

pytestmark = pytest.mark.lifecycle


@pytest.fixture
def machines_config(tmp_path):
    path = tmp_path / "machines.yaml"
    path.write_text(
        yaml.safe_dump(
            {
                "project_name": PROJECT,
                "machines": [
                    {"name": name, "model": MODEL, "dataset": dict(DATASET)}
                    for name in NAMES
                ],
            }
        )
    )
    return str(path)


@pytest.fixture(autouse=True)
def _clean_store():
    yield
    STORE.clear()


def test_lifecycle_run_dry_run_reports_every_machine(
    models_root, machines_config
):
    collection = os.path.join(models_root, BASE_REVISION)
    result = CliRunner().invoke(
        gordo_tpu_cli,
        [
            "lifecycle",
            "run",
            machines_config,
            collection,
            "--once",
            "--dry-run",
        ],
    )
    assert result.exit_code == 0, result.output
    for name in NAMES:
        assert name in result.output
    # dry run never creates revisions
    assert [e for e in os.listdir(models_root) if e.isdigit()] == [
        BASE_REVISION
    ]


def test_lifecycle_status_renders_state_and_json(models_root, machines_config):
    collection = os.path.join(models_root, BASE_REVISION)
    CliRunner().invoke(
        gordo_tpu_cli,
        ["lifecycle", "run", machines_config, collection, "--once"],
    )
    result = CliRunner().invoke(
        gordo_tpu_cli, ["lifecycle", "status", models_root]
    )
    assert result.exit_code == 0, result.output
    assert "phase:    idle" in result.output
    assert BASE_REVISION in result.output

    as_json = CliRunner().invoke(
        gordo_tpu_cli, ["lifecycle", "status", models_root, "--as-json"]
    )
    assert as_json.exit_code == 0
    doc = json.loads(as_json.output)
    assert doc["state"]["anchor_revision"] == BASE_REVISION


def test_promote_and_rollback_require_a_canary(models_root):
    collection = os.path.join(models_root, BASE_REVISION)
    promote = CliRunner().invoke(
        gordo_tpu_cli, ["lifecycle", "promote", collection, "--force"]
    )
    assert promote.exit_code != 0
    assert "no canary to promote" in promote.output
    rollback = CliRunner().invoke(
        gordo_tpu_cli, ["lifecycle", "rollback", collection]
    )
    assert rollback.exit_code != 0
    assert "no canary to roll back" in rollback.output
