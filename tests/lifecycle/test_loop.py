"""
The supervisor end to end: steady state, drift → incremental rebuild →
canary → gated promotion with hot-swap, gate failure → rollback with
quarantine, cooldown, and zero-5xx serving through a full cycle.
"""

import json
import os
import threading

import pytest
from werkzeug.test import Client

from gordo_tpu.lifecycle import LifecycleState, restore_serving_state
from gordo_tpu.lifecycle.gates import GateConfig
from gordo_tpu.parallel.journal import BuildJournal
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.lifecycle.conftest import (
    BASE_REVISION,
    NAMES,
    PROJECT,
    TAGS,
    frames_for,
    make_supervisor,
)
from tests.server.conftest import temp_env_vars

pytestmark = pytest.mark.lifecycle


def test_steady_state_never_canaries(models_root, probe_windows):
    healthy, _ = probe_windows
    supervisor = make_supervisor(models_root)
    for _ in range(3):
        report = supervisor.run_cycle(frames_for(NAMES, healthy))
        assert report.phase == "idle"
        assert not report.stale and report.canary_revision is None
    assert sorted(os.listdir(models_root))[-1] == BASE_REVISION
    supervisor.close()


def test_drift_rebuilds_only_stale_and_promotes(models_root, probe_windows):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(models_root)
    store = supervisor.store
    base_dir = os.path.join(models_root, BASE_REVISION)
    supervisor.run_cycle(frames_for(NAMES, healthy))  # calibration

    frames = frames_for(NAMES, healthy)
    frames[NAMES[1]] = drifted
    report = supervisor.run_cycle(frames)

    assert report.stale == [NAMES[1]]
    assert report.details["rebuilt"] == [NAMES[1]]
    assert report.promoted and not report.rolled_back
    canary = report.canary_revision
    assert canary == "101"

    # ONLY the stale member went through the build (journal evidence)
    journal = BuildJournal.load(
        os.path.join(models_root, ".lifecycle", f"build-{canary}")
    )
    assert sorted(journal.machines()) == [NAMES[1]]

    # untouched members were hardlinked, the stale one replaced
    canary_dir = os.path.join(models_root, canary)
    same = os.stat(os.path.join(base_dir, NAMES[0], "model.pkl")).st_ino
    assert same == os.stat(os.path.join(canary_dir, NAMES[0], "model.pkl")).st_ino
    assert os.stat(os.path.join(base_dir, NAMES[1], "model.pkl")).st_ino != (
        os.stat(os.path.join(canary_dir, NAMES[1], "model.pkl")).st_ino
    )

    # the hot swap landed: requests for the base dir route to the canary
    assert store.route(base_dir) == canary_dir
    assert store.canary_status() is None  # promotion cleared the slice
    assert supervisor.serving_revision == canary

    # state survived and the next cycle is steady again
    state = LifecycleState.load(models_root)
    assert state.phase == "idle" and state.serving_revision == canary
    follow_up = supervisor.run_cycle(frames_for(NAMES, healthy))
    assert follow_up.phase == "idle" and not follow_up.stale
    supervisor.close()


def test_gate_failure_rolls_back_and_quarantines(models_root, probe_windows):
    healthy, drifted = probe_windows
    # an impossible residual gate: every canary fails it
    supervisor = make_supervisor(
        models_root, gates=GateConfig(residual_ratio=1e-6)
    )
    store = supervisor.store
    base_dir = os.path.join(models_root, BASE_REVISION)
    supervisor.run_cycle(frames_for(NAMES, healthy))
    frames = frames_for(NAMES, healthy)
    frames[NAMES[2]] = drifted
    report = supervisor.run_cycle(frames)

    assert report.rolled_back and not report.promoted
    assert not report.gate["passed"]
    # serving never moved
    assert store.route(base_dir) == base_dir
    assert store.canary_status() is None
    assert supervisor.serving_revision == BASE_REVISION
    # the quarantine record explains it
    state = LifecycleState.load(models_root)
    records = state.quarantined()
    assert len(records) == 1
    assert records[0]["canary_revision"] == report.canary_revision
    assert NAMES[2] in records[0]["machines"]
    assert any("residual" in reason for reason in records[0]["reasons"])
    supervisor.close()


def test_quarantine_cooldown_suppresses_canary_storm(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(
        models_root,
        gates=GateConfig(residual_ratio=1e-6),
        quarantine_cooldown_s=3600.0,
    )
    supervisor.run_cycle(frames_for(NAMES, healthy))
    frames = frames_for(NAMES, healthy)
    frames[NAMES[1]] = drifted
    first = supervisor.run_cycle(frames)
    assert first.rolled_back
    # the same drift again: cooldown suppresses a second canary
    second = supervisor.run_cycle(frames)
    assert not second.canary_revision
    assert second.details.get("cooldown") == [NAMES[1]]
    supervisor.close()


def test_no_auto_promote_leaves_canary_serving_then_manual_promote(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(models_root, auto_promote=False)
    supervisor.run_cycle(frames_for(NAMES, healthy))
    frames = frames_for(NAMES, healthy)
    frames[NAMES[0]] = drifted
    report = supervisor.run_cycle(frames)
    assert report.phase == "canary_serving"
    assert not report.promoted and not report.rolled_back
    assert supervisor.store.canary_status() is not None

    manual = supervisor.promote()
    assert manual.promoted
    assert supervisor.serving_revision == report.canary_revision
    supervisor.close()


def test_manual_rollback(models_root, probe_windows):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(models_root, auto_promote=False)
    supervisor.run_cycle(frames_for(NAMES, healthy))
    frames = frames_for(NAMES, healthy)
    frames[NAMES[0]] = drifted
    report = supervisor.run_cycle(frames)
    assert report.phase == "canary_serving"
    manual = supervisor.rollback("operator says no")
    assert manual.rolled_back
    assert supervisor.serving_revision == BASE_REVISION
    records = LifecycleState.load(models_root).quarantined()
    assert records and records[-1]["reasons"] == ["operator says no"]
    supervisor.close()


def _payload(window):
    rows = window.iloc[:8]
    index = [ts.isoformat() for ts in rows.index]
    return {
        "X": {
            tag: {ts: float(v) for ts, v in zip(index, rows[tag])}
            for tag in TAGS
        }
    }


def test_full_cycle_route_level_zero_5xx(models_root, probe_windows):
    """The acceptance drill: concurrent clients through drift → canary
    → rollback AND drift → canary → promote; every response is 200 and
    stamps exactly one known revision (never torn, never 5xx)."""
    healthy, drifted = probe_windows
    base_dir = os.path.join(models_root, BASE_REVISION)
    payload = _payload(healthy)
    with temp_env_vars(
        MODEL_COLLECTION_DIR=base_dir, GORDO_TPU_SERVE_WARMUP="0"
    ):
        app = build_app(config={"EXPECTED_MODELS": NAMES})
        supervisor = make_supervisor(
            models_root, store=STORE, gates=GateConfig(residual_ratio=1e-6)
        )
        try:
            stop = threading.Event()
            outcomes = []

            def hammer(i):
                client = Client(app)
                while not stop.is_set():
                    name = NAMES[i % len(NAMES)]
                    resp = client.post(
                        f"/gordo/v0/{PROJECT}/{name}/prediction", json=payload
                    )
                    outcomes.append(
                        (resp.status_code, resp.headers.get("revision"))
                    )

            threads = [
                threading.Thread(target=hammer, args=(i,)) for i in range(4)
            ]
            for thread in threads:
                thread.start()
            try:
                supervisor.run_cycle(frames_for(NAMES, healthy))
                bad_frames = frames_for(NAMES, healthy)
                bad_frames[NAMES[1]] = drifted
                rolled = supervisor.run_cycle(bad_frames)  # gates fail
                assert rolled.rolled_back
                # now a healthy promotion path
                supervisor.config.gates = GateConfig()
                supervisor.config.quarantine_cooldown_s = 0.0
                supervisor.run_cycle(bad_frames)
                promoted = supervisor.run_cycle(bad_frames)
                promoted_any = rolled.canary_revision and (
                    promoted.promoted or promoted.canary_revision
                )
                assert promoted_any
            finally:
                stop.set()
                for thread in threads:
                    thread.join(timeout=30)

            assert outcomes
            statuses = {code for code, _ in outcomes}
            assert statuses == {200}, statuses
            revisions = {rev for _, rev in outcomes}
            known = set(
                entry
                for entry in os.listdir(models_root)
                if entry.isdigit()
            )
            assert revisions <= known, (revisions, known)
        finally:
            supervisor.close()
            STORE.clear()


def test_restore_serving_state_reinstalls_promotion(
    models_root, probe_windows
):
    healthy, drifted = probe_windows
    supervisor = make_supervisor(models_root, store=STORE)
    base_dir = os.path.join(models_root, BASE_REVISION)
    try:
        supervisor.run_cycle(frames_for(NAMES, healthy))
        frames = frames_for(NAMES, healthy)
        frames[NAMES[1]] = drifted
        report = supervisor.run_cycle(frames)
        assert report.promoted
        promoted_dir = os.path.join(models_root, report.canary_revision)

        # simulate a server restart: routing state is process memory
        STORE.clear()
        assert STORE.route(base_dir) == base_dir
        assert restore_serving_state(base_dir) == report.canary_revision
        assert STORE.route(base_dir) == promoted_dir

        # build_app applies it too (and /prediction serves the new rev)
        STORE.clear()
        with temp_env_vars(
            MODEL_COLLECTION_DIR=base_dir, GORDO_TPU_SERVE_WARMUP="0"
        ):
            app = build_app(config={"EXPECTED_MODELS": NAMES})
            resp = Client(app).post(
                f"/gordo/v0/{PROJECT}/{NAMES[0]}/prediction",
                json=_payload(healthy),
            )
            assert resp.status_code == 200, resp.data
            assert resp.headers["revision"] == report.canary_revision
            body = json.loads(resp.data)
            assert body["revision"] == report.canary_revision
    finally:
        supervisor.close()
        STORE.clear()
