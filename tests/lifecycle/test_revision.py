"""
Canary revision assembly: numeric revision allocation, hardlinked
publish, idempotence, and refusal to ship incomplete artifacts.
"""

import os
import shutil

import pytest

from gordo_tpu import serializer
from gordo_tpu.lifecycle.revision import (
    delete_revision_dir,
    list_revisions,
    next_revision,
    publish_canary,
    revision_complete,
)
from gordo_tpu.serializer.serializer import is_staging_dir

from tests.lifecycle.conftest import BASE_REVISION, NAMES

pytestmark = pytest.mark.lifecycle


def test_revision_allocation(tmp_path):
    assert list_revisions(str(tmp_path)) == []
    assert next_revision(str(tmp_path)) == "1"
    for revision in ("8", "9", "10"):
        (tmp_path / revision).mkdir()
    (tmp_path / "not-a-revision").mkdir()
    assert list_revisions(str(tmp_path)) == ["8", "9", "10"]
    assert next_revision(str(tmp_path)) == "11"


@pytest.fixture
def rebuilt_dir(models_root, tmp_path):
    """A 'rebuild output' holding fresh copies of one member."""
    build = tmp_path / "build"
    build.mkdir()
    source = os.path.join(models_root, BASE_REVISION, NAMES[1])
    shutil.copytree(source, build / NAMES[1])
    return str(build)


def test_publish_links_untouched_and_takes_rebuilt(models_root, rebuilt_dir):
    target = publish_canary(
        models_root, BASE_REVISION, rebuilt_dir, [NAMES[1]], "101"
    )
    assert sorted(serializer.list_model_dirs(target)) == sorted(NAMES)
    assert revision_complete(target)
    # untouched members share inodes with the base (no bytes copied)
    base = os.path.join(models_root, BASE_REVISION)
    for name in (NAMES[0], NAMES[2]):
        assert os.stat(os.path.join(base, name, "model.pkl")).st_ino == (
            os.stat(os.path.join(target, name, "model.pkl")).st_ino
        )
    # the rebuilt member came from the build dir, not the base
    assert os.stat(os.path.join(rebuilt_dir, NAMES[1], "model.pkl")).st_ino == (
        os.stat(os.path.join(target, NAMES[1], "model.pkl")).st_ino
    )
    # the base build's plan rides along for the next incremental replay
    assert os.path.isfile(os.path.join(target, "fleet_plan.json"))
    # no staging leftovers
    assert not [e for e in os.listdir(models_root) if is_staging_dir(e)]


def test_publish_is_idempotent(models_root, rebuilt_dir):
    first = publish_canary(
        models_root, BASE_REVISION, rebuilt_dir, [NAMES[1]], "101"
    )
    again = publish_canary(
        models_root, BASE_REVISION, rebuilt_dir, [NAMES[1]], "101"
    )
    assert first == again
    assert revision_complete(again)


def test_publish_refuses_incomplete_rebuilt_artifacts(models_root, tmp_path):
    build = tmp_path / "torn-build"
    (build / NAMES[1]).mkdir(parents=True)
    (build / NAMES[1] / "model.pkl").write_bytes(b"torn")
    with pytest.raises(RuntimeError, match="incomplete"):
        publish_canary(
            models_root, BASE_REVISION, str(build), [NAMES[1]], "101"
        )
    assert "101" not in list_revisions(models_root)


def test_publish_refuses_foreign_incomplete_target(models_root, rebuilt_dir):
    os.makedirs(os.path.join(models_root, "101", "junk"))
    with pytest.raises(RuntimeError, match="refusing"):
        publish_canary(
            models_root, BASE_REVISION, rebuilt_dir, [NAMES[1]], "101"
        )


def test_delete_revision_dir(models_root, rebuilt_dir):
    publish_canary(models_root, BASE_REVISION, rebuilt_dir, [NAMES[1]], "101")
    assert delete_revision_dir(models_root, "101") is not None
    assert "101" not in list_revisions(models_root)
    assert delete_revision_dir(models_root, "101") is None
