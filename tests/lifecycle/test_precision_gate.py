"""
Precision-parity gate drills: pass on healthy bf16, fail on corrupted
quantization, crash == fail (never an exception), and the canary gate
(`evaluate_canary`) engages the precision check exactly when the active
serving precision is reduced.
"""

import os

import pytest

from gordo_tpu.lifecycle.gates import (
    GateConfig,
    evaluate_canary,
    evaluate_precision_parity,
)
from gordo_tpu.models.spec import FeedForwardSpec
from gordo_tpu.server.fleet_store import RevisionFleet

from tests.lifecycle.conftest import BASE_REVISION, NAMES
from tests.server.conftest import temp_env_vars

pytestmark = [pytest.mark.lifecycle, pytest.mark.precision]


@pytest.fixture
def fleet(models_root):
    """A fresh RevisionFleet per test (gate verdicts and cast buckets
    live on the fleet object — tests must not share them)."""
    fleet = RevisionFleet(os.path.join(models_root, BASE_REVISION))
    fleet.warm(NAMES)
    return fleet


def shared_spec(fleet) -> FeedForwardSpec:
    specs = fleet.loaded_specs()
    assert specs, "fleet did not load"
    return specs[NAMES[0]]


def test_parity_gate_passes_healthy_bf16(fleet):
    report = evaluate_precision_parity(fleet, shared_spec(fleet), "bf16")
    assert report.passed, report.failures
    parity = report.checks["parity"]
    assert parity["precision"] == "bf16"
    assert parity["agreement_min"] >= 0.98
    assert set(parity["members"]) == set(NAMES)


def test_parity_gate_fails_on_corrupt_quantization(fleet, monkeypatch):
    def corrupt_cast(stacked, precision):
        import jax

        return jax.tree_util.tree_map(lambda a: a * 0.0, stacked)

    monkeypatch.setattr(
        "gordo_tpu.serve.precision.cast_bucket_params", corrupt_cast
    )
    report = evaluate_precision_parity(fleet, shared_spec(fleet), "bf16")
    assert not report.passed
    assert any("bf16" in failure for failure in report.failures)
    assert report.checks["parity"]["agreement_min"] < 0.98


def test_crashing_evaluation_is_a_failed_gate(fleet, monkeypatch):
    def boom(*args, **kwargs):
        raise RuntimeError("synthetic parity crash")

    monkeypatch.setattr("gordo_tpu.serve.precision.evaluate_parity", boom)
    report = evaluate_precision_parity(fleet, shared_spec(fleet), "bf16")
    assert not report.passed
    assert "crashed" in report.failures[0]
    # a KeyboardInterrupt must NOT be swallowed into a gate verdict
    monkeypatch.setattr(
        "gordo_tpu.serve.precision.evaluate_parity",
        lambda *a, **k: (_ for _ in ()).throw(KeyboardInterrupt()),
    )
    with pytest.raises(KeyboardInterrupt):
        evaluate_precision_parity(fleet, shared_spec(fleet), "bf16")


def test_canary_gate_engages_precision_parity_when_reduced(
    models_root, probe_windows, monkeypatch
):
    healthy, _ = probe_windows
    base = RevisionFleet(os.path.join(models_root, BASE_REVISION))
    canary = RevisionFleet(os.path.join(models_root, BASE_REVISION))
    frames = {name: healthy for name in NAMES}

    # f32 serving: the precision gate stays out of the report entirely
    gate = evaluate_canary(base, canary, frames, NAMES, GateConfig())
    assert gate.passed, gate.failures
    assert "precision_parity" not in gate.checks

    # bf16 serving: the canary must additionally prove verdict parity
    with temp_env_vars(GORDO_TPU_SERVE_PRECISION="bf16"):
        gate = evaluate_canary(base, canary, frames, NAMES, GateConfig())
        assert gate.passed, gate.failures
        assert gate.checks["precision_parity"]
        (entry,) = gate.checks["precision_parity"].values()
        assert entry["agreement_min"] >= 0.98

        # ... and a badly-quantizing canary is REJECTED (the loop's
        # rollback machinery then keeps the f32 base serving)
        def corrupt_cast(stacked, precision):
            import jax

            return jax.tree_util.tree_map(lambda a: a * 0.0, stacked)

        monkeypatch.setattr(
            "gordo_tpu.serve.precision.cast_bucket_params", corrupt_cast
        )
        fresh_canary = RevisionFleet(os.path.join(models_root, BASE_REVISION))
        gate = evaluate_canary(base, fresh_canary, frames, NAMES, GateConfig())
        assert not gate.passed
        assert any("bf16" in failure for failure in gate.failures)
