"""
Ingest-suite fixtures: a model collection whose machines exercise every
compiled-preprocessing shape — two same-architecture detectors whose
base estimators are sklearn Pipelines with REAL fitted scalers (a
MinMaxScaler and a StandardScaler, one spec bucket, non-identity plans)
plus one bare hourglass machine (the identity plan, where the compiled
path must stay bit-identical to the host path).
"""

import pytest
from werkzeug.test import Client

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import STORE

from tests.server.conftest import temp_env_vars  # noqa: F401 (re-export)

PROJECT = "ingest-project"
REVISION = "1710000000000"

SCALED_NAMES = ["scaled-mm", "scaled-std"]

#: the two scaled machines share ONE feedforward architecture (so their
#: member plans stack into one FleetIngestPlan); the scalers differ so
#: the stacked scale/offset rows must differ per member
INGEST_CONFIG = """
machines:
  - name: scaled-mm
    dataset: &ds
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [ing-1, ing-2, ing-3, ing-4]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
              - sklearn.preprocessing.MinMaxScaler
              - gordo_tpu.models.JaxAutoEncoder:
                  kind: feedforward_model
                  encoding_dim: [8, 4]
                  encoding_func: [tanh, tanh]
                  decoding_dim: [4, 8]
                  decoding_func: [tanh, tanh]
                  epochs: 1
  - name: scaled-std
    dataset: *ds
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
              - sklearn.preprocessing.StandardScaler
              - gordo_tpu.models.JaxAutoEncoder:
                  kind: feedforward_model
                  encoding_dim: [8, 4]
                  encoding_func: [tanh, tanh]
                  decoding_dim: [4, 8]
                  decoding_func: [tanh, tanh]
                  epochs: 1
  - name: plain-id
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [ing-1, ing-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        compression_factor: 0.5
        encoding_layers: 1
        epochs: 1
"""


@pytest.fixture(scope="session")
def ingest_collection(tmp_path_factory):
    root = tmp_path_factory.mktemp("ingest-collection") / REVISION
    for model, machine in local_build(INGEST_CONFIG, project_name=PROJECT):
        serializer.dump(
            model, str(root / machine.name), metadata=machine.to_dict()
        )
    return str(root)


@pytest.fixture
def ingest_client(ingest_collection):
    with temp_env_vars(MODEL_COLLECTION_DIR=ingest_collection):
        STORE.clear()
        yield Client(build_app(config={}))
    STORE.clear()


@pytest.fixture(scope="session")
def scaled_payload():
    """A 5-row JSON X/y payload matching the scaled machines' tags."""
    index = [f"2020-03-01T00:{m:02d}:00+00:00" for m in range(0, 50, 10)]
    values = {
        f"ing-{i}": {ts: 0.2 * i + 0.03 * j for j, ts in enumerate(index)}
        for i in range(1, 5)
    }
    return {"X": values, "y": values}
