"""
Raw-column device-transfer units: both rungs (per-column dlpack and the
host staging fallback) must produce the same device values, every
fallback must be counted with its reason, and a backend with no working
dlpack must degrade gracefully — never fail the request.
"""

import numpy as np
import pytest

import jax

from gordo_tpu.ingest import (
    RawColumns,
    ingest_stats,
    reset_ingest_stats,
    to_device,
)

pytestmark = pytest.mark.ingest


@pytest.fixture(autouse=True)
def fresh_stats():
    reset_ingest_stats()
    yield
    reset_ingest_stats()


def _columns(rows=6, width=3, seed=5):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=rows) for _ in range(width)]


def test_raw_columns_shapes_and_host_matrix():
    cols = _columns()
    raw = RawColumns.from_columns(cols)
    assert (raw.rows, raw.width) == (6, 3)
    host = raw.host_matrix()
    assert host.dtype == np.float32 and host.flags["C_CONTIGUOUS"]
    np.testing.assert_array_equal(
        host, np.column_stack(cols).astype(np.float32)
    )
    assert raw.host_matrix() is host  # built at most once
    assert raw.nbytes == sum(c.nbytes for c in cols)

    mat = np.column_stack(cols)
    raw_m = RawColumns.from_matrix(mat)
    assert (raw_m.rows, raw_m.width) == (6, 3)
    np.testing.assert_array_equal(raw_m.host_matrix(), host)


def test_dlpack_and_host_rungs_agree():
    cols = _columns()
    want = np.column_stack(cols).astype(np.float32)
    fast = np.asarray(to_device(RawColumns.from_columns(cols), dlpack=True))
    slow = np.asarray(to_device(RawColumns.from_columns(cols), dlpack=False))
    np.testing.assert_array_equal(fast, want)
    np.testing.assert_array_equal(slow, want)
    stats = ingest_stats()
    assert stats["dlpack_transfers"] == 1
    assert stats["host_transfers"] == 1
    assert stats["dlpack_columns"] == 3
    assert stats["fallback_reasons"] == {"disabled": 1}


def test_row_padding_happens_on_both_rungs():
    cols = _columns(rows=5)
    for dlpack in (True, False):
        X = np.asarray(
            to_device(
                RawColumns.from_columns(cols), padded_rows=8, dlpack=dlpack
            )
        )
        assert X.shape == (8, 3)
        np.testing.assert_array_equal(
            X[:5], np.column_stack(cols).astype(np.float32)
        )
        np.testing.assert_array_equal(X[5:], 0.0)


def test_matrix_mode_takes_the_host_rung():
    mat = np.column_stack(_columns())
    X = np.asarray(to_device(RawColumns.from_matrix(mat), dlpack=True))
    np.testing.assert_array_equal(X, mat.astype(np.float32))
    stats = ingest_stats()
    assert stats["host_transfers"] == 1
    assert stats["fallback_reasons"] == {"no_columns": 1}


def test_dlpack_unavailable_falls_back_and_counts(monkeypatch):
    """A backend whose dlpack import refuses (or is absent) must serve
    every request over the host rung, with the reason counted."""

    def broken(*_args, **_kwargs):
        raise RuntimeError("dlpack unavailable on this backend")

    monkeypatch.setattr(jax.dlpack, "from_dlpack", broken)
    cols = _columns()
    X = np.asarray(to_device(RawColumns.from_columns(cols), dlpack=True))
    np.testing.assert_array_equal(X, np.column_stack(cols).astype(np.float32))
    stats = ingest_stats()
    assert stats["dlpack_transfers"] == 0
    assert stats["host_transfers"] == 1
    assert stats["fallback_reasons"] == {"RuntimeError": 1}


def test_f64_columns_cast_and_transfer():
    cols = [np.arange(4, dtype=np.float64) for _ in range(2)]
    X = np.asarray(to_device(RawColumns.from_columns(cols), dlpack=True))
    assert X.dtype == np.float32
    np.testing.assert_array_equal(X, np.column_stack(cols).astype(np.float32))
    assert ingest_stats()["dlpack_transfers"] == 1
