"""
Ladder-snapped stream cuts: a big multi-window backlog flush must take
the largest whole-window span that lands exactly on a serve row-ladder
rung (re-using the request plane's compiled shapes instead of minting a
worst-case padded one), leave the remainder buffered for the next
watermark flush, and never bend the zero-gap invariant.
"""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu import serve
from gordo_tpu.planner.ladder import snap_rows
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.stream.scorer import WindowScorer
from gordo_tpu.stream.session import StreamSession

from tests.stream.test_scorer import FakeFleet, events_of

pytestmark = [pytest.mark.ingest, pytest.mark.stream]

WINDOW = 32


@pytest.fixture(autouse=True)
def routed_fake_fleet(monkeypatch, tmp_path):
    """Fake-fleet routing plus a standalone breaker board (no engine)."""
    routed = str(tmp_path / "rev-a")
    fleet = FakeFleet(routed)
    monkeypatch.setattr(STORE, "route", lambda directory: routed)
    monkeypatch.setattr(STORE, "fleet", lambda directory: fleet)
    engine = serve.get_engine()
    serve.install_engine(None)
    serve.reset_stream_breakers()
    yield fleet
    serve.reset_stream_breakers()
    serve.install_engine(engine)


def make_session(tmp_path, ring_rows=512):
    return StreamSession(
        "proj", "sid", str(tmp_path / "rev-a"), ring_rows=ring_rows,
        outbox_events=64,
    )


def frame(rows):
    return pd.DataFrame({"tag-1": np.arange(rows, dtype=float)})


def test_snap_rows_picks_the_largest_aligned_rung():
    # default ladder (32, 128, 512, ...): 224 pending -> the 128 rung
    assert snap_rows(224, WINDOW) == 128
    # a rung is only eligible via its WHOLE-window capacity: with
    # window 48, rung 128 holds 2 windows = 96 rows
    assert snap_rows(200, 48, ladder=(32, 128)) == 96
    # below the smallest aligned size, freshness wins: take everything
    assert snap_rows(60, 24, ladder=(128, 512)) == 48
    # no whole window buffered -> nothing to cut
    assert snap_rows(WINDOW - 1, WINDOW) == 0
    assert snap_rows(100, 0) == 0


def test_cut_windows_snap_keeps_remainder_buffered(tmp_path):
    session = make_session(tmp_path)
    session.append_rows("m-1", frame(224))  # 7 whole windows of 32
    cuts = session.cut_windows(
        WINDOW, snap=lambda pending: snap_rows(pending, WINDOW)
    )
    chunks, first_seq, last_seq, windows, _oldest = cuts["m-1"]
    assert (first_seq, last_seq, windows) == (1, 128, 4)
    assert sum(len(c) for c in chunks) == 128
    stats = session.stats()["machines"]["m-1"]
    assert stats["rows_pending"] == 96  # remainder stays buffered


def test_cut_windows_defensively_floors_a_ragged_snap(tmp_path):
    session = make_session(tmp_path)
    session.append_rows("m-1", frame(3 * WINDOW))
    cuts = session.cut_windows(WINDOW, snap=lambda pending: WINDOW + 7)
    assert cuts["m-1"][3] == 1  # floored to one whole window
    assert session.stats()["machines"]["m-1"]["rows_pending"] == 2 * WINDOW


def test_backlog_flush_snaps_then_drains_with_contiguous_spans(tmp_path):
    """The scorer's flush wires the snap in: a 224-row backlog scores
    128 rows (the rung), the 96-row remainder rides later flushes, and
    the spans abut exactly — zero-gap accounting intact throughout."""
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    session.append_rows("m-1", frame(224))

    summary = scorer.flush(session)
    assert summary["scored"] == {"m-1": 128}
    stats = session.stats()["machines"]["m-1"]
    assert stats["rows_pending"] == 96

    # the remainder drains one 32-rung at a time on later watermarks
    assert scorer.flush(session)["scored"] == {"m-1": 32}
    assert scorer.flush(session)["scored"] == {"m-1": 32}
    assert scorer.flush(session)["scored"] == {"m-1": 32}
    assert scorer.flush(session)["scored"] == {}

    anomalies = events_of(session, "anomaly")
    assert [a["windows"] for a in anomalies] == [4, 1, 1, 1]
    assert [a["first_seq"] for a in anomalies] == [1, 129, 161, 193]
    for earlier, later in zip(anomalies, anomalies[1:]):
        assert earlier["last_seq"] + 1 == later["first_seq"]
    stats = session.stats()["machines"]["m-1"]
    assert stats["rows_scored"] == 224
    assert stats["rows_pending"] == 0
    assert (
        stats["rows_scored"]
        + stats["rows_failed"]
        + stats["rows_pending"]
        + stats["rows_shed"]
        == stats["rows_in"]
    )


def test_backlog_drain_mints_only_ladder_rung_shapes(
    tmp_path, routed_fake_fleet
):
    """Compile-count pin: draining ragged backlogs of many different
    sizes submits ONLY ladder-aligned cut sizes, so the compiled-shape
    population a drain can mint is bounded by the ladder's rung count —
    never by how ragged the backlogs were."""
    sizes = []
    orig = routed_fake_fleet.fleet_scores

    def recording(inputs):
        sizes.extend(len(X) for X in inputs.values())
        return orig(inputs)

    routed_fake_fleet.fleet_scores = recording
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path, ring_rows=2048)
    for backlog in (224, 197, 510, 333, 75):
        session.append_rows("m-1", frame(backlog))
        while scorer.flush(session)["scored"]:
            pass
    assert sizes
    # whole-window capacities of the default (32, 128, 512, ...) ladder
    aligned = {(rung // WINDOW) * WINDOW for rung in (32, 128, 512)}
    assert set(sizes) <= aligned
    assert len(set(sizes)) <= len(aligned)


def test_small_flushes_are_untouched_by_snapping(tmp_path):
    """Below the smallest aligned rung the whole backlog still scores
    on the first flush — snapping must never delay a small payload."""
    scorer = WindowScorer(5)
    session = make_session(tmp_path)
    session.append_rows("m-1", frame(12))  # 2 whole windows + 2 spare
    summary = scorer.flush(session)
    assert summary["scored"] == {"m-1": 10}
    assert session.stats()["machines"]["m-1"]["rows_pending"] == 2
