"""
Compiled-plan extraction units: every supported scaler's composed
affine must reproduce sklearn's own ``transform`` numbers, and every
unsupported shape must answer None (the host-fallback cue) — never a
silently wrong compilation.
"""

import numpy as np
import pytest
from sklearn.decomposition import PCA
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import (
    MaxAbsScaler,
    MinMaxScaler,
    RobustScaler,
    StandardScaler,
)

from gordo_tpu.ingest import build_fleet_plan, extract_member_plan
from gordo_tpu.ingest.plan import _affine_of

pytestmark = pytest.mark.ingest

N_FEATURES = 4


def _fit_data(seed=7, rows=200):
    rng = np.random.default_rng(seed)
    return rng.normal(loc=3.0, scale=2.5, size=(rows, N_FEATURES))


class _FakeModel:
    """A detector-shaped object graph: ``base_estimator`` is an sklearn
    Pipeline whose last step stands in for the estimator."""

    def __init__(self, transformers):
        steps = [(f"step_{i}", t) for i, t in enumerate(transformers)]
        steps.append(("estimator", object()))
        self.base_estimator = Pipeline.__new__(Pipeline)
        self.base_estimator.steps = steps


@pytest.mark.parametrize(
    "scaler",
    [
        MinMaxScaler(),
        MinMaxScaler(feature_range=(-1, 1)),
        StandardScaler(),
        StandardScaler(with_mean=False),
        StandardScaler(with_std=False),
        MaxAbsScaler(),
        RobustScaler(),
        RobustScaler(with_centering=False),
        RobustScaler(with_scaling=False),
    ],
)
def test_affine_matches_sklearn_transform(scaler):
    X = _fit_data()
    scaler.fit(X)
    scale, offset = _affine_of(scaler)
    np.testing.assert_allclose(
        X * scale + offset, scaler.transform(X), rtol=1e-10, atol=1e-12
    )


def test_chained_scalers_compose_in_pipeline_order():
    X = _fit_data(seed=11)
    first = MinMaxScaler().fit(X)
    second = StandardScaler().fit(first.transform(X))
    plan = extract_member_plan(_FakeModel([first, second]), N_FEATURES)
    assert plan is not None and not plan.identity
    want = second.transform(first.transform(X))
    got = X.astype(np.float32) * plan.scale + plan.offset
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_identity_plan_for_bare_estimator():
    class Bare:
        pass

    plan = extract_member_plan(Bare(), N_FEATURES)
    assert plan is not None and plan.identity


@pytest.mark.parametrize(
    "transformer",
    [
        MinMaxScaler(clip=True),
        MinMaxScaler(),  # unfitted: nothing to compile
        PCA(n_components=2),  # width-changing / not affine
    ],
)
def test_uncompilable_steps_answer_none(transformer):
    if getattr(transformer, "clip", False):
        transformer.fit(_fit_data())
    plan = extract_member_plan(_FakeModel([transformer]), N_FEATURES)
    assert plan is None


def test_scaler_subclass_is_never_compiled():
    class Sneaky(MinMaxScaler):
        def transform(self, X):
            return super().transform(X) ** 2

    sneaky = Sneaky().fit(_fit_data())
    assert _affine_of(sneaky) is None
    assert extract_member_plan(_FakeModel([sneaky]), N_FEATURES) is None


def test_fleet_plan_stacks_members_in_order():
    X = _fit_data(seed=3)
    mm = MinMaxScaler().fit(X)
    std = StandardScaler().fit(X)
    plan = build_fleet_plan(
        [("a", _FakeModel([mm])), ("b", _FakeModel([std]))], N_FEATURES
    )
    assert plan is not None and not plan.identity
    assert plan.names == ["a", "b"]
    assert np.asarray(plan.scale).shape == (2, N_FEATURES)
    np.testing.assert_allclose(
        np.asarray(plan.scale)[0], np.asarray(mm.scale_, np.float32)
    )
    assert plan.nbytes == 2 * N_FEATURES * 4 * 2
    # host copies mirror the device arrays (the fleet route's staging)
    np.testing.assert_array_equal(plan.host_scale, np.asarray(plan.scale))


def test_fleet_plan_is_all_or_nothing():
    class Bare:
        pass

    mm = MinMaxScaler().fit(_fit_data())
    clipped = MinMaxScaler(clip=True).fit(_fit_data())
    assert (
        build_fleet_plan(
            [("ok", _FakeModel([mm])), ("bad", _FakeModel([clipped]))],
            N_FEATURES,
        )
        is None
    )
    # all-identity bucket: identity plan, zero resident bytes
    plan = build_fleet_plan([("a", Bare()), ("b", Bare())], N_FEATURES)
    assert plan is not None and plan.identity
    assert plan.scale is None and plan.nbytes == 0
    assert build_fleet_plan([], N_FEATURES) is None
