"""
Compiled-vs-host parity: the device-resident ingest path must answer the
SAME scores the host sklearn walk answers — across wire formats
(JSON × Arrow), batching modes (micro-batched × unbatched), routes
(prediction / anomaly / fleet / windowed), transfer rungs (dlpack × host
staging), and a mid-batch hot-swap that invalidates the compiled plan.
Identity plans (bare estimators) must stay BIT-identical; non-identity
plans compute float32 on device against the host's float64-then-cast, so
they pin tolerance parity plus verdict agreement.
"""

import json
import re

import numpy as np
import pandas as pd
import pytest
from werkzeug.test import Client

from gordo_tpu.ingest import INGEST_COMPILED_ENV, INGEST_DLPACK_ENV
from gordo_tpu.server import build_app, wire
from gordo_tpu.server.fleet_store import STORE

from tests.server.conftest import temp_env_vars

from .conftest import PROJECT

pytestmark = pytest.mark.ingest

TIME_RE = re.compile(rb'"time-seconds": "[0-9.]+"')


def _norm(body: bytes) -> bytes:
    """Blank the per-request wall-clock field before byte comparison."""
    return TIME_RE.sub(b'"time-seconds": "T"', body)


def _leaves(node, path=()):
    """Flatten a nested response dict to {path: leaf-list} at the level
    where values stop being dicts (routes differ in nesting depth)."""
    out = {}
    for key, value in node.items():
        if isinstance(value, dict):
            if value and not any(isinstance(v, dict) for v in value.values()):
                out[path + (key,)] = list(value.values())
            else:
                out.update(_leaves(value, path + (key,)))
        else:
            out[path + (key,)] = value
    return out


def _frame(payload):
    X = pd.DataFrame(
        {tag: list(col.values()) for tag, col in payload["X"].items()},
        index=pd.DatetimeIndex(
            list(next(iter(payload["X"].values())))
        ),
    )
    return X


def _json_arrays(resp):
    """Every numeric leaf of a JSON scoring response as {path: array}."""
    data = json.loads(resp.data)["data"]
    out = {}
    for group, subs in data.items():
        for sub, cells in subs.items():
            values = list(cells.values())
            try:
                out[(group, sub)] = np.asarray(values, dtype=float)
            except (TypeError, ValueError):
                out[(group, sub)] = np.asarray(values, dtype=object)
    return out


def _assert_close(got, want, rtol=2e-3, atol=1e-4):
    assert set(got) == set(want)
    for key in want:
        if want[key].dtype == object:
            np.testing.assert_array_equal(got[key], want[key], err_msg=str(key))
        else:
            np.testing.assert_allclose(
                got[key], want[key], rtol=rtol, atol=atol, err_msg=str(key)
            )


def _post(collection_dir, path, payload=None, data=None, headers=None):
    client = Client(build_app(config={}))
    if data is not None:
        resp = client.post(path, data=data, headers=headers)
    else:
        resp = client.post(path, json=payload)
    assert resp.status_code == 200, resp.data[:300]
    return resp


def _compiled_vs_host(collection_dir, path, payload):
    """The same request with the compiled plan on and off."""
    responses = {}
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        for switch in ("1", "0"):
            with temp_env_vars(**{INGEST_COMPILED_ENV: switch}):
                STORE.clear()
                responses[switch] = _post(collection_dir, path, payload)
    return responses


@pytest.mark.parametrize("route", ["prediction", "anomaly/prediction"])
@pytest.mark.parametrize("name", ["scaled-mm", "scaled-std"])
def test_compiled_scaler_matches_host_json(
    ingest_collection, scaled_payload, route, name
):
    responses = _compiled_vs_host(
        ingest_collection, f"/gordo/v0/{PROJECT}/{name}/{route}", scaled_payload
    )
    _assert_close(
        _json_arrays(responses["1"]), _json_arrays(responses["0"])
    )


def test_identity_plan_is_bit_identical(ingest_collection):
    """Bare-estimator machines run the classic program on the compiled
    path: identical BYTES with the plan on and off."""
    index = [f"2020-03-01T00:{m:02d}:00+00:00" for m in range(0, 50, 10)]
    payload = {
        "X": {
            f"ing-{i}": {ts: 0.3 * i + 0.05 * j for j, ts in enumerate(index)}
            for i in (1, 2)
        }
    }
    responses = _compiled_vs_host(
        ingest_collection, f"/gordo/v0/{PROJECT}/plain-id/prediction", payload
    )
    assert _norm(responses["1"].data) == _norm(responses["0"].data)


def test_arrow_wire_matches_json_with_compiled_ingest(
    ingest_collection, scaled_payload
):
    """Arrow requests ride the raw-column stash + dlpack rung; JSON
    requests stage from the decoded matrix — same verdicts."""
    X = _frame(scaled_payload)
    path = f"/gordo/v0/{PROJECT}/scaled-mm/anomaly/prediction"
    with temp_env_vars(MODEL_COLLECTION_DIR=ingest_collection):
        STORE.clear()
        json_resp = _post(ingest_collection, path, scaled_payload)
        arrow_resp = _post(
            ingest_collection,
            path,
            data=wire.encode_request(X, X),
            headers={"Content-Type": wire.ARROW_CONTENT_TYPE},
        )
    _assert_close(
        _json_arrays(arrow_resp),
        _json_arrays(json_resp),
        rtol=1e-5,
        atol=1e-6,
    )


def test_dlpack_rung_matches_host_staging_exactly(
    ingest_collection, scaled_payload
):
    """The two transfer rungs move the same float32 values — identical
    bytes, not just tolerance parity."""
    X = _frame(scaled_payload)
    path = f"/gordo/v0/{PROJECT}/scaled-mm/prediction"
    bodies = {}
    with temp_env_vars(MODEL_COLLECTION_DIR=ingest_collection):
        for switch in ("1", "0"):
            with temp_env_vars(**{INGEST_DLPACK_ENV: switch}):
                STORE.clear()
                bodies[switch] = _post(
                    ingest_collection,
                    path,
                    data=wire.encode_request(X),
                    headers={"Content-Type": wire.ARROW_CONTENT_TYPE},
                ).data
    assert _norm(bodies["1"]) == _norm(bodies["0"])


def test_fleet_route_compiled_matches_host(ingest_collection, scaled_payload):
    """The fleet route applies the plan host-side from the cached
    host_scale/host_offset copies — same verdicts as the sklearn walk."""
    payload = {
        "X": {
            "scaled-mm": scaled_payload["X"],
            "scaled-std": scaled_payload["X"],
        }
    }
    responses = _compiled_vs_host(
        ingest_collection, f"/gordo/v0/{PROJECT}/prediction/fleet", payload
    )
    on = json.loads(responses["1"].data)
    off = json.loads(responses["0"].data)
    assert on.get("errors", {}) == off.get("errors", {}) == {}
    got, want = _leaves(on["data"]), _leaves(off["data"])
    assert set(got) == set(want)
    for path, cells in want.items():
        try:
            want_arr = np.asarray(cells, dtype=float)
        except (TypeError, ValueError):
            np.testing.assert_array_equal(got[path], cells, err_msg=str(path))
            continue
        np.testing.assert_allclose(
            np.asarray(got[path], dtype=float),
            want_arr,
            rtol=2e-3,
            atol=1e-4,
            err_msg=str(path),
        )


def test_batched_compiled_matches_unbatched_host(
    ingest_collection, scaled_payload
):
    """Micro-batched raw-column scoring (the fused preprocess prologue)
    vs the unbatched host path: same scores for the same rows."""
    from tests.serve.conftest import installed_engine

    path = f"/gordo/v0/{PROJECT}/scaled-mm/anomaly/prediction"
    with temp_env_vars(MODEL_COLLECTION_DIR=ingest_collection):
        with temp_env_vars(**{INGEST_COMPILED_ENV: "0"}):
            STORE.clear()
            host = _post(ingest_collection, path, scaled_payload)
        STORE.clear()
        with installed_engine() as engine:
            batched = _post(ingest_collection, path, scaled_payload)
            stats = engine.stats()
    assert stats["ingest_requests"] >= 1
    assert stats["ingest_batches"] >= 1
    _assert_close(_json_arrays(batched), _json_arrays(host))


def test_mid_batch_hot_swap_replans_to_host_path(
    ingest_collection, scaled_payload, monkeypatch
):
    """A plan whose member list no longer matches the bucket at flush
    time (a hot-load landed between admission and flush) must be
    discarded: the batch re-materializes legacy payloads, counts a
    replan, and still answers the right scores."""
    from gordo_tpu.ingest.plan import FleetIngestPlan
    from gordo_tpu.server.fleet_store import RevisionFleet

    from tests.serve.conftest import installed_engine

    with temp_env_vars(MODEL_COLLECTION_DIR=ingest_collection):
        STORE.clear()
        fleet = STORE.fleet(ingest_collection)
        model = fleet.model("scaled-mm")
        X = _frame(scaled_payload)
        want = np.asarray(model.predict(X))
        spec = fleet.loaded_specs()["scaled-mm"]
        real = fleet.ingest_plan(spec)
        assert real is not None and not real.identity

        calls = {"n": 0}
        original = RevisionFleet.ingest_plan

        def shifty(self, s):
            calls["n"] += 1
            if calls["n"] <= 1:
                return original(self, s)  # admission sees the real plan
            return FleetIngestPlan(  # flush sees a stale member list
                ["ghost"],
                real.scale,
                real.offset,
                identity=False,
                host_scale=real.host_scale,
                host_offset=real.host_offset,
            )

        monkeypatch.setattr(RevisionFleet, "ingest_plan", shifty)
        with installed_engine() as engine:
            got = engine.batched_predict(
                ingest_collection, "scaled-mm", model, X
            )
            stats = engine.stats()
    assert stats["ingest_replans"] >= 1
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


LSTM_CONFIG = """
machines:
  - name: lstm-ingest
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [lt-1, lt-2]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxLSTMAutoEncoder:
            kind: lstm_model
            lookback_window: 4
            epochs: 1
"""


@pytest.fixture(scope="module")
def lstm_collection(tmp_path_factory):
    from gordo_tpu import serializer
    from gordo_tpu.builder import local_build

    root = tmp_path_factory.mktemp("ingest-lstm") / "1710000000001"
    for model, machine in local_build(LSTM_CONFIG, project_name=PROJECT):
        serializer.dump(
            model, str(root / machine.name), metadata=machine.to_dict()
        )
    return str(root)


def test_windowed_route_keeps_host_path_bit_identical(lstm_collection):
    """Windowed (LSTM) specs have no compiled plan: the route must take
    the host path with the ingest subsystem on — identical bytes."""
    n_rows = 12
    index = [f"2020-03-01T{h:02d}:00:00+00:00" for h in range(n_rows)]
    values = {
        f"lt-{i}": {ts: 0.1 * i + 0.01 * j for j, ts in enumerate(index)}
        for i in (1, 2)
    }
    payload = {"X": values, "y": values}
    responses = _compiled_vs_host(
        lstm_collection,
        f"/gordo/v0/{PROJECT}/lstm-ingest/anomaly/prediction",
        payload,
    )
    assert _norm(responses["1"].data) == _norm(responses["0"].data)
