"""
Artifact contract: a dumped model must reload in a FRESH process (new JAX
runtime, no warm caches) and predict bit-identically — the
device-independence guarantee the serving plane relies on when builder
pods write artifacts that server pods (different hosts, possibly no TPU)
later unpickle.
"""

import os
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.builder import local_build

CONFIG = """
machines:
  - name: contract-ae
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
              - sklearn.preprocessing.MinMaxScaler
              - gordo_tpu.models.JaxAutoEncoder:
                  kind: feedforward_hourglass
                  epochs: 2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [ca-1, ca-2, ca-3]
  - name: contract-lstm
    model:
      gordo_tpu.models.JaxLSTMAutoEncoder:
        kind: lstm_model
        lookback_window: 4
        epochs: 1
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [ca-1, ca-2]
"""

RELOADER = textwrap.dedent(
    """
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from gordo_tpu import serializer

    model_dir, probe_path, out_path = sys.argv[1:4]
    model = serializer.load(model_dir)
    probe = np.load(probe_path)
    np.save(out_path, np.asarray(model.predict(probe)))
    """
)


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    root = tmp_path_factory.mktemp("artifact-contract")
    artifacts = {}
    for model, machine in local_build(CONFIG):
        model_dir = root / machine.name
        serializer.dump(model, str(model_dir), metadata=machine.to_dict())
        artifacts[machine.name] = (model, str(model_dir))
    return artifacts


@pytest.mark.parametrize(
    "name,width", [("contract-ae", 3), ("contract-lstm", 2)]
)
def test_fresh_process_reload_predicts_identically(built, tmp_path, name, width):
    model, model_dir = built[name]
    probe = np.random.RandomState(0).rand(32, width).astype(np.float32)
    expected = np.asarray(model.predict(probe))

    probe_path = str(tmp_path / f"{name}-probe.npy")
    out_path = str(tmp_path / f"{name}-out.npy")
    np.save(probe_path, probe)
    result = subprocess.run(
        [sys.executable, "-c", RELOADER, model_dir, probe_path, out_path],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    got = np.load(out_path)
    np.testing.assert_array_equal(got, expected)


def test_info_checksum_present_and_stable(built):
    for _, model_dir in built.values():
        info = serializer.load_info(model_dir)
        assert info["checksum"]
        assert info["checksum"] == serializer.load_info(model_dir)["checksum"]


def test_dumps_loads_bytes_identical_predictions(built):
    model, _ = built["contract-ae"]
    probe = np.random.RandomState(1).rand(8, 3).astype(np.float32)
    clone = serializer.loads(serializer.dumps(model))
    np.testing.assert_array_equal(
        np.asarray(clone.predict(probe)), np.asarray(model.predict(probe))
    )


def test_download_model_wire_format_round_trips(built):
    """The /download-model wire format is serializer.dumps — a client on a
    CPU-only laptop must be able to unpickle and use it."""
    model, model_dir = built["contract-ae"]
    payload = serializer.dumps(model)
    with tempfile.TemporaryDirectory() as tmp:
        blob = os.path.join(tmp, "model.pickle")
        with open(blob, "wb") as f:
            f.write(payload)
        loader = textwrap.dedent(
            """
            import pickle
            import sys

            import jax

            jax.config.update("jax_platforms", "cpu")

            import numpy as np

            with open(sys.argv[1], "rb") as f:
                model = pickle.load(f)
            out = model.predict(np.zeros((4, 3), np.float32))
            assert out.shape == (4, 3), out.shape
            print("ok")
            """
        )
        result = subprocess.run(
            [sys.executable, "-c", loader, blob],
            capture_output=True,
            text=True,
            timeout=300,
        )
        assert result.returncode == 0, result.stderr[-2000:]
        assert "ok" in result.stdout
