"""
Watermark scoring drills against a fake fleet: quarantine gating (rows
stay buffered, innocents keep scoring), half-open recovery on the live
stream, the ``stream_score`` fault site, hot-swap revision pinning with
contiguous row spans, and breaker classification of client-data errors.
"""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu import serve
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.stream.scorer import WindowScorer
from gordo_tpu.stream.session import StreamSession
from gordo_tpu.utils.faults import FaultRule, inject

from .test_session import parse_frames

pytestmark = [pytest.mark.stream, pytest.mark.chaos]

WINDOW = 4


class FakeFleet:
    """fleet_scores twin: every machine echoes rows of mse 0.5, except
    names in ``poison`` which land in the errors dict."""

    def __init__(self, directory):
        self.directory = directory
        self.poison = {}

    def model(self, name):
        return object()

    def loaded_specs(self):
        return {}

    def fleet_scores(self, inputs):
        scores, errors = {}, {}
        for name, X in inputs.items():
            exc = self.poison.get(name)
            if exc is not None:
                errors[name] = exc
            else:
                rows = len(X)
                scores[name] = (np.zeros((rows, 2)), np.full(rows, 0.5))
        return scores, errors


@pytest.fixture
def fake_store(monkeypatch, tmp_path):
    """Route the module STORE at a fake fleet; returns the fleet and a
    swap(dir) hook that re-pins routing at a new revision dir."""
    state = {"routed": str(tmp_path / "rev-a")}
    fleets = {}

    def route(directory):
        return state["routed"]

    def fleet(directory):
        return fleets.setdefault(directory, FakeFleet(directory))

    monkeypatch.setattr(STORE, "route", route)
    monkeypatch.setattr(STORE, "fleet", fleet)

    def swap(directory):
        state["routed"] = str(directory)

    return fleets, swap, state


@pytest.fixture(autouse=True)
def fresh_breakers(monkeypatch):
    """Standalone stream breaker board, threshold 1, short cooldown —
    and no engine, so the board is truly the stream's own."""
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "0.15")
    monkeypatch.setenv("GORDO_TPU_BREAKER_BACKOFF", "1.0")
    engine = serve.get_engine()
    serve.install_engine(None)
    serve.reset_stream_breakers()
    yield
    serve.reset_stream_breakers()
    serve.install_engine(engine)


def make_session(tmp_path):
    return StreamSession(
        "proj", "sid", str(tmp_path / "rev-a"), ring_rows=64,
        outbox_events=64,
    )


def frame(rows):
    return pd.DataFrame({"tag-1": np.arange(rows, dtype=float)})


def events_of(session, kind=None):
    frames = parse_frames(
        list(session.subscribe(heartbeat_s=0.01, idle_timeout_s=0.02))
    )
    if kind is None:
        return frames
    return [data for _, k, data in frames if k == kind]


def test_flush_scores_full_windows_only(fake_store, tmp_path):
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    session.append_rows("m-1", frame(WINDOW + 1))  # one window + 1 spare
    session.append_rows("m-2", frame(WINDOW - 1))  # below the watermark
    summary = scorer.flush(session)
    assert summary["scored"] == {"m-1": WINDOW}
    assert summary["rows"] == WINDOW
    anomalies = events_of(session, "anomaly")
    assert anomalies == [
        {
            "machine": "m-1",
            "first_seq": 1,
            "last_seq": WINDOW,
            "rows": WINDOW,
            "windows": 1,
            "mse_mean": 0.5,
            "mse_max": 0.5,
            "revision": "rev-a",
        }
    ]
    stats = session.stats()["machines"]
    assert stats["m-1"]["rows_pending"] == 1
    assert stats["m-2"]["rows_pending"] == WINDOW - 1
    assert stats["m-2"]["rows_scored"] == 0


def test_poison_is_quarantined_while_innocents_keep_scoring(
    fake_store, tmp_path
):
    fleets, _swap, state = fake_store
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    fleet = fleets.setdefault(state["routed"], FakeFleet(state["routed"]))
    fleet.poison["bad"] = RuntimeError("device poisoned")

    # flush 1: the poison member fails server-side -> breaker trips
    session.append_rows("bad", frame(WINDOW))
    session.append_rows("good", frame(WINDOW))
    summary = scorer.flush(session)
    assert summary["scored"] == {"good": WINDOW}
    assert summary["errors"] == {"bad": "RuntimeError"}

    # flush 2: the tripped member is gated BEFORE cutting — its rows
    # stay buffered; the innocent scores the same flush
    session.append_rows("bad", frame(WINDOW))
    session.append_rows("good", frame(WINDOW))
    summary = scorer.flush(session)
    assert "bad" in summary["quarantined"]
    assert summary["quarantined"]["bad"] > 0  # the Retry-After hint
    assert summary["scored"] == {"good": WINDOW}
    stats = session.stats()["machines"]
    assert stats["bad"]["rows_pending"] == WINDOW  # buffered, not dropped
    assert stats["bad"]["quarantined"] is True
    assert stats["good"]["rows_scored"] == 2 * WINDOW  # zero innocent drops

    frames = events_of(session)
    kinds = [k for _, k, _ in frames]
    assert kinds.count("quarantined") == 1  # deduped, not per-flush noise
    quarantine = [d for _, k, d in frames if k == "quarantined"][0]
    assert quarantine["machine"] == "bad"
    assert quarantine["retry_after_s"] > 0


def test_half_open_probe_recovers_on_the_live_stream(fake_store, tmp_path):
    import time

    fleets, _swap, state = fake_store
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    fleet = fleets.setdefault(state["routed"], FakeFleet(state["routed"]))
    fleet.poison["bad"] = RuntimeError("device poisoned")

    session.append_rows("bad", frame(WINDOW))
    scorer.flush(session)  # window 1 cut, fails server-side: trips
    session.append_rows("bad", frame(WINDOW))
    assert "bad" in scorer.flush(session)["quarantined"]
    session.append_rows("bad", frame(WINDOW))  # buffers while quarantined

    fleet.poison.clear()  # the fault clears
    time.sleep(0.2)  # past the 0.15s cooldown -> half-open
    summary = scorer.flush(session)
    # the half-open probe scores the ENTIRE quarantine-era backlog as
    # one contiguous span — buffered windows were never dropped
    assert summary["scored"] == {"bad": 2 * WINDOW}
    frames = events_of(session)
    kinds = [k for _, k, _ in frames]
    assert "recovered" in kinds
    anomalies = [d for _, k, d in frames if k == "anomaly"]
    # rows 1..WINDOW were cut by flush 1 and failed; the backlog span
    # picks up exactly where the failed window ended
    assert anomalies[-1]["first_seq"] == WINDOW + 1
    assert anomalies[-1]["last_seq"] == 3 * WINDOW
    assert anomalies[-1]["windows"] == 2
    stats = session.stats()["machines"]["bad"]
    assert stats["quarantined"] is False
    assert stats["rows_scored"] == 2 * WINDOW
    assert stats["rows_failed"] == WINDOW
    # zero-gap ledger across the whole episode
    assert (
        stats["rows_scored"]
        + stats["rows_failed"]
        + stats["rows_pending"]
        + stats["rows_shed"]
        == stats["rows_in"]
    )


def test_stream_score_fault_site_is_per_member(fake_store, tmp_path):
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    session.append_rows("bad", frame(WINDOW))
    session.append_rows("good", frame(WINDOW))
    with inject(FaultRule("stream_score", match="sid:bad", times=None)):
        summary = scorer.flush(session)
    assert summary["scored"] == {"good": WINDOW}
    assert summary["errors"] == {"bad": "FaultInjected"}
    errors = events_of(session, "error")
    assert errors == [
        {"machine": "bad", "first_seq": 1, "last_seq": WINDOW,
         "error": "FaultInjected"}
    ]
    stats = session.stats()["machines"]["bad"]
    assert stats["rows_failed"] == WINDOW
    assert stats["score_errors"] == 1


def test_hot_swap_pins_revision_per_flush_with_contiguous_spans(
    fake_store, tmp_path
):
    _fleets, swap, _state = fake_store
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)

    session.append_rows("m-1", frame(WINDOW))
    scorer.flush(session)
    swap(tmp_path / "rev-b")  # the promotion lands between flushes
    session.append_rows("m-1", frame(WINDOW))
    scorer.flush(session)

    anomalies = events_of(session, "anomaly")
    assert [a["revision"] for a in anomalies] == ["rev-a", "rev-b"]
    # zero-gap across the swap: spans abut exactly
    assert anomalies[0]["last_seq"] + 1 == anomalies[1]["first_seq"]
    assert [a["first_seq"] for a in anomalies] == [1, WINDOW + 1]


def test_client_data_errors_do_not_trip_the_breaker(fake_store, tmp_path):
    fleets, _swap, state = fake_store
    scorer = WindowScorer(WINDOW)
    session = make_session(tmp_path)
    fleet = fleets.setdefault(state["routed"], FakeFleet(state["routed"]))
    fleet.poison["m-1"] = ValueError("wrong columns")

    session.append_rows("m-1", frame(WINDOW))
    summary = scorer.flush(session)
    assert summary["errors"] == {"m-1": "ValueError"}
    # threshold is 1: a server-side error would have quarantined it
    fleet.poison.clear()
    session.append_rows("m-1", frame(WINDOW))
    summary = scorer.flush(session)
    assert summary["quarantined"] == {}
    assert summary["scored"] == {"m-1": WINDOW}


def test_stream_only_scoring_populates_the_health_ledger(
    fake_store, tmp_path, monkeypatch
):
    """Satellite: a stream-only deployment (no HTTP scoring traffic at
    all) still narrates per-machine health through the anchor ledger."""
    from gordo_tpu.telemetry.fleet_health import ledger_for, reset_ledgers

    reset_ledgers()
    anchor = tmp_path / "anchor"
    anchor.mkdir()
    scorer = WindowScorer(WINDOW, ledger_anchor=str(anchor))
    session = make_session(tmp_path)
    try:
        session.append_rows("m-1", frame(WINDOW))
        scorer.flush(session)
        doc = ledger_for(str(anchor)).document() or {}
        record = (doc.get("machines") or {}).get("m-1") or {}
        assert record, doc
        serving = record.get("serving") or {}
        assert serving.get("rows", 0) >= WINDOW
        assert serving.get("residual_mean") == pytest.approx(0.5)
        assert serving.get("requests", 0) >= 1
        assert serving.get("errors", 0) == 0
    finally:
        reset_ledgers()
