"""
Stream sessions: SSE subscription replay/cursor semantics, terminal
frames, backpressure surfacing, the ``stream_emit`` fault drill, and the
per-machine zero-gap accounting. No scoring here — frames are plain
lists and events are appended by hand.
"""

import json
import threading

import pytest

from gordo_tpu.stream.events import StreamEvent, heartbeat_frame
from gordo_tpu.stream.session import StreamSession
from gordo_tpu.utils.faults import FaultRule, inject

pytestmark = pytest.mark.stream


def make_session(ring_rows=100, outbox_events=100) -> StreamSession:
    return StreamSession(
        "proj", "s1", "/tmp/anchor", ring_rows=ring_rows,
        outbox_events=outbox_events,
    )


def parse_frames(frames):
    """Decode SSE wire frames into (id, event, data) tuples; heartbeats
    come back as ("", "heartbeat", None)."""
    out = []
    for frame in frames:
        assert frame.endswith("\n\n"), frame
        if frame.startswith(":"):
            out.append(("", "heartbeat", None))
            continue
        fields = dict(
            line.split(": ", 1) for line in frame.strip().split("\n")
        )
        out.append(
            (
                fields.get("id", ""),
                fields["event"],
                json.loads(fields["data"]),
            )
        )
    return out


def collect(session, **kwargs):
    return parse_frames(list(session.subscribe(**kwargs)))


# -- subscribe replay / cursor ----------------------------------------------


def test_subscribe_opens_then_replays_then_terminates():
    session = make_session()
    session.emit(StreamEvent("anomaly", {"machine": "m-1"}))
    session.emit(StreamEvent("anomaly", {"machine": "m-2"}))
    session.close("end", reason="done")
    frames = collect(session)
    ids, kinds, datas = zip(*frames)
    assert kinds == ("open", "anomaly", "anomaly", "end")
    # the open frame is subscription-local: no id (it must never
    # advance a reconnecting consumer's Last-Event-ID)
    assert ids[0] == ""
    assert [i for i in ids[1:]] == ["1", "2", "3"]
    assert datas[0]["stream"] == "s1"
    assert datas[-1]["reason"] == "done"


def test_subscribe_from_cursor_skips_consumed_events():
    session = make_session()
    for i in range(4):
        session.emit(StreamEvent("anomaly", {"n": i}))
    session.close()
    frames = collect(session, cursor=2)
    kinds = [kind for _, kind, _ in frames]
    assert kinds == ["open", "anomaly", "anomaly", "end"]
    assert [data["n"] for _, kind, data in frames if kind == "anomaly"] == [
        2,
        3,
    ]


def test_reconnect_resumes_without_gap_or_duplicate():
    """The disconnect drill: consume a prefix, 'drop the connection',
    reconnect with the last seen id — the tail continues exactly."""
    session = make_session()
    for i in range(6):
        session.emit(StreamEvent("anomaly", {"n": i}))
    first_half = collect(session, max_events=3)
    last_id = int([i for i, _, _ in first_half if i][-1])
    session.close()
    second_half = collect(session, cursor=last_id)
    seen = [
        data["n"]
        for _, kind, data in first_half + second_half
        if kind == "anomaly"
    ]
    assert seen == [0, 1, 2, 3, 4, 5]


def test_slow_consumer_outbox_eviction_is_reported():
    session = make_session(outbox_events=3)
    for i in range(8):
        session.emit(StreamEvent("anomaly", {"n": i}))
    session.close()  # terminal occupies one outbox slot too
    frames = collect(session)
    kinds = [kind for _, kind, _ in frames]
    assert kinds[0] == "open"
    assert kinds[1] == "shed"
    shed = frames[1][2]
    assert shed["scope"] == "outbox"
    assert shed["dropped"] == 6  # 9 events, 3 retained
    assert session.stats()["events_dropped_outbox"] == 6


def test_idle_subscription_heartbeats_then_times_out():
    session = make_session()
    frames = list(
        session.subscribe(heartbeat_s=0.01, idle_timeout_s=0.05)
    )
    assert frames[0].startswith("event: open")
    # heartbeats carry the cursor + pending-row payload (still SSE
    # comment frames — no id:, Last-Event-ID never advances)
    assert heartbeat_frame(cursor=0, pending_rows=0) in frames[1:]
    for frame in frames[1:]:
        assert frame.startswith(": keep-alive")
        assert "id:" not in frame


def test_max_events_bounds_the_response():
    session = make_session()
    for i in range(5):
        session.emit(StreamEvent("anomaly", {"n": i}))
    frames = collect(session, max_events=2)
    assert [kind for _, kind, _ in frames] == ["open", "anomaly", "anomaly"]


# -- close / drain -----------------------------------------------------------


def test_close_is_idempotent_one_terminal_frame():
    session = make_session()
    session.close("drain", reason="server draining")
    session.close("end", reason="too late")
    frames = collect(session)
    kinds = [kind for _, kind, _ in frames]
    assert kinds == ["open", "drain"]
    assert frames[1][2]["reason"] == "server draining"


def test_close_wakes_blocked_subscriber_with_terminal_frame():
    session = make_session()
    got = []

    def consume():
        got.extend(parse_frames(list(session.subscribe(heartbeat_s=5.0))))

    thread = threading.Thread(target=consume, daemon=True)
    thread.start()
    # subscriber is parked in the condition wait; drain must wake it
    session.close("drain", reason="server draining")
    thread.join(timeout=10)
    assert not thread.is_alive()
    assert [kind for _, kind, _ in got] == ["open", "drain"]


# -- backpressure ------------------------------------------------------------


def test_ring_overflow_emits_shed_control_frame():
    session = make_session(ring_rows=4)
    session.append_rows("m-1", [1, 2, 3])
    first_seq, shed = session.append_rows("m-1", [4, 5, 6])
    assert shed == 2
    session.close()
    frames = collect(session)
    shed_frames = [data for _, kind, data in frames if kind == "shed"]
    assert shed_frames == [
        {
            "scope": "ring",
            "machine": "m-1",
            "dropped": 2,
            "rows_shed_total": 2,
        }
    ]
    stats = session.stats()["machines"]["m-1"]
    assert stats["rows_in"] == 6
    assert stats["rows_shed"] == 2
    assert stats["rows_pending"] == 4
    # the zero-gap ledger: every ingested row is accounted for
    assert (
        stats["rows_scored"]
        + stats["rows_failed"]
        + stats["rows_pending"]
        + stats["rows_shed"]
        == stats["rows_in"]
    )


# -- the stream_emit fault drill ---------------------------------------------


def test_emit_fault_drops_are_counted_and_surfaced():
    session = make_session()
    rule = FaultRule("stream_emit", match="s1:anomaly", times=2)
    with inject(rule):
        session.emit(StreamEvent("anomaly", {"n": 0}))  # dropped
        session.emit(StreamEvent("anomaly", {"n": 1}))  # dropped
        session.emit(StreamEvent("anomaly", {"n": 2}))  # lands
    session.close()
    frames = collect(session)
    kinds = [kind for _, kind, _ in frames]
    # the deferred loss report precedes the first event that landed
    assert kinds == ["open", "shed", "anomaly", "end"]
    assert frames[1][2] == {"scope": "emit", "dropped": 2}
    assert frames[2][2]["n"] == 2
    assert session.stats()["events_dropped_emit"] == 2


def test_emit_fault_cannot_suppress_terminal_frame():
    """A drill matching EVERY emit on the stream must still let the
    terminal through: close() uses the unfaulted append."""
    session = make_session()
    with inject(FaultRule("stream_emit", match="s1:*", times=None)):
        session.emit(StreamEvent("anomaly", {"n": 0}))
        session.close("drain", reason="server draining")
    frames = collect(session)
    kinds = [kind for _, kind, _ in frames]
    assert kinds == ["open", "shed", "drain"]
    assert frames[1][2] == {"scope": "emit", "dropped": 1}
