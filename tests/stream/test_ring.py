"""
Streaming ring buffers: bounded row rings with 1-based monotonic row
sequence numbers and oldest-first shedding, and the bounded event outbox
with cursor replay + honest eviction accounting. Pure stdlib units (the
"frames" are plain lists) — the seq arithmetic here is what the zero-gap
soak audit leans on.
"""

import pytest

from gordo_tpu.stream.ring import EventRing, RowRing

pytestmark = pytest.mark.stream


# -- RowRing -----------------------------------------------------------------


def test_row_ring_append_assigns_contiguous_seqs():
    ring = RowRing(100)
    first, shed = ring.append([1, 2, 3])
    assert (first, shed) == (1, 0)
    first, shed = ring.append([4, 5])
    assert (first, shed) == (4, 0)
    assert ring.pending_rows == 5
    assert ring.next_seq == 6
    assert ring.shed_rows == 0


def test_row_ring_take_returns_exact_span():
    ring = RowRing(100)
    ring.append([1, 2, 3])
    ring.append([4, 5, 6])
    chunks, first, last, _ = ring.take(4)
    assert first == 1 and last == 4
    assert [row for chunk in chunks for row in chunk] == [1, 2, 3, 4]
    assert ring.pending_rows == 2
    # the remainder keeps its original seqs
    chunks, first, last, _ = ring.take(2)
    assert first == 5 and last == 6
    assert [row for chunk in chunks for row in chunk] == [5, 6]


def test_row_ring_take_insufficient_rows_is_none():
    ring = RowRing(100)
    ring.append([1, 2])
    assert ring.take(3) is None
    assert ring.pending_rows == 2  # nothing consumed on refusal


def test_row_ring_sheds_oldest_first_and_counts():
    ring = RowRing(4)
    ring.append([1, 2, 3])
    first, shed = ring.append([4, 5, 6])
    assert first == 4
    assert shed == 2  # rows 1-2 evicted to fit 6 pending into 4
    assert ring.pending_rows == 4
    assert ring.shed_rows == 2
    # what remains is the NEWEST 4 rows, seqs intact
    chunks, first, last, _ = ring.take(4)
    assert (first, last) == (3, 6)
    assert [row for chunk in chunks for row in chunk] == [3, 4, 5, 6]


def test_row_ring_oversized_chunk_keeps_newest_capacity_rows():
    ring = RowRing(3)
    first, shed = ring.append([1, 2, 3, 4, 5])
    assert first == 1
    assert shed == 2
    chunks, first, last, _ = ring.take(3)
    # seqs 1-2 were shed from inside the oversized chunk itself
    assert (first, last) == (3, 5)
    assert [row for chunk in chunks for row in chunk] == [3, 4, 5]


def test_row_ring_seq_continuity_across_shed_and_take():
    """The zero-gap invariant's bookkeeping: every row seq is consumed
    exactly once, either by take() or by the shed counter."""
    ring = RowRing(5)
    total_in = 0
    taken = []
    for batch in ([1] * 4, [2] * 4, [3] * 4):
        ring.append(list(batch))
        total_in += len(batch)
        got = ring.take(3)
        if got is not None:
            _, first, last, _ = got
            taken.append((first, last))
    consumed = sum(last - first + 1 for first, last in taken)
    assert consumed + ring.pending_rows + ring.shed_rows == total_in
    # spans never overlap and never run backwards
    for (_, prev_last), (nxt_first, _) in zip(taken, taken[1:]):
        assert nxt_first > prev_last


# -- EventRing ---------------------------------------------------------------


def test_event_ring_since_replays_from_cursor():
    ring = EventRing(10)
    assert ring.append("a") == 1
    assert ring.append("b") == 2
    assert ring.append("c") == 3
    batch, missed = ring.since(0)
    assert [seq for seq, _ in batch] == [1, 2, 3]
    assert missed == 0
    batch, missed = ring.since(2)
    assert [(seq, ev) for seq, ev in batch] == [(3, "c")]
    assert missed == 0
    assert ring.since(3) == ([], 0)


def test_event_ring_eviction_reports_missed_events():
    ring = EventRing(2)
    for event in "abcd":
        ring.append(event)
    assert ring.latest_seq == 4
    assert ring.oldest_seq == 3
    assert ring.dropped == 2
    batch, missed = ring.since(0)
    assert [ev for _, ev in batch] == ["c", "d"]
    assert missed == 2  # "a" and "b" are gone and the reader is told
    # a cursor inside the retained window misses nothing
    batch, missed = ring.since(3)
    assert [ev for _, ev in batch] == ["d"]
    assert missed == 0
