"""
StreamPlane coordination: session admission/cap/TTL, the ingest ack
(backpressure fields, per-machine ``stream_ingest`` fault isolation),
drain semantics, and the process-global install/reset lifecycle.
"""

import pandas as pd
import pytest

from gordo_tpu import serve
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.stream import (
    PlaneSaturated,
    StreamConfig,
    StreamPlane,
    ensure_plane,
    get_plane,
    install_plane,
    reset_plane,
)
from gordo_tpu.utils.faults import FaultRule, inject

from .test_scorer import FakeFleet
from .test_session import parse_frames

pytestmark = pytest.mark.stream


@pytest.fixture(autouse=True)
def fake_store(monkeypatch, tmp_path):
    fleets = {}
    monkeypatch.setattr(STORE, "route", lambda directory: directory)
    monkeypatch.setattr(
        STORE,
        "fleet",
        lambda directory: fleets.setdefault(directory, FakeFleet(directory)),
    )
    engine = serve.get_engine()
    serve.install_engine(None)
    serve.reset_stream_breakers()
    yield fleets
    serve.reset_stream_breakers()
    serve.install_engine(engine)


def make_plane(**overrides):
    defaults = dict(
        ring_rows=16,
        window_rows=4,
        outbox_events=32,
        session_ttl_s=60.0,
        heartbeat_s=0.05,
        max_sessions=2,
        shed_retry_s=0.5,
    )
    defaults.update(overrides)
    return StreamPlane(StreamConfig(**defaults))


def frame(rows):
    return pd.DataFrame({"tag-1": [float(i) for i in range(rows)]})


def test_session_cap_rejects_with_retry_hint():
    plane = make_plane(max_sessions=2)
    plane.session("p", "s1", "/dir")
    plane.session("p", "s2", "/dir")
    plane.session("p", "s1", "/dir")  # existing: not a new admission
    with pytest.raises(PlaneSaturated) as info:
        plane.session("p", "s3", "/dir")
    assert info.value.retry_after_s == 0.5
    assert plane.stats()["counters"]["sessions_rejected"] == 1


def test_idle_session_expires_with_terminal_end_frame():
    plane = make_plane(session_ttl_s=1.0)
    session = plane.session("p", "s1", "/dir")
    session.last_used -= 5.0  # age it past the TTL by hand
    assert plane.session("p", "s2", "/dir") is not None  # triggers prune
    assert session.closed
    frames = parse_frames(list(session.subscribe(heartbeat_s=0.01)))
    assert frames[-1][1] == "end"
    assert "expired" in frames[-1][2]["reason"]
    assert plane.stats()["counters"]["sessions_expired"] == 1
    assert plane.session("p", "s1", "/dir", create=False) is None


def test_ingest_ack_reports_scored_rows_and_cursor():
    plane = make_plane()
    session = plane.session("p", "s1", "/dir")
    ack = plane.ingest(session, {"m-1": frame(4), "m-2": frame(2)})
    assert ack["accepted"] == {"m-1": 4, "m-2": 2}
    assert ack["scored"] == {"m-1": 4}  # m-2 below the watermark
    assert ack["errors"] == {}
    assert ack["backpressure"] is False
    assert "retry_after_s" not in ack
    assert ack["cursor"] == session.latest_seq() >= 1


def test_ingest_backpressure_ack_when_ring_sheds():
    plane = make_plane(ring_rows=4, window_rows=100)  # never scores
    session = plane.session("p", "s1", "/dir")
    plane.ingest(session, {"m-1": frame(3)})
    ack = plane.ingest(session, {"m-1": frame(3)})
    assert ack["backpressure"] is True
    assert ack["shed"] == {"m-1": 2}
    assert ack["retry_after_s"] == 0.5
    assert ack["accepted"] == {"m-1": 3}  # accepted then shed oldest-first


def test_stream_ingest_fault_isolates_one_machine():
    plane = make_plane()
    session = plane.session("p", "s1", "/dir")
    with inject(FaultRule("stream_ingest", match="s1:bad", times=None)):
        ack = plane.ingest(
            session, {"bad": frame(4), "good": frame(4)}
        )
    assert ack["errors"]["bad"]["status"] == 500
    assert "bad" not in ack["accepted"]
    assert ack["accepted"] == {"good": 4}  # the innocent's rows landed
    assert ack["scored"] == {"good": 4}
    assert session.stats()["machines"].get("bad") is None  # nothing buffered


def test_drain_closes_live_sessions_and_refuses_new_ones():
    plane = make_plane()
    s1 = plane.session("p", "s1", "/dir")
    s2 = plane.session("p", "s2", "/dir")
    s2.close("end")  # already closed: drain must not double-terminal it
    assert plane.drain() == 1
    assert s1.closed
    frames = parse_frames(list(s1.subscribe(heartbeat_s=0.01)))
    assert frames[-1][1] == "drain"
    assert frames[-1][2]["reason"] == "server draining"
    assert plane.drain() == 0  # idempotent
    with pytest.raises(PlaneSaturated):
        plane.session("p", "s3", "/dir")
    assert plane.stats()["draining"] is True


def test_install_ensure_reset_lifecycle(monkeypatch):
    reset_plane()
    assert get_plane() is None
    monkeypatch.setenv("GORDO_TPU_STREAM_ENABLED", "0")
    assert ensure_plane() is None  # disabled: no plane materializes
    monkeypatch.setenv("GORDO_TPU_STREAM_ENABLED", "1")
    plane = ensure_plane()
    assert plane is not None
    assert ensure_plane() is plane  # idempotent
    assert get_plane() is plane
    reset_plane()
    assert get_plane() is None


def test_attach_drift_feeds_streamed_windows():
    class Monitor:
        def __init__(self):
            self.seen = []

        def observe_scores(self, frames, scores):
            self.seen.append((sorted(frames), sorted(scores)))

    plane = make_plane()
    monitor = Monitor()
    plane.attach_drift(monitor)
    session = plane.session("p", "s1", "/dir")
    plane.ingest(session, {"m-1": frame(4)})
    assert monitor.seen == [(["m-1"], ["m-1"])]
