"""
Client tests against the in-process fake cluster (reference:
tests/gordo/client/test_client.py).
"""

import json

import pandas as pd
import pytest

from gordo_tpu.client import Client, ForwardPredictionsToDisk, PredictionResult
from gordo_tpu.client.io import (
    BadGordoRequest,
    HttpUnprocessableEntity,
    NotFound,
    ResourceGone,
    _handle_response,
)
from gordo_tpu.machine import Machine

START, END = "2020-03-01T00:00:00+00:00", "2020-03-01T06:00:00+00:00"


def test_get_machine_names(gordo_client):
    assert sorted(gordo_client.get_machine_names()) == ["machine-a", "machine-b"]


def test_get_revisions(gordo_client):
    revisions = gordo_client.get_revisions()
    assert revisions["latest"] in revisions["available-revisions"]


def test_get_metadata(gordo_client):
    metadata = gordo_client.get_metadata()
    assert set(metadata) == {"machine-a", "machine-b"}
    assert metadata["machine-a"]["dataset"]["tag_list"] == [
        {"name": "tag-1"},
        {"name": "tag-2"},
        {"name": "tag-3"},
    ] or metadata["machine-a"]["dataset"]["tag_list"] == ["tag-1", "tag-2", "tag-3"]
    assert metadata.get("no-such-target") is None


def test_get_available_machines(gordo_client):
    machines = gordo_client.get_available_machines()
    assert all(isinstance(m, Machine) for m in machines)
    with pytest.raises(NotFound):
        gordo_client.get_available_machines(["not-deployed"])
    only_a = gordo_client.get_available_machines(["machine-a"])
    assert [m.name for m in only_a] == ["machine-a"]


def test_download_model(gordo_client):
    models = gordo_client.download_model(["machine-a"])
    assert set(models) == {"machine-a"}
    # The downloaded model predicts out of the box.
    import numpy as np

    X = pd.DataFrame(
        np.random.rand(8, 3), columns=["tag-1", "tag-2", "tag-3"]
    )
    assert models["machine-a"].predict(X).shape[0] == 8


@pytest.mark.parametrize("use_parquet", [False, True])
def test_predict(ml_server, use_parquet):
    client = Client(project="client-project", session=ml_server, use_parquet=use_parquet)
    results = client.predict(START, END)
    assert {r.name for r in results} == {"machine-a", "machine-b"}
    for result in results:
        assert isinstance(result, PredictionResult)
        assert result.error_messages == []
        assert len(result.predictions) > 0
        top = {c[0] for c in result.predictions.columns}
        assert {"model-input", "model-output", "total-anomaly-scaled"} <= top


def test_predict_batched_equals_single(ml_server):
    whole = Client(project="client-project", session=ml_server).predict(
        START, END, targets=["machine-b"]
    )[0]
    batched = Client(
        project="client-project", session=ml_server, batch_size=7
    ).predict(START, END, targets=["machine-b"])[0]
    pd.testing.assert_frame_equal(whole.predictions, batched.predictions)


def test_predict_forwards(ml_server, tmp_path):
    destination = tmp_path / "sink"
    client = Client(
        project="client-project",
        session=ml_server,
        prediction_forwarder=ForwardPredictionsToDisk(str(destination)),
    )
    client.predict(START, END, targets=["machine-a"])
    saved = pd.read_parquet(destination / "machine-a.parquet")
    assert len(saved) > 0
    assert any(c.startswith("total-anomaly-scaled") for c in saved.columns)


def test_predict_records_data_fetch_failures(ml_server):
    # A tz-naive window is rejected by the dataset layer; the failure must
    # land in error_messages for that machine, not abort the whole replay.
    client = Client(project="client-project", session=ml_server)
    results = client.predict("2020-03-01 00:00:00", "2020-03-01 06:00:00")
    assert {r.name for r in results} == {"machine-a", "machine-b"}
    for result in results:
        assert result.predictions is None
        assert len(result.error_messages) == 1
        assert "Failed to fetch data" in result.error_messages[0]


def test_revision_pinning(gordo_client, ml_server):
    latest = gordo_client.get_revisions()["latest"]
    pinned = Client(project="client-project", session=ml_server, revision=latest)
    assert sorted(pinned.get_machine_names()) == ["machine-a", "machine-b"]
    gone = Client(project="client-project", session=ml_server, revision="123456")
    with pytest.raises(ResourceGone):
        gone.get_machine_names()


def test_handle_response_exceptions():
    class FakeResp:
        def __init__(self, status_code, payload=b"", headers=None):
            self.status_code = status_code
            self.content = payload
            self.headers = headers or {}
            self.text = payload.decode() if isinstance(payload, bytes) else payload

        def json(self):
            import json

            return json.loads(self.content)

    assert _handle_response(FakeResp(200, b"raw-bytes")) == b"raw-bytes"
    assert _handle_response(
        FakeResp(200, b'{"ok": true}', {"content-type": "application/json"})
    ) == {"ok": True}
    with pytest.raises(HttpUnprocessableEntity):
        _handle_response(FakeResp(422))
    with pytest.raises(ResourceGone):
        _handle_response(FakeResp(410))
    with pytest.raises(NotFound):
        _handle_response(FakeResp(404))
    with pytest.raises(BadGordoRequest):
        _handle_response(FakeResp(403))
    with pytest.raises(IOError):
        _handle_response(FakeResp(500))


def test_client_cli_registered():
    from gordo_tpu.cli.cli import gordo_tpu_cli

    assert "client" in gordo_tpu_cli.commands
    assert set(gordo_tpu_cli.commands["client"].commands) == {
        "metadata",
        "download-model",
        "predict",
    }


def test_fleet_anomaly_scores(ml_server):
    """One batch request scores every machine through the fused route."""
    client = Client(project="client-project", session=ml_server)
    results = client.fleet_anomaly_scores(START, END)
    assert set(results) == {"machine-a", "machine-b"}
    for name, result in results.items():
        assert not result.error_messages
        frame = result.predictions
        assert frame is not None and len(frame) > 0
        assert "total-anomaly-unscaled" in frame.columns
        assert (frame["total-anomaly-unscaled"] >= 0).all()


def test_fleet_anomaly_scores_all_failures_still_per_machine(ml_server):
    """A batch whose every machine fails server-side (HTTP 400 + errors
    body) must return per-machine error results, not raise."""
    client = Client(project="client-project", session=ml_server)
    machines = client.get_available_machines(["machine-a"])

    bad_payload = {"machine-a": {"not-a-tag": {"also-not-a-date": 1.0}}}
    # drive through the internal POST path the public method uses
    body = client._post_fleet_request(bad_payload)
    assert body.get("errors", {}).get("machine-a", {}).get("status") in (400, 422)


def test_fleet_anomaly_scores_maps_error_body_per_machine(ml_server):
    """The PUBLIC method must turn a 400-with-errors body into per-machine
    PredictionResults (not raise, not drop entries)."""

    class AllErrorsSession:
        """Delegates everything but fleet POSTs, which fail per-machine."""

        def __init__(self, inner):
            self.inner = inner

        def get(self, *args, **kwargs):
            return self.inner.get(*args, **kwargs)

        def post(self, url, **kwargs):
            if url.endswith("/prediction/fleet"):
                names = list(kwargs["json"]["X"])
                import requests

                resp = requests.models.Response()
                resp.status_code = 400
                resp.headers["content-type"] = "application/json"
                resp._content = json.dumps(
                    {
                        "data": {},
                        "errors": {
                            name: {"error": f"boom {name}", "status": 500}
                            for name in names
                        },
                    }
                ).encode()
                return resp
            return self.inner.post(url, **kwargs)

    client = Client(
        project="client-project", session=AllErrorsSession(ml_server)
    )
    results = client.fleet_anomaly_scores(START, END)
    assert set(results) == {"machine-a", "machine-b"}
    for name, result in results.items():
        assert result.predictions is None
        assert any(f"boom {name}" in msg for msg in result.error_messages)


def test_fleet_anomaly_scores_full_frames(ml_server):
    """full=True answers complete anomaly frames for detector machines —
    the series set the replay Job forwards (template: `predict --fleet`)."""
    client = Client(project="client-project", session=ml_server)
    results = client.fleet_anomaly_scores(START, END, full=True)
    assert set(results) == {"machine-a", "machine-b"}
    for result in results.values():
        assert not result.error_messages
        frame = result.predictions
        assert frame is not None and len(frame) > 0
        groups = (
            set(frame.columns.get_level_values(0))
            if hasattr(frame.columns, "get_level_values")
            else set(frame.columns)
        )
        # detector machines carry the full column groups; a plain model
        # would fall back to the lean pair
        if "tag-anomaly-unscaled" in groups:
            for needed in (
                "model-output",
                "tag-anomaly-scaled",
                "total-anomaly-scaled",
                "total-anomaly-unscaled",
                "anomaly-confidence",
            ):
                assert needed in groups, f"missing {needed}: {groups}"
        else:
            assert "total-anomaly-unscaled" in groups


def test_fleet_full_forwards_predictions(ml_server, tmp_path):
    """fleet_anomaly_scores honors prediction_forwarder like predict()
    does — the Influx/parquet sink of the `--fleet` replay path."""
    from gordo_tpu.client.forwarders import ForwardPredictionsToDisk

    client = Client(
        project="client-project",
        session=ml_server,
        prediction_forwarder=ForwardPredictionsToDisk(str(tmp_path)),
    )
    results = client.fleet_anomaly_scores(START, END, full=True)
    import os

    written = sorted(os.listdir(tmp_path))
    assert written == ["machine-a.parquet", "machine-b.parquet"]
    import pandas as pd

    frame = pd.read_parquet(tmp_path / "machine-a.parquet")
    assert len(frame) == len(results["machine-a"].predictions)
    assert any("total-anomaly-unscaled" in c for c in frame.columns)
