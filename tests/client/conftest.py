"""
Fake-cluster fixtures for client tests (SURVEY.md §3.5): a trained model
collection served by the in-process WSGI app, reached through a
requests.Session-compatible adapter injected into the Client — the
equivalent of the reference's `responses`-based ml_server fixture
(tests/conftest.py:333-422) without the `responses` package.
"""

import io
import os
from urllib.parse import urlsplit

import pytest
from werkzeug.test import Client as WerkzeugClient

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.server import build_app

PROJECT = "client-project"
REVISION = "1700000000000"

CONFIG = """
machines:
  - name: machine-a
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_hourglass
            compression_factor: 0.5
            encoding_layers: 1
            epochs: 1
  - name: machine-b
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [6]
            encoding_func: [tanh]
            decoding_dim: [6]
            decoding_func: [tanh]
            epochs: 1
"""


class _ResponseAdapter:
    """werkzeug TestResponse presented with the requests.Response surface
    the Client consumes."""

    def __init__(self, resp):
        self._resp = resp
        self.status_code = resp.status_code
        self.headers = resp.headers
        self.content = resp.get_data()

    def json(self):
        return self._resp.get_json()

    @property
    def text(self):
        return self.content.decode(errors="replace")


class WSGISession:
    """requests.Session look-alike that dispatches into a werkzeug test
    client, ignoring scheme/host (everything is the one in-process app)."""

    def __init__(self, wsgi_client: WerkzeugClient):
        self.client = wsgi_client

    def get(self, url, params=None, **kwargs):
        return _ResponseAdapter(
            self.client.get(urlsplit(url).path, query_string=params or {})
        )

    def post(self, url, params=None, json=None, files=None, **kwargs):
        path = urlsplit(url).path
        if files is not None:
            data = {
                name: (io.BytesIO(payload), f"{name}.parquet")
                for name, payload in files.items()
            }
            resp = self.client.post(path, query_string=params or {}, data=data)
        else:
            resp = self.client.post(path, query_string=params or {}, json=json)
        return _ResponseAdapter(resp)


@pytest.fixture(scope="session")
def client_collection_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("client-collection")
    for model, machine in local_build(CONFIG, project_name=PROJECT):
        serializer.dump(
            model,
            str(root / REVISION / machine.name),
            metadata=machine.to_dict(),
        )
    return str(root / REVISION)


@pytest.fixture
def ml_server(client_collection_dir, monkeypatch):
    """The deployed system: a WSGI session bound to the served collection."""
    monkeypatch.setenv("MODEL_COLLECTION_DIR", client_collection_dir)
    app = build_app(config={"EXPECTED_MODELS": ["machine-a", "machine-b"]})
    return WSGISession(WerkzeugClient(app))


@pytest.fixture
def gordo_client(ml_server):
    from gordo_tpu.client import Client

    return Client(project=PROJECT, session=ml_server)
