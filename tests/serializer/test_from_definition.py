import yaml
import pytest
from sklearn.decomposition import PCA
from sklearn.pipeline import FeatureUnion, Pipeline
from sklearn.preprocessing import MinMaxScaler

from gordo_tpu import serializer
from gordo_tpu.models import EarlyStopping, JaxAutoEncoder, Sequential
from gordo_tpu.models.anomaly import DiffBasedAnomalyDetector


def test_nested_pipeline_from_yaml():
    definition = yaml.safe_load(
        """
        sklearn.pipeline.Pipeline:
            steps:
                - sklearn.decomposition.PCA:
                    n_components: 2
                - sklearn.pipeline.FeatureUnion:
                    - sklearn.decomposition.PCA:
                        n_components: 3
                    - sklearn.pipeline.Pipeline:
                        - sklearn.preprocessing.MinMaxScaler
                        - sklearn.decomposition.TruncatedSVD:
                            n_components: 2
                - sklearn.preprocessing.MinMaxScaler
        """
    )
    pipe = serializer.from_definition(definition)
    assert isinstance(pipe, Pipeline)
    assert isinstance(pipe.steps[0][1], PCA)
    assert isinstance(pipe.steps[1][1], FeatureUnion)
    assert isinstance(pipe.steps[2][1], MinMaxScaler)
    assert pipe.steps[0][0] == "step_0"


def test_bare_string_step():
    scaler = serializer.from_definition("sklearn.preprocessing.MinMaxScaler")
    assert isinstance(scaler, MinMaxScaler)


def test_tuple_coercion():
    scaler = serializer.from_definition(
        {"sklearn.preprocessing.MinMaxScaler": {"feature_range": [-1, 1]}}
    )
    assert scaler.feature_range == (-1, 1)


def test_from_definition_hook():
    model = serializer.from_definition(
        {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "compression_factor": 0.25,
            }
        }
    )
    assert isinstance(model, JaxAutoEncoder)
    assert model.kind == "feedforward_hourglass"
    assert model.kwargs["compression_factor"] == 0.25


def test_string_param_resolves_to_estimator_instance():
    det = serializer.from_definition(
        {
            "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": "sklearn.linear_model.LinearRegression"
            }
        }
    )
    assert isinstance(det, DiffBasedAnomalyDetector)
    assert type(det.base_estimator).__name__ == "LinearRegression"


def test_string_param_resolves_to_function():
    transformer = serializer.from_definition(
        {
            "sklearn.preprocessing.FunctionTransformer": {
                "func": "gordo_tpu.models.transformer_funcs.general.multiply_by",
                "kw_args": {"factor": 2},
            }
        }
    )
    import numpy as np

    out = transformer.fit_transform(np.array([[1.0], [2.0]]))
    assert out.tolist() == [[2.0], [4.0]]


def test_reference_compat_paths_rewrite():
    model = serializer.from_definition(
        {"gordo.machine.model.models.KerasAutoEncoder": {"kind": "feedforward_model"}}
    )
    assert isinstance(model, JaxAutoEncoder)


def test_sequential_layers_container():
    seq = serializer.from_definition(
        yaml.safe_load(
            """
            tensorflow.keras.models.Sequential:
                layers:
                    - tensorflow.keras.layers.Dense:
                        units: 4
                    - tensorflow.keras.layers.Dense:
                        units: 2
            """
        )
    )
    assert isinstance(seq, Sequential)
    assert [layer.units for layer in seq.layers] == [4, 2]


def test_build_callbacks():
    callbacks = serializer.build_callbacks(
        [
            {
                "tensorflow.keras.callbacks.EarlyStopping": {
                    "monitor": "val_loss",
                    "patience": 5,
                }
            }
        ]
    )
    assert isinstance(callbacks[0], EarlyStopping)
    assert callbacks[0].patience == 5


def test_unknown_path_raises():
    with pytest.raises(ImportError):
        serializer.from_definition({"no.such.module.Klass": {}})
