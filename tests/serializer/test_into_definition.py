import yaml
from sklearn.decomposition import PCA
from sklearn.pipeline import Pipeline

from gordo_tpu import serializer
from gordo_tpu.models import JaxAutoEncoder
from gordo_tpu.models.anomaly import DiffBasedAnomalyDetector


def test_pipeline_round_trip():
    definition = yaml.safe_load(
        """
        sklearn.pipeline.Pipeline:
            steps:
                - sklearn.preprocessing.MinMaxScaler
                - sklearn.decomposition.PCA:
                    n_components: 2
        """
    )
    pipe = serializer.from_definition(definition)
    out = serializer.into_definition(pipe)
    rebuilt = serializer.from_definition(out)
    assert isinstance(rebuilt, Pipeline)
    assert isinstance(rebuilt.steps[1][1], PCA)
    assert rebuilt.steps[1][1].n_components == 2


def test_estimator_hook_round_trip():
    model = JaxAutoEncoder(kind="feedforward_symmetric", dims=(4, 2), epochs=3)
    out = serializer.into_definition(model)
    key = "gordo_tpu.models.estimators.JaxAutoEncoder"
    assert key in out
    assert out[key]["kind"] == "feedforward_symmetric"
    assert out[key]["epochs"] == 3
    rebuilt = serializer.from_definition(out)
    assert isinstance(rebuilt, JaxAutoEncoder)
    assert rebuilt.kwargs["dims"] == (4, 2)


def test_anomaly_detector_not_flattened_by_delegation():
    det = DiffBasedAnomalyDetector(
        base_estimator=JaxAutoEncoder(kind="feedforward_hourglass")
    )
    out = serializer.into_definition(det)
    key = next(iter(out))
    assert key.endswith("DiffBasedAnomalyDetector")
    inner = out[key]["base_estimator"]
    assert next(iter(inner)).endswith("JaxAutoEncoder")
    rebuilt = serializer.from_definition(out)
    assert isinstance(rebuilt, DiffBasedAnomalyDetector)
    assert isinstance(rebuilt.base_estimator, JaxAutoEncoder)


def test_function_reference_decomposes_to_path():
    from sklearn.preprocessing import FunctionTransformer

    from gordo_tpu.models.transformer_funcs.general import multiply_by

    ft = FunctionTransformer(func=multiply_by, kw_args={"factor": 3})
    out = serializer.into_definition(ft)
    params = out["sklearn.preprocessing._function_transformer.FunctionTransformer"]
    assert params["func"] == "gordo_tpu.models.transformer_funcs.general.multiply_by"
