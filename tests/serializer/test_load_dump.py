import numpy as np
import pytest
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import MinMaxScaler

from gordo_tpu import serializer
from gordo_tpu.models import JaxAutoEncoder


def test_dumps_loads_bytes():
    scaler = MinMaxScaler(feature_range=(0, 2))
    restored = serializer.loads(serializer.dumps(scaler))
    assert restored.feature_range == (0, 2)


def test_dump_load_directory(tmp_path):
    X = np.random.RandomState(0).rand(64, 3).astype(np.float32)
    pipe = Pipeline(
        [
            ("scale", MinMaxScaler()),
            ("model", JaxAutoEncoder(kind="feedforward_hourglass", epochs=1)),
        ]
    )
    pipe.fit(X, X)
    expected = pipe.predict(X)

    serializer.dump(pipe, tmp_path, metadata={"machine": "m1"}, info={"extra": 1})
    restored = serializer.load(tmp_path)
    np.testing.assert_allclose(restored.predict(X), expected, rtol=1e-5)

    metadata = serializer.load_metadata(tmp_path)
    assert metadata["machine"] == "m1"
    info = serializer.load_info(tmp_path)
    assert "checksum" in info and info["extra"] == 1


def test_load_metadata_parent_fallback(tmp_path):
    sub = tmp_path / "sub"
    sub.mkdir()
    serializer.dump(MinMaxScaler(), tmp_path, metadata={"at": "parent"})
    assert serializer.load_metadata(str(sub))["at"] == "parent"


def test_load_metadata_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        serializer.load_metadata(str(tmp_path / "nothing"))
