import numpy as np
import pytest
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import MinMaxScaler

from gordo_tpu import serializer
from gordo_tpu.models import JaxAutoEncoder


def test_dumps_loads_bytes():
    scaler = MinMaxScaler(feature_range=(0, 2))
    restored = serializer.loads(serializer.dumps(scaler))
    assert restored.feature_range == (0, 2)


def test_dump_load_directory(tmp_path):
    X = np.random.RandomState(0).rand(64, 3).astype(np.float32)
    pipe = Pipeline(
        [
            ("scale", MinMaxScaler()),
            ("model", JaxAutoEncoder(kind="feedforward_hourglass", epochs=1)),
        ]
    )
    pipe.fit(X, X)
    expected = pipe.predict(X)

    serializer.dump(pipe, tmp_path, metadata={"machine": "m1"}, info={"extra": 1})
    restored = serializer.load(tmp_path)
    np.testing.assert_allclose(restored.predict(X), expected, rtol=1e-5)

    metadata = serializer.load_metadata(tmp_path)
    assert metadata["machine"] == "m1"
    info = serializer.load_info(tmp_path)
    assert "checksum" in info and info["extra"] == 1


def test_load_metadata_parent_fallback(tmp_path):
    sub = tmp_path / "sub"
    sub.mkdir()
    serializer.dump(MinMaxScaler(), tmp_path, metadata={"at": "parent"})
    assert serializer.load_metadata(str(sub))["at"] == "parent"


def test_load_metadata_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        serializer.load_metadata(str(tmp_path / "nothing"))


def test_dump_atomic_replaces_prior_artifact(tmp_path):
    dest = tmp_path / "model-dir"
    serializer.dump_atomic(MinMaxScaler(feature_range=(0, 2)), str(dest))
    serializer.dump_atomic(MinMaxScaler(feature_range=(0, 5)), str(dest))
    assert serializer.load(str(dest)).feature_range == (0, 5)
    # no staging dirs left behind
    assert [e for e in tmp_path.iterdir() if e.name.startswith(".")] == []


def test_dump_atomic_preserves_unrelated_files_in_mixed_dir(tmp_path):
    """The legacy dump merged into an existing dir; dump_atomic must never
    rmtree a dest holding other content (`gordo build config.yaml .` would
    otherwise delete the user's working directory)."""
    dest = tmp_path / "workdir"
    dest.mkdir()
    (dest / "notes.txt").write_text("keep me")
    serializer.dump_atomic(MinMaxScaler(), str(dest), metadata={"m": 1})
    assert (dest / "notes.txt").read_text() == "keep me"
    assert serializer.load_metadata(str(dest))["m"] == 1
    assert isinstance(serializer.load(str(dest)), MinMaxScaler)
    assert [e for e in tmp_path.iterdir() if e.name.startswith(".")] == []


def test_dump_atomic_dir_mode_honors_umask(tmp_path):
    """mkdtemp's private 0700 must not leak onto artifact dirs — the model
    server often runs as a different UID on the shared volume."""
    import os
    import stat

    dest = tmp_path / "served-model"
    serializer.dump_atomic(MinMaxScaler(), str(dest))
    umask = os.umask(0)
    os.umask(umask)
    expected = 0o777 & ~umask
    assert stat.S_IMODE(os.stat(dest).st_mode) == expected
