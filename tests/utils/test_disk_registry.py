import pytest

from gordo_tpu.utils import disk_registry


def test_write_get_delete_roundtrip(tmp_path):
    registry = tmp_path / "registry"
    disk_registry.write_key(registry, "abc123", "/some/path")
    assert disk_registry.get_value(registry, "abc123") == "/some/path"
    assert disk_registry.delete_value(registry, "abc123") is True
    assert disk_registry.get_value(registry, "abc123") is None
    assert disk_registry.delete_value(registry, "abc123") is False


def test_get_missing_registry_dir(tmp_path):
    assert disk_registry.get_value(tmp_path / "nope", "key") is None


def test_overwrite_key(tmp_path):
    disk_registry.write_key(tmp_path, "k", "v1")
    disk_registry.write_key(tmp_path, "k", "v2")
    assert disk_registry.get_value(tmp_path, "k") == "v2"


@pytest.mark.parametrize("bad_key", ["../escape", "a/b", "", "a b"])
def test_invalid_keys_rejected(tmp_path, bad_key):
    with pytest.raises(ValueError):
        disk_registry.write_key(tmp_path, bad_key, "v")
