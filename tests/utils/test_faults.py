"""Unit coverage for the deterministic fault-injection registry and the
retry helper it exercises."""

import pytest

from gordo_tpu.utils import faults
from gordo_tpu.utils.faults import FaultInjected, FaultRule, fault_point, inject
from gordo_tpu.utils.retry import retry_call

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def test_no_rules_is_a_noop():
    fault_point("data_fetch", "anything")  # must not raise


def test_rule_fires_once_then_passes():
    with inject(FaultRule("data_fetch", times=1)):
        with pytest.raises(FaultInjected):
            fault_point("data_fetch", "m-1")
        fault_point("data_fetch", "m-1")  # times exhausted


def test_rule_scoped_to_site_and_key_glob():
    with inject(FaultRule("data_fetch", match="poison-*", times=None)):
        fault_point("data_fetch", "healthy-1")
        fault_point("device_program", "poison-1")  # wrong site
        with pytest.raises(FaultInjected):
            fault_point("data_fetch", "poison-1")
        with pytest.raises(FaultInjected):
            fault_point("data_fetch", "poison-2")  # unlimited times


def test_after_skips_first_n_matching_calls():
    rule = FaultRule("dump_artifact", after=2, times=1)
    with inject(rule):
        fault_point("dump_artifact", "m-1")
        fault_point("dump_artifact", "m-2")
        with pytest.raises(FaultInjected):
            fault_point("dump_artifact", "m-3")
        fault_point("dump_artifact", "m-4")
    assert rule.seen == 4 and rule.fired == 1


def test_device_program_default_exc_is_resource_exhausted():
    from gordo_tpu.parallel.fleet import is_device_error

    with inject(FaultRule("device_program")):
        with pytest.raises(faults.InjectedDeviceError) as exc_info:
            fault_point("device_program", "m-1")
    assert "RESOURCE_EXHAUSTED" in str(exc_info.value)
    assert is_device_error(exc_info.value)


def test_process_kill_site_raises_system_exit_by_default():
    with inject(FaultRule("process_kill_after_n_machines", after=1)):
        fault_point("process_kill_after_n_machines", "m-1")
        with pytest.raises(SystemExit):
            fault_point("process_kill_after_n_machines", "m-2")


def test_nested_scopes_unwind_independently():
    outer = FaultRule("data_fetch", match="outer-*", times=None)
    inner = FaultRule("data_fetch", match="inner-*", times=None)
    with inject(outer):
        with inject(inner):
            with pytest.raises(FaultInjected):
                fault_point("data_fetch", "inner-1")
        fault_point("data_fetch", "inner-1")  # inner scope gone
        with pytest.raises(FaultInjected):
            fault_point("data_fetch", "outer-1")
    fault_point("data_fetch", "outer-1")


def test_nested_equal_rules_unwind_by_identity():
    """Exiting an inner scope must remove ITS rule object, not an equal
    outer-scope rule (dataclass __eq__ ignores the counters)."""
    outer = FaultRule("data_fetch", times=1)
    inner = FaultRule("data_fetch", times=1)
    assert outer == inner
    with inject(outer):
        with inject(inner):
            with pytest.raises(FaultInjected):
                fault_point("data_fetch", "m")  # consumes the OUTER budget
        # outer scope still governed by its own (now spent) rule; the
        # inner rule's untouched budget must be gone with its scope
        assert inner.fired == 0 and outer.fired == 1
        fault_point("data_fetch", "m")  # outer budget spent: passes
    fault_point("data_fetch", "m")


def test_env_rules_parse_and_fire(monkeypatch):
    monkeypatch.setenv(
        faults.ENV_VAR, "dump_artifact:m-*:after=1:exc=SystemExit"
    )
    fault_point("dump_artifact", "m-a")
    with pytest.raises(SystemExit):
        fault_point("dump_artifact", "m-b")


def test_env_parse_rejects_unknown_site_and_option():
    with pytest.raises(ValueError):
        faults.parse_rules("not_a_site")
    with pytest.raises(ValueError):
        faults.parse_rules("data_fetch:*:bogus=1")
    with pytest.raises(ValueError):
        faults.parse_rules("data_fetch:*:exc=NotAnError")


def test_parse_multiple_rules():
    rules = faults.parse_rules(
        "data_fetch:m-*:times=2; device_program:*:times=inf:kill"
    )
    assert [r.site for r in rules] == ["data_fetch", "device_program"]
    assert rules[0].times == 2 and rules[0].match == "m-*"
    assert rules[1].times is None and rules[1].kill


# -- retry_call ----------------------------------------------------------


def test_retry_call_retries_then_succeeds():
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        return "ok"

    retried = []
    assert (
        retry_call(
            flaky,
            attempts=3,
            backoff=0,
            on_retry=lambda a, e: retried.append(a),
        )
        == "ok"
    )
    assert retried == [1, 2]


def test_retry_call_exhausts_and_reraises():
    def always_fails():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always_fails, attempts=2, backoff=0)


def test_retry_call_no_retry_types_raise_immediately():
    calls = []

    def config_error():
        calls.append(1)
        raise ValueError("bad config")

    with pytest.raises(ValueError):
        retry_call(
            config_error, attempts=5, backoff=0, no_retry=(ValueError,)
        )
    assert len(calls) == 1


def test_retry_call_never_swallows_shutdown_signals():
    def interrupted():
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        retry_call(
            interrupted, attempts=5, backoff=0, retry_on=(BaseException,)
        )


def test_retry_call_deadline_stops_retrying():
    calls = []

    def slow_failure():
        calls.append(1)
        raise OSError("still down")

    with pytest.raises(OSError):
        # next sleep (10s) would cross the 0.01s deadline → immediate raise
        retry_call(slow_failure, attempts=10, backoff=10, deadline=0.01)
    assert len(calls) == 1
