import pytest

import gordo_tpu
from gordo_tpu.utils.version import (
    GordoPR,
    GordoRelease,
    GordoSHA,
    GordoSpecial,
    Special,
    parse_version,
)


@pytest.mark.parametrize(
    "tag,expected",
    [
        ("1.2.3", GordoRelease(1, 2, 3)),
        ("10.0.1-rc1", GordoRelease(10, 0, 1, "-rc1")),
        ("latest", GordoSpecial(Special.LATEST)),
        ("stable", GordoSpecial(Special.STABLE)),
        ("pr-123", GordoPR(123)),
        ("abc1234", GordoSHA("abc1234")),
    ],
)
def test_parse_docker_tag(tag, expected):
    parsed = parse_version(tag)
    assert parsed == expected
    assert parsed.get_version() == tag


def test_unparseable_tag():
    with pytest.raises(ValueError):
        parse_version("Not A Tag!")


def test_package_version_parses():
    major, minor, patch, suffix = gordo_tpu.parse_version(gordo_tpu.__version__)
    assert (major, minor) == (
        gordo_tpu.MAJOR_VERSION,
        gordo_tpu.MINOR_VERSION,
    )


def test_unstable_version():
    assert gordo_tpu.parse_version("1.2.3.dev4")[3] == "dev4"
    assert not gordo_tpu.version_is_stable("1.2.3.dev4")
