import os

from gordo_tpu.utils.profiling import annotate, maybe_trace


def test_maybe_trace_noop_without_env(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PROFILE_DIR", raising=False)
    with maybe_trace("x"):
        pass
    with annotate("y"):
        pass


def test_maybe_trace_writes_trace(monkeypatch, tmp_path):
    monkeypatch.setenv("GORDO_TPU_PROFILE_DIR", str(tmp_path))
    import jax.numpy as jnp

    with maybe_trace("unit"):
        with annotate("region"):
            (jnp.ones((4, 4)) @ jnp.ones((4, 4))).block_until_ready()
    # the profiler writes its plugin dir layout under <dir>/unit
    assert (tmp_path / "unit").exists()
    assert any((tmp_path / "unit").rglob("*")), "no trace output written"
