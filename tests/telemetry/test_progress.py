"""BuildProgress heartbeat + build-status rendering coverage."""

import json
import os

import pytest

from gordo_tpu.telemetry import (
    BuildProgress,
    eta_seconds,
    load_status,
    render_status,
)
from gordo_tpu.telemetry.progress import BUILD_STATUS_FILE

pytestmark = pytest.mark.observability


def test_heartbeat_writes_atomic_document(tmp_path):
    progress = BuildProgress(
        str(tmp_path), project="p", total=4, heartbeat_seconds=0
    )
    progress.phase("plan")
    progress.machine_completed("m-1")
    doc = load_status(str(tmp_path))
    assert doc["project"] == "p"
    assert doc["state"] == "running"
    assert doc["phase"] == "plan"
    assert doc["machines"]["total"] == 4
    assert doc["machines"]["completed"] == 1
    # no staging leftovers from the atomic replace
    assert sorted(os.listdir(tmp_path)) == [BUILD_STATUS_FILE]


def test_default_heartbeat_is_throttled(tmp_path, monkeypatch):
    """The env-default throttle bounds status writes to ~2/s at ANY
    fleet size — per-completion writes would tax small builds for
    durability the journal already provides exactly."""
    monkeypatch.delenv("GORDO_TPU_TELEMETRY_HEARTBEAT", raising=False)
    progress = BuildProgress(str(tmp_path), project="p", total=100)
    assert progress.heartbeat_seconds == 0.5
    progress.phase("dump")  # forced
    for i in range(50):
        progress.machine_completed(f"m-{i}")  # throttled away
    assert load_status(str(tmp_path))["machines"]["completed"] == 0
    monkeypatch.setenv("GORDO_TPU_TELEMETRY_HEARTBEAT", "0")
    assert BuildProgress(str(tmp_path), total=1).heartbeat_seconds == 0.0


def test_phase_table_tracks_running_and_done(tmp_path):
    seconds = {}
    progress = BuildProgress(
        str(tmp_path), project="p", total=2, phase_seconds=seconds
    )
    progress.phase("plan")
    seconds["plan"] = 0.5
    progress.phase("dump")
    doc = load_status(str(tmp_path))
    assert doc["phases"]["plan"] == {"seconds": 0.5, "status": "done"}
    assert doc["phases"]["dump"]["status"] == "running"


def test_finish_states(tmp_path):
    progress = BuildProgress(str(tmp_path), project="p", total=1)
    progress.machine_completed("m")
    progress.finish("complete")
    doc = load_status(str(tmp_path))
    assert doc["state"] == "complete" and doc["phase"] is None

    progress2 = BuildProgress(str(tmp_path), project="p", total=1)
    progress2.machine_failed("m")
    progress2.finish("failed")
    assert load_status(str(tmp_path))["state"] == "failed"


def test_heartbeat_throttle_skips_midstream_writes(tmp_path):
    progress = BuildProgress(
        str(tmp_path), project="p", total=10, heartbeat_seconds=3600.0
    )
    progress.phase("dump")  # forced write
    first = (tmp_path / BUILD_STATUS_FILE).read_text()
    progress.machine_completed("m-1")  # throttled away
    assert (tmp_path / BUILD_STATUS_FILE).read_text() == first
    progress.finish("complete")  # forced
    assert load_status(str(tmp_path))["machines"]["completed"] == 1


def test_concurrent_completions_never_tear_the_document(tmp_path):
    """The dump pool reports completions from 8 threads with the
    fault-drill heartbeat (0 = write every completion); the shared
    pid-named tmp path must be serialized or a sibling's open() truncates
    an in-flight write and renames torn JSON into the status file."""
    import concurrent.futures

    progress = BuildProgress(
        str(tmp_path), project="p", total=64, heartbeat_seconds=0
    )
    pool = concurrent.futures.ThreadPoolExecutor(8)
    try:
        list(pool.map(progress.machine_completed, [f"m-{i}" for i in range(64)]))
    finally:
        pool.shutdown(wait=True)
    doc = load_status(str(tmp_path))
    assert doc is not None, "torn/unparseable build_status.json"
    assert doc["machines"]["completed"] == 64


def test_no_output_dir_counts_without_writing():
    progress = BuildProgress(None, project="p", total=3)
    progress.phase("plan")
    progress.machine_completed("m")
    assert progress.completed == 1
    assert progress.document()["machines"]["completed"] == 1


def test_unreadable_or_missing_status_is_none(tmp_path):
    assert load_status(str(tmp_path)) is None
    (tmp_path / BUILD_STATUS_FILE).write_text("{torn")
    assert load_status(str(tmp_path)) is None
    (tmp_path / BUILD_STATUS_FILE).write_text(json.dumps([1, 2]))
    assert load_status(str(tmp_path)) is None


def test_eta_from_completed_machine_rate():
    doc = {
        "state": "running",
        "elapsed_sec": 100.0,
        "machines": {"total": 10, "completed": 4, "resumed": 1, "failed": 1},
    }
    # 4 remaining at 25s/machine
    assert eta_seconds(doc) == pytest.approx(100.0)
    doc["machines"]["completed"] = 0
    assert eta_seconds(doc) is None
    doc["machines"].update(completed=8, resumed=1, failed=1)
    assert eta_seconds(doc) == 0.0
    assert eta_seconds({**doc, "state": "complete"}) is None


def test_render_status_covers_counts_phases_and_eta(tmp_path):
    seconds = {"plan": 0.25, "dump": 1.5}
    progress = BuildProgress(
        str(tmp_path),
        project="render-p",
        total=8,
        phase_seconds=seconds,
        heartbeat_seconds=0,
    )
    progress.phase("plan")
    progress.phase("dump")
    for i in range(3):
        progress.machine_completed(f"m-{i}")
    progress.machine_failed("m-x")
    text = render_status(load_status(str(tmp_path)))
    assert "render-p" in text
    assert "running (phase: dump)" in text
    assert "3/8 done" in text and "1 failed" in text
    assert "ETA" in text
    assert "plan" in text and "1.50" in text
    # finished builds render without an ETA
    progress.finish("complete")
    assert "ETA" not in render_status(load_status(str(tmp_path)))
