"""
Fleet-scale observability suite (PR 16): the sharded health-ledger
layout (adaptive resharding, dirty-shard flushing, monolithic-snapshot
migration, crash-torn dual-layout merge), the rollup manifest's
counting-open read contract, manifest-window trace skipping, the
bounded fleet-status surface with explicit machine selection/paging,
and the O(unhealthy) breaker-board summary at 5k tracked members.

Corpora come from ``benchmarks/fleetgen.py`` — the same deterministic
generator the ``bench_scale.py`` harness drives at 10k members; here
the fleets are sized to stay inside the tier-1 budget while still
crossing every scale threshold (reshard trigger, inline cap).
"""

import json
import os
import sys
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[2]
if str(REPO_ROOT / "benchmarks") not in sys.path:
    sys.path.insert(0, str(REPO_ROOT / "benchmarks"))

import fleetgen  # noqa: E402  (benchmarks/fleetgen.py, path-injected above)

from gordo_tpu.telemetry.aggregate import (  # noqa: E402
    ROLLUP_DIR,
    ROLLUP_MANIFEST_FILE,
    RollupStore,
    sink_window_index,
)
from gordo_tpu.telemetry.fleet_health import (  # noqa: E402
    FLEET_HEALTH_FILE,
    FLEET_HEALTH_SHARD_DIR,
    FLEET_HEALTH_SUMMARY_FILE,
    FleetHealthLedger,
    fleet_status_document,
    health_snapshot_units,
    ledger_for,
    load_health,
    load_merged_health,
    reset_ledgers,
)
from gordo_tpu.telemetry.trace_analysis import iter_trace_files  # noqa: E402

pytestmark = [pytest.mark.scale, pytest.mark.observability]


@pytest.fixture(autouse=True)
def _fresh_registry():
    reset_ledgers()
    yield
    reset_ledgers()


def make_ledger(tmp_path, **kwargs) -> FleetHealthLedger:
    kwargs.setdefault("heartbeat_seconds", 0.0)
    return FleetHealthLedger(directory=str(tmp_path), **kwargs)


def shard_files(tmp_path):
    shard_dir = tmp_path / FLEET_HEALTH_SHARD_DIR
    if not shard_dir.is_dir():
        return []
    return sorted(
        entry
        for entry in os.listdir(shard_dir)
        if entry.startswith("shard-") and entry.endswith(".json")
    )


# -- shard layout -------------------------------------------------------------


def test_small_fleet_keeps_monolithic_snapshot(tmp_path):
    ledger = make_ledger(tmp_path)
    fleetgen.populate_ledger(ledger, fleetgen.machine_names(40))
    assert (tmp_path / FLEET_HEALTH_FILE).exists()
    assert not (tmp_path / FLEET_HEALTH_SHARD_DIR).exists()
    assert len(load_health(str(tmp_path))["machines"]) == 40


def test_adaptive_reshard_partitions_without_overlap(tmp_path):
    """Past the per-shard target the layout splits: every machine lands
    in exactly one shard file, the monolithic spelling is retired, and
    ``summary.json`` carries the bounded fold."""
    names = fleetgen.machine_names(1200)
    # a long heartbeat keeps throttled per-record writes out of the
    # test's way — only state transitions and the final flush persist
    ledger = make_ledger(tmp_path, heartbeat_seconds=3600.0)
    fleetgen.populate_ledger(ledger, names)

    # ceil(1200 / 512) = 3 -> next power of two = 4 shards
    files = shard_files(tmp_path)
    assert files == [f"shard-{i:03d}of004.json" for i in range(4)]
    assert not (tmp_path / FLEET_HEALTH_FILE).exists()

    seen = []
    for entry in files:
        doc = json.loads((tmp_path / FLEET_HEALTH_SHARD_DIR / entry).read_text())
        assert doc["kind"] == "fleet-health-shard"
        assert doc["shards"] == 4
        seen.extend(doc["machines"])
    assert len(seen) == len(set(seen)) == 1200  # a partition, not a cover
    assert sorted(seen) == names

    summary_doc = json.loads(
        (tmp_path / FLEET_HEALTH_SHARD_DIR / FLEET_HEALTH_SUMMARY_FILE).read_text()
    )
    assert summary_doc["machines_total"] == 1200
    assert summary_doc["summary"]["machines"] == 1200
    assert summary_doc["offenders"]  # drift/quarantine sprinkled by fleetgen


def test_pinned_shard_count_from_env(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_HEALTH_SHARDS", "8")
    ledger = make_ledger(tmp_path)
    fleetgen.populate_ledger(ledger, fleetgen.machine_names(64))
    files = shard_files(tmp_path)
    assert files and all(entry.endswith("of008.json") for entry in files)


def test_dirty_flush_rewrites_only_the_owning_shard(tmp_path):
    """One machine's update costs one bounded shard file (plus the
    summary) — never a rewrite of the whole fleet. This is the contract
    the BENCH_SCALE ``ledger_dirty_flush_shard_ratio`` gate holds at
    10k members."""
    names = fleetgen.machine_names(1200)
    ledger = make_ledger(tmp_path, heartbeat_seconds=3600.0)
    fleetgen.populate_ledger(ledger, names)

    shard_dir = tmp_path / FLEET_HEALTH_SHARD_DIR
    before = {
        entry: (shard_dir / entry).read_bytes()
        for entry in os.listdir(shard_dir)
    }
    ledger.record_scores(names[0], rows=5, residual_mean=0.5, write=False)
    ledger.flush()
    after = {
        entry: (shard_dir / entry).read_bytes()
        for entry in os.listdir(shard_dir)
    }

    assert set(before) == set(after)
    changed = {entry for entry in after if after[entry] != before[entry]}
    owning = f"shard-{ledger._shard_of(names[0]):03d}of004.json"
    assert changed == {owning, FLEET_HEALTH_SUMMARY_FILE}


# -- migration ----------------------------------------------------------------


def test_monolithic_snapshot_migrates_and_is_never_reread(tmp_path, monkeypatch):
    """The legacy monolithic ``fleet_health.json`` is read ONCE at
    restore; the first sharded flush reshards it and retires the file.
    A poisoned legacy file planted afterwards must be invisible to
    every reader — the shard layout is authoritative."""
    names = fleetgen.machine_names(1200)
    monkeypatch.setenv("GORDO_TPU_HEALTH_SHARDS", "1")  # force old layout
    legacy = make_ledger(tmp_path, heartbeat_seconds=3600.0)
    fleetgen.populate_ledger(legacy, names)
    assert (tmp_path / FLEET_HEALTH_FILE).exists()
    assert not shard_files(tmp_path)
    monkeypatch.delenv("GORDO_TPU_HEALTH_SHARDS")
    reset_ledgers()

    ledger = ledger_for(str(tmp_path))
    assert ledger.machine_count() == 1200  # the one-time legacy read
    ledger.flush()
    assert len(shard_files(tmp_path)) == 4
    assert not (tmp_path / FLEET_HEALTH_FILE).exists()  # retired

    (tmp_path / FLEET_HEALTH_FILE).write_text(
        json.dumps(
            {
                "version": 1,
                "machines": {"poison-machine": {}},
                "summary": {"machines": 1},
            }
        )
    )
    reset_ledgers()
    restored = ledger_for(str(tmp_path))
    assert restored.machine_count() == 1200
    assert restored.machine("poison-machine") is None
    assert "poison-machine" not in load_health(str(tmp_path))["machines"]


def test_crash_torn_dual_layout_never_double_counts(tmp_path, monkeypatch):
    """A worker that crashed between the shard flush and the legacy
    unlink leaves BOTH layouts under one stem; it must count once, the
    shard directory winning."""
    names = fleetgen.machine_names(8)
    monkeypatch.setenv("GORDO_TPU_HEALTH_SHARDS", "4")
    ledger = make_ledger(tmp_path)
    for name in names:
        ledger.record_request(name)
    ledger.flush()
    monkeypatch.delenv("GORDO_TPU_HEALTH_SHARDS")

    # resurrect the legacy spelling with inflated counts
    stale = {
        "version": 1,
        "updated_at": "2099-01-01T00:00:00+00:00",
        "machines": {
            name: {"serving": {"requests": 100, "errors": 100, "rows": 0}}
            for name in names
        },
        "summary": {"machines": 8},
    }
    (tmp_path / FLEET_HEALTH_FILE).write_text(json.dumps(stale))

    units = health_snapshot_units(str(tmp_path))
    assert [unit["kind"] for unit in units] == ["shards"]

    reset_ledgers()
    merged = load_merged_health(str(tmp_path))
    assert merged["summary"]["machines"] == 8
    for name in names:
        assert merged["machines"][name]["serving"]["requests"] == 1


# -- bounded fleet-status -----------------------------------------------------


def test_fleet_status_bounds_past_inline_threshold(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_FLEET_STATUS_MAX_MACHINES", "50")
    names = fleetgen.machine_names(120)
    ledger = ledger_for(str(tmp_path))
    fleetgen.populate_ledger(ledger, names)

    doc = fleet_status_document(str(tmp_path))
    health = doc["health"]
    assert health["machines"] is None
    assert health["machines_truncated"] is True
    assert health["machines_total"] == 120
    assert health["summary"]["machines"] == 120
    offenders = health["top_offenders"]
    assert 0 < len(offenders) <= 10
    assert all(o["state"] != "healthy" for o in offenders)


def test_fleet_status_explicit_selection_and_paging(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_FLEET_STATUS_MAX_MACHINES", "50")
    names = fleetgen.machine_names(120)
    ledger = ledger_for(str(tmp_path))
    fleetgen.populate_ledger(ledger, names)

    paged = fleet_status_document(str(tmp_path), machines="all", limit=10)
    assert sorted(paged["health"]["machines"]) == names[:10]
    assert paged["health"]["machines_truncated"] is True
    assert paged["health"]["machines_offset"] == 0

    tail = fleet_status_document(
        str(tmp_path), machines="all", limit=10, offset=115
    )
    assert sorted(tail["health"]["machines"]) == names[115:]
    assert tail["health"]["machines_truncated"] is False

    # state filter: fleetgen quarantines every 503rd member (index 0)
    quarantined = fleet_status_document(
        str(tmp_path), machines="quarantined"
    )
    assert list(quarantined["health"]["machines"]) == [names[0]]

    picked = fleet_status_document(
        str(tmp_path), machines=f"{names[7]},{names[9]},no-such-machine"
    )
    assert sorted(picked["health"]["machines"]) == [names[7], names[9]]

    summary_only = fleet_status_document(str(tmp_path), machines="none")
    assert summary_only["health"]["machines"] is None
    assert summary_only["health"]["machines_total"] == 120


def test_fleet_status_page_limit_capped_by_knob(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_FLEET_STATUS_MAX_MACHINES", "20")
    names = fleetgen.machine_names(60)
    ledger = ledger_for(str(tmp_path))
    for name in names:
        ledger.record_request(name)
    ledger.flush()
    doc = fleet_status_document(str(tmp_path), machines="all", limit=10_000)
    assert len(doc["health"]["machines"]) == 20  # one page never exceeds it
    assert doc["health"]["machines_truncated"] is True


# -- rollup manifest ----------------------------------------------------------


def _span_corpus(tmp_path):
    names = fleetgen.machine_names(16)
    fleetgen.write_span_corpus(str(tmp_path), 2000, names, windows=8)
    RollupStore(str(tmp_path), seconds=60).aggregate()


def test_merged_rollup_opens_only_manifest_selected_files(tmp_path):
    """The counting-open contract BENCH_SCALE gates at 10k members: a
    bounded-window read opens the manifest plus exactly the overlapping
    window files — never a directory walk over every rotation."""
    _span_corpus(tmp_path)
    since = fleetgen.EPOCH + 60
    until = fleetgen.EPOCH + 180

    reader = RollupStore(str(tmp_path), seconds=60)  # no in-memory manifest
    opened = []
    original = reader._load_json

    def counting(path):
        opened.append(os.path.basename(path))
        return original(path)

    reader._load_json = counting
    doc = reader.merged(since=since, until=until)

    grid = range(
        (int(fleetgen.EPOCH) // 60 - 2) * 60, int(until) + 120, 60
    )
    selected = [s for s in grid if s + 60 > since and s < until]
    assert doc["window"]["merged_windows"] == len(selected)
    assert opened.count(ROLLUP_MANIFEST_FILE) == 1
    window_files = [n for n in opened if n != ROLLUP_MANIFEST_FILE]
    assert sorted(window_files) == sorted(f"{s}.json" for s in selected)


def test_manifest_tracks_sink_span_windows(tmp_path):
    _span_corpus(tmp_path)
    index = sink_window_index(str(tmp_path))
    entry = index["serve_trace.jsonl"]
    assert entry["complete"] is True
    assert fleetgen.EPOCH <= float(entry["min_ts"]) <= float(entry["max_ts"])


def test_rollup_reader_falls_back_without_usable_manifest(
    tmp_path, monkeypatch
):
    """No manifest trust (switch off, or a seconds-mismatched doc from
    another store generation) -> the directory walk answers, with
    identical results."""
    _span_corpus(tmp_path)
    since = fleetgen.EPOCH + 60
    until = fleetgen.EPOCH + 180
    baseline = RollupStore(str(tmp_path), seconds=60).merged(
        since=since, until=until
    )
    assert baseline["window"]["merged_windows"] > 0

    monkeypatch.setenv("GORDO_TPU_ROLLUP_MANIFEST", "0")
    walked = RollupStore(str(tmp_path), seconds=60).merged(
        since=since, until=until
    )
    assert walked == baseline
    monkeypatch.delenv("GORDO_TPU_ROLLUP_MANIFEST")

    manifest_path = tmp_path / ROLLUP_DIR / ROLLUP_MANIFEST_FILE
    doc = json.loads(manifest_path.read_text())
    doc["seconds"] = 999
    manifest_path.write_text(json.dumps(doc))
    stale = RollupStore(str(tmp_path), seconds=60).merged(
        since=since, until=until
    )
    assert stale == baseline


# -- trace window skipping ----------------------------------------------------


def test_trace_since_skips_rotated_generations_by_recorded_window(tmp_path):
    """``gordo-tpu trace --since`` trusts the manifest's recorded span
    windows over filesystem mtimes, in BOTH directions: a recently
    touched generation of ancient spans is skipped; an old-mtime file
    whose spans overlap the cutoff is read."""
    base = tmp_path / "serve_trace.jsonl"
    gen2 = tmp_path / "serve_trace.jsonl.2"  # oldest generation
    gen1 = tmp_path / "serve_trace.jsonl.1"
    for path in (gen2, gen1, base):
        path.write_text("")
    now = time.time()
    since = now - 3600.0
    os.utime(gen2, (now, now))  # mtime lies fresh; spans are ancient
    os.utime(gen1, (1.0, 1.0))  # mtime lies ancient; spans overlap

    index = {
        gen2.name: {"min_ts": 0.0, "max_ts": since - 100.0, "complete": True},
        gen1.name: {
            "min_ts": since + 10.0,
            "max_ts": since + 50.0,
            "complete": True,
        },
    }
    kept = iter_trace_files(str(base), since_ts=since, window_index=index)
    assert kept == [str(gen1), str(base)]  # the live file always stays

    # an incomplete window (reducer mid-file) is not authoritative:
    # the mtime heuristic decides, as it always did
    for entry in index.values():
        entry["complete"] = False
    kept = iter_trace_files(str(base), since_ts=since, window_index=index)
    assert kept == [str(gen2), str(base)]
    assert kept == iter_trace_files(str(base), since_ts=since)  # no index


# -- breaker board at scale ---------------------------------------------------


class _NoIterDict(dict):
    """A member map that fails the test on any full-map iteration —
    ``len()`` and keyed access stay legal."""

    def _refuse(self, *args, **kwargs):
        raise AssertionError("board summary iterated the full member map")

    __iter__ = _refuse
    keys = _refuse
    values = _refuse
    items = _refuse
    copy = _refuse


def test_breaker_summary_never_iterates_member_map(tmp_path):
    """5k tracked members, 8 tripped: the bounded summary costs
    O(unhealthy) — the full map is only ever ``len()``-counted."""
    board = fleetgen.make_breaker_board(5000, tripped=8)
    board._members = _NoIterDict(board._members)

    summary = board.summary(top_k=5)
    assert summary["tracked"] == 5000
    assert summary["open"] == 8
    assert summary["half_open"] == 0
    assert summary["trips"] == 8
    assert len(summary["members"]) == 5
    assert all(m["trips"] >= 1 for m in summary["members"])

    # the compatibility spelling rides the same bounded path
    legacy = board.snapshot(detail_cap=0)
    assert legacy["open"] == 8 and legacy["members"] == []


# -- the generator itself -----------------------------------------------------


def test_fleetgen_plan_covers_every_member():
    plan = fleetgen.build_fleet_plan(256)
    totals = plan.doc["totals"]
    assert totals["members"] == 256
    assert 1 <= totals["buckets"] < 256  # families coalesce, like a real fleet


def test_fleetgen_corpora_are_deterministic(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    a.mkdir(), b.mkdir()
    names = fleetgen.machine_names(32)
    path_a, first, last = fleetgen.write_span_corpus(str(a), 500, names)
    path_b, _, _ = fleetgen.write_span_corpus(str(b), 500, names)
    assert Path(path_a).read_bytes() == Path(path_b).read_bytes()
    assert first == fleetgen.EPOCH and last > first
