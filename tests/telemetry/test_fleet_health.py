"""
The per-member fleet health ledger (PR 9): record semantics, the golden
``fleet_health.json`` schema, persistence round-trips, the master
switch, and the joined fleet-status document.
"""

import json
import os

import pytest

from gordo_tpu.telemetry import fleet_health
from gordo_tpu.telemetry.fleet_health import (
    FLEET_HEALTH_FILE,
    NULL_LEDGER,
    SCORE_BUCKETS,
    FleetHealthLedger,
    fleet_status_document,
    health_score,
    ledger_for,
    ledger_summaries,
    load_health,
    machine_state,
    render_fleet_status,
    reset_ledgers,
)

pytestmark = [pytest.mark.fleet_health, pytest.mark.observability]


@pytest.fixture(autouse=True)
def _fresh_registry():
    reset_ledgers()
    yield
    reset_ledgers()


def make_ledger(tmp_path, **kwargs) -> FleetHealthLedger:
    kwargs.setdefault("heartbeat_seconds", 0.0)
    return FleetHealthLedger(directory=str(tmp_path), **kwargs)


# -- golden schema ------------------------------------------------------------

#: the pinned per-machine record shape — the fleet-status surface, the
#: Prometheus collector and external dashboards all parse this; drift
#: here must be deliberate
MACHINE_SECTIONS = {
    "serving": {"requests", "errors", "rows", "residual_mean", "last_request_at"},
    "drift": {
        "drifted",
        "reasons",
        "feature_shift_max",
        "residual_ratio",
        "window_rows",
        "evaluated_at",
    },
    "build": {
        "revision",
        "final_loss",
        "degraded",
        "failed",
        "error",
        "bisects",
        "retries",
        "built_at",
    },
    "quarantine": {"active", "revision", "reasons", "since"},
    "breaker": {"state", "trips", "cooldown_s", "reason", "updated_at"},
    "health": {"score", "state"},
}


def test_snapshot_golden_schema(tmp_path):
    ledger = make_ledger(tmp_path, project="p")
    ledger.record_request("m-1", error=True)
    ledger.record_scores("m-1", rows=10, residual_mean=0.25)
    ledger.record_build("m-1", revision="7", final_loss=0.01, bisects=2)
    ledger.record_drift(
        "m-1", True, ["feature-shift t"], {"feature_shift_max": 3.0}
    )
    ledger.flush()

    doc = load_health(str(tmp_path))
    assert doc["version"] == 1
    assert doc["project"] == "p"
    assert set(doc) >= {"version", "project", "updated_at", "machines", "summary"}
    record = doc["machines"]["m-1"]
    assert set(record) == set(MACHINE_SECTIONS)
    for section, keys in MACHINE_SECTIONS.items():
        assert set(record[section]) == keys, section
    summary = doc["summary"]
    assert set(summary) == {
        "machines",
        "healthy",
        "degraded",
        "drifting",
        "quarantined",
        "requests",
        "errors",
        "error_rate",
        "breaker_tripped",
        "score_histogram",
    }
    assert summary["score_histogram"]["buckets"] == list(SCORE_BUCKETS)
    assert sum(summary["score_histogram"]["counts"]) == summary["machines"]


def test_lifecycle_file_names_stay_mirrored():
    """fleet_health.py reads the lifecycle state files by path without
    importing the lifecycle package (the layering contract); the
    mirrored spellings must never drift apart."""
    from gordo_tpu.lifecycle.state import (
        LIFECYCLE_DIR,
        QUARANTINE_FILE,
        STATE_FILE,
    )

    assert fleet_health._LIFECYCLE_DIR == LIFECYCLE_DIR
    assert fleet_health._LIFECYCLE_STATE_FILE == STATE_FILE
    assert fleet_health._LIFECYCLE_QUARANTINE_FILE == QUARANTINE_FILE


# -- record semantics ---------------------------------------------------------


def test_states_by_severity(tmp_path):
    ledger = make_ledger(tmp_path)
    ledger.record_drift("m", True, ["drift"])
    assert ledger.machine("m")["health"]["state"] == "drifting"
    ledger.record_build("m", degraded=True)
    assert ledger.machine("m")["health"]["state"] == "degraded"
    ledger.record_quarantine(["m"], revision="9", reasons=["gate"])
    assert ledger.machine("m")["health"]["state"] == "quarantined"
    # promotion of a rebuilt member clears quarantine, drift AND the
    # degraded/failed flags — a rebuild that passed the gates and took
    # traffic IS a successful build; nothing may read 'degraded' forever
    ledger.record_promotion("10", ["m"])
    machine = ledger.machine("m")
    assert machine["quarantine"]["active"] is False
    assert machine["drift"]["drifted"] is False
    assert machine["build"]["revision"] == "10"
    assert machine["build"]["degraded"] is False
    assert machine["health"]["state"] == "healthy"


def test_clean_rebuild_clears_failure_evidence(tmp_path):
    ledger = make_ledger(tmp_path)
    ledger.record_build("m", failed=True, error="RuntimeError('boom')")
    assert ledger.machine("m")["health"]["state"] == "degraded"
    # the next clean build supersedes the evidence
    ledger.record_build("m", revision="8", failed=False, degraded=False)
    machine = ledger.machine("m")
    assert machine["build"]["failed"] is False
    assert machine["build"]["error"] is None
    assert machine["health"]["state"] == "healthy"


def test_health_score_is_monotone_in_badness():
    healthy = fleet_health._new_machine()
    drifted = fleet_health._new_machine()
    drifted["drift"]["drifted"] = True
    quarantined = json.loads(json.dumps(drifted))
    quarantined["quarantine"]["active"] = True
    assert health_score(healthy) == 1.0
    assert health_score(drifted) < health_score(healthy)
    assert health_score(quarantined) < health_score(drifted)
    assert machine_state(healthy) == "healthy"


def test_error_rate_degrades_score(tmp_path):
    ledger = make_ledger(tmp_path)
    for _ in range(9):
        ledger.record_request("m")
    ledger.record_request("m", error=True)
    machine = ledger.machine("m")
    assert machine["serving"]["requests"] == 10
    assert machine["serving"]["errors"] == 1
    assert 0.6 < machine["health"]["score"] < 1.0


def test_residual_window_decays(tmp_path):
    ledger = make_ledger(tmp_path, window_rows=100)
    ledger.record_scores("m", rows=100, residual_mean=1.0)
    ledger.record_scores("m", rows=100, residual_mean=3.0)
    mean = ledger.machine("m")["serving"]["residual_mean"]
    # with decay the later window dominates a plain average
    assert mean > 2.0


def test_restore_round_trip(tmp_path):
    ledger = make_ledger(tmp_path)
    ledger.record_request("m-1", error=True)
    ledger.record_quarantine(["m-2"], revision="3", reasons=["r"])
    ledger.record_plan_accuracy({"actual_compiles": 2})
    ledger.flush()

    fresh = make_ledger(tmp_path)
    fresh.restore(load_health(str(tmp_path)))
    assert fresh.machine("m-1")["serving"]["errors"] == 1
    assert fresh.machine("m-2")["quarantine"]["active"] is True
    assert fresh.document()["plan_accuracy"] == {"actual_compiles": 2}


def test_ledger_for_reloads_persisted_snapshot(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_TELEMETRY", "1")
    ledger = ledger_for(str(tmp_path))
    ledger.record_request("m-1")
    ledger.flush()
    reset_ledgers()
    again = ledger_for(str(tmp_path))
    assert again is not ledger
    assert again.machine("m-1")["serving"]["requests"] == 1
    # one ledger per normalized path
    assert ledger_for(str(tmp_path) + os.sep) is again
    assert str(tmp_path) in ledger_summaries()


def test_master_switch_disables_everything(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_FLEET_HEALTH", "0")
    ledger = ledger_for(str(tmp_path))
    assert ledger is NULL_LEDGER
    ledger.record_request("m", error=True)
    ledger.record_drift("m", True, write=False)
    ledger.flush()
    assert not os.path.exists(os.path.join(str(tmp_path), FLEET_HEALTH_FILE))
    monkeypatch.setenv("GORDO_TPU_FLEET_HEALTH", "1")
    monkeypatch.setenv("GORDO_TPU_TELEMETRY", "0")
    assert ledger_for(str(tmp_path)) is NULL_LEDGER


def test_atomic_write_leaves_no_tmp(tmp_path):
    ledger = make_ledger(tmp_path)
    ledger.record_request("m")
    ledger.flush()
    leftovers = [
        name
        for name in os.listdir(str(tmp_path))
        if name != FLEET_HEALTH_FILE
    ]
    assert leftovers == []


def test_snapshot_is_a_builder_dropping(tmp_path):
    from gordo_tpu import serializer

    assert serializer.is_builder_dropping(FLEET_HEALTH_FILE)
    ledger = make_ledger(tmp_path)
    ledger.record_request("m")
    ledger.flush()
    assert serializer.list_model_dirs(str(tmp_path)) == []


# -- the joined surface -------------------------------------------------------


def test_fleet_status_document_joins_all_sections(tmp_path):
    revision_dir = tmp_path / "100"
    revision_dir.mkdir()
    with open(revision_dir / "build_status.json", "w") as f:
        json.dump(
            {
                "version": 1,
                "state": "complete",
                "machines": {"total": 3, "completed": 3, "failed": 0},
                "phases": {},
            },
            f,
        )
    with open(revision_dir / "fleet_plan.json", "w") as f:
        json.dump(
            {
                "strategy": "packed",
                "totals": {"buckets": 1, "compiles": 1, "padding_waste": 0.1},
            },
            f,
        )
    lifecycle_dir = tmp_path / ".lifecycle"
    lifecycle_dir.mkdir()
    with open(lifecycle_dir / "state.json", "w") as f:
        json.dump(
            {
                "version": 1,
                "phase": "idle",
                "serving_revision": "101",
                "canary_revision": None,
                "stale": [],
                "history": [{"event": "promoted"}],
            },
            f,
        )
    with open(lifecycle_dir / "quarantine.json", "w") as f:
        json.dump([{"canary_revision": "102", "machines": ["m-2"]}], f)

    ledger = FleetHealthLedger(
        directory=str(revision_dir), heartbeat_seconds=0.0
    )
    ledger.record_request("m-1")
    ledger.record_quarantine(["m-2"], revision="102", reasons=["gate fail"])
    ledger.record_plan_accuracy(
        {
            "actual_compiles": 1,
            "actual_fit_s": 1.5,
            "measured_member_waste": 0.25,
            "measured_hbm_peak_bytes": 1 << 20,
        }
    )
    ledger.flush()

    doc = fleet_status_document(
        str(revision_dir),
        device={
            "memory": {
                "available": True,
                "measured_devices": 1,
                "bytes_in_use": 1024,
                "peak_bytes_in_use": 2048,
            },
            "compile_cache": {
                "build": {"compiles": 2, "cache_hits": 6, "hit_rate": 0.75}
            },
        },
        programs={"programs": 2, "signatures": 4},
    )
    assert doc["revision"] == "100"
    assert doc["build"]["state"] == "complete"
    assert doc["plan"]["strategy"] == "packed"
    assert doc["plan"]["accuracy"]["measured_member_waste"] == 0.25
    assert doc["lifecycle"]["serving_revision"] == "101"
    assert doc["lifecycle"]["quarantine_records"] == 1
    assert doc["health"]["summary"]["quarantined"] == 1
    assert doc["programs"]["signatures"] == 4

    rendered = render_fleet_status(doc)
    assert "packed" in rendered
    assert "quarantined" in rendered
    assert "m-2" in rendered
    assert "hit rate" in rendered
    # the document round-trips through JSON (the route serves it)
    json.dumps(doc)


def test_fleet_status_document_degrades_per_section(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    doc = fleet_status_document(str(empty))
    assert doc["build"] is None
    assert doc["plan"] is None
    assert doc["lifecycle"] is None
    assert doc["health"] is None
    rendered = render_fleet_status(doc)
    assert "no build_status.json" in rendered
    assert "no fleet_health.json" in rendered


# -- the CLI ------------------------------------------------------------------


@pytest.mark.parametrize("as_json", [False, True])
def test_fleet_status_cli(tmp_path, as_json):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import fleet_status as fleet_status_cmd

    revision_dir = tmp_path / "100"
    revision_dir.mkdir()
    ledger = FleetHealthLedger(
        directory=str(revision_dir), heartbeat_seconds=0.0
    )
    ledger.record_request("m-1", error=True)
    ledger.record_drift("m-1", True, ["feature-shift t (3.00σ)"])
    ledger.flush()
    reset_ledgers()  # the CLI reads the persisted snapshot, not memory

    args = [str(revision_dir)] + (["--as-json"] if as_json else [])
    result = CliRunner().invoke(fleet_status_cmd, args)
    assert result.exit_code == 0, result.output
    if as_json:
        doc = json.loads(result.output)
        assert doc["revision"] == "100"
        assert doc["health"]["summary"]["drifting"] == 1
        assert "compile_cache" in doc["device"]
    else:
        assert "drifting" in result.output
        assert "m-1" in result.output


def test_fleet_status_cli_missing_directory():
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import fleet_status as fleet_status_cmd

    result = CliRunner().invoke(fleet_status_cmd, ["/no/such/dir"])
    assert result.exit_code != 0
    assert "No such directory" in result.output


# -- the serving circuit-breaker section (PR 15) ------------------------------


@pytest.mark.chaos
def test_record_breaker_trip_and_recovery(tmp_path):
    ledger = make_ledger(tmp_path)
    ledger.record_breaker(
        "m-1", "open", trips=2, cooldown_s=60.0, reason="XlaRuntimeError(...)"
    )
    doc = ledger.document()
    record = doc["machines"]["m-1"]
    assert record["breaker"]["state"] == "open"
    assert record["breaker"]["trips"] == 2
    assert record["breaker"]["cooldown_s"] == 60.0
    assert record["breaker"]["updated_at"]
    # an open breaker IS a serving quarantine in the headline state,
    # and it costs health score
    assert record["health"]["state"] == "quarantined"
    assert record["health"]["score"] < 1.0
    assert doc["summary"]["quarantined"] == 1
    ledger.record_breaker("m-1", "closed", trips=2)
    record = ledger.document()["machines"]["m-1"]
    assert record["breaker"]["state"] == "closed"
    assert record["health"]["state"] == "healthy"


@pytest.mark.chaos
def test_breaker_state_transitions_force_snapshot_writes(tmp_path):
    ledger = make_ledger(tmp_path, heartbeat_seconds=3600.0)
    ledger.record_breaker("m-1", "open", trips=1)
    doc = load_health(str(tmp_path))
    assert doc["machines"]["m-1"]["breaker"]["state"] == "open"


@pytest.mark.chaos
def test_breaker_section_merges_newest_stamp_wins(tmp_path):
    from gordo_tpu.telemetry.fleet_health import merge_health_documents

    older = make_ledger(tmp_path / "a")
    older.record_breaker("m-1", "open", trips=1)
    doc_a = older.document()
    newer = make_ledger(tmp_path / "b")
    newer.record_breaker("m-1", "closed", trips=1)
    doc_b = newer.document()
    # force the ordering regardless of wall-clock resolution
    doc_a["machines"]["m-1"]["breaker"]["updated_at"] = "2026-01-01T00:00:00+00:00"
    doc_b["machines"]["m-1"]["breaker"]["updated_at"] = "2026-01-02T00:00:00+00:00"
    merged = merge_health_documents([doc_a, doc_b])
    assert merged["machines"]["m-1"]["breaker"]["state"] == "closed"
    merged = merge_health_documents([doc_b, doc_a])
    assert merged["machines"]["m-1"]["breaker"]["state"] == "closed"


@pytest.mark.chaos
def test_pre_breaker_snapshots_restore_cleanly(tmp_path):
    """Snapshots persisted before the breaker section existed load
    without it and read as healthy/closed."""
    ledger = make_ledger(tmp_path)
    ledger.record_request("m-1")
    doc = ledger.document()
    for record in doc["machines"].values():
        record.pop("breaker", None)
    fresh = make_ledger(tmp_path / "fresh")
    fresh.restore(doc)
    restored = fresh.document()["machines"]["m-1"]
    assert restored["breaker"]["state"] == "closed"
    assert restored["health"]["state"] == "healthy"


@pytest.mark.chaos
def test_render_fleet_status_shows_breaker_state(tmp_path):
    from gordo_tpu.telemetry.fleet_health import render_fleet_status

    ledger = make_ledger(tmp_path)
    ledger.record_request("m-1")
    doc = fleet_status_document(
        str(tmp_path),
        serving={
            "precision": {"config": "f32", "coalesced": {}},
            "gates": [],
            "breaker": {
                "open": 1,
                "half_open": 0,
                "trips": 2,
                "members": [
                    {"member": "m-1", "state": "open", "cooldown_s": 60.0}
                ],
            },
        },
    )
    rendered = render_fleet_status(doc)
    assert "breakers: 1 open" in rendered
    assert "m-1: open, cooldown 60.0s" in rendered


@pytest.mark.chaos
def test_stale_breaker_record_stops_reading_quarantined(tmp_path):
    """A dead server's forgotten 'open' record must not display a
    serving machine as quarantined forever: past the staleness cutoff
    the headline state and score read the breaker as retired."""
    import datetime

    ledger = make_ledger(tmp_path)
    ledger.record_breaker("m-1", "open", trips=1, cooldown_s=30.0)
    fresh = ledger.machine("m-1")
    assert fresh["health"]["state"] == "quarantined"
    old = (
        datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(hours=3)
    ).isoformat()
    with ledger._lock:
        ledger._machines["m-1"]["breaker"]["updated_at"] = old
    stale = ledger.machine("m-1")
    assert stale["health"]["state"] == "healthy"
    assert stale["health"]["score"] == 1.0
