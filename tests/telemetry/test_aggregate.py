"""The cross-worker telemetry reducer: windowed rollups, dedupe,
incremental offsets, rotation-during-read safety, and per-worker merge
exactness (the foundations under the SLO engine)."""

import datetime
import json
import os
import threading

import pytest

from gordo_tpu.telemetry.aggregate import (
    LATENCY_BUCKETS_MS,
    ROLLUP_DIR,
    ROLLUP_MANIFEST_FILE,
    ROLLUP_STATE_FILE,
    RollupStore,
    discover_sinks,
    file_signature,
    histogram_add,
    histogram_merge,
    histogram_percentile,
    merge_rollups,
    new_histogram,
    parse_span_time,
    sink_bases,
    summarize_rollup,
)

pytestmark = pytest.mark.slo

NOW = 1_754_000_000.0  # a fixed, boring epoch


def iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


def request_span(
    i, ts, status=200, wall_ms=100.0, machine="m-1", trace_prefix=0
):
    return {
        "name": "request",
        "context": {
            "trace_id": f"{trace_prefix:08x}{i:024x}",
            "span_id": f"{i:016x}",
        },
        "parent_id": None,
        "kind": "server",
        "start_time": iso(ts - wall_ms / 1000.0),
        "end_time": iso(ts),
        "duration_ms": wall_ms,
        "status": {"status_code": "OK"},
        "attributes": {"http.status_code": status, "gordo_name": machine},
        "resource": {"service.name": "test"},
    }


def stage_span(i, ts, name="inference", ms=40.0, trace_prefix=0):
    return {
        "name": name,
        "context": {
            "trace_id": f"{trace_prefix:08x}{i:024x}",
            "span_id": f"a{i:015x}",
        },
        "parent_id": f"{i:016x}",
        "kind": "internal",
        "start_time": iso(ts - ms / 1000.0),
        "end_time": iso(ts),
        "duration_ms": ms,
        "status": {"status_code": "OK"},
        "attributes": {},
        "resource": {"service.name": "test"},
    }


def write_spans(path, spans, mode="w"):
    with open(path, mode) as handle:
        for span in spans:
            handle.write(json.dumps(span) + "\n")


# -- histogram math -----------------------------------------------------------


def test_histogram_add_and_percentile():
    histogram = new_histogram()
    for value in (10.0, 20.0, 30.0, 40.0, 1000.0):
        histogram_add(histogram, value)
    assert histogram["count"] == 5
    assert histogram["sum_ms"] == pytest.approx(1100.0)
    p50 = histogram_percentile(histogram, 0.50)
    assert 10.0 < p50 <= 50.0
    assert histogram_percentile(histogram, 1.0) >= 750.0
    assert histogram_percentile(new_histogram(), 0.5) == 0.0


def test_histogram_overflow_bucket_reports_top_edge():
    histogram = new_histogram()
    histogram_add(histogram, 10_000_000.0)  # way past the last edge
    assert histogram["counts"][-1] == 1
    assert histogram_percentile(histogram, 0.5) == LATENCY_BUCKETS_MS[-1]


def test_histogram_merge_same_edges():
    a, b = new_histogram(), new_histogram()
    for value in (5.0, 50.0):
        histogram_add(a, value)
    for value in (500.0, 5000.0):
        histogram_add(b, value)
    histogram_merge(a, b)
    assert a["count"] == 4
    assert a["sum_ms"] == pytest.approx(5555.0)
    assert sum(a["counts"]) == 4


def test_parse_span_time():
    assert parse_span_time(iso(NOW)) == pytest.approx(NOW)
    assert parse_span_time("garbage") is None
    assert parse_span_time(None) is None


# -- discovery ----------------------------------------------------------------


def test_sink_bases_and_discovery(tmp_path):
    d = str(tmp_path)
    write_spans(os.path.join(d, "serve_trace.jsonl"), [request_span(1, NOW)])
    write_spans(
        os.path.join(d, "serve_trace-123.jsonl"), [request_span(2, NOW)]
    )
    write_spans(
        os.path.join(d, "serve_trace-123.jsonl.1"), [request_span(3, NOW)]
    )
    write_spans(os.path.join(d, "build_trace.jsonl"), [])
    bases = sink_bases(d, "serve_trace.jsonl")
    assert [os.path.basename(b) for b in bases] == [
        "serve_trace-123.jsonl",
        "serve_trace.jsonl",
    ]
    kinds = {}
    for kind, path in discover_sinks(d):
        kinds.setdefault(kind, []).append(os.path.basename(path))
    # rotated generation read BEFORE its live file
    assert kinds["serve"] == [
        "serve_trace-123.jsonl.1",
        "serve_trace-123.jsonl",
        "serve_trace.jsonl",
    ]
    assert kinds["build"] == ["build_trace.jsonl"]


def test_file_signature_follows_rotated_bytes(tmp_path):
    path = tmp_path / "serve_trace.jsonl"
    write_spans(str(path), [request_span(1, NOW)])
    signature = file_signature(str(path))
    os.replace(str(path), str(path) + ".1")
    assert file_signature(str(path) + ".1") == signature
    assert file_signature(str(path)) is None


# -- the reducer --------------------------------------------------------------


def test_rollup_windows_and_contents(tmp_path):
    d = str(tmp_path)
    spans = []
    # two windows: 10 ok + 2 errors at NOW, 5 ok at NOW+120
    for i in range(10):
        spans.append(request_span(i, NOW + i * 0.1, wall_ms=100.0))
        spans.append(stage_span(i, NOW + i * 0.1))
    for i in range(10, 12):
        spans.append(request_span(i, NOW + i * 0.1, status=503))
    for i in range(20, 25):
        spans.append(request_span(i, NOW + 120.0))
    write_spans(os.path.join(d, "serve_trace.jsonl"), spans)

    store = RollupStore(d, seconds=60)
    report = store.aggregate()
    assert report["spans_read"] == len(spans)
    assert len(report["windows_updated"]) == 2

    first = store._load_json(store.rollup_path(store.window_start(NOW)))
    assert first["requests"]["count"] == 12
    assert first["requests"]["errors"] == 2
    assert first["requests"]["by_class"]["5xx"] == 2
    assert first["machines"]["m-1"] == {"requests": 12, "errors": 2}
    assert first["stages"]["inference"]["count"] == 10

    merged = store.merged(since=NOW - 60, until=NOW + 300)
    summary = summarize_rollup(merged)
    assert summary["requests"] == 17
    assert summary["errors"] == 2
    assert summary["machines"]["m-1"]["error_rate"] == pytest.approx(
        2 / 17, abs=1e-6
    )
    assert summary["stages"]["inference"]["p50_ms"] > 0


def test_aggregate_is_incremental(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "serve_trace.jsonl")
    write_spans(path, [request_span(i, NOW) for i in range(5)])
    store = RollupStore(d, seconds=60)
    assert store.aggregate()["spans_read"] == 5
    # unchanged corpus: zero spans re-read
    assert store.aggregate()["spans_read"] == 0
    # appending folds ONLY the delta, into the existing rollup
    write_spans(path, [request_span(i, NOW) for i in range(5, 8)], mode="a")
    assert store.aggregate()["spans_read"] == 3
    merged = store.merged()
    assert merged["requests"]["count"] == 8
    # a fresh store instance resumes from the persisted state file
    assert RollupStore(d, seconds=60).aggregate()["spans_read"] == 0


def test_dedupe_by_trace_and_span_id(tmp_path):
    d = str(tmp_path)
    spans = [request_span(i, NOW) for i in range(4)]
    # the same spans duplicated into a second worker sink (e.g. a copied
    # generation): they must count once
    write_spans(os.path.join(d, "serve_trace-1.jsonl"), spans)
    write_spans(os.path.join(d, "serve_trace-2.jsonl"), spans)
    store = RollupStore(d, seconds=60)
    store.aggregate()
    assert store.merged()["requests"]["count"] == 4


def test_three_worker_sinks_sum_exactly(tmp_path):
    """The satellite regression: aggregated RED counts == the sum of
    per-worker counts (3 simulated workers, disjoint traffic)."""
    d = str(tmp_path)
    per_worker = {}
    for worker, pid in enumerate((1001, 1002, 1003)):
        spans = []
        errors = 0
        for i in range(30 + worker):
            status = 500 if i % 7 == 0 else 200
            errors += status == 500
            spans.append(
                request_span(
                    i, NOW + i, status=status, trace_prefix=pid
                )
            )
        per_worker[pid] = {"requests": len(spans), "errors": errors}
        write_spans(os.path.join(d, f"serve_trace-{pid}.jsonl"), spans)
    store = RollupStore(d, seconds=60)
    store.aggregate()
    summary = summarize_rollup(store.merged())
    assert summary["requests"] == sum(
        w["requests"] for w in per_worker.values()
    )
    assert summary["errors"] == sum(w["errors"] for w in per_worker.values())


def test_torn_tail_line_reread_exactly_once(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "serve_trace.jsonl")
    write_spans(path, [request_span(0, NOW)])
    with open(path, "a") as handle:
        handle.write(json.dumps(request_span(1, NOW))[:40])  # torn write
    store = RollupStore(d, seconds=60)
    store.aggregate()
    assert store.merged()["requests"]["count"] == 1
    # the writer finishes the line; the completed span counts once
    with open(path, "a") as handle:
        handle.write(json.dumps(request_span(1, NOW))[40:] + "\n")
    store.aggregate()
    assert store.merged()["requests"]["count"] == 2


def test_build_trace_folds_into_build_section(tmp_path):
    d = str(tmp_path)
    spans = []
    for i in range(6):
        spans.append(
            {
                "name": "device_program",
                "context": {"trace_id": f"{i:032x}", "span_id": f"{i:016x}"},
                "parent_id": None,
                "kind": "internal",
                "start_time": iso(NOW),
                "end_time": iso(NOW + 1),
                "duration_ms": 1000.0,
                "status": {"status_code": "OK"},
                "attributes": {"compile": i < 2},
                "resource": {},
            }
        )
    write_spans(os.path.join(d, "build_trace.jsonl"), spans)
    store = RollupStore(d, seconds=60)
    store.aggregate()
    build = store.merged()["build"]
    assert build["device_programs"] == 6
    assert build["compiles"] == 2


def test_rollup_pruning(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_SLO_ROLLUP_KEEP", "3")
    d = str(tmp_path)
    spans = [
        request_span(i, NOW + i * 60.0) for i in range(8)
    ]  # 8 distinct windows
    write_spans(os.path.join(d, "serve_trace.jsonl"), spans)
    store = RollupStore(d, seconds=60)
    report = store.aggregate()
    assert report["rollups_pruned"] == 5
    kept = [
        entry
        for entry in os.listdir(store.rollup_dir)
        if entry != ROLLUP_STATE_FILE
        and entry != ROLLUP_MANIFEST_FILE
        and not entry.startswith(".")
    ]
    assert len(kept) == 3
    # the manifest tracks exactly the surviving windows
    manifest = store._load_json(store.manifest_path)
    assert sorted(manifest["windows"]) == sorted(
        entry[: -len(".json")] for entry in kept
    )


def test_rollup_dir_and_state_are_droppings():
    from gordo_tpu.serializer import is_builder_dropping

    assert is_builder_dropping(ROLLUP_DIR)
    assert is_builder_dropping("slo_state.json")
    assert is_builder_dropping("slos.toml")
    assert is_builder_dropping("serve_trace-1234.jsonl")
    assert is_builder_dropping("serve_trace-1234.jsonl.2")
    assert is_builder_dropping("fleet_health-1234.json")
    assert not is_builder_dropping("my-model")


def test_rotation_during_read_never_drops_or_double_counts(tmp_path):
    """The pinned contract: a reader aggregating WHILE the writer
    rotates the sink must converge on exactly-once folding — no span
    dropped when its bytes moved to ``.1`` mid-read, none double-counted
    when the reader sees the same bytes at two paths."""
    from gordo_tpu.telemetry.recorder import SpanRecorder

    d = str(tmp_path)
    path = os.path.join(d, "serve_trace.jsonl")
    total = 600
    recorder = SpanRecorder(
        sink_path=path, max_bytes=8 * 1024, keep=50
    )  # rotates every ~20 spans
    store = RollupStore(d, seconds=3600)
    stop = threading.Event()
    aggregation_errors = []

    def reader():
        while not stop.is_set():
            try:
                store.aggregate()
            except Exception as exc:  # noqa: BLE001 - the assertion
                aggregation_errors.append(exc)
                return

    thread = threading.Thread(target=reader)
    thread.start()
    try:
        for i in range(total):
            recorder.emit(request_span(i, NOW + i * 0.01))
    finally:
        stop.set()
        thread.join(timeout=30)
    recorder.close()
    assert not aggregation_errors
    # the settling pass: everything the concurrent passes missed
    store.aggregate()
    merged = store.merged()
    assert merged["requests"]["count"] == total


def test_dead_worker_sinks_pruned_once_consumed_and_cold(tmp_path):
    """A dead worker's fully-folded, day-cold trace chain is
    garbage-collected by the reducer; a live worker's (this process)
    never is, a freshly-written chain never is (the age gate backs up
    the namespace-blind pid probe), and health snapshots are never
    touched."""
    import time as time_mod

    d = str(tmp_path)
    dead_pid = 2**22 + 11  # beyond any real pid on this host
    live_pid = os.getpid()
    old = time_mod.time() - 2 * 86400
    spans = [request_span(i, NOW, trace_prefix=1) for i in range(4)]
    write_spans(os.path.join(d, f"serve_trace-{dead_pid}.jsonl"), spans)
    write_spans(
        os.path.join(d, f"serve_trace-{dead_pid}.jsonl.1"),
        [request_span(10, NOW, trace_prefix=2)],
    )
    write_spans(
        os.path.join(d, f"serve_trace-{live_pid}.jsonl"),
        [request_span(20, NOW, trace_prefix=3)],
    )
    fresh_dead = os.path.join(d, f"serve_trace-{dead_pid + 1}.jsonl")
    write_spans(fresh_dead, [request_span(30, NOW, trace_prefix=4)])
    health = os.path.join(d, f"fleet_health-{dead_pid}.json")
    with open(health, "w") as handle:
        handle.write("{}")
    for name in (
        f"serve_trace-{dead_pid}.jsonl",
        f"serve_trace-{dead_pid}.jsonl.1",
    ):
        os.utime(os.path.join(d, name), (old, old))
    store = RollupStore(d, seconds=60)
    report = store.aggregate()
    assert report["worker_sinks_pruned"] == 2
    assert not os.path.exists(
        os.path.join(d, f"serve_trace-{dead_pid}.jsonl")
    )
    assert not os.path.exists(
        os.path.join(d, f"serve_trace-{dead_pid}.jsonl.1")
    )
    assert os.path.exists(os.path.join(d, f"serve_trace-{live_pid}.jsonl"))
    assert os.path.exists(fresh_dead)  # dead pid but written today
    assert os.path.exists(health)
    # the folded spans survive in the rollups
    assert store.merged()["requests"]["count"] == 7


def test_sink_gc_disabled_by_knob(tmp_path, monkeypatch):
    import time as time_mod

    monkeypatch.setenv("GORDO_TPU_SLO_SINK_GC_AGE", "0")
    d = str(tmp_path)
    dead = os.path.join(d, f"serve_trace-{2**22 + 13}.jsonl")
    write_spans(dead, [request_span(0, NOW)])
    old = time_mod.time() - 2 * 86400
    os.utime(dead, (old, old))
    report = RollupStore(d, seconds=60).aggregate()
    assert report["worker_sinks_pruned"] == 0
    assert os.path.exists(dead)


def test_signature_stable_for_short_first_line(tmp_path):
    """A sink whose only line is shorter than the 256-byte head read
    must keep its identity when line two lands — a raw prefix hash
    would orphan the saved offset and double-fold line one."""
    path = tmp_path / "serve_trace.jsonl"
    short = json.dumps(
        {"name": "request", "context": {"trace_id": "t", "span_id": "s"}}
    )
    assert len(short) < 200
    path.write_text(short + "\n")
    first = file_signature(str(path))
    with open(path, "a") as handle:
        handle.write(json.dumps(request_span(1, NOW)) + "\n")
    assert file_signature(str(path)) == first
    # a torn (incomplete) first line has no identity yet
    torn = tmp_path / "torn.jsonl"
    torn.write_text(short)  # no newline
    assert file_signature(str(torn)) is None


def test_short_first_line_not_double_counted(tmp_path):
    d = str(tmp_path)
    path = os.path.join(d, "serve_trace.jsonl")
    # a minimal-but-valid request span, well under 256 bytes
    tiny = {
        "name": "request",
        "context": {"trace_id": "a" * 32, "span_id": "b" * 16},
        "kind": "server",
        "end_time": iso(NOW),
        "duration_ms": 5.0,
        "attributes": {"http.status_code": 200},
    }
    assert len(json.dumps(tiny)) < 256
    with open(path, "w") as handle:
        handle.write(json.dumps(tiny) + "\n")
    store = RollupStore(d, seconds=60)
    store.aggregate()
    # the file grows past the old 256-byte hash basis
    write_spans(path, [request_span(i, NOW) for i in range(3)], mode="a")
    store.aggregate()
    assert store.merged()["requests"]["count"] == 4


def test_writer_reopens_unlinked_sink(tmp_path):
    """A sink deleted under a live writer (a namespace-blind GC) must
    not orphan the fd — the next write starts a fresh file."""
    from gordo_tpu.telemetry.recorder import SpanRecorder

    path = str(tmp_path / "serve_trace.jsonl")
    recorder = SpanRecorder(sink_path=path)
    recorder.emit(request_span(0, NOW))
    assert os.path.exists(path)
    os.remove(path)
    recorder.emit(request_span(1, NOW))
    recorder.close()
    assert os.path.exists(path)
    with open(path) as handle:
        assert len(handle.readlines()) == 1  # the post-unlink span


def test_ledger_registry_rebuilds_after_fork(tmp_path, monkeypatch):
    """A ledger inherited across a fork (gunicorn --preload) froze the
    PARENT's pid into its snapshot path; ledger_for must rebuild it in
    the child instead of letting N workers clobber one file."""
    from gordo_tpu.telemetry import fleet_health

    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "1")
    fleet_health.reset_ledgers()
    try:
        parent = fleet_health.ledger_for(str(tmp_path))
        assert parent.path.endswith(f"-{os.getpid()}.json")
        # simulate the fork: the cached ledger claims another pid
        parent._pid = os.getpid() + 1
        child = fleet_health.ledger_for(str(tmp_path))
        assert child is not parent
        assert child._pid == os.getpid()
        assert child.path.endswith(f"-{os.getpid()}.json")
    finally:
        fleet_health.reset_ledgers()


def test_merge_rollups_is_count_additive():
    a = {
        "requests": {"count": 3, "errors": 1, "by_class": {"5xx": 1, "2xx": 2}},
        "latency_ms": new_histogram(),
        "stages": {},
        "machines": {"m": {"requests": 3, "errors": 1}},
        "build": {"device_programs": 0, "compiles": 0, "phases": {}},
        "spans": 3,
        "window": {"start": 0, "seconds": 60},
    }
    import copy

    b = copy.deepcopy(a)
    merged = merge_rollups(copy.deepcopy(a), b)
    assert merged["requests"]["count"] == 6
    assert merged["machines"]["m"]["requests"] == 6
    assert merged["spans"] == 6
