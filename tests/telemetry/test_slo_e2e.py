"""The ISSUE's acceptance drill, end to end through the real CLI:
3 worker sinks + an injected server-error burst → aggregated rollups
match per-worker sums exactly, the fast-burn alert transitions
pending→firing within one evaluation and resolves after recovery, and
``gordo-tpu slo check`` exits non-zero only while firing."""

import json
import os
import time

import pytest
from click.testing import CliRunner

from gordo_tpu.cli.cli import gordo_tpu_cli
from gordo_tpu.telemetry import slo
from gordo_tpu.telemetry.aggregate import RollupStore, summarize_rollup

from .test_aggregate import request_span, write_spans

pytestmark = pytest.mark.slo

#: a drill-friendly objective set: 1% budget, fast threshold 10x — the
#: burst must push the 1h bad fraction over 10%, recovery volume pulls
#: it back under without waiting for windows to age out
DRILL_SLOS = """
[[slo]]
name = "availability"
objective = "availability"
target = 0.99
window = "30d"

[burn]
fast_window = "1h"
fast_threshold = 10.0
fast_severity = "page"
slow_window = "6h"
slow_threshold = 6.0
slow_severity = "ticket"
confirmation_divisor = 12
"""

WORKER_PIDS = (3001, 3002, 3003)


@pytest.fixture(autouse=True)
def _fresh_registry():
    slo.reset_statuses()
    yield
    slo.reset_statuses()


def _write_phase(directory, now, phase):
    """Per-worker spans for one drill phase; returns per-worker counts."""
    counts = {}
    for worker, pid in enumerate(WORKER_PIDS):
        spans = []
        errors = 0
        if phase == "healthy":
            # ~45 min of clean traffic per worker
            for i in range(700):
                spans.append(
                    request_span(
                        i, now - 2700 + i * 3.5, wall_ms=80.0,
                        trace_prefix=pid,
                    )
                )
        elif phase == "burst":
            # the injected server-error burst, just now
            for i in range(120):
                spans.append(
                    request_span(
                        5_000 + i, now - 120 + i, status=500,
                        trace_prefix=pid,
                    )
                )
                errors += 1
        elif phase == "recovery":
            # heavy clean traffic drowns the burst inside every window
            for i in range(3000):
                spans.append(
                    request_span(
                        10_000 + i, now - 240 + i * 0.08, wall_ms=80.0,
                        trace_prefix=pid,
                    )
                )
        counts[pid] = {"requests": len(spans), "errors": errors}
        write_spans(
            os.path.join(directory, f"serve_trace-{pid}.jsonl"),
            spans,
            mode="a",
        )
    return counts


def _check(directory):
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli, ["slo", "check", directory, "--as-json"]
    )
    doc = json.loads(result.output[result.output.index("{"):])
    return result.exit_code, doc


def test_slo_drill_end_to_end(tmp_path):
    d = str(tmp_path)
    (tmp_path / "slos.toml").write_text(DRILL_SLOS)
    now = time.time()

    healthy = _write_phase(d, now, "healthy")

    # 1. clean traffic: inside SLO, check exits 0
    code, doc = _check(d)
    assert code == 0, doc
    assert doc["ok"] and doc["firing"] == 0

    # aggregated rollups match per-worker sums EXACTLY
    store = RollupStore(d)
    summary = summarize_rollup(store.merged())
    assert summary["requests"] == sum(
        w["requests"] for w in healthy.values()
    )
    assert summary["errors"] == 0

    # 2. the burst: first evaluation arms the alert (pending, exit 0)
    burst = _write_phase(d, now, "burst")
    code, doc = _check(d)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "pending"
    assert code == 0

    # per-worker sums still exact after the burst folds in
    summary = summarize_rollup(RollupStore(d).merged())
    expected_requests = sum(
        w["requests"] for w in healthy.values()
    ) + sum(w["requests"] for w in burst.values())
    expected_errors = sum(w["errors"] for w in burst.values())
    assert summary["requests"] == expected_requests
    assert summary["errors"] == expected_errors

    # 3. pending -> firing within ONE evaluation; check exits non-zero
    code, doc = _check(d)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "firing"
    assert code == 1
    assert not doc["ok"]

    # the persisted state machine agrees (what lifecycle reads)
    assert [a["id"] for a in slo.firing_alerts(d, severity="page")] == [
        "availability:fast"
    ]

    # 4. recovery: clean volume pulls every window under threshold —
    # firing -> resolved, and check exits 0 again
    _write_phase(d, now, "recovery")
    code, doc = _check(d)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "resolved"
    assert code == 0
    assert doc["ok"]

    # 5. and the cycle closes: resolved -> inactive
    code, doc = _check(d)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "inactive"
    assert code == 0


def test_slo_status_cli_renders(tmp_path):
    d = str(tmp_path)
    (tmp_path / "slos.toml").write_text(DRILL_SLOS)
    now = time.time()
    _write_phase(d, now, "healthy")
    runner = CliRunner()
    result = runner.invoke(gordo_tpu_cli, ["slo", "status", d])
    assert result.exit_code == 0, result.output
    assert "availability" in result.output
    assert "budget remaining" in result.output
    assert "inside SLO" in result.output


def test_slo_cli_rejects_missing_directory():
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli, ["slo", "check", "/nonexistent-drill-dir"]
    )
    assert result.exit_code != 0
    assert "No such directory" in result.output


def test_slo_cli_rejects_bad_config(tmp_path):
    (tmp_path / "slos.toml").write_text(
        '[[slo]]\nname = "x"\nobjective = "bogus"\ntarget = 0.9\n'
    )
    runner = CliRunner()
    result = runner.invoke(gordo_tpu_cli, ["slo", "status", str(tmp_path)])
    assert result.exit_code != 0
    assert "Bad SLO config" in result.output
