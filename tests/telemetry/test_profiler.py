"""Sampling-profiler coverage: it observes a busy thread's frames,
attributes self time to the active stage, costs the target thread no
instrumentation, and honors the per-request/env toggles."""

import threading
import time

import pytest

from gordo_tpu.telemetry.profiler import (
    SAMPLE_RATE_ENV,
    SamplingProfiler,
    sample_rate,
    should_profile,
)

pytestmark = pytest.mark.observability


def _busy_work(duration_s: float):
    """Spin in THIS frame so samples attribute here."""
    deadline = time.perf_counter() + duration_s
    total = 0
    while time.perf_counter() < deadline:
        total += sum(range(100))
    return total


def test_profiler_samples_the_target_thread():
    stage = {"name": "inference"}
    profiler = SamplingProfiler(interval_s=0.002)
    profiler.start(stage_getter=lambda: stage["name"])
    # busy-spin until enough samples landed (the sampling thread can be
    # starved on a loaded CI host — wall-clock alone is not a bound)
    deadline = time.perf_counter() + 10.0
    while profiler._samples < 12 and time.perf_counter() < deadline:
        _busy_work(0.1)
    report = profiler.stop()
    assert report["samples"] > 10
    assert report["interval_ms"] == 2.0
    assert report["duration_ms"] >= 100
    frames = report["frames"]
    assert frames, "no frames aggregated"
    # the busy loop dominates self time, attributed to the active stage
    top = frames[0]
    assert top["stage"] == "inference"
    assert "_busy_work" in top["function"]
    assert top["self_ms"] == pytest.approx(top["samples"] * 2.0)


def test_profiler_tracks_stage_transitions():
    stage = {"name": "a"}
    profiler = SamplingProfiler(interval_s=0.002)
    profiler.start(stage_getter=lambda: stage["name"])
    for name in ("a", "b"):
        stage["name"] = name
        deadline = time.perf_counter() + 10.0
        while (
            not any(key[0] == name for key in profiler._counts)
            and time.perf_counter() < deadline
        ):
            _busy_work(0.05)
    report = profiler.stop()
    stages = {frame["stage"] for frame in report["frames"]}
    assert {"a", "b"} <= stages


def test_profiler_profiles_another_thread_and_misses_after_exit():
    release = threading.Event()

    def target():
        release.wait(2.0)

    thread = threading.Thread(target=target)
    thread.start()
    profiler = SamplingProfiler(interval_s=0.002)
    profiler.start(thread_id=thread.ident)
    deadline = time.perf_counter() + 10.0
    while profiler._samples < 2 and time.perf_counter() < deadline:
        time.sleep(0.01)
    release.set()
    thread.join()
    # samples after thread death are "missed", not a crash
    while profiler._missed < 2 and time.perf_counter() < deadline:
        time.sleep(0.01)
    report = profiler.stop()
    assert report["samples"] > 0
    assert report["missed"] > 0


def test_profiler_stage_getter_failure_is_one_mislabeled_sample():
    calls = {"n": 0}

    def flaky_stage():
        calls["n"] += 1
        if calls["n"] % 2:
            raise RuntimeError("mid-mutation read")
        return "ok"

    profiler = SamplingProfiler(interval_s=0.002)
    profiler.start(stage_getter=flaky_stage)
    deadline = time.perf_counter() + 10.0
    while profiler._samples < 6 and time.perf_counter() < deadline:
        _busy_work(0.05)
    report = profiler.stop()
    assert report["samples"] > 5  # the profiler survived the raises
    assert {"-", "ok"} >= {f["stage"] for f in report["frames"]} or any(
        f["stage"] in ("-", "ok") for f in report["frames"]
    )


def test_report_truncates_to_max_frames():
    profiler = SamplingProfiler(interval_s=0.002)
    profiler._counts = {(f"s{i}", f"f{i}"): i + 1 for i in range(40)}
    profiler._samples = sum(range(1, 41))
    report = profiler.report(max_frames=5)
    assert len(report["frames"]) == 5
    assert report["truncated_frames"] == 35
    # heaviest first
    assert report["frames"][0]["samples"] == 40


def test_should_profile_explicit_param_wins(monkeypatch):
    monkeypatch.delenv(SAMPLE_RATE_ENV, raising=False)
    assert should_profile("1")
    assert should_profile("true")
    assert should_profile("device")
    assert not should_profile("0")
    assert not should_profile("off")
    assert not should_profile(None)  # no rate configured


def test_sample_rate_env(monkeypatch):
    monkeypatch.setenv(SAMPLE_RATE_ENV, "0.25")
    assert sample_rate() == 0.25
    monkeypatch.setenv(SAMPLE_RATE_ENV, "7")  # clamped
    assert sample_rate() == 1.0
    monkeypatch.setenv(SAMPLE_RATE_ENV, "not-a-number")
    assert sample_rate() == 0.0
    monkeypatch.setenv(SAMPLE_RATE_ENV, "1")
    assert should_profile(None)  # every request sampled at rate 1
