"""Trace-sink rotation: a months-lived process's telemetry footprint is
capped at ~(keep+1) * max_bytes per sink, and everything downstream
(readers, artifact discovery) understands rotated generations."""

import json
import os

import pytest

from gordo_tpu.telemetry import KEEP_ENV, MAX_BYTES_ENV, SpanRecorder
from gordo_tpu.telemetry.trace_analysis import read_trace

pytestmark = pytest.mark.observability


def _fill(rec, n, name="s"):
    for i in range(n):
        with rec.span(name, i=i, pad="x" * 200):
            pass


def test_sink_rotates_at_max_bytes(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    rec = SpanRecorder(sink_path=sink, max_bytes=4096, keep=2)
    _fill(rec, 60)
    rec.close()
    files = sorted(os.listdir(tmp_path))
    assert "trace.jsonl" in files or "trace.jsonl.1" in files
    assert "trace.jsonl.1" in files
    # never more than keep rotated generations
    rotated = [f for f in files if f.startswith("trace.jsonl.")]
    assert len(rotated) <= 2
    for name in rotated:
        assert json.loads((tmp_path / name).read_text().splitlines()[0])


def test_rotation_bounds_total_footprint(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    rec = SpanRecorder(sink_path=sink, max_bytes=2048, keep=1)
    _fill(rec, 300)
    rec.close()
    total = sum(
        (tmp_path / f).stat().st_size for f in os.listdir(tmp_path)
    )
    # keep+1 generations, each at most max_bytes plus one span of slop
    assert total < 3 * 2048


def test_keep_zero_truncates_instead_of_rotating(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    rec = SpanRecorder(sink_path=sink, max_bytes=2048, keep=0)
    _fill(rec, 100)
    rec.close()
    files = os.listdir(tmp_path)
    assert all(not f.startswith("trace.jsonl.") for f in files)


def test_zero_max_bytes_disables_rotation(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    rec = SpanRecorder(sink_path=sink, max_bytes=0, keep=3)
    _fill(rec, 100)
    rec.close()
    assert os.listdir(tmp_path) == ["trace.jsonl"]


def test_env_knobs_configure_rotation(tmp_path, monkeypatch):
    monkeypatch.setenv(MAX_BYTES_ENV, "4096")
    monkeypatch.setenv(KEEP_ENV, "1")
    rec = SpanRecorder(sink_path=str(tmp_path / "t.jsonl"))
    assert rec.max_bytes == 4096 and rec.keep == 1
    monkeypatch.setenv(MAX_BYTES_ENV, "garbage")
    rec2 = SpanRecorder(sink_path=str(tmp_path / "t2.jsonl"))
    assert rec2.max_bytes > 4096  # fell back to the default


def test_read_trace_spans_rotated_generations_oldest_first(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    # sized so 120 spans span ~3 generations, all inside keep=3
    rec = SpanRecorder(sink_path=sink, max_bytes=16384, keep=3)
    _fill(rec, 120)
    rec.close()
    spans = list(read_trace(sink))
    indices = [s["attributes"]["i"] for s in spans]
    assert indices == sorted(indices), "rotated files must read in order"
    assert len(indices) > 60  # rotation kept more than one file's worth


def test_rotated_trace_files_are_builder_droppings():
    from gordo_tpu.serializer import is_builder_dropping

    assert is_builder_dropping("build_trace.jsonl")
    assert is_builder_dropping("build_trace.jsonl.1")
    assert is_builder_dropping("serve_trace.jsonl")
    assert is_builder_dropping("serve_trace.jsonl.3")
    assert not is_builder_dropping("my-model")


def test_async_sink_rotates_and_flushes(tmp_path):
    sink = str(tmp_path / "trace.jsonl")
    rec = SpanRecorder(
        sink_path=sink, max_bytes=4096, keep=2, async_sink=True
    )
    _fill(rec, 80)
    rec.flush()
    files = sorted(os.listdir(tmp_path))
    assert any(f.startswith("trace.jsonl.") for f in files)
    rec.close()
