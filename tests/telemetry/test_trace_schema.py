"""Golden-schema contract for the JSONL span traces.

Downstream consumers — cost-model calibration (``planner.calibrate``),
``gordo-tpu trace``, the Prometheus span listener, external OTLP
shippers — parse these dicts by field name. A rename or type change
must fail HERE, not in a consumer three PRs later. The schema below is
the wire contract; extending it (new optional fields) is fine, breaking
it is a conscious decision that updates this file.
"""

import json

import pytest

from gordo_tpu.telemetry import SpanRecorder

pytestmark = pytest.mark.observability

#: required fields and types of EVERY span in build_trace.jsonl /
#: serve_trace.jsonl (the SpanRecorder wire shape)
SPAN_SCHEMA = {
    "name": str,
    "context": dict,
    "parent_id": (str, type(None)),
    "kind": str,
    "start_time": str,
    "end_time": str,
    "duration_ms": (int, float),
    "status": dict,
    "attributes": dict,
    "resource": dict,
}

CONTEXT_SCHEMA = {"trace_id": str, "span_id": str}

#: optional fields, checked when present
LINK_SCHEMA = {"context": dict}


def assert_span_schema(span: dict):
    for field, types in SPAN_SCHEMA.items():
        assert field in span, f"span missing {field!r}: {span}"
        assert isinstance(span[field], types), (field, span[field])
    for field, types in CONTEXT_SCHEMA.items():
        assert isinstance(span["context"][field], types)
    assert len(span["context"]["trace_id"]) == 32
    assert len(span["context"]["span_id"]) == 16
    assert span["status"]["status_code"] in ("OK", "ERROR")
    assert span["kind"] in ("internal", "event", "server")
    assert span["resource"]["service.name"]
    json.dumps(span)  # wire-serializable, always
    for link in span.get("links", []):
        assert isinstance(link["context"]["trace_id"], str)
        assert isinstance(link["context"]["span_id"], str)


def test_recorded_span_schema(tmp_path):
    sink = tmp_path / "t.jsonl"
    rec = SpanRecorder(sink_path=str(sink), retain_spans=True)
    with rec.span("device_program", program="fit", compile=True):
        pass
    with rec.span("serve_batch", size=3) as handle:
        handle.link("a" * 32, "b" * 16, name="m-1", queue_wait_ms=0.5)
    rec.event("machine_built", machine="m-1")
    rec.record("queue_wait", 0.003)
    rec.close()
    lines = [json.loads(l) for l in sink.read_text().splitlines()]
    assert len(lines) == 4
    for span in lines:
        assert_span_schema(span)
    batch = next(s for s in lines if s["name"] == "serve_batch")
    assert batch["links"][0]["attributes"]["name"] == "m-1"
    event = next(s for s in lines if s["kind"] == "event")
    assert event["duration_ms"] == 0


def test_error_span_schema():
    rec = SpanRecorder()
    with pytest.raises(RuntimeError):
        with rec.span("doomed"):
            raise RuntimeError("boom")
    (span,) = rec.finished()
    assert_span_schema(span)
    assert span["status"]["status_code"] == "ERROR"
    assert "boom" in span["status"]["description"]


def test_exported_request_trace_schema(tmp_path, monkeypatch):
    """The serving-side export path: request root span (kind=server),
    nested stage spans, and the profile span — the exact shapes
    ``gordo-tpu trace`` and the route bench consume."""
    from gordo_tpu import telemetry
    from gordo_tpu.telemetry import serving

    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "1")
    monkeypatch.setenv(telemetry.TRACE_DIR_ENV, str(tmp_path))
    serving.reset_serve_recorder()
    try:
        trace_id, span_id = "c" * 32, "d" * 16
        timing = SpanRecorder(service="gordo-tpu-server", trace_id=trace_id)
        timing.default_parent_id = span_id
        with timing.span("inference"):
            pass
        serving.export_request_trace(
            timing,
            span_id=span_id,
            parent_id="e" * 16,
            start=1_700_000_000.0,
            duration_s=0.25,
            attributes={
                "http.method": "POST",
                "http.route": "prediction",
                "http.status_code": 200,
                "gordo_name": "m-1",
                "revision": "123",
            },
            profile={
                "samples": 10,
                "interval_ms": 5.0,
                "duration_ms": 50.0,
                "frames": [
                    {
                        "stage": "inference",
                        "function": "x.py:f",
                        "samples": 9,
                        "self_ms": 45.0,
                    }
                ],
            },
        )
        recorder = serving.serve_recorder()
        recorder.flush()
        lines = [
            json.loads(l)
            for l in open(serving.serve_trace_path()).read().splitlines()
        ]
        by_name = {s["name"]: s for s in lines}
        assert set(by_name) == {"inference", "request", "profile"}
        for span in lines:
            assert_span_schema(span)
            assert span["context"]["trace_id"] == trace_id
        request = by_name["request"]
        assert request["kind"] == "server"
        assert request["context"]["span_id"] == span_id
        assert request["parent_id"] == "e" * 16
        assert request["duration_ms"] == 250.0
        assert request["attributes"]["http.status_code"] == 200
        # stage + profile spans nest under the request span
        assert by_name["inference"]["parent_id"] == span_id
        assert by_name["profile"]["parent_id"] == span_id
        assert by_name["profile"]["attributes"]["frames"][0]["self_ms"] == 45.0
    finally:
        serving.reset_serve_recorder()


@pytest.mark.stream
def test_stream_span_schema(tmp_path, monkeypatch):
    """The streaming plane's span vocabulary, golden-checked end to end:
    one ``stream_ingest`` per ingest POST, one enriched ``stream_score``
    per watermark flush (row accounting split, freshness lag numbers,
    the compact rows-weighted ``lag_hist``, predicted vs measured device
    time, and OTel links back to the drained ingests), and one
    ``stream_emit`` per event fan-out."""
    import numpy as np
    import pandas as pd

    from gordo_tpu import serve, telemetry
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.stream import (
        StreamConfig,
        StreamPlane,
        reset_stream_telemetry,
    )
    from gordo_tpu.telemetry import serving

    class EchoFleet:
        def model(self, name):
            return object()

        def loaded_specs(self):
            return {}

        def fleet_scores(self, inputs):
            return (
                {
                    name: (
                        np.zeros((len(X), 2)),
                        np.full(len(X), 0.5),
                    )
                    for name, X in inputs.items()
                },
                {},
            )

    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "1")
    monkeypatch.setenv(telemetry.TRACE_DIR_ENV, str(tmp_path))
    fleet = EchoFleet()
    monkeypatch.setattr(STORE, "route", lambda directory: directory)
    monkeypatch.setattr(STORE, "fleet", lambda directory: fleet)
    engine = serve.get_engine()
    serve.install_engine(None)
    serve.reset_stream_breakers()
    serving.reset_serve_recorder()
    reset_stream_telemetry()
    try:
        plane = StreamPlane(
            StreamConfig(
                ring_rows=16,
                window_rows=4,
                outbox_events=32,
                session_ttl_s=60.0,
                heartbeat_s=0.05,
                max_sessions=2,
                shed_retry_s=0.5,
            )
        )
        session = plane.session("p", "s1", str(tmp_path / "rev-a"))
        plane.ingest(
            session,
            {
                "m-1": pd.DataFrame({"t": [float(i) for i in range(4)]}),
                "m-2": pd.DataFrame({"t": [float(i) for i in range(4)]}),
            },
        )
        serving.serve_recorder().flush()
        lines = [
            json.loads(l)
            for l in open(serving.serve_trace_path()).read().splitlines()
        ]
        by_name = {s["name"]: s for s in lines}
        assert {"stream_ingest", "stream_score", "stream_emit"} <= set(
            by_name
        )
        for span in lines:
            assert_span_schema(span)
        ingest = by_name["stream_ingest"]
        assert ingest["attributes"]["stream"] == "s1"
        assert ingest["attributes"]["machines"] == 2
        assert ingest["attributes"]["rows"] == 8
        assert ingest["attributes"]["shed"] == 0
        assert ingest["attributes"]["errors"] == 0
        score = by_name["stream_score"]
        attrs = score["attributes"]
        assert attrs["stream"] == "s1"
        assert attrs["rows"] == 8
        assert attrs["rows_scored"] == 8
        assert attrs["rows_failed"] == 0
        assert attrs["windows"] == 2
        assert attrs["shed"] == 0
        assert attrs["revision"] == "rev-a"
        assert attrs["lag_p50_ms"] >= 0.0
        assert attrs["lag_max_ms"] >= attrs["lag_p50_ms"]
        assert attrs["lag_sum_ms"] >= 0.0
        assert isinstance(attrs["lag_hist"], list)
        assert sum(attrs["lag_hist"]) == 8  # rows-weighted
        assert attrs["device_ms"] >= 0.0
        assert "predicted_device_ms" in attrs
        # the flush links back to the ingest exchange it drained
        linked = [
            link["context"]["span_id"] for link in score.get("links") or []
        ]
        assert ingest["context"]["span_id"] in linked
        emit = by_name["stream_emit"]
        assert emit["attributes"]["stream"] == "s1"
        assert emit["attributes"]["events"] == 2
        assert emit["attributes"]["machines"] == 2
    finally:
        serving.reset_serve_recorder()
        serve.reset_stream_breakers()
        serve.install_engine(engine)
        reset_stream_telemetry()


def test_bench_gate_paths_match_committed_bench_docs():
    """Every gate spec path must resolve inside the committed baseline
    document it gates — a bench schema rename that would silently turn
    the regression gate into a no-op fails here."""
    import os

    from gordo_tpu.telemetry.benchgate import BASELINE_FILES, GATES, get_path

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    for bench, specs in GATES.items():
        baseline = os.path.join(repo_root, BASELINE_FILES[bench])
        if not os.path.exists(baseline):
            continue
        with open(baseline) as handle:
            doc = json.load(handle)
        assert doc.get("bench") == bench, baseline
        for spec in specs:
            assert get_path(doc, spec.path) is not None, (
                f"{BASELINE_FILES[bench]}: gate path {spec.path!r} "
                "resolves to nothing — schema drifted under the gate"
            )
