"""Span recorder unit coverage: nesting, thread safety, the JSONL sink,
listeners, compile/run program attribution, and env gating."""

import json
import threading

import pytest

from gordo_tpu import telemetry
from gordo_tpu.telemetry import (
    NULL_RECORDER,
    SpanRecorder,
    activate,
    enabled,
    get_recorder,
    program_span,
)

pytestmark = pytest.mark.observability


def test_span_records_duration_attributes_and_status():
    rec = SpanRecorder()
    with rec.span("work", machines=3) as handle:
        handle.set(found=7)
    (span,) = rec.finished("work")
    assert span["attributes"] == {"machines": 3, "found": 7}
    assert span["status"]["status_code"] == "OK"
    assert span["duration_ms"] >= 0
    assert span["context"]["trace_id"] == rec.trace_id
    assert span["parent_id"] is None
    assert span["kind"] == "internal"


def test_nested_spans_carry_parent_ids():
    rec = SpanRecorder()
    with rec.span("outer"):
        with rec.span("inner"):
            rec.event("marker", n=1)
    marker, inner, outer = rec.finished()
    assert outer["name"] == "outer" and outer["parent_id"] is None
    assert inner["parent_id"] == outer["context"]["span_id"]
    assert marker["parent_id"] == inner["context"]["span_id"]
    assert marker["kind"] == "event" and marker["duration_ms"] == 0


def test_exception_marks_span_error_and_propagates():
    rec = SpanRecorder()
    with pytest.raises(ValueError):
        with rec.span("doomed"):
            raise ValueError("boom")
    (span,) = rec.finished("doomed")
    assert span["status"]["status_code"] == "ERROR"
    assert "boom" in span["status"]["description"]


def test_jsonl_sink_is_line_per_span_and_durable(tmp_path):
    sink = tmp_path / "trace.jsonl"
    rec = SpanRecorder(sink_path=str(sink))
    with rec.span("a"):
        pass
    # durable the instant the span closes, before close()
    lines = sink.read_text().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["name"] == "a"
    rec.event("b")
    rec.close()
    assert [json.loads(l)["name"] for l in sink.read_text().splitlines()] == [
        "a",
        "b",
    ]


def test_sink_failure_never_raises(tmp_path):
    rec = SpanRecorder(
        sink_path=str(tmp_path / "nodir" / "x.jsonl"), retain_spans=True
    )
    with rec.span("still-works"):
        pass
    assert rec.finished("still-works")


def test_sink_backed_recorders_do_not_retain_by_default(tmp_path):
    """A build recorder's span stream is unbounded (hours of chunked CV
    phases and per-machine events); with a sink configured the JSONL
    file is the record and memory must stay flat."""
    sink = tmp_path / "t.jsonl"
    rec = SpanRecorder(sink_path=str(sink))
    assert not rec.retain_spans
    with rec.span("a"):
        pass
    assert rec.finished() == []
    assert json.loads(sink.read_text())["name"] == "a"
    # in-memory recorders (the server's per-request timing) retain
    assert SpanRecorder().retain_spans


def test_thread_spans_are_independent_roots():
    rec = SpanRecorder()
    results = []

    def worker(i):
        with rec.span("threaded", worker=i):
            results.append(i)

    with rec.span("main"):
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    threaded = rec.finished("threaded")
    assert len(threaded) == 4
    # pool threads do not run inside the main thread's span
    assert all(s["parent_id"] is None for s in threaded)


def test_listeners_called_per_span_and_never_fail_recording():
    rec = SpanRecorder()
    seen = []
    rec.add_listener(lambda s: seen.append(s["name"]))
    rec.add_listener(lambda s: 1 / 0)  # a broken listener is swallowed
    with rec.span("x"):
        pass
    rec.event("y")
    assert seen == ["x", "y"]
    assert len(rec.finished()) == 2


def test_durations_sum_per_name_in_first_seen_order():
    rec = SpanRecorder()
    for _ in range(2):
        with rec.span("alpha"):
            pass
    with rec.span("beta"):
        pass
    durations = rec.durations()
    assert list(durations) == ["alpha", "beta"]
    assert durations["alpha"] >= 0


def test_activate_scopes_the_global_recorder():
    rec = SpanRecorder()
    assert get_recorder() is NULL_RECORDER
    with activate(rec):
        assert get_recorder() is rec
        with get_recorder().span("inside"):
            pass
    assert get_recorder() is NULL_RECORDER
    assert rec.finished("inside")


def test_null_recorder_is_inert():
    with NULL_RECORDER.span("nope", a=1) as handle:
        handle.set(b=2)
    NULL_RECORDER.event("nope")
    assert NULL_RECORDER.finished() == []
    assert NULL_RECORDER.durations() == {}
    assert not NULL_RECORDER.enabled


def test_program_span_first_call_is_compile_then_run():
    telemetry.reset_seen_programs()
    rec = SpanRecorder()
    with activate(rec):
        with program_span("prog", ("spec", (8, 4)), members=2):
            pass
        with program_span("prog", ("spec", (8, 4)), members=2):
            pass
        with program_span("prog", ("spec", (16, 4))):  # new shape → compile
            pass
    flags = [
        (s["attributes"]["program"], s["attributes"]["compile"])
        for s in rec.finished("device_program")
    ]
    assert flags == [("prog", True), ("prog", False), ("prog", True)]


def test_program_registration_survives_inactive_recorder():
    """A program compiled while no recorder is active must still count
    as seen — a later traced call with the same signature is a cache
    hit, not a compile."""
    telemetry.reset_seen_programs()
    with program_span("p2", "sig"):
        pass  # NULL recorder active: nothing recorded, but registered
    rec = SpanRecorder()
    with activate(rec):
        with program_span("p2", "sig"):
            pass
    (span,) = rec.finished("device_program")
    assert span["attributes"]["compile"] is False


def test_enabled_env_gate(monkeypatch):
    monkeypatch.delenv(telemetry.TELEMETRY_ENV, raising=False)
    assert enabled()
    for value in ("0", "false", "off", "no", "False", " OFF "):
        monkeypatch.setenv(telemetry.TELEMETRY_ENV, value)
        assert not enabled()
    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "1")
    assert enabled()
