"""
Streaming-plane observability: stream span → rollup folding, the
freshness/integrity SLO objectives (including the pending→firing→
resolved drill over an injected lag stall), the bounded Prometheus
collector, the fleet-status stream section, and the trace analyzer's
stream-session breakdown.
"""

import json
import os

import pytest

from gordo_tpu.telemetry import slo
from gordo_tpu.telemetry.aggregate import (
    LATENCY_BUCKETS_MS,
    RollupStore,
    merge_rollups,
    new_histogram,
    summarize_rollup,
)

from .test_aggregate import NOW, iso, write_spans

pytestmark = [pytest.mark.stream, pytest.mark.observability]


def lag_hist_for(lag_ms: float, rows: int):
    """A compact span lag_hist: all ``rows`` at one lag value."""
    counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)
    slot = len(LATENCY_BUCKETS_MS)
    for i, edge in enumerate(LATENCY_BUCKETS_MS):
        if lag_ms <= edge:
            slot = i
            break
    counts[slot] = rows
    return counts


def stream_ingest_span(i, ts, rows=32, stream="s1"):
    return {
        "name": "stream_ingest",
        "context": {
            "trace_id": f"{i:032x}",
            "span_id": f"{i:016x}",
        },
        "parent_id": None,
        "kind": "internal",
        "start_time": iso(ts - 0.002),
        "end_time": iso(ts),
        "duration_ms": 2.0,
        "status": {"status_code": "OK"},
        "attributes": {
            "stream": stream,
            "machines": 1,
            "rows": rows,
            "shed": 0,
            "errors": 0,
        },
        "resource": {"service.name": "test"},
    }


def stream_score_span(
    i,
    ts,
    rows=32,
    rows_failed=0,
    shed=0,
    lag_ms=50.0,
    flush_ms=20.0,
    stream="s1",
):
    scored = rows - rows_failed
    return {
        "name": "stream_score",
        "context": {
            "trace_id": f"{i + 500:032x}",
            "span_id": f"{i + 500:016x}",
        },
        "parent_id": None,
        "kind": "internal",
        "start_time": iso(ts - flush_ms / 1000.0),
        "end_time": iso(ts),
        "duration_ms": flush_ms,
        "status": {"status_code": "OK"},
        "attributes": {
            "stream": stream,
            "machines": 1,
            "rows": rows,
            "rows_scored": scored,
            "rows_failed": rows_failed,
            "windows": max(1, rows // 32),
            "shed": shed,
            "revision": "rev-a",
            "lag_p50_ms": lag_ms,
            "lag_max_ms": lag_ms,
            "lag_hist": lag_hist_for(lag_ms, rows),
            "lag_sum_ms": lag_ms * rows,
            "predicted_device_ms": 1.5,
            "device_ms": 2.0,
        },
        "resource": {"service.name": "test"},
    }


# -- rollup folding -----------------------------------------------------------


def test_stream_spans_fold_into_rollup_stream_section(tmp_path):
    d = str(tmp_path)
    write_spans(
        os.path.join(d, "serve_trace.jsonl"),
        [
            stream_ingest_span(1, NOW, rows=64),
            stream_score_span(
                1, NOW + 1, rows=32, lag_ms=50.0, flush_ms=20.0
            ),
            stream_score_span(
                2, NOW + 2, rows=32, rows_failed=8, shed=4, lag_ms=200.0
            ),
        ],
    )
    store = RollupStore(d)
    store.aggregate()
    rollup = store.merged(since=NOW - 3600, until=NOW + 3600)
    stream = rollup["stream"]
    assert stream["rows_in"] == 64
    assert stream["rows_scored"] == 32 + 24
    assert stream["rows_failed"] == 8
    assert stream["rows_shed"] == 4
    assert stream["flushes"] == 2
    assert stream["windows"] == 2
    assert stream["flush_ms"]["count"] == 2
    # the lag histogram is rows-weighted: 64 rows across the two spans
    assert stream["lag_ms"]["count"] == 64
    assert stream["lag_ms"]["sum_ms"] == pytest.approx(
        50.0 * 32 + 200.0 * 32
    )
    # stream spans are not request stages
    assert "stream_score" not in rollup["stages"]
    assert "stream_ingest" not in rollup["stages"]

    summary = summarize_rollup(rollup)
    assert summary["stream"]["rows_in"] == 64
    assert summary["stream"]["flushes"] == 2
    assert summary["stream"]["lag_p95_ms"] > 0.0


def test_stream_section_merges_and_tolerates_pre_upgrade_rollups():
    from gordo_tpu.telemetry.aggregate import _empty_rollup

    a = _empty_rollup(NOW, 300)
    a["stream"]["rows_in"] = 10
    a["stream"]["flushes"] = 1
    legacy = _empty_rollup(NOW, 300)
    del legacy["stream"]  # a rollup written before this section existed
    merged = merge_rollups(a, legacy)
    assert merged["stream"]["rows_in"] == 10
    b = _empty_rollup(NOW, 300)
    b["stream"]["rows_in"] = 5
    b["stream"]["rows_shed"] = 2
    merge_rollups(a, b)
    assert a["stream"]["rows_in"] == 15
    assert a["stream"]["rows_shed"] == 2


# -- the SLO objectives -------------------------------------------------------


def freshness_spec(threshold_ms=100.0, target=0.95):
    return slo.SloSpec(
        name="stream-freshness",
        objective="stream_freshness",
        target=target,
        window="30d",
        window_s=30 * 86400.0,
        threshold_ms=threshold_ms,
    )


def integrity_spec(target=0.999):
    return slo.SloSpec(
        name="stream-integrity",
        objective="stream_integrity",
        target=target,
        window="30d",
        window_s=30 * 86400.0,
    )


def test_stream_objectives_require_threshold_and_parse(tmp_path):
    path = tmp_path / "slos.toml"
    path.write_text(
        '[[slo]]\nname = "f"\nobjective = "stream_freshness"\n'
        'target = 0.95\nthreshold_ms = 250.0\nwindow = "7d"\n'
        '[[slo]]\nname = "i"\nobjective = "stream_integrity"\n'
        'target = 0.99\nwindow = "7d"\n'
    )
    config = slo.load_slo_config(path=str(path))
    assert [s.objective for s in config.slos] == [
        "stream_freshness",
        "stream_integrity",
    ]
    path.write_text(
        '[[slo]]\nname = "f"\nobjective = "stream_freshness"\n'
        'target = 0.95\nwindow = "7d"\n'
    )
    with pytest.raises(ValueError, match="threshold_ms"):
        slo.load_slo_config(path=str(path))


def test_stream_bad_fractions_read_the_stream_section():
    rollup = {
        "stream": {
            "rows_in": 100,
            "rows_scored": 90,
            "rows_failed": 6,
            "rows_shed": 4,
            "flushes": 3,
            "windows": 3,
            "flush_ms": new_histogram(),
            "lag_ms": {
                "buckets_ms": list(LATENCY_BUCKETS_MS),
                "counts": [0] * (len(LATENCY_BUCKETS_MS) + 1),
                "count": 0,
                "sum_ms": 0.0,
            },
        }
    }
    lag = rollup["stream"]["lag_ms"]
    for lag_ms, rows in ((50.0, 75), (10_000.0, 25)):
        counts = lag_hist_for(lag_ms, rows)
        lag["counts"] = [a + b for a, b in zip(lag["counts"], counts)]
        lag["count"] += rows
        lag["sum_ms"] += lag_ms * rows
    fraction, total = slo.bad_fraction(freshness_spec(100.0), rollup)
    assert total == 100
    assert fraction == pytest.approx(0.25, abs=0.02)
    fraction, total = slo.bad_fraction(integrity_spec(), rollup)
    assert total == 100
    assert fraction == pytest.approx(0.10)
    # zero stream traffic never burns budget
    assert slo.bad_fraction(freshness_spec(), {}) == (0.0, 0)
    assert slo.bad_fraction(integrity_spec(), {}) == (0.0, 0)


def test_freshness_stall_drives_pending_to_firing_then_resolves(tmp_path):
    """The acceptance drill in miniature: a lag stall (every row scored
    10s late against a 100ms objective) pushes the freshness alert
    pending → firing — which `firing_alerts(severity='page')` surfaces,
    the exact gate the lifecycle supervisor's auto-promotion consults —
    and the alert resolves once the stall leaves the burn windows."""
    d = str(tmp_path)
    config_path = tmp_path / "slos.toml"
    config_path.write_text(
        '[[slo]]\nname = "stream-freshness"\n'
        'objective = "stream_freshness"\n'
        'target = 0.95\nthreshold_ms = 100.0\nwindow = "30d"\n'
    )
    config = slo.load_slo_config(path=str(config_path))
    write_spans(
        os.path.join(d, "serve_trace.jsonl"),
        [
            stream_ingest_span(i, NOW - 30 + i, rows=32)
            for i in range(4)
        ]
        + [
            stream_score_span(
                i, NOW - 28 + i, rows=32, lag_ms=10_000.0
            )
            for i in range(4)
        ],
    )
    doc = slo.evaluate(d, config=config, now=NOW)
    entry = doc["slos"][0]
    assert entry["objective"] == "stream_freshness"
    assert entry["bad_fraction"] == pytest.approx(1.0)
    assert entry["lag_p95_ms"] >= 5000.0
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["stream-freshness:fast"] == "pending"
    assert not doc["ok"] or doc["firing"] == 0  # pending, not yet firing

    doc = slo.evaluate(d, config=config, now=NOW + 60)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["stream-freshness:fast"] == "firing"
    assert doc["ok"] is False
    firing = slo.firing_alerts(d, severity="page")
    assert [a["id"] for a in firing] == ["stream-freshness:fast"]

    # the stall ages out of every burn window -> the page resolves and
    # the promotion gate opens again
    later = NOW + 40 * 86400.0
    doc = slo.evaluate(d, config=config, now=later)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["stream-freshness:fast"] == "resolved"
    assert doc["ok"] is True
    assert slo.firing_alerts(d, severity="page") == []


# -- Prometheus exposition ----------------------------------------------------


def test_stream_plane_collector_is_bounded_and_accurate(monkeypatch):
    pytest.importorskip("prometheus_client")
    import pandas as pd
    from prometheus_client.core import CollectorRegistry

    from gordo_tpu import stream as stream_pkg
    from gordo_tpu.server.prometheus.metrics import StreamPlaneCollector
    from gordo_tpu.stream import (
        StreamConfig,
        StreamPlane,
        install_plane,
        reset_stream_telemetry,
    )

    reset_stream_telemetry()
    plane = StreamPlane(
        StreamConfig(
            ring_rows=4096,
            window_rows=1_000_000,  # nothing flushes: rows stay pending
            outbox_events=8,
            session_ttl_s=60.0,
            heartbeat_s=0.05,
            max_sessions=4,
            shed_retry_s=0.5,
        )
    )
    install_plane(plane)
    try:
        session = plane.session("p", "s1", "/tmp/anchor")
        n_machines = 1000  # fleet-scale: must not appear in any label
        for m in range(n_machines):
            session.append_rows(
                f"m-{m}", pd.DataFrame({"t": [1.0, 2.0]})
            )
        session.channel("m-0").quarantine_notified = True
        stream_pkg.stream_telemetry().observe_ingest(2 * n_machines)
        stream_pkg.stream_telemetry().observe_flush(
            0.02,
            rows_scored=100,
            rows_failed=5,
            rows_shed=3,
            lags_ms=[40.0],
            lag_weights=[100],
        )

        registry = CollectorRegistry()
        registry.register(StreamPlaneCollector())
        families = {
            family.name: family for family in registry.collect()
        }
        assert families["gordo_stream_pending_rows"].samples[0].value == (
            2 * n_machines
        )
        assert (
            families["gordo_stream_quarantined_machines"]
            .samples[0]
            .value
            == 1
        )
        by_label = {
            sample.labels.get("state"): sample.value
            for sample in families["gordo_stream_sessions"].samples
        }
        assert by_label == {"active": 1, "tombstoned": 0}
        rows = {
            sample.labels["outcome"]: sample.value
            for sample in families["gordo_stream_rows"].samples
        }
        assert rows["in"] == 2 * n_machines
        assert rows["scored"] == 100
        assert rows["failed"] == 5
        assert rows["shed"] == 3
        # BOUNDED: total series count is a fixed constant — label values
        # are small enums, never machine or stream names
        all_samples = [
            sample
            for family in families.values()
            for sample in family.samples
        ]
        assert len(all_samples) < 100
        for sample in all_samples:
            for value in sample.labels.values():
                assert not value.startswith("m-")
        lag_buckets = [
            sample
            for sample in families[
                "gordo_stream_score_lag_ms"
            ].samples
            if sample.name.endswith("_bucket")
        ]
        assert lag_buckets[-1].labels["le"] == "+Inf"
        assert lag_buckets[-1].value == 100
    finally:
        install_plane(None)
        reset_stream_telemetry()


def test_stream_collector_rides_fleet_console_registration():
    pytest.importorskip("prometheus_client")
    from prometheus_client.core import CollectorRegistry

    from gordo_tpu.server.prometheus.metrics import (
        register_fleet_console_collectors,
    )

    registry = CollectorRegistry()
    register_fleet_console_collectors(registry)
    names = {family.name for family in registry.collect()}
    assert "gordo_stream_rows" in names
    assert "gordo_stream_score_lag_ms" in names
    # idempotent per registry (the WeakSet guard)
    register_fleet_console_collectors(registry)


# -- fleet-status + trace surfaces --------------------------------------------


def test_fleet_status_document_carries_stream_section(tmp_path):
    import pandas as pd

    from gordo_tpu.stream import (
        StreamConfig,
        StreamPlane,
        install_plane,
        reset_stream_telemetry,
        stream_plane_section,
    )
    from gordo_tpu.telemetry.fleet_health import (
        fleet_status_document,
        render_fleet_status,
    )

    reset_stream_telemetry()
    plane = StreamPlane(
        StreamConfig(
            ring_rows=64,
            window_rows=1_000_000,
            outbox_events=8,
            session_ttl_s=60.0,
            heartbeat_s=0.05,
            max_sessions=4,
            shed_retry_s=0.5,
        )
    )
    install_plane(plane)
    try:
        session = plane.session("p", "s1", str(tmp_path))
        session.append_rows("m-1", pd.DataFrame({"t": [1.0, 2.0, 3.0]}))
        # callers inject the section (telemetry never imports the plane)
        doc = fleet_status_document(
            str(tmp_path), stream=stream_plane_section()
        )
        stream = doc["stream"]
        assert stream["sessions_active"] == 1
        assert stream["accounting"]["rows_in"] == 3
        assert stream["accounting"]["rows_pending"] == 3
        assert stream["accounting"]["gap"] == 0
        rendered = render_fleet_status(doc)
        assert "Stream:" in rendered
        assert "3 in" in rendered
    finally:
        install_plane(None)
        reset_stream_telemetry()
    # no plane installed -> the section degrades to None (CLI process)
    assert stream_plane_section() is None
    assert (
        fleet_status_document(str(tmp_path), stream=stream_plane_section())[
            "stream"
        ]
        is None
    )


def test_trace_analyzer_stream_breakdown(tmp_path):
    from gordo_tpu.telemetry.trace_analysis import (
        analyze_trace,
        render_analysis,
    )

    path = os.path.join(str(tmp_path), "serve_trace.jsonl")
    ingest = stream_ingest_span(1, NOW, rows=64)
    score = stream_score_span(1, NOW + 1, rows=64, lag_ms=80.0)
    score["links"] = [
        {
            "context": {
                "trace_id": ingest["context"]["trace_id"],
                "span_id": ingest["context"]["span_id"],
            },
            "attributes": {},
        }
    ]
    emit = dict(
        stream_ingest_span(3, NOW + 1, rows=0),
        name="stream_emit",
        attributes={"stream": "s1", "events": 2, "machines": 2},
    )
    write_spans(path, [ingest, score, emit])
    doc = analyze_trace(path)
    breakdown = doc["stream_breakdown"]
    entry = breakdown["streams"]["s1"]
    assert entry["rows_in"] == 64
    assert entry["rows_scored"] == 64
    assert entry["flushes"] == 1
    assert entry["linked_ingests"] == 1
    assert entry["lag_p50_ms"] == pytest.approx(80.0)
    assert entry["device_p50_ms"] == pytest.approx(2.0)
    assert entry["predicted_device_p50_ms"] == pytest.approx(1.5)
    assert [step["stage"] for step in entry["critical_path"]] == [
        "stream_ingest",
        "stream_score",
        "stream_emit",
    ]
    assert breakdown["totals"]["rows_in"] == 64
    rendered = render_analysis(doc)
    assert "Stream sessions: 1" in rendered
    assert "critical path (s1, median)" in rendered
    # stream spans never pollute the request stage partition
    assert doc["request_breakdown"] is None
