"""W3C trace-context unit coverage: traceparent parse/format, the
contextvar binding + log stamping, and the recorder's trace-identity
extensions (explicit trace id, default parent, links, emit)."""

import logging

import pytest

from gordo_tpu.telemetry import (
    SpanRecorder,
    bind_trace,
    current_trace_id,
    format_traceparent,
    parse_traceparent,
    new_span_id,
    new_trace_id,
)
from gordo_tpu.telemetry.tracing import TraceIdFilter

pytestmark = pytest.mark.observability

TRACE = "0af7651916cd43dd8448eb211c80319c"
SPAN = "b7ad6b7169203331"


def test_parse_traceparent_roundtrip():
    header = format_traceparent(TRACE, SPAN)
    assert header == f"00-{TRACE}-{SPAN}-01"
    ctx = parse_traceparent(header)
    assert ctx.trace_id == TRACE and ctx.span_id == SPAN


def test_parse_traceparent_rejects_malformed():
    for bad in (
        None,
        "",
        "garbage",
        "00-short-span-01",
        f"00-{'0' * 32}-{SPAN}-01",  # all-zero trace id is invalid
        f"00-{TRACE}-{'0' * 16}-01",  # all-zero span id is invalid
        f"ff-{TRACE}-{SPAN}-01",  # unknown version
        f"00-{TRACE.upper()}-{SPAN}-XX",
    ):
        assert parse_traceparent(bad) is None, bad


def test_parse_traceparent_tolerates_case_and_whitespace():
    header = f"  00-{TRACE.upper()}-{SPAN.upper()}-01  "
    ctx = parse_traceparent(header)
    assert ctx is not None and ctx.trace_id == TRACE


def test_id_shapes():
    assert len(new_trace_id()) == 32
    assert len(new_span_id()) == 16
    assert new_trace_id() != new_trace_id()


def test_bind_trace_scopes_the_contextvar():
    assert current_trace_id() == ""
    with bind_trace(TRACE):
        assert current_trace_id() == TRACE
        with bind_trace("b" * 32):
            assert current_trace_id() == "b" * 32
        assert current_trace_id() == TRACE
    assert current_trace_id() == ""


def test_trace_id_filter_stamps_records():
    record = logging.LogRecord("t", logging.INFO, "f", 1, "msg", (), None)
    filt = TraceIdFilter()
    assert filt.filter(record)
    assert record.trace_id == "-"
    with bind_trace(TRACE):
        record2 = logging.LogRecord("t", logging.INFO, "f", 1, "msg", (), None)
        filt.filter(record2)
        assert record2.trace_id == TRACE


def test_log_record_factory_stamps_in_request_messages():
    """install_trace_log_stamping works process-wide through the record
    factory — a CHILD module logger's messages carry the bound trace id
    (a plain logger filter would not inherit to children)."""
    from gordo_tpu.telemetry.tracing import install_trace_log_stamping

    install_trace_log_stamping()
    child = logging.getLogger("gordo_tpu.some.deep.module")
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = Capture()
    child.addHandler(handler)
    try:
        with bind_trace(TRACE):
            child.warning("inside request %s", "x")
        child.warning("outside request")
    finally:
        child.removeHandler(handler)
    inside, outside = records
    assert f"trace_id={TRACE}" in inside.getMessage()
    assert inside.trace_id == TRACE
    assert "trace_id=" not in outside.getMessage()
    assert outside.trace_id == "-"


# -- recorder trace-identity extensions --------------------------------------


def test_recorder_adopts_explicit_trace_id():
    rec = SpanRecorder(trace_id=TRACE)
    with rec.span("stage"):
        pass
    (span,) = rec.finished()
    assert span["context"]["trace_id"] == TRACE


def test_default_parent_id_roots_spans_under_the_request_span():
    rec = SpanRecorder(trace_id=TRACE)
    rec.default_parent_id = SPAN
    with rec.span("outer"):
        with rec.span("inner"):
            pass
    rec.record("external", 0.01)
    rec.event("mark")
    inner, outer, external, mark = (
        rec.finished("inner")[0],
        rec.finished("outer")[0],
        rec.finished("external")[0],
        rec.finished("mark")[0],
    )
    # top-level spans parent onto the request span; nesting still wins
    assert outer["parent_id"] == SPAN
    assert inner["parent_id"] == outer["context"]["span_id"]
    assert external["parent_id"] == SPAN
    assert mark["parent_id"] == SPAN


def test_span_links_carry_foreign_trace_context():
    rec = SpanRecorder()
    with rec.span("serve_batch") as handle:
        handle.link(TRACE, SPAN, name="machine-1", queue_wait_ms=1.5)
        handle.link("c" * 32, "d" * 16)
    (span,) = rec.finished()
    assert span["links"][0]["context"] == {
        "trace_id": TRACE,
        "span_id": SPAN,
    }
    assert span["links"][0]["attributes"]["name"] == "machine-1"
    assert "attributes" not in span["links"][1]
    # spans without links stay link-free (schema stability)
    with rec.span("plain"):
        pass
    assert "links" not in rec.finished("plain")[0]


def test_emit_records_prebuilt_spans(tmp_path):
    import json

    sink = tmp_path / "t.jsonl"
    shared = SpanRecorder(sink_path=str(sink))
    request = SpanRecorder(trace_id=TRACE)
    with request.span("stage"):
        pass
    for span in request.finished():
        shared.emit(span)
    written = json.loads(sink.read_text().splitlines()[0])
    # the emitted span keeps ITS trace id, not the shared recorder's
    assert written["context"]["trace_id"] == TRACE
