"""bench-check regression-gate coverage: metric evaluation semantics,
tolerance scaling, and the CLI exiting non-zero on an injected
synthetic regression (the acceptance drill)."""

import json

import pytest

from gordo_tpu.telemetry.benchgate import (
    GATES,
    MetricSpec,
    compare,
    compare_files,
    get_path,
    render_report,
)

pytestmark = pytest.mark.observability


BASELINE = {
    "bench": "route-observability",
    "route": {
        "throughput_rps": 20.0,
        "p50_ms": 700.0,
        "attribution_coverage": 0.95,
        "stages": {"response_assemble": {"p50_ms": 1.0}},
    },
    "scoring_overhead": {"overhead_us_per_request": 20.0},
    # the columnar-wire acceptance set (PR 12), tightened by the
    # device-resident ingest subsystem (PR 19: gap budget 3.0 -> 1.5,
    # plus the decode+staging absolute budget)
    "route_gap_p50_ratio": 1.2,
    "ingest_p50_ms": 2.0,
    "route_batched_vs_unbatched": 0.95,
}


def _candidate(**overrides):
    doc = json.loads(json.dumps(BASELINE))
    for path, value in overrides.items():
        node = doc
        parts = path.split(".")
        for part in parts[:-1]:
            node = node[part]
        node[parts[-1]] = value
    return doc


def test_get_path():
    assert get_path(BASELINE, "route.p50_ms") == 700.0
    assert get_path(BASELINE, "route.missing") is None
    assert get_path(BASELINE, "nope.deeper") is None


def test_identical_run_passes():
    report = compare(BASELINE, _candidate())
    assert report["ok"] and report["regressions"] == 0


def test_within_tolerance_passes():
    report = compare(BASELINE, _candidate(**{"route.throughput_rps": 17.0}))
    assert report["ok"]  # -15% vs 25% tolerance


def test_throughput_regression_fails():
    report = compare(BASELINE, _candidate(**{"route.throughput_rps": 10.0}))
    assert not report["ok"]
    (failure,) = [r for r in report["results"] if r["status"] == "regression"]
    assert failure["path"] == "route.throughput_rps"


def test_latency_regression_fails():
    report = compare(BASELINE, _candidate(**{"route.p50_ms": 1500.0}))
    assert not report["ok"]


def test_budget_bound_is_baseline_independent():
    report = compare(
        BASELINE,
        _candidate(**{"scoring_overhead.overhead_us_per_request": 100.0}),
    )
    assert not report["ok"]
    failure = next(r for r in report["results"] if r["status"] == "regression")
    assert "budget" in failure["detail"]


def test_tolerance_scale_loosens_the_gate():
    candidate = _candidate(**{"route.throughput_rps": 12.0})  # -40%
    assert not compare(BASELINE, candidate)["ok"]
    assert compare(BASELINE, candidate, tolerance_scale=2.0)["ok"]


def test_tolerance_scale_applies_to_budget_bounds_too():
    """--tolerance promises 'twice as lenient' for EVERY gate; a budget
    metric (the noisiest kind — wall-clock overhead deltas) must not
    veto the loosening."""
    candidate = _candidate(
        **{"scoring_overhead.overhead_us_per_request": 90.0}
    )
    assert not compare(BASELINE, candidate)["ok"]  # budget is 60
    assert compare(BASELINE, candidate, tolerance_scale=2.0)["ok"]  # 120


def test_min_bound_floor_and_scaling():
    """min_bound: an absolute floor (the route-level batching parity
    gate); --tolerance DIVIDES the floor (more lenient = lower)."""
    candidate = _candidate(**{"route_batched_vs_unbatched": 0.5})
    report = compare(BASELINE, candidate)
    assert not report["ok"]
    failure = next(
        r for r in report["results"] if r["status"] == "regression"
    )
    assert "floor" in failure["detail"]
    assert compare(BASELINE, candidate, tolerance_scale=1.5)["ok"]  # 0.4


def test_missing_candidate_metric_is_a_regression():
    candidate = _candidate()
    del candidate["route"]["p50_ms"]
    report = compare(BASELINE, candidate)
    assert not report["ok"]


def test_missing_baseline_metric_is_skipped_not_failed():
    baseline = json.loads(json.dumps(BASELINE))
    del baseline["route"]["attribution_coverage"]
    report = compare(baseline, _candidate())
    assert report["ok"]
    assert any(r["status"] == "skipped" for r in report["results"])


def test_bench_mismatch_is_an_error():
    with pytest.raises(ValueError, match="bench mismatch"):
        compare(BASELINE, {"bench": "lifecycle-hot-swap"})


def test_unknown_bench_is_an_error():
    with pytest.raises(ValueError, match="no gate specs"):
        compare({"bench": "x"}, {"bench": "x"})


def test_truthy_spec():
    specs = [MetricSpec("flag", "ok", "truthy")]
    assert compare({"ok": True}, {"ok": True}, specs=specs)["ok"]
    assert not compare({"ok": True}, {"ok": False}, specs=specs)["ok"]


def test_render_report_names_the_failure():
    report = compare(BASELINE, _candidate(**{"route.throughput_rps": 1.0}))
    report["baseline"], report["candidate"] = "b.json", "c.json"
    text = render_report(report)
    assert "FAIL" in text and "throughput" in text
    assert "regression" in text


def test_every_gate_has_a_baseline_file():
    from gordo_tpu.telemetry.benchgate import BASELINE_FILES

    assert set(GATES) == set(BASELINE_FILES)


# -- the CLI drill: injected synthetic regression → non-zero exit ------------


def _write(path, doc):
    with open(path, "w") as handle:
        json.dump(doc, handle)


def test_bench_check_cli_gates_synthetic_regression(tmp_path):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import bench_check

    runner = CliRunner()
    baseline = tmp_path / "BENCH_ROUTE.json"
    _write(baseline, BASELINE)

    good = tmp_path / "fresh_good.json"
    _write(good, _candidate(**{"route.throughput_rps": 21.0}))
    result = runner.invoke(bench_check, [str(good), "--baseline", str(baseline)])
    assert result.exit_code == 0, result.output

    # injected regression: throughput halves -> the gate must trip
    bad = tmp_path / "fresh_bad.json"
    _write(bad, _candidate(**{"route.throughput_rps": 8.0}))
    result = runner.invoke(bench_check, [str(bad), "--baseline", str(baseline)])
    assert result.exit_code != 0
    assert "FAIL" in result.output

    # --report-only always exits 0 (the CI visibility mode)
    result = runner.invoke(
        bench_check, [str(bad), "--baseline", str(baseline), "--report-only"]
    )
    assert result.exit_code == 0, result.output
    assert "FAIL" in result.output

    # --as-json emits the machine-readable report
    result = runner.invoke(
        bench_check,
        [str(bad), "--baseline", str(baseline), "--as-json", "--report-only"],
    )
    doc = json.loads(result.output)
    assert doc["regressions"] >= 1


def test_bench_check_cli_finds_committed_baseline_beside_candidate(tmp_path):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import bench_check

    _write(tmp_path / "BENCH_ROUTE.json", BASELINE)
    fresh = tmp_path / "fresh.json"
    _write(fresh, _candidate())
    result = CliRunner().invoke(bench_check, [str(fresh)])
    assert result.exit_code == 0, result.output
    assert "BENCH_ROUTE.json" in result.output
