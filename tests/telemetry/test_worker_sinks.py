"""The multi-worker sink split: pid-suffixed sink paths under gunicorn,
and the read-merge every surface does over them — the regression for
N workers silently overwriting each other's ``fleet_health.json`` /
racing each other's ``serve_trace.jsonl`` rotations."""

import json
import os

import pytest

from gordo_tpu.telemetry import fleet_health
from gordo_tpu.telemetry.fleet_health import (
    FleetHealthLedger,
    load_merged_health,
    merge_health_documents,
)
from gordo_tpu.telemetry.recorder import worker_sink_path, worker_sinks_enabled
from gordo_tpu.telemetry.trace_analysis import analyze_trace, trace_bases

from .test_aggregate import NOW, request_span, stage_span, write_spans

pytestmark = [pytest.mark.slo, pytest.mark.fleet_health]


@pytest.fixture(autouse=True)
def _no_multiproc(monkeypatch):
    monkeypatch.delenv("PROMETHEUS_MULTIPROC_DIR", raising=False)
    monkeypatch.delenv("prometheus_multiproc_dir", raising=False)
    monkeypatch.delenv("GORDO_TPU_WORKER_SINKS", raising=False)


# -- the switch ---------------------------------------------------------------


def test_worker_sinks_default_off_single_process():
    assert not worker_sinks_enabled()
    assert worker_sink_path("/x/serve_trace.jsonl") == "/x/serve_trace.jsonl"


def test_worker_sinks_auto_on_under_multiproc(monkeypatch, tmp_path):
    monkeypatch.setenv("PROMETHEUS_MULTIPROC_DIR", str(tmp_path))
    assert worker_sinks_enabled()
    suffixed = worker_sink_path("/x/serve_trace.jsonl")
    assert suffixed == f"/x/serve_trace-{os.getpid()}.jsonl"
    # explicit off overrides the auto-detection
    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "0")
    assert not worker_sinks_enabled()


def test_worker_sinks_explicit_on(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "1")
    assert worker_sink_path("/x/fleet_health.json") == (
        f"/x/fleet_health-{os.getpid()}.json"
    )


def test_serve_trace_path_gets_suffix(monkeypatch, tmp_path):
    from gordo_tpu.telemetry.serving import serve_trace_path

    monkeypatch.setenv("GORDO_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "1")
    assert serve_trace_path() == os.path.join(
        str(tmp_path), f"serve_trace-{os.getpid()}.jsonl"
    )


def test_ledger_path_gets_suffix(monkeypatch, tmp_path):
    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "1")
    ledger = FleetHealthLedger(directory=str(tmp_path))
    assert ledger.path == os.path.join(
        str(tmp_path), f"fleet_health-{os.getpid()}.json"
    )


# -- the health merge ---------------------------------------------------------


def _worker_ledger_doc(requests, errors, rows=0, residual=None):
    ledger = FleetHealthLedger(directory=None)
    for i in range(requests):
        ledger.record_request("m-1", error=i < errors)
    if rows:
        ledger.record_scores("m-1", rows, residual, write=False)
    return ledger.document()


def test_merge_health_documents_sums_red_counts(tmp_path):
    """The satellite regression: 3 simulated workers' snapshots —
    aggregated RED counts == sum of per-worker counts."""
    per_worker = [(100, 5), (200, 0), (50, 2)]
    docs = []
    for pid, (requests, errors) in zip((9001, 9002, 9003), per_worker):
        doc = _worker_ledger_doc(requests, errors)
        path = tmp_path / f"fleet_health-{pid}.json"
        path.write_text(json.dumps(doc))
        docs.append(doc)
    merged = load_merged_health(str(tmp_path))
    machine = merged["machines"]["m-1"]
    assert machine["serving"]["requests"] == sum(r for r, _ in per_worker)
    assert machine["serving"]["errors"] == sum(e for _, e in per_worker)
    summary = merged["summary"]
    assert summary["requests"] == sum(r for r, _ in per_worker)
    assert summary["errors"] == sum(e for _, e in per_worker)
    assert merged["workers_merged"] == 3


def test_merge_weights_residual_mean_by_rows():
    docs = [
        _worker_ledger_doc(10, 0, rows=100, residual=1.0),
        _worker_ledger_doc(10, 0, rows=300, residual=5.0),
    ]
    merged = merge_health_documents(docs)
    residual = merged["machines"]["m-1"]["serving"]["residual_mean"]
    assert residual == pytest.approx((1.0 * 100 + 5.0 * 300) / 400)


def test_merge_newest_state_section_wins():
    old = _worker_ledger_doc(1, 0)
    new = _worker_ledger_doc(1, 0)
    old["machines"]["m-1"]["drift"].update(
        {"drifted": True, "evaluated_at": "2026-01-01T00:00:00+00:00"}
    )
    new["machines"]["m-1"]["drift"].update(
        {"drifted": False, "evaluated_at": "2026-02-01T00:00:00+00:00"}
    )
    merged = merge_health_documents([old, new])
    assert merged["machines"]["m-1"]["drift"]["drifted"] is False
    # order independence: the newest stamp wins either way
    merged = merge_health_documents([new, old])
    assert merged["machines"]["m-1"]["drift"]["drifted"] is False


def test_merge_recomputes_health_and_summary():
    doc = _worker_ledger_doc(100, 50)  # heavy error rate
    merged = merge_health_documents([doc])
    machine = merged["machines"]["m-1"]
    assert machine["health"]["score"] < 1.0
    assert merged["summary"]["machines"] == 1


def test_fleet_status_document_merges_worker_snapshots(tmp_path, monkeypatch):
    """The joined console over a dir where 3 workers snapshotted."""
    from gordo_tpu.telemetry import fleet_status_document

    monkeypatch.setenv("GORDO_TPU_WORKER_SINKS", "1")
    fleet_health.reset_ledgers()
    try:
        for pid, requests in zip((9001, 9002), (10, 20)):
            doc = _worker_ledger_doc(requests, 0)
            (tmp_path / f"fleet_health-{pid}.json").write_text(
                json.dumps(doc)
            )
        # plus THIS process's live ledger, which has persisted its own
        # pid-suffixed snapshot — the live doc must not double-count
        # with its own file
        ledger = fleet_health.ledger_for(str(tmp_path))
        for _ in range(5):
            ledger.record_request("m-1")
        ledger.flush()
        doc = fleet_status_document(str(tmp_path))
        assert doc["health"]["machines"]["m-1"]["serving"]["requests"] == 35
        assert doc["health"]["workers_merged"] == 3
    finally:
        fleet_health.reset_ledgers()


# -- the trace merge ----------------------------------------------------------


def test_trace_analysis_read_merges_worker_sinks(tmp_path):
    d = str(tmp_path)
    total = 0
    for pid in (7001, 7002, 7003):
        spans = []
        for i in range(10):
            spans.append(
                request_span(i, NOW + i, wall_ms=100.0, trace_prefix=pid)
            )
            spans.append(stage_span(i, NOW + i, trace_prefix=pid))
            total += 1
        write_spans(os.path.join(d, f"serve_trace-{pid}.jsonl"), spans)
    bases = trace_bases(d, "serve_trace.jsonl")
    assert len(bases) == 3
    doc = analyze_trace(bases)
    assert doc["span_summary"]["request"]["count"] == total
    assert doc["request_breakdown"]["requests"] == total


def test_trace_since_skips_cold_generations(tmp_path, monkeypatch):
    from gordo_tpu.telemetry import trace_analysis

    d = str(tmp_path)
    base = os.path.join(d, "serve_trace.jsonl")
    old = [request_span(i, NOW - 7 * 86400) for i in range(5)]
    new = [request_span(100 + i, NOW) for i in range(3)]
    write_spans(base + ".1", old)
    write_spans(base, old + new)
    # age the rotated generation's mtime a week back
    os.utime(base + ".1", (NOW - 7 * 86400, NOW - 7 * 86400))

    opened = []
    original_open = open

    def counting_open(path, *args, **kwargs):
        opened.append(path)
        return original_open(path, *args, **kwargs)

    monkeypatch.setattr("builtins.open", counting_open)
    doc = trace_analysis.analyze_trace(base, since_ts=NOW - 3600)
    # the week-old generation was never opened, and only the in-window
    # spans were analyzed
    assert not any(str(p).endswith(".1") for p in opened)
    assert doc["span_summary"]["request"]["count"] == 3
