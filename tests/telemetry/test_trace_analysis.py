"""Trace-analysis coverage: span summaries, the request breakdown with
attribution coverage, profile-frame aggregation, and the ``gordo-tpu
trace`` CLI over a synthetic serve trace."""

import json

import pytest

from gordo_tpu.telemetry.trace_analysis import (
    analyze_trace,
    percentile,
    read_trace,
    render_analysis,
    request_breakdown,
    summarize_spans,
    top_profile_frames,
)

pytestmark = pytest.mark.observability


def _span(name, duration_ms, trace_id, span_id, parent_id=None, kind="internal",
          attributes=None, **extra):
    return {
        "name": name,
        "context": {"trace_id": trace_id, "span_id": span_id},
        "parent_id": parent_id,
        "kind": kind,
        "start_time": "2026-01-01T00:00:00+00:00",
        "end_time": "2026-01-01T00:00:01+00:00",
        "duration_ms": duration_ms,
        "status": {"status_code": "OK"},
        "attributes": attributes or {},
        "resource": {"service.name": "test"},
        **extra,
    }


def _request(i, wall_ms, stages):
    trace_id = f"{i:032x}"
    span_id = f"{i:016x}"
    spans = [
        _span("request", wall_ms, trace_id, span_id, kind="server")
    ]
    for j, (stage, ms) in enumerate(stages.items()):
        spans.append(
            _span(stage, ms, trace_id, f"{i}{j:015x}", parent_id=span_id)
        )
    return spans


@pytest.fixture
def synthetic_trace(tmp_path):
    spans = []
    # 9 well-instrumented requests + 1 with a big unattributed gap
    for i in range(1, 10):
        wall = 100.0 + i
        spans.extend(
            _request(
                i,
                wall,
                {
                    "data_decode": 30.0,
                    "inference": 40.0 + i,
                    "serialize": 25.0,
                },
            )
        )
    spans.extend(_request(10, 500.0, {"inference": 50.0}))
    # a profile span and a batch span (neither is a request stage)
    spans.append(
        _span(
            "profile",
            50.0,
            f"{1:032x}",
            "f" * 16,
            parent_id=f"{1:016x}",
            attributes={
                "frames": [
                    {"stage": "inference", "function": "a.py:f", "samples": 8,
                     "self_ms": 40.0},
                    {"stage": "serialize", "function": "b.py:g", "samples": 2,
                     "self_ms": 10.0},
                ]
            },
        )
    )
    spans.append(_span("serve_batch", 12.0, "e" * 32, "e" * 16))
    path = tmp_path / "serve_trace.jsonl"
    with open(path, "w") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
        f.write("not json\n")  # torn tail line must be skipped
    return str(path)


def test_percentile_nearest_rank():
    values = sorted(float(v) for v in range(1, 101))
    assert percentile(values, 0.5) == pytest.approx(51.0, abs=1.0)
    assert percentile(values, 0.99) == pytest.approx(99.0, abs=1.0)
    assert percentile([], 0.5) == 0.0


def test_read_trace_skips_torn_lines(synthetic_trace):
    spans = list(read_trace(synthetic_trace))
    assert all(isinstance(s, dict) for s in spans)
    assert any(s["name"] == "request" for s in spans)


def test_summarize_spans(synthetic_trace):
    summary = summarize_spans(read_trace(synthetic_trace))
    assert summary["request"]["count"] == 10
    assert summary["inference"]["count"] == 10
    assert summary["serve_batch"]["p50_ms"] == 12.0


def test_request_breakdown_attribution(synthetic_trace):
    breakdown = request_breakdown(read_trace(synthetic_trace))
    assert breakdown["requests"] == 10
    # median request is one of the ~105ms well-instrumented ones
    assert 100 <= breakdown["walltime_p50_ms"] <= 110
    stages = breakdown["stages"]
    assert set(stages) == {"data_decode", "inference", "serialize"}
    # ~95ms attributed out of ~105ms walltime for 9 of 10 requests
    assert 0.85 <= breakdown["attribution_coverage"] <= 1.0
    # the profile span is NOT a stage
    assert "profile" not in stages
    # critical path is the median request's stages, longest first
    path_stages = [step["stage"] for step in breakdown["critical_path"]]
    assert path_stages[0] == "inference"
    assert set(path_stages) == set(stages)


def test_request_breakdown_none_without_requests(tmp_path):
    path = tmp_path / "t.jsonl"
    path.write_text(json.dumps(_span("build_phase", 5.0, "a" * 32, "b" * 16)) + "\n")
    assert request_breakdown(read_trace(str(path))) is None


def test_top_profile_frames(synthetic_trace):
    frames = top_profile_frames(read_trace(synthetic_trace))
    assert frames[0]["function"] == "a.py:f"
    assert frames[0]["self_ms"] == 40.0
    assert frames[0]["stage"] == "inference"


def test_analyze_and_render(synthetic_trace):
    doc = analyze_trace(synthetic_trace)
    text = render_analysis(doc)
    assert "attribution coverage" in text
    assert "critical path" in text
    assert "inference" in text
    json.dumps(doc)  # --as-json must always serialize


def test_trace_cli(synthetic_trace, tmp_path):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import trace as trace_cmd

    runner = CliRunner()
    # file target
    result = runner.invoke(trace_cmd, [synthetic_trace])
    assert result.exit_code == 0, result.output
    assert "attribution coverage" in result.output
    # directory target
    result = runner.invoke(trace_cmd, [str(tmp_path)])
    assert result.exit_code == 0, result.output
    # --as-json round-trips
    result = runner.invoke(trace_cmd, [synthetic_trace, "--as-json"])
    assert result.exit_code == 0
    doc = json.loads(result.output)
    assert doc["request_breakdown"]["requests"] == 10
    # missing target is a clean error, not a traceback
    result = runner.invoke(trace_cmd, [str(tmp_path / "nope")])
    assert result.exit_code != 0
    assert "No such trace" in result.output


def test_trace_cli_since_and_last(synthetic_trace):
    """--since/--last restrict the analysis window; the fixture's spans
    all end at 2026-01-01T00:00:01Z, so a cutoff before that keeps them
    and one after drops them."""
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import trace as trace_cmd

    runner = CliRunner()
    result = runner.invoke(
        trace_cmd,
        [synthetic_trace, "--since", "2025-12-31T00:00:00+00:00", "--as-json"],
    )
    assert result.exit_code == 0, result.output
    doc = json.loads(result.output)
    assert doc["request_breakdown"]["requests"] == 10
    assert doc["window"]["since_ts"] is not None

    result = runner.invoke(
        trace_cmd,
        [synthetic_trace, "--since", "2026-06-01T00:00:00+00:00", "--as-json"],
    )
    doc = json.loads(result.output)
    assert doc["spans_read"] == 0

    # --last measures back from NOW: the 2026-01-01 fixture spans are in
    # the past, so a short trailing window is empty
    result = runner.invoke(
        trace_cmd, [synthetic_trace, "--last", "1h", "--as-json"]
    )
    doc = json.loads(result.output)
    assert doc["spans_read"] == 0

    # exclusive options and unparseable cutoffs are clean errors
    result = runner.invoke(
        trace_cmd, [synthetic_trace, "--since", "x", "--last", "1h"]
    )
    assert result.exit_code != 0
    assert "exclusive" in result.output
    result = runner.invoke(trace_cmd, [synthetic_trace, "--since", "whenever"])
    assert result.exit_code != 0
    assert "Unparseable" in result.output
    result = runner.invoke(trace_cmd, [synthetic_trace, "--last", "soonish"])
    assert result.exit_code != 0
