"""The SLO engine: config parsing (tomllib and the 3.10 subset parser),
burn-rate math, the pending→firing→resolved state machine, atomic
persistence, and the status document."""

import json
import os

import pytest

from gordo_tpu.telemetry import slo
from gordo_tpu.telemetry.aggregate import histogram_add, new_histogram

from .test_aggregate import NOW, request_span, write_spans

pytestmark = pytest.mark.slo


# -- config -------------------------------------------------------------------


def test_packaged_defaults_load():
    config = slo.load_slo_config()
    names = [spec.name for spec in config.slos]
    assert "availability" in names
    assert "full-route-p95" in names
    rules = {rule.name: rule for rule in config.rules}
    assert rules["fast"].threshold == pytest.approx(14.4)
    assert rules["fast"].severity == "page"
    assert rules["slow"].window_s == pytest.approx(6 * 3600)
    assert rules["fast"].confirmation_s == pytest.approx(300.0)


def test_subset_parser_matches_packaged_file():
    with open(slo.DEFAULT_SLOS_PATH) as handle:
        doc = slo._parse_toml_subset(handle.read())
    assert [entry["name"] for entry in doc["slo"]] == [
        "availability",
        "full-route-p95",
        "stream-freshness",
        "stream-integrity",
    ]
    assert doc["burn"]["confirmation_divisor"] == 12
    assert doc["slo"][1]["threshold_ms"] == 1000.0
    assert doc["slo"][2]["objective"] == "stream_freshness"
    assert doc["slo"][2]["threshold_ms"] == 5000.0


def test_config_resolution_order(tmp_path, monkeypatch):
    local = tmp_path / "slos.toml"
    local.write_text(
        '[[slo]]\nname = "local"\nobjective = "availability"\n'
        'target = 0.99\nwindow = "1d"\n'
    )
    assert slo.resolve_config_path(str(tmp_path)) == str(local)
    config = slo.load_slo_config(str(tmp_path))
    assert [spec.name for spec in config.slos] == ["local"]
    override = tmp_path / "override.toml"
    override.write_text(local.read_text())
    monkeypatch.setenv(slo.SLO_CONFIG_ENV, str(override))
    assert slo.resolve_config_path(str(tmp_path)) == str(override)
    # no local file, no override -> the packaged defaults
    monkeypatch.delenv(slo.SLO_CONFIG_ENV)
    assert (
        slo.resolve_config_path(str(tmp_path / "empty"))
        == slo.DEFAULT_SLOS_PATH
    )


@pytest.mark.parametrize(
    "body",
    [
        '[[slo]]\nname = "x"\nobjective = "nope"\ntarget = 0.9\n',
        '[[slo]]\nname = "x"\nobjective = "availability"\ntarget = 1.5\n',
        '[[slo]]\nname = "x"\nobjective = "latency"\ntarget = 0.9\n',
        '[[slo]]\nname = "x"\nobjective = "availability"\ntarget = 0.9\n'
        '[[slo]]\nname = "x"\nobjective = "availability"\ntarget = 0.9\n',
    ],
)
def test_malformed_config_raises(tmp_path, body):
    path = tmp_path / "slos.toml"
    path.write_text(body)
    with pytest.raises(ValueError):
        slo.load_slo_config(path=str(path))


def test_parse_duration():
    assert slo.parse_duration("30d") == pytest.approx(30 * 86400)
    assert slo.parse_duration("90m") == pytest.approx(5400)
    assert slo.parse_duration(45) == 45.0
    with pytest.raises(ValueError):
        slo.parse_duration("soon")


# -- math ---------------------------------------------------------------------


def test_histogram_fraction_over():
    histogram = new_histogram()
    for value in (100.0, 100.0, 100.0, 2000.0):
        histogram_add(histogram, value)
    over = slo.histogram_fraction_over(histogram, 1000.0)
    assert over == pytest.approx(0.25, abs=0.05)
    assert slo.histogram_fraction_over(new_histogram(), 1000.0) == 0.0
    assert slo.histogram_fraction_over(histogram, 0.0) == 1.0


def test_burn_rate():
    spec = slo.SloSpec(
        name="a", objective="availability", target=0.999,
        window="30d", window_s=30 * 86400.0,
    )
    assert slo.burn_rate(spec, 0.001) == pytest.approx(1.0)
    assert slo.burn_rate(spec, 0.0144) == pytest.approx(14.4)


# -- the state machine --------------------------------------------------------


@pytest.mark.parametrize(
    "previous,exceeded,expected",
    [
        (None, True, "pending"),
        ("inactive", True, "pending"),
        ("pending", True, "firing"),
        ("firing", True, "firing"),
        ("resolved", True, "pending"),
        (None, False, "inactive"),
        ("pending", False, "inactive"),
        ("firing", False, "resolved"),
        ("resolved", False, "inactive"),
    ],
)
def test_advance_alert_state(previous, exceeded, expected):
    assert slo.advance_alert_state(previous, exceeded) == expected


# -- evaluation ---------------------------------------------------------------


@pytest.fixture(autouse=True)
def _fresh_registry():
    slo.reset_statuses()
    yield
    slo.reset_statuses()


def _healthy_then_burst(directory, burst_errors=60):
    """2h of healthy traffic, then a 5xx burst just before NOW."""
    spans = [
        request_span(i, NOW - 7200 + i * 3.6, wall_ms=100.0)
        for i in range(2000)
    ]
    spans += [
        request_span(10_000 + i, NOW - 60 + i * 0.5, status=500)
        for i in range(burst_errors)
    ]
    write_spans(os.path.join(directory, "serve_trace.jsonl"), spans)


def test_evaluate_pending_then_firing_then_resolved(tmp_path):
    d = str(tmp_path)
    _healthy_then_burst(d)
    doc = slo.evaluate(d, now=NOW)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "pending"
    assert doc["firing"] == 0 and doc["ok"]

    doc = slo.evaluate(d, now=NOW + 30)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "firing"
    assert doc["firing"] >= 1 and not doc["ok"]
    assert slo.firing_alerts(d, severity="page")

    # recovery: the burst ages out of every alert window
    doc = slo.evaluate(d, now=NOW + 8 * 3600)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "resolved"
    assert doc["ok"]
    assert not slo.firing_alerts(d)

    doc = slo.evaluate(d, now=NOW + 8 * 3600 + 60)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "inactive"


def test_confirmation_window_blocks_stale_burn(tmp_path):
    """An old burst still inside the 1h window but outside the 5m
    confirmation window must NOT trip the fast alert (the multi-window
    point: stale incidents don't page)."""
    d = str(tmp_path)
    spans = [
        request_span(i, NOW - 3000 + i * 0.5, status=500) for i in range(100)
    ]
    spans += [
        request_span(1000 + i, NOW - 200 + i, wall_ms=50.0) for i in range(100)
    ]
    write_spans(os.path.join(d, "serve_trace.jsonl"), spans)
    doc = slo.evaluate(d, now=NOW)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "inactive"


def test_state_persists_and_is_atomic(tmp_path):
    d = str(tmp_path)
    _healthy_then_burst(d)
    slo.evaluate(d, now=NOW)
    state_file = slo.state_path(d)
    assert os.path.exists(state_file)
    # no staging leftovers from the atomic replace
    leftovers = [n for n in os.listdir(d) if ".tmp-" in n]
    assert leftovers == []
    persisted = slo.load_alert_states(d)
    assert persisted["availability:fast"]["state"] == "pending"
    # a fresh process (fresh registry) reads the same machine state and
    # advances it — pending -> firing on the next evaluation
    slo.reset_statuses()
    doc = slo.evaluate(d, now=NOW + 30)
    states = {a["id"]: a["state"] for a in doc["alerts"]}
    assert states["availability:fast"] == "firing"


def test_latency_slo_budget(tmp_path):
    d = str(tmp_path)
    spans = [
        request_span(i, NOW - 1800 + i, wall_ms=5000.0) for i in range(100)
    ]
    write_spans(os.path.join(d, "serve_trace.jsonl"), spans)
    doc = slo.evaluate(d, now=NOW)
    latency = next(s for s in doc["slos"] if s["name"] == "full-route-p95")
    assert latency["bad_fraction"] == pytest.approx(1.0)
    assert latency["budget"]["remaining_ratio"] == pytest.approx(0.0)
    assert latency["latency_p95_ms"] >= 1000.0


def test_status_document_shape_and_registry(tmp_path):
    d = str(tmp_path)
    _healthy_then_burst(d, burst_errors=0)
    doc = slo.evaluate(d, now=NOW)
    assert doc["ok"] and doc["firing"] == 0
    for entry in doc["slos"]:
        assert set(entry["burn_rates"]) == {"1h", "6h"}
        assert 0.0 <= entry["budget"]["remaining_ratio"] <= 1.0
    assert doc["recent"]["requests"] > 0
    # the registry feeds the fleet-status join and the scrape collector
    section = slo.slo_section(d)
    assert section["ok"] is True
    assert section["budgets"]
    rendered = slo.render_slo_status(doc)
    assert "inside SLO" in rendered


def test_slo_section_from_persisted_state_only(tmp_path):
    d = str(tmp_path)
    _healthy_then_burst(d)
    slo.evaluate(d, now=NOW)
    slo.evaluate(d, now=NOW + 30)  # -> firing
    slo.reset_statuses()  # "another process": no cached status
    section = slo.slo_section(d)
    assert section is not None
    assert section["firing"] >= 1
    assert section["ok"] is False
    assert section["budgets"] is None


def test_undeclared_alerts_are_dropped(tmp_path):
    d = str(tmp_path)
    _healthy_then_burst(d)
    slo.evaluate(d, now=NOW)
    state_file = slo.state_path(d)
    with open(state_file) as handle:
        state = json.load(handle)
    state["alerts"]["ghost:fast"] = {"state": "firing", "severity": "page"}
    with open(state_file, "w") as handle:
        json.dump(state, handle)
    slo.evaluate(d, now=NOW + 30)
    assert "ghost:fast" not in slo.load_alert_states(d)


def test_subset_parser_bad_value_raises_valueerror(tmp_path):
    """literal_eval's SyntaxError (a `0..99` typo) must surface as the
    contract's ValueError, so the CLI/route answer cleanly."""
    path = tmp_path / "slos.toml"
    path.write_text(
        '[[slo]]\nname = "x"\nobjective = "availability"\ntarget = 0..99\n'
    )
    with pytest.raises(ValueError, match="bad value"):
        slo._parse_toml_subset(path.read_text())


def test_evaluate_cached_throttles(tmp_path, monkeypatch):
    d = str(tmp_path)
    _healthy_then_burst(d, burst_errors=0)
    calls = []
    original = slo.evaluate

    def counting(directory, *args, **kwargs):
        calls.append(directory)
        return original(directory, *args, **kwargs)

    monkeypatch.setattr(slo, "evaluate", counting)
    first = slo.evaluate_cached(d, max_age_s=3600)
    second = slo.evaluate_cached(d, max_age_s=3600)
    assert len(calls) == 1  # the second call served the cache
    assert second is first
    slo.evaluate_cached(d, max_age_s=0)  # 0 = always evaluate
    assert len(calls) == 2


def test_firing_alerts_staleness_cutoff(tmp_path):
    """A state document whose evaluator died hours ago must not hold
    promotions forever; a missing stamp stays conservative (holds)."""
    d = str(tmp_path)
    _healthy_then_burst(d)
    slo.evaluate(d, now=NOW)
    slo.evaluate(d, now=NOW + 30)  # -> firing, stamped at NOW + 30
    assert slo.firing_alerts(d, severity="page")
    # fresh enough within the bound (relative to the stamp, wall clock
    # is far past NOW, so any finite bound is exceeded)
    assert not slo.firing_alerts(
        d, severity="page", max_age_s=slo.STALE_ALERT_HOLD_S
    )
    # no stamp at all -> unknown age -> conservative hold
    state_file = slo.state_path(d)
    with open(state_file) as handle:
        state = json.load(handle)
    state.pop("updated_at", None)
    with open(state_file, "w") as handle:
        json.dump(state, handle)
    assert slo.firing_alerts(d, severity="page", max_age_s=60)


def test_fleet_status_document_joins_slo(tmp_path):
    from gordo_tpu.telemetry import fleet_status_document

    d = str(tmp_path)
    _healthy_then_burst(d)
    slo.evaluate(d, now=NOW)
    doc = fleet_status_document(d)
    assert doc["slo"] is not None
    assert doc["slo"]["pending"] >= 1
    from gordo_tpu.telemetry import render_fleet_status

    assert "SLO:" in render_fleet_status(doc)
