"""
Device-utilization telemetry (PR 9): the compile-cache hit counters,
their ``program_span`` / serving wiring, the memory snapshot's degrade
contract, and the ``device_utilization`` event schema.
"""

import pytest

from gordo_tpu import telemetry
from gordo_tpu.telemetry import device

pytestmark = [pytest.mark.fleet_health, pytest.mark.observability]


@pytest.fixture(autouse=True)
def _fresh_counters():
    device.reset_program_counters()
    telemetry.reset_seen_programs()
    yield
    device.reset_program_counters()
    telemetry.reset_seen_programs()


def test_program_counters_accumulate_per_kind():
    device.note_program_execution(True)
    device.note_program_execution(False)
    device.note_program_execution(False)
    device.note_program_execution(True, kind="serve")
    counters = device.program_cache_counters()
    assert counters["build"] == {
        "compiles": 1,
        "cache_hits": 2,
        "hit_rate": round(2 / 3, 4),
    }
    assert counters["serve"]["compiles"] == 1
    assert counters["serve"]["hit_rate"] == 0.0


def test_program_span_feeds_the_counters():
    """program_span's first-call-per-signature attribution IS the
    compile-cache hit/miss signal — the same call that marks the span
    must feed the console counters, recorder active or not."""
    with telemetry.program_span("fleet_fit", ("spec", (4, 8))):
        pass
    with telemetry.program_span("fleet_fit", ("spec", (4, 8))):
        pass
    with telemetry.program_span("fleet_fit", ("spec", (8, 8))):
        pass
    counters = device.program_cache_counters()["build"]
    assert counters["compiles"] == 2
    assert counters["cache_hits"] == 1


def test_memory_snapshot_never_raises(monkeypatch):
    """On any backend the snapshot is a dict (or None when disabled) —
    platforms without Device.memory_stats degrade to available=False,
    they never break the caller."""
    snapshot = device.memory_snapshot()
    assert snapshot is None or isinstance(snapshot, dict)
    if isinstance(snapshot, dict):
        assert "available" in snapshot
        if snapshot["available"]:
            assert snapshot["bytes_in_use"] >= 0
            assert snapshot["peak_bytes_in_use"] >= snapshot["bytes_in_use"] * 0
    monkeypatch.setenv("GORDO_TPU_DEVICE_TELEMETRY", "0")
    assert device.memory_snapshot() is None
    monkeypatch.setenv("GORDO_TPU_DEVICE_TELEMETRY", "1")
    monkeypatch.setenv("GORDO_TPU_TELEMETRY", "0")
    assert device.memory_snapshot() is None


def test_utilization_snapshot_sections():
    device.note_program_execution(True)
    doc = device.utilization_snapshot()
    assert "compile_cache" in doc
    assert doc["compile_cache"]["build"]["compiles"] == 1
    # memory may be absent (no jax stats) but never truthy-and-empty
    if "memory" in doc:
        assert isinstance(doc["memory"], dict)


def test_persistent_cache_info_counts_entries(tmp_path, monkeypatch):
    cache_dir = tmp_path / "compile-cache"
    cache_dir.mkdir()
    (cache_dir / "entry-1").write_bytes(b"x" * 100)
    (cache_dir / "entry-2").write_bytes(b"y" * 50)
    device.note_compile_cache_dir(str(cache_dir))
    try:
        info = device.persistent_cache_info()
        assert info == {"path": str(cache_dir), "entries": 2, "bytes": 150}
    finally:
        device.note_compile_cache_dir(None)
    # unconfigured and no env knob -> None
    monkeypatch.delenv("GORDO_TPU_COMPILE_CACHE", raising=False)
    assert device.persistent_cache_info() is None


def test_emit_device_utilization_event_schema():
    """When memory stats exist the event carries flattened memory_*
    attributes + the build counters; when they don't, nothing is
    emitted (callers treat None as 'not measurable')."""
    recorder = telemetry.SpanRecorder()
    snapshot = device.emit_device_utilization(recorder, phase="final_fit")
    events = recorder.finished("device_utilization")
    if snapshot is None:
        assert events == []
        return
    assert len(events) == 1
    attrs = events[0]["attributes"]
    assert attrs["phase"] == "final_fit"
    assert "compiles" in attrs and "cache_hits" in attrs
    assert attrs["memory_devices"] == snapshot["devices"]


@pytest.mark.precision
def test_program_counters_bucket_by_precision():
    """The serve kind's counters gain a per-precision breakdown (the
    precision ladder's compile accounting); kinds fed without a
    precision stay exactly as before."""
    device.note_program_execution(True, kind="serve", precision="f32")
    device.note_program_execution(False, kind="serve", precision="f32")
    device.note_program_execution(True, kind="serve", precision="bf16")
    device.note_program_execution(True, kind="build")
    counters = device.program_cache_counters()
    serve = counters["serve"]
    assert serve["compiles"] == 2 and serve["cache_hits"] == 1
    assert serve["by_precision"]["f32"] == {"compiles": 1, "cache_hits": 1}
    assert serve["by_precision"]["bf16"] == {"compiles": 1, "cache_hits": 0}
    assert "by_precision" not in counters["build"]
    # the snapshot is a COPY: mutating it never corrupts the live counts
    serve["by_precision"]["f32"]["compiles"] = 999
    assert (
        device.program_cache_counters()["serve"]["by_precision"]["f32"][
            "compiles"
        ]
        == 1
    )
