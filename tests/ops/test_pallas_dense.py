"""Pallas fused fleet forward vs the reference jnp forward (interpret mode)."""

import jax
import numpy as np
import pytest

from gordo_tpu.models.factories import feedforward_hourglass, feedforward_model
from gordo_tpu.models.nn import forward_feedforward, init_feedforward
from gordo_tpu.ops.pallas_dense import (
    fleet_anomaly_scores_pallas,
    fleet_feedforward_pallas,
)


def _stacked(spec, m, rng):
    keys = jax.random.split(jax.random.PRNGKey(rng), m)
    return jax.vmap(lambda k: init_feedforward(k, spec))(keys)


@pytest.mark.parametrize("m,b", [(1, 8), (4, 32)])
def test_pallas_forward_matches_jnp(m, b):
    spec = feedforward_hourglass(12)
    params = _stacked(spec, m, 0)
    X = np.random.RandomState(0).rand(m, b, 12).astype(np.float32)

    expected = jax.vmap(lambda p, x: forward_feedforward(spec, p, x)[0])(params, X)
    got = fleet_feedforward_pallas(spec, params, X, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_pallas_forward_explicit_dims_relu():
    spec = feedforward_model(6, 6, encoding_dim=(8, 4), decoding_dim=(4, 8),
                             encoding_func=("relu", "relu"), decoding_func=("relu", "relu"))
    params = _stacked(spec, 3, 1)
    X = np.random.RandomState(1).rand(3, 16, 6).astype(np.float32)
    expected = jax.vmap(lambda p, x: forward_feedforward(spec, p, x)[0])(params, X)
    got = fleet_feedforward_pallas(spec, params, X, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_pallas_anomaly_scores():
    spec = feedforward_hourglass(5)
    params = _stacked(spec, 2, 2)
    X = np.random.RandomState(2).rand(2, 10, 5).astype(np.float32)
    out, err = fleet_anomaly_scores_pallas(spec, params, X, X, interpret=True)
    expected_out = jax.vmap(lambda p, x: forward_feedforward(spec, p, x)[0])(params, X)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected_out), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(err),
        ((np.asarray(expected_out) - X) ** 2).mean(-1),
        rtol=1e-5, atol=1e-6,
    )


def test_pallas_forward_large_batch_blocked(monkeypatch):
    """B larger than the row-block tile: the batch grid axis + padding must
    keep parity (this bounds VMEM for big serving requests)."""
    import gordo_tpu.ops.pallas_dense as pallas_dense

    monkeypatch.setattr(pallas_dense, "BLOCK_B", 16)
    spec = feedforward_hourglass(7)
    params = _stacked(spec, 2, 3)
    # 50 rows: 3 full 16-row blocks + a 2-row tail forcing padding
    X = np.random.RandomState(3).rand(2, 50, 7).astype(np.float32)
    expected = jax.vmap(lambda p, x: forward_feedforward(spec, p, x)[0])(params, X)
    got = pallas_dense.fleet_feedforward_pallas(spec, params, X, interpret=True)
    assert got.shape == (2, 50, 7)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), rtol=1e-5, atol=1e-6)
