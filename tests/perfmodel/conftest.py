"""
Perfmodel-suite fixtures: synthetic trace corpora drawn from a KNOWN
multiplicative cost law (so fits have a ground truth to recover), span
builders matching the telemetry plane's JSONL schema, and a fitted
cost-table fixture the consumer tests load through the real
``fit_and_promote`` path.
"""

import json
import math
import os

import pytest

from gordo_tpu.models.spec import FeedForwardSpec
from gordo_tpu.telemetry import SERVE_TRACE_FILE

SPEC = FeedForwardSpec(
    n_features=3, n_features_out=3, dims=(6, 3), activations=("tanh", "tanh")
)

FLOPS = 100.0

#: the ground-truth law the synthetic corpora follow:
#: device_ms = 0.05 * members^0.9 * rows^0.8 (× 0.7 at bf16) — exactly
#: log-linear in the learned feature vocabulary, so a correct fit drives
#: holdout error to ~0 while the analytic defaults stay far off
def true_device_ms(members, rows, precision="f32"):
    scale = 0.7 if precision == "bf16" else 1.0
    return 0.05 * (members ** 0.9) * (rows ** 0.8) * scale


def true_compile_ms(flops=FLOPS):
    return 40.0 + 0.2 * flops


def serve_span(index, members, rows, precision="f32", device_ms=None, **extra):
    attrs = {
        "flops_per_sample": FLOPS,
        "padded_members": members,
        "padded_rows": rows,
        "precision": precision,
        "device_ms": (
            device_ms
            if device_ms is not None
            else true_device_ms(members, rows, precision)
        ),
    }
    attrs.update(extra)
    return {
        "name": "serve_batch",
        "context": {"trace_id": "t", "span_id": f"s-{index}"},
        "attributes": attrs,
    }


def compile_span(index, members, rows, precision="f32", device_ms=None):
    return {
        "name": "device_program",
        "context": {"trace_id": "t", "span_id": f"c-{index}"},
        "attributes": {
            "program": "fleet_forward",
            "compile": True,
            "flops_per_sample": FLOPS,
            "stacked_members": members,
            "stacked_samples": rows,
            "precision": precision,
            "device_ms": (
                device_ms if device_ms is not None else true_compile_ms()
            ),
        },
    }


def grid_spans(jitter=0.0):
    """A (members × rows × precision) grid of serve spans plus one
    compile span per shape — 72 device rows, 36 compile rows."""
    spans = []
    shapes = [
        (m, r, p)
        for p in ("f32", "bf16")
        for m in (1, 2, 4, 8, 12, 16)
        for r in (16, 32, 128)
    ]
    for i, (m, r, p) in enumerate(shapes):
        spans.append(compile_span(len(spans), m, r, p))
        for k in range(2):
            wobble = 1.0 + jitter * math.sin(i + k)
            spans.append(
                serve_span(
                    len(spans), m, r, p,
                    device_ms=true_device_ms(m, r, p) * wobble,
                )
            )
    return spans


def write_corpus(directory, spans):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, SERVE_TRACE_FILE)
    with open(path, "w") as f:
        for span in spans:
            f.write(json.dumps(span) + "\n")
    return path


@pytest.fixture
def corpus_dir(tmp_path):
    directory = str(tmp_path / "telemetry")
    write_corpus(directory, grid_spans(jitter=0.02))
    return directory


@pytest.fixture
def fitted_table_path(corpus_dir, tmp_path):
    """A cost table with a promoted learned section, produced by the
    real harvest→fit→gate→save path."""
    from gordo_tpu.perfmodel import fit_and_promote

    path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(corpus_dir, table_path=path, min_samples=8)
    assert report["promoted"], report
    return path
