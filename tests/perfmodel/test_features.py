"""
Harvesting drills: spans → training rows. Rows are extracted only when
the span carries the full static feature set AND a positive measured
target — anything else is skipped, never guessed — and corpus discovery
reuses the telemetry plane's own trace readers (rotated generations,
per-worker sinks, span dedup).
"""

import math

import pytest

from gordo_tpu.perfmodel import (
    corpus_fingerprint,
    harvest_corpus,
    rows_from_spans,
)
from gordo_tpu.planner.costmodel import learned_feature_vector

from tests.perfmodel.conftest import (
    FLOPS,
    compile_span,
    grid_spans,
    serve_span,
    write_corpus,
)

pytestmark = pytest.mark.perfmodel


def test_serve_batch_spans_become_forward_device_rows():
    rows = rows_from_spans([serve_span(0, members=4, rows=32, device_ms=7.5)])
    assert len(rows) == 1
    row = rows[0]
    assert (row.target, row.program, row.y) == ("device_ms", "fleet_forward", 7.5)
    assert row.features == tuple(
        learned_feature_vector(FLOPS, 4, 32, 1, "f32")
    )


def test_compile_spans_pin_shape_axes_to_one():
    """Compile cost tracks program complexity, not data volume: the
    member/row/epoch features pin to log(1)=0 exactly like
    ``CostModel.predict_compile_s`` evaluates them."""
    rows = rows_from_spans([compile_span(0, members=8, rows=512)])
    assert len(rows) == 1
    row = rows[0]
    assert (row.target, row.program) == ("compile_ms", "fleet_forward")
    assert row.features[1:4] == (0.0, 0.0, 0.0)


def test_rows_without_static_features_or_targets_are_skipped():
    missing_flops = serve_span(0, members=4, rows=32)
    del missing_flops["attributes"]["flops_per_sample"]
    zero = serve_span(1, members=4, rows=32, device_ms=0.0)
    negative = serve_span(2, members=4, rows=32, device_ms=-1.0)
    unknown = {"name": "other_span", "attributes": {"device_ms": 5.0}}
    assert rows_from_spans([missing_flops, zero, negative, unknown, None]) == []


def test_hbm_attribute_adds_a_peak_memory_row():
    span = serve_span(0, members=4, rows=32, device_ms=7.5, hbm_bytes=1 << 20)
    rows = rows_from_spans([span])
    assert {r.target for r in rows} == {"device_ms", "hbm_bytes"}
    hbm = next(r for r in rows if r.target == "hbm_bytes")
    assert hbm.y == float(1 << 20)
    assert hbm.program == "fleet_forward"


def test_device_program_run_spans_use_their_program_attribute():
    span = compile_span(0, members=4, rows=64)
    span["attributes"].pop("compile")
    span["attributes"]["program"] = "fleet_fit"
    span["attributes"]["epochs"] = 3
    rows = rows_from_spans([span])
    assert len(rows) == 1
    assert rows[0].program == "fleet_fit"
    assert rows[0].features[3] == pytest.approx(math.log(3))


def test_harvest_corpus_empty_and_absent_directories(tmp_path):
    rows, stats = harvest_corpus(str(tmp_path / "nowhere"))
    assert rows == [] and stats["spans"] == 0
    empty = tmp_path / "empty"
    empty.mkdir()
    rows, stats = harvest_corpus(str(empty))
    assert rows == [] and stats["rows"] == 0


def test_harvest_corpus_counts_populations(tmp_path):
    directory = str(tmp_path / "telemetry")
    write_corpus(directory, grid_spans())
    rows, stats = harvest_corpus(directory)
    assert stats["rows"] == len(rows) > 0
    assert stats["rows_by_model"]["device_ms/fleet_forward"] == 72
    assert stats["rows_by_model"]["compile_ms/fleet_forward"] == 36


def test_harvest_skips_torn_trailing_line(tmp_path):
    directory = str(tmp_path / "telemetry")
    path = write_corpus(directory, [serve_span(0, members=2, rows=16)])
    with open(path, "a") as f:
        f.write('{"name": "serve_batch", "attributes": {"padded')  # torn
    rows, _ = harvest_corpus(directory)
    assert len(rows) == 1


def test_fingerprint_is_order_independent_and_content_sensitive():
    a = rows_from_spans(grid_spans())
    b = list(reversed(a))
    assert corpus_fingerprint(a) == corpus_fingerprint(b)
    assert corpus_fingerprint(a) != corpus_fingerprint(a[:-1])
