"""
The knob-off regression contract: with ``GORDO_TPU_PERFMODEL`` unset, a
cost table carrying a fitted learned section must produce BYTE-IDENTICAL
FleetPlan JSON to the same table without the section — the learned model
may only change behavior when asked to. With the knob on, the plan doc
records that the learned ruler participated.
"""

from types import SimpleNamespace

import pytest

from gordo_tpu.planner.costmodel import CostModel, CostTable
from gordo_tpu.planner.packing import PACKED, plan_train_buckets
from gordo_tpu.planner.plan import build_plan_doc, config_fingerprint

from tests.perfmodel.conftest import SPEC
from tests.perfmodel.test_table_safety import valid_section

pytestmark = [pytest.mark.perfmodel, pytest.mark.planner]

CONFIG = SimpleNamespace(
    epochs=2,
    batch_size=16,
    validation_split=0.1,
    shuffle=False,
    early_stopping=None,
)


def training_section():
    """A learned section whose models answer the TRAINING programs the
    planner costs (wide domain box, deliberately wild coefficients — if
    the knob-off path consulted them, packing would visibly change)."""
    entry = {
        "coef": [5.0, 0.1, 1.5, 1.2, 1.0, 0.0, 0.0],
        "lo": [0.0] * 6,
        "hi": [30.0] * 6,
        "n": 64,
        "holdout_mae_log": 0.05,
    }
    section = valid_section()
    section["targets"] = {
        "device_ms": {"fleet_fit": dict(entry), "fleet_forward": dict(entry)},
        "compile_ms": {"fleet_fit": dict(entry)},
        "hbm_bytes": {"fleet_fit": dict(entry)},
    }
    return section


def make_plan(table):
    members = [
        SimpleNamespace(name=name, spec=SPEC, n=n)
        for name, n in (("a", 50), ("b", 120), ("c", 700))
    ]
    cost_model = CostModel(table)
    buckets = plan_train_buckets(
        members, CONFIG, strategy=PACKED, cost_model=cost_model
    )
    return build_plan_doc(
        [(CONFIG, buckets)],
        PACKED,
        cost_model.mesh_shape,
        cost_model.table,
        config_fingerprint(["k1", "k2", "k3"]),
    )


def test_knob_off_plans_are_byte_identical(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL", raising=False)
    plain = make_plan(CostTable())
    with_section = make_plan(CostTable(learned=training_section()))
    assert with_section.to_json() == plain.to_json()
    assert with_section.plan_hash == plain.plan_hash
    assert with_section.doc["cost_table"]["learned"] is False


def test_knob_off_explicit_zero_is_the_same_contract(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERFMODEL", "0")
    plain = make_plan(CostTable())
    with_section = make_plan(CostTable(learned=training_section()))
    assert with_section.to_json() == plain.to_json()


def test_knob_on_plan_records_the_learned_ruler(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERFMODEL", "1")
    learned = make_plan(CostTable(learned=training_section()))
    assert learned.doc["cost_table"]["learned"] is True
    # a learned-section-free table stays honest about its ruler
    assert make_plan(CostTable()).doc["cost_table"]["learned"] is False


def test_knob_on_predictions_actually_diverge(monkeypatch):
    """The knob must route predictions through the regressors — a knob
    that only flips a doc flag would pass the parity tests vacuously."""
    monkeypatch.delenv("GORDO_TPU_PERFMODEL", raising=False)
    table = CostTable(learned=training_section())
    off = CostModel(table, use_learned=False)
    on = CostModel(table, use_learned=True)
    assert on.predict_serve_step_s(SPEC, 8, 128, "f32") != off.predict_serve_step_s(
        SPEC, 8, 128, "f32"
    )
    assert on.predict_run_s("fleet_fit", SPEC, 8, 128, 2) != off.predict_run_s(
        "fleet_fit", SPEC, 8, 128, 2
    )


def test_cold_start_plan_matches_the_analytic_defaults(tmp_path, monkeypatch):
    """Satellite 3, the cold-start half: an empty corpus promotes no
    table, and planning through ``load_table_safe`` of the absent path
    is byte-identical to the analytic defaults — knob on or off."""
    from gordo_tpu.perfmodel import fit_and_promote
    from gordo_tpu.planner.costmodel import load_table_safe

    empty = tmp_path / "empty-corpus"
    empty.mkdir()
    table_path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(str(empty), table_path=table_path)
    assert report["promoted"] is False
    for knob in ("0", "1"):
        monkeypatch.setenv("GORDO_TPU_PERFMODEL", knob)
        cold = make_plan(load_table_safe(table_path))
        assert cold.to_json() == make_plan(CostTable()).to_json()
