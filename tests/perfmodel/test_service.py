"""
Promotion-lifecycle drills: fit_and_promote end to end (gate, write,
fingerprint skip, carry-forward), the per-model accuracy gate's
verdicts, and the supervisor-facing recalibration hook's safety
contract (env-gated, never raises).
"""

import json
import os

import pytest

from gordo_tpu.perfmodel import (
    default_table_path,
    fit_and_promote,
    harvest_corpus,
    maybe_recalibrate,
    section_status,
)
from gordo_tpu.perfmodel.service import _gate_entry
from gordo_tpu.planner.costmodel import COST_TABLE_FILE, CostTable, load_table_safe

from tests.perfmodel.conftest import compile_span, write_corpus

pytestmark = pytest.mark.perfmodel


def test_fit_and_promote_installs_a_gated_section(corpus_dir, tmp_path):
    path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(corpus_dir, table_path=path, min_samples=8)
    assert report["promoted"] is True
    assert report["reason"] == "promoted"
    assert all(m["accepted"] for m in report["models"])
    for model in report["models"]:
        # the gate's whole point: every promoted model beat analytic
        assert model["holdout_mae_log"] <= model["analytic_mae_log"]
    table = load_table_safe(path)
    assert table.has_learned
    assert table.learned["corpus"]["fingerprint"] == report["fingerprint"]
    # analytic factors survive promotion untouched
    assert table.throughput == CostTable().throughput


def test_unchanged_corpus_skips_the_refit(corpus_dir, tmp_path):
    path = str(tmp_path / "cost_table.json")
    fit_and_promote(corpus_dir, table_path=path, min_samples=8)
    before = open(path).read()
    again = fit_and_promote(corpus_dir, table_path=path, min_samples=8)
    assert again["promoted"] is False
    assert again["reason"] == "corpus unchanged since incumbent fit"
    assert open(path).read() == before
    # force overrides the fingerprint skip (but not the gate)
    forced = fit_and_promote(
        corpus_dir, table_path=path, min_samples=8, force=True
    )
    assert forced["promoted"] is True


def test_empty_corpus_promotes_nothing_and_writes_nothing(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(str(empty), table_path=path)
    assert report["promoted"] is False
    assert "empty corpus" in report["reason"]
    assert not os.path.exists(path)


def test_below_floor_corpus_keeps_the_incumbent_table(tmp_path):
    directory = str(tmp_path / "telemetry")
    write_corpus(directory, [compile_span(i, 2, 16) for i in range(4)])
    path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(directory, table_path=path, min_samples=32)
    assert report["promoted"] is False
    assert "sample floor" in report["reason"]
    assert not os.path.exists(path)


def test_gate_rejects_a_candidate_that_loses_to_analytic(corpus_dir):
    rows, _ = harvest_corpus(corpus_dir)
    population = [r for r in rows if r.target == "device_ms"]
    bad_entry = {
        "coef": [50.0, 0, 0, 0, 0, 0, 0],  # predicts e^50 ms everywhere
        "lo": [0.0] * 6,
        "hi": [50.0] * 6,
        "n": len(population),
        "holdout_mae_log": 45.0,
    }
    verdict = _gate_entry(
        "device_ms", "fleet_forward", bad_entry, population, CostTable()
    )
    assert verdict["accepted"] is False
    assert verdict["reason"] == "loses to analytic"


def test_gate_rejects_a_candidate_that_loses_to_the_incumbent(
    corpus_dir, fitted_table_path
):
    rows, _ = harvest_corpus(corpus_dir)
    population = [r for r in rows if r.target == "device_ms"]
    incumbent = load_table_safe(fitted_table_path)
    # an "ok but worse than the promoted fit" candidate: beats the (far
    # off) analytic defaults, loses to the incumbent regressor
    mediocre = {
        "coef": incumbent.learned_entry("device_ms", "fleet_forward")["coef"],
        "lo": [0.0] * 6,
        "hi": [50.0] * 6,
        "n": len(population),
        "holdout_mae_log": 1.0,
    }
    verdict = _gate_entry(
        "device_ms", "fleet_forward", mediocre, population, incumbent
    )
    assert verdict["accepted"] is False
    assert verdict["reason"] == "loses to incumbent"
    assert verdict["incumbent_mae_log"] is not None


def test_hbm_gate_uses_the_median_baseline(tmp_path):
    """hbm_bytes has no feature-only analytic counterpart: its gate
    baseline is the train-median predictor."""
    from tests.perfmodel.conftest import serve_span

    directory = str(tmp_path / "telemetry")
    spans = [
        serve_span(i, m, r, device_ms=1.0, hbm_bytes=1024.0 * m * r)
        for i, (m, r) in enumerate(
            (m, r) for m in (1, 2, 4, 8, 12, 16) for r in (16, 32, 64, 128)
        )
    ]
    write_corpus(directory, spans)
    path = str(tmp_path / "cost_table.json")
    report = fit_and_promote(directory, table_path=path, min_samples=8)
    hbm = [m for m in report["models"] if m["target"] == "hbm_bytes"]
    assert len(hbm) == 1
    assert hbm[0]["accepted"] is True
    assert hbm[0]["analytic_mae_log"] is not None  # the median baseline
    table = load_table_safe(path)
    predicted = table.learned_predict(
        "hbm_bytes",
        "fleet_forward",
        [r for r in harvest_corpus(directory)[0] if r.target == "hbm_bytes"][0]
        .features,
    )
    assert predicted == pytest.approx(1024.0 * 1 * 16, rel=0.2)


def test_serve_only_refit_carries_forward_other_models(
    corpus_dir, fitted_table_path, tmp_path
):
    """A later corpus that only exercises compile spans must not evict
    the incumbent device_ms regressor from the table."""
    incumbent = load_table_safe(fitted_table_path)
    assert incumbent.learned_entry("device_ms", "fleet_forward")
    compile_only = str(tmp_path / "compile-only")
    write_corpus(
        compile_only,
        [
            compile_span(i, 1, 1, device_ms=60.0 + 0.01 * i)
            for i in range(24)
        ],
    )
    report = fit_and_promote(
        compile_only, table_path=fitted_table_path, min_samples=8
    )
    assert report["promoted"] is True
    table = load_table_safe(fitted_table_path)
    assert table.learned_entry("compile_ms", "fleet_forward") is not None
    assert table.learned_entry("device_ms", "fleet_forward") is not None


def test_default_table_path_resolution(monkeypatch, tmp_path):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_TABLE", raising=False)
    assert default_table_path() is None
    assert default_table_path(str(tmp_path)) == str(
        tmp_path / COST_TABLE_FILE
    )
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_TABLE", "/pinned/table.json")
    assert default_table_path(str(tmp_path)) == "/pinned/table.json"


def test_section_status_reports_the_models(fitted_table_path):
    doc = section_status(fitted_table_path)
    assert doc["exists"] and doc["learned"]
    assert {m["target"] for m in doc["models"]} >= {"device_ms", "compile_ms"}
    assert "fingerprint" in doc["corpus"]
    absent = section_status("/nowhere/cost_table.json")
    assert absent["exists"] is False and absent["learned"] is False


def test_maybe_recalibrate_is_env_gated_and_never_raises(
    monkeypatch, corpus_dir, tmp_path
):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_RECAL", raising=False)
    assert maybe_recalibrate(corpus_dir) is None  # knob off: no-op
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_RECAL", "1")
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_MIN_SAMPLES", "8")
    path = str(tmp_path / "cost_table.json")
    result = maybe_recalibrate(corpus_dir, table_path=path)
    assert result is not None and result["promoted"] is True
    # a blown-up fit is a warning + None, never an exception
    import gordo_tpu.perfmodel.service as service

    monkeypatch.setattr(
        service,
        "fit_and_promote",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert service.maybe_recalibrate(corpus_dir, table_path=path) is None


def test_supervisor_hook_records_the_recalibration(
    monkeypatch, corpus_dir, tmp_path
):
    """The lifecycle hook surface: env-gated, reads the telemetry-dir
    knob, stamps the cycle report and emits one recorder event."""
    from gordo_tpu.lifecycle.loop import CycleReport, LifecycleSupervisor

    events = []

    class FakeRecorder:
        def event(self, name, **attrs):
            events.append((name, attrs))

    sup = LifecycleSupervisor.__new__(LifecycleSupervisor)
    sup.collection_dir = corpus_dir
    sup.recorder = FakeRecorder()
    report = CycleReport()
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_RECAL", raising=False)
    monkeypatch.delenv("GORDO_TPU_TELEMETRY_DIR", raising=False)
    sup._maybe_recalibrate(report)
    assert "perfmodel" not in report.details and not events

    monkeypatch.setenv("GORDO_TPU_PERFMODEL_RECAL", "1")
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_MIN_SAMPLES", "8")
    monkeypatch.setenv(
        "GORDO_TPU_PERFMODEL_TABLE", str(tmp_path / "cost_table.json")
    )
    sup._maybe_recalibrate(report)
    assert report.details["perfmodel"]["promoted"] is True
    assert events and events[0][0] == "perfmodel_recalibrated"
    assert events[0][1]["promoted"] is True
