"""
Cold-start and corrupt-table drills (the satellite-3 contract): a
missing, truncated, mis-versioned or hand-mangled ``cost_table.json``
must warn and degrade to the analytic defaults — never traceback — and
a malformed ``learned`` section must degrade alone, keeping the table's
calibrated factors.
"""

import json
import logging

import pytest

from gordo_tpu.planner.costmodel import (
    CostModel,
    CostTable,
    load_table_safe,
    validate_learned_section,
)

from tests.perfmodel.conftest import SPEC

pytestmark = pytest.mark.perfmodel


def valid_section():
    return {
        "version": 1,
        "features": [
            "log_flops_per_sample",
            "log_members",
            "log_rows",
            "log_epochs",
            "bf16",
            "int8",
        ],
        "targets": {
            "device_ms": {
                "fleet_forward": {
                    "coef": [0.1, 0.0, 1.0, 1.0, 0.0, 0.0, 0.0],
                    "lo": [0.0] * 6,
                    "hi": [20.0] * 6,
                    "n": 64,
                    "holdout_mae_log": 0.05,
                }
            }
        },
    }


def test_load_table_safe_never_raises(tmp_path, caplog):
    assert load_table_safe(None).calibrated is False
    with caplog.at_level(logging.WARNING):
        missing = load_table_safe(str(tmp_path / "nowhere.json"))
    assert missing.to_dict() == CostTable().to_dict()
    assert "Unusable cost table" in caplog.text

    truncated = tmp_path / "truncated.json"
    truncated.write_text('{"version": 1, "run_factors": {"fleet')
    assert load_table_safe(str(truncated)).to_dict() == CostTable().to_dict()

    wrong_version = tmp_path / "versioned.json"
    wrong_version.write_text(json.dumps({"version": 99}))
    assert (
        load_table_safe(str(wrong_version)).to_dict() == CostTable().to_dict()
    )


@pytest.mark.parametrize(
    "mangle",
    [
        lambda s: 7,  # not a dict
        lambda s: {**s, "version": 2},  # future section version
        lambda s: {**s, "features": ["log_flops_per_sample"]},  # vocab drift
        lambda s: {**s, "targets": "oops"},
        lambda s: {**s, "targets": {"warp_speed": s["targets"]["device_ms"]}},
        lambda s: {
            **s,
            "targets": {
                "device_ms": {
                    "fleet_forward": {
                        **s["targets"]["device_ms"]["fleet_forward"],
                        "coef": [1.0, 2.0],  # wrong arity
                    }
                }
            },
        },
        lambda s: {
            **s,
            "targets": {
                "device_ms": {
                    "fleet_forward": {
                        **s["targets"]["device_ms"]["fleet_forward"],
                        "coef": [float("nan")] + [0.0] * 6,
                    }
                }
            },
        },
    ],
)
def test_malformed_learned_sections_degrade_with_a_warning(mangle, caplog):
    with caplog.at_level(logging.WARNING):
        assert validate_learned_section(mangle(valid_section())) is None
    assert "learned section" in caplog.text


def test_a_bad_learned_section_degrades_without_rejecting_the_table(
    tmp_path, caplog
):
    """The calibrated factors are still good: only the learned section
    is dropped."""
    doc = CostTable(run_factors={"fleet_fit": 3.0}).to_dict()
    doc["learned"] = {**valid_section(), "version": 42}
    path = tmp_path / "cost_table.json"
    path.write_text(json.dumps(doc))
    with caplog.at_level(logging.WARNING):
        table = load_table_safe(str(path))
    assert table.run_factors == {"fleet_fit": 3.0}  # factors survive
    assert table.learned is None and not table.has_learned
    assert "falling back to the analytic model" in caplog.text


def test_valid_section_round_trips_through_save_and_load(tmp_path):
    table = CostTable(learned=valid_section())
    path = str(tmp_path / "cost_table.json")
    table.save(path)
    loaded = load_table_safe(path)
    assert loaded.has_learned
    assert loaded.to_dict() == table.to_dict()


def test_knob_off_model_ignores_a_learned_section(monkeypatch):
    """One consistent ruler: with GORDO_TPU_PERFMODEL unset the learned
    section is inert — predictions are byte-for-byte the analytic
    model's even when the table carries fitted regressors."""
    monkeypatch.delenv("GORDO_TPU_PERFMODEL", raising=False)
    learned = CostModel(CostTable(learned=valid_section()))
    plain = CostModel(CostTable())
    for members, rows in ((1, 16), (8, 128), (16, 512)):
        assert learned.predict_serve_step_s(
            SPEC, members, rows, "f32"
        ) == plain.predict_serve_step_s(SPEC, members, rows, "f32")
    # the same table with the knob pinned on diverges in-domain
    pinned = CostModel(CostTable(learned=valid_section()), use_learned=True)
    assert pinned.predict_serve_step_s(SPEC, 8, 128, "f32") != plain.predict_serve_step_s(
        SPEC, 8, 128, "f32"
    )


def test_use_learned_resolves_once_at_construction(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL", raising=False)
    model = CostModel(CostTable(learned=valid_section()))
    assert model.use_learned is False
    monkeypatch.setenv("GORDO_TPU_PERFMODEL", "1")
    assert model.use_learned is False  # pinned for the instance lifetime
    assert CostModel(CostTable(learned=valid_section())).use_learned is True
