"""
Consumer drills: the serving-plane surfaces that READ the learned
model — the engine's predicted-HBM batch cap and OOM demotion, the
precision nomination, the trace report's predicted-vs-actual section,
and the ``gordo-tpu perfmodel`` CLI — each gated by its own knob and
degrading to the exact pre-perfmodel behavior when the model cannot
answer.
"""

import json

import pytest
from click.testing import CliRunner

from gordo_tpu.cli.cli import gordo_tpu_cli
from gordo_tpu.planner.costmodel import CostModel, CostTable
from gordo_tpu.serve import ServeConfig, ServeEngine
from gordo_tpu.serve import precision as P
from gordo_tpu.telemetry.trace_analysis import (
    analyze_trace,
    prediction_accuracy,
    render_analysis,
)

from tests.perfmodel.conftest import SPEC, write_corpus, grid_spans

pytestmark = [pytest.mark.perfmodel, pytest.mark.serve]


@pytest.fixture
def engine():
    engine = ServeEngine(
        ServeConfig(
            max_size=8,
            max_delay_ms=60.0,
            queue_depth=64,
            deadline_ms=10000.0,
            dispatchers=1,
            row_ladder=(8, 32),
            warmup_max_rows=32,
        )
    )
    try:
        yield engine
    finally:
        engine.shutdown(drain=True)


# -- predicted-HBM batch cap -------------------------------------------------


def test_model_row_cap_defaults_off(engine, monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES", raising=False)
    assert engine._model_row_cap(SPEC, "f32") is None


def test_model_row_cap_picks_the_tallest_fitting_rung(engine, monkeypatch):
    model = engine._cost_model()
    top = engine.member_ladder[-1]
    low = model.predict_serve_hbm_bytes(SPEC, top, 8, "f32")
    high = model.predict_serve_hbm_bytes(SPEC, top, 32, "f32")
    assert low < high
    # budget between the two rungs: only the 8-row rung fits
    monkeypatch.setenv(
        "GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES", str((low + high) // 2)
    )
    assert engine._model_row_cap(SPEC, "f32") == 8
    # budget above both: the top rung (== uncapped behavior)
    engine._model_row_caps.clear()
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES", str(high * 2))
    assert engine._model_row_cap(SPEC, "f32") == 32
    # budget below both: 0 — every batch serves unbatched
    engine._model_row_caps.clear()
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BATCH_CAP_BYTES", str(low // 2))
    assert engine._model_row_cap(SPEC, "f32") == 0


# -- predicted-HBM OOM demotion ----------------------------------------------


def test_hbm_aware_cap_defaults_off(engine, monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_BREAKER", raising=False)
    assert engine._hbm_aware_cap(SPEC, "f32", 8, 32, "members") is None


def test_hbm_aware_cap_drops_to_a_predicted_safe_rung(engine, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BREAKER", "1")
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BREAKER_SAFETY", "0.8")
    cap = engine._hbm_aware_cap(SPEC, "f32", 8, 32, "members")
    model = engine._cost_model()
    failed = model.predict_serve_hbm_bytes(SPEC, 8, 32, "f32")
    assert cap is not None and cap < 8
    assert model.predict_serve_hbm_bytes(SPEC, cap, 32, "f32") <= 0.8 * failed
    # a tight safety margin may skip SEVERAL rungs at once
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BREAKER_SAFETY", "0.3")
    tight = engine._hbm_aware_cap(SPEC, "f32", 8, 32, "members")
    assert tight is not None and tight <= cap
    # rows axis: the demoted rung comes off the configured row ladder
    row_cap = engine._hbm_aware_cap(SPEC, "f32", 1, 32, "rows")
    assert row_cap in (None, 8)  # 8 is the only lower rung


def test_oom_demotion_records_whether_the_model_informed_it(
    engine, monkeypatch
):
    exc = RuntimeError("RESOURCE_EXHAUSTED: out of memory")
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_BREAKER", raising=False)
    engine._note_resource_exhausted(SPEC, "f32", 8, 32, exc)
    fixed = engine._member_caps.get((SPEC, "f32"))
    assert fixed == 4  # the fixed heuristic: padded // 2
    engine._member_caps.clear()
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BREAKER", "1")
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_BREAKER_SAFETY", "0.3")
    engine._note_resource_exhausted(SPEC, "f32", 8, 32, exc)
    informed = engine._member_caps.get((SPEC, "f32"))
    assert informed is not None and informed < fixed  # skipped rungs


# -- precision nomination ----------------------------------------------------


def reduced_favoring_table():
    """A learned section with measured evidence that bf16 is fastest."""
    entry = {
        "coef": [0.1, 0.0, 1.0, 1.0, 0.0, -0.5, 0.2],
        "lo": [0.0] * 6,
        "hi": [30.0] * 6,
        "n": 64,
        "holdout_mae_log": 0.05,
    }
    return CostTable(
        learned={
            "version": 1,
            "features": [
                "log_flops_per_sample",
                "log_members",
                "log_rows",
                "log_epochs",
                "bf16",
                "int8",
            ],
            "targets": {"device_ms": {"fleet_forward": entry}},
        }
    )


def test_model_preferred_defaults_off(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_PRECISION", raising=False)
    model = CostModel(reduced_favoring_table(), use_learned=True)
    assert P.model_preferred(SPEC, 8, 32, model) is None


def test_model_preferred_nominates_the_measured_fastest(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_PRECISION", "1")
    model = CostModel(reduced_favoring_table(), use_learned=True)
    assert P.model_preferred(SPEC, 8, 32, model) == "bf16"


def test_model_preferred_requires_evidence_for_every_rung(monkeypatch):
    """Partial evidence keeps the configured rung: an analytic-only
    table (whose per-precision priors ALWAYS favor reduced) must not
    flip the f32 default."""
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_PRECISION", "1")
    assert P.model_preferred(SPEC, 8, 32, CostModel(CostTable())) is None
    # an f32-favoring section nominates nothing either
    table = reduced_favoring_table()
    entry = table.learned["targets"]["device_ms"]["fleet_forward"]
    entry["coef"] = [0.1, 0.0, 1.0, 1.0, 0.0, 0.5, 0.7]  # f32 wins
    assert P.model_preferred(SPEC, 8, 32, CostModel(table)) is None


# -- trace predicted-vs-actual section ---------------------------------------


def accuracy_spans():
    return [
        {
            "name": "serve_batch",
            "attributes": {
                "program": "fleet_forward",
                "device_ms": 10.0,
                "predicted_device_ms": 12.0,
            },
        },
        {
            "name": "serve_batch",
            "attributes": {
                "program": "fleet_forward",
                "device_ms": 20.0,
                "predicted_device_ms": 18.0,
            },
        },
        {  # the -1.0 estimator-unavailable sentinel is excluded
            "name": "serve_batch",
            "attributes": {"device_ms": 5.0, "predicted_device_ms": -1.0},
        },
        {  # measured-zero spans never divide by zero
            "name": "serve_batch",
            "attributes": {"device_ms": 0.0, "predicted_device_ms": 3.0},
        },
    ]


def test_prediction_accuracy_scores_only_honest_pairs():
    doc = prediction_accuracy(accuracy_spans())
    assert set(doc) == {"fleet_forward"}
    entry = doc["fleet_forward"]
    assert entry["count"] == 2
    assert entry["error_p50"] == pytest.approx(0.1)
    assert entry["error_p95"] == pytest.approx(0.2)
    assert entry["bias"] == pytest.approx(0.9)
    assert prediction_accuracy([]) is None


def test_trace_report_carries_the_accuracy_table(tmp_path):
    path = tmp_path / "serve_trace.jsonl"
    with open(path, "w") as f:
        for span in accuracy_spans():
            f.write(json.dumps(span) + "\n")
    doc = analyze_trace(str(path))
    assert doc["prediction_accuracy"]["fleet_forward"]["count"] == 2
    text = render_analysis(doc)
    assert "Prediction accuracy" in text
    assert "fleet_forward" in text


# -- the perfmodel CLI -------------------------------------------------------


def test_perfmodel_cli_fit_status_eval(tmp_path):
    corpus = str(tmp_path / "telemetry")
    write_corpus(corpus, grid_spans(jitter=0.02))
    table = str(tmp_path / "cost_table.json")
    runner = CliRunner()

    result = runner.invoke(
        gordo_tpu_cli,
        [
            "perfmodel", "fit", corpus,
            "--table", table, "--min-samples", "8", "--as-json",
        ],
    )
    assert result.exit_code == 0, result.output
    doc = json.loads(result.output)
    assert doc["promoted"] is True

    result = runner.invoke(
        gordo_tpu_cli, ["perfmodel", "status", "--table", table, "--as-json"]
    )
    assert result.exit_code == 0, result.output
    status = json.loads(result.output)
    assert status["learned"] is True
    assert {m["target"] for m in status["models"]} >= {
        "device_ms", "compile_ms",
    }

    result = runner.invoke(
        gordo_tpu_cli,
        ["perfmodel", "eval", corpus, "--table", table, "--as-json"],
    )
    assert result.exit_code == 0, result.output
    evaluation = json.loads(result.output)
    forward = next(
        m
        for m in evaluation["models"]
        if (m["target"], m["program"]) == ("device_ms", "fleet_forward")
    )
    assert forward["learned_mae_log"] < forward["analytic_mae_log"]


def test_perfmodel_cli_fit_on_an_empty_corpus_is_calm(tmp_path):
    empty = tmp_path / "empty"
    empty.mkdir()
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        ["perfmodel", "fit", str(empty), "--as-json"],
    )
    assert result.exit_code == 0, result.output
    doc = json.loads(result.output)
    assert doc["promoted"] is False
    assert "empty corpus" in doc["reason"]
