"""
Regressor drills: the closed-form ridge recovers a known log-linear
law, the holdout split is deterministic and stratified, and the sample
floor refuses to fit noise-sized populations.
"""

import math

import pytest

from gordo_tpu.perfmodel import (
    analytic_prediction,
    evaluate_rows,
    fit_ridge,
    fit_section,
    holdout_split,
)
from gordo_tpu.perfmodel.features import TrainingRow, rows_from_spans
from gordo_tpu.perfmodel.model import coef_predict, min_samples_floor
from gordo_tpu.planner.costmodel import CostTable, learned_feature_vector

from tests.perfmodel.conftest import grid_spans, true_device_ms

pytestmark = pytest.mark.perfmodel


def device_rows():
    return [
        r
        for r in rows_from_spans(grid_spans())
        if r.target == "device_ms"
    ]


def test_fit_ridge_recovers_an_exact_log_linear_law():
    """y = 0.05 * members^0.9 * rows^0.8 is exactly linear in the log
    features; the closed-form fit must recover the exponents."""
    rows = device_rows()
    coef = fit_ridge(
        [r.features for r in rows], [math.log(r.y) for r in rows]
    )
    assert coef[2] == pytest.approx(0.9, abs=0.02)  # log_members
    assert coef[3] == pytest.approx(0.8, abs=0.02)  # log_rows
    assert coef[5] == pytest.approx(math.log(0.7), abs=0.05)  # bf16 scale
    for row in rows:
        assert coef_predict(coef, row.features) == pytest.approx(
            row.y, rel=0.05
        )


def test_fit_ridge_rejects_empty_input():
    with pytest.raises(ValueError):
        fit_ridge([], [])


def test_holdout_split_is_deterministic_and_stratified():
    rows = device_rows()
    train_a, holdout_a = holdout_split(rows)
    train_b, holdout_b = holdout_split(list(reversed(rows)))
    assert train_a == train_b and holdout_a == holdout_b
    assert len(holdout_a) == pytest.approx(len(rows) / 4, abs=1)
    assert sorted(train_a + holdout_a) == sorted(rows)


def test_tiny_populations_still_hold_one_out():
    rows = [
        TrainingRow("device_ms", "p", (float(i), 0, 0, 0, 0, 0), float(i + 1))
        for i in range(3)
    ]
    train, holdout = holdout_split(rows)
    assert len(holdout) == 1 and len(train) == 2


def test_evaluate_rows_excludes_unanswered_predictions():
    rows = device_rows()[:8]
    mae, n = evaluate_rows(rows, lambda r: r.y)  # perfect oracle
    assert (mae, n) == (pytest.approx(0.0), 8)
    mae, n = evaluate_rows(rows, lambda r: None)
    assert n == 0 and mae == math.inf
    # half answered: only the answered half is scored
    mae, n = evaluate_rows(
        rows, lambda r: r.y if r.features[1] > 0.0 else None
    )
    assert 0 < n < 8


def test_analytic_prediction_replays_the_formula_per_target():
    table = CostTable()
    features = learned_feature_vector(100.0, 8, 128, 1, "f32")
    device = analytic_prediction(table, "device_ms", "fleet_forward", features)
    # (flops*members*rows / throughput + dispatch) * 1000
    expected = (100.0 * 8 * 128 / table.throughput + table.dispatch_s) * 1000.0
    assert device == pytest.approx(expected, rel=1e-6)
    compiled = analytic_prediction(table, "compile_ms", "fleet_forward", features)
    assert compiled == pytest.approx(
        (table.compile_floor_s + table.compile_per_flop * 100.0) * 1000.0,
        rel=1e-6,
    )
    # HBM has no feature-only analytic counterpart
    assert analytic_prediction(table, "hbm_bytes", "fleet_forward", features) is None


def test_min_samples_floor_env_and_override(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PERFMODEL_MIN_SAMPLES", raising=False)
    assert min_samples_floor() == 32
    monkeypatch.setenv("GORDO_TPU_PERFMODEL_MIN_SAMPLES", "10")
    assert min_samples_floor() == 10
    assert min_samples_floor(override=4) == 4
    assert min_samples_floor(override=0) == 2  # never below 2


def test_fit_section_skips_small_populations_and_reports_them():
    rows = device_rows()
    rows.append(
        TrainingRow("device_ms", "fleet_fit", rows[0].features, 5.0)
    )
    section = fit_section(rows, min_samples=8)
    assert "fleet_forward" in section["targets"]["device_ms"]
    assert "fleet_fit" not in section["targets"]["device_ms"]
    assert section["skipped"] == {"device_ms/fleet_fit": 1}
    entry = section["targets"]["device_ms"]["fleet_forward"]
    assert entry["n"] == len(rows) - 1
    assert entry["holdout_mae_log"] < 0.05  # the law is exactly learnable
    assert len(entry["coef"]) == 7
    assert len(entry["lo"]) == len(entry["hi"]) == 6


def test_fit_section_returns_none_when_nothing_qualifies():
    assert fit_section(device_rows()[:4], min_samples=100) is None
    assert fit_section([], min_samples=2) is None


def test_fit_section_round_trips_through_table_validation():
    from gordo_tpu.planner.costmodel import validate_learned_section

    section = fit_section(device_rows(), min_samples=8)
    assert validate_learned_section(section) is section
    table = CostTable(learned=section)
    row = device_rows()[0]
    predicted = table.learned_predict("device_ms", "fleet_forward", row.features)
    assert predicted == pytest.approx(row.y, rel=0.1)


def test_learned_prediction_refuses_out_of_domain_shapes():
    section = fit_section(device_rows(), min_samples=8)
    table = CostTable(learned=section)
    # 4096 members is far outside the trained 1..16 box + slack
    far = learned_feature_vector(100.0, 4096, 32, 1, "f32")
    assert table.learned_predict("device_ms", "fleet_forward", far) is None
