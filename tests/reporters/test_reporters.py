"""
Reporter tests, mirroring the reference's strategy
(tests/gordo/reporters/test_postgres_reporter.py and
test_mlflow_reporter.py) but with the dependency-free local backends:
sqlite for Postgres, the file tracking store for MLflow.
"""

import json
import os

import pytest

from gordo_tpu.builder import ModelBuilder
from gordo_tpu.machine import Machine
from gordo_tpu.reporters import (
    LogReporter,
    MlFlowReporter,
    MlflowLoggingError,
    PostgresReporter,
    PostgresReporterException,
    create_reporters,
)
from gordo_tpu.reporters.mlflow import (
    FileTrackingClient,
    batch_log_items,
    get_kwargs_from_secret,
    get_machine_log_items,
    get_spauth_kwargs,
    get_workspace_kwargs,
    mlflow_context,
)

MODEL_DEF = {
    "gordo_tpu.models.JaxAutoEncoder": {
        "kind": "feedforward_model",
        "encoding_dim": [8, 4],
        "encoding_func": ["tanh", "tanh"],
        "decoding_dim": [4, 8],
        "decoding_func": ["tanh", "tanh"],
        "epochs": 2,
    }
}
DATASET_DEF = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
    "tag_list": ["tag-1", "tag-2"],
}


@pytest.fixture(scope="module")
def built_machine():
    machine = Machine.from_config(
        {"name": "machine-1", "model": MODEL_DEF, "dataset": dict(DATASET_DEF)},
        project_name="proj",
    )
    _, machine = ModelBuilder(machine).build()
    return machine


# -- postgres ----------------------------------------------------------------


def test_postgres_reporter_upserts(tmp_path, built_machine):
    db = f"sqlite://{tmp_path}/machines.db"
    reporter = PostgresReporter(host=db)
    reporter.report(built_machine)
    row = reporter.fetch("machine-1")
    assert row["name"] == "machine-1"
    assert row["dataset"]["tag_list"] == ["tag-1", "tag-2"]
    assert row["model"] == built_machine.to_dict()["model"]
    assert "build_metadata" in row["metadata"]

    # Reporting the same machine again updates, not duplicates.
    reporter.report(built_machine)
    count_conn = reporter._conn
    (n,) = count_conn.execute("SELECT COUNT(*) FROM machine").fetchone()
    assert n == 1


def test_postgres_reporter_memory_backend(built_machine):
    reporter = PostgresReporter(host="sqlite://:memory:")
    reporter.report(built_machine)
    assert reporter.fetch("machine-1")["name"] == "machine-1"


def test_postgres_reporter_fetch_missing():
    reporter = PostgresReporter(host="sqlite://:memory:")
    with pytest.raises(PostgresReporterException):
        reporter.fetch("nope")


def test_postgres_reporter_requires_driver_for_real_host():
    # No psycopg2 in this environment: a non-sqlite host must fail loudly.
    with pytest.raises(PostgresReporterException):
        PostgresReporter(host="postgres.example.com")


def test_postgres_reporter_round_trips_serializer(tmp_path):
    db = f"sqlite://{tmp_path}/machines.db"
    reporter = PostgresReporter(host=db)
    definition = reporter.to_dict()
    assert definition["gordo_tpu.reporters.postgres.PostgresReporter"]["host"] == db
    clone = PostgresReporter.from_dict(definition)
    assert isinstance(clone, PostgresReporter)
    assert clone.host == db


# -- mlflow ------------------------------------------------------------------


def test_get_machine_log_items(built_machine):
    metrics, params = get_machine_log_items(built_machine)
    param_keys = [p.key for p in params]
    assert "project_name" in param_keys
    assert "name" in param_keys
    assert "train_start_date" in param_keys
    assert "model_offset" in param_keys
    assert any(k.startswith("fold-1") for k in param_keys)  # CV split bounds

    metric_keys = {m.key for m in metrics}
    # Aggregate CV metrics present, per-tag ones skipped.
    assert any(k.startswith("explained-variance-score") for k in metric_keys)
    assert not any("tag-1" in k for k in metric_keys)
    # Fit history series logged step-wise.
    assert "loss" in metric_keys
    loss_steps = [m.step for m in metrics if m.key == "loss"]
    assert loss_steps == list(range(len(loss_steps)))
    assert "model_training_duration_sec" in metric_keys


@pytest.mark.parametrize(
    "n_metrics,n_params,expected_batches",
    [(0, 0, 0), (1, 1, 1), (200, 100, 1), (201, 100, 2), (10, 250, 3)],
)
def test_batch_log_items_limits(n_metrics, n_params, expected_batches):
    from gordo_tpu.reporters.mlflow import Metric, Param

    metrics = [Metric(f"m{i}", 1.0, 0, 0) for i in range(n_metrics)]
    params = [Param(f"p{i}", "v") for i in range(n_params)]
    batches = batch_log_items(metrics, params)
    assert len(batches) == expected_batches
    assert all(len(b["metrics"]) <= 200 for b in batches)
    assert all(len(b["params"]) <= 100 for b in batches)
    assert sum(len(b["metrics"]) for b in batches) == n_metrics
    assert sum(len(b["params"]) for b in batches) == n_params


def test_get_kwargs_from_secret(monkeypatch):
    with pytest.raises(MlflowLoggingError):
        get_kwargs_from_secret("NOT_SET_VAR", ["a"])
    monkeypatch.setenv("SECRET", "1:2:3")
    assert get_kwargs_from_secret("SECRET", ["a", "b", "c"]) == {
        "a": "1",
        "b": "2",
        "c": "3",
    }
    with pytest.raises(MlflowLoggingError):
        get_kwargs_from_secret("SECRET", ["a", "b"])
    monkeypatch.setenv("SECRET", "")
    assert get_kwargs_from_secret("SECRET", ["a", "b"]) == {}


def test_workspace_and_spauth_kwargs(monkeypatch):
    monkeypatch.setenv("AZUREML_WORKSPACE_STR", "sub:rg:ws")
    monkeypatch.setenv("DL_SERVICE_AUTH_STR", "tenant:spid:sppw")
    assert get_workspace_kwargs() == {
        "subscription_id": "sub",
        "resource_group": "rg",
        "workspace_name": "ws",
    }
    assert get_spauth_kwargs() == {
        "tenant_id": "tenant",
        "service_principal_id": "spid",
        "service_principal_password": "sppw",
    }


def test_mlflow_context_file_backend(tmp_path, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_MLFLOW_DIR", str(tmp_path))
    with mlflow_context("exp", "key123") as (client, run_id):
        assert isinstance(client, FileTrackingClient)
        client.log_batch(run_id, metrics=[], params=[])
    run_dir = os.path.join(str(tmp_path), run_id)
    assert open(os.path.join(run_dir, "status")).read() == "FINISHED"
    assert json.load(open(os.path.join(run_dir, "tags.json"))) == {
        "model_key": "key123"
    }


def test_mlflow_reporter_end_to_end(tmp_path, monkeypatch, built_machine):
    monkeypatch.setenv("GORDO_TPU_MLFLOW_DIR", str(tmp_path))
    MlFlowReporter().report(built_machine)

    exp_dir = tmp_path / "machine-1"
    runs = list(exp_dir.iterdir())
    assert len(runs) == 1
    run_dir = runs[0]
    batches = [
        json.loads(line)
        for line in (run_dir / "batches.jsonl").read_text().splitlines()
    ]
    assert batches
    all_params = [p for b in batches for p in b["params"]]
    assert ["name", "machine-1"] in all_params
    metadata = json.load(open(run_dir / "artifacts" / "metadata.json"))
    assert metadata["name"] == "machine-1"
    assert (run_dir / "status").read_text() == "FINISHED"


# -- wiring ------------------------------------------------------------------


def test_create_reporters_from_definitions(tmp_path):
    db = f"sqlite://{tmp_path}/machines.db"
    reporters = create_reporters(
        [
            {"gordo_tpu.reporters.postgres.PostgresReporter": {"host": db}},
            {"gordo_tpu.reporters.base.LogReporter": {}},
        ]
    )
    assert isinstance(reporters[0], PostgresReporter)
    assert isinstance(reporters[1], LogReporter)


def test_machine_report_runs_configured_reporters(tmp_path, built_machine):
    db = f"sqlite://{tmp_path}/machines.db"
    built_machine.runtime = {
        "reporters": [
            {"gordo_tpu.reporters.postgres.PostgresReporter": {"host": db}}
        ]
    }
    built_machine.report()
    assert PostgresReporter(host=db).fetch("machine-1")["name"] == "machine-1"
