import pytest
import yaml

from gordo_tpu.machine import Machine
from gordo_tpu.machine.metadata import Metadata

MODEL_DEF = {
    "gordo_tpu.models.JaxAutoEncoder": {"kind": "feedforward_hourglass"}
}
DATASET_DEF = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-10T00:00:00+00:00",
    "tag_list": ["tag-1", "tag-2"],
}


def make_machine(**overrides):
    config = {
        "name": "my-machine",
        "model": MODEL_DEF,
        "dataset": dict(DATASET_DEF),
        **overrides,
    }
    return Machine.from_config(config, project_name="test-project")


def test_from_config_basics():
    machine = make_machine()
    assert machine.name == "my-machine"
    assert machine.project_name == "test-project"
    assert machine.host == "gordoserver-test-project-my-machine"
    assert machine.evaluation["cv_mode"] == "full_build"
    assert isinstance(machine.metadata, Metadata)


def test_globals_merge_directions():
    config_globals = {
        "runtime": {"server": {"replicas": 2}},
        "evaluation": {"cv_mode": "cross_val_only"},
        "dataset": {"resolution": "1h"},
    }
    machine = Machine.from_config(
        {
            "name": "m",
            "model": MODEL_DEF,
            "dataset": dict(DATASET_DEF),
            "runtime": {"server": {"replicas": 5}},
            "evaluation": {"cv_mode": "full_build"},
        },
        project_name="p",
        config_globals=config_globals,
    )
    # machine-local overrides globals for runtime + evaluation
    assert machine.runtime["server"]["replicas"] == 5
    assert machine.evaluation["cv_mode"] == "full_build"
    # reference quirk: globals patch over the machine's dataset block
    assert machine.dataset.resolution == "1h"


def test_invalid_name_rejected():
    with pytest.raises(ValueError):
        make_machine(name="Invalid_Name!")


def test_invalid_model_rejected():
    with pytest.raises(ValueError):
        make_machine(model={"no.such.module.Klass": {}})


def test_yaml_in_string_fields_parsed():
    machine = Machine.from_config(
        {
            "name": "m",
            "model": yaml.dump(MODEL_DEF),
            "dataset": yaml.dump(DATASET_DEF),
        },
        project_name="p",
    )
    assert machine.dataset.resolution == "10min"


def test_json_round_trip():
    machine = make_machine()
    clone = Machine.from_dict(yaml.safe_load(machine.to_json()))
    assert clone == machine
    assert clone.dataset.to_dict()["tag_list"] == ["tag-1", "tag-2"]


def test_to_yaml_round_trip():
    machine = make_machine()
    clone = Machine.from_dict(yaml.safe_load(machine.to_yaml()))
    assert clone == machine


def test_missing_model_raises():
    with pytest.raises(ValueError):
        Machine.from_config(
            {"name": "m", "dataset": dict(DATASET_DEF)}, project_name="p"
        )


def test_missing_project_name_raises():
    with pytest.raises(ValueError):
        Machine.from_config({"name": "m", "model": MODEL_DEF, "dataset": dict(DATASET_DEF)})
