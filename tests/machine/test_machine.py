import pytest
import yaml

from gordo_tpu.machine import Machine
from gordo_tpu.machine.metadata import Metadata

MODEL_DEF = {
    "gordo_tpu.models.JaxAutoEncoder": {"kind": "feedforward_hourglass"}
}
DATASET_DEF = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-10T00:00:00+00:00",
    "tag_list": ["tag-1", "tag-2"],
}


def make_machine(**overrides):
    config = {
        "name": "my-machine",
        "model": MODEL_DEF,
        "dataset": dict(DATASET_DEF),
        **overrides,
    }
    return Machine.from_config(config, project_name="test-project")


def test_from_config_basics():
    machine = make_machine()
    assert machine.name == "my-machine"
    assert machine.project_name == "test-project"
    assert machine.host == "gordoserver-test-project-my-machine"
    assert machine.evaluation["cv_mode"] == "full_build"
    assert isinstance(machine.metadata, Metadata)


def test_globals_merge_directions():
    config_globals = {
        "runtime": {"server": {"replicas": 2}},
        "evaluation": {"cv_mode": "cross_val_only"},
        "dataset": {"resolution": "1h"},
    }
    machine = Machine.from_config(
        {
            "name": "m",
            "model": MODEL_DEF,
            "dataset": dict(DATASET_DEF),
            "runtime": {"server": {"replicas": 5}},
            "evaluation": {"cv_mode": "full_build"},
        },
        project_name="p",
        config_globals=config_globals,
    )
    # machine-local overrides globals for runtime + evaluation
    assert machine.runtime["server"]["replicas"] == 5
    assert machine.evaluation["cv_mode"] == "full_build"
    # reference quirk: globals patch over the machine's dataset block
    assert machine.dataset.resolution == "1h"


def test_invalid_name_rejected():
    with pytest.raises(ValueError):
        make_machine(name="Invalid_Name!")


def test_invalid_model_rejected():
    with pytest.raises(ValueError):
        make_machine(model={"no.such.module.Klass": {}})


def test_yaml_in_string_fields_parsed():
    machine = Machine.from_config(
        {
            "name": "m",
            "model": yaml.dump(MODEL_DEF),
            "dataset": yaml.dump(DATASET_DEF),
        },
        project_name="p",
    )
    assert machine.dataset.resolution == "10min"


def test_json_round_trip():
    machine = make_machine()
    clone = Machine.from_dict(yaml.safe_load(machine.to_json()))
    assert clone == machine
    assert clone.dataset.to_dict()["tag_list"] == ["tag-1", "tag-2"]


def test_to_yaml_round_trip():
    machine = make_machine()
    clone = Machine.from_dict(yaml.safe_load(machine.to_yaml()))
    assert clone == machine


def test_missing_model_raises():
    with pytest.raises(ValueError):
        Machine.from_config(
            {"name": "m", "dataset": dict(DATASET_DEF)}, project_name="p"
        )


def test_missing_project_name_raises():
    with pytest.raises(ValueError):
        Machine.from_config({"name": "m", "model": MODEL_DEF, "dataset": dict(DATASET_DEF)})


def test_copy_is_independent_and_cache_free():
    """Machine.copy(): build results must not share mutable state with the
    caller's Machine, and a live dataset's provider caches (e.g.
    FileDataProvider's loaded wide frame) must not be duplicated into the
    copy — the dataset is rebuilt from config."""
    machine = Machine.from_config(
        {
            "name": "copy-src",
            "model": {"gordo_tpu.models.JaxAutoEncoder": {"kind": "feedforward_model"}},
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2020-01-01T00:00:00+00:00",
                "train_end_date": "2020-01-02T00:00:00+00:00",
                "tag_list": ["cp-a", "cp-b"],
            },
        },
        project_name="copy-proj",
    )
    machine.dataset.get_data()  # populate any lazy per-dataset state
    clone = machine.copy()
    assert clone is not machine
    assert clone.dataset is not machine.dataset
    assert clone.metadata is not machine.metadata
    # dataset was rebuilt from config, not carried over as the live object
    assert clone.dataset.to_dict() == machine.dataset.to_dict()
    # mutating the clone's metadata must not leak back
    clone.metadata.user_defined["machine-metadata"] = {"x": 1}
    assert machine.metadata.user_defined.get("machine-metadata") != {"x": 1}


def test_copy_strips_file_provider_frame_cache(tmp_path):
    """A FileDataProvider that has loaded its source must copy WITHOUT the
    cached frame (review finding: deepcopy duplicated multi-MB frames into
    every build result)."""
    import numpy as np
    import pandas as pd

    idx = pd.date_range("2020-01-01", periods=200, freq="10min", tz="UTC")
    frame = pd.DataFrame(
        {"fp-a": np.arange(200.0), "fp-b": np.ones(200)}, index=idx
    )
    path = tmp_path / "data.parquet"
    frame.to_parquet(path)
    machine = Machine.from_config(
        {
            "name": "copy-file",
            "model": {"gordo_tpu.models.JaxAutoEncoder": {"kind": "feedforward_model"}},
            "dataset": {
                "type": "TimeSeriesDataset",
                "train_start_date": "2020-01-01T00:00:00+00:00",
                "train_end_date": "2020-01-03T00:00:00+00:00",
                "tag_list": ["fp-a", "fp-b"],
                "data_provider": {"type": "FileDataProvider", "path": str(path)},
            },
        },
        project_name="copy-proj",
    )
    machine.dataset.get_data()  # loads + caches the wide frame
    assert machine.dataset.data_provider._wide_frame is not None
    clone = machine.copy()
    assert clone.dataset.data_provider._wide_frame is None


def test_metadata_to_dict_matches_dataclasses_json_walk():
    """The hand-rolled Metadata.to_dict must emit exactly what the generic
    dataclasses_json walk emits (schema parity pinned), round-trip through
    from_dict, and return independent copies of the dict leaves."""
    pytest.importorskip(
        "dataclasses_json",
        reason="schema-parity pin needs the real dataclasses_json walk "
        "(the stdlib compat shim has no .schema())",
    )
    from gordo_tpu.machine.metadata import (
        BuildMetadata,
        CrossValidationMetaData,
        DatasetBuildMetadata,
        Metadata,
        ModelBuildMetadata,
    )

    meta = Metadata(
        user_defined={"global-metadata": {"a": 1}, "machine-metadata": {}},
        build_metadata=BuildMetadata(
            model=ModelBuildMetadata(
                model_offset=2,
                model_creation_date="2026-01-01",
                model_builder_version="1.2.3",
                cross_validation=CrossValidationMetaData(
                    scores={"r2-score": {"fold-1": 0.5}},
                    cv_duration_sec=1.5,
                    splits={"fold-1": [0, 1]},
                ),
                model_training_duration_sec=3.0,
                model_meta={"history": {"loss": [1.0, 0.5]}},
            ),
            dataset=DatasetBuildMetadata(
                query_duration_sec=0.1, dataset_meta={"row_count": 10}
            ),
        ),
    )
    # the override must actually be installed — @dataclass_json clobbers a
    # to_dict defined in the class body (review finding: the first version
    # of this optimization was silently dead code)
    from dataclasses_json.api import DataClassJsonMixin

    assert Metadata.to_dict is not DataClassJsonMixin.to_dict
    got = meta.to_dict()
    # the generic walk on an equal instance
    generic = Metadata.schema().dump(meta)
    assert got == generic
    # round-trip
    back = Metadata.from_dict(got)
    assert back.build_metadata.model.cross_validation.scores == {
        "r2-score": {"fold-1": 0.5}
    }
    # independence: mutating the snapshot must not touch the instance
    got["build_metadata"]["model"]["cross_validation"]["scores"]["x"] = 1
    assert "x" not in meta.build_metadata.model.cross_validation.scores
