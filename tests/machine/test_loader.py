import pytest

from gordo_tpu.machine.loader import (
    load_globals_config,
    load_machine_config,
    load_model_config,
)


def test_yaml_string_fields_parsed():
    config = load_machine_config(
        {"name": "m", "model": "{'a.b.C': {'x': 1}}", "runtime": "{'k': 2}"}
    )
    assert config["model"] == {"a.b.C": {"x": 1}}
    assert config["runtime"] == {"k": 2}


def test_name_required():
    with pytest.raises(ValueError):
        load_machine_config({"model": {}})


def test_project_name_required():
    with pytest.raises(ValueError):
        load_model_config({"name": "m"})
    config = load_model_config({"name": "m", "project_name": "p"})
    assert config["project_name"] == "p"


def test_globals_none_ok():
    assert load_globals_config(None) == {}
    with pytest.raises(ValueError):
        load_globals_config(["not", "a", "dict"])
