import datetime

import pytest

from gordo_tpu.machine.validators import (
    BaseDescriptor,
    ValidDatetime,
    ValidMachineRuntime,
    ValidTagList,
    ValidUrlString,
    fix_resource_limits,
)


class Holder:
    url = ValidUrlString()
    dt = ValidDatetime()
    tags = ValidTagList()
    runtime = ValidMachineRuntime()


@pytest.mark.parametrize("good", ["valid-name", "a", "abc123", "a-b-c"])
def test_valid_url_strings(good):
    h = Holder()
    h.url = good
    assert h.url == good


@pytest.mark.parametrize(
    "bad", ["Has_Underscore", "UPPER", "-leading", "trailing-", "a" * 64, "", "dot.ted"]
)
def test_invalid_url_strings(bad):
    with pytest.raises(ValueError):
        Holder().url = bad


def test_datetime_requires_tz():
    h = Holder()
    h.dt = "2020-01-01T00:00:00+00:00"
    assert h.dt.tzinfo is not None
    with pytest.raises(ValueError):
        h.dt = datetime.datetime(2020, 1, 1)
    with pytest.raises(ValueError):
        h.dt = "2020-01-01T00:00:00"


def test_tag_list():
    h = Holder()
    h.tags = ["a", "b"]
    assert h.tags == ["a", "b"]
    with pytest.raises(ValueError):
        h.tags = []


def test_fix_resource_limits():
    out = fix_resource_limits(
        {"requests": {"memory": 1000, "cpu": 100}, "limits": {"memory": 500, "cpu": 200}}
    )
    assert out["limits"]["memory"] == 1000
    assert out["limits"]["cpu"] == 200


def test_fix_resource_limits_non_numeric():
    with pytest.raises(ValueError):
        fix_resource_limits(
            {"requests": {"memory": "1G"}, "limits": {"memory": 500}}
        )


def test_runtime_fixes_nested_resources():
    h = Holder()
    h.runtime = {
        "builder": {
            "resources": {
                "requests": {"memory": 4000},
                "limits": {"memory": 1000},
            }
        }
    }
    assert h.runtime["builder"]["resources"]["limits"]["memory"] == 4000


def test_descriptor_base():
    class D(BaseDescriptor):
        pass

    class Obj:
        x = D()

    o = Obj()
    assert o.x is None
    o.x = 5
    assert o.x == 5
