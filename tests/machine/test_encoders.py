"""JSON/YAML encoders for machine documents (reference: gordo/machine/encoders.py)."""

import datetime
import json

import yaml

from gordo_tpu.dataset.sensor_tag import SensorTag
from gordo_tpu.machine.encoders import MachineJSONEncoder, MachineSafeDumper


def test_json_encoder_datetime():
    stamp = datetime.datetime(2020, 1, 2, 3, 4, 5, tzinfo=datetime.timezone.utc)
    out = json.loads(json.dumps({"t": stamp}, cls=MachineJSONEncoder))
    assert out["t"].startswith("2020-01-02")
    assert "03:04:05" in out["t"]


def test_json_encoder_sensor_tag():
    tag = SensorTag("tag-a", asset="plant-1")
    out = json.loads(json.dumps({"tag": tag}, cls=MachineJSONEncoder))
    assert out["tag"]["name"] == "tag-a"
    assert out["tag"]["asset"] == "plant-1"


def test_json_encoder_rejects_unknown():
    class Strange:
        pass

    try:
        json.dumps({"x": Strange()}, cls=MachineJSONEncoder)
    except TypeError:
        return
    raise AssertionError("unknown types must still raise TypeError")


def test_safe_dumper_multiline_literal_block():
    document = {"model": "line-one\nline-two\n"}
    text = yaml.dump(document, Dumper=MachineSafeDumper)
    # multi-line strings render as YAML literal blocks (the config dialect
    # the reference embeds model/dataset strings with)
    assert "|" in text
    assert yaml.safe_load(text) == document


def test_safe_dumper_round_trips_machine_to_yaml():
    from gordo_tpu.machine import Machine

    machine = Machine.from_config(
        {
            "name": "enc-machine",
            "model": {
                "gordo_tpu.models.JaxAutoEncoder": {"kind": "feedforward_hourglass"}
            },
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2020-01-01T00:00:00+00:00",
                "train_end_date": "2020-01-02T00:00:00+00:00",
                "tag_list": ["e-1", "e-2"],
            },
        },
        project_name="enc-proj",
    )
    restored = yaml.safe_load(machine.to_yaml())
    assert restored["name"] == "enc-machine"
    assert restored["dataset"]["tag_list"] == ["e-1", "e-2"]
