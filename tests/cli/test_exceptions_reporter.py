"""
Exceptions-reporter tests (reference:
tests/gordo/cli/test_exceptions_reporter.py): exit-code mapping by
inheritance depth, report levels, message trimming and ASCII scrubbing.
"""

import json
import sys

import pytest

from gordo_tpu.cli.exceptions_reporter import ExceptionsReporter, ReportLevel
from gordo_tpu.utils.text import replace_all_non_ascii_chars


class CustomError(ValueError):
    pass


@pytest.fixture
def reporter():
    return ExceptionsReporter(((Exception, 1), (ValueError, 2), (CustomError, 3)))


def _capture(reporter, level, exc, report_file, **report_kwargs):
    try:
        raise exc
    except Exception:
        reporter.report(level, *sys.exc_info(), report_file, **report_kwargs)


def test_report_levels():
    assert ReportLevel.get_by_name("MESSAGE") is ReportLevel.MESSAGE
    assert ReportLevel.get_by_name("nope") is None
    assert ReportLevel.get_by_name("nope", ReportLevel.EXIT_CODE) is ReportLevel.EXIT_CODE
    assert set(ReportLevel.get_names()) == {
        "EXIT_CODE",
        "TYPE",
        "MESSAGE",
        "TRACEBACK",
    }


def test_exit_code_most_derived_wins(reporter):
    # CustomError is a ValueError is an Exception; the deepest match rules.
    assert reporter.exception_exit_code(CustomError) == 3
    assert reporter.exception_exit_code(ValueError) == 2
    assert reporter.exception_exit_code(KeyError) == 1  # falls back to Exception
    assert reporter.exception_exit_code(None) == 0  # no exception -> success


def test_report_message_level(reporter, tmp_path):
    path = tmp_path / "report.json"
    with open(path, "w") as fh:
        _capture(reporter, ReportLevel.MESSAGE, ValueError("bad value"), fh)
    report = json.loads(path.read_text())
    assert report["type"] == "ValueError"
    assert report["message"] == "bad value"


def test_report_type_level(reporter, tmp_path):
    path = tmp_path / "report.json"
    with open(path, "w") as fh:
        _capture(reporter, ReportLevel.TYPE, CustomError("x"), fh)
    report = json.loads(path.read_text())
    assert report["type"] == "CustomError"
    assert "message" not in report


def test_report_exit_code_level_is_empty(reporter, tmp_path):
    path = tmp_path / "report.json"
    with open(path, "w") as fh:
        _capture(reporter, ReportLevel.EXIT_CODE, ValueError("x"), fh)
    assert json.loads(path.read_text()) == {}


def test_report_traceback_level(reporter, tmp_path):
    path = tmp_path / "report.json"
    with open(path, "w") as fh:
        _capture(reporter, ReportLevel.TRACEBACK, ValueError("boom"), fh)
    report = json.loads(path.read_text())
    assert "traceback" in report
    assert "boom" in report["traceback"]


def test_report_trims_long_messages(reporter, tmp_path):
    # The k8s termination-message file caps at 2024 bytes; the CLI passes
    # max_message_len=2024-500 (reference cli/cli.py:180).
    path = tmp_path / "report.json"
    with open(path, "w") as fh:
        _capture(
            reporter,
            ReportLevel.MESSAGE,
            ValueError("x" * 5000),
            fh,
            max_message_len=2024 - 500,
        )
    report = json.loads(path.read_text())
    assert len(report["message"]) <= 2024 - 500
    assert report["message"].startswith("xxx")


def test_safe_report_swallows_io_errors(reporter, tmp_path):
    # A bad path must not raise out of the exception handler.
    try:
        raise ValueError("x")
    except Exception:
        reporter.safe_report(
            ReportLevel.MESSAGE,
            *sys.exc_info(),
            str(tmp_path / "no-such-dir" / "report.json"),
        )


def test_non_ascii_scrubbing():
    assert replace_all_non_ascii_chars("øre 100%", "?") == "?re 100%"
    assert replace_all_non_ascii_chars("plain") == "plain"
    assert replace_all_non_ascii_chars("åß∂", "_") == "___"
