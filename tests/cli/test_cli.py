"""CLI tests (reference model: tests/gordo/cli/)."""

import json
import os

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu import serializer
from gordo_tpu.cli import gordo_tpu_cli
from gordo_tpu.cli.cli import expand_model, get_all_score_strings

MACHINE_CONFIG = {
    "name": "test-machine",
    "project_name": "test-project",
    "dataset": {
        "type": "RandomDataset",
        "train_start_date": "2020-01-01T00:00:00+00:00",
        "train_end_date": "2020-01-05T00:00:00+00:00",
        "tag_list": ["tag-1", "tag-2"],
    },
    "model": {
        "gordo_tpu.models.JaxAutoEncoder": {
            "kind": "feedforward_model",
            "encoding_dim": [8, 4],
            "encoding_func": ["tanh", "tanh"],
            "decoding_dim": [4, 8],
            "decoding_func": ["tanh", "tanh"],
            "epochs": 1,
        }
    },
}


@pytest.fixture
def runner():
    return CliRunner()


def test_version(runner):
    result = runner.invoke(gordo_tpu_cli, ["--version"])
    assert result.exit_code == 0
    assert result.output.strip()


def test_build_via_env(runner, tmp_path):
    out_dir = tmp_path / "out"
    result = runner.invoke(
        gordo_tpu_cli,
        ["build"],
        env={
            "MACHINE": json.dumps(MACHINE_CONFIG),
            "OUTPUT_DIR": str(out_dir),
        },
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert (out_dir / "model.pkl").is_file()
    assert (out_dir / "metadata.json").is_file()
    metadata = serializer.load_metadata(str(out_dir))
    assert metadata["name"] == "test-machine"
    # Model config was round-tripped through the serializer and re-keyed by
    # the canonical module path with its construction params preserved
    model_def = metadata["model"]["gordo_tpu.models.estimators.JaxAutoEncoder"]
    assert model_def["kind"] == "feedforward_model"
    assert model_def["epochs"] == 1


def test_build_print_cv_scores(runner, tmp_path):
    result = runner.invoke(
        gordo_tpu_cli,
        ["build", "--print-cv-scores"],
        env={
            "MACHINE": json.dumps(MACHINE_CONFIG),
            "OUTPUT_DIR": str(tmp_path / "out"),
        },
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    assert "explained-variance-score_fold-mean=" in result.output


def test_build_model_parameter_expansion(runner, tmp_path):
    config = dict(MACHINE_CONFIG)
    config["model"] = (
        '{"gordo_tpu.models.JaxAutoEncoder": '
        '{"kind": "feedforward_hourglass", "epochs": {{ n_epochs }}}}'
    )
    result = runner.invoke(
        gordo_tpu_cli,
        ["build", "--model-parameter", "n_epochs,1"],
        env={"MACHINE": json.dumps(config), "OUTPUT_DIR": str(tmp_path / "out")},
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output


def test_build_exit_code_and_exception_report(runner, tmp_path):
    config = dict(MACHINE_CONFIG)
    # tz-naive dates → ConfigException → exit code 100
    config["dataset"] = dict(
        config["dataset"], train_start_date="2020-01-01", train_end_date="2020-01-05"
    )
    report_file = tmp_path / "exception.json"
    result = runner.invoke(
        gordo_tpu_cli,
        ["build", "--exceptions-report-level", "MESSAGE"],
        env={
            "MACHINE": json.dumps(config),
            "OUTPUT_DIR": str(tmp_path / "out"),
            "EXCEPTIONS_REPORTER_FILE": str(report_file),
        },
    )
    assert result.exit_code == 100
    report = json.loads(report_file.read_text())
    assert report["type"] == "ConfigException"
    assert "message" in report


def test_build_fleet(runner, tmp_path):
    machines_yaml = yaml.safe_dump(
        {
            "machines": [
                dict(MACHINE_CONFIG, name=f"fleet-m-{i}") for i in range(2)
            ]
        }
    )
    config_path = tmp_path / "machines.yaml"
    config_path.write_text(machines_yaml)
    out_dir = tmp_path / "out"
    result = runner.invoke(
        gordo_tpu_cli,
        ["build-fleet", str(config_path), str(out_dir)],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    for i in range(2):
        assert (out_dir / f"fleet-m-{i}" / "model.pkl").is_file()
        metadata = serializer.load_metadata(str(out_dir / f"fleet-m-{i}"))
        assert metadata["name"] == f"fleet-m-{i}"


def test_build_fleet_resume_skips_journaled_machines(runner, tmp_path):
    """`build-fleet --resume` must skip machines journaled complete (no
    rebuild: artifact bytes/mtime untouched) and rebuild any machine
    whose artifact is missing — the post-crash recovery contract."""
    import shutil

    machines_yaml = yaml.safe_dump(
        {
            "machines": [
                dict(MACHINE_CONFIG, name=f"resume-m-{i}") for i in range(2)
            ]
        }
    )
    config_path = tmp_path / "machines.yaml"
    config_path.write_text(machines_yaml)
    out_dir = tmp_path / "out"
    result = runner.invoke(
        gordo_tpu_cli,
        ["build-fleet", str(config_path), str(out_dir)],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert (out_dir / "build_state.json").is_file()
    kept = out_dir / "resume-m-0" / "model.pkl"
    kept_stat = (kept.read_bytes(), kept.stat().st_mtime_ns)
    # simulate a crash that lost one machine's artifact
    shutil.rmtree(out_dir / "resume-m-1")

    result = runner.invoke(
        gordo_tpu_cli,
        ["build-fleet", str(config_path), str(out_dir), "--resume"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert (out_dir / "resume-m-1" / "model.pkl").is_file()
    # the journaled-complete machine was not rebuilt
    assert (kept.read_bytes(), kept.stat().st_mtime_ns) == kept_stat


def test_build_fleet_register_cache(runner, tmp_path):
    machines_yaml = yaml.safe_dump(
        {"machines": [dict(MACHINE_CONFIG, name="cached-m")]}
    )
    config_path = tmp_path / "machines.yaml"
    config_path.write_text(machines_yaml)
    register = tmp_path / "register"

    def run(out):
        result = runner.invoke(
            gordo_tpu_cli,
            [
                "build-fleet",
                str(config_path),
                str(out),
                "--model-register-dir",
                str(register),
            ],
            catch_exceptions=False,
        )
        assert result.exit_code == 0, result.output

    run(tmp_path / "out1")
    first = serializer.load_metadata(str(tmp_path / "out1" / "cached-m"))
    assert (register / "builds").is_dir()

    run(tmp_path / "out2")
    second = serializer.load_metadata(str(tmp_path / "out2" / "cached-m"))
    # Second run was a cache hit: same trained artifact, retrieval stamped
    assert "date_of_retrieval" in second["metadata"]["user_defined"]
    assert (
        first["metadata"]["build_metadata"]["model"]["model_creation_date"]
        == second["metadata"]["build_metadata"]["model"]["model_creation_date"]
    )


def test_expand_model():
    expanded = expand_model(
        '{"pkg.Model": {"depth": {{ depth }}}}', {"depth": 3}
    )
    assert expanded == {"pkg.Model": {"depth": 3}}


def test_expand_model_missing_parameter():
    with pytest.raises(ValueError, match="Model parameter missing value"):
        expand_model('{"pkg.Model": {"depth": {{ depth }}}}', {})


def test_get_all_score_strings_format(runner, tmp_path):
    from gordo_tpu.builder import ModelBuilder
    from gordo_tpu.machine import Machine

    machine = Machine.from_config(MACHINE_CONFIG, project_name="test-project")
    _, machine_out = ModelBuilder(machine).build()
    scores = get_all_score_strings(machine_out)
    assert any(s.startswith("r2-score_fold-1=") for s in scores)


# -- revision lifecycle commands --------------------------------------------


def test_wait_for_models_returns_when_present(tmp_path):
    from gordo_tpu.cli.cli import wait_for_models

    for name in ("w-a", "w-b"):
        (tmp_path / name).mkdir()
        (tmp_path / name / "metadata.json").write_text("{}")
    result = CliRunner().invoke(
        wait_for_models,
        [str(tmp_path), "--name", "w-a", "--name", "w-b", "--timeout", "5"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    assert "All 2 models present" in result.output


def test_wait_for_models_times_out_naming_missing(tmp_path):
    from gordo_tpu.cli.cli import wait_for_models

    (tmp_path / "w-a").mkdir()
    (tmp_path / "w-a" / "metadata.json").write_text("{}")
    result = CliRunner().invoke(
        wait_for_models,
        [
            str(tmp_path),
            "--name", "w-a", "--name", "w-missing",
            "--timeout", "1", "--poll-interval", "1",
        ],
    )
    assert result.exit_code != 0
    assert "w-missing" in result.output


def test_wait_for_models_reads_expected_models_env(tmp_path, monkeypatch):
    from gordo_tpu.cli.cli import wait_for_models

    (tmp_path / "env-a").mkdir()
    (tmp_path / "env-a" / "metadata.json").write_text("{}")
    monkeypatch.setenv("EXPECTED_MODELS", '["env-a"]')
    result = CliRunner().invoke(
        wait_for_models, [str(tmp_path), "--timeout", "5"], catch_exceptions=False
    )
    assert result.exit_code == 0


def test_cleanup_revisions_keeps_newest_and_current(tmp_path):
    from gordo_tpu.cli.cli import cleanup_revisions

    # five numeric revision dirs + one non-revision dir that must survive
    for revision in ("100", "200", "300", "400", "500"):
        (tmp_path / revision).mkdir()
    (tmp_path / "register").mkdir()
    result = CliRunner().invoke(
        cleanup_revisions,
        [str(tmp_path), "200", "--keep", "2"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    kept = sorted(p.name for p in tmp_path.iterdir())
    # newest two (400, 500) + current (200) + non-revision dir
    assert kept == ["200", "400", "500", "register"]


def test_cleanup_revisions_dry_run(tmp_path):
    from gordo_tpu.cli.cli import cleanup_revisions

    for revision in ("100", "200"):
        (tmp_path / revision).mkdir()
    result = CliRunner().invoke(
        cleanup_revisions,
        [str(tmp_path), "200", "--keep", "1", "--dry-run"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    assert sorted(p.name for p in tmp_path.iterdir()) == ["100", "200"]
    assert "Would delete" in result.output


def test_build_fleet_partial_failure_exit_code_and_artifacts(runner, tmp_path):
    """failFast:false at the CLI: good machines' artifacts land, the exit
    code maps the first failure (InsufficientDataError -> 80), and the
    exception report is written for the k8s termination message."""
    config = {
        "machines": [
            {
                "name": "ok-machine",
                "project_name": "p",
                "model": {
                    "gordo_tpu.models.JaxAutoEncoder": {
                        "kind": "feedforward_hourglass",
                        "epochs": 1,
                    }
                },
                "dataset": {
                    "type": "RandomDataset",
                    "train_start_date": "2020-01-01T00:00:00+00:00",
                    "train_end_date": "2020-01-02T00:00:00+00:00",
                    "tag_list": ["bf-1", "bf-2"],
                },
            },
            {
                "name": "starved-machine",
                "project_name": "p",
                "model": {
                    "gordo_tpu.models.JaxAutoEncoder": {
                        "kind": "feedforward_hourglass",
                        "epochs": 1,
                    }
                },
                "dataset": {
                    "type": "RandomDataset",
                    "train_start_date": "2020-01-01T00:00:00+00:00",
                    "train_end_date": "2020-01-02T00:00:00+00:00",
                    "tag_list": ["bf-3", "bf-4"],
                    "n_samples_threshold": 10_000_000,
                },
            },
        ]
    }
    config_path = tmp_path / "machines.yaml"
    config_path.write_text(yaml.safe_dump(config))
    out_dir = tmp_path / "out"
    report_path = tmp_path / "termination-log"

    from gordo_tpu.cli.cli import build_fleet

    result = runner.invoke(
        build_fleet,
        [
            str(config_path),
            str(out_dir),
            "--exceptions-reporter-file",
            str(report_path),
            "--exceptions-report-level",
            "MESSAGE",
        ],
    )
    assert result.exit_code == 80  # InsufficientDataError's mapped code
    assert (out_dir / "ok-machine" / "model.pkl").exists()
    assert not (out_dir / "starved-machine").exists()
    report = json.loads(report_path.read_text())
    assert "InsufficientDataError" in report["type"]


def test_cleanup_revisions_orders_numerically(tmp_path):
    """'1000' is newer than '999' — retention must sort numerically."""
    from gordo_tpu.cli.cli import cleanup_revisions

    for revision in ("999", "1000"):
        (tmp_path / revision).mkdir()
    result = CliRunner().invoke(
        cleanup_revisions,
        [str(tmp_path), "1000", "--keep", "1"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    assert sorted(p.name for p in tmp_path.iterdir()) == ["1000"]


class TestEnsureSingleWorkflow:
    """The deploy-lock guard (reference ensure-single-workflow semantics,
    inverted: the stale deploy aborts itself)."""

    def _run(self, runner, root, revision, *extra):
        return runner.invoke(
            gordo_tpu_cli,
            ["ensure-single-workflow", str(root), revision, *extra],
        )

    def test_fresh_acquire_writes_lock(self, runner, tmp_path):
        result = self._run(runner, tmp_path, "1600000000000")
        assert result.exit_code == 0, result.output
        import json as json_mod

        lock = json_mod.load(open(tmp_path / "deploy.lock"))
        assert lock["revision"] == "1600000000000"

    def test_same_revision_is_idempotent(self, runner, tmp_path):
        assert self._run(runner, tmp_path, "1600000000000").exit_code == 0
        assert self._run(runner, tmp_path, "1600000000000").exit_code == 0

    def test_newer_revision_takes_over(self, runner, tmp_path):
        assert self._run(runner, tmp_path, "1600000000000").exit_code == 0
        assert self._run(runner, tmp_path, "1600000000001").exit_code == 0
        import json as json_mod

        lock = json_mod.load(open(tmp_path / "deploy.lock"))
        assert lock["revision"] == "1600000000001"

    def test_stale_revision_fails(self, runner, tmp_path):
        assert self._run(runner, tmp_path, "1600000000001").exit_code == 0
        result = self._run(runner, tmp_path, "1600000000000")
        assert result.exit_code != 0
        assert "stale" in result.output
        # and the newer lock is untouched
        import json as json_mod

        lock = json_mod.load(open(tmp_path / "deploy.lock"))
        assert lock["revision"] == "1600000000001"

    def test_check_only_does_not_write(self, runner, tmp_path):
        result = self._run(runner, tmp_path, "1600000000000", "--check-only")
        assert result.exit_code == 0, result.output
        assert not (tmp_path / "deploy.lock").exists()

    def test_check_only_stale_fails(self, runner, tmp_path):
        assert self._run(runner, tmp_path, "1600000000005").exit_code == 0
        result = self._run(runner, tmp_path, "1600000000004", "--check-only")
        assert result.exit_code != 0

    def test_corrupt_lock_is_overwritten(self, runner, tmp_path):
        (tmp_path / "deploy.lock").write_text("{not json")
        result = self._run(runner, tmp_path, "1600000000000")
        assert result.exit_code == 0, result.output

    def test_non_numeric_revision_rejected(self, runner, tmp_path):
        result = self._run(runner, tmp_path, "not-a-revision")
        assert result.exit_code != 0
