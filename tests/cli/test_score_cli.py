"""
The batch-scoring CLI (`gordo-tpu score`) — the product call site of the
ring (time-sharded) predict path: long windowed series score with the
time axis sharded over the mesh instead of a host-side window blowup.
"""

import numpy as np
import pandas as pd
import pytest
from click.testing import CliRunner

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.cli import gordo_tpu_cli

LSTM_CONFIG = """
machines:
  - name: score-lstm
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-a, tag-b, tag-c]
    model:
      gordo_tpu.models.JaxLSTMAutoEncoder:
        kind: lstm_model
        lookback_window: 4
        encoding_dim: [8]
        encoding_func: [tanh]
        decoding_dim: [8]
        decoding_func: [tanh]
        epochs: 1
"""

DETECTOR_CONFIG = """
machines:
  - name: score-detector
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-a, tag-b, tag-c]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_hourglass
            encoding_layers: 1
            epochs: 1
"""


@pytest.fixture(scope="module")
def lstm_model_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("score-model") / "score-lstm"
    model, machine = next(local_build(LSTM_CONFIG, project_name="score"))
    serializer.dump(model, str(out), metadata=machine.to_dict())
    return str(out)


@pytest.fixture
def input_frame(tmp_path):
    rng = np.random.RandomState(5)
    index = pd.date_range("2020-02-01", periods=300, freq="10min", tz="UTC")
    frame = pd.DataFrame(
        rng.rand(300, 3).astype(np.float32),
        index=index,
        columns=["tag-a", "tag-b", "tag-c"],
    )
    path = tmp_path / "input.parquet"
    frame.to_parquet(path)
    return frame, str(path)


def test_score_cli_takes_ring_path_and_matches_direct(
    lstm_model_dir, input_frame, tmp_path, monkeypatch
):
    """With the row threshold lowered, `score` must execute the ring
    (time-sharded) predict end to end AND produce exactly the direct
    path's numbers."""
    from gordo_tpu.parallel import sequence

    frame, input_path = input_frame
    calls = []
    real = sequence.ring_windowed_predict

    def spy(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(sequence, "ring_windowed_predict", spy)
    monkeypatch.setenv(sequence.RING_PREDICT_ROWS_ENV, "64")

    out = tmp_path / "scores-ring.parquet"
    result = CliRunner().invoke(
        gordo_tpu_cli,
        ["score", lstm_model_dir, str(out), "--input", input_path,
         "--predict-only"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    assert calls, "ring path did not execute"
    ring = pd.read_parquet(out)
    assert len(ring) == 300 - 3  # lookback 4 AE => offset 3

    # direct (ring disabled) must agree
    monkeypatch.setenv(sequence.RING_PREDICT_ROWS_ENV, "0")
    out2 = tmp_path / "scores-direct.parquet"
    result = CliRunner().invoke(
        gordo_tpu_cli,
        ["score", lstm_model_dir, str(out2), "--input", input_path,
         "--predict-only"],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    direct = pd.read_parquet(out2)
    np.testing.assert_allclose(
        ring.to_numpy(), direct.to_numpy(), rtol=1e-4, atol=1e-5
    )


def test_score_cli_anomaly_frame_from_dataset_window(tmp_path):
    """--start/--end re-points the machine's own dataset config; detector
    models emit the full (pipe-flattened) anomaly frame."""
    model_dir = tmp_path / "score-detector"
    model, machine = next(local_build(DETECTOR_CONFIG, project_name="score"))
    serializer.dump(model, str(model_dir), metadata=machine.to_dict())

    out = tmp_path / "anomalies.parquet"
    result = CliRunner().invoke(
        gordo_tpu_cli,
        [
            "score",
            str(model_dir),
            str(out),
            "--start",
            "2020-02-01T00:00:00+00:00",
            "--end",
            "2020-02-02T00:00:00+00:00",
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    frame = pd.read_parquet(out)
    assert len(frame) > 0
    assert any(c.startswith("total-anomaly-unscaled") for c in frame.columns)
    assert any(c.startswith("anomaly-confidence") for c in frame.columns)


def test_score_cli_requires_input_or_window(lstm_model_dir, tmp_path):
    result = CliRunner().invoke(
        gordo_tpu_cli, ["score", lstm_model_dir, str(tmp_path / "x.parquet")]
    )
    assert result.exit_code != 0
    assert "--input" in result.output
