"""End-to-end serving observability through the WSGI app: traceparent
propagation, the serve_trace.jsonl request export, per-request
profiling, RED metrics, and the telemetry master-switch contract on the
request hot path."""

import json
import os

import pytest
from werkzeug.test import Client

from gordo_tpu import telemetry
from gordo_tpu.server import build_app
from gordo_tpu.telemetry import serving as serve_trace

from .conftest import temp_env_vars

pytestmark = pytest.mark.observability

TRACE = "0af7651916cd43dd8448eb211c80319c"
SPAN = "b7ad6b7169203331"


@pytest.fixture
def traced_client(collection_dir, tmp_path):
    """A client whose app exports every request to serve_trace.jsonl."""
    trace_dir = str(tmp_path / "telemetry")
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="1.0",
    ):
        serve_trace.reset_serve_recorder()
        app = build_app(config={"EXPECTED_MODELS": ["machine-1", "machine-2"]})
        yield Client(app), trace_dir
    serve_trace.reset_serve_recorder()


def _read_trace(trace_dir):
    serve_trace.serve_recorder().flush()
    path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
    with open(path) as handle:
        return [json.loads(line) for line in handle]


def url(rest):
    return f"/gordo/v0/test-project/{rest}"


def test_every_response_carries_a_traceparent(traced_client):
    client, _ = traced_client
    resp = client.get(url("machine-1/metadata"))
    assert resp.status_code == 200
    header = resp.headers["traceparent"]
    ctx = telemetry.parse_traceparent(header)
    assert ctx is not None and ctx.sampled


def test_incoming_traceparent_continues_the_trace(traced_client):
    client, trace_dir = traced_client
    incoming = f"00-{TRACE}-{SPAN}-01"
    resp = client.get(
        url("machine-1/metadata"), headers={"traceparent": incoming}
    )
    echoed = telemetry.parse_traceparent(resp.headers["traceparent"])
    assert echoed.trace_id == TRACE
    assert echoed.span_id != SPAN  # the server's own span, same trace
    spans = _read_trace(trace_dir)
    request_span = next(
        s
        for s in spans
        if s["name"] == "request" and s["context"]["trace_id"] == TRACE
    )
    # the request span is a child of the caller's span
    assert request_span["parent_id"] == SPAN
    assert request_span["context"]["span_id"] == echoed.span_id


def test_unsampled_upstream_trace_is_not_exported(traced_client):
    client, trace_dir = traced_client
    other = "c" * 32
    resp = client.get(
        url("machine-1/metadata"),
        headers={"traceparent": f"00-{other}-{SPAN}-00"},
    )
    echoed = telemetry.parse_traceparent(resp.headers["traceparent"])
    assert echoed.trace_id == other and not echoed.sampled
    serve_trace.serve_recorder().flush()
    path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
    if os.path.exists(path):
        spans = [json.loads(line) for line in open(path)]
        assert all(s["context"]["trace_id"] != other for s in spans)


def test_prediction_exports_stage_spans_under_the_request(
    traced_client, sensor_payload
):
    client, trace_dir = traced_client
    resp = client.post(url("machine-1/prediction"), json=sensor_payload)
    assert resp.status_code == 200
    trace_id = telemetry.parse_traceparent(
        resp.headers["traceparent"]
    ).trace_id
    spans = [
        s for s in _read_trace(trace_dir)
        if s["context"]["trace_id"] == trace_id
    ]
    by_name = {s["name"]: s for s in spans}
    request_span = by_name["request"]
    assert request_span["kind"] == "server"
    assert request_span["attributes"]["http.route"] == "prediction"
    assert request_span["attributes"]["http.status_code"] == 200
    assert request_span["attributes"]["gordo_name"] == "machine-1"
    for stage in (
        "model_resolve",
        "data_decode",
        "device_ingest",
        "inference",
        "response_assemble",
        "serialize",
    ):
        assert stage in by_name, f"stage {stage} not exported"
        assert by_name[stage]["parent_id"] == request_span["context"]["span_id"]
    # stages explain the request: the trace analysis reproduces it
    from gordo_tpu.telemetry.trace_analysis import request_breakdown

    breakdown = request_breakdown(spans)
    assert breakdown["requests"] == 1
    assert breakdown["attribution_coverage"] > 0.5


def test_server_errors_mark_the_request_span(traced_client):
    client, trace_dir = traced_client
    resp = client.post(
        url("machine-1/prediction"), json={"X": "not-a-frame"}
    )
    assert resp.status_code >= 400
    spans = _read_trace(trace_dir)
    trace_id = telemetry.parse_traceparent(
        resp.headers["traceparent"]
    ).trace_id
    request_span = next(
        s
        for s in spans
        if s["name"] == "request" and s["context"]["trace_id"] == trace_id
    )
    assert request_span["attributes"]["http.status_code"] == resp.status_code


def test_profile_param_attaches_a_profile_span(traced_client, sensor_payload):
    client, trace_dir = traced_client
    resp = client.post(
        url("machine-1/prediction") + "?profile=1", json=sensor_payload
    )
    assert resp.status_code == 200
    trace_id = telemetry.parse_traceparent(
        resp.headers["traceparent"]
    ).trace_id
    spans = [
        s for s in _read_trace(trace_dir)
        if s["context"]["trace_id"] == trace_id
    ]
    profile = next(s for s in spans if s["name"] == "profile")
    assert profile["attributes"]["interval_ms"] > 0
    assert isinstance(profile["attributes"]["frames"], list)
    request_span = next(s for s in spans if s["name"] == "request")
    assert profile["parent_id"] == request_span["context"]["span_id"]


def test_healthcheck_is_never_exported(traced_client):
    client, trace_dir = traced_client
    client.get("/healthcheck")
    client.get("/server-version")
    serve_trace.serve_recorder().flush()
    path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
    if os.path.exists(path):
        for line in open(path):
            span = json.loads(line)
            assert span["attributes"].get("http.route") not in (
                "healthcheck",
                "server-version",
            )


def test_sampling_rate_zero_exports_nothing(collection_dir, tmp_path):
    trace_dir = str(tmp_path / "t0")
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="0",
    ):
        serve_trace.reset_serve_recorder()
        app = build_app(config={})
        client = Client(app)
        resp = client.get(url("machine-1/metadata"))
        # trace ids still flow (headers, logs) — only export is gated
        assert telemetry.parse_traceparent(resp.headers["traceparent"])
        serve_trace.serve_recorder().flush()
        path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
        assert not os.path.exists(path) or not open(path).read()
    serve_trace.reset_serve_recorder()


# -- the master switch: GORDO_TPU_TELEMETRY=0 on the request hot path --------


def test_telemetry_off_writes_zero_files_and_skips_span_export(
    collection_dir, tmp_path, sensor_payload
):
    """The regression test the satellite asks for: with the master
    switch off the serve path must write NO telemetry files and skip
    span-export construction entirely — while Server-Timing (reference
    parity, in-memory only) keeps working."""
    trace_dir = str(tmp_path / "off-telemetry")
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_TELEMETRY="0",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="1.0",
    ):
        serve_trace.reset_serve_recorder()
        # the shared recorder short-circuits to the null recorder —
        # request handling never constructs an export
        assert serve_trace.serve_recorder() is telemetry.NULL_RECORDER
        app = build_app(config={})
        client = Client(app)
        resp = client.post(
            url("machine-1/prediction") + "?profile=1", json=sensor_payload
        )
        assert resp.status_code == 200
        # Server-Timing survives (it predates telemetry and is in-memory)
        assert "inference" in resp.headers["Server-Timing"]
        # no telemetry file anywhere under the configured dir
        assert not os.path.exists(trace_dir)
    serve_trace.reset_serve_recorder()


def test_telemetry_off_engine_skips_trace_construction(
    collection_dir, tmp_path
):
    """The micro-batching engine side of the master switch: no recorder,
    no BatchItem trace context, no batch spans."""
    from gordo_tpu.serve import ServeConfig, ServeEngine

    trace_dir = str(tmp_path / "off-engine")
    with temp_env_vars(
        GORDO_TPU_TELEMETRY="0", GORDO_TPU_TELEMETRY_DIR=trace_dir
    ):
        serve_trace.reset_serve_recorder()
        engine = ServeEngine(ServeConfig(max_size=4))
        try:
            assert engine._recorder is telemetry.NULL_RECORDER
            assert not os.path.exists(trace_dir)
        finally:
            engine.shutdown(drain=False)
    serve_trace.reset_serve_recorder()
