"""The ``/gordo/v0/<project>/slo`` route and the scrape-time SLO
gauges: the serving surface of the fleet SLO engine."""

import datetime
import json
import os

import pytest
from prometheus_client import CollectorRegistry

from gordo_tpu.telemetry import slo

# Must match tests/server/conftest.py
PROJECT = "test-project"

pytestmark = [pytest.mark.slo, pytest.mark.observability]


def url(rest: str) -> str:
    return f"/gordo/v0/{PROJECT}/{rest}"


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    slo.reset_statuses()
    yield
    slo.reset_statuses()


def iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).isoformat()


def write_serve_trace(directory, requests=50, errors=0):
    import time

    now = time.time()
    spans = []
    for i in range(requests):
        status = 500 if i < errors else 200
        spans.append(
            {
                "name": "request",
                "context": {
                    "trace_id": f"{i:032x}",
                    "span_id": f"{i:016x}",
                },
                "parent_id": None,
                "kind": "server",
                "start_time": iso(now - 600 + i),
                "end_time": iso(now - 600 + i),
                "duration_ms": 90.0,
                "status": {"status_code": "OK"},
                "attributes": {
                    "http.status_code": status,
                    "gordo_name": "machine-1",
                },
                "resource": {},
            }
        )
    with open(os.path.join(directory, "serve_trace.jsonl"), "w") as handle:
        for span in spans:
            handle.write(json.dumps(span) + "\n")


def test_slo_route_answers_status_document(
    client, collection_dir, tmp_path, monkeypatch
):
    telemetry_dir = tmp_path / "telemetry"
    telemetry_dir.mkdir()
    write_serve_trace(str(telemetry_dir))
    monkeypatch.setenv("GORDO_TPU_TELEMETRY_DIR", str(telemetry_dir))

    resp = client.get(url("slo"))
    assert resp.status_code == 200
    doc = resp.json
    assert doc["ok"] is True
    names = [entry["name"] for entry in doc["slos"]]
    assert "availability" in names
    assert doc["recent"]["requests"] == 50
    # the evaluation persisted its machinery beside the sinks
    assert (telemetry_dir / "rollups").is_dir()
    assert (telemetry_dir / "slo_state.json").exists()


def test_slo_route_404_without_telemetry_dir(client, monkeypatch):
    # the anchor collection dir exists but holds no sinks and no
    # telemetry dir is configured -> the route still evaluates over the
    # anchor (empty traffic, clean budgets)
    monkeypatch.delenv("GORDO_TPU_TELEMETRY_DIR", raising=False)
    resp = client.get(url("slo"))
    # anchor dir exists -> evaluates (requests=0, inside SLO)
    assert resp.status_code == 200
    assert resp.json["ok"] is True


def test_slo_route_422_on_bad_config(client, tmp_path, monkeypatch):
    telemetry_dir = tmp_path / "telemetry"
    telemetry_dir.mkdir()
    (telemetry_dir / "slos.toml").write_text(
        '[[slo]]\nname = "x"\nobjective = "bogus"\ntarget = 0.5\n'
    )
    monkeypatch.setenv("GORDO_TPU_TELEMETRY_DIR", str(telemetry_dir))
    resp = client.get(url("slo"))
    assert resp.status_code == 422
    assert "Bad SLO config" in resp.json["error"]


def test_slo_gauges_bounded_and_on_every_registry(tmp_path, monkeypatch):
    """gordo_slo_* ride every scrape registry (incl. the multiprocess
    fan-in) with label cardinality bounded by the declared slos.toml."""
    import pytest as _pytest

    from gordo_tpu.server.prometheus.metrics import (
        multiprocess_registry,
        register_fleet_console_collectors,
    )

    _pytest.importorskip("prometheus_client.multiprocess")
    d = tmp_path / "telemetry"
    d.mkdir()
    write_serve_trace(str(d), requests=40, errors=0)
    slo.evaluate(str(d))

    in_process = CollectorRegistry()
    register_fleet_console_collectors(in_process)
    register_fleet_console_collectors(in_process)  # idempotent

    monkeypatch.setenv(
        "PROMETHEUS_MULTIPROC_DIR", str(tmp_path / "multiproc")
    )
    fan_in = multiprocess_registry()
    assert fan_in is not None

    for registry in (in_process, fan_in):
        assert (
            registry.get_sample_value(
                "gordo_slo_error_budget_remaining_ratio",
                {"slo": "availability"},
            )
            == 1.0
        )
        assert (
            registry.get_sample_value(
                "gordo_slo_burn_rate", {"slo": "availability", "window": "1h"}
            )
            == 0.0
        )
        assert (
            registry.get_sample_value(
                "gordo_slo_alert_state", {"slo": "availability"}
            )
            == 0
        )


def test_slo_alert_state_gauge_tracks_firing(tmp_path):
    from gordo_tpu.server.prometheus.metrics import (
        register_fleet_console_collectors,
    )

    d = tmp_path / "telemetry"
    d.mkdir()
    (d / "slos.toml").write_text(
        '[[slo]]\nname = "availability"\nobjective = "availability"\n'
        'target = 0.99\nwindow = "30d"\n'
        "[burn]\nfast_threshold = 5.0\n"
    )
    write_serve_trace(str(d), requests=40, errors=40)
    slo.evaluate(str(d))  # pending
    slo.evaluate(str(d))  # firing
    registry = CollectorRegistry()
    register_fleet_console_collectors(registry)
    assert (
        registry.get_sample_value(
            "gordo_slo_alert_state", {"slo": "availability"}
        )
        == 2
    )


def test_scrape_refresh_respects_throttle(tmp_path, monkeypatch):
    """Scrapes with a fresh cache never re-evaluate; 0 disables
    scrape-driven evaluation entirely."""
    d = tmp_path / "telemetry"
    d.mkdir()
    write_serve_trace(str(d), requests=10)
    calls = []
    original = slo.evaluate

    def counting(directory, *args, **kwargs):
        calls.append(directory)
        return original(directory, *args, **kwargs)

    monkeypatch.setattr(slo, "evaluate", counting)
    slo.watch(str(d))
    monkeypatch.setenv("GORDO_TPU_SLO_SCRAPE_REFRESH", "0")
    assert slo.scrape_statuses() == {}  # cached-only mode, nothing cached
    assert calls == []
    monkeypatch.setenv("GORDO_TPU_SLO_SCRAPE_REFRESH", "3600")
    statuses = slo.scrape_statuses()
    assert len(calls) == 1  # stale cache -> one evaluation
    assert os.path.normpath(str(d)) in statuses
    slo.scrape_statuses()
    assert len(calls) == 1  # fresh cache -> throttled
