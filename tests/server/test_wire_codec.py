"""
Unit contracts of the wire codec itself: the dict-free JSON encoder's
byte equivalence with the legacy serializer on adversarial values, the
fleet container round trip, the vectorized anomaly assembly's numeric
identity with ``DiffBasedAnomalyDetector.anomaly``, and the resolution
cache's staleness behavior.
"""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.server import wire
from gordo_tpu.server.wire import json_codec
from gordo_tpu.server.wire.columns import WireColumn, WireTable
from gordo_tpu.utils import json_compat

pytestmark = pytest.mark.wire


def _reference_bytes(table: WireTable, extra: dict) -> bytes:
    payload = {"data": table.to_wire_dict()}
    payload.update(extra)
    return json_compat.dumps(
        payload, default=str, ignore_nan=True
    ).encode()


def test_json_encoder_matches_reference_on_tricky_values():
    index = pd.date_range(
        "2020-01-01", periods=4, freq="10min", tz="UTC"
    )
    table = WireTable(
        index,
        [
            WireColumn("start", "", ["a", None, 'q"uote', "é"]),
            WireColumn(
                "vals",
                "f",
                np.array([1.5, float("nan"), float("inf"), -0.0]),
            ),
            WireColumn("vals", "i", np.array([1, -2, 3, 4], dtype=np.int64)),
            WireColumn("vals", "b", np.array([True, False, True, False])),
            WireColumn("total-x", "", np.array([0.1, 0.2, 0.3, 0.4])),
        ],
    )
    extra = {"revision": "123", "note": "naïve"}
    assert json_codec.encode_response(table, extra) == _reference_bytes(
        table, extra
    )


def test_json_encoder_integer_index_keys():
    table = WireTable(
        pd.RangeIndex(3),
        [WireColumn("vals", "x", np.array([0.25, 0.5, 1.0]))],
    )
    assert json_codec.encode_response(table, {}) == _reference_bytes(
        table, {}
    )


def test_stream_chunks_concatenate_to_encode_response():
    table = WireTable(
        pd.RangeIndex(2),
        [
            WireColumn("a", "x", np.array([1.0, 2.0])),
            WireColumn("b", "", np.array([3.0, 4.0])),
        ],
    )
    chunks = list(json_codec.iter_encode_response(table, {"revision": "9"}))
    assert len(chunks) > 2  # actually streamed, group by group
    assert b"".join(chunks) == json_codec.encode_response(
        table, {"revision": "9"}
    )


def test_fleet_container_round_trip():
    entries = {"m-1": b"\x00\x01payload", "m-2": b""}
    extra = {"errors": {"m-3": {"status": 404}}, "full": True}
    packed = wire.pack_streams(entries, extra)
    got_entries, got_extra = wire.unpack_streams(packed)
    assert got_entries == entries
    assert got_extra == extra


@pytest.mark.parametrize(
    "garbage",
    [b"", b"GDTAF1", b"GDTAF1\xff\xff\xff\xff", b"nope", b"GDTAF1\x01\x00\x00\x00\x10\x00\x00\x00xx"],
)
def test_fleet_container_garbage_raises(garbage):
    with pytest.raises(wire.ArrowDecodeError):
        wire.unpack_streams(garbage)


def test_arrow_request_round_trip_zero_copy_types():
    index = pd.date_range("2020-01-01", periods=8, freq="h", tz="UTC")
    X = pd.DataFrame(
        {"t-1": np.linspace(0, 1, 8), "t-2": np.linspace(1, 2, 8)},
        index=index,
    )
    y = X * 2.0
    buf = wire.encode_request(X, y)
    x_cols, y_cols, got_index = wire.decode_frames(buf)
    assert set(x_cols) == {"t-1", "t-2"}
    assert set(y_cols) == {"t-1", "t-2"}
    np.testing.assert_array_equal(x_cols["t-1"], X["t-1"].to_numpy())
    np.testing.assert_array_equal(y_cols["t-2"], y["t-2"].to_numpy())
    assert isinstance(got_index, pd.DatetimeIndex)
    assert list(got_index) == list(index)


def test_anomaly_table_matches_detector_frame():
    """The vectorized assembly IS the detector's anomaly() — same
    columns, same float bits — on a hand-fitted detector."""
    from sklearn.preprocessing import MinMaxScaler

    from gordo_tpu.models.anomaly.diff import DiffBasedAnomalyDetector

    rng = np.random.RandomState(0)
    index = pd.date_range("2020-01-01", periods=32, freq="10min", tz="UTC")
    X = pd.DataFrame(
        rng.rand(32, 3), columns=["a", "b", "c"], index=index
    )
    y = X.copy()

    class _Identity:
        def predict(self, values):
            return np.asarray(values, dtype=np.float32) * np.float32(0.9)

    model = DiffBasedAnomalyDetector(
        base_estimator=_Identity(), scaler=MinMaxScaler()
    )
    model.scaler.fit(y)
    model.feature_thresholds_ = pd.Series(
        [0.5, 0.4, 0.3], index=["a", "b", "c"]
    )
    model.aggregate_threshold_ = 0.123

    recon = model.predict(X)
    frequency = pd.tseries.frequencies.to_offset("10min")
    legacy = model.anomaly(X, y, frequency=frequency, model_output=recon)
    table = wire.anomaly_table(
        model, X, y, recon, frequency=frequency, keep_smooth=False
    )
    fast = table.to_frame()
    pd.testing.assert_frame_equal(fast, legacy, check_exact=True)


def test_anomaly_table_require_thresholds_raises():
    from sklearn.preprocessing import MinMaxScaler

    from gordo_tpu.models.anomaly.diff import DiffBasedAnomalyDetector

    index = pd.date_range("2020-01-01", periods=4, freq="h", tz="UTC")
    X = pd.DataFrame(np.ones((4, 2)), columns=["a", "b"], index=index)

    class _Identity:
        def predict(self, values):
            return np.asarray(values, dtype=np.float32)

    model = DiffBasedAnomalyDetector(
        base_estimator=_Identity(), scaler=MinMaxScaler()
    )
    model.scaler.fit(X)
    with pytest.raises(AttributeError):
        wire.anomaly_table(model, X, X, model.predict(X))


def test_resolution_cache_probes_not_recomputation(collection_dir):
    """resolution() parses metadata once per revision; repeated calls
    answer the same object, and DELETE-style invalidation drops it."""
    from gordo_tpu.server.fleet_store import STORE

    STORE.clear()
    fleet = STORE.fleet(collection_dir)
    first = fleet.resolution("machine-1")
    assert fleet.resolution("machine-1") is first
    assert first.tag_names == ["tag-1", "tag-2", "tag-3", "tag-4"]
    assert first.model is fleet.model("machine-1")
    STORE.invalidate(collection_dir)
    fresh = STORE.fleet(collection_dir).resolution("machine-1")
    assert fresh is not first


def test_alignment_plan_cached(collection_dir):
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.server.utils import frame_from_columns

    STORE.clear()
    resolution = STORE.fleet(collection_dir).resolution("machine-1")
    expected = resolution.tag_names
    shuffled = {
        name: np.arange(3, dtype=float) + i
        for i, name in enumerate(reversed(expected))
    }
    frame = frame_from_columns(resolution, shuffled, None, expected)
    assert list(frame.columns) == expected
    assert resolution.alignment(
        tuple(shuffled), tuple(expected)
    ) == tuple(expected)
    # second pass hits the cached plan and yields the same frame
    again = frame_from_columns(resolution, shuffled, None, expected)
    pd.testing.assert_frame_equal(frame, again)


def test_alignment_mismatch_is_400(collection_dir):
    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.server.utils import ServerError, frame_from_columns

    STORE.clear()
    resolution = STORE.fleet(collection_dir).resolution("machine-1")
    with pytest.raises(ServerError) as err:
        frame_from_columns(
            resolution,
            {"bogus": np.arange(3, dtype=float)},
            None,
            resolution.tag_names,
        )
    assert err.value.status == 400
