"""
Fused LSTM fleet serving: LSTMSpec models join per-spec stacked buckets
(on-device window gathering) instead of falling back to sequential
per-model predicts, and mixed FF/LSTM fleets score in one request.
"""

import json

import numpy as np
import pytest
from werkzeug.test import Client

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.server import build_app
from gordo_tpu.server.fleet_store import RevisionFleet

from .conftest import temp_env_vars

PROJECT = "lstm-fleet-project"

MIXED_CONFIG = """
machines:
  - name: lstm-ae-1
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3]
    model:
      gordo_tpu.models.JaxLSTMAutoEncoder:
        kind: lstm_model
        lookback_window: 4
        epochs: 1
  - name: lstm-ae-2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-4, tag-5, tag-6]
    model:
      gordo_tpu.models.JaxLSTMAutoEncoder:
        kind: lstm_model
        lookback_window: 4
        epochs: 1
  - name: lstm-forecast
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-7, tag-8, tag-9]
    model:
      gordo_tpu.models.JaxLSTMForecast:
        kind: lstm_model
        lookback_window: 4
        epochs: 1
  - name: dense-ae
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        encoding_layers: 1
        epochs: 1
"""

NAMES = ["lstm-ae-1", "lstm-ae-2", "lstm-forecast", "dense-ae"]


@pytest.fixture(scope="module")
def mixed_collection_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("lstm-fleet") / "1700000000000"
    for model, machine in local_build(MIXED_CONFIG, project_name=PROJECT):
        serializer.dump(
            model, str(root / machine.name), metadata=machine.to_dict()
        )
    return str(root)


@pytest.fixture(scope="module")
def warmed_fleet(mixed_collection_dir):
    fleet = RevisionFleet(mixed_collection_dir)
    assert sorted(fleet.warm()) == sorted(NAMES)
    return fleet


def test_lstm_models_join_spec_buckets(warmed_fleet):
    from gordo_tpu.models.spec import LSTMSpec

    specs = warmed_fleet.loaded_specs()
    lstm_specs = {n: s for n, s in specs.items() if isinstance(s, LSTMSpec)}
    assert set(lstm_specs) == {"lstm-ae-1", "lstm-ae-2", "lstm-forecast"}
    # identical architecture ⇒ ONE bucket regardless of lookahead
    assert len(set(lstm_specs.values())) == 1
    names, stacked = warmed_fleet.spec_bucket(next(iter(lstm_specs.values())))
    assert names == ["lstm-ae-1", "lstm-ae-2", "lstm-forecast"]


def test_fused_lstm_scores_match_sequential_predict(warmed_fleet):
    rng = np.random.RandomState(3)
    inputs = {
        "lstm-ae-1": rng.rand(12, 3).astype(np.float32),
        "lstm-ae-2": rng.rand(17, 3).astype(np.float32),  # ragged lengths
        "lstm-forecast": rng.rand(12, 3).astype(np.float32),
        "dense-ae": rng.rand(9, 3).astype(np.float32),
    }
    scores, errors = warmed_fleet.fleet_scores(inputs)
    assert not errors
    assert set(scores) == set(inputs)
    for name in inputs:
        model = warmed_fleet.model(name)
        expected = np.asarray(model.predict(inputs[name]))
        recon, mse = scores[name]
        np.testing.assert_allclose(recon, expected, rtol=1e-4, atol=1e-5)
        assert mse.shape == (len(expected),)
    # the offset contract: AE output shorter by lookback-1, forecast by lookback
    assert scores["lstm-ae-1"][0].shape[0] == 12 - 3
    assert scores["lstm-forecast"][0].shape[0] == 12 - 4


def test_too_short_series_is_per_machine_error(warmed_fleet):
    rng = np.random.RandomState(4)
    inputs = {
        "lstm-ae-1": rng.rand(3, 3).astype(np.float32),  # < lookback rows
        "lstm-ae-2": rng.rand(12, 3).astype(np.float32),
    }
    scores, errors = warmed_fleet.fleet_scores(inputs)
    assert "lstm-ae-1" in errors and "lstm-ae-1" not in scores
    assert "lstm-ae-2" in scores and "lstm-ae-2" not in errors


def test_mixed_fleet_route(mixed_collection_dir):
    with temp_env_vars(MODEL_COLLECTION_DIR=mixed_collection_dir):
        client = Client(build_app(config={"EXPECTED_MODELS": NAMES}))
        index = [
            f"2020-03-01T00:{10 * j:02d}:00+00:00" for j in range(6)
        ]
        tag_groups = {
            "lstm-ae-1": ["tag-1", "tag-2", "tag-3"],
            "lstm-forecast": ["tag-7", "tag-8", "tag-9"],
            "dense-ae": ["tag-1", "tag-2", "tag-3"],
        }
        payload = {
            name: {
                tag: {ts: 0.1 * i + 0.01 * j for j, ts in enumerate(index)}
                for i, tag in enumerate(tags)
            }
            for name, tags in tag_groups.items()
        }
        resp = client.post(
            f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload}
        )
        assert resp.status_code == 200, resp.text
        body = json.loads(resp.data)
        assert set(body["data"]) == set(tag_groups)
        # model offsets survive the wire: 6 rows in, lookback 4
        assert len(body["data"]["dense-ae"]["total-anomaly-unscaled"]) == 6
        assert len(body["data"]["lstm-ae-1"]["total-anomaly-unscaled"]) == 6 - 3
        assert len(body["data"]["lstm-forecast"]["total-anomaly-unscaled"]) == 6 - 4
