"""
The streaming plane over HTTP (PR 17): ingest acks, SSE replay/resume
with cursors and ``Last-Event-ID``, per-machine decode isolation on both
body formats, quarantine notices on reconnect (+ half-open recovery on
the live stream), hot-swap span contiguity, the 429/410/400/503 ladder,
stream-only health-ledger population, and the ``drain_and_stop`` audit
with concurrent long-lived subscribers.
"""

import json
import os
import threading
import time

import pytest
from werkzeug.test import Client

from gordo_tpu import serve
from gordo_tpu.server import build_app
from gordo_tpu.server.app import drain_and_stop
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.server.utils import dataframe_from_dict
from gordo_tpu.stream import (
    StreamConfig,
    StreamPlane,
    install_plane,
    reset_plane,
)
from gordo_tpu.telemetry.fleet_health import (
    FLEET_HEALTH_FILE,
    ledger_for,
    reset_ledgers,
)
from gordo_tpu.utils.faults import FaultRule, inject

from .conftest import OLD_REVISION, PROJECT, temp_env_vars

pytestmark = [pytest.mark.stream, pytest.mark.serve]

WINDOW = 5  # the sensor_payload fixture is 5 rows tall: one exact window


def url(rest: str) -> str:
    return f"/gordo/v0/{PROJECT}/stream/{rest}"


def parse_sse(raw: bytes):
    """SSE wire bytes -> list of (id, event, data) frames (heartbeat
    comments come back as ("", "heartbeat", None))."""
    out = []
    for block in raw.decode().split("\n\n"):
        if not block.strip():
            continue
        if block.startswith(":"):
            out.append(("", "heartbeat", None))
            continue
        fields = dict(line.split(": ", 1) for line in block.split("\n"))
        out.append(
            (
                fields.get("id", ""),
                fields["event"],
                json.loads(fields["data"]),
            )
        )
    return out


@pytest.fixture
def stream_client(collection_dir):
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_BREAKER_THRESHOLD="1",
        GORDO_TPU_BREAKER_COOLDOWN_S="0.2",
        GORDO_TPU_BREAKER_BACKOFF="1.0",
    ):
        reset_ledgers()
        engine = serve.get_engine()
        serve.install_engine(None)
        serve.reset_stream_breakers()
        plane = StreamPlane(
            StreamConfig(
                ring_rows=64,
                window_rows=WINDOW,
                outbox_events=64,
                session_ttl_s=60.0,
                heartbeat_s=0.2,
                max_sessions=4,
                shed_retry_s=0.5,
            )
        )
        install_plane(plane)
        app = build_app(
            config={"EXPECTED_MODELS": ["machine-1", "machine-2"]}
        )
        yield Client(app), app, plane
        reset_plane()
        serve.reset_stream_breakers()
        serve.install_engine(engine)
        reset_ledgers()
        path = os.path.join(collection_dir, FLEET_HEALTH_FILE)
        if os.path.exists(path):
            os.remove(path)


@pytest.fixture
def json_body(sensor_payload):
    return {"X": {"machine-1": sensor_payload["X"]}}


# -- ingest + events ---------------------------------------------------------


def test_json_ingest_scores_a_window_and_emits_anomaly(
    stream_client, json_body
):
    client, _app, _plane = stream_client
    resp = client.post(url("s1/ingest"), json=json_body)
    assert resp.status_code == 200, resp.data
    ack = resp.json
    assert ack["accepted"] == {"machine-1": WINDOW}
    assert ack["scored"] == {"machine-1": WINDOW}
    assert ack["errors"] == {}
    assert ack["backpressure"] is False
    assert ack["cursor"] >= 1

    resp = client.get(url("s1/events?max_events=5&idle_timeout_s=0.3"))
    assert resp.status_code == 200
    assert resp.headers["Content-Type"].startswith("text/event-stream")
    assert resp.headers["Cache-Control"] == "no-cache"
    frames = parse_sse(resp.data)
    assert frames[0][1] == "open"
    anomalies = [d for _, kind, d in frames if kind == "anomaly"]
    assert len(anomalies) == 1
    anomaly = anomalies[0]
    assert anomaly["machine"] == "machine-1"
    assert (anomaly["first_seq"], anomaly["last_seq"]) == (1, WINDOW)
    assert anomaly["mse_mean"] is not None
    assert anomaly["revision"]


def test_arrow_ingest_rides_the_fleet_wire_container(
    stream_client, sensor_payload
):
    client, _app, _plane = stream_client
    from gordo_tpu.server import wire

    X = dataframe_from_dict(sensor_payload["X"])
    body = wire.pack_streams({"machine-1": wire.encode_request(X)})
    resp = client.post(
        url("s-arrow/ingest"),
        data=body,
        content_type=wire.ARROW_CONTENT_TYPE,
    )
    assert resp.status_code == 200, resp.data
    ack = resp.json
    assert ack["accepted"] == {"machine-1": WINDOW}
    assert ack["scored"] == {"machine-1": WINDOW}


def test_ingest_isolates_unknown_machine_per_entry(
    stream_client, json_body, sensor_payload
):
    client, _app, _plane = stream_client
    body = {
        "X": {
            **json_body["X"],
            "no-such-machine": sensor_payload["X"],
        }
    }
    resp = client.post(url("s1/ingest"), json=body)
    assert resp.status_code == 200  # the good machine still landed
    ack = resp.json
    assert ack["accepted"] == {"machine-1": WINDOW}
    assert ack["errors"]["no-such-machine"]["status"] == 404


def test_reconnect_with_cursor_resumes_without_gap(
    stream_client, json_body
):
    client, _app, _plane = stream_client
    client.post(url("s1/ingest"), json=json_body)
    client.post(url("s1/ingest"), json=json_body)

    first = parse_sse(
        client.get(url("s1/events?max_events=1&idle_timeout_s=0.3")).data
    )
    anomaly_ids = [int(i) for i, kind, _ in first if kind == "anomaly"]
    assert len(anomaly_ids) == 1

    # reconnect presenting the standard Last-Event-ID header: the
    # second window's anomaly arrives, the first is NOT replayed
    resp = client.get(
        url("s1/events?max_events=5&idle_timeout_s=0.3"),
        headers={"Last-Event-ID": str(anomaly_ids[0])},
    )
    tail = parse_sse(resp.data)
    anomalies = [d for _, kind, d in tail if kind == "anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["first_seq"] == WINDOW + 1
    assert anomalies[0]["last_seq"] == 2 * WINDOW


def test_backpressure_ack_and_shed_frame_on_ring_overflow(
    stream_client, json_body
):
    client, _app, plane = stream_client
    # shrink the ring under the watermark so nothing ever scores and
    # the second ingest must shed oldest-first
    plane.config.ring_rows = 6
    plane.scorer.window_rows = 100
    client.post(url("bp/ingest"), json=json_body)
    resp = client.post(url("bp/ingest"), json=json_body)
    assert resp.status_code == 200
    ack = resp.json
    assert ack["backpressure"] is True
    assert ack["shed"] == {"machine-1": 4}  # 10 rows into a 6-row ring
    assert ack["retry_after_s"] == 0.5
    frames = parse_sse(
        client.get(url("bp/events?max_events=3&idle_timeout_s=0.3")).data
    )
    sheds = [d for _, kind, d in frames if kind == "shed"]
    assert sheds and sheds[0]["scope"] == "ring"
    assert sheds[0]["dropped"] == 4


# -- quarantine / reconnect / recovery ---------------------------------------


def test_reconnect_learns_quarantine_immediately_then_recovers(
    stream_client, json_body
):
    """Satellites 3a+3b: a consumer reconnecting to a stream whose
    member is quarantined gets the ``quarantined`` notice (with its
    Retry-After hint) in the prelude, before any replay; once the
    cooldown lapses, scoring resumes on the LIVE stream and emits
    ``recovered``."""
    client, _app, _plane = stream_client
    with inject(
        FaultRule("stream_score", match="sq:machine-1", times=None)
    ):
        # ingest 1 cuts a window that fails server-side -> trips (threshold 1)
        client.post(url("sq/ingest"), json=json_body)
        # ingest 2: gated before cutting -> quarantined in the ack
        ack = client.post(url("sq/ingest"), json=json_body).json
        assert "machine-1" in ack["quarantined"]

        # a FRESH subscription (the reconnect): quarantine notice is in
        # the prelude — un-id'd, ahead of the replayed event tail
        frames = parse_sse(
            client.get(url("sq/events?max_events=1&idle_timeout_s=0.3")).data
        )
        kinds = [kind for _, kind, _ in frames]
        assert kinds[0] == "open"
        assert kinds[1] == "quarantined"
        notice_id, _, notice = frames[1]
        assert notice_id == ""  # prelude frames never advance the cursor
        assert notice["machine"] == "machine-1"
        assert notice["retry_after_s"] is not None

    # fault gone; past the 0.2s cooldown the next flush is the probe
    time.sleep(0.3)
    ack = client.post(url("sq/ingest"), json=json_body).json
    assert ack["quarantined"] == {}
    # the whole quarantine-era backlog scores: rows 6..15 in one span
    assert ack["scored"] == {"machine-1": 2 * WINDOW}
    frames = parse_sse(
        client.get(url("sq/events?max_events=10&idle_timeout_s=0.3")).data
    )
    kinds = [kind for _, kind, _ in frames]
    assert "recovered" in kinds
    # and a fresh reconnect carries NO stale quarantine prelude
    frames = parse_sse(
        client.get(url("sq/events?max_events=1&idle_timeout_s=0.3")).data
    )
    assert frames[1][1] != "quarantined"


# -- hot-swap ----------------------------------------------------------------


def test_hot_swap_mid_stream_keeps_spans_contiguous(
    stream_client, json_body, model_collection_root, collection_dir
):
    client, _app, _plane = stream_client
    old_dir = str(model_collection_root / OLD_REVISION)
    try:
        client.post(url("swap/ingest"), json=json_body)
        STORE.swap(collection_dir, old_dir, warm=False)
        client.post(url("swap/ingest"), json=json_body)
        frames = parse_sse(
            client.get(url("swap/events?max_events=9&idle_timeout_s=0.3")).data
        )
        anomalies = [d for _, kind, d in frames if kind == "anomaly"]
        assert len(anomalies) == 2
        # the promotion landed between windows: revision changed, spans abut
        assert [a["revision"] for a in anomalies] == [
            os.path.basename(collection_dir),
            OLD_REVISION,
        ]
        assert anomalies[0]["last_seq"] + 1 == anomalies[1]["first_seq"]
    finally:
        STORE.swap(collection_dir, collection_dir, warm=False)


# -- the error ladder --------------------------------------------------------


def test_stream_error_ladder(stream_client, json_body):
    client, _app, plane = stream_client
    # 400: malformed stream id
    assert (
        client.post(url("no spaces/ingest"), json=json_body).status_code
        == 400
    )
    # 400: bodyless ingest
    assert client.post(url("s1/ingest"), json={}).status_code == 400
    # 404: closing a stream that never existed
    assert client.delete(url("nope")).status_code == 404
    # 410: ingest into a closed stream
    client.post(url("s1/ingest"), json=json_body)
    assert client.delete(url("s1")).status_code == 200
    assert client.post(url("s1/ingest"), json=json_body).status_code == 410
    # 429 + Retry-After: the session cap (max_sessions=4; the closed
    # s1 is a tombstone and no longer counts against admission)
    for i in range(5):
        resp = client.post(url(f"cap-{i}/ingest"), json=json_body)
        if resp.status_code == 429:
            break
    assert resp.status_code == 429
    assert int(resp.headers["Retry-After"]) >= 1
    assert "retry_after_s" in resp.json


def test_stream_disabled_answers_503(collection_dir, json_body):
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_STREAM_ENABLED="0",
    ):
        install_plane(None)
        app = build_app(config={"EXPECTED_MODELS": []})
        client = Client(app)
        resp = client.post(url("s1/ingest"), json=json_body)
        assert resp.status_code == 503
        status = client.get(url("status"))
        assert status.status_code == 200
        assert status.json["enabled"] is False
        assert status.json["sessions"] == {}


def test_stream_status_surfaces_session_counters(stream_client, json_body):
    client, _app, _plane = stream_client
    client.post(url("s1/ingest"), json=json_body)
    doc = client.get(url("status")).json
    assert doc["enabled"] is True
    session = doc["sessions"][f"{PROJECT}/s1"]
    machine = session["machines"]["machine-1"]
    assert machine["rows_in"] == WINDOW
    assert machine["rows_scored"] == WINDOW
    assert doc["counters"]["ingest_batches"] == 1
    # the observability surfaces: per-machine freshness, the summed
    # zero-gap accounting, and the process-global telemetry rollup
    assert machine["last_score_lag_ms"] is not None
    assert machine["last_score_lag_ms"] >= 0.0
    accounting = session["accounting"]
    assert accounting["rows_in"] == WINDOW
    assert accounting["gap"] == 0
    assert session["lag"]["score_lag_max_ms"] >= 0.0
    telemetry = doc["telemetry"]
    assert telemetry["rows_in"] >= WINDOW
    assert telemetry["rows_scored"] >= WINDOW
    assert telemetry["flushes"] >= 1
    assert telemetry["lag_ms"]["count"] >= WINDOW  # rows-weighted


# -- stream-only health ledger (satellite 2) ---------------------------------


def test_stream_only_deployment_populates_fleet_health(
    stream_client, json_body, collection_dir
):
    client, _app, _plane = stream_client
    client.post(url("s1/ingest"), json=json_body)
    record = (
        (ledger_for(collection_dir).document() or {}).get("machines") or {}
    ).get("machine-1") or {}
    assert record, "stream scoring must narrate machine health"
    assert record["serving"]["rows"] >= WINDOW
    assert record["serving"]["requests"] >= 1
    # and the fleet-health route serves it — no HTTP scoring ever ran
    doc = client.get(f"/gordo/v0/{PROJECT}/fleet-health").json
    assert doc["health"]["machines"]["machine-1"]["serving"]["rows"] >= WINDOW


# -- drain_and_stop audit (satellite 1) --------------------------------------


def test_drain_and_stop_terminates_concurrent_subscribers(
    stream_client, json_body
):
    """Long-lived SSE connections across drain: every concurrent
    subscriber's response ends with the terminal ``drain`` frame (no
    dead sockets, no missing terminals), and the plane refuses new
    sessions afterwards."""
    client, app, plane = stream_client
    client.post(url("s1/ingest"), json=json_body)
    results = [None, None]

    def subscribe(i):
        # no max_events / idle_timeout: this response only ends when a
        # terminal frame arrives — the long-lived production shape
        resp = Client(app).get(url("s1/events"), buffered=False)
        results[i] = parse_sse(b"".join(
            part if isinstance(part, bytes) else part.encode()
            for part in resp.response
        ))

    threads = [
        threading.Thread(target=subscribe, args=(i,), daemon=True)
        for i in range(2)
    ]
    for thread in threads:
        thread.start()
    deadline = time.monotonic() + 5.0
    while plane.session(PROJECT, "s1", "", create=False).subscribers < 2:
        assert time.monotonic() < deadline
        time.sleep(0.01)

    drain_and_stop(app, server=None, engine=None)

    for thread in threads:
        thread.join(timeout=10)
    assert not any(thread.is_alive() for thread in threads)
    for frames in results:
        kinds = [kind for _, kind, _ in frames]
        assert kinds[-1] == "drain", kinds
        assert frames[-1][2]["reason"] == "server draining"
    # drained plane refuses admission; draining is visible in status
    resp = client.post(url("s2/ingest"), json=json_body)
    assert resp.status_code == 429
    # and a second drain is a no-op (SIGTERM races are real)
    assert plane.drain() == 0
