"""
Stage-attribution contract for the reshaped wire pipeline (PR 12): the
columnar fast path kept the canonical stage names —
``model_resolve`` / ``data_decode`` / ``device_ingest`` /
``inference`` / ``response_assemble`` / ``serialize`` — and the exported request traces
must still explain ≥0.9 of request walltime on BOTH wire formats, or
``gordo-tpu trace`` (and the bench gate built on it) goes blind to the
very pipeline this PR rebuilt.
"""

import json
import os

import pandas as pd
import pytest
from werkzeug.test import Client

from gordo_tpu import telemetry
from gordo_tpu.server import build_app
from gordo_tpu.server import wire
from gordo_tpu.server.fleet_store import STORE
from gordo_tpu.telemetry import serving as serve_trace
from gordo_tpu.telemetry.trace_analysis import request_breakdown

from .conftest import temp_env_vars

pytestmark = [pytest.mark.wire, pytest.mark.observability]

WIRE_STAGES = (
    "model_resolve",
    "data_decode",
    "device_ingest",
    "inference",
    "response_assemble",
    "serialize",
)


@pytest.fixture
def traced(collection_dir, tmp_path):
    trace_dir = str(tmp_path / "telemetry")
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir,
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=trace_dir,
        GORDO_TPU_TRACE_SAMPLE_RATE="1.0",
    ):
        serve_trace.reset_serve_recorder()
        STORE.clear()
        yield Client(build_app(config={})), trace_dir
    serve_trace.reset_serve_recorder()


def _spans(trace_dir):
    serve_trace.serve_recorder().flush()
    path = os.path.join(trace_dir, telemetry.SERVE_TRACE_FILE)
    with open(path) as handle:
        return [json.loads(line) for line in handle]


def _bench_sized_payloads():
    """A bench-scale request (256 rows × 4 tags): the attribution
    contract is about REAL serving traffic — on a 5-row request the
    fixed per-request machinery (context/trace setup, routing,
    negotiation) dominates walltime and coverage measures the wrong
    thing."""
    index = pd.date_range(
        "2020-03-01", periods=256, freq="1min", tz="UTC"
    )
    X = pd.DataFrame(
        {f"tag-{i}": [0.1 * i + 0.001 * j for j in range(256)] for i in range(1, 5)},
        index=index,
    )
    json_x = {
        tag: {ts.isoformat(): value for ts, value in column.items()}
        for tag, column in X.to_dict().items()
    }
    return X, {"X": json_x, "y": json_x}


@pytest.mark.parametrize("wire_format", ["json", "arrow"])
def test_columnar_route_keeps_stage_attribution(traced, wire_format):
    import threading

    client, trace_dir = traced
    url = "/gordo/v0/test-project/machine-1/anomaly/prediction"
    X, json_payload = _bench_sized_payloads()
    arrow_body = wire.encode_request(X, X)

    def one_request():
        if wire_format == "arrow":
            resp = client.post(
                url,
                data=arrow_body,
                headers={
                    "Content-Type": wire.ARROW_CONTENT_TYPE,
                    "Accept": wire.ARROW_CONTENT_TYPE,
                },
            )
        else:
            resp = client.post(url, json=json_payload)
        assert resp.status_code == 200
        # Server-Timing carries every wire stage, whatever the format
        timing = resp.headers["Server-Timing"]
        for stage in WIRE_STAGES:
            assert stage in timing, (wire_format, stage, timing)

    one_request()  # warm caches/compiles
    # concurrent clients: the ≥0.9 contract describes SERVING traffic —
    # under concurrency scheduler waits land inside whichever stage owns
    # the work, while an idle single-threaded request is mostly fixed
    # per-request machinery and would measure the wrong thing
    threads = [
        threading.Thread(target=lambda: [one_request() for _ in range(3)])
        for _ in range(4)
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=120)

    spans = _spans(trace_dir)
    names = {s["name"] for s in spans}
    for stage in WIRE_STAGES:
        assert stage in names, f"{stage} not exported on {wire_format}"
    breakdown = request_breakdown(spans)
    assert breakdown["attribution_coverage"] >= 0.9, breakdown
