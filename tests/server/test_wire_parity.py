"""
Columnar wire fast-path parity: the fast JSON encoder must produce the
legacy serializer's bytes EXACTLY, and every (request format × response
format) cell of the negotiation matrix must score identically — batched,
unbatched, and across a concurrent hot-swap.
"""

import json
import re
import threading

import numpy as np
import pandas as pd
import pytest
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server import wire
from gordo_tpu.server.fleet_store import STORE

from .conftest import temp_env_vars

pytestmark = pytest.mark.wire

TIME_RE = re.compile(rb'"time-seconds": "[0-9.]+"')


def _norm(body: bytes) -> bytes:
    return TIME_RE.sub(b'"time-seconds": "T"', body)


def _client(collection_dir):
    return Client(build_app(config={}))


def _arrow_frames(sensor_payload):
    X = pd.DataFrame(
        {
            tag: list(col.values())
            for tag, col in sensor_payload["X"].items()
        },
        index=pd.DatetimeIndex(list(next(iter(sensor_payload["X"].values())))),
    )
    return X


@pytest.mark.parametrize(
    "path",
    [
        "/gordo/v0/test-project/machine-1/prediction",
        "/gordo/v0/test-project/machine-1/anomaly/prediction",
        "/gordo/v0/test-project/machine-2/prediction",
    ],
)
def test_fast_json_bytes_identical_to_legacy(
    collection_dir, sensor_payload, path
):
    """GORDO_TPU_WIRE_COLUMNAR on vs off: byte-for-byte identical JSON."""
    payload = sensor_payload
    if "machine-2" in path:
        payload = {
            "X": {t: sensor_payload["X"][t] for t in ("tag-1", "tag-2")}
        }
    bodies = {}
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        for switch in ("1", "0"):
            with temp_env_vars(GORDO_TPU_WIRE_COLUMNAR=switch):
                STORE.clear()
                resp = _client(collection_dir).post(path, json=payload)
                assert resp.status_code == 200
                bodies[switch] = _norm(resp.data)
    assert bodies["1"] == bodies["0"]


def test_fleet_full_json_bytes_identical_to_legacy(
    collection_dir, sensor_payload
):
    bodies = {}
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        for switch in ("1", "0"):
            with temp_env_vars(GORDO_TPU_WIRE_COLUMNAR=switch):
                STORE.clear()
                resp = _client(collection_dir).post(
                    "/gordo/v0/test-project/prediction/fleet?full",
                    json={"X": {"machine-1": sensor_payload["X"]}},
                )
                assert resp.status_code == 200
                bodies[switch] = _norm(resp.data)
    assert bodies["1"] == bodies["0"]


def _assert_columns_equal(got, want):
    for key in want:
        try:
            a = np.asarray(got[key], dtype=float)
            b = np.asarray(want[key], dtype=float)
        except (TypeError, ValueError):
            # object columns (start/end ISO strings, None)
            a = np.asarray(got[key], dtype=object)
            b = np.asarray(want[key], dtype=object)
        np.testing.assert_array_equal(a, b, err_msg=str(key))


def _decode_any(resp):
    """One response (JSON or Arrow) as {group: {sub: np.array}}."""
    if resp.content_type == wire.ARROW_CONTENT_TYPE:
        frame, _ = wire.decode_response(resp.data)
        return {
            (group, sub): frame[(group, sub)].to_numpy()
            for group, sub in frame.columns
        }
    data = json.loads(resp.data)["data"]
    out = {}
    for group, subs in data.items():
        for sub, cells in subs.items():
            # scalar groups nest under their own name on the wire
            out[(group, "" if sub == group else sub)] = np.array(
                [v for v in cells.values()], dtype=object
            )
    return out


@pytest.mark.parametrize("request_format", ["json", "arrow"])
@pytest.mark.parametrize("response_format", ["json", "arrow"])
def test_format_matrix_identical_scores(
    collection_dir, sensor_payload, request_format, response_format
):
    """Every request×response format combination answers numerically
    identical anomaly columns."""
    X = _arrow_frames(sensor_payload)
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        client = _client(collection_dir)
        url = "/gordo/v0/test-project/machine-1/anomaly/prediction"
        headers = {}
        if response_format == "arrow":
            headers["Accept"] = wire.ARROW_CONTENT_TYPE
        if request_format == "arrow":
            resp = client.post(
                url,
                data=wire.encode_request(X, X),
                headers={
                    **headers,
                    "Content-Type": wire.ARROW_CONTENT_TYPE,
                },
            )
        else:
            resp = client.post(url, json=sensor_payload, headers=headers)
        assert resp.status_code == 200, resp.data[:300]
        got = _decode_any(resp)

        # the reference cell: JSON in, JSON out
        reference = client.post(url, json=sensor_payload)
        assert reference.status_code == 200
        want = _decode_any(reference)

    assert set(got) == set(want)
    _assert_columns_equal(got, want)


def test_fleet_arrow_container_matches_json(collection_dir, sensor_payload):
    """The fleet route's Arrow container carries the same verdicts as
    its JSON twin — full mode, per-machine record batches."""
    X = _arrow_frames(sensor_payload)
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        client = _client(collection_dir)
        json_resp = client.post(
            "/gordo/v0/test-project/prediction/fleet?full",
            json={"X": {"machine-1": sensor_payload["X"]}},
        )
        assert json_resp.status_code == 200
        body = wire.pack_streams(
            {"machine-1": wire.encode_request(X, X)}, extra={"full": True}
        )
        arrow_resp = client.post(
            "/gordo/v0/test-project/prediction/fleet",
            data=body,
            headers={
                "Content-Type": wire.ARROW_CONTENT_TYPE,
                "Accept": wire.ARROW_CONTENT_TYPE,
            },
        )
        assert arrow_resp.status_code == 200
        assert arrow_resp.content_type == wire.ARROW_CONTENT_TYPE

    json_entry = json.loads(json_resp.data)["data"]["machine-1"]
    entries, extra = wire.unpack_streams(arrow_resp.data)
    assert extra.get("errors") == {}
    frame, _ = wire.decode_response(entries["machine-1"])
    j_total = np.array(
        list(json_entry["total-anomaly-scaled"]["total-anomaly-scaled"].values()),
        dtype=float,
    )
    a_total = frame[("total-anomaly-scaled", "")].to_numpy(dtype=float)
    np.testing.assert_array_equal(a_total, j_total)


def test_fleet_lean_arrow(collection_dir, sensor_payload):
    """Lean (default) fleet mode over Arrow: model-output + per-row mse
    per machine."""
    X = _arrow_frames(sensor_payload)
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        resp = _client(collection_dir).post(
            "/gordo/v0/test-project/prediction/fleet",
            data=wire.pack_streams({"machine-1": wire.encode_request(X)}),
            headers={
                "Content-Type": wire.ARROW_CONTENT_TYPE,
                "Accept": wire.ARROW_CONTENT_TYPE,
            },
        )
        assert resp.status_code == 200
    entries, extra = wire.unpack_streams(resp.data)
    frame, _ = wire.decode_response(entries["machine-1"])
    groups = {group for group, _ in frame.columns}
    assert groups == {"model-output", "total-anomaly-unscaled"}
    assert np.isfinite(
        frame[("total-anomaly-unscaled", "")].to_numpy(dtype=float)
    ).all()


@pytest.mark.parametrize(
    "path",
    [
        "/gordo/v0/test-project/machine-1/prediction",
        "/gordo/v0/test-project/machine-1/anomaly/prediction",
    ],
)
def test_arrow_served_from_legacy_frame_fallback(
    collection_dir, sensor_payload, path
):
    """Review regression: with the columnar path off (the documented
    escape hatch — and the same code path custom detectors take), an
    Arrow-accepting client must still get a bridged Arrow response,
    not a bogus duplicate-labels 400."""
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        client = _client(collection_dir)
        fast = client.post(
            path,
            json=sensor_payload,
            headers={"Accept": wire.ARROW_CONTENT_TYPE},
        )
        assert fast.status_code == 200
        with temp_env_vars(GORDO_TPU_WIRE_COLUMNAR="0"):
            bridged = client.post(
                path,
                json=sensor_payload,
                headers={"Accept": wire.ARROW_CONTENT_TYPE},
            )
    assert bridged.status_code == 200, bridged.data[:300]
    assert bridged.content_type == wire.ARROW_CONTENT_TYPE
    fast_frame, _ = wire.decode_response(fast.data)
    bridged_frame, _ = wire.decode_response(bridged.data)
    assert list(fast_frame.columns) == list(bridged_frame.columns)
    for column in fast_frame.columns:
        np.testing.assert_array_equal(
            fast_frame[column].to_numpy(),
            bridged_frame[column].to_numpy(),
            err_msg=str(column),
        )


def test_fleet_arrow_served_from_legacy_frame_fallback(
    collection_dir, sensor_payload
):
    """Same bridge on the fleet full path (where the legacy frame rides
    per-machine error isolation, never a whole-batch failure)."""
    X = _arrow_frames(sensor_payload)
    body = wire.pack_streams(
        {"machine-1": wire.encode_request(X, X)}, extra={"full": True}
    )
    with temp_env_vars(
        MODEL_COLLECTION_DIR=collection_dir, GORDO_TPU_WIRE_COLUMNAR="0"
    ):
        STORE.clear()
        resp = _client(collection_dir).post(
            "/gordo/v0/test-project/prediction/fleet",
            data=body,
            headers={
                "Content-Type": wire.ARROW_CONTENT_TYPE,
                "Accept": wire.ARROW_CONTENT_TYPE,
            },
        )
    assert resp.status_code == 200, resp.data[:300]
    entries, extra = wire.unpack_streams(resp.data)
    assert extra["errors"] == {}
    frame, _ = wire.decode_response(entries["machine-1"])
    assert ("total-anomaly-scaled", "") in list(frame.columns)


def test_duplicate_label_frames_are_not_arrow_representable():
    """Review regression: WireTable.from_frame on a duplicate-label
    frame must flag itself non-unique (the encoders' refusal guard)
    instead of smuggling 2-D column blocks into the wire."""
    frame = pd.DataFrame(
        np.arange(6, dtype=float).reshape(2, 3),
        columns=pd.MultiIndex.from_tuples(
            [("g", "a"), ("g", "a"), ("g", "b")]
        ),
    )
    assert not wire.WireTable.from_frame(frame).unique_labels()


def test_matrix_parity_under_batching(collection_dir, sensor_payload):
    """Batched (micro-batcher) vs unbatched, JSON vs Arrow: identical
    scores for the same rows."""
    from gordo_tpu import serve
    from gordo_tpu.serve import ServeConfig, ServeEngine

    X = _arrow_frames(sensor_payload)
    url = "/gordo/v0/test-project/machine-1/anomaly/prediction"
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        client = _client(collection_dir)
        unbatched = client.post(url, json=sensor_payload)
        assert unbatched.status_code == 200

        engine = ServeEngine(
            ServeConfig(max_size=4, max_delay_ms=5.0, deadline_ms=30000.0)
        )
        serve.install_engine(engine)
        try:
            batched_json = client.post(url, json=sensor_payload)
            batched_arrow = client.post(
                url,
                data=wire.encode_request(X, X),
                headers={
                    "Content-Type": wire.ARROW_CONTENT_TYPE,
                    "Accept": wire.ARROW_CONTENT_TYPE,
                },
            )
        finally:
            serve.install_engine(None)
            engine.shutdown(drain=True)
    assert batched_json.status_code == 200
    assert batched_arrow.status_code == 200
    want = _decode_any(unbatched)
    for resp in (batched_json, batched_arrow):
        _assert_columns_equal(_decode_any(resp), want)


def test_mixed_formats_concurrent_hot_swap(
    model_collection_root, collection_dir, sensor_payload
):
    """The PR 6 snapshot contract extended to the codec path: concurrent
    clients mixing JSON and Arrow against one app, while the store
    hot-swaps revisions under them — every response 200 and internally
    consistent, no torn decodes."""
    from .conftest import OLD_REVISION

    X = _arrow_frames(sensor_payload)
    old_dir = str(model_collection_root / OLD_REVISION)
    url = "/gordo/v0/test-project/machine-1/anomaly/prediction"
    arrow_body = wire.encode_request(X, X)
    failures = []
    stop = threading.Event()

    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        app = build_app(config={})

        def worker(use_arrow: bool):
            client = Client(app)
            while not stop.is_set():
                try:
                    if use_arrow:
                        resp = client.post(
                            url,
                            data=arrow_body,
                            headers={
                                "Content-Type": wire.ARROW_CONTENT_TYPE,
                                "Accept": wire.ARROW_CONTENT_TYPE,
                            },
                        )
                        assert resp.status_code == 200, resp.data[:200]
                        frame, extra = wire.decode_response(resp.data)
                        assert extra["revision"] in (
                            resp.headers["revision"],
                        )
                        total = frame[
                            ("total-anomaly-scaled", "")
                        ].to_numpy(dtype=float)
                    else:
                        resp = client.post(url, json=sensor_payload)
                        assert resp.status_code == 200, resp.data[:200]
                        doc = json.loads(resp.data)
                        assert doc["revision"] == resp.headers["revision"]
                        total = np.array(
                            list(
                                doc["data"]["total-anomaly-scaled"][
                                    "total-anomaly-scaled"
                                ].values()
                            ),
                            dtype=float,
                        )
                    assert np.isfinite(total).all()
                except Exception as exc:  # noqa: BLE001 - collected
                    failures.append(repr(exc))
                    return

        threads = [
            threading.Thread(target=worker, args=(i % 2 == 0,))
            for i in range(4)
        ]
        for thread in threads:
            thread.start()
        try:
            for _ in range(6):
                STORE.swap(collection_dir, old_dir)
                STORE.swap(collection_dir, collection_dir)  # rollback
        finally:
            stop.set()
            for thread in threads:
                thread.join(timeout=30)
    assert not failures, failures
