"""
Content-negotiation contracts for the columnar wire formats: 406 for
unservable Accept headers, 415 for unsupported request bodies, 400 (as
JSON) for malformed Arrow, graceful JSON-only degradation when pyarrow
is unavailable, and the streaming-encode knob's byte parity.
"""

import json

import pandas as pd
import pytest
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server import wire
from gordo_tpu.server.fleet_store import STORE

from .conftest import temp_env_vars

pytestmark = pytest.mark.wire

URL = "/gordo/v0/test-project/machine-1/prediction"
ANOMALY_URL = "/gordo/v0/test-project/machine-1/anomaly/prediction"


@pytest.fixture
def wire_client(collection_dir):
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        STORE.clear()
        yield Client(build_app(config={}))


def test_unknown_accept_is_406(wire_client, sensor_payload):
    resp = wire_client.post(
        URL, json=sensor_payload, headers={"Accept": "text/html"}
    )
    assert resp.status_code == 406
    assert resp.content_type.startswith("application/json")
    assert "application/json" in json.loads(resp.data)["message"]


def test_wildcard_accept_stays_json(wire_client, sensor_payload):
    resp = wire_client.post(
        URL, json=sensor_payload, headers={"Accept": "*/*"}
    )
    assert resp.status_code == 200
    assert resp.content_type.startswith("application/json")


def test_browser_style_accept_stays_json(wire_client, sensor_payload):
    resp = wire_client.post(
        URL,
        json=sensor_payload,
        headers={"Accept": "text/html,application/xhtml+xml,*/*;q=0.8"},
    )
    assert resp.status_code == 200
    assert resp.content_type.startswith("application/json")


def test_arrow_accept_answers_arrow(wire_client, sensor_payload):
    resp = wire_client.post(
        URL,
        json=sensor_payload,
        headers={"Accept": wire.ARROW_CONTENT_TYPE},
    )
    assert resp.status_code == 200
    assert resp.content_type == wire.ARROW_CONTENT_TYPE
    frame, extra = wire.decode_response(resp.data)
    assert ("model-output" in {g for g, _ in frame.columns})
    assert extra["revision"] == resp.headers["revision"]


def test_malformed_arrow_body_is_400_json(wire_client):
    resp = wire_client.post(
        URL,
        data=b"not an ipc stream at all",
        headers={"Content-Type": wire.ARROW_CONTENT_TYPE},
    )
    assert resp.status_code == 400
    assert resp.content_type.startswith("application/json")
    assert "Arrow" in json.loads(resp.data)["message"]


def test_truncated_fleet_container_is_400(wire_client):
    resp = wire_client.post(
        "/gordo/v0/test-project/prediction/fleet",
        data=b"GDTAF1\x02\x00\x00\x00trunc",
        headers={"Content-Type": wire.ARROW_CONTENT_TYPE},
    )
    assert resp.status_code == 400


def test_arrow_disabled_degrades_to_json(wire_client, sensor_payload):
    """A client accepting Arrow AND json gets json when the Arrow codec
    is off; one accepting ONLY Arrow gets 406; an Arrow BODY gets 415."""
    with temp_env_vars(GORDO_TPU_WIRE_ARROW="0"):
        both = wire_client.post(
            URL,
            json=sensor_payload,
            headers={
                "Accept": f"{wire.ARROW_CONTENT_TYPE}, application/json;q=0.5"
            },
        )
        assert both.status_code == 200
        assert both.content_type.startswith("application/json")

        only = wire_client.post(
            URL,
            json=sensor_payload,
            headers={"Accept": wire.ARROW_CONTENT_TYPE},
        )
        assert only.status_code == 406

        body = wire_client.post(
            URL,
            data=b"\x00\x00",
            headers={"Content-Type": wire.ARROW_CONTENT_TYPE},
        )
        assert body.status_code == 415


def test_raw_parquet_body(wire_client, sensor_payload):
    """A raw application/x-parquet body decodes as X (no multipart)."""
    X = pd.DataFrame(
        {t: list(c.values()) for t, c in sensor_payload["X"].items()},
        index=pd.DatetimeIndex(
            list(next(iter(sensor_payload["X"].values())))
        ),
    )
    from gordo_tpu.server.utils import dataframe_into_parquet_bytes

    resp = wire_client.post(
        URL,
        data=dataframe_into_parquet_bytes(X),
        headers={"Content-Type": "application/x-parquet"},
    )
    assert resp.status_code == 200
    assert json.loads(resp.data)["data"]["model-output"]


def test_format_parquet_query_arg_wins(wire_client, sensor_payload):
    """Legacy precedence: ?format=parquet beats any Accept header."""
    resp = wire_client.post(
        URL + "?format=parquet",
        json=sensor_payload,
        headers={"Accept": wire.ARROW_CONTENT_TYPE},
    )
    assert resp.status_code == 200
    assert resp.content_type == "application/octet-stream"
    from gordo_tpu.server.utils import dataframe_from_parquet_bytes

    frame = dataframe_from_parquet_bytes(resp.data)
    assert "model-output" in {c[0] for c in frame.columns}


def test_negotiated_parquet_accept(wire_client, sensor_payload):
    resp = wire_client.post(
        URL,
        json=sensor_payload,
        headers={"Accept": "application/x-parquet"},
    )
    assert resp.status_code == 200
    assert resp.content_type == "application/octet-stream"


def test_parquet_response_identical_fast_and_legacy(
    collection_dir, sensor_payload
):
    """The ?format=parquet wire keeps decoding to the same frame whether
    the columnar path assembled it or the legacy pandas path did."""
    from gordo_tpu.server.utils import dataframe_from_parquet_bytes

    frames = {}
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        for switch in ("1", "0"):
            with temp_env_vars(GORDO_TPU_WIRE_COLUMNAR=switch):
                STORE.clear()
                resp = Client(build_app(config={})).post(
                    ANOMALY_URL + "?format=parquet", json=sensor_payload
                )
                assert resp.status_code == 200
                frames[switch] = dataframe_from_parquet_bytes(resp.data)
    pd.testing.assert_frame_equal(frames["1"], frames["0"])


def test_stream_mode_bytes_identical(wire_client, sensor_payload):
    """GORDO_TPU_WIRE_STREAM chunks concatenate to the exact unstreamed
    body."""
    plain = wire_client.post(ANOMALY_URL, json=sensor_payload)
    assert plain.status_code == 200
    with temp_env_vars(GORDO_TPU_WIRE_STREAM="1"):
        streamed = wire_client.post(ANOMALY_URL, json=sensor_payload)
    assert streamed.status_code == 200
    import re

    norm = lambda b: re.sub(  # noqa: E731
        rb'"time-seconds": "[0-9.]+"', b'"T"', b
    )
    assert norm(streamed.data) == norm(plain.data)


def test_fleet_parquet_accept_is_406(wire_client, sensor_payload):
    resp = wire_client.post(
        "/gordo/v0/test-project/prediction/fleet?format=parquet",
        json={"X": {"machine-1": sensor_payload["X"]}},
    )
    assert resp.status_code == 406
