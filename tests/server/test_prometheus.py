"""Prometheus request-metrics tests (reference: gordo/server/prometheus/)."""

from prometheus_client import CollectorRegistry
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server.prometheus.server import build_metrics_app


def test_request_metrics_collected(client, collection_dir, monkeypatch):
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    assert c.get("/gordo/v0/test-project/machine-1/metadata").status_code == 200
    # healthcheck is in ignore_paths and must not be counted
    assert c.get("/healthcheck").status_code == 200

    count = registry.get_sample_value(
        "gordo_server_requests_total",
        {
            "method": "GET",
            "path": "/gordo/v0/{project}/{name}/metadata",
            "status_code": "200",
            "gordo_name": "machine-1",
            "project": "test-project",
        },
    )
    assert count == 1
    info = registry.get_sample_value(
        "gordo_server_info",
        {"version": __import__("gordo_tpu").__version__, "project": "test-project"},
    )
    assert info == 1
    # the /healthcheck hit was ignored: no sample with that path exists
    assert not any(
        sample.labels.get("path") == "/healthcheck"
        for metric in registry.collect()
        for sample in metric.samples
    )


def test_metrics_app_serves_scrape():
    registry = CollectorRegistry()
    app = build_metrics_app(registry=registry)
    c = Client(app)
    resp = c.get("/metrics")
    assert resp.status_code == 200
    assert c.get("/nope").status_code == 404
