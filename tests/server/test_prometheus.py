"""Prometheus request-metrics tests (reference: gordo/server/prometheus/)."""

import gc
import weakref

import pytest
from prometheus_client import CollectorRegistry
from werkzeug.test import Client

from gordo_tpu.server import build_app
from gordo_tpu.server.prometheus import metrics as prom_metrics
from gordo_tpu.server.prometheus.metrics import (
    GordoServerPrometheusMetrics,
    fleet_build_metrics,
    fleet_build_robustness_counters,
)
from gordo_tpu.server.prometheus.server import build_metrics_app


def test_request_metrics_collected(client, collection_dir, monkeypatch):
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    assert c.get("/gordo/v0/test-project/machine-1/metadata").status_code == 200
    # healthcheck is in ignore_paths and must not be counted
    assert c.get("/healthcheck").status_code == 200

    count = registry.get_sample_value(
        "gordo_server_requests_total",
        {
            "method": "GET",
            "path": "/gordo/v0/{project}/{name}/metadata",
            "status_code": "200",
            "gordo_name": "machine-1",
            "project": "test-project",
        },
    )
    assert count == 1
    info = registry.get_sample_value(
        "gordo_server_info",
        {"version": __import__("gordo_tpu").__version__, "project": "test-project"},
    )
    assert info == 1
    # the /healthcheck hit was ignored: no sample with that path exists
    assert not any(
        sample.labels.get("path") == "/healthcheck"
        for metric in registry.collect()
        for sample in metric.samples
    )


def test_metrics_app_serves_scrape():
    registry = CollectorRegistry()
    app = build_metrics_app(registry=registry)
    c = Client(app)
    resp = c.get("/metrics")
    assert resp.status_code == 200
    assert c.get("/nope").status_code == 404


# -- label-cardinality guards ----------------------------------------------


def test_unmatched_scanner_paths_collapse_to_one_label(
    client, collection_dir, monkeypatch
):
    """Paths outside the API shape (scanners, typos) must not mint
    timeseries: every such request lands on the single ``{unmatched}``
    path label."""
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    for path in ("/wp-admin/setup.php", "/.env", "/gordo/nope", "/x" * 50):
        assert c.get(path).status_code == 404
    paths = {
        sample.labels["path"]
        for metric in registry.collect()
        for sample in metric.samples
        if "path" in sample.labels
    }
    assert "{unmatched}" in paths
    # no scanner path ever became a label value
    assert all(p == "{unmatched}" or p.startswith("/gordo") for p in paths)
    count = registry.get_sample_value(
        "gordo_server_requests_total",
        {
            "method": "GET",
            "path": "{unmatched}",
            "status_code": "404",
            "gordo_name": "",
            "project": "test-project",
        },
    )
    assert count == 4


def test_revision_ids_collapse_in_path_label(client, collection_dir, monkeypatch):
    """DELETE revision/<id> paths collapse the numeric id to
    ``{revision}`` — revisions are unbounded (one per deploy) and must
    not become label values."""
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    # the current revision can't be deleted (409) — perfect: the request
    # is observed without touching the collection
    resp = c.delete("/gordo/v0/test-project/machine-1/revision/1602324482000")
    assert resp.status_code == 409
    count = registry.get_sample_value(
        "gordo_server_requests_total",
        {
            "method": "DELETE",
            "path": "/gordo/v0/{project}/{name}/revision/{revision}",
            "status_code": "409",
            "gordo_name": "machine-1",
            "project": "test-project",
        },
    )
    assert count == 1
    assert not any(
        "1602324482000" in sample.labels.get("path", "")
        for metric in registry.collect()
        for sample in metric.samples
    )


def test_multiproc_dir_auto_created_before_first_metric_write(
    tmp_path, monkeypatch
):
    """prometheus_client crashes at first metric write when the mmap dir
    is missing; both env spellings must be created up front."""
    for env_name in ("PROMETHEUS_MULTIPROC_DIR", "prometheus_multiproc_dir"):
        target = tmp_path / env_name / "mp"
        assert not target.exists()
        for other in ("PROMETHEUS_MULTIPROC_DIR", "prometheus_multiproc_dir"):
            monkeypatch.delenv(other, raising=False)
        monkeypatch.setenv(env_name, str(target))
        GordoServerPrometheusMetrics(
            project="p", registry=CollectorRegistry()
        )
        assert target.is_dir()


# -- build-metric registry bookkeeping -------------------------------------


def test_build_metrics_keyed_by_live_registry_not_id():
    """The per-registry metric cache must hold the registry itself (weak
    key), not ``id(registry)``: a GC'd registry can hand its id to a new
    one, which would then silently receive stale Counter objects that
    its scrapes never see."""
    r1 = CollectorRegistry()
    c1 = fleet_build_robustness_counters(r1)
    c1["fleet_retries"].labels(project="p").inc()
    assert (
        r1.get_sample_value(
            "gordo_fleet_build_member_retries_total", {"project": "p"}
        )
        == 1
    )
    # stable per live registry (the subset dict is rebuilt per call but
    # the metric objects are the cached ones)
    assert (
        fleet_build_robustness_counters(r1)["fleet_retries"]
        is c1["fleet_retries"]
    )
    # the cache must not keep dead registries (or their metrics) alive
    ref = weakref.ref(r1)
    del r1, c1
    gc.collect()
    assert ref() is None
    # a fresh registry always gets fresh metrics registered to IT: its
    # scrape sees the increments (the id-reuse bug left them invisible)
    r2 = CollectorRegistry()
    c2 = fleet_build_robustness_counters(r2)
    c2["fleet_retries"].labels(project="p").inc(3)
    assert (
        r2.get_sample_value(
            "gordo_fleet_build_member_retries_total", {"project": "p"}
        )
        == 3
    )


def test_fleet_build_metric_set_complete():
    registry = CollectorRegistry()
    metrics = fleet_build_metrics(registry)
    metrics["phase_duration"].labels(project="p", phase="dump").observe(0.5)
    metrics["compile_duration"].labels(
        project="p", program="fleet_fit", shape="(2, 128, 4)"
    ).observe(1.5)
    metrics["member_final_loss"].labels(project="p").observe(0.01)
    metrics["machines_total"].labels(project="p").set(10)
    metrics["machines_completed"].labels(project="p").set(4)
    metrics["machines_failed"].labels(project="p").set(1)
    assert (
        registry.get_sample_value(
            "gordo_fleet_build_phase_duration_seconds_count",
            {"project": "p", "phase": "dump"},
        )
        == 1
    )
    assert (
        registry.get_sample_value(
            "gordo_fleet_compile_duration_seconds_count",
            {"project": "p", "program": "fleet_fit", "shape": "(2, 128, 4)"},
        )
        == 1
    )
    assert (
        registry.get_sample_value(
            "gordo_fleet_member_final_loss_count", {"project": "p"}
        )
        == 1
    )
    assert (
        registry.get_sample_value(
            "gordo_fleet_build_machines_completed", {"project": "p"}
        )
        == 4
    )


def test_record_helpers_hit_default_registry():
    """The record_* helpers FleetBuilder's telemetry listener calls
    land in the default REGISTRY under the caller's project label."""
    from prometheus_client import REGISTRY

    prom_metrics.record_fleet_build_phase("helper-proj", "cv_train", 2.0)
    prom_metrics.record_fleet_compile(
        "helper-proj", "fleet_fit", "(1, 64, 2)", 0.2
    )
    prom_metrics.record_member_final_loss("helper-proj", 0.5)
    prom_metrics.set_fleet_build_progress("helper-proj", 5, 2, 1)
    assert (
        REGISTRY.get_sample_value(
            "gordo_fleet_build_phase_duration_seconds_count",
            {"project": "helper-proj", "phase": "cv_train"},
        )
        >= 1
    )
    assert (
        REGISTRY.get_sample_value(
            "gordo_fleet_build_machines_total", {"project": "helper-proj"}
        )
        == 5
    )


# -- serving RED metrics (stage histograms + explicit error counter) ---------


def test_stage_duration_histograms_per_request(
    client, collection_dir, sensor_payload, monkeypatch
):
    """Every instrumented request stage lands one observation in
    gordo_server_stage_duration_seconds{endpoint,stage} — the aggregable
    form of the Server-Timing header."""
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    resp = c.post(
        "/gordo/v0/test-project/machine-1/prediction", json=sensor_payload
    )
    assert resp.status_code == 200
    for stage in (
        "model_resolve",
        "data_decode",
        "device_ingest",
        "inference",
        "response_assemble",
        "serialize",
    ):
        count = registry.get_sample_value(
            "gordo_server_stage_duration_seconds_count",
            {
                "project": "test-project",
                "endpoint": "prediction",
                "stage": stage,
            },
        )
        assert count == 1, f"stage {stage} not observed"
    # stage sums roughly partition the request duration
    total = registry.get_sample_value(
        "gordo_server_request_duration_seconds_sum",
        {
            "method": "POST",
            "path": "/gordo/v0/{project}/{name}/prediction",
            "status_code": "200",
            "gordo_name": "machine-1",
            "project": "test-project",
        },
    )
    stage_sum = sum(
        registry.get_sample_value(
            "gordo_server_stage_duration_seconds_sum",
            {
                "project": "test-project",
                "endpoint": "prediction",
                "stage": stage,
            },
        )
        for stage in (
            "model_resolve",
            "data_decode",
            "device_ingest",
            "inference",
            "response_assemble",
            "serialize",
        )
    )
    assert 0 < stage_sum <= total


def test_error_counter_classifies_client_and_server_errors(
    client, collection_dir, monkeypatch
):
    monkeypatch.setenv("MODEL_COLLECTION_DIR", collection_dir)
    registry = CollectorRegistry()
    app = build_app(
        config={"ENABLE_PROMETHEUS": True, "PROJECT": "test-project"},
        prometheus_registry=registry,
    )
    c = Client(app)
    # a 404: client-kind error
    assert c.get("/gordo/v0/test-project/no-such/metadata").status_code == 404
    # a 200: no error counted
    assert c.get("/gordo/v0/test-project/machine-1/metadata").status_code == 200
    client_errors = registry.get_sample_value(
        "gordo_server_request_errors_total",
        {
            "method": "GET",
            "path": "/gordo/v0/{project}/{name}/metadata",
            "status_code": "404",
            "gordo_name": "no-such",
            "project": "test-project",
            "kind": "client",
        },
    )
    assert client_errors == 1
    # no error sample exists for the 200
    assert not any(
        sample.labels.get("status_code") == "200"
        for metric in registry.collect()
        for sample in metric.samples
        if sample.name == "gordo_server_request_errors_total"
    )


def test_label_child_cache_matches_uncached_observe(collection_dir, monkeypatch):
    """The hot-path label caches must be pure speedups: repeated
    observations accumulate exactly like uncached .labels() calls."""
    from gordo_tpu.server.prometheus.metrics import (
        GordoServerPrometheusMetrics,
    )

    registry = CollectorRegistry()
    red = GordoServerPrometheusMetrics(project="p", registry=registry)

    class Req:
        method = "POST"
        path = "/gordo/v0/p/m-1/prediction"

    class Resp:
        status_code = 200
        gordo_stage_durations = {"inference": 0.25}
        gordo_endpoint = "prediction"

    for _ in range(3):
        red.observe(Req(), Resp(), 0.5)
    labels = {
        "method": "POST",
        "path": "/gordo/v0/{project}/{name}/prediction",
        "status_code": "200",
        "gordo_name": "m-1",
        "project": "p",
    }
    assert (
        registry.get_sample_value("gordo_server_requests_total", labels) == 3
    )
    assert (
        registry.get_sample_value(
            "gordo_server_request_duration_seconds_sum", labels
        )
        == 1.5
    )
    assert (
        registry.get_sample_value(
            "gordo_server_stage_duration_seconds_count",
            {"project": "p", "endpoint": "prediction", "stage": "inference"},
        )
        == 3
    )


# -- fleet-console collectors (PR 9) ----------------------------------------


def test_fleet_console_collectors_on_every_scrape_registry(
    tmp_path, monkeypatch
):
    """The bounded fleet-health gauges and device counters are
    scrape-time collectors (no mmap backing), so like the program-cache
    gauge they must ride BOTH the in-process registry and the fresh
    multiprocess fan-in registry — and registration must be idempotent."""
    import pytest as _pytest

    from gordo_tpu.server.prometheus.metrics import (
        multiprocess_registry,
        register_fleet_console_collectors,
    )
    from gordo_tpu.telemetry import device
    from gordo_tpu.telemetry.fleet_health import ledger_for, reset_ledgers

    _pytest.importorskip("prometheus_client.multiprocess")
    reset_ledgers()
    device.reset_program_counters()
    try:
        ledger = ledger_for(str(tmp_path / "collection"))
        ledger.record_request("m-1", error=True)
        ledger.record_quarantine(["m-2"], revision="9", reasons=["gate"])
        device.note_program_execution(True, kind="serve")

        in_process = CollectorRegistry()
        register_fleet_console_collectors(in_process)
        register_fleet_console_collectors(in_process)  # idempotent

        monkeypatch.setenv(
            "PROMETHEUS_MULTIPROC_DIR", str(tmp_path / "multiproc")
        )
        fan_in = multiprocess_registry()
        assert fan_in is not None

        for registry in (in_process, fan_in):
            assert (
                registry.get_sample_value(
                    "gordo_fleet_health_machines", {"state": "quarantined"}
                )
                == 1
            )
            # m-1 has errors (its score drops) but no state flag — it
            # stays counted healthy; only drift/degrade/quarantine move
            # the state counters
            assert (
                registry.get_sample_value(
                    "gordo_fleet_health_machines", {"state": "healthy"}
                )
                == 1
            )
            # the score histogram's +Inf bucket counts every machine
            assert (
                registry.get_sample_value(
                    "gordo_fleet_health_score_bucket", {"le": "+Inf"}
                )
                == 2
            )
            # gsum is the sum of SCORES (mean health = gsum/gcount),
            # never the machine count: m-1 at 0.7 (all-error requests)
            # + m-2 at 0.5 (quarantined)
            assert (
                registry.get_sample_value(
                    "gordo_fleet_health_score_gsum", {}
                )
                == _pytest.approx(1.2)
            )
            assert (
                registry.get_sample_value(
                    "gordo_compile_cache_events_total",
                    {"side": "serve", "result": "compile"},
                )
                == 1
            )
        # label sets are CONSTANT-bounded: 4 states, no machine names
        samples = [
            sample
            for metric in in_process.collect()
            if metric.name == "gordo_fleet_health_machines"
            for sample in metric.samples
        ]
        assert {s.labels["state"] for s in samples} == {
            "healthy",
            "degraded",
            "drifting",
            "quarantined",
        }
    finally:
        reset_ledgers()
        device.reset_program_counters()


@pytest.mark.scale
def test_store_revision_bytes_gauge(client, collection_dir, sensor_payload):
    """``gordo_store_revision_bytes`` (PR 16): per-revision resident-byte
    estimates from the serving store, revision basenames only in the
    label (bounded by N_CACHED_REVISIONS — the PR 8 cardinality
    contract) with the constant three-value ``kind`` axis."""
    import json as _json

    from gordo_tpu.server.fleet_store import STORE
    from gordo_tpu.server.prometheus.metrics import (
        register_program_cache_collector,
    )

    # score through the route so the served revision is resident
    resp = client.post(
        "/gordo/v0/test-project/machine-1/prediction",
        data=_json.dumps(sensor_payload),
        content_type="application/json",
    )
    assert resp.status_code == 200

    stats = STORE.revision_stats()
    assert stats, "served revision should be resident in the store"

    registry = CollectorRegistry()
    register_program_cache_collector(registry)
    for revision, expected in stats.items():
        value = registry.get_sample_value(
            "gordo_store_revision_bytes",
            {"revision": revision, "kind": "model"},
        )
        assert value == expected["model_bytes"]
        assert value > 0  # real loaded params, not a stub
    samples = [
        sample
        for metric in registry.collect()
        if metric.name == "gordo_store_revision_bytes"
        for sample in metric.samples
    ]
    assert {s.labels["kind"] for s in samples} == {"model", "stacked", "cast"}
    # revision labels are basenames (bounded), never member names
    assert {s.labels["revision"] for s in samples} == set(stats)


def test_serve_metrics_breaker_counters_and_gauge():
    """The serving circuit-breaker metric set (PR 15): transitions by
    entered state (bounded vocabulary) and the open-member gauge."""
    import pytest

    pytest.importorskip("prometheus_client")
    from gordo_tpu.server.prometheus.metrics import ServeMetrics

    registry = CollectorRegistry()
    metrics = ServeMetrics(project="p", registry=registry)
    metrics.observe_breaker("open")
    metrics.observe_breaker("half_open")
    metrics.observe_breaker("closed")
    metrics.observe_breaker("open")
    metrics.set_breaker_open(1)
    metrics.observe_shed("runner_error")
    assert (
        registry.get_sample_value(
            "gordo_server_breaker_transitions_total",
            {"project": "p", "state": "open"},
        )
        == 2
    )
    assert (
        registry.get_sample_value(
            "gordo_server_breaker_transitions_total",
            {"project": "p", "state": "closed"},
        )
        == 1
    )
    assert (
        registry.get_sample_value(
            "gordo_server_breaker_open_members", {"project": "p"}
        )
        == 1
    )
    assert (
        registry.get_sample_value(
            "gordo_server_batch_shed_total",
            {"project": "p", "reason": "runner_error"},
        )
        == 1
    )
