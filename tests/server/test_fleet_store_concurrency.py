"""
Fleet-store behavior under the serving concurrency model (gunicorn gthread
workers = one shared store, many threads): single residency must survive
load races, bucket scoring must not deadlock against concurrent
single-model serving, and restacking must never corrupt results.
"""

import threading

import numpy as np

from gordo_tpu.server.fleet_store import FleetModelStore, RevisionFleet


def test_concurrent_model_loads_single_residency(collection_dir):
    fleet = RevisionFleet(collection_dir)
    seen = []
    errors = []

    def load():
        try:
            seen.append(id(fleet.model("machine-1")))
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=load) for _ in range(16)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert not errors
    assert len(set(seen)) == 1  # every thread got the same resident object


def test_concurrent_scores_and_loads_no_deadlock(collection_dir):
    fleet = RevisionFleet(collection_dir)
    fleet.warm()
    rng = np.random.RandomState(0)
    inputs = {
        "machine-1": rng.rand(6, 4).astype(np.float32),
        "machine-2": rng.rand(6, 2).astype(np.float32),
    }
    # warm compile outside the threads so timing races hit locks, not XLA
    baseline, errors0 = fleet.fleet_scores(inputs)
    assert not errors0

    failures = []
    done = threading.Barrier(9, timeout=120)

    def score():
        try:
            scores, errors = fleet.fleet_scores(inputs)
            assert not errors
            for name in inputs:
                np.testing.assert_allclose(
                    scores[name][0], baseline[name][0], rtol=1e-5, atol=1e-6
                )
        except Exception as exc:  # noqa: BLE001
            failures.append(exc)
        finally:
            done.wait()

    def serve_single():
        try:
            for _ in range(5):
                fleet.model("machine-2").predict(inputs["machine-2"])
        except Exception as exc:  # noqa: BLE001
            failures.append(exc)
        finally:
            done.wait()

    threads = [threading.Thread(target=score) for _ in range(4)] + [
        threading.Thread(target=serve_single) for _ in range(4)
    ]
    for thread in threads:
        thread.start()
    done.wait()
    for thread in threads:
        thread.join(timeout=60)
    assert not failures, failures


def test_store_concurrent_fleet_creation_one_instance(collection_dir):
    store = FleetModelStore(max_revisions=4)
    fleets = []

    def get():
        fleets.append(id(store.fleet(collection_dir)))

    threads = [threading.Thread(target=get) for _ in range(12)]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join()
    assert len(set(fleets)) == 1
