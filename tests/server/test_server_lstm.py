"""
The LSTM model-offset contract through the full serving stack: windowed
models emit ``lookback_window + lookahead - 1`` fewer rows than they are
fed, and the response frame must align timestamps accordingly (reference:
model offset threading through model/utils.py make_base_dataframe and the
anomaly blueprint).
"""

import json

import pytest
from werkzeug.test import Client

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.server import build_app

from .conftest import temp_env_vars

PROJECT = "lstm-proj"
REVISION = "1700000000001"
LOOKBACK = 4

CONFIG = f"""
machines:
  - name: lstm-served
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [lt-1, lt-2]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxLSTMAutoEncoder:
            kind: lstm_model
            lookback_window: {LOOKBACK}
            epochs: 1
"""


@pytest.fixture(scope="module")
def lstm_collection(tmp_path_factory):
    root = tmp_path_factory.mktemp("lstm-collection") / REVISION
    for model, machine in local_build(CONFIG, project_name=PROJECT):
        serializer.dump(
            model, str(root / machine.name), metadata=machine.to_dict()
        )
    return str(root)


@pytest.fixture
def lstm_client(lstm_collection):
    with temp_env_vars(MODEL_COLLECTION_DIR=lstm_collection):
        yield Client(build_app())


@pytest.fixture
def lstm_payload():
    n_rows = 12
    index = [f"2020-03-01T{h:02d}:00:00+00:00" for h in range(n_rows)]
    values = {
        f"lt-{i}": {ts: 0.1 * i + 0.01 * j for j, ts in enumerate(index)}
        for i in (1, 2)
    }
    return {"X": values, "y": values}, index, n_rows


def test_lstm_anomaly_rows_shortened_by_offset(lstm_client, lstm_payload):
    payload, index, n_rows = lstm_payload
    resp = lstm_client.post(
        f"/gordo/v0/{PROJECT}/lstm-served/anomaly/prediction", json=payload
    )
    assert resp.status_code == 200, resp.text
    data = json.loads(resp.data)["data"]
    rows = next(iter(data["total-anomaly-scaled"].values()))
    assert len(rows) == n_rows - (LOOKBACK - 1)
    # output is tail-aligned: the first emitted timestamp is index[offset]
    import dateutil.parser

    first_emitted = dateutil.parser.parse(sorted(rows)[0])
    assert first_emitted == dateutil.parser.parse(index[LOOKBACK - 1])


def test_lstm_metadata_reports_model_offset(lstm_client):
    resp = lstm_client.get(f"/gordo/v0/{PROJECT}/lstm-served/metadata")
    metadata = json.loads(resp.data)["metadata"]
    offset = metadata["metadata"]["build_metadata"]["model"]["model_offset"]
    assert offset == LOOKBACK - 1


def test_lstm_anomaly_too_few_rows_is_client_error(lstm_client):
    index = [f"2020-03-01T0{h}:00:00+00:00" for h in range(2)]  # < lookback
    values = {
        f"lt-{i}": {ts: 0.5 for ts in index} for i in (1, 2)
    }
    resp = lstm_client.post(
        f"/gordo/v0/{PROJECT}/lstm-served/anomaly/prediction",
        json={"X": values, "y": values},
    )
    assert resp.status_code in (400, 422)


def test_lstm_anomaly_parquet_response(lstm_client, lstm_payload):
    payload, _, n_rows = lstm_payload
    resp = lstm_client.post(
        f"/gordo/v0/{PROJECT}/lstm-served/anomaly/prediction?format=parquet",
        json=payload,
    )
    assert resp.status_code == 200
    from gordo_tpu.server.utils import dataframe_from_parquet_bytes

    frame = dataframe_from_parquet_bytes(resp.data)
    assert len(frame) == n_rows - (LOOKBACK - 1)
    top_level = {c[0] for c in frame.columns}
    assert {"model-input", "model-output", "total-anomaly-scaled"} <= top_level
