"""Wire-format tests for the server IO helpers (reference gordo/server/utils.py)."""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.server.utils import (
    ServerError,
    dataframe_from_dict,
    dataframe_from_parquet_bytes,
    dataframe_into_parquet_bytes,
    dataframe_to_dict,
    verify_dataframe,
)


def _multiindex_frame(index):
    columns = pd.MultiIndex.from_tuples(
        (f"feature{i}", f"sub-feature-{ii}") for i in range(2) for ii in range(2)
    )
    return pd.DataFrame(np.arange(8).reshape((2, 4)), columns=columns, index=index)


def test_dataframe_to_dict_midnight_index_serializes_date_only():
    """Reference wire-format parity (utils.py:129-131): an all-midnight
    DatetimeIndex serializes via astype(str) as date-only keys."""
    df = _multiindex_frame(pd.date_range("2019-01-01", "2019-02-01", periods=2))
    out = dataframe_to_dict(df)
    assert out["feature0"]["sub-feature-0"] == {"2019-01-01": 0, "2019-02-01": 4}


def test_dataframe_to_dict_intraday_index_keeps_time():
    df = _multiindex_frame(
        pd.DatetimeIndex(["2019-01-01 06:30:00", "2019-01-01 12:45:00"])
    )
    out = dataframe_to_dict(df)
    assert list(out["feature1"]["sub-feature-1"]) == [
        "2019-01-01 06:30:00",
        "2019-01-01 12:45:00",
    ]


@pytest.mark.parametrize(
    "index",
    [
        pd.date_range("2019-01-01", "2019-02-01", periods=4),
        pd.DatetimeIndex(["2019-01-01 06:30:00", "2019-01-02 12:00:01"]),
        pd.RangeIndex(3),
    ],
)
def test_dict_wire_format_roundtrip(index):
    columns = pd.MultiIndex.from_tuples(
        (f"f{i}", f"s{ii}") for i in range(2) for ii in range(2)
    )
    df = pd.DataFrame(
        np.arange(4 * len(index)).reshape((len(index), 4)),
        columns=columns,
        index=index,
    )
    restored = dataframe_from_dict(dataframe_to_dict(df))
    np.testing.assert_array_equal(restored.to_numpy(), df.to_numpy())
    if isinstance(index, pd.DatetimeIndex):
        assert (restored.index == index).all()


def test_dataframe_to_dict_does_not_mutate_input():
    df = _multiindex_frame(pd.date_range("2019-01-01", "2019-02-01", periods=2))
    dataframe_to_dict(df)
    assert isinstance(df.index, pd.DatetimeIndex)


def test_parquet_roundtrip_preserves_multiindex():
    df = _multiindex_frame(pd.date_range("2019-01-01", "2019-02-01", periods=2))
    restored = dataframe_from_parquet_bytes(dataframe_into_parquet_bytes(df))
    pd.testing.assert_frame_equal(restored, df)


def test_verify_dataframe_rejects_multiindex_input():
    df = _multiindex_frame(pd.RangeIndex(2))
    with pytest.raises(ServerError) as excinfo:
        verify_dataframe(df, ["a", "b"])
    assert excinfo.value.status == 400


def test_verify_dataframe_names_unlabeled_columns():
    df = pd.DataFrame(np.zeros((3, 2)))
    out = verify_dataframe(df, ["tag-1", "tag-2"])
    assert list(out.columns) == ["tag-1", "tag-2"]


def test_verify_dataframe_selects_and_orders_named_columns():
    df = pd.DataFrame(np.arange(9).reshape(3, 3), columns=["c", "a", "b"])
    out = verify_dataframe(df, ["a", "b"])
    assert list(out.columns) == ["a", "b"]


def test_verify_dataframe_wrong_width_is_400():
    df = pd.DataFrame(np.zeros((3, 3)))
    with pytest.raises(ServerError) as excinfo:
        verify_dataframe(df, ["a", "b"])
    assert excinfo.value.status == 400


def test_dataframe_to_dict_object_dtype_boxes_numpy_scalars():
    """Object-dtype columns must yield python natives (np.int64 would
    break stdlib-json clients; review finding)."""
    df = pd.DataFrame({"a": pd.Series([np.int64(5), "x"], dtype=object)})
    out = dataframe_to_dict(df)
    v = out["a"][0]
    assert type(v) is int and v == 5


def test_dataframe_to_dict_duplicate_columns_degrade_not_crash():
    """Duplicate column labels keep pandas' warn-and-omit semantics (the
    old behavior) instead of raising."""
    import warnings

    df = pd.DataFrame([[1, 2], [3, 4]], columns=["a", "a"])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = dataframe_to_dict(df)
    assert "a" in out


def test_dataframe_to_dict_object_dtype_boxes_numpy_datetimes():
    """np.datetime64/timedelta64 in object columns must box to
    Timestamp/Timedelta, not raw nanosecond ints (review finding)."""
    df = pd.DataFrame(
        {
            "t": pd.Series([np.datetime64("2020-01-01", "ns")], dtype=object),
            "d": pd.Series([np.timedelta64(1, "h")], dtype=object),
        }
    )
    out = dataframe_to_dict(df)
    assert isinstance(out["t"][0], pd.Timestamp)
    assert out["t"][0] == pd.Timestamp("2020-01-01")
    assert isinstance(out["d"][0], pd.Timedelta)


def test_delete_revision_reclaims_dir_despite_journal_and_staging(tmp_path):
    """build_state.json and orphaned `.tmp-*` staging dirs are builder
    droppings, not models: deleting the last model must still reclaim the
    revision directory."""
    from gordo_tpu import serializer
    from gordo_tpu.server.utils import delete_revision
    from sklearn.preprocessing import MinMaxScaler

    revision = tmp_path / "1602324482000"
    revision.mkdir()
    serializer.dump(MinMaxScaler(), str(revision / "only-model"), metadata={})
    (revision / "build_state.json").write_text("{}")
    (revision / ".dead.tmp-1").mkdir()

    delete_revision(str(revision), "only-model")
    assert not revision.exists()
