"""
The server-side observability surface: the ``build-status`` route
serving the builder's heartbeat document, and the per-stage
``Server-Timing`` entries the request recorder produces.
"""

import json
import os

import pytest

from gordo_tpu.telemetry.progress import BUILD_STATUS_FILE

# Must match tests/server/conftest.py
PROJECT = "test-project"
REVISION = "1602324482000"

pytestmark = pytest.mark.observability


def url(rest: str) -> str:
    return f"/gordo/v0/{PROJECT}/{rest}"


@pytest.fixture
def status_doc(collection_dir):
    doc = {
        "version": 1,
        "project": PROJECT,
        "state": "running",
        "phase": "dump",
        "elapsed_sec": 12.0,
        "machines": {
            "total": 5,
            "completed": 2,
            "failed": 0,
            "resumed": 0,
            "cached": 0,
            "degraded": 0,
        },
        "phases": {"plan": {"seconds": 0.2, "status": "done"}},
    }
    path = os.path.join(collection_dir, BUILD_STATUS_FILE)
    with open(path, "w") as f:
        json.dump(doc, f)
    yield doc
    os.remove(path)


def test_build_status_route_serves_heartbeat(client, status_doc):
    resp = client.get(url("build-status"))
    assert resp.status_code == 200
    body = resp.json
    assert body["state"] == "running"
    assert body["phase"] == "dump"
    assert body["machines"]["completed"] == 2
    # served like every document of this revision
    assert body["revision"] == REVISION
    assert resp.headers["revision"] == REVISION


def test_build_status_404_when_no_build_wrote_one(client):
    resp = client.get(url("build-status"))
    assert resp.status_code == 404
    assert "error" in resp.json


def test_build_status_ignored_by_model_listing(client, status_doc):
    resp = client.get(url("models"))
    assert sorted(resp.json["models"]) == ["machine-1", "machine-2"]


def test_server_timing_carries_stage_breakdown(client, sensor_payload):
    resp = client.post(
        url("machine-1/prediction"), json={"X": sensor_payload["X"]}
    )
    assert resp.status_code == 200
    timing = resp.headers["Server-Timing"]
    for stage in ("model_resolve", "data_decode", "inference", "serialize"):
        assert f"{stage};dur=" in timing
    # reference-parity total stays last, in seconds, under its old name
    assert timing.rstrip().rpartition(",")[2].strip().startswith(
        "request_walltime_s;dur="
    )


def test_server_timing_anomaly_route_stages(client, sensor_payload):
    resp = client.post(
        url("machine-1/anomaly/prediction"),
        json={"X": sensor_payload["X"], "y": sensor_payload["y"]},
    )
    assert resp.status_code == 200
    timing = resp.headers["Server-Timing"]
    for stage in ("model_resolve", "data_decode", "inference", "serialize"):
        assert f"{stage};dur=" in timing


def test_server_timing_on_non_handler_routes_still_present(client):
    resp = client.get("/healthcheck")
    assert "Server-Timing" in resp.headers
    assert "request_walltime_s;dur=" in resp.headers["Server-Timing"]
