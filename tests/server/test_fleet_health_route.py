"""
The ``fleet-health`` route (PR 9): the joined fleet-status document over
the served collection, and the serving-side health-ledger feed
(per-machine request/error counts from the prediction routes, residual
means from the fleet route).
"""

import json
import os

import pytest

from gordo_tpu.telemetry.fleet_health import (
    FLEET_HEALTH_FILE,
    ledger_for,
    reset_ledgers,
)

# Must match tests/server/conftest.py
PROJECT = "test-project"
REVISION = "1602324482000"

pytestmark = [pytest.mark.fleet_health, pytest.mark.observability]


def url(rest: str) -> str:
    return f"/gordo/v0/{PROJECT}/{rest}"


@pytest.fixture(autouse=True)
def _fresh_ledgers(collection_dir):
    reset_ledgers()
    yield
    reset_ledgers()
    # the collection dir is session-scoped; snapshots must not leak
    # into later tests (e.g. model listings)
    path = os.path.join(collection_dir, FLEET_HEALTH_FILE)
    if os.path.exists(path):
        os.remove(path)


def test_fleet_health_route_serves_joined_document(client, collection_dir):
    ledger = ledger_for(collection_dir)
    ledger.record_request("machine-1")
    ledger.record_drift(
        "machine-1", True, ["feature-shift tag-1 (3.00σ)"],
        {"feature_shift_max": 3.0},
    )

    resp = client.get(url("fleet-health"))
    assert resp.status_code == 200
    doc = resp.json
    assert doc["directory"] == os.path.normpath(collection_dir)
    assert doc["revision"] == REVISION
    # the live in-process ledger answers, snapshot or not
    summary = doc["health"]["summary"]
    assert summary["machines"] == 1
    assert summary["drifting"] == 1
    machine = doc["health"]["machines"]["machine-1"]
    assert machine["health"]["state"] == "drifting"
    assert machine["drift"]["reasons"] == ["feature-shift tag-1 (3.00σ)"]
    # device + program sections always present (may be degraded)
    assert "compile_cache" in doc["device"]
    assert set(doc["programs"]) == {"programs", "signatures", "by_precision"}
    # missing sections are null, not errors
    assert doc["build"] is None
    assert doc["lifecycle"] is None


@pytest.mark.scale
def test_fleet_health_route_machine_selection_params(client, collection_dir):
    """The bounded-surface query grammar (PR 16): ``?machines=`` selects
    records explicitly, ``?limit=``/``?offset=`` page the selection."""
    ledger = ledger_for(collection_dir)
    names = [f"route-m-{i:02d}" for i in range(12)]
    for name in names:
        ledger.record_request(name)

    doc = client.get(url("fleet-health?machines=none")).json
    assert doc["health"]["machines"] is None
    assert doc["health"]["machines_total"] == 12
    assert doc["health"]["machines_truncated"] is True
    assert doc["health"]["summary"]["machines"] == 12

    doc = client.get(url("fleet-health?machines=all&limit=5")).json
    assert sorted(doc["health"]["machines"]) == names[:5]
    assert doc["health"]["machines_offset"] == 0
    assert doc["health"]["machines_truncated"] is True

    doc = client.get(url("fleet-health?machines=all&limit=5&offset=10")).json
    assert sorted(doc["health"]["machines"]) == names[10:]
    assert doc["health"]["machines_truncated"] is False

    doc = client.get(
        url("fleet-health?machines=route-m-03,route-m-07,no-such")
    ).json
    assert sorted(doc["health"]["machines"]) == ["route-m-03", "route-m-07"]

    # malformed paging never errors — it falls back to defaults
    doc = client.get(url("fleet-health?machines=all&limit=zap&offset=zap")).json
    assert len(doc["health"]["machines"]) == 12


def test_fleet_health_route_without_any_data_still_answers(client):
    resp = client.get(url("fleet-health"))
    assert resp.status_code == 200
    assert resp.json["health"] is None


def test_prediction_requests_feed_the_ledger(
    client, collection_dir, sensor_payload
):
    resp = client.post(
        url("machine-1/prediction"),
        data=json.dumps(sensor_payload),
        content_type="application/json",
    )
    assert resp.status_code == 200
    ledger = ledger_for(collection_dir)
    machine = ledger.machine("machine-1")
    assert machine["serving"]["requests"] == 1
    assert machine["serving"]["errors"] == 0
    # a metadata GET is not scoring traffic — it must not count
    assert client.get(url("machine-1/metadata")).status_code == 200
    assert ledger.machine("machine-1")["serving"]["requests"] == 1


def test_unknown_model_names_never_mint_ledger_records(
    client, collection_dir
):
    """gordo_name is client-supplied URL text: a scanner hitting random
    model paths must not grow the ledger (the request-derived-identity
    cardinality class, moved from labels into the ledger)."""
    for name in ("no-such-model", "also-missing"):
        resp = client.post(
            url(f"{name}/prediction"),
            data=json.dumps({"X": {}}),
            content_type="application/json",
        )
        assert resp.status_code >= 400
    ledger = ledger_for(collection_dir)
    assert ledger.machine("no-such-model") is None
    assert ledger.machine("also-missing") is None
    assert ledger.summary()["machines"] == 0


def test_client_errors_do_not_mark_the_machine(client, collection_dir):
    resp = client.post(
        url("machine-1/prediction"),
        data=json.dumps({"X": {"wrong": {"2020-01-01T00:00:00+00:00": 1.0}}}),
        content_type="application/json",
    )
    assert 400 <= resp.status_code < 500
    machine = ledger_for(collection_dir).machine("machine-1")
    assert machine["serving"]["requests"] == 1
    assert machine["serving"]["errors"] == 0
    assert machine["health"]["state"] == "healthy"


def test_fleet_route_records_residual_means(
    client, collection_dir, sensor_payload
):
    resp = client.post(
        url("prediction/fleet"),
        data=json.dumps({"X": {"machine-1": sensor_payload["X"]}}),
        content_type="application/json",
    )
    assert resp.status_code == 200
    assert "machine-1" in resp.json["data"]
    machine = ledger_for(collection_dir).machine("machine-1")
    assert machine["serving"]["requests"] == 1
    assert machine["serving"]["rows"] > 0
    assert machine["serving"]["residual_mean"] is not None
    assert machine["serving"]["residual_mean"] >= 0.0


def test_health_switch_off_keeps_routes_clean(
    client, collection_dir, sensor_payload, monkeypatch
):
    monkeypatch.setenv("GORDO_TPU_FLEET_HEALTH", "0")
    resp = client.post(
        url("machine-1/prediction"),
        data=json.dumps(sensor_payload),
        content_type="application/json",
    )
    assert resp.status_code == 200
    assert not os.path.exists(os.path.join(collection_dir, FLEET_HEALTH_FILE))
    # the route still answers — health section simply null
    assert client.get(url("fleet-health")).status_code == 200
