"""
Fleet-resident serving: the revision store (no per-model eviction, device
params resident) and the batch fleet-prediction route that scores many
models as one fused device program.
"""

import json

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.server.fleet_store import FleetModelStore, RevisionFleet

from .conftest import PROJECT


@pytest.fixture
def fleet_payload(sensor_payload):
    """Per-machine X frames: machine-1 has 4 tags, machine-2 has 2."""
    index = sorted(next(iter(sensor_payload["X"].values())))
    return {
        "machine-1": sensor_payload["X"],
        "machine-2": {
            f"tag-{i}": {ts: 0.05 * i + 0.02 * j for j, ts in enumerate(index)}
            for i in range(1, 3)
        },
    }


def test_fleet_prediction_route(client, fleet_payload):
    resp = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": fleet_payload}
    )
    assert resp.status_code == 200, resp.text
    body = json.loads(resp.data)
    assert set(body["data"]) == {"machine-1", "machine-2"}
    for name, payload in fleet_payload.items():
        entry = body["data"][name]
        n_rows = len(next(iter(payload.values())))
        assert len(entry["total-anomaly-unscaled"]) == n_rows
        assert len(entry["model-output"]) == len(payload)  # one col per tag
    assert "revision" in body


def test_fleet_prediction_matches_single_model(client, collection_dir, fleet_payload):
    """The fused bucket path must agree with each model's own predict."""
    resp = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": fleet_payload}
    )
    body = json.loads(resp.data)

    from gordo_tpu.server.utils import dataframe_from_dict

    for name in fleet_payload:
        model = serializer.load(f"{collection_dir}/{name}")
        X = dataframe_from_dict(fleet_payload[name])
        expected = np.asarray(model.predict(X))
        got_cols = body["data"][name]["model-output"]
        got = np.column_stack(
            [
                [got_cols[str(i)][k] for k in sorted(got_cols[str(i)])]
                for i in range(expected.shape[1])
            ]
        )
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_fleet_prediction_missing_model_reported_per_machine(client, fleet_payload):
    payload = {**fleet_payload, "no-such-machine": fleet_payload["machine-2"]}
    resp = client.post(f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload})
    assert resp.status_code == 200  # good machines still scored
    body = json.loads(resp.data)
    assert set(body["data"]) == {"machine-1", "machine-2"}
    assert body["errors"]["no-such-machine"]["status"] == 404


def test_fleet_prediction_requires_body(client):
    resp = client.post(f"/gordo/v0/{PROJECT}/prediction/fleet", json={})
    assert resp.status_code == 400


def test_fleet_prediction_wrong_columns_is_per_machine_error(client, fleet_payload):
    # three wrong-named columns into a 2-tag model: neither a name match
    # nor a width match, so verification must fail for that machine
    bad = {
        "machine-2": {
            name: {"2020-03-01T00:00:00+00:00": 1.0} for name in ("a", "b", "c")
        }
    }
    resp = client.post(f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": bad})
    assert resp.status_code == 400
    body = json.loads(resp.data)
    assert body["errors"]["machine-2"]["status"] == 400


# -- the store itself --------------------------------------------------------


def test_store_single_residency(collection_dir):
    store = FleetModelStore(max_revisions=2)
    first = store.get_model(collection_dir, "machine-1")
    again = store.get_model(collection_dir, "machine-1")
    assert first is again  # loaded once, resident — not re-unpickled


def test_store_revision_eviction(collection_dir, tmp_path):
    store = FleetModelStore(max_revisions=1)
    fleet_a = store.fleet(collection_dir)
    fleet_b = store.fleet(str(tmp_path))  # different revision key
    assert store.fleet(str(tmp_path)) is fleet_b
    assert store.fleet(collection_dir) is not fleet_a  # evicted by b


def test_store_invalidate(collection_dir):
    store = FleetModelStore(max_revisions=2)
    fleet = store.fleet(collection_dir)
    store.invalidate(collection_dir)
    assert store.fleet(collection_dir) is not fleet


def test_fleet_scores_bucket_groups_same_spec(collection_dir):
    """Models sharing a spec score through ONE stacked bucket program."""
    fleet = RevisionFleet(collection_dir)
    fleet.warm()
    specs = fleet.loaded_specs()
    assert set(specs) == {"machine-1", "machine-2"}

    rng = np.random.RandomState(0)
    inputs = {
        "machine-1": rng.rand(7, 4).astype(np.float32),
        "machine-2": rng.rand(5, 2).astype(np.float32),
    }
    scores, errors = fleet.fleet_scores(inputs)
    assert not errors
    for name, (recon, mse) in scores.items():
        assert recon.shape[0] == len(inputs[name])
        assert mse.shape == (len(inputs[name]),)
        assert np.all(np.isfinite(mse)) and np.all(mse >= 0)
        # parity with the model's own predict
        model = fleet.model(name)
        np.testing.assert_allclose(
            recon, np.asarray(model.predict(inputs[name])), rtol=1e-4, atol=1e-5
        )


def test_fleet_prediction_malformed_frame_is_per_machine_error(client, fleet_payload):
    """A bad payload for one machine must not 500 the batch."""
    payload = {
        **fleet_payload,
        "machine-2": {"tag-1": {"not-a-date": 1.0}, "tag-2": {"not-a-date": 2.0}},
    }
    resp = client.post(f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload})
    assert resp.status_code == 200  # machine-1 still scored
    body = json.loads(resp.data)
    assert "machine-1" in body["data"]
    assert body["errors"]["machine-2"]["status"] == 400


def test_fleet_prediction_broken_model_is_per_machine_error(
    client, collection_dir, fleet_payload, tmp_path
):
    """metadata.json present but model.pkl gone: that machine 404s in
    errors, the rest of the batch still scores (review finding)."""
    import shutil

    broken_dir = f"{collection_dir}/broken-machine"
    shutil.copytree(f"{collection_dir}/machine-2", broken_dir)
    try:
        import os

        os.remove(f"{broken_dir}/model.pkl")
        payload = {**fleet_payload, "broken-machine": fleet_payload["machine-2"]}
        resp = client.post(
            f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload}
        )
        assert resp.status_code == 200
        body = json.loads(resp.data)
        assert set(body["data"]) == {"machine-1", "machine-2"}
        assert body["errors"]["broken-machine"]["status"] == 404
    finally:
        shutil.rmtree(broken_dir, ignore_errors=True)


def test_fleet_prediction_corrupt_artifact_is_generic_500(
    client, collection_dir, fleet_payload
):
    """A model.pkl that fails to DESERIALIZE is a server-side problem: the
    per-machine error must be generic (load-error text can carry server
    paths) with status 500, while the rest of the batch still scores."""
    import shutil

    corrupt_dir = f"{collection_dir}/corrupt-machine"
    shutil.copytree(f"{collection_dir}/machine-2", corrupt_dir)
    try:
        with open(f"{corrupt_dir}/model.pkl", "wb") as f:
            f.write(b"not a pickle at all")
        payload = {**fleet_payload, "corrupt-machine": fleet_payload["machine-2"]}
        resp = client.post(
            f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload}
        )
        assert resp.status_code == 200
        body = json.loads(resp.data)
        assert set(body["data"]) == {"machine-1", "machine-2"}
        err = body["errors"]["corrupt-machine"]
        assert err["status"] == 500
        assert err["error"] == "Model could not be loaded"
        assert "corrupt-machine" not in err["error"]  # no paths, no details
    finally:
        shutil.rmtree(corrupt_dir, ignore_errors=True)


def test_fleet_prediction_value_error_is_400(client, collection_dir, fleet_payload):
    """A client-data ValueError in scoring (e.g. too few rows for a
    windowed model) is a per-machine 400, matching the single-model
    routes' ValueError contract."""
    import shutil

    from gordo_tpu.builder import local_build
    from gordo_tpu import serializer

    lstm_dir = f"{collection_dir}/lstm-short"
    config = """
    machines:
      - name: lstm-short
        model:
          gordo_tpu.models.JaxLSTMAutoEncoder: {kind: lstm_model, lookback_window: 8, epochs: 1}
        dataset:
          type: RandomDataset
          train_start_date: "2020-01-01T00:00:00+00:00"
          train_end_date: "2020-01-03T00:00:00+00:00"
          tag_list: [tag-1, tag-2]
    """
    model, machine = next(local_build(config, project_name="test-project"))
    serializer.dump(model, lstm_dir, metadata=machine.to_dict())
    try:
        # 5 rows < lookback 8 → the LSTM's predict raises ValueError
        index = sorted(next(iter(fleet_payload["machine-2"].values())))[:5]
        payload = {
            "lstm-short": {
                t: {ts: 0.5 for ts in index} for t in ("tag-1", "tag-2")
            }
        }
        resp = client.post(
            f"/gordo/v0/{PROJECT}/prediction/fleet", json={"X": payload}
        )
        body = json.loads(resp.data)
        assert body["errors"]["lstm-short"]["status"] == 400
        assert "lookback" in body["errors"]["lstm-short"]["error"]
    finally:
        shutil.rmtree(lstm_dir, ignore_errors=True)


def test_warm_survives_corrupt_artifact(collection_dir, tmp_path):
    """One truncated pickle must not abort warming the rest."""
    import shutil

    work = tmp_path / "rev"
    shutil.copytree(collection_dir, work)
    (work / "machine-1" / "model.pkl").write_bytes(b"truncated garbage")
    fleet = RevisionFleet(str(work))
    loaded = fleet.warm()
    assert "machine-2" in loaded
    assert "machine-1" not in loaded


def test_fleet_and_single_routes_share_wire_key_format(client, fleet_payload):
    """The fleet route and the single-model routes must emit identical
    index keys for identical input (index_wire_keys is the single shared
    definition — this pins the cross-route consistency clients rely on)."""
    resp_fleet = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet",
        json={"X": {"machine-1": fleet_payload["machine-1"]}},
    )
    assert resp_fleet.status_code == 200
    fleet_keys = sorted(
        json.loads(resp_fleet.data)["data"]["machine-1"]["model-output"]["0"]
    )
    resp_single = client.post(
        f"/gordo/v0/{PROJECT}/machine-1/prediction",
        json={"X": fleet_payload["machine-1"]},
    )
    assert resp_single.status_code == 200
    body = json.loads(resp_single.data)["data"]
    # single-model responses name sub-columns by tag; the INDEX keys are
    # the shared wire format under test
    first_tag = next(iter(body["model-output"]))
    single_keys = sorted(body["model-output"][first_tag])
    assert fleet_keys == single_keys


def test_fleet_full_mode_matches_single_anomaly_route(client, sensor_payload):
    """?full: detector machines answer the single anomaly route's column
    groups, assembled from the fused reconstruction."""
    single = client.post(
        f"/gordo/v0/{PROJECT}/machine-1/anomaly/prediction", json=sensor_payload
    )
    assert single.status_code == 200, single.text
    single_data = json.loads(single.data)["data"]

    fleet = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet?full=1",
        json={"X": {"machine-1": sensor_payload["X"]}},
    )
    assert fleet.status_code == 200, fleet.text
    entry = json.loads(fleet.data)["data"]["machine-1"]

    assert set(entry) == set(single_data)  # same column groups incl.
    # tag-anomaly-*, total-anomaly-*, anomaly-confidence
    for group in (
        "model-input",
        "model-output",
        "tag-anomaly-scaled",
        "tag-anomaly-unscaled",
        "total-anomaly-scaled",
        "total-anomaly-unscaled",
        "anomaly-confidence",
        "total-anomaly-confidence",
    ):
        assert group in entry, f"missing column group {group}"
    # numeric parity with the single-model route (nested {col: {ts: v}}
    # or flat {ts: v} — compare whatever shape the wire uses, recursively)
    def assert_close(got, expected, path):
        if isinstance(expected, dict):
            assert set(got) == set(expected), path
            for key in expected:
                assert_close(got[key], expected[key], f"{path}/{key}")
        else:
            assert got == pytest.approx(expected, rel=1e-5, abs=1e-7), path

    for group in ("total-anomaly-unscaled", "total-anomaly-scaled"):
        assert_close(entry[group], single_data[group], group)


def test_fleet_full_mode_non_detector_stays_lean(client, fleet_payload):
    """machine-2 is a plain AE (no detector): full mode falls back to the
    lean {model-output, total-anomaly-unscaled} shape for it."""
    resp = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet?full=1",
        json={"X": {"machine-2": fleet_payload["machine-2"]}},
    )
    assert resp.status_code == 200, resp.text
    entry = json.loads(resp.data)["data"]["machine-2"]
    assert set(entry) == {"model-output", "total-anomaly-unscaled"}


def test_fleet_full_mode_drops_smooth_without_all_columns(client, sensor_payload):
    resp = client.post(
        f"/gordo/v0/{PROJECT}/prediction/fleet?full=1",
        json={"X": {"machine-1": sensor_payload["X"]}},
    )
    entry = json.loads(resp.data)["data"]["machine-1"]
    assert not any(key.startswith("smooth-") for key in entry)
