"""
FleetModelStore lifecycle routing: hot-swap redirects, canary traffic
slices, and their interplay with invalidation.
"""

import os

import pytest

from gordo_tpu.server.fleet_store import FleetModelStore

pytestmark = pytest.mark.lifecycle


@pytest.fixture
def roots(tmp_path):
    base = tmp_path / "100"
    canary = tmp_path / "101"
    base.mkdir()
    canary.mkdir()
    return str(base), str(canary)


def test_route_is_identity_without_lifecycle_state(roots):
    base, _ = roots
    store = FleetModelStore(max_revisions=2)
    assert store.route(base) == base


def test_swap_redirects_and_swap_back_restores(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.swap(base, canary, warm=False)
    assert store.route(base) == canary
    # requests already routed keep their fleet; the base fleet object
    # is untouched by the swap (pinned-snapshot contract)
    store.swap(base, base, warm=False)
    assert store.route(base) == base


def test_canary_slice_alternates_deterministically(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.set_canary(base, canary, fraction=0.5, warm=False)
    routed = [store.route(base) for _ in range(6)]
    assert routed.count(canary) == 3
    assert routed.count(base) == 3
    status = store.canary_status()
    assert status["fraction"] == pytest.approx(0.5)
    store.clear_canary(base)
    assert store.canary_status() is None
    assert {store.route(base) for _ in range(4)} == {base}


def test_canary_fraction_validation(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    with pytest.raises(ValueError):
        store.set_canary(base, canary, fraction=0.0)
    with pytest.raises(ValueError):
        store.set_canary(base, canary, fraction=1.5)


def test_swap_clears_canary_slice(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.set_canary(base, canary, fraction=1.0, warm=False)
    assert store.route(base) == canary
    store.swap(base, canary, warm=False)
    assert store.canary_status() is None
    assert store.route(base) == canary  # via the redirect now


def test_invalidating_the_target_drops_routing_to_it(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.swap(base, canary, warm=False)
    store.invalidate(canary)
    assert store.route(base) == base

    store.set_canary(base, canary, fraction=1.0, warm=False)
    store.invalidate(canary)
    assert store.canary_status() is None


def test_invalidating_the_source_keeps_the_redirect(roots):
    """A redirect is serving state, not a cache of the source dir: the
    DELETE route invalidating the (stale) source must not un-promote."""
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.swap(base, canary, warm=False)
    store.invalidate(base)
    assert store.route(base) == canary


def test_clear_resets_all_routing(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.swap(base, canary, warm=False)
    store.set_canary(base, canary, fraction=1.0, warm=False)
    store.clear()
    assert store.route(base) == base
    assert store.canary_status() is None


def test_routing_tolerates_cosmetic_path_differences(roots):
    """MODEL_COLLECTION_DIR often carries a trailing slash; a recorded
    promotion/canary must still route for it."""
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    store.swap(base, canary, warm=False)  # installed with the clean path
    assert store.route(base + "/") == canary
    assert store.route(base + "//") == canary
    store.swap(base + "/", base, warm=False)  # swap-back via slashed form
    assert store.route(base) == base

    store.set_canary(base + "/", canary, fraction=1.0, warm=False)
    assert store.route(base) == canary
    store.clear_canary(base)
    assert store.canary_status() is None


def test_ensure_fleet_never_evicts_the_mru_served_revision(roots, tmp_path):
    """Installing a canary must not evict the actively-serving fleet:
    the MRU fast path never refreshes its LRU slot, so without the
    re-rank the hottest revision looks least-recently-used."""
    base, canary = roots
    cold = tmp_path / "99"
    cold.mkdir()
    store = FleetModelStore(max_revisions=2)
    serving = store.fleet(base)
    store.fleet(str(cold))  # cold revision now looks newer than base
    assert store.fleet(base) is serving  # served via the MRU fast path
    store.set_canary(base, canary, fraction=0.5, warm=False)
    # the canary displaced the COLD revision, not the serving one
    assert store.fleet(base) is serving
    assert os.path.realpath(base) in store._revisions


def test_swap_preinstalls_mru_for_the_new_dir(roots):
    base, canary = roots
    store = FleetModelStore(max_revisions=2)
    fleet = store.swap(base, canary, warm=False)
    # the swapped-in fleet is already the lock-free fast path
    assert store._mru == (canary, fleet)
    assert store.fleet(canary) is fleet
    assert os.path.realpath(canary) == fleet.collection_dir
