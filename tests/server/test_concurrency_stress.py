"""Concurrency stress drills for the contracts the new lint rules
encode: the fleet store's copy-on-write read path under concurrent
hot-swap/DELETE churn (no torn reads, no dict-mutated-during-iteration),
and `ledger_for` across a real fork (fresh pid, fresh snapshot path —
the gunicorn --preload bug class)."""

import os
import threading
import time

import pytest

from gordo_tpu.server.fleet_store import FleetModelStore, RevisionFleet

from tests.server.conftest import OLD_REVISION, REVISION, temp_env_vars

pytestmark = pytest.mark.concurrency

STRESS_SECONDS = 3.0


def _run_hammer(workers, duration_s=STRESS_SECONDS):
    """Run worker callables in a tight loop for ``duration_s``;
    returns the list of raised exceptions (want: empty)."""
    deadline = time.monotonic() + duration_s
    failures = []

    def loop(fn):
        while time.monotonic() < deadline:
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 - collected for assert
                failures.append(exc)
                return

    threads = [
        threading.Thread(target=loop, args=(fn,), daemon=True)
        for fn in workers
    ]
    for thread in threads:
        thread.start()
    for thread in threads:
        thread.join(timeout=duration_s + 60.0)
    assert not any(thread.is_alive() for thread in threads), "hammer deadlock"
    return failures


def test_cow_reads_survive_hot_swap_and_delete_churn(model_collection_root):
    """Readers dereference the COW dicts lock-free while hot-swaps and
    DELETE-revision invalidations churn the store: iteration over a
    snapshot must never see a mutation (`dict changed size during
    iteration` is exactly the torn read COW exists to prevent), and a
    resolved model must always be internally consistent."""
    current = str(model_collection_root / REVISION)
    old = str(model_collection_root / OLD_REVISION)
    store = FleetModelStore(max_revisions=2)

    def read_models():
        fleet = store.fleet(store.route(current))
        model = fleet.model("machine-1")
        assert model is not None
        # iterate the COW snapshots: in-place mutation anywhere would
        # raise RuntimeError mid-iteration
        specs = fleet.loaded_specs()
        for name, spec in specs.items():
            assert name and spec is not None
        resolution = fleet.resolution("machine-1")
        assert resolution.model is not None
        assert list(resolution.tag_names)

    def swap_churn():
        store.swap(current, old, warm=False)
        store.swap(current, current, warm=False)  # rollback to disk truth

    def delete_churn():
        store.invalidate(old)
        time.sleep(0.001)

    def route_reads():
        routed = store.route(current)
        assert routed in (current, old)

    failures = _run_hammer(
        [read_models, read_models, read_models, swap_churn, delete_churn, route_reads]
    )
    assert not failures, failures


def test_revision_fleet_warm_races_bucket_reads(collection_dir):
    """Concurrent warm() (whole-dict COW replacement per load) against
    loaded_specs() iteration and spec_bucket() lookups: single
    residency and consistent snapshots throughout."""
    fleet = RevisionFleet(collection_dir)
    ids = set()

    def warm():
        loaded = fleet.warm()
        assert loaded  # artifacts exist

    def snapshot_reads():
        specs = fleet.loaded_specs()
        for name in list(specs):
            model = fleet.model(name)
            ids.add((name, id(model)))

    failures = _run_hammer([warm, warm, snapshot_reads, snapshot_reads], 1.5)
    assert not failures, failures
    # single residency: one object identity per machine, ever
    names = {name for name, _ in ids}
    assert len(ids) == len(names)


@pytest.mark.skipif(
    not hasattr(os, "fork"), reason="fork drill requires POSIX fork"
)
@pytest.mark.filterwarnings("ignore::RuntimeWarning")  # fork-with-threads
def test_ledger_for_across_fork_gets_fresh_pid_sink(tmp_path):
    """The frozen-pid-path bug class, end to end: a child forked after
    the parent built its ledger must get a FRESH ledger bound to its
    own pid-suffixed snapshot path (via the registered post-fork reset
    + the `_pid` check), never the parent's — N workers clobbering one
    shared fleet_health.json was the PR 10 collision."""
    from gordo_tpu.telemetry import fleet_health

    with temp_env_vars(
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_FLEET_HEALTH="1",
        GORDO_TPU_WORKER_SINKS="1",
    ):
        fleet_health.reset_ledgers()
        try:
            parent = fleet_health.ledger_for(str(tmp_path))
            parent.record_request("machine-1")
            parent_path = parent.path
            assert str(os.getpid()) in os.path.basename(parent_path)

            pid = os.fork()
            if pid == 0:
                # child: verdict via exit code only — no pytest
                # machinery may run on this side of the fork
                code = 3
                try:
                    child = fleet_health.ledger_for(str(tmp_path))
                    fresh = (
                        child is not parent
                        and child._pid == os.getpid()
                        and child.path != parent_path
                        and str(os.getpid())
                        in os.path.basename(child.path)
                    )
                    code = 0 if fresh else 1
                except BaseException:
                    code = 2
                os._exit(code)

            _, status = os.waitpid(pid, 0)
            assert os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0, (
                f"fork child exit status {status}"
            )
            # the parent's ledger is untouched by the child's existence
            assert fleet_health.ledger_for(str(tmp_path)) is parent
        finally:
            fleet_health.reset_ledgers()


@pytest.mark.skipif(
    not hasattr(os, "fork"), reason="fork drill requires POSIX fork"
)
@pytest.mark.filterwarnings("ignore::RuntimeWarning")  # fork-with-threads
def test_serve_recorder_reset_runs_in_forked_child(tmp_path):
    """The other registered reset: a forked child must not inherit the
    parent's recorder (its writer thread does not exist post-fork —
    spans would queue forever into a sink nobody drains)."""
    from gordo_tpu.telemetry import serving as serve_trace

    with temp_env_vars(
        GORDO_TPU_TELEMETRY="1",
        GORDO_TPU_TELEMETRY_DIR=str(tmp_path),
        GORDO_TPU_WORKER_SINKS="1",
    ):
        serve_trace.reset_serve_recorder()
        try:
            parent_recorder = serve_trace.serve_recorder()
            assert parent_recorder is not serve_trace.NULL_RECORDER

            pid = os.fork()
            if pid == 0:
                code = 3
                try:
                    fresh = serve_trace._recorder is None
                    rebuilt = serve_trace.serve_recorder()
                    code = (
                        0
                        if fresh and rebuilt is not parent_recorder
                        else 1
                    )
                except BaseException:
                    code = 2
                os._exit(code)

            _, status = os.waitpid(pid, 0)
            assert os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0, (
                f"fork child exit status {status}"
            )
        finally:
            serve_trace.reset_serve_recorder()