"""
Server fixtures: a trained model-collection directory (two anomaly models +
one plain transformer-style model) served by the WSGI app through
werkzeug's test client — the in-process "deployed system" of SURVEY.md §3.5.
"""

import contextlib
import os

import pytest
from werkzeug.test import Client

from gordo_tpu import serializer
from gordo_tpu.builder import local_build
from gordo_tpu.server import build_app

PROJECT = "test-project"
REVISION = "1602324482000"
OLD_REVISION = "1602324482001"

CONFIG = """
machines:
  - name: machine-1
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2, tag-3, tag-4]
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        base_estimator:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_model
            encoding_dim: [8, 4]
            encoding_func: [tanh, tanh]
            decoding_dim: [4, 8]
            decoding_func: [tanh, tanh]
            epochs: 1
  - name: machine-2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        compression_factor: 0.5
        encoding_layers: 1
        epochs: 1
"""


@contextlib.contextmanager
def temp_env_vars(**kwargs):
    """Set environment variables for the duration of the block."""
    originals = {key: os.environ.get(key) for key in kwargs}
    os.environ.update({k: str(v) for k, v in kwargs.items()})
    try:
        yield
    finally:
        for key, original in originals.items():
            if original is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = original


@pytest.fixture(scope="session")
def model_collection_root(tmp_path_factory):
    """
    ``<root>/<revision>/<machine-name>/{model.pkl,metadata.json,info.json}``
    for two revisions (the older one only holds machine-1).
    """
    root = tmp_path_factory.mktemp("model-collection")
    builds = list(local_build(CONFIG, project_name=PROJECT))
    for model, machine in builds:
        out_dir = root / REVISION / machine.name
        serializer.dump(model, str(out_dir), metadata=machine.to_dict())
    # An older revision with just machine-1, for revision routing/deletion.
    model, machine = builds[0]
    serializer.dump(
        model, str(root / OLD_REVISION / machine.name), metadata=machine.to_dict()
    )
    return root


@pytest.fixture(scope="session")
def collection_dir(model_collection_root):
    return str(model_collection_root / REVISION)


@pytest.fixture
def client(collection_dir):
    with temp_env_vars(MODEL_COLLECTION_DIR=collection_dir):
        app = build_app(
            config={"EXPECTED_MODELS": ["machine-1", "machine-2"]}
        )
        yield Client(app)


@pytest.fixture(scope="session")
def sensor_payload(model_collection_root):
    """A valid JSON X/y payload matching machine-1's four tags."""
    index = [
        "2020-03-01T00:00:00+00:00",
        "2020-03-01T00:10:00+00:00",
        "2020-03-01T00:20:00+00:00",
        "2020-03-01T00:30:00+00:00",
        "2020-03-01T00:40:00+00:00",
    ]
    values = {
        f"tag-{i}": {ts: 0.1 * i + 0.01 * j for j, ts in enumerate(index)}
        for i in range(1, 5)
    }
    return {"X": values, "y": values}
