"""
Server route tests against the in-process WSGI app (reference test model:
tests/gordo/server/*)."""

import io
import pickle

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.server import utils as server_utils

# Must match tests/server/conftest.py
PROJECT = "test-project"
REVISION = "1602324482000"
OLD_REVISION = "1602324482001"


def url(rest: str) -> str:
    return f"/gordo/v0/{PROJECT}/{rest}"


def test_healthcheck(client):
    resp = client.get("/healthcheck")
    assert resp.status_code == 200


def test_server_version(client):
    resp = client.get("/server-version")
    assert resp.status_code == 200
    assert "version" in resp.json


def test_model_list(client):
    resp = client.get(url("models"))
    assert resp.status_code == 200
    assert sorted(resp.json["models"]) == ["machine-1", "machine-2"]


def test_expected_models(client):
    resp = client.get(url("expected-models"))
    assert resp.json["expected-models"] == ["machine-1", "machine-2"]


def test_revision_list(client):
    resp = client.get(url("revisions"))
    assert resp.json["latest"] == REVISION
    assert REVISION in resp.json["available-revisions"]
    assert OLD_REVISION in resp.json["available-revisions"]


def test_metadata_route(client):
    resp = client.get(url("machine-1/metadata"))
    assert resp.status_code == 200
    body = resp.json
    assert body["revision"] == REVISION
    assert resp.headers["revision"] == REVISION
    assert "gordo-server-version" in body
    assert body["metadata"]["name"] == "machine-1"
    assert "checksum" in body  # from info.json
    assert "Server-Timing" in resp.headers


def test_metadata_as_healthcheck(client):
    assert client.get(url("machine-1/healthcheck")).status_code == 200


def test_metadata_missing_model(client):
    resp = client.get(url("no-such-model/metadata"))
    assert resp.status_code == 404


def test_bad_model_name(client):
    resp = client.get(url("_bad_name_/metadata"))
    assert resp.status_code == 422


def test_revision_query_param(client):
    resp = client.get(url("machine-1/metadata"), query_string={"revision": OLD_REVISION})
    assert resp.status_code == 200
    assert resp.json["revision"] == OLD_REVISION
    # machine-2 only exists in the latest revision
    resp = client.get(url("machine-2/metadata"), query_string={"revision": OLD_REVISION})
    assert resp.status_code == 404


def test_revision_header(client):
    resp = client.get(url("machine-1/metadata"), headers={"revision": OLD_REVISION})
    assert resp.status_code == 200
    assert resp.json["revision"] == OLD_REVISION


def test_revision_malformed(client):
    resp = client.get(url("machine-1/metadata"), query_string={"revision": "not-digits"})
    assert resp.status_code == 410
    assert "error" in resp.json


def test_revision_with_newline_is_safe_410(client):
    # Malformed revisions must not be echoed into headers (werkzeug would
    # crash on the newline) — just a clean 410.
    resp = client.get(url("machine-1/metadata"), query_string={"revision": "\nabc"})
    assert resp.status_code == 410
    assert "revision" not in resp.headers


def test_revision_not_found(client):
    resp = client.get(url("machine-1/metadata"), query_string={"revision": "999999"})
    assert resp.status_code == 410
    assert "not found" in resp.json["error"]


def test_prediction_json(client, sensor_payload):
    resp = client.post(url("machine-1/prediction"), json={"X": sensor_payload["X"]})
    assert resp.status_code == 200
    data = resp.json["data"]
    assert set(data) >= {"start", "end", "model-input", "model-output"}
    assert len(data["model-output"]) == 4  # four tags
    assert resp.json["revision"] == REVISION


def test_prediction_without_X(client):
    resp = client.post(url("machine-1/prediction"), json={"y": {}})
    assert resp.status_code == 400
    assert "X" in resp.json["message"]


def test_prediction_wrong_width(client):
    X = {"a": {"2020-01-01T00:00:00+00:00": 1.0}}
    resp = client.post(url("machine-1/prediction"), json={"X": X})
    assert resp.status_code == 400
    assert "Unexpected features" in resp.json["message"]


def test_prediction_unlabeled_columns_get_tag_names(client):
    # list-like/positional columns of the right width are accepted
    X = {i: {"2020-01-01T00:00:00+00:00": 0.5} for i in range(4)}
    resp = client.post(url("machine-1/prediction"), json={"X": X})
    assert resp.status_code == 200


def test_prediction_parquet_roundtrip(client, sensor_payload):
    X = pd.DataFrame(
        np.random.RandomState(0).rand(10, 4),
        columns=[f"tag-{i}" for i in range(1, 5)],
        index=pd.date_range("2020-03-01", periods=10, freq="10min", tz="UTC"),
    )
    parquet = server_utils.dataframe_into_parquet_bytes(X)
    resp = client.post(
        url("machine-1/prediction"),
        query_string={"format": "parquet"},
        data={"X": (io.BytesIO(parquet), "X")},
    )
    assert resp.status_code == 200
    df = server_utils.dataframe_from_parquet_bytes(resp.data)
    assert "model-output" in df.columns.get_level_values(0)
    assert len(df) == 10


def test_anomaly_prediction(client, sensor_payload):
    resp = client.post(url("machine-1/anomaly/prediction"), json=sensor_payload)
    assert resp.status_code == 200
    data = resp.json["data"]
    for key in (
        "tag-anomaly-scaled",
        "tag-anomaly-unscaled",
        "total-anomaly-scaled",
        "total-anomaly-unscaled",
        "anomaly-confidence",
        "total-anomaly-confidence",
        "model-input",
        "model-output",
    ):
        assert key in data, f"missing {key} in {sorted(data)}"
    assert "time-seconds" in resp.json


def test_anomaly_requires_y(client, sensor_payload):
    resp = client.post(
        url("machine-1/anomaly/prediction"), json={"X": sensor_payload["X"]}
    )
    assert resp.status_code == 400
    assert "y" in resp.json["message"]


def test_anomaly_non_anomaly_model_is_422(client, sensor_payload):
    X = {k: v for k, v in list(sensor_payload["X"].items())[:2]}
    resp = client.post(
        url("machine-2/anomaly/prediction"), json={"X": X, "y": X}
    )
    assert resp.status_code == 422
    assert "not an AnomalyDetector" in resp.json["message"]


def test_anomaly_smooth_columns_dropped_by_default(client, sensor_payload):
    # machine-1's detector has window=None → no smooth columns either way,
    # so drive the column filter directly through a windowed detector.
    resp_default = client.post(url("machine-1/anomaly/prediction"), json=sensor_payload)
    resp_all = client.post(
        url("machine-1/anomaly/prediction"),
        query_string={"all_columns": "true"},
        json=sensor_payload,
    )
    assert resp_default.status_code == resp_all.status_code == 200
    assert not any(c.startswith("smooth-") for c in resp_default.json["data"])


def test_download_model(client):
    resp = client.get(url("machine-1/download-model"))
    assert resp.status_code == 200
    model = pickle.loads(resp.data)
    X = np.random.RandomState(0).rand(5, 4).astype(np.float32)
    out = model.predict(pd.DataFrame(X, columns=[f"tag-{i}" for i in range(1, 5)]))
    assert out.shape == (5, 4)


def test_delete_current_revision_rejected(client):
    resp = client.delete(url(f"machine-1/revision/{REVISION}"))
    assert resp.status_code == 409


def test_delete_revision_bad_format(client):
    resp = client.delete(url("machine-1/revision/not-digits"))
    assert resp.status_code == 422


def test_delete_missing_revision_model(client):
    resp = client.delete(url("machine-1/revision/55555"))
    assert resp.status_code == 404


def test_delete_old_revision(client, model_collection_root):
    import gordo_tpu.serializer as serializer
    from gordo_tpu.builder import local_build

    # Create a disposable revision then delete it through the API.
    rev = "777777"
    src = model_collection_root / OLD_REVISION / "machine-1"
    dst = model_collection_root / rev / "machine-1"
    import shutil

    shutil.copytree(src, dst)
    resp = client.delete(url(f"machine-1/revision/{rev}"))
    assert resp.status_code == 200
    assert resp.json["ok"] is True
    assert not dst.exists()
    assert not (model_collection_root / rev).exists()


def test_proxy_path_adaptation(client):
    # Envoy forwards the full path; the middleware must still route it.
    resp = client.get(
        url("machine-1/metadata"),
        headers={"X-Envoy-Original-Path": url("machine-1/metadata")},
    )
    assert resp.status_code == 200


def test_trailing_slash_ok(client):
    assert client.get(url("models") + "/").status_code == 200
