"""The shipped example configs must load, build, and generate workflows
(the reference executes its examples as tests: tests/test_examples.py)."""

import os

import pytest
import yaml

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


@pytest.fixture(scope="module")
def example_config_path():
    return os.path.join(EXAMPLES, "config.yaml")


def test_example_config_builds_first_machine(example_config_path):
    from gordo_tpu.builder import local_build
    from gordo_tpu.workflow.workflow_generator import get_dict_from_yaml

    with open(example_config_path) as fh:
        config = get_dict_from_yaml(fh)
    # Trim to one machine + fewer epochs to keep the test fast.
    config["machines"] = config["machines"][:1]
    model, machine = next(local_build(yaml.safe_dump(config)))
    assert machine.name == "ct-23-0001"
    assert machine.metadata.build_metadata.model.cross_validation.scores


def test_example_config_generates_workflow(example_config_path, tmp_path):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo_tpu_cli

    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            example_config_path,
            "--project-name",
            "example-project",
        ],
    )
    assert result.exit_code == 0, result.output
    docs = [d for d in yaml.safe_load_all(result.output) if d]
    assert docs, "workflow generate emitted no documents"


def test_example_model_configurations_all_resolve():
    from gordo_tpu import serializer

    with open(os.path.join(EXAMPLES, "model-configuration.yaml")) as fh:
        blocks = yaml.safe_load(fh)
    for name, definition in blocks.items():
        model = serializer.from_definition(definition)
        assert model is not None, name
        # and they round-trip back into definitions
        serializer.into_definition(model)


def test_example_file_data_config_trains(tmp_path):
    """examples/config-file-data.yaml works end to end once its path points
    at a real parquet export (generated here exactly as its header shows)."""
    import numpy as np
    import pandas as pd

    from gordo_tpu.builder import local_build

    idx = pd.date_range("2020-01-01", "2020-02-01", freq="10min", tz="UTC")
    parquet = tmp_path / "plant-a.parquet"
    pd.DataFrame(
        {f"plant-tag-{i}": np.random.rand(len(idx)) for i in (1, 2, 3)}, index=idx
    ).to_parquet(parquet)

    with open(os.path.join(EXAMPLES, "config-file-data.yaml")) as fh:
        config = yaml.safe_load(fh)
    provider = config["machines"][0]["dataset"]["data_provider"]
    assert provider["type"] == "FileDataProvider"
    provider["path"] = str(parquet)
    config["globals"]["model"][
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
    ]["base_estimator"]["sklearn.pipeline.Pipeline"]["steps"][1][
        "gordo_tpu.models.estimators.JaxAutoEncoder"
    ]["epochs"] = 1

    model, machine = next(local_build(yaml.safe_dump(config)))
    assert model.aggregate_threshold_ is not None
    meta = machine.metadata.build_metadata.dataset.dataset_meta
    # train_end_date is exclusive, so the final 00:00 point drops off
    assert meta["row_count"] == len(idx) - 1


def test_example_influx_callbacks_config_trains(monkeypatch):
    """examples/config-influx-callbacks.yaml works end to end against an
    in-memory Influx fake (the same series layout the example's header
    describes), with its callback stack riding the host loop."""
    import re
    import sys
    import types

    import numpy as np
    import pandas as pd

    from gordo_tpu.builder import local_build

    idx = pd.date_range("2020-01-01", "2020-02-01", freq="10min", tz="UTC")

    class FakeDataFrameClient:
        store = {
            f"plant-tag-{i}": pd.DataFrame(
                {"Value": np.sin(np.arange(len(idx)) / (40.0 + i))}, index=idx
            )
            for i in (1, 2, 3)
        }

        def __init__(self, *args, **kwargs):
            pass

        def query(self, q):
            tag = re.search(r'"tag" = \'([^\']+)\'', q).group(1)
            return {"sensors": self.store[tag]}

    module = types.ModuleType("influxdb")
    module.DataFrameClient = FakeDataFrameClient
    monkeypatch.setitem(sys.modules, "influxdb", module)

    with open(os.path.join(EXAMPLES, "config-influx-callbacks.yaml")) as fh:
        config = yaml.safe_load(fh)
    estimator = config["globals"]["model"][
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector"
    ]["base_estimator"]["sklearn.pipeline.Pipeline"]["steps"][1][
        "gordo_tpu.models.estimators.JaxAutoEncoder"
    ]
    assert len(estimator["callbacks"]) == 3
    estimator["epochs"] = 2

    model, machine = next(local_build(yaml.safe_dump(config)))
    assert machine.name == "plant-b-compressor"
    assert model.aggregate_threshold_ is not None
