"""The shipped example configs must load, build, and generate workflows
(the reference executes its examples as tests: tests/test_examples.py)."""

import os

import pytest
import yaml

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


@pytest.fixture(scope="module")
def example_config_path():
    return os.path.join(EXAMPLES, "config.yaml")


def test_example_config_builds_first_machine(example_config_path):
    from gordo_tpu.builder import local_build
    from gordo_tpu.workflow.workflow_generator import get_dict_from_yaml

    with open(example_config_path) as fh:
        config = get_dict_from_yaml(fh)
    # Trim to one machine + fewer epochs to keep the test fast.
    config["machines"] = config["machines"][:1]
    model, machine = next(local_build(yaml.safe_dump(config)))
    assert machine.name == "ct-23-0001"
    assert machine.metadata.build_metadata.model.cross_validation.scores


def test_example_config_generates_workflow(example_config_path, tmp_path):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo_tpu_cli

    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            example_config_path,
            "--project-name",
            "example-project",
        ],
    )
    assert result.exit_code == 0, result.output
    docs = [d for d in yaml.safe_load_all(result.output) if d]
    assert docs, "workflow generate emitted no documents"


def test_example_model_configurations_all_resolve():
    from gordo_tpu import serializer

    with open(os.path.join(EXAMPLES, "model-configuration.yaml")) as fh:
        blocks = yaml.safe_load(fh)
    for name, definition in blocks.items():
        model = serializer.from_definition(definition)
        assert model is not None, name
        # and they round-trip back into definitions
        serializer.into_definition(model)
