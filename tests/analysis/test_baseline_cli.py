"""Baseline semantics (justification required, fingerprint matching,
stale detection) and the `gordo-tpu lint` CLI gate: exit codes, --as-json,
--report-only, --update-baseline."""

import json
import os

import pytest
from click.testing import CliRunner

from gordo_tpu.analysis import (
    BaselineError,
    default_rules,
    load_baseline,
    run_lint,
    split_by_baseline,
    write_baseline,
)
from gordo_tpu.cli.cli import lint as lint_cli

pytestmark = pytest.mark.analysis

VIOLATION = "from gordo_tpu.server import app\n"


def test_baseline_requires_justification(tmp_path):
    path = tmp_path / "lint_baseline.json"
    path.write_text(
        json.dumps(
            {
                "version": 1,
                "entries": [
                    {
                        "rule": "layering",
                        "path": "x.py",
                        "fingerprint": "abc",
                        "justification": "   ",
                    }
                ],
            }
        )
    )
    with pytest.raises(BaselineError, match="justification"):
        load_baseline(str(path))


def test_baseline_version_and_shape_enforced(tmp_path):
    path = tmp_path / "lint_baseline.json"
    path.write_text(json.dumps({"version": 99, "entries": []}))
    with pytest.raises(BaselineError, match="version"):
        load_baseline(str(path))
    path.write_text("{not json")
    with pytest.raises(BaselineError, match="unparseable"):
        load_baseline(str(path))


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(str(tmp_path / "nope.json")) == []


def test_split_matches_by_fingerprint_and_reports_stale(make_tree, tmp_path):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    findings = run_lint(root, default_rules()).findings
    baseline_path = tmp_path / "lint_baseline.json"
    write_baseline(str(baseline_path), findings, "known, tracked in #123")
    entries = load_baseline(str(baseline_path))
    new, baselined, stale = split_by_baseline(findings, entries)
    assert not new and len(baselined) == 1 and not stale
    # fix the violation: the entry goes stale
    (tmp_path / "gordo_tpu/telemetry/bad.py").write_text("x = 1\n")
    findings = run_lint(root, default_rules()).findings
    new, baselined, stale = split_by_baseline(findings, entries)
    assert not new and not baselined and len(stale) == 1


def _run_cli(root, *args):
    return CliRunner().invoke(lint_cli, ["--root", root, *args])


def test_cli_exits_nonzero_on_new_finding(make_tree):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    result = _run_cli(root)
    assert result.exit_code == 1
    assert "NEW findings" in result.output
    assert "[layering]" in result.output


def test_cli_report_only_always_exits_zero(make_tree):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    result = _run_cli(root, "--report-only")
    assert result.exit_code == 0
    assert "NEW findings" in result.output


def test_cli_as_json_document(make_tree):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    result = _run_cli(root, "--as-json", "--report-only")
    assert result.exit_code == 0
    doc = json.loads(result.output)
    assert doc["ok"] is False
    assert doc["counts"]["new"] == 1
    assert doc["findings"][0]["rule"] == "layering"


def test_cli_update_baseline_then_clean(make_tree):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    result = _run_cli(root, "--update-baseline")
    assert result.exit_code == 0, result.output
    baseline_path = os.path.join(root, "lint_baseline.json")
    assert os.path.exists(baseline_path)
    # the generated FIXME justification is non-empty, so the gate loads
    # it and the finding is grandfathered
    result = _run_cli(root)
    assert result.exit_code == 0, result.output
    assert "baselined" in result.output


def test_update_baseline_preserves_existing_justifications(make_tree):
    root = make_tree(
        {
            "gordo_tpu/telemetry/bad.py": VIOLATION,
            "gordo_tpu/telemetry/bad2.py": "from gordo_tpu.serve import engine\n",
        }
    )
    findings = run_lint(root, default_rules()).findings
    assert len(findings) == 2
    baseline_path = os.path.join(root, "lint_baseline.json")
    # hand-write a justification for the FIRST finding only
    write_baseline(baseline_path, findings[:1], "hand-written rationale #1")
    # regenerate over both: the existing entry must keep its text
    result = _run_cli(root, "--update-baseline")
    assert result.exit_code == 0, result.output
    entries = {e.fingerprint: e for e in load_baseline(baseline_path)}
    assert len(entries) == 2
    assert entries[findings[0].fingerprint].justification == (
        "hand-written rationale #1"
    )
    assert "FIXME" in entries[findings[1].fingerprint].justification


def test_parse_error_fails_gate_and_report_says_so(make_tree):
    root = make_tree({"gordo_tpu/telemetry/broken.py": "def f(:\n"})
    result = _run_cli(root)
    assert result.exit_code == 1
    assert "unparseable" in result.output
    assert "lint: OK" not in result.output


def test_cli_clean_tree_exits_zero(make_tree):
    root = make_tree({"gordo_tpu/telemetry/ok.py": "x = 1\n"})
    result = _run_cli(root)
    assert result.exit_code == 0
    assert "lint: OK" in result.output


def test_cli_rejects_unjustified_baseline(make_tree, tmp_path):
    root = make_tree({"gordo_tpu/telemetry/ok.py": "x = 1\n"})
    bad = tmp_path / "bad_baseline.json"
    bad.write_text(
        json.dumps(
            {
                "version": 1,
                "entries": [
                    {"rule": "layering", "path": "x.py", "fingerprint": "abc"}
                ],
            }
        )
    )
    result = _run_cli(root, "--baseline", str(bad))
    assert result.exit_code != 0
    assert "justification" in result.output
