"""Rule fixtures: layering arrows (absolute, relative, lazy imports) and
the three jax-hazard rules (device-sync-outside-span, stdlib-only
packages, unhashable jit static args)."""

import pytest

pytestmark = pytest.mark.analysis


def _rules(result, name):
    return [f for f in result.findings if f.rule == name]


# -- layering ----------------------------------------------------------------


def test_layering_relative_and_lazy_imports(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "def lazy():\n"
                "    from ..serializer import serializer\n"
                "    return serializer\n"
            )
        }
    )
    found = _rules(result, "layering")
    assert len(found) == 1
    assert "gordo_tpu.serializer" in found[0].message


def test_layering_planner_must_not_import_serve(lint_tree):
    result = lint_tree(
        {"gordo_tpu/planner/bad.py": "import gordo_tpu.serve.engine\n"}
    )
    assert len(_rules(result, "layering")) == 1


def test_layering_allows_declared_directions(lint_tree):
    # serve -> planner is the declared direction (ladder re-export)
    result = lint_tree(
        {"gordo_tpu/serve/ok.py": "from gordo_tpu.planner import ladder\n"}
    )
    assert not _rules(result, "layering")


def test_layering_utils_is_bottom_of_stack(lint_tree):
    result = lint_tree(
        {"gordo_tpu/utils/bad.py": "from gordo_tpu.telemetry import recorder\n"}
    )
    assert len(_rules(result, "layering")) == 1


# -- jax-device-sync ---------------------------------------------------------


def test_device_sync_outside_span_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/bad.py": (
                "import jax\n"
                "def run(outputs):\n"
                "    return jax.block_until_ready(outputs)\n"
            )
        }
    )
    found = _rules(result, "jax-device-sync")
    assert len(found) == 1
    assert "program_span" in found[0].message


def test_device_sync_inside_span_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/ok.py": (
                "import jax\n"
                "from gordo_tpu import telemetry\n"
                "def run(fit, args, spec):\n"
                "    with telemetry.program_span('fit', spec):\n"
                "        out = fit(*args)\n"
                "        return jax.device_get(out)\n"
            )
        }
    )
    assert not _rules(result, "jax-device-sync")


def test_device_sync_in_sanctioned_helper_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/ok.py": (
                "import jax\n"
                "def fetch_to_host(tree):\n"
                "    return jax.device_get(tree)\n"
            )
        }
    )
    assert not _rules(result, "jax-device-sync")


def test_device_sync_outside_scoped_packages_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/client/ok.py": (
                "import jax\n"
                "def f(x):\n"
                "    return jax.device_get(x)\n"
            )
        }
    )
    assert not _rules(result, "jax-device-sync")


# -- jax-stdlib-only ---------------------------------------------------------


def test_stdlib_only_flags_lazy_numpy_in_telemetry(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "def f():\n"
                "    import numpy as np\n"
                "    return np.zeros(3)\n"
            )
        }
    )
    found = _rules(result, "jax-stdlib-only")
    assert len(found) == 1
    assert "numpy" in found[0].message


def test_stdlib_only_allows_stdlib_and_package_relatives(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": (
                "import json, threading\n"
                "from ..utils.env import env_int\n"
                "assert json and threading and env_int\n"
            )
        }
    )
    assert not _rules(result, "jax-stdlib-only")


# -- jax-static-argnum -------------------------------------------------------


def test_static_argnum_unhashable_annotation(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/bad.py": (
                "import jax\n"
                "from functools import partial\n"
                "@partial(jax.jit, static_argnums=(1,))\n"
                "def f(x, shape: list):\n"
                "    return x\n"
            )
        }
    )
    found = _rules(result, "jax-static-argnum")
    assert len(found) == 1
    assert "shape" in found[0].message


def test_static_argname_unhashable_default(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/bad.py": (
                "import jax\n"
                "def g(x, opts={}):\n"
                "    return x\n"
                "g_jit = jax.jit(g, static_argnames=('opts',))\n"
            )
        }
    )
    found = _rules(result, "jax-static-argnum")
    assert len(found) == 1
    assert "opts" in found[0].message


def test_static_argnum_hashable_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/parallel/ok.py": (
                "import jax\n"
                "from functools import partial\n"
                "@partial(jax.jit, static_argnums=(1,), static_argnames=('interpret',))\n"
                "def f(x, n: int, interpret: bool = False):\n"
                "    return x\n"
            )
        }
    )
    assert not _rules(result, "jax-static-argnum")
