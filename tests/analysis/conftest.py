"""Shared fixture-tree helpers for the static-analysis suite: each rule
test writes a tiny `gordo_tpu/`-shaped tree into tmp_path and lints it
with the committed contracts, so the tests exercise exactly what CI runs."""

import os
import textwrap

import pytest


@pytest.fixture
def make_tree(tmp_path):
    """Write ``{relpath: source}`` under ``tmp_path`` and return the root
    (sources are dedented; relpaths use ``/``)."""

    def _make(files):
        for relpath, source in files.items():
            path = tmp_path / relpath
            path.parent.mkdir(parents=True, exist_ok=True)
            path.write_text(textwrap.dedent(source))
            # parent packages need __init__.py only for humans; the
            # linter walks files, not imports
        return str(tmp_path)

    return _make


@pytest.fixture
def lint_tree(make_tree):
    """Build a tree, lint it with the shipped rules (optionally a
    controlled env registry), return the findings list."""
    from gordo_tpu.analysis import default_rules, run_lint

    def _lint(files, env_registry=None, rules=None):
        root = make_tree(files)
        result = run_lint(
            root, rules if rules is not None else default_rules(env_registry)
        )
        return result

    return _lint


REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
