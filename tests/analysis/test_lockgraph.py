"""The runtime lock-order harness: traced-lock semantics (site
identity, reentrancy, Condition aliasing), edge aggregation and the
sink round-trip, cycle detection, hotspot ranking, and the
`gordo-tpu lockgraph` CLI gate."""

import json
import threading

import pytest
from click.testing import CliRunner

from gordo_tpu.analysis import lockgraph
from gordo_tpu.cli.cli import lockgraph as lockgraph_cli

pytestmark = [pytest.mark.analysis, pytest.mark.concurrency]


@pytest.fixture
def traced(tmp_path):
    """Install tracing into a tmp sink; always uninstall (leaking the
    patched factories would instrument every later test)."""
    sink = str(tmp_path / "lock_trace.jsonl")
    lockgraph.install_lock_trace(sink)
    try:
        yield sink
    finally:
        lockgraph.uninstall_lock_trace()


def _edge(src, dst, count=1, max_wait_ms=0.0, total_wait_ms=0.0):
    return {
        "src": src,
        "dst": dst,
        "count": count,
        "max_wait_ms": max_wait_ms,
        "total_wait_ms": total_wait_ms,
    }


# -- traced locks --------------------------------------------------------------


def test_nested_acquisition_records_ordering_edge(traced):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    assert isinstance(lock_a, lockgraph.TracedLock)
    with lock_a:
        with lock_b:
            pass
    edges = lockgraph._state.snapshot()
    assert len(edges) == 1
    assert edges[0]["src"] != edges[0]["dst"]
    assert edges[0]["count"] == 1
    # same ordering again only bumps the count
    with lock_a:
        with lock_b:
            pass
    assert lockgraph._state.snapshot()[0]["count"] == 2


def test_rlock_reentrancy_records_no_self_edge(traced):
    rlock = threading.RLock()
    with rlock:
        with rlock:
            pass
    assert lockgraph._state.snapshot() == []
    assert lockgraph._state.held() == []  # balanced


def test_condition_shares_its_locks_site(traced):
    lock = threading.Lock()
    condition = threading.Condition(lock)
    outer = threading.Lock()
    with outer:
        with condition:
            pass
        with lock:
            pass
    edges = lockgraph._state.snapshot()
    # both nestings resolve to the SAME edge: Condition(lock) is lock
    assert len(edges) == 1
    assert edges[0]["count"] == 2


def test_condition_wait_keeps_stack_balanced(traced):
    condition = threading.Condition(threading.Lock())
    with condition:
        condition.wait(timeout=0.01)
    assert lockgraph._state.held() == []


def test_held_stack_is_per_thread(traced):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    done = threading.Event()

    def other():
        # this thread holds nothing of ours: acquiring B here must not
        # record an A -> B edge off the MAIN thread's held stack
        with lock_b:
            done.set()

    with lock_a:
        thread = threading.Thread(target=other, daemon=True)
        thread.start()
        assert done.wait(timeout=5.0)
        thread.join(timeout=5.0)
    # stdlib internals (Event/Thread create traced locks too) may add
    # their own edges; the contract is that no A -> B ordering exists
    pairs = {(e["src"], e["dst"]) for e in lockgraph._state.snapshot()}
    assert (lock_a._site, lock_b._site) not in pairs
    assert (lock_b._site, lock_a._site) not in pairs


def test_dump_and_load_round_trip(traced):
    lock_a = threading.Lock()
    lock_b = threading.Lock()
    with lock_a:
        with lock_b:
            pass
    path = lockgraph.dump_edges()
    assert path.endswith(".jsonl")
    # the pid lands in the filename at DUMP time, so a forked worker
    # writes its own sink instead of clobbering the parent's
    import os

    assert f"-{os.getpid()}" in os.path.basename(path)
    lines = [json.loads(line) for line in open(path, encoding="utf-8")]
    assert "meta" in lines[0]
    edges = lockgraph.load_edges([path])
    assert len(edges) == 1
    # merging the same sink twice doubles counts (multi-pid merge shape)
    merged = lockgraph.load_edges([path, path])
    assert merged[0]["count"] == 2 * edges[0]["count"]


def test_install_is_off_without_knob(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_LOCK_TRACE", raising=False)
    assert lockgraph.lock_trace_sink() is None
    assert lockgraph.install_lock_trace() is False
    assert threading.Lock is lockgraph._REAL_LOCK


def test_sink_path_spellings(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_LOCK_TRACE", "1")
    assert lockgraph.lock_trace_sink() == lockgraph.DEFAULT_SINK
    monkeypatch.setenv("GORDO_TPU_LOCK_TRACE", "/tmp/x/edges.jsonl")
    assert lockgraph.lock_trace_sink() == "/tmp/x/edges.jsonl"
    monkeypatch.setenv("GORDO_TPU_LOCK_TRACE", "off")
    assert lockgraph.lock_trace_sink() is None


# -- analysis ------------------------------------------------------------------


def test_cycle_detection_finds_abba():
    edges = [_edge("A", "B"), _edge("B", "A")]
    cycles = lockgraph.find_cycles(edges)
    assert len(cycles) == 1
    assert set(cycles[0]) == {"A", "B"}


def test_cycle_detection_finds_longer_cycles_once():
    edges = [_edge("A", "B"), _edge("B", "C"), _edge("C", "A")]
    cycles = lockgraph.find_cycles(edges)
    assert len(cycles) == 1
    assert set(cycles[0]) == {"A", "B", "C"}


def test_distinct_cycles_over_the_same_nodes_both_report():
    # A->B->C->A and A->C->B->A share a node set but are two distinct
    # ordering violations (different thread pairs) — report both
    edges = [
        _edge("A", "B"),
        _edge("B", "C"),
        _edge("C", "A"),
        _edge("A", "C"),
        _edge("C", "B"),
        _edge("B", "A"),
    ]
    cycles = lockgraph.find_cycles(edges)
    three_node = [c for c in cycles if len(set(c)) == 3]
    assert len(three_node) == 2


def test_acyclic_graph_has_no_cycles():
    edges = [_edge("A", "B"), _edge("A", "C"), _edge("B", "C")]
    assert lockgraph.find_cycles(edges) == []


def test_self_loop_is_reentrancy_not_a_cycle():
    assert lockgraph.find_cycles([_edge("A", "A")]) == []


def test_hotspots_rank_by_worst_single_wait():
    edges = [
        _edge("A", "B", count=100, max_wait_ms=0.5, total_wait_ms=20.0),
        _edge("A", "C", count=2, max_wait_ms=9.0, total_wait_ms=9.5),
    ]
    ranked = lockgraph.hotspots(edges, top=1)
    assert ranked[0]["dst"] == "C"


def test_analyze_report_shape(tmp_path):
    sink = tmp_path / "edges.jsonl"
    sink.write_text(
        json.dumps(_edge("A", "B")) + "\n" + json.dumps(_edge("B", "A")) + "\n"
    )
    report = lockgraph.analyze([str(sink)])
    assert report["ok"] is False
    assert report["locks"] == 2
    assert report["edges"] == 2
    assert any("A" in cycle for cycle in report["cycles"])


# -- the CLI gate --------------------------------------------------------------


def test_lockgraph_cli_passes_on_acyclic_sink(tmp_path):
    sink = tmp_path / "lock_trace-1.jsonl"
    sink.write_text(json.dumps(_edge("A", "B")) + "\n")
    result = CliRunner().invoke(lockgraph_cli, [str(sink)])
    assert result.exit_code == 0, result.output
    assert "OK" in result.output


def test_lockgraph_cli_fails_on_cycle(tmp_path):
    sink = tmp_path / "lock_trace-1.jsonl"
    sink.write_text(
        json.dumps(_edge("A", "B")) + "\n" + json.dumps(_edge("B", "A")) + "\n"
    )
    result = CliRunner().invoke(lockgraph_cli, [str(sink)])
    assert result.exit_code == 1
    assert "CYCLE" in result.output
    # --report-only prints but never gates
    result = CliRunner().invoke(lockgraph_cli, ["--report-only", str(sink)])
    assert result.exit_code == 0


def test_lockgraph_cli_globs_multi_pid_sinks(tmp_path):
    (tmp_path / "lock_trace-1.jsonl").write_text(
        json.dumps(_edge("A", "B")) + "\n"
    )
    (tmp_path / "lock_trace-2.jsonl").write_text(
        json.dumps(_edge("B", "A")) + "\n"
    )
    result = CliRunner().invoke(
        lockgraph_cli, ["--as-json", str(tmp_path / "lock_trace-*.jsonl")]
    )
    assert result.exit_code == 1
    doc = json.loads(result.output)
    assert doc["edges"] == 2 and not doc["ok"]


def test_lockgraph_cli_errors_on_missing_sink(tmp_path):
    result = CliRunner().invoke(
        lockgraph_cli, [str(tmp_path / "nope.jsonl")]
    )
    assert result.exit_code != 0
    assert "no trace sinks" in result.output


# -- end-to-end: a real deadlock-shaped workload -------------------------------


def test_traced_threads_expose_abba_deadlock_potential(traced, tmp_path):
    # the orderings are recorded SEQUENTIALLY on purpose: that is the
    # harness's whole value — it exposes the A->B vs B->A hazard from
    # runs where the deadlock never actually fired
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def ab():
        with lock_a:
            with lock_b:
                pass

    def ba():
        with lock_b:
            with lock_a:
                pass

    for target in (ab, ba):
        thread = threading.Thread(target=target, daemon=True)
        thread.start()
        thread.join(timeout=10.0)
    path = lockgraph.dump_edges()
    report = lockgraph.analyze([path])
    assert report["ok"] is False
    assert any(
        lock_a._site in cycle and lock_b._site in cycle
        for cycle in report["cycles"]
    )
