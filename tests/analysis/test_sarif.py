"""SARIF 2.1.0 output: document shape, rule metadata, baseline
suppressions, stable fingerprints, parse-error notifications, and the
`gordo-tpu lint --sarif` CLI path."""

import json

import pytest
from click.testing import CliRunner

from gordo_tpu.analysis import (
    default_rules,
    run_lint,
    sarif_document,
    split_by_baseline,
)
from gordo_tpu.analysis.baseline import BaselineEntry
from gordo_tpu.cli.cli import lint as lint_cli

pytestmark = pytest.mark.analysis

VIOLATION = "from gordo_tpu.server import app\n"


@pytest.fixture
def lint_outcome(make_tree):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    rules = default_rules()
    result = run_lint(root, rules)
    assert result.findings  # layering violation fixture must fire
    return root, rules, result


def test_sarif_document_shape(lint_outcome):
    _, rules, result = lint_outcome
    doc = sarif_document(result, result.findings, [], rules=rules, version="9.9.9")
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "gordo-tpu-lint"
    assert driver["version"] == "9.9.9"
    rule_ids = {rule["id"] for rule in driver["rules"]}
    # the full catalog rides along, concurrency family included
    assert {
        "layering",
        "lock-guard",
        "cow-publish",
        "fork-safety",
        "thread-lifecycle",
    } <= rule_ids
    for rule in driver["rules"]:
        assert rule["shortDescription"]["text"]


def test_sarif_results_carry_location_and_fingerprint(lint_outcome):
    _, rules, result = lint_outcome
    doc = sarif_document(result, result.findings, [], rules=rules)
    results = doc["runs"][0]["results"]
    assert results
    for entry in results:
        location = entry["locations"][0]["physicalLocation"]
        assert location["artifactLocation"]["uri"].endswith("bad.py")
        assert location["region"]["startLine"] >= 1
        assert location["region"]["startColumn"] >= 1  # SARIF is 1-based
        assert entry["partialFingerprints"]["gordoLint/v1"]
        assert "suppressions" not in entry


def test_sarif_baselined_findings_become_suppressions(lint_outcome):
    _, rules, result = lint_outcome
    finding = result.findings[0]
    entries = [
        BaselineEntry(
            rule=finding.rule,
            path=finding.path,
            fingerprint=finding.fingerprint,
            justification="a deliberate fixture exemption with a reason",
        )
    ]
    new, baselined, _ = split_by_baseline(result.findings, entries)
    doc = sarif_document(result, new, baselined, entries=entries, rules=rules)
    suppressed = [
        r for r in doc["runs"][0]["results"] if "suppressions" in r
    ]
    assert len(suppressed) == len(baselined) >= 1
    suppression = suppressed[0]["suppressions"][0]
    assert suppression["kind"] == "external"
    assert suppression["status"] == "accepted"
    assert "deliberate fixture exemption" in suppression["justification"]


def test_sarif_parse_errors_become_notifications(make_tree):
    root = make_tree({"gordo_tpu/telemetry/broken.py": "def broken(:\n"})
    result = run_lint(root, default_rules())
    assert result.parse_errors
    doc = sarif_document(result, [], [], rules=())
    invocation = doc["runs"][0]["invocations"][0]
    assert invocation["executionSuccessful"] is False
    notes = invocation["toolExecutionNotifications"]
    assert notes and "unparseable" in notes[0]["message"]["text"]


def test_lint_cli_writes_sarif_artifact(make_tree, tmp_path):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    sarif_path = tmp_path / "out" / "lint.sarif"
    sarif_path.parent.mkdir()
    result = CliRunner().invoke(
        lint_cli,
        ["--root", root, "--sarif", str(sarif_path), "--report-only"],
    )
    assert result.exit_code == 0, result.output
    doc = json.loads(sarif_path.read_text())
    assert doc["version"] == "2.1.0"
    assert doc["runs"][0]["results"]
