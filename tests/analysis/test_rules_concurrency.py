"""Rule fixtures for the concurrency contract family: lock-guard
inference (guards, helper-chain fixpoint, Condition aliasing, COW
exemption, module scope), cow-publish mutation discipline, fork-safety
pid-memoization, and thread-lifecycle."""

import pytest

pytestmark = [pytest.mark.analysis, pytest.mark.concurrency]


def _rules(result, name):
    return [f for f in result.findings if f.rule == name]


# -- lock-guard: class scope ---------------------------------------------------


def test_unlocked_write_of_guarded_attribute_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = {}

                    def put(self, key, value):
                        with self._lock:
                            self._items = {**self._items, key: value}

                    def reset(self):
                        self._items = {}
            """
        }
    )
    found = _rules(result, "lock-guard")
    assert len(found) == 1
    assert "Store._items" in found[0].message
    assert "_lock" in found[0].message


def test_all_writes_locked_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = {}

                    def put(self, key, value):
                        with self._lock:
                            self._items = {**self._items, key: value}

                    def reset(self):
                        with self._lock:
                            self._items = {}
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_init_writes_are_construction_not_findings(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Store:
                    def __init__(self, seed):
                        self._lock = threading.Lock()
                        self._items = {}
                        self._items = dict(seed)

                    def put(self, key, value):
                        with self._lock:
                            self._items = {**self._items, key: value}
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_helper_called_only_under_lock_is_lock_held(lint_tree):
    # the submit -> _take_batch -> _ready_key chain: helpers whose every
    # call site holds the lock count as locked, to fixpoint
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Batcher:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._total = 0

                    def submit(self, n):
                        with self._lock:
                            self._bump(n)

                    def _bump(self, n):
                        self._mark(n)

                    def _mark(self, n):
                        self._total += n
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_locked_helper_chain_defined_before_its_callers_is_clean(lint_tree):
    # the _reshard_locked -> _machine -> record_* shape: the deepest
    # helper is DEFINED before the function that seeds its lock context.
    # The fixpoint must not let a not-yet-seeded private caller inject a
    # spurious unlocked context on the first sweep (the empty-context
    # default is a check-time fallback, never a propagated context).
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Ledger:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._count = 0

                    def _grow(self):
                        self._count += 1

                    def _ensure(self):
                        self._grow()

                    def add(self):
                        with self._lock:
                            self._ensure()
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_helper_with_one_unlocked_call_site_is_not_assumed_locked(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import threading

                class Batcher:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._total = 0

                    def submit(self, n):
                        with self._lock:
                            self._bump(n)

                    def poke(self, n):
                        self._bump(n)

                    def _bump(self, n):
                        self._total += n
            """
        }
    )
    found = _rules(result, "lock-guard")
    assert len(found) == 1
    assert "Batcher._total" in found[0].message


def test_condition_aliases_its_underlying_lock(lint_tree):
    # the MicroBatcher idiom: Condition(self._lock) IS self._lock
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Batcher:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._work = threading.Condition(self._lock)
                        self._queues = {}

                    def submit(self, key, item):
                        with self._work:
                            self._queues[key] = item

                    def clear(self):
                        with self._lock:
                            self._queues = {}
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_publishing_return_of_guarded_attribute_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._items = {}

                    def put(self, key, value):
                        with self._lock:
                            self._items[key] = value

                    def items(self):
                        return self._items
            """
        }
    )
    found = _rules(result, "lock-guard")
    assert len(found) == 1
    assert "returned without its lock" in found[0].message


def test_declared_cow_attribute_returns_lock_free(lint_tree):
    # the committed contracts declare RevisionFleet._models COW: writes
    # must still hold the lock, but lock-free publishing reads are the
    # pattern (loaded_specs / the per-request hot path)
    result = lint_tree(
        {
            "gordo_tpu/server/fleet_store.py": """
                import threading

                class RevisionFleet:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._models = {}

                    def load(self, name, model):
                        with self._lock:
                            self._models = {**self._models, name: model}

                    def loaded(self):
                        return self._models
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_suppression_silences_lock_guard(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Store:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._tick = 0

                    def bump(self):
                        with self._lock:
                            self._tick += 1

                    def fast_bump(self):
                        # gt-lint: disable=lock-guard -- approximate by design
                        self._tick += 1
            """
        }
    )
    assert not _rules(result, "lock-guard")
    assert result.suppressed >= 1


# -- lock-guard: module scope --------------------------------------------------


def test_module_registry_written_without_module_lock_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": """
                import threading

                _lock = threading.Lock()
                _cache = {}

                def put(key, value):
                    with _lock:
                        _cache[key] = value

                def sneak(key, value):
                    _cache[key] = value
            """
        }
    )
    found = _rules(result, "lock-guard")
    assert len(found) == 1
    assert "_cache" in found[0].message


def test_function_local_shadow_is_not_a_module_write(lint_tree):
    # honest Python scoping: without `global`, `store = ...` binds a
    # local, even when a module name matches — the double-checked
    # `store = _stores.get(key)` read pattern must not be flagged
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": """
                import threading

                _lock = threading.Lock()
                _stores = {}

                def store_for(key):
                    store = _stores.get(key)
                    if store is not None:
                        return store
                    with _lock:
                        store = _stores.get(key)
                        if store is None:
                            store = _stores[key] = object()
                    return store
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_module_helper_called_only_under_lock_is_lock_held(lint_tree):
    # the call-context fixpoint works at module scope too: a helper
    # whose only call site holds the module lock is not a finding
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": """
                import threading

                _lock = threading.Lock()
                _cache = {}

                def put(key, value):
                    with _lock:
                        _store(key, value)

                def _store(key, value):
                    _cache[key] = value
            """
        }
    )
    assert not _rules(result, "lock-guard")


def test_global_rebind_under_lock_infers_guard(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": """
                import threading

                _lock = threading.Lock()
                _recorder = None

                def set_recorder(value):
                    global _recorder
                    with _lock:
                        _recorder = value

                def drop_recorder():
                    global _recorder
                    _recorder = None
            """
        }
    )
    found = _rules(result, "lock-guard")
    assert len(found) == 1
    assert "_recorder" in found[0].message


# -- cow-publish ---------------------------------------------------------------


def test_in_place_mutation_of_cow_attribute_is_flagged_tree_wide(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/lifecycle/bad.py": """
                def poke(fleet, name, model):
                    fleet._models[name] = model

                def merge(fleet, extra):
                    fleet._models.update(extra)
            """
        }
    )
    found = _rules(result, "cow-publish")
    assert len(found) == 2
    assert all("_models" in f.message for f in found)


def test_whole_object_replacement_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/server/fleet_store.py": """
                import threading

                class RevisionFleet:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._models = {}

                    def load(self, name, model):
                        staged = dict(self._models)
                        staged[name] = model
                        with self._lock:
                            self._models = staged
            """
        }
    )
    assert not _rules(result, "cow-publish")


def test_bare_name_cow_mutation_flagged_only_in_declaring_module(lint_tree):
    # `_recorder` is declared COW for gordo_tpu.telemetry.serving; a
    # same-named local list in an unrelated module is not a claim
    result = lint_tree(
        {
            "gordo_tpu/builder/ok.py": """
                def collect(rows):
                    _recorder = []
                    _recorder.append(rows)
                    return _recorder
            """
        }
    )
    assert not _rules(result, "cow-publish")


# -- fork-safety ---------------------------------------------------------------

_FORK_BAD = """
    import os
    import threading

    _lock = threading.Lock()
    _sinks = {}

    def sink_for(directory):
        key = f"{directory}-{os.getpid()}"
        with _lock:
            if key not in _sinks:
                _sinks[key] = open(key, "a")
            return _sinks[key]
"""


def test_pid_memoization_without_reset_hook_is_flagged(lint_tree):
    result = lint_tree({"gordo_tpu/telemetry/bad.py": _FORK_BAD})
    found = _rules(result, "fork-safety")
    assert len(found) == 1
    assert "_sinks" in found[0].message
    assert "post-fork" in found[0].message


def test_registered_reset_hook_satisfies_fork_safety(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": _FORK_BAD
            + """

    from gordo_tpu.utils.postfork import register_postfork_reset

    def _reset():
        global _sinks
        _sinks = {}

    register_postfork_reset(_reset)
"""
        }
    )
    assert not _rules(result, "fork-safety")


def test_os_register_at_fork_also_satisfies_fork_safety(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": _FORK_BAD
            + """

    os.register_at_fork(after_in_child=_sinks.clear)
"""
        }
    )
    assert not _rules(result, "fork-safety")


def test_registry_without_pid_derivation_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/ok.py": """
                import threading

                _lock = threading.Lock()
                _stores = {}

                def store_for(key):
                    with _lock:
                        if key not in _stores:
                            _stores[key] = object()
                        return _stores[key]
            """
        }
    )
    assert not _rules(result, "fork-safety")


def test_fork_safety_scoped_to_forking_packages(lint_tree):
    # the planner never runs inside forked gunicorn workers
    result = lint_tree({"gordo_tpu/planner/ok.py": _FORK_BAD})
    assert not _rules(result, "fork-safety")


# -- thread-lifecycle ----------------------------------------------------------


def test_non_daemon_unjoined_thread_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import threading

                def start():
                    thread = threading.Thread(target=print)
                    thread.start()
                    return thread
            """
        }
    )
    found = _rules(result, "thread-lifecycle")
    assert len(found) == 1
    assert "daemon" in found[0].message


def test_string_and_path_joins_are_not_shutdown_evidence(lint_tree):
    # os.path.join / sep.join must not read as Thread.join — nearly
    # every module joins paths, which would disable the rule wholesale
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import os
                import threading

                def start(parts):
                    label = "-".join(parts)
                    path = os.path.join("a", "b", label)
                    thread = threading.Thread(target=print)
                    thread.start()
                    return path
            """
        }
    )
    found = _rules(result, "thread-lifecycle")
    assert len(found) == 1
    assert "daemon" in found[0].message


def test_daemon_thread_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                def start():
                    thread = threading.Thread(target=print, daemon=True)
                    thread.start()
                    return thread
            """
        }
    )
    assert not _rules(result, "thread-lifecycle")


def test_joined_thread_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                class Worker:
                    def start(self):
                        self._thread = threading.Thread(target=print)
                        self._thread.start()

                    def stop(self):
                        self._thread.join(timeout=5.0)
            """
        }
    )
    assert not _rules(result, "thread-lifecycle")


def test_unstoppable_worker_loop_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": """
                import threading
                import time

                def _loop():
                    while True:
                        time.sleep(1.0)

                def start():
                    threading.Thread(target=_loop, daemon=True).start()
            """
        }
    )
    found = _rules(result, "thread-lifecycle")
    assert len(found) == 1
    assert "while True" in found[0].message


def test_stop_event_checked_loop_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": """
                import threading

                _stop = threading.Event()

                def _loop():
                    while True:
                        if _stop.wait(timeout=0.05):
                            return

                def start():
                    threading.Thread(target=_loop, daemon=True).start()
            """
        }
    )
    assert not _rules(result, "thread-lifecycle")


def test_non_thread_while_true_is_ignored(lint_tree):
    # CLI polling loops and file readers are not thread worker loops
    result = lint_tree(
        {
            "gordo_tpu/cli/ok.py": """
                import time

                def wait_for(path, exists):
                    while True:
                        if exists(path):
                            break
                        time.sleep(1.0)
            """
        }
    )
    assert not _rules(result, "thread-lifecycle")
