"""Env-knob registry gates: the generated reference table in
docs/configuration.md must match the registry (drift test), every
``GORDO_TPU_*`` token anywhere in the package source must be a declared
knob, and the typed accessors keep their warn-once fallback contract."""

import logging
import os
import re
import subprocess
import sys

import pytest

from gordo_tpu.utils import env as env_mod
from gordo_tpu.utils.env import (
    KNOBS,
    env_bool,
    env_float,
    env_int,
    env_str,
    knob_sections,
)

from .conftest import REPO_ROOT

pytestmark = pytest.mark.analysis


def test_docs_table_is_not_stale():
    result = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "docs", "generate_env_docs.py"),
            "--check",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert result.returncode == 0, (
        "docs/configuration.md drifted from the knob registry:\n"
        + result.stderr
    )


def test_every_source_token_is_a_declared_knob():
    """The grep-the-world drift net: any `GORDO_TPU_*` token in package
    source — code, docstrings, comments — must be a registered knob.
    This is what caught `GORDO_TPU_DOCTEST_KNOB` living only in a
    doctest."""
    token_re = re.compile(r"GORDO_TPU_[A-Z0-9_]+")
    undeclared = {}
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(REPO_ROOT, "gordo_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as handle:
                for token in token_re.findall(handle.read()):
                    if token not in KNOBS and token != "GORDO_TPU_":
                        undeclared.setdefault(token, path)
    assert not undeclared, (
        f"undeclared GORDO_TPU_* tokens in source: {undeclared} — declare "
        "them in gordo_tpu/utils/env.py KNOBS (and regenerate docs) or "
        "rename"
    )


def test_registry_hygiene():
    assert len(KNOBS) >= 45
    for knob in KNOBS.values():
        assert knob.name.startswith("GORDO_TPU_")
        assert knob.type in ("int", "float", "bool", "str"), knob.name
        assert knob.doc.strip(), f"{knob.name} has no doc line"
        assert knob.section in knob_sections()
    # sections render in declaration order and are stable
    assert knob_sections()[0] == "Performance"


def test_accessors_parse_and_fall_back(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "12")
    assert env_int("GORDO_TPU_DOCTEST_KNOB", 7) == 12
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "2.5")
    assert env_float("GORDO_TPU_DOCTEST_KNOB", 0.0) == 2.5
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "on")
    assert env_bool("GORDO_TPU_DOCTEST_KNOB", False) is True
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "no")
    assert env_bool("GORDO_TPU_DOCTEST_KNOB", True) is False
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "")
    assert env_str("GORDO_TPU_DOCTEST_KNOB", "fallback") == "fallback"
    # an EMPTY bool var (blanked-out manifest line) means unset, not
    # False — default-on knobs like GORDO_TPU_TELEMETRY must stay on
    assert env_bool("GORDO_TPU_DOCTEST_KNOB", True) is True
    assert env_bool("GORDO_TPU_DOCTEST_KNOB", False) is False
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "garbage")
    assert env_int("GORDO_TPU_DOCTEST_KNOB", 7) == 7
    assert env_bool("GORDO_TPU_DOCTEST_KNOB", True) is True


def test_malformed_value_warns_once(monkeypatch, caplog):
    monkeypatch.setenv("GORDO_TPU_DOCTEST_KNOB", "not-an-int-xyz")
    env_mod._warned.discard(("GORDO_TPU_DOCTEST_KNOB", "not-an-int-xyz"))
    with caplog.at_level(logging.WARNING, logger="gordo_tpu.utils.env"):
        assert env_int("GORDO_TPU_DOCTEST_KNOB", 7) == 7
        assert env_int("GORDO_TPU_DOCTEST_KNOB", 7) == 7
    warnings = [r for r in caplog.records if "Invalid" in r.getMessage()]
    assert len(warnings) == 1
