"""Engine semantics: suppression comments (same-line, standalone-line,
file-wide), fingerprint stability across unrelated line drift, and
parse-error surfacing."""

import pytest

from gordo_tpu.analysis import default_rules, run_lint

pytestmark = pytest.mark.analysis

#: a telemetry file importing the server — one guaranteed layering finding
VIOLATION = "from gordo_tpu.server import app\n"


def _findings(result, rule=None):
    return [f for f in result.findings if rule is None or f.rule == rule]


def test_plain_violation_is_found(lint_tree):
    result = lint_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    found = _findings(result, "layering")
    assert len(found) == 1
    assert found[0].path == "gordo_tpu/telemetry/bad.py"
    assert found[0].line == 1
    assert "gordo_tpu.server" in found[0].message
    assert found[0].fingerprint  # stamped


def test_same_line_suppression(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "from gordo_tpu.server import app  "
                "# gt-lint: disable=layering -- test escape\n"
            )
        }
    )
    assert not _findings(result, "layering")
    assert result.suppressed == 1


def test_standalone_comment_suppresses_next_line(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "# gt-lint: disable=layering -- the next line is blessed\n"
                "from gordo_tpu.server import app\n"
            )
        }
    )
    assert not _findings(result, "layering")
    assert result.suppressed == 1


def test_standalone_comment_covers_multiline_statement(lint_tree):
    # the finding anchors on the continuation line holding time.time(),
    # not the statement's first line — the suppression must still hit
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": (
                "import time\n"
                "def wait(timeout):\n"
                "    # gt-lint: disable=clock-discipline -- drill\n"
                "    deadline = (\n"
                "        time.time() + timeout\n"
                "    )\n"
                "    return deadline\n"
            )
        }
    )
    assert not _findings(result, "clock-discipline")
    assert result.suppressed == 1


def test_env_constant_suffix_collision_resolves_to_neither(lint_tree):
    # two modules both named env.py exporting FOO_ENV with DIFFERENT
    # values: `env.FOO_ENV` is ambiguous and must not resolve first-wins
    result = lint_tree(
        {
            "gordo_tpu/a/env.py": "FOO_ENV = 'GORDO_TPU_AAA'\n",
            "gordo_tpu/b/env.py": "FOO_ENV = 'GORDO_TPU_BBB'\n",
            "gordo_tpu/models/reader.py": (
                "import os\n"
                "from gordo_tpu.a import env\n"
                "v = os.getenv(env.FOO_ENV)\n"
            ),
        }
    )
    # unresolvable → no env-registry finding, rather than a finding
    # naming the wrong knob
    assert not _findings(result, "env-registry")


def test_file_disable_suppresses_everywhere(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "# gt-lint: file-disable=layering\n"
                "from gordo_tpu.server import app\n"
                "from gordo_tpu.serve import engine\n"
            )
        }
    )
    assert not _findings(result, "layering")
    assert result.suppressed == 2


def test_suppression_is_per_rule(lint_tree):
    # suppressing an unrelated rule must not hide the layering finding
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "from gordo_tpu.server import app  "
                "# gt-lint: disable=clock-discipline\n"
            )
        }
    )
    assert len(_findings(result, "layering")) == 1


def test_fingerprint_stable_across_line_drift(make_tree, tmp_path):
    root = make_tree({"gordo_tpu/telemetry/bad.py": VIOLATION})
    first = run_lint(root, default_rules()).findings[0]
    # unrelated code above moves the finding down two lines
    (tmp_path / "gordo_tpu/telemetry/bad.py").write_text(
        "import os\nimport sys\n" + VIOLATION + "assert os and sys\n"
    )
    second = run_lint(root, default_rules()).findings[0]
    assert second.line == 3 != first.line
    assert second.fingerprint == first.fingerprint


def test_duplicate_findings_fingerprint_by_occurrence(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/bad.py": (
                "def f():\n"
                "    from gordo_tpu.server import app\n"
                "def g():\n"
                "    from gordo_tpu.server import app\n"
            )
        }
    )
    found = _findings(result, "layering")
    assert len(found) == 2
    assert found[0].fingerprint != found[1].fingerprint


def test_parse_errors_are_reported_not_fatal(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/telemetry/broken.py": "def f(:\n",
            "gordo_tpu/telemetry/bad.py": VIOLATION,
        }
    )
    assert len(result.parse_errors) == 1
    assert "broken.py" in result.parse_errors[0]
    assert len(_findings(result, "layering")) == 1


def test_parse_errors_fail_the_document_like_the_gate(lint_tree):
    # ok mirrors the CLI exit: an unparseable file is not a clean run,
    # even with zero findings
    from gordo_tpu.analysis import lint_document

    result = lint_tree({"gordo_tpu/telemetry/broken.py": "def f(:\n"})
    assert not result.findings
    doc = lint_document(result, [], [], [])
    assert doc["ok"] is False
    assert doc["counts"]["parse_errors"] == 1
