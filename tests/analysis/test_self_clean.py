"""The tier-1 self-run gate: `gordo_tpu/` itself must lint clean against
the committed baseline — the same invocation CI's `lint` job runs. A new
violation anywhere in the package fails THIS test before it fails CI."""

import os

import pytest

from gordo_tpu.analysis import (
    default_baseline_path,
    default_rules,
    load_baseline,
    run_lint,
    split_by_baseline,
)

from .conftest import REPO_ROOT

pytestmark = pytest.mark.analysis


@pytest.fixture(scope="module")
def self_result():
    return run_lint(REPO_ROOT, default_rules())


def test_tree_parses_clean(self_result):
    assert not self_result.parse_errors


def test_no_new_findings_against_committed_baseline(self_result):
    entries = load_baseline(default_baseline_path(REPO_ROOT))
    new, _, stale = split_by_baseline(self_result.findings, entries)
    assert not new, "new lint findings:\n" + "\n".join(
        f.render() + f"  [fp {f.fingerprint}]" for f in new
    )
    assert not stale, (
        "stale baseline entries (finding fixed? remove the entry): "
        + ", ".join(f"{e.rule}@{e.path}" for e in stale)
    )


def test_committed_baseline_entries_are_justified():
    # load_baseline raises on unjustified entries; also pin that the
    # baseline stays SMALL — it is a grandfather list, not a mute button
    entries = load_baseline(default_baseline_path(REPO_ROOT))
    assert len(entries) <= 5
    for entry in entries:
        assert len(entry.justification) > 40, (
            f"{entry.rule}@{entry.path}: a one-liner is not a "
            "justification"
        )


def test_contracts_file_is_loadable_and_complete():
    from gordo_tpu.analysis import load_contracts

    contracts = load_contracts()
    assert contracts.arrows, "layering arrows missing from contracts.toml"
    assert contracts.jax_sync_scopes
    assert contracts.jax_stdlib_only
    assert contracts.atomic_scopes
    assert contracts.prometheus_scopes
    assert contracts.env_prefix == "GORDO_TPU_"


def test_toml_subset_parser_matches_contract_shape():
    # the 3.10 fallback parser must read the committed file identically
    # to tomllib's view of it (exercised directly so a 3.11+ CI still
    # covers the shim)
    from gordo_tpu.analysis.contracts import (
        DEFAULT_CONTRACTS_PATH,
        _parse_toml_subset,
    )

    with open(DEFAULT_CONTRACTS_PATH, encoding="utf-8") as handle:
        doc = _parse_toml_subset(handle.read())
    assert {a["module"] for a in doc["layering"]["arrows"]} >= {
        "gordo_tpu.telemetry",
        "gordo_tpu.utils",
        "gordo_tpu.planner",
    }
    assert "jax" in doc["env"]["prefix"] or doc["env"]["prefix"] == "GORDO_TPU_"
    try:
        import tomllib
    except ImportError:
        return
    with open(DEFAULT_CONTRACTS_PATH, "rb") as handle:
        assert doc == tomllib.load(handle)


def test_suppressions_in_tree_carry_reasons():
    # every in-tree `# gt-lint:` comment must carry a ` -- reason` tail;
    # a bare suppression is a mute button with no paper trail
    import re

    bad = []
    for dirpath, dirnames, filenames in os.walk(
        os.path.join(REPO_ROOT, "gordo_tpu")
    ):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            with open(path, encoding="utf-8") as handle:
                for lineno, line in enumerate(handle, 1):
                    # real directives only — docstring *mentions* of the
                    # grammar spell the rule as a <placeholder>
                    if re.search(
                        r"gt-lint:\s*(file-)?disable=[a-z][a-z\-,]*", line
                    ) and "--" not in line:
                        bad.append(f"{path}:{lineno}")
    assert not bad, f"suppressions without reasons: {bad}"
