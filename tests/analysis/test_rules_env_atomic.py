"""Rule fixtures: the env-knob registry contract (typed accessors,
declared knobs, constant resolution) and atomic artifact writes."""

import pytest

from gordo_tpu.utils.env import Knob

pytestmark = pytest.mark.analysis

#: a controlled registry so the fixtures don't depend on the live knob set
REGISTRY = {
    "GORDO_TPU_GOOD": Knob("GORDO_TPU_GOOD", "int", 1, "A declared knob."),
    "GORDO_TPU_BLANK": Knob("GORDO_TPU_BLANK", "int", 1, ""),
}


def _rules(result, name):
    return [f for f in result.findings if f.rule == name]


# -- env-registry ------------------------------------------------------------


def test_raw_environ_read_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/bad.py": (
                "import os\n"
                "v = os.environ.get('GORDO_TPU_GOOD', '1')\n"
                "w = os.getenv('GORDO_TPU_GOOD')\n"
                "x = os.environ['GORDO_TPU_GOOD']\n"
            )
        },
        env_registry=REGISTRY,
    )
    assert len(_rules(result, "env-registry")) == 3


def test_accessor_read_of_declared_knob_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/ok.py": (
                "from gordo_tpu.utils.env import env_int\n"
                "v = env_int('GORDO_TPU_GOOD', 1)\n"
            )
        },
        env_registry=REGISTRY,
    )
    assert not _rules(result, "env-registry")


def test_undeclared_knob_is_flagged_even_through_accessor(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/bad.py": (
                "from gordo_tpu.utils.env import env_int\n"
                "v = env_int('GORDO_TPU_NOT_DECLARED', 1)\n"
            )
        },
        env_registry=REGISTRY,
    )
    found = _rules(result, "env-registry")
    assert len(found) == 1
    assert "undeclared" in found[0].message


def test_knob_without_doc_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/bad.py": (
                "from gordo_tpu.utils.env import env_int\n"
                "v = env_int('GORDO_TPU_BLANK', 1)\n"
            )
        },
        env_registry=REGISTRY,
    )
    found = _rules(result, "env-registry")
    assert len(found) == 1
    assert "doc" in found[0].message


def test_knob_name_resolves_through_module_constant(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/bad.py": (
                "import os\n"
                "KNOB_ENV = 'GORDO_TPU_NOT_DECLARED'\n"
                "v = os.getenv(KNOB_ENV)\n"
            )
        },
        env_registry=REGISTRY,
    )
    messages = [f.message for f in _rules(result, "env-registry")]
    assert len(messages) == 2  # raw read + undeclared
    assert any("raw environ" in m for m in messages)
    assert any("undeclared" in m for m in messages)


def test_knob_name_resolves_across_modules(lint_tree):
    # the cross-file case: os.getenv(other.KNOB_ENV)
    result = lint_tree(
        {
            "gordo_tpu/telemetry/consts.py": "TRACE_ENV = 'GORDO_TPU_GOOD'\n",
            "gordo_tpu/models/bad.py": (
                "import os\n"
                "from gordo_tpu.telemetry import consts\n"
                "v = os.getenv(consts.TRACE_ENV)\n"
            ),
        },
        env_registry=REGISTRY,
    )
    assert any(
        "raw environ read of `GORDO_TPU_GOOD`" in f.message
        for f in _rules(result, "env-registry")
    )


def test_environ_write_is_not_a_read(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/ok.py": (
                "import os\n"
                "os.environ['GORDO_TPU_GOOD'] = '2'\n"
                "os.environ.pop('GORDO_TPU_GOOD', None)\n"
            )
        },
        env_registry=REGISTRY,
    )
    # pop() IS a read-ish mutation; the rule only tracks get/getenv/
    # subscript-loads, so neither line fires
    assert not _rules(result, "env-registry")


def test_non_gordo_vars_are_ignored(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/models/ok.py": (
                "import os\n"
                "v = os.getenv('JAX_PLATFORMS')\n"
            )
        },
        env_registry=REGISTRY,
    )
    assert not _rules(result, "env-registry")


# -- atomic-write ------------------------------------------------------------


def test_bare_artifact_write_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/builder/bad.py": (
                "import json\n"
                "def save(doc, path):\n"
                "    with open(path, 'w') as f:\n"
                "        json.dump(doc, f)\n"
            )
        }
    )
    found = _rules(result, "atomic-write")
    assert len(found) == 2  # the open AND the json.dump
    assert "torn file" in found[0].message


def test_stage_then_replace_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/builder/ok.py": (
                "import json, os\n"
                "def save(doc, path):\n"
                "    tmp = path + '.tmp'\n"
                "    with open(tmp, 'w') as f:\n"
                "        json.dump(doc, f)\n"
                "    os.replace(tmp, path)\n"
            )
        }
    )
    assert not _rules(result, "atomic-write")


def test_append_mode_and_reads_are_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/builder/ok.py": (
                "def log(path, line):\n"
                "    with open(path, 'a') as f:\n"
                "        f.write(line)\n"
                "def read(path):\n"
                "    with open(path) as f:\n"
                "        return f.read()\n"
            )
        }
    )
    assert not _rules(result, "atomic-write")


def test_allowlisted_dump_function_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serializer/ok.py": (
                "import pickle\n"
                "def dump(obj, path):\n"
                "    with open(path, 'wb') as f:\n"
                "        pickle.dump(obj, f)\n"
            )
        }
    )
    assert not _rules(result, "atomic-write")


def test_writes_outside_artifact_packages_are_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/client/ok.py": (
                "def save(path, text):\n"
                "    with open(path, 'w') as f:\n"
                "        f.write(text)\n"
            )
        }
    )
    assert not _rules(result, "atomic-write")
