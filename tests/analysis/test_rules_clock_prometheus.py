"""Rule fixtures: clock discipline in deadline math and Prometheus label
cardinality."""

import pytest

pytestmark = pytest.mark.analysis


def _rules(result, name):
    return [f for f in result.findings if f.rule == name]


# -- clock-discipline --------------------------------------------------------


def test_wall_clock_deadline_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": (
                "import time\n"
                "def wait(timeout):\n"
                "    deadline = time.time() + timeout\n"
                "    return deadline\n"
            )
        }
    )
    found = _rules(result, "clock-discipline")
    assert len(found) == 1
    assert "monotonic" in found[0].message


def test_wall_clock_comparison_against_deadline_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": (
                "import time\n"
                "def expired(self):\n"
                "    return time.time() > self.deadline\n"
            )
        }
    )
    assert len(_rules(result, "clock-discipline")) == 1


def test_wall_clock_timestamps_are_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": (
                "import time\n"
                "def stamp(doc):\n"
                "    doc['started_at'] = time.time()\n"
                "    now = time.time()\n"
                "    return now\n"
            )
        }
    )
    assert not _rules(result, "clock-discipline")


def test_monotonic_deadline_is_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/ok.py": (
                "import time\n"
                "def wait(timeout):\n"
                "    deadline = time.monotonic() + timeout\n"
                "    return deadline\n"
            )
        }
    )
    assert not _rules(result, "clock-discipline")


# -- prometheus-cardinality --------------------------------------------------


def test_request_attribute_label_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/server/bad.py": (
                "def observe(counter, request):\n"
                "    counter.labels(path=request.path).inc()\n"
            )
        }
    )
    found = _rules(result, "prometheus-cardinality")
    assert len(found) == 1
    assert "request" in found[0].message


def test_fstring_label_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/server/bad.py": (
                "def observe(counter, name):\n"
                "    counter.labels(model=f'model-{name}').inc()\n"
            )
        }
    )
    found = _rules(result, "prometheus-cardinality")
    assert len(found) == 1
    assert "f-string" in found[0].message


def test_regex_capture_flows_into_label(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/server/bad.py": (
                "def observe(counter, match):\n"
                "    name = match.group('name')\n"
                "    counter.labels(model=name).inc()\n"
            )
        }
    )
    assert len(_rules(result, "prometheus-cardinality")) == 1


def test_constant_and_sanitized_labels_are_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/server/ok.py": (
                "def observe(self, counter, request, response):\n"
                "    labels = self._labels(request, response)\n"
                "    counter.labels(**labels).inc()\n"
                "    counter.labels(path='/static', reason='shed').inc()\n"
            )
        }
    )
    assert not _rules(result, "prometheus-cardinality")


def test_labels_outside_server_packages_are_clean(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/client/ok.py": (
                "def observe(counter, request):\n"
                "    counter.labels(path=request.path).inc()\n"
            )
        }
    )
    assert not _rules(result, "prometheus-cardinality")


# -- member-identity label values (PR 9: the per-member loss-gauge class) ----


def test_loop_variable_over_member_collection_is_flagged(lint_tree):
    # the exact shape that minted one gordo_fleet_member_final_loss
    # timeseries per fleet member before the bounded histogram
    result = lint_tree(
        {
            "gordo_tpu/parallel/bad.py": (
                "def export(gauge, member_losses):\n"
                "    for name, loss in member_losses.items():\n"
                "        gauge.labels(name).set(loss)\n"
            )
        }
    )
    found = _rules(result, "prometheus-cardinality")
    assert len(found) == 1
    assert "loop variable" in found[0].message


def test_machine_name_attribute_label_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/lifecycle/bad.py": (
                "def export(gauge, machine):\n"
                "    gauge.labels(machine.name).set(1)\n"
            )
        }
    )
    found = _rules(result, "prometheus-cardinality")
    assert len(found) == 1
    assert "member-identity" in found[0].message


def test_bounded_stage_loop_is_clean(lint_tree):
    # iterating a bounded per-request stage dict is NOT a member loop —
    # the taint is the member collection's name, not loops per se
    # (this is the live shape in server/prometheus/metrics.py observe())
    result = lint_tree(
        {
            "gordo_tpu/server/ok.py": (
                "def observe(histogram, stages):\n"
                "    for stage, seconds in stages.items():\n"
                "        histogram.labels(stage=stage).observe(seconds)\n"
            )
        }
    )
    assert not _rules(result, "prometheus-cardinality")


def test_member_loop_comprehension_is_flagged(lint_tree):
    result = lint_tree(
        {
            "gordo_tpu/serve/bad.py": (
                "def export(gauge, machines):\n"
                "    return [gauge.labels(m) for m in sorted(machines)]\n"
            )
        }
    )
    assert len(_rules(result, "prometheus-cardinality")) == 1
