"""
Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

The CI/test tier never needs TPU hardware (SURVEY.md §4's implication:
end-to-end runs on CPU JAX); multi-chip sharding is exercised against
``--xla_force_host_platform_device_count=8``. The axon TPU plugin registers
itself via sitecustomize and overrides JAX_PLATFORMS through jax.config, so
we must reset the config value, not just the env var.
"""

import os

# Must be in place before the CPU backend initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Opt-in lock-order tracing (GORDO_TPU_LOCK_TRACE): install BEFORE any
# gordo_tpu module creates its module/instance locks, so the traced run
# covers the serving stack's whole lock population. Edges aggregate
# in-process and dump atexit into a pid-suffixed JSONL sink;
# `gordo-tpu lockgraph 'lock_trace-*.jsonl'` is the deadlock gate CI
# runs over the serve/telemetry/lifecycle suites.
if os.environ.get("GORDO_TPU_LOCK_TRACE"):
    from gordo_tpu.analysis.lockgraph import install_lock_trace

    install_lock_trace()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def sensor_frame() -> pd.DataFrame:
    """Deterministic 200×4 sensor DataFrame with tz-aware 10min index."""
    rng = np.random.RandomState(7)
    index = pd.date_range("2020-01-01", periods=200, freq="10min", tz="UTC")
    data = np.stack(
        [
            50 + 10 * np.sin(np.linspace(0, 6, 200) + phase)
            + rng.standard_normal(200)
            for phase in range(4)
        ],
        axis=1,
    ).astype(np.float32)
    return pd.DataFrame(data, columns=[f"tag-{i}" for i in range(4)], index=index)


@pytest.fixture(scope="session")
def tiny_model_definition() -> dict:
    """A small, fast AE definition used across builder/server tests."""
    return {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_model",
                    "encoding_dim": [8, 4],
                    "encoding_func": ["tanh", "tanh"],
                    "decoding_dim": [4, 8],
                    "decoding_func": ["tanh", "tanh"],
                    "epochs": 2,
                    "batch_size": 32,
                }
            }
        }
    }
