"""
Offline manifest-schema gate (the analog of the reference's `argo lint`
dockertest — reference gordo/workflow/workflow_generator/helpers.py:66-99,
tests/conftest.py:258-330): every fixture render must validate against
the vendored k8s schemas, and a deliberately broken template must FAIL,
proving the gate actually bites.
"""

import os

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu.cli import gordo_tpu_cli
from gordo_tpu.workflow.manifest_validation import validate_manifests
from gordo_tpu.workflow.workflow_generator.workflow_generator import (
    default_workflow_template,
)

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
FIXTURES = sorted(f for f in os.listdir(DATA_DIR) if f.endswith(".yml"))


def render(config_path, *extra):
    # --no-validate: these tests call validate_manifests directly as the
    # assertion; the CLI's own inline gate (tested separately below)
    # would otherwise refuse the deliberately-broken renders up front.
    result = CliRunner().invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_path,
            "--project-name",
            "fixture-proj",
            "--project-revision",
            "1600000000000",
            "--no-validate",
            *extra,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    return list(yaml.safe_load_all(result.output))


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_renders_validate_against_schemas(fixture):
    docs = render(os.path.join(DATA_DIR, fixture))
    errors = validate_manifests(docs)
    assert not errors, "\n".join(errors)


# Each case mutates the pristine template the way a real editing slip
# would, and names the class of error the gate must catch.
BREAKAGES = {
    "misspelled-containers-key": ("containers:", "continers:"),
    "wrong-deployment-apiversion": ("apiVersion: apps/v1\nkind: Deployment", "apiVersion: apps/v1beta1\nkind: Deployment"),
    "dangling-volume-mount": ("- name: fleet-config", "- name: fleet-cfg"),
    "bad-restart-policy": ("restartPolicy: Never", "restartPolicy: never"),
}


@pytest.mark.parametrize("breakage", sorted(BREAKAGES))
def test_broken_template_fails_validation(breakage, tmp_path):
    source = open(default_workflow_template()).read()
    needle, replacement = BREAKAGES[breakage]
    assert needle in source, f"breakage {breakage}: needle not in template"
    broken = tmp_path / "broken.yml.template"
    broken.write_text(source.replace(needle, replacement, 1))

    docs = render(
        os.path.join(DATA_DIR, FIXTURES[0]),
        "--workflow-template",
        str(broken),
    )
    errors = validate_manifests(docs)
    assert errors, f"{breakage}: validation passed on a broken template"


def test_cli_validate_gate_blocks_broken_render(tmp_path):
    """`workflow generate` validates by default and fails the command on
    a broken template; --no-validate is the explicit escape hatch."""
    source = open(default_workflow_template()).read()
    needle, replacement = BREAKAGES["misspelled-containers-key"]
    broken = tmp_path / "broken.yml.template"
    broken.write_text(source.replace(needle, replacement, 1))
    args = [
        "workflow",
        "generate",
        "--machine-config",
        os.path.join(DATA_DIR, FIXTURES[0]),
        "--project-name",
        "fixture-proj",
        "--workflow-template",
        str(broken),
    ]

    result = CliRunner().invoke(gordo_tpu_cli, args)
    assert result.exit_code != 0
    assert "failed schema validation" in result.output

    bypassed = CliRunner().invoke(gordo_tpu_cli, args + ["--no-validate"])
    assert bypassed.exit_code == 0, bypassed.output


def test_unknown_kind_is_an_error():
    docs = [
        {
            "apiVersion": "v1",
            "kind": "Gadget",
            "metadata": {"name": "x"},
        }
    ]
    errors = validate_manifests(docs)
    assert errors and "unknown kind" in errors[0]
