from gordo_tpu.workflow.helpers import patch_dict


def test_patch_adds_and_replaces_never_removes():
    original = {"a": {"x": 1, "y": 2}, "keep": True}
    patch = {"a": {"x": 10, "z": 3}, "new": 4}
    out = patch_dict(original, patch)
    assert out == {"a": {"x": 10, "y": 2, "z": 3}, "keep": True, "new": 4}
    # inputs untouched
    assert original == {"a": {"x": 1, "y": 2}, "keep": True}
    assert patch == {"a": {"x": 10, "z": 3}, "new": 4}


def test_patch_replaces_non_dict_with_dict():
    assert patch_dict({"a": 1}, {"a": {"b": 2}}) == {"a": {"b": 2}}


def test_patch_empty():
    assert patch_dict({}, {"a": 1}) == {"a": 1}
    assert patch_dict({"a": 1}, {}) == {"a": 1}
