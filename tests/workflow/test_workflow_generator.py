"""
Workflow-generator tests: render through the real CLI and assert on the
parsed YAML (reference model:
tests/gordo/workflow/test_workflow_generator/ — with no fake `argo` binary
needed, since the TPU workflow has no argo dependency).
"""

import json

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu.cli import gordo_tpu_cli
from gordo_tpu.workflow.workflow_generator.tpu import (
    gke_accelerator_label,
    slice_geometry,
)

CONFIG = """
machines:
  - name: machine-1
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        epochs: 1
  - name: machine-2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-05T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        epochs: 1
"""


@pytest.fixture
def config_file(tmp_path):
    path = tmp_path / "config.yml"
    path.write_text(CONFIG)
    return str(path)


def generate(config_file, *extra_args):
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_file,
            "--project-name",
            "test-proj",
            "--project-revision",
            "1234567890123",
            *extra_args,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    return list(yaml.safe_load_all(result.output))


def by_kind(docs, kind):
    return [d for d in docs if d and d.get("kind") == kind]


def builder_jobs(docs):
    """The fleet-builder Jobs (the cleanup/replay Jobs are also kind Job)."""
    return [
        j for j in by_kind(docs, "Job") if j["metadata"]["name"].startswith("gordo-fleet-")
    ]


def fleet_configmaps(docs):
    """The machine-shard ConfigMaps (Grafana's datasource CM is one too)."""
    return [
        c
        for c in by_kind(docs, "ConfigMap")
        if "fleet-config" in c["metadata"]["name"]
    ]


def test_generates_expected_documents(config_file):
    docs = generate(config_file)
    kinds = [d["kind"] for d in docs if d]
    assert "PersistentVolumeClaim" in kinds
    assert "ConfigMap" in kinds
    assert "Job" in kinds
    assert "Deployment" in kinds
    assert "Service" in kinds
    assert "HorizontalPodAutoscaler" in kinds


def test_fleet_job_shape(config_file):
    docs = generate(config_file)
    (job,) = builder_jobs(docs)
    geometry = slice_geometry("v5litepod-16")
    spec = job["spec"]
    assert spec["parallelism"] == geometry.hosts
    assert spec["completions"] == geometry.hosts
    assert spec["completionMode"] == "Indexed"
    pod = spec["template"]["spec"]
    assert (
        pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
        == gke_accelerator_label("v5litepod-16")
    )
    container = pod["containers"][0]
    assert container["command"] == ["gordo-tpu"]
    assert "build-fleet" in container["args"]
    assert (
        container["resources"]["limits"]["google.com/tpu"]
        == geometry.chips_per_host
    )


def test_configmap_embeds_machines(config_file):
    docs = generate(config_file)
    (cm,) = fleet_configmaps(docs)
    machines = yaml.safe_load(cm["data"]["machines.yaml"])["machines"]
    assert [m["name"] for m in machines] == ["machine-1", "machine-2"]
    assert machines[0]["project_name"] == "test-proj"
    # Fully-validated machine dicts: model + dataset survived normalization
    assert "gordo_tpu.models.JaxAutoEncoder" in machines[0]["model"]


def test_machines_per_slice_sharding(tmp_path, config_file):
    config = yaml.safe_load(CONFIG)
    config["globals"] = {"runtime": {"fleet": {"machines_per_slice": 1}}}
    path = tmp_path / "sharded.yml"
    path.write_text(yaml.safe_dump(config))
    docs = generate(str(path))
    assert len(builder_jobs(docs)) == 2  # one slice Job per machine shard


def test_split_workflows(config_file):
    docs = generate(config_file, "--split-workflows", "1")
    # two chunks, but project-level resources render exactly once — a
    # duplicated PVC/Deployment would break kustomize/ArgoCD/SSA
    assert len(by_kind(docs, "PersistentVolumeClaim")) == 1
    assert len(by_kind(docs, "Deployment")) == 1
    assert len({d["metadata"]["name"] for d in by_kind(docs, "StatefulSet")}) == len(
        by_kind(docs, "StatefulSet")
    )
    # while per-chunk resources cover every machine
    assert len(builder_jobs(docs)) == 2
    assert {m["metadata"]["name"] for m in by_kind(docs, "Model")} == {
        "test-proj-machine-1",
        "test-proj-machine-2",
    }
    # no duplicate (kind, name) identities anywhere in the stream
    identities = [(d["kind"], d["metadata"]["name"]) for d in docs if d]
    assert len(identities) == len(set(identities))


def test_server_plane(config_file):
    docs = generate(config_file)
    (deployment,) = by_kind(docs, "Deployment")
    containers = deployment["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in containers] == ["server", "metrics"]
    env = {e["name"]: e.get("value") for e in containers[0]["env"]}
    assert env["PROJECT"] == "test-proj"
    assert json.loads(env["EXPECTED_MODELS"]) == ["machine-1", "machine-2"]
    assert "/1234567890123" in env["MODEL_COLLECTION_DIR"]
    (hpa,) = by_kind(docs, "HorizontalPodAutoscaler")
    assert hpa["spec"]["maxReplicas"] == 20  # 2 machines * 10


def test_without_prometheus(config_file):
    docs = generate(config_file, "--without-prometheus")
    (deployment,) = by_kind(docs, "Deployment")
    containers = deployment["spec"]["template"]["spec"]["containers"]
    assert [c["name"] for c in containers] == ["server"]


def test_hpa_none(config_file):
    docs = generate(config_file, "--ml-server-hpa-type", "none")
    assert not by_kind(docs, "HorizontalPodAutoscaler")


def test_keda_requires_flags(config_file):
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_file,
            "--project-name",
            "test-proj",
            "--ml-server-hpa-type",
            "keda",
        ],
    )
    assert result.exit_code != 0
    assert "--with-keda" in result.output


def test_keda_scaled_object(config_file):
    docs = generate(
        config_file,
        "--ml-server-hpa-type",
        "keda",
        "--with-keda",
        "--prometheus-server-address",
        "http://prometheus:9090",
    )
    (scaled,) = by_kind(docs, "ScaledObject")
    trigger = scaled["spec"]["triggers"][0]
    assert trigger["type"] == "prometheus"
    # project_name was templated into the query
    assert 'project=~"test-proj"' in trigger["metadata"]["query"]


def test_resources_labels_and_owner_references(config_file):
    docs = generate(
        config_file,
        "--resources-labels",
        '{"team": "abc"}',
        "--owner-references",
        json.dumps(
            [{"uid": "1", "name": "n", "kind": "Deployment", "apiVersion": "v1"}]
        ),
    )
    (job,) = builder_jobs(docs)
    assert job["metadata"]["labels"]["team"] == "abc"
    assert job["metadata"]["ownerReferences"][0]["uid"] == "1"


def test_output_file(tmp_path, config_file):
    out = tmp_path / "workflow.yml"
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_file,
            "--project-name",
            "test-proj",
            "--output-file",
            str(out),
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0
    docs = list(yaml.safe_load_all(out.read_text()))
    assert by_kind(docs, "Job")


def test_postgres_reporter_injected(config_file):
    docs = generate(config_file)
    (cm,) = fleet_configmaps(docs)
    machines = yaml.safe_load(cm["data"]["machines.yaml"])["machines"]
    reporters = machines[0]["runtime"]["reporters"]
    assert any("PostgresReporter" in str(r) for r in reporters)


# -- deploy plane: ServiceMonitor / Istio / replay / cleanup ----------------


def test_service_monitor_emitted_with_prometheus(config_file):
    docs = generate(config_file)
    (monitor,) = by_kind(docs, "ServiceMonitor")
    assert monitor["spec"]["selector"]["matchLabels"]["app"] == (
        "gordo-tpu-server-test-proj"
    )
    assert monitor["spec"]["endpoints"][0]["port"] == "metrics"
    # the Service actually carries the selected label
    services = by_kind(docs, "Service")
    server_service = next(
        s for s in services if s["metadata"]["name"] == "gordo-tpu-server-test-proj"
    )
    assert server_service["metadata"]["labels"]["app"] == "gordo-tpu-server-test-proj"


def test_service_monitor_absent_without_prometheus(config_file):
    docs = generate(config_file, "--without-prometheus")
    assert not by_kind(docs, "ServiceMonitor")


def test_istio_virtual_service_flag_gated(config_file):
    assert not by_kind(generate(config_file), "VirtualService")
    docs = generate(
        config_file, "--with-istio", "--istio-gateway", "my-ns/my-gateway"
    )
    (vs,) = by_kind(docs, "VirtualService")
    assert vs["spec"]["gateways"] == ["my-ns/my-gateway"]
    match = vs["spec"]["http"][0]["match"][0]["uri"]["prefix"]
    assert match == "/gordo/v0/test-proj/"
    route = vs["spec"]["http"][0]["route"][0]["destination"]
    assert route["host"] == "gordo-tpu-server-test-proj"


def test_prediction_replay_job(config_file):
    assert not [
        j
        for j in by_kind(generate(config_file), "Job")
        if "replay" in j["metadata"]["name"]
    ]
    docs = generate(
        config_file,
        "--with-prediction-replay",
        "--replay-start",
        "2020-01-01T00:00:00+00:00",
        "--replay-end",
        "2020-01-02T00:00:00+00:00",
        "--client-max-instances",
        "7",
    )
    (replay,) = [
        j for j in by_kind(docs, "Job") if "replay" in j["metadata"]["name"]
    ]
    pod = replay["spec"]["template"]["spec"]
    # gated behind the builders via the wait-for-models initContainer
    assert pod["initContainers"][0]["command"] == ["gordo-tpu", "wait-for-models"]
    env = {e["name"]: e.get("value") for e in pod["initContainers"][0]["env"]}
    assert json.loads(env["EXPECTED_MODELS"]) == ["machine-1", "machine-2"]
    args = pod["containers"][0]["args"]
    assert "2020-01-01T00:00:00+00:00" in args
    assert args[args.index("--parallelism") + 1] == "7"
    assert any("predictions/1234567890123" in a for a in args)


def test_revision_cleanup_job_default_on(config_file):
    docs = generate(config_file)
    (cleanup,) = [
        j for j in by_kind(docs, "Job") if "cleanup" in j["metadata"]["name"]
    ]
    pod = cleanup["spec"]["template"]["spec"]
    assert pod["initContainers"][0]["command"] == ["gordo-tpu", "wait-for-models"]
    args = pod["containers"][0]["args"]
    assert args[args.index("--keep") + 1] == "3"
    assert "1234567890123" in args


def test_revision_cleanup_disabled(config_file):
    docs = generate(config_file, "--revisions-to-keep", "0")
    assert not [
        j for j in by_kind(docs, "Job") if "cleanup" in j["metadata"]["name"]
    ]


# -- infra plane: Influx / Postgres / Grafana / Model CRDs ------------------


def test_infra_statefulsets_emitted_with_influx(config_file):
    docs = generate(config_file)
    statefulsets = {d["metadata"]["name"] for d in by_kind(docs, "StatefulSet")}
    assert statefulsets == {
        "gordo-influx-test-proj",
        "gordo-postgres-test-proj",
        "gordo-grafana-test-proj",
    }
    services = {d["metadata"]["name"] for d in by_kind(docs, "Service")}
    assert {"gordo-influx-test-proj", "gordo-postgres-test-proj",
            "gordo-grafana-test-proj"} <= services
    # influx sizing scales with machine count (NormalizedConfig constants)
    (influx,) = [
        d for d in by_kind(docs, "StatefulSet")
        if d["metadata"]["name"] == "gordo-influx-test-proj"
    ]
    mem = influx["spec"]["template"]["spec"]["containers"][0]["resources"][
        "requests"]["memory"]
    assert mem == f"{3000 + 220 * 2}M"  # 2 machines


def test_grafana_datasource_provisioned(config_file):
    docs = generate(config_file)
    cm = next(
        d for d in by_kind(docs, "ConfigMap")
        if "grafana-datasources" in d["metadata"]["name"]
    )
    ds = yaml.safe_load(cm["data"]["datasources.yaml"])["datasources"][0]
    assert ds["url"] == "http://gordo-influx-test-proj:8086"
    assert ds["database"] == "test-proj"


def test_infra_absent_when_influx_disabled(tmp_path):
    config = yaml.safe_load(CONFIG)
    for machine in config["machines"]:
        machine["runtime"] = {"influx": {"enable": False}}
    path = tmp_path / "no-influx.yml"
    path.write_text(yaml.safe_dump(config))
    docs = generate(str(path))
    assert not by_kind(docs, "StatefulSet")
    # and no Postgres reporter got injected either
    (cm,) = fleet_configmaps(docs)
    machines = yaml.safe_load(cm["data"]["machines.yaml"])["machines"]
    assert not any(
        "PostgresReporter" in str(m.get("runtime", {}).get("reporters", []))
        for m in machines
    )


def test_model_crds_per_machine(config_file):
    docs = generate(config_file)
    models = by_kind(docs, "Model")
    assert {m["metadata"]["name"] for m in models} == {
        "test-proj-machine-1",
        "test-proj-machine-2",
    }
    for model in models:
        assert model["apiVersion"] == "equinor.com/v1"
        config = model["spec"]["config"]
        assert config["name"] in ("machine-1", "machine-2")
        assert "dataset" in config and "model" in config


def test_model_crds_disabled(config_file):
    docs = generate(config_file, "--without-model-crds")
    assert not by_kind(docs, "Model")


def test_per_revision_resources_get_fresh_names(config_file):
    """k8s Jobs are immutable: redeploying a new revision must create NEW
    Jobs/ConfigMaps, so their names carry the revision."""
    docs_a = generate(config_file)  # revision 1234567890123 (the default)
    # click takes the LAST occurrence of a non-multiple option, so the
    # helper's default revision is overridden here
    docs_b = generate(config_file, "--project-revision", "9999999999999")

    def job_and_cm_names(docs):
        return {
            d["metadata"]["name"]
            for d in docs
            if d and (
                d["kind"] == "Job"
                or (d["kind"] == "ConfigMap" and "fleet-config" in d["metadata"]["name"])
            )
        }

    assert job_and_cm_names(docs_a).isdisjoint(job_and_cm_names(docs_b))
    # ...and the builder pod hostname (job name + "-<index>") stays a
    # valid DNS label for the jax.distributed coordinator address
    for job in builder_jobs(docs_a):
        assert len(job["metadata"]["name"]) + len("-0") <= 63


def test_jobs_have_ttl(config_file):
    docs = generate(config_file, "--with-prediction-replay")
    jobs = by_kind(docs, "Job")
    assert len(jobs) == 4  # deploy-guard + builder + replay + cleanup
    for job in jobs:
        assert job["spec"]["ttlSecondsAfterFinished"] == 7 * 24 * 3600
    (job,) = builder_jobs(generate(config_file, "--job-ttl-seconds", "60"))
    assert job["spec"]["ttlSecondsAfterFinished"] == 60


def test_project_name_length_guard(config_file):
    runner = CliRunner()
    result = runner.invoke(
        gordo_tpu_cli,
        [
            "workflow", "generate",
            "--machine-config", config_file,
            "--project-name", "x" * 40,
        ],
    )
    assert result.exit_code != 0
    assert "63-char" in result.output
