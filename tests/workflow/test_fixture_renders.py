"""
Fixture-driven render sweep: every config in ``data/`` goes through the
real ``workflow generate`` CLI and the emitted documents are checked for
structural invariants (reference model: the ~20 config fixtures of
tests/gordo/workflow/test_workflow_generator/data asserted via the CLI).
"""

import json
import os

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu.cli import gordo_tpu_cli

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")
FIXTURES = sorted(f for f in os.listdir(DATA_DIR) if f.endswith(".yml"))


def render(config_path, *extra):
    result = CliRunner().invoke(
        gordo_tpu_cli,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_path,
            "--project-name",
            "fixture-proj",
            "--project-revision",
            "1600000000000",
            *extra,
        ],
        catch_exceptions=False,
    )
    assert result.exit_code == 0, result.output
    return list(yaml.safe_load_all(result.output))


def expected_machines(config_path):
    with open(config_path) as f:
        config = yaml.safe_load(f)
    if "spec" in config:  # CRD-wrapped
        config = config["spec"]["config"]
    return [m["name"] for m in config["machines"]]


@pytest.mark.parametrize("fixture", FIXTURES)
def test_fixture_renders_valid_workflow(fixture):
    config_path = os.path.join(DATA_DIR, fixture)
    names = expected_machines(config_path)
    docs = render(config_path)

    kinds = [d["kind"] for d in docs if d]
    for kind in ("PersistentVolumeClaim", "ConfigMap", "Job", "Deployment", "Service"):
        assert kind in kinds, f"{fixture}: no {kind} emitted"

    # every doc labeled with the project
    for doc in docs:
        if not doc:
            continue
        labels = doc["metadata"]["labels"]
        assert (
            labels["applications.gordo.equinor.com/project-name"] == "fixture-proj"
        ), f"{fixture}: {doc['kind']} missing project label"

    # all machines present across the shard ConfigMaps, fully resolved
    embedded = []
    shard_cms = (
        d
        for d in docs
        if d and d["kind"] == "ConfigMap" and "machines.yaml" in d.get("data", {})
    )
    for cm in shard_cms:
        machines = yaml.safe_load(cm["data"]["machines.yaml"])["machines"]
        for machine in machines:
            embedded.append(machine["name"])
            assert machine["project_name"] == "fixture-proj"
            assert machine["model"], f"{fixture}: machine without model"
            assert machine["dataset"], f"{fixture}: machine without dataset"
    assert sorted(embedded) == sorted(names), fixture

    # server knows the full expected-model set
    (deployment,) = (d for d in docs if d and d["kind"] == "Deployment")
    env = {
        e["name"]: e.get("value")
        for e in deployment["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    assert sorted(json.loads(env["EXPECTED_MODELS"])) == sorted(names), fixture


def test_machines_per_slice_fixture_shards():
    config_path = os.path.join(DATA_DIR, "machines-per-slice.yml")
    docs = render(config_path)
    builder = [
        d
        for d in docs
        if d and d["kind"] == "Job" and d["metadata"]["name"].startswith("gordo-fleet-")
    ]
    assert len(builder) == 2  # 3 machines / 2 per slice


def test_custom_runtime_resources_fixture():
    config_path = os.path.join(DATA_DIR, "custom-runtime-resources.yml")
    docs = render(config_path)
    (job,) = (
        d
        for d in docs
        if d and d["kind"] == "Job" and d["metadata"]["name"].startswith("gordo-fleet-")
    )
    resources = job["spec"]["template"]["spec"]["containers"][0]["resources"]
    assert resources["requests"]["memory"] == "1000M"
    assert resources["limits"]["cpu"] == "1000m"
    (deployment,) = (d for d in docs if d and d["kind"] == "Deployment")
    server_resources = deployment["spec"]["template"]["spec"]["containers"][0][
        "resources"
    ]
    assert server_resources["limits"]["memory"] == "2000M"


def test_runtime_env_fixture_reaches_builder():
    config_path = os.path.join(DATA_DIR, "runtime-env-and-reporters.yml")
    docs = render(config_path)
    (job,) = (
        d
        for d in docs
        if d and d["kind"] == "Job" and d["metadata"]["name"].startswith("gordo-fleet-")
    )
    env = {
        e["name"]: e.get("value")
        for e in job["spec"]["template"]["spec"]["containers"][0]["env"]
    }
    assert env["CUSTOM_FLAG"] == "on"


def test_deploy_guard_and_builder_lock_check():
    """The single-writer guard (reference ensure-single-workflow
    semantics): a guard Job acquires the revision lock and every builder
    pod re-checks it via an initContainer."""
    config_path = os.path.join(DATA_DIR, FIXTURES[0])
    docs = render(config_path)
    guard_jobs = [
        d
        for d in docs
        if d and d["kind"] == "Job" and d["metadata"]["name"].startswith("gordo-tpu-guard-")
    ]
    assert len(guard_jobs) == 1
    (container,) = guard_jobs[0]["spec"]["template"]["spec"]["containers"]
    assert container["command"] == ["gordo-tpu", "ensure-single-workflow"]
    assert container["args"][1] == "1600000000000"

    builders = [
        d
        for d in docs
        if d and d["kind"] == "Job" and d["metadata"]["name"].startswith("gordo-fleet-")
    ]
    assert builders
    for job in builders:
        inits = job["spec"]["template"]["spec"]["initContainers"]
        assert any(
            c["command"] == ["gordo-tpu", "ensure-single-workflow"] for c in inits
        ), "builder Job missing the revision-lock initContainer"


def test_grafana_dashboards_provisioned():
    """Grafana ships a provisioned per-project anomaly dashboard, not just
    the datasource (reference: resources/grafana/dashboards)."""
    config_path = os.path.join(DATA_DIR, FIXTURES[0])
    docs = render(config_path)
    (cm,) = [
        d
        for d in docs
        if d
        and d["kind"] == "ConfigMap"
        and d["metadata"]["name"].startswith("gordo-grafana-dashboards-")
    ]
    provider = yaml.safe_load(cm["data"]["provider.yaml"])
    assert provider["providers"][0]["type"] == "file"
    dashboard = json.loads(cm["data"]["anomaly.json"])
    assert dashboard["title"].startswith("fixture-proj")
    queries = [
        target["query"]
        for panel in dashboard["panels"]
        for target in panel.get("targets", [])
    ]
    assert any("total-anomaly-unscaled" in q for q in queries)
    assert any("total-anomaly-confidence" in q for q in queries)

    # and the statefulset mounts both the provider and the dashboards
    grafana = [
        d
        for d in docs
        if d
        and d["kind"] == "StatefulSet"
        and d["metadata"]["name"].startswith("gordo-grafana-")
    ]
    (sts,) = grafana
    mounts = {
        m["mountPath"]
        for m in sts["spec"]["template"]["spec"]["containers"][0]["volumeMounts"]
    }
    assert "/etc/grafana/provisioning/dashboards" in mounts
    assert "/var/lib/grafana/provisioned-dashboards" in mounts
