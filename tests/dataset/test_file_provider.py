"""FileDataProvider: parquet/CSV tag series from disk, resolvable from YAML."""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import FileDataProvider, GordoBaseDataset
from gordo_tpu.dataset.sensor_tag import SensorTag

START, END = "2020-01-01T00:00:00+00:00", "2020-01-03T00:00:00+00:00"
TAGS = ["ft-tag-1", "ft-tag-2", "ft-tag-3"]


def _index(periods=288, tz="UTC"):
    return pd.date_range("2020-01-01", periods=periods, freq="10min", tz=tz)


@pytest.fixture
def wide_parquet(tmp_path):
    idx = _index()
    frame = pd.DataFrame(
        {tag: np.linspace(0, 1, len(idx)) + i for i, tag in enumerate(TAGS)},
        index=idx,
    )
    path = tmp_path / "wide.parquet"
    frame.to_parquet(path)
    return str(path)


@pytest.fixture
def tag_dir_csv(tmp_path):
    directory = tmp_path / "tags"
    directory.mkdir()
    idx = _index(tz=None)  # naive timestamps: provider must localize
    for i, tag in enumerate(TAGS):
        pd.DataFrame({"time": idx, "value": np.full(len(idx), float(i))}).to_csv(
            directory / f"{tag}.csv", index=False
        )
    return str(directory)


def test_wide_parquet_series(wide_parquet):
    provider = FileDataProvider(path=wide_parquet)
    series = list(
        provider.load_series(
            pd.Timestamp(START), pd.Timestamp(END), [SensorTag(t) for t in TAGS]
        )
    )
    assert [s.name for s in series] == TAGS
    assert all(isinstance(s.index, pd.DatetimeIndex) for s in series)
    assert all(s.index.tz is not None for s in series)
    np.testing.assert_allclose(series[1].iloc[0], 1.0)


def test_wide_parquet_respects_date_window(wide_parquet):
    provider = FileDataProvider(path=wide_parquet)
    (series,) = provider.load_series(
        pd.Timestamp("2020-01-01T06:00:00+00:00"),
        pd.Timestamp("2020-01-01T12:00:00+00:00"),
        [SensorTag(TAGS[0])],
    )
    assert series.index.min() >= pd.Timestamp("2020-01-01T06:00:00+00:00")
    assert series.index.max() < pd.Timestamp("2020-01-01T12:00:00+00:00")


def test_per_tag_csv_directory(tag_dir_csv):
    provider = FileDataProvider(
        path=tag_dir_csv, timestamp_column="time", value_column="value"
    )
    series = list(
        provider.load_series(
            pd.Timestamp(START), pd.Timestamp(END), [SensorTag(t) for t in TAGS]
        )
    )
    assert [s.name for s in series] == TAGS
    np.testing.assert_allclose(series[2].to_numpy(), 2.0)


def test_tag_column_map(wide_parquet):
    provider = FileDataProvider(
        path=wide_parquet, tag_column_map={"renamed-tag": "ft-tag-2"}
    )
    assert provider.can_handle_tag(SensorTag("renamed-tag"))
    (series,) = provider.load_series(
        pd.Timestamp(START), pd.Timestamp(END), [SensorTag("renamed-tag")]
    )
    assert series.name == "renamed-tag"
    np.testing.assert_allclose(series.iloc[0], 1.0)


def test_can_handle_tag(wide_parquet, tag_dir_csv):
    wide = FileDataProvider(path=wide_parquet)
    assert wide.can_handle_tag(SensorTag("ft-tag-1"))
    assert not wide.can_handle_tag(SensorTag("nope"))
    directory = FileDataProvider(path=tag_dir_csv)
    assert directory.can_handle_tag(SensorTag("ft-tag-2"))
    assert not directory.can_handle_tag(SensorTag("nope"))


def test_missing_tag_raises(wide_parquet):
    provider = FileDataProvider(path=wide_parquet)
    with pytest.raises(ValueError, match="nope"):
        list(
            provider.load_series(
                pd.Timestamp(START), pd.Timestamp(END), [SensorTag("nope")]
            )
        )


def test_unsupported_extension_raises(tmp_path):
    path = tmp_path / "data.xlsx"
    path.write_text("nope")
    with pytest.raises(ValueError, match="Unsupported file format"):
        FileDataProvider(path=str(path))._read_frame(str(path))


def test_round_trips_through_dataset_config(wide_parquet):
    """The YAML surface: dataset dict -> provider -> (X, y) arrays."""
    dataset = GordoBaseDataset.from_dict(
        {
            "type": "TimeSeriesDataset",
            "data_provider": {"type": "FileDataProvider", "path": wide_parquet},
            "tag_list": TAGS,
            "train_start_date": START,
            "train_end_date": END,
        }
    )
    X, y = dataset.get_data()
    assert list(X.columns) == TAGS
    assert len(X) > 100
    # provider config survives to_dict (the build-metadata contract)
    provider_dict = dataset.to_dict()["data_provider"]
    assert provider_dict["path"] == wide_parquet


def test_local_build_trains_from_files(wide_parquet):
    """End to end: a YAML config pointing at a parquet file trains a model."""
    from gordo_tpu.builder import local_build

    config = f"""
    machines:
      - name: file-machine
        model:
          gordo_tpu.models.JaxAutoEncoder:
            kind: feedforward_hourglass
            epochs: 1
        dataset:
          data_provider:
            type: FileDataProvider
            path: {wide_parquet}
          tag_list: [{", ".join(TAGS)}]
          train_start_date: "{START}"
          train_end_date: "{END}"
    """
    model, machine = next(local_build(config))
    assert model.params_ is not None
    dataset_meta = machine.metadata.build_metadata.dataset.dataset_meta
    assert dataset_meta["row_count"] > 100
