import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import (
    ConfigException,
    GordoBaseDataset,
    InsufficientDataError,
    ListBackedDataProvider,
    RandomDataProvider,
    RandomDataset,
    TimeSeriesDataset,
)
from gordo_tpu.dataset.datasets import normalize_frequency

START, END = "2020-01-01T00:00:00+00:00", "2020-01-10T00:00:00+00:00"


def test_random_dataset_get_data_deterministic():
    ds1 = RandomDataset(START, END, tag_list=["tag-a", "tag-b"])
    ds2 = RandomDataset(START, END, tag_list=["tag-a", "tag-b"])
    X1, y1 = ds1.get_data()
    X2, y2 = ds2.get_data()
    pd.testing.assert_frame_equal(X1, X2)
    assert list(X1.columns) == ["tag-a", "tag-b"]
    assert X1.index.tz is not None
    # y defaults to X
    pd.testing.assert_frame_equal(X1, y1)


def test_target_tag_list_splits_y():
    ds = RandomDataset(START, END, tag_list=["a", "b"], target_tag_list=["c"])
    X, y = ds.get_data()
    assert list(X.columns) == ["a", "b"]
    assert list(y.columns) == ["c"]
    assert len(X) == len(y)


def test_from_dict_round_trip():
    ds = RandomDataset(START, END, tag_list=["a", "b"], resolution="1h")
    config = ds.to_dict()
    assert config["type"].endswith("RandomDataset")
    rebuilt = GordoBaseDataset.from_dict(config)
    X1, _ = ds.get_data()
    X2, _ = rebuilt.get_data()
    pd.testing.assert_frame_equal(X1, X2)


def test_insufficient_data_threshold():
    ds = RandomDataset(START, END, tag_list=["a"], n_samples_threshold=10**9)
    with pytest.raises(InsufficientDataError):
        ds.get_data()


def test_tz_naive_dates_rejected():
    with pytest.raises(ConfigException):
        RandomDataset("2020-01-01", "2020-01-10", tag_list=["a"])


def test_reversed_dates_rejected():
    with pytest.raises(ConfigException):
        RandomDataset(END, START, tag_list=["a"])


def test_row_filter():
    index = pd.date_range(START, periods=100, freq="10min", tz="UTC")
    series = [
        pd.Series(np.arange(100.0), index=index, name="a"),
        pd.Series(np.ones(100), index=index, name="b"),
    ]
    ds = TimeSeriesDataset(
        START,
        END,
        tag_list=["a", "b"],
        data_provider=ListBackedDataProvider(series=series),
        row_filter="`a` < 50",
    )
    X, _ = ds.get_data()
    assert (X["a"] < 50).all()
    assert ds.get_metadata()["filtered_rows"] > 0


def test_trainable_arrays_dtype():
    ds = RandomDataset(START, END, tag_list=["a", "b"])
    X, y, index = ds.trainable_arrays()
    assert X.dtype == np.float32 and y.dtype == np.float32
    assert len(index) == len(X)


def test_metadata_contents():
    ds = RandomDataset(START, END, tag_list=["a"])
    ds.get_data()
    meta = ds.get_metadata()
    assert meta["row_count"] > 0
    assert "x_hist" in meta and "a" in meta["x_hist"]


@pytest.mark.parametrize(
    "legacy,modern", [("10T", "10min"), ("1H", "1h"), ("30s", "30s"), ("5min", "5min")]
)
def test_normalize_frequency(legacy, modern):
    assert normalize_frequency(legacy) == modern


def test_provider_deterministic_per_tag():
    provider = RandomDataProvider()
    t0, t1 = pd.Timestamp(START), pd.Timestamp(END)
    s1 = list(provider.load_series(t0, t1, ["x"]))[0]
    s2 = list(provider.load_series(t0, t1, ["x"]))[0]
    pd.testing.assert_series_equal(s1, s2)


def _ragged_series():
    """Three tags with different spans, irregular stamps, interior gaps
    (empty resample bins), and duplicated values around bin edges."""
    rng = np.random.RandomState(5)
    idx_a = pd.date_range("2020-01-01 00:03", "2020-01-03 23:00", freq="7min", tz="UTC")
    idx_b = pd.date_range("2020-01-01 12:00", "2020-01-04 12:00", freq="13min", tz="UTC")
    idx_c = pd.date_range("2020-01-02 02:30", "2020-01-03 11:00", freq="1min", tz="UTC")
    a = pd.Series(rng.rand(len(idx_a)), index=idx_a, name="rg-a")
    b = pd.Series(rng.rand(len(idx_b)), index=idx_b, name="rg-b")
    # carve an interior gap into c: its 10min resample gets NaN bins
    c = pd.Series(rng.rand(len(idx_c)), index=idx_c, name="rg-c")
    c = c[(c.index < "2020-01-02 20:00") | (c.index > "2020-01-03 04:00")]
    return [a, b, c]


def _build(series, **kwargs):
    return TimeSeriesDataset(
        "2020-01-01T00:00:00+00:00",
        "2020-01-05T00:00:00+00:00",
        tag_list=[s.name for s in series],
        data_provider=ListBackedDataProvider(series=series),
        **kwargs,
    )


def test_fast_resample_path_matches_per_series_path():
    """The one-pass frame resample (_resample_joined) must reproduce the
    per-series resample + inner join exactly: ragged spans, interior empty
    bins and irregular stamps included."""
    series = _ragged_series()
    ds = _build(series)
    fast = ds._load_and_join()

    slow_ds = _build(series)
    slow_ds._resample_joined = lambda _: (_ for _ in ()).throw(ValueError("off"))
    slow = slow_ds._load_and_join()
    pd.testing.assert_frame_equal(fast, slow)


def test_fast_resample_path_skipped_for_non_day_dividing_resolution():
    """A resolution that does not divide a day (e.g. 7min) must take the
    per-series path: resample origins are per-series midnights, so the
    frame fast path would not be bin-exact."""
    series = _ragged_series()
    ds = _build(series, resolution="7min")
    called = {}

    def boom(_):
        called["fast"] = True
        raise AssertionError("fast path must not run for 7min resolution")

    ds._resample_joined = boom
    data = ds._load_and_join()
    assert not called
    # (the result itself is empty here: per-series 7min bins anchor to each
    # series' own first midnight, 1440 % 7 != 0 misaligns the labels and the
    # inner join drops everything — exactly the divergence the gate guards)
    assert list(data.columns) == ["rg-a", "rg-b", "rg-c"]


def test_multiple_aggregation_methods_unchanged():
    series = _ragged_series()
    ds = _build(series, aggregation_methods=["mean", "max"])
    data = ds._load_and_join()
    assert any(col.endswith("_mean") for col in data.columns)
    assert any(col.endswith("_max") for col in data.columns)


def test_sum_aggregation_takes_per_series_path():
    """'sum' turns all-NaN bins into 0, which would defeat the fast path's
    span trim and fabricate zero rows for out-of-span tags — it must use
    the per-series path (review finding: 504 fabricated vs 196 real rows)."""
    series = _ragged_series()
    ds = _build(series, aggregation_methods="sum")

    def boom(_):
        raise AssertionError("fast path must not run for sum aggregation")

    ds._resample_joined = boom
    data = ds._load_and_join()
    # inner-join semantics: rows only inside the intersection of tag spans
    assert data.index.min() >= pd.Timestamp("2020-01-02 02:00", tz="UTC")
    assert data.index.max() <= pd.Timestamp("2020-01-03 11:00", tz="UTC")


@pytest.mark.parametrize("agg", ["mean", "std", "max"])
def test_fast_resample_path_matches_with_nan_boundary_bins(agg):
    """Boundary bins that aggregate to NaN (std of a single observation,
    NaN-valued raw samples at a span edge) must still be trimmed by span
    LABELS, exactly like the per-series inner join (review finding: a
    value-based trim dropped such bins and shifted interpolation)."""
    rng = np.random.RandomState(9)
    # tag with exactly ONE observation in its first bin -> std ddof=1 = NaN
    idx_a = pd.DatetimeIndex(
        [pd.Timestamp("2020-01-01 00:09", tz="UTC")]
    ).append(pd.date_range("2020-01-01 00:10", "2020-01-02 12:00", freq="3min", tz="UTC"))
    a = pd.Series(rng.rand(len(idx_a)), index=idx_a, name="nb-a")
    # tag with NaN raw values covering its entire first in-span bin
    idx_b = pd.date_range("2020-01-01 00:00", "2020-01-02 18:00", freq="4min", tz="UTC")
    vals_b = rng.rand(len(idx_b))
    vals_b[:3] = np.nan
    b = pd.Series(vals_b, index=idx_b, name="nb-b")
    series = [a, b]

    ds = _build(series, aggregation_methods=agg)
    fast = ds._load_and_join()
    slow_ds = _build(series, aggregation_methods=agg)
    slow_ds._resample_joined = lambda _: (_ for _ in ()).throw(ValueError("off"))
    slow = slow_ds._load_and_join()
    pd.testing.assert_frame_equal(fast, slow)


class TestInterpolationParity:
    """_interpolate_linear_limited must be bit-identical to pandas
    DataFrame.interpolate(method='linear', limit=N) — it replaced the
    pandas call on the product build path purely for speed."""

    @pytest.mark.parametrize("dtype", [np.float64, np.float32])
    @pytest.mark.parametrize("limit", [1, 2, 8, 48])
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_matches_pandas_on_random_nan_patterns(self, limit, seed, dtype):
        from gordo_tpu.dataset.datasets import _interpolate_linear_limited

        rng = np.random.RandomState(seed)
        n, k = 300, 5
        values = rng.standard_normal((n, k)).astype(dtype)
        # random NaN runs incl. leading/trailing gaps and a full-NaN column
        mask = rng.rand(n, k) < 0.4
        mask[:7, 0] = True
        mask[-9:, 1] = True
        mask[:, 4] = True
        values[mask] = np.nan
        index = pd.date_range("2020-01-01", periods=n, freq="10min", tz="UTC")
        frame = pd.DataFrame(values, index=index, columns=list("abcde"))

        expected = frame.interpolate(method="linear", limit=limit)
        actual = _interpolate_linear_limited(frame, limit)
        # dtype parity too: pandas preserves float32 frames; the f64 work
        # buffer must not widen the result (check_dtype defaults to True)
        pd.testing.assert_frame_equal(actual, expected)

    def test_no_nan_frame_is_returned_unchanged(self):
        from gordo_tpu.dataset.datasets import _interpolate_linear_limited

        frame = pd.DataFrame(
            np.arange(12.0).reshape(4, 3), columns=list("xyz")
        )
        pd.testing.assert_frame_equal(
            _interpolate_linear_limited(frame, 3),
            frame.interpolate(method="linear", limit=3),
        )
