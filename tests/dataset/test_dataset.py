import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import (
    ConfigException,
    GordoBaseDataset,
    InsufficientDataError,
    ListBackedDataProvider,
    RandomDataProvider,
    RandomDataset,
    TimeSeriesDataset,
)
from gordo_tpu.dataset.datasets import normalize_frequency

START, END = "2020-01-01T00:00:00+00:00", "2020-01-10T00:00:00+00:00"


def test_random_dataset_get_data_deterministic():
    ds1 = RandomDataset(START, END, tag_list=["tag-a", "tag-b"])
    ds2 = RandomDataset(START, END, tag_list=["tag-a", "tag-b"])
    X1, y1 = ds1.get_data()
    X2, y2 = ds2.get_data()
    pd.testing.assert_frame_equal(X1, X2)
    assert list(X1.columns) == ["tag-a", "tag-b"]
    assert X1.index.tz is not None
    # y defaults to X
    pd.testing.assert_frame_equal(X1, y1)


def test_target_tag_list_splits_y():
    ds = RandomDataset(START, END, tag_list=["a", "b"], target_tag_list=["c"])
    X, y = ds.get_data()
    assert list(X.columns) == ["a", "b"]
    assert list(y.columns) == ["c"]
    assert len(X) == len(y)


def test_from_dict_round_trip():
    ds = RandomDataset(START, END, tag_list=["a", "b"], resolution="1h")
    config = ds.to_dict()
    assert config["type"].endswith("RandomDataset")
    rebuilt = GordoBaseDataset.from_dict(config)
    X1, _ = ds.get_data()
    X2, _ = rebuilt.get_data()
    pd.testing.assert_frame_equal(X1, X2)


def test_insufficient_data_threshold():
    ds = RandomDataset(START, END, tag_list=["a"], n_samples_threshold=10**9)
    with pytest.raises(InsufficientDataError):
        ds.get_data()


def test_tz_naive_dates_rejected():
    with pytest.raises(ConfigException):
        RandomDataset("2020-01-01", "2020-01-10", tag_list=["a"])


def test_reversed_dates_rejected():
    with pytest.raises(ConfigException):
        RandomDataset(END, START, tag_list=["a"])


def test_row_filter():
    index = pd.date_range(START, periods=100, freq="10min", tz="UTC")
    series = [
        pd.Series(np.arange(100.0), index=index, name="a"),
        pd.Series(np.ones(100), index=index, name="b"),
    ]
    ds = TimeSeriesDataset(
        START,
        END,
        tag_list=["a", "b"],
        data_provider=ListBackedDataProvider(series=series),
        row_filter="`a` < 50",
    )
    X, _ = ds.get_data()
    assert (X["a"] < 50).all()
    assert ds.get_metadata()["filtered_rows"] > 0


def test_trainable_arrays_dtype():
    ds = RandomDataset(START, END, tag_list=["a", "b"])
    X, y, index = ds.trainable_arrays()
    assert X.dtype == np.float32 and y.dtype == np.float32
    assert len(index) == len(X)


def test_metadata_contents():
    ds = RandomDataset(START, END, tag_list=["a"])
    ds.get_data()
    meta = ds.get_metadata()
    assert meta["row_count"] > 0
    assert "x_hist" in meta and "a" in meta["x_hist"]


@pytest.mark.parametrize(
    "legacy,modern", [("10T", "10min"), ("1H", "1h"), ("30s", "30s"), ("5min", "5min")]
)
def test_normalize_frequency(legacy, modern):
    assert normalize_frequency(legacy) == modern


def test_provider_deterministic_per_tag():
    provider = RandomDataProvider()
    t0, t1 = pd.Timestamp(START), pd.Timestamp(END)
    s1 = list(provider.load_series(t0, t1, ["x"]))[0]
    s2 = list(provider.load_series(t0, t1, ["x"]))[0]
    pd.testing.assert_series_equal(s1, s2)
