"""
InfluxDataProvider — the TSDB reader that closes the data loop the Influx
forwarder opens. An in-memory DataFrameClient fake stands in for influxdb
(the reference dockertests run a real Influx container; same contract,
no container): sensor-layout reads, window filtering, and the full
forwarder→provider replay round trip, including from a YAML config
through local_build.
"""

import re
import sys
import types

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset.data_provider import GordoBaseDataProvider, InfluxDataProvider
from gordo_tpu.dataset.sensor_tag import SensorTag

UTC = "UTC"

_QUERY_RE = re.compile(
    r'SELECT "(?P<field>[^"]+)" FROM "(?P<measurement>[^"]+)" WHERE '
    r"time >= (?P<start>\d+) AND time < (?P<end>\d+)(?P<conds>.*)$"
)
_COND_RE = re.compile(r'"(?P<key>[^"]+)" = \'(?P<value>[^\']*)\'')


class FakeDataFrameClient:
    """In-memory influxdb.DataFrameClient: write_points stores frames per
    (measurement, influx-tags); query parses the provider's InfluxQL."""

    def __init__(self, *args, **kwargs):
        self.writes = []  # (measurement, tags dict, frame)

    def write_points(self, dataframe, measurement, tags=None, **kwargs):
        self.writes.append((measurement, dict(tags or {}), dataframe.copy()))

    def query(self, q):
        match = _QUERY_RE.match(q)
        assert match, f"fake client cannot parse: {q}"
        field = match.group("field")
        conds = dict(
            (m.group("key"), m.group("value"))
            for m in _COND_RE.finditer(match.group("conds"))
        )
        start = pd.Timestamp(int(match.group("start")), tz=UTC)
        end = pd.Timestamp(int(match.group("end")), tz=UTC)
        parts = []
        for measurement, tags, frame in self.writes:
            if measurement != match.group("measurement"):
                continue
            if any(tags.get(k) != v for k, v in conds.items() if k in tags):
                continue
            # conditions on keys the write didn't tag with must also
            # match (sensor layout stores the sensor name as a tag)
            if any(k not in tags for k in conds if k not in frame.columns):
                continue
            if field not in frame.columns:
                continue
            index = frame.index
            if index.tz is None:
                index = index.tz_localize(UTC)
            mask = (index >= start) & (index < end)
            if mask.any():
                sub = frame.loc[mask, [field]]
                sub.index = index[mask]
                parts.append(sub)
        if not parts:
            return {}
        return {match.group("measurement"): pd.concat(parts).sort_index()}


@pytest.fixture
def fake_influx(monkeypatch):
    """A fake `influxdb` module whose DataFrameClient is one shared
    in-memory instance, so forwarder and provider see the same store."""
    client = FakeDataFrameClient()
    module = types.ModuleType("influxdb")
    module.DataFrameClient = lambda *a, **k: client
    monkeypatch.setitem(sys.modules, "influxdb", module)
    return client


def _seed_sensors(client, tags, n=200):
    index = pd.date_range("2020-01-01", periods=n, freq="10min", tz=UTC)
    for i, tag in enumerate(tags):
        frame = pd.DataFrame(
            {"Value": np.sin(np.linspace(0, 8, n)) + 0.1 * i}, index=index
        )
        client.write_points(frame, measurement="sensors", tags={"tag": tag})
    return index


def test_sensor_layout_reads_window(fake_influx):
    index = _seed_sensors(fake_influx, ["t1", "t2"])
    provider = InfluxDataProvider(measurement="sensors", client=fake_influx)
    series = list(
        provider.load_series(
            index[10], index[50], [SensorTag("t1"), SensorTag("t2")]
        )
    )
    assert [s.name for s in series] == ["t1", "t2"]
    for s in series:
        assert s.index.min() >= index[10] and s.index.max() < index[50]
        assert len(s) == 40


def test_missing_tag_raises_value_error(fake_influx):
    index = _seed_sensors(fake_influx, ["t1"])
    provider = InfluxDataProvider(measurement="sensors", client=fake_influx)
    with pytest.raises(ValueError, match="no-such-tag"):
        list(provider.load_series(index[0], index[50], [SensorTag("no-such-tag")]))


def test_roundtrip_through_serializer_dict(fake_influx):
    provider = InfluxDataProvider(
        measurement="sensors", uri="u:p@host:8086/db", value_name="V"
    )
    config = provider.to_dict()
    assert config["measurement"] == "sensors"
    restored = GordoBaseDataProvider.from_dict(config)
    assert isinstance(restored, InfluxDataProvider)
    assert restored.value_name == "V"


def test_forwarder_replay_loop(fake_influx):
    """What ForwardPredictionsIntoInflux writes, the provider reads back
    (field layout) — the reference client's Influx replay, closed."""
    from gordo_tpu.client.forwarders import ForwardPredictionsIntoInflux

    index = pd.date_range("2020-02-01", periods=60, freq="10min", tz=UTC)
    predictions = pd.DataFrame(
        {
            ("model-output", "t1"): np.linspace(0, 1, 60),
            ("total-anomaly-unscaled", "total-anomaly-unscaled"): np.linspace(
                1, 2, 60
            ),
        },
        index=index,
    )
    predictions.columns = pd.MultiIndex.from_tuples(predictions.columns)

    class Machine:
        name = "machine-a"

    forwarder = ForwardPredictionsIntoInflux(
        destination_influx_uri="u:p@host:8086/db"
    )
    forwarder.forward_predictions(predictions, machine=Machine())

    provider = InfluxDataProvider(
        measurement="predictions",
        fields_are_tags=True,
        where_tags={"machine": "machine-a"},
        client=fake_influx,
    )
    (series,) = list(
        provider.load_series(
            index[0],
            index[30],
            [SensorTag("total-anomaly-unscaled|total-anomaly-unscaled")],
        )
    )
    np.testing.assert_allclose(series.to_numpy(), np.linspace(1, 2, 60)[:30])


def test_config_builds_end_to_end(fake_influx):
    """A YAML config whose dataset reads from InfluxDataProvider trains a
    model through local_build — the provider in the real product path."""
    from gordo_tpu.builder import local_build

    _seed_sensors(fake_influx, ["tag-1", "tag-2"], n=400)
    config = """
machines:
  - name: influx-machine
    dataset:
      type: TimeSeriesDataset
      data_provider:
        type: InfluxDataProvider
        measurement: sensors
        uri: user:pass@influx-host:8086/sensordb
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-03T00:00:00+00:00"
      tag_list: [tag-1, tag-2]
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        epochs: 1
"""
    model, machine = next(local_build(config, project_name="p"))
    assert machine.metadata.build_metadata.model.model_offset is not None
    out = model.predict(np.zeros((4, 2), np.float32))
    assert np.asarray(out).shape == (4, 2)
