import pytest

from gordo_tpu.dataset.sensor_tag import (
    SensorTag,
    SensorTagNormalizationError,
    normalize_sensor_tag,
    normalize_sensor_tags,
    to_list_of_strings,
    unique_tag_names,
)


@pytest.mark.parametrize(
    "raw,expected",
    [
        ("T1", SensorTag("T1")),
        ({"name": "T1", "asset": "A"}, SensorTag("T1", "A")),
        (["T1", "A"], SensorTag("T1", "A")),
        (("T1",), SensorTag("T1")),
        (SensorTag("T1", "A"), SensorTag("T1", "A")),
    ],
)
def test_normalize_forms(raw, expected):
    assert normalize_sensor_tag(raw) == expected


def test_default_asset_applied():
    assert normalize_sensor_tags(["T1"], asset="plant")[0].asset == "plant"


def test_to_list_of_strings():
    assert to_list_of_strings([SensorTag("a"), "b"]) == ["a", "b"]


def test_unique_tag_names_union_and_conflict():
    union = unique_tag_names(["a", SensorTag("a"), "b"])
    assert set(union) == {"a", "b"}
    with pytest.raises(SensorTagNormalizationError):
        unique_tag_names([SensorTag("a", "x"), SensorTag("a", "y")])


def test_malformed_tag_raises():
    with pytest.raises(SensorTagNormalizationError):
        normalize_sensor_tag({"asset": "no-name"})
