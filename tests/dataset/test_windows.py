import numpy as np
import pytest

from gordo_tpu.ops.windows import (
    model_offset,
    num_windows,
    sliding_windows,
    window_targets,
    windowed_dataset,
)


def test_window_alignment_lookahead_zero():
    """lookahead=0: target is the last row of each window (AE semantics)."""
    X = np.arange(20).reshape(10, 2)
    windows, targets = windowed_dataset(X, X, lookback=3, lookahead=0)
    assert windows.shape == (8, 3, 2)
    for k in range(len(windows)):
        np.testing.assert_array_equal(windows[k][-1], targets[k])


def test_window_alignment_lookahead_one():
    """lookahead=1: target is one step past the window (forecast semantics)."""
    X = np.arange(20).reshape(10, 2)
    windows, targets = windowed_dataset(X, X, lookback=3, lookahead=1)
    assert windows.shape == (7, 3, 2)
    for k in range(len(windows)):
        np.testing.assert_array_equal(windows[k][-1] + 2, targets[k])


@pytest.mark.parametrize(
    "n,lookback,lookahead,expected_count,expected_offset",
    [
        (100, 20, 0, 81, 19),
        (100, 20, 1, 80, 20),
        (10, 1, 0, 10, 0),
        (10, 1, 1, 9, 1),
        (10, 5, 2, 4, 6),
    ],
)
def test_counts_match_reference_semantics(
    n, lookback, lookahead, expected_count, expected_offset
):
    X = np.zeros((n, 3))
    assert num_windows(n, lookback, lookahead) == expected_count
    assert model_offset(lookback, lookahead) == expected_offset
    assert len(sliding_windows(X, lookback, lookahead)) == expected_count
    assert len(window_targets(X, lookback, lookahead)) == expected_count
    # count + offset == n
    assert expected_count + expected_offset == n


def test_too_short_series_raises():
    with pytest.raises(ValueError):
        sliding_windows(np.zeros((3, 1)), lookback=5, lookahead=0)
