"""
Docs gates: the committed API reference covers every public module
(docs/generate_api.py output is checked in; regenerating must not
discover modules the committed tree misses), and the docs index links
every page set.
"""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
API_DIR = os.path.join(REPO, "docs", "api")


def test_committed_api_reference_covers_every_public_module(tmp_path):
    result = subprocess.run(
        [sys.executable, os.path.join(REPO, "docs", "generate_api.py"), str(tmp_path)],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    generated = {p for p in os.listdir(tmp_path) if p.endswith(".md")}
    committed = {p for p in os.listdir(API_DIR) if p.endswith(".md")}
    missing = generated - committed
    stale = committed - generated
    assert not missing and not stale, (
        f"API reference out of date — run `make docs`. Missing pages: "
        f"{sorted(missing)[:10]}; stale pages: {sorted(stale)[:10]}"
    )


def test_api_pages_are_not_empty():
    for page in os.listdir(API_DIR):
        path = os.path.join(API_DIR, page)
        with open(path) as f:
            content = f.read()
        assert len(content) > 40, f"{page} is effectively empty"


def test_docs_index_links_core_pages():
    with open(os.path.join(REPO, "docs", "index.md")) as f:
        index = f.read()
    for page in (
        "architecture.md",
        "configuration.md",
        "building.md",
        "serving.md",
        "distributed.md",
        "howto-serving.md",
        "api/index.md",
    ):
        assert page in index, f"docs/index.md does not link {page}"
