"""
Packed (block-diagonal) fleet training: per-model math preserved exactly,
G× fewer device matmuls (models/packing.py + FleetTrainer(packing=...)).
"""

import numpy as np
import pytest

from gordo_tpu.models.factories import feedforward_hourglass, feedforward_model
from gordo_tpu.models.packing import (
    PackedFeedForwardSpec,
    auto_packing,
    forward_packed,
    init_packed,
    unpack_params,
)
from gordo_tpu.models.training import FitConfig
from gordo_tpu.parallel import FleetMember, FleetTrainer

#: packed-supermodel compiles are minute-scale on CPU hosts: runs in the
#: dedicated `parallel` CI job, outside the tier-1 `-m 'not slow'` budget.
pytestmark = pytest.mark.slow


def _members(spec, m, n=48, seed0=0):
    rng = np.random.RandomState(7)
    return [
        FleetMember(
            name=f"pk-{i}",
            spec=spec,
            X=(X := rng.rand(n, spec.n_features).astype(np.float32)),
            y=X,
            seed=seed0 + i,
        )
        for i in range(m)
    ]


def test_auto_packing_fills_mxu_lanes():
    spec = feedforward_hourglass(20)  # widest layer = 20
    assert auto_packing(spec, 100) == 6  # 128 // 20
    assert auto_packing(spec, 3) == 3  # capped by member count
    wide = feedforward_hourglass(200)
    assert auto_packing(wide, 100) == 1  # already tile-wide


def test_packed_forward_matches_unpacked():
    """Per-member outputs must match: off-block contributions are exact
    zeros, so the only difference is dot-product summation order (a
    G·F-wide reduction rounds differently than an F-wide one)."""
    import jax

    from gordo_tpu.models.nn import forward_feedforward, init_feedforward

    base = feedforward_hourglass(6, encoding_layers=2)
    g = 4
    pspec = PackedFeedForwardSpec(base=base, g=g)
    keys = jax.random.split(jax.random.PRNGKey(0), g)
    packed = init_packed(keys, pspec)

    rng = np.random.RandomState(0)
    xs = [rng.rand(16, 6).astype(np.float32) for _ in range(g)]
    x_packed = np.concatenate(xs, axis=1)
    out_packed, penalties = forward_packed(pspec, packed, x_packed)
    out_packed = np.asarray(out_packed)

    for gi in range(g):
        params_gi = init_feedforward(keys[gi], base)
        expected, expected_pen = forward_feedforward(base, params_gi, xs[gi])
        np.testing.assert_allclose(
            out_packed[:, gi * 6 : (gi + 1) * 6],
            np.asarray(expected),
            rtol=1e-5,
            atol=5e-7,
        )
        # init parity too: the unpacked block equals a fresh per-member init
        member = unpack_params(packed, pspec, gi)
        for key in params_gi:
            np.testing.assert_array_equal(
                np.asarray(member[key]["W"]), np.asarray(params_gi[key]["W"])
            )


def test_packed_training_matches_unpacked_no_shuffle():
    """With shuffle=False the packed engine must train each member like
    the unpacked fleet (same batches, same gradients, same Adam
    trajectory — differing only in float summation order)."""
    spec = feedforward_hourglass(5, encoding_layers=1)
    members = _members(spec, 6)
    config = FitConfig(epochs=3, batch_size=16, shuffle=False, validation_split=0.25)

    plain = FleetTrainer().train([m for m in members], config)
    packed = FleetTrainer(packing=3).train([m for m in members], config)

    for a, b in zip(plain, packed):
        assert a.name == b.name
        np.testing.assert_allclose(
            a.history.history["loss"], b.history.history["loss"], rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(
            a.history.history["val_loss"],
            b.history.history["val_loss"],
            rtol=1e-4,
            atol=1e-6,
        )
        for key in a.params:
            np.testing.assert_allclose(
                a.params[key]["W"], b.params[key]["W"], rtol=1e-4, atol=1e-6
            )
    assert packed[0].history.params["packed"] == 3


def test_packed_training_ragged_members():
    """Members with different real lengths (zero-weight padding rows) must
    not bleed into each other."""
    spec = feedforward_hourglass(4, encoding_layers=1)
    rng = np.random.RandomState(3)
    members = [
        FleetMember(
            name=f"rg-{i}",
            spec=spec,
            X=(X := rng.rand(n, 4).astype(np.float32)),
            y=X,
            seed=i,
        )
        for i, n in enumerate((40, 24, 33))
    ]
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)
    plain = FleetTrainer().train(list(members), config)
    packed = FleetTrainer(packing=3).train(list(members), config)
    for a, b in zip(plain, packed):
        # ragged packs share Adam's step count, so members whose padding
        # batches are real data for pack-mates drift by bias-correction
        # factors (documented in models/packing.py) — tolerance reflects it
        np.testing.assert_allclose(
            a.history.history["loss"], b.history.history["loss"], rtol=2e-2, atol=1e-5
        )


def test_packed_training_with_l1_activity():
    """The reference's l1 activity penalty must stay per-member."""
    spec = feedforward_model(
        4, 4,
        encoding_dim=(6, 3), decoding_dim=(3, 6),
        encoding_func=("tanh", "tanh"), decoding_func=("tanh", "tanh"),
    )
    assert spec.l1_activity and any(spec.l1_activity)
    members = _members(spec, 4, n=32)
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)
    plain = FleetTrainer().train(list(members), config)
    packed = FleetTrainer(packing=2).train(list(members), config)
    for a, b in zip(plain, packed):
        np.testing.assert_allclose(
            a.history.history["loss"], b.history.history["loss"], rtol=1e-4, atol=1e-6
        )


def test_packing_falls_back_for_early_stopping():
    spec = feedforward_hourglass(4, encoding_layers=1)
    members = _members(spec, 4, n=32)
    config = FitConfig(
        epochs=3, batch_size=16, shuffle=False,
        early_stopping=("loss", 1, 0.0, False), validation_split=0.25,
    )
    trainer = FleetTrainer(packing="auto")
    assert trainer._packing_factor(spec, len(members), config) == 1
    results = trainer.train(list(members), config)  # unpacked path works
    assert len(results) == 4


def test_packed_auto_mode_trains():
    spec = feedforward_hourglass(8)
    members = _members(spec, 10, n=40)
    config = FitConfig(epochs=2, batch_size=16, shuffle=True)
    results = FleetTrainer(packing="auto").train(list(members), config)
    assert len(results) == 10
    for result in results:
        assert np.isfinite(result.history.history["loss"][-1])
        assert result.params["out"]["W"].shape == (
            results[0].params["out"]["W"].shape
        )


def test_packed_respects_retry_on_divergence():
    """The diverged-member retry loop reads packed histories fine."""
    spec = feedforward_hourglass(4, encoding_layers=1)
    members = _members(spec, 4, n=32)
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)
    results = FleetTrainer(packing=2).train(list(members), config, retry_failed=1)
    assert all(np.isfinite(r.history.history["loss"][-1]) for r in results)


def test_fleet_builder_packs_via_env(monkeypatch, tmp_path):
    """GORDO_TPU_PACKING wires packing into the whole build path."""
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import FleetBuilder

    monkeypatch.setenv("GORDO_TPU_PACKING", "2")
    machines = [
        Machine.from_config(
            {
                "name": f"pk-env-{i}",
                "model": {
                    "gordo_tpu.models.JaxAutoEncoder": {
                        "kind": "feedforward_hourglass",
                        "encoding_layers": 1,
                        "epochs": 1,
                    }
                },
                "dataset": {
                    "type": "RandomDataset",
                    "train_start_date": "2020-01-01T00:00:00+00:00",
                    "train_end_date": "2020-01-02T00:00:00+00:00",
                    "tag_list": [f"pk-{i}-a", f"pk-{i}-b"],
                },
            },
            project_name="pk-proj",
        )
        for i in range(4)
    ]
    builder = FleetBuilder(machines)
    assert builder.trainer.packing == 2
    results = builder.build(output_dir=str(tmp_path))
    assert len(results) == 4
    for model, machine in results:
        assert (tmp_path / machine.name / "model.pkl").exists()


def test_fleet_builder_survives_malformed_packing_env(monkeypatch):
    """A typo'd GORDO_TPU_PACKING warns and disables packing instead of
    crashing the whole build at FleetBuilder construction (the
    malformed-env contract every knob now carries)."""
    from gordo_tpu.parallel import FleetBuilder

    monkeypatch.setenv("GORDO_TPU_PACKING", "fast")
    builder = FleetBuilder([])
    assert builder.trainer.packing is None
