"""
Deterministic fault-injection coverage of the fleet build supervisor:
crash-safe atomic dumps, the build journal + --resume, bucket bisection
with sequential degradation, and data-plane retry — every path the
reference got for free from Argo pod isolation, exercised on CPU.
"""

import os

import numpy as np
import pytest

from gordo_tpu import serializer
from gordo_tpu.machine import Machine
from gordo_tpu.parallel import FleetBuilder
from gordo_tpu.parallel.journal import (
    JOURNAL_FILE,
    BuildJournal,
    artifact_complete,
    clean_staging_dirs,
)
from gordo_tpu.utils import faults
from gordo_tpu.utils.faults import FaultRule, inject

pytestmark = pytest.mark.faults

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": 1,
            }
        }
    }
}


def make_machine(name, tags=("t1", "t2"), model=None):
    return Machine.from_config(
        {
            "name": name,
            "model": model or MODEL,
            "dataset": {**DATASET, "tag_list": list(tags)},
        },
        project_name="fault-test",
    )


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


def artifact_dirs(output_dir):
    return sorted(
        e
        for e in os.listdir(output_dir)
        if os.path.isdir(os.path.join(output_dir, e)) and not e.startswith(".")
    )


def staging_dirs(output_dir):
    return [e for e in os.listdir(output_dir) if e.startswith(".") and ".tmp-" in e]


# -- the acceptance path: kill after N machines, then --resume -----------


def test_kill_mid_fleet_then_resume_completes_without_rebuilds(tmp_path):
    """A process death after N machines + ``--resume`` must yield the
    same artifact contract as an uninterrupted build: every machine's
    artifact complete, journaled machines NOT rebuilt, and no
    ``.tmp-*`` staging dirs anywhere the serving store could see."""
    out = tmp_path / "out"
    names = [f"mk-{i}" for i in range(4)]
    machines = [make_machine(n) for n in names]

    # First two artifact dumps land; every later dump dies mid-write
    # (SystemExit, like a kill — and _try_call must NOT swallow it).
    with inject(
        FaultRule("dump_artifact", after=2, times=None, exc=SystemExit)
    ):
        with pytest.raises(SystemExit):
            FleetBuilder(machines).build(output_dir=str(out))

    done = artifact_dirs(out)
    assert len(done) == 2
    assert staging_dirs(out) == []  # atomic dump cleaned its staging dirs
    journal = BuildJournal.load(str(out))
    state = journal.machines()
    assert sorted(n for n, e in state.items() if e["status"] == "built") == done
    # interrupted machines are journaled at their last completed phase
    for name in set(names) - set(done):
        assert state[name]["status"] in ("planned", "data_loaded", "cv_done")
    for name in done:
        assert artifact_complete(str(out / name))

    before = {
        name: (
            (out / name / "model.pkl").read_bytes(),
            (out / name / "model.pkl").stat().st_mtime_ns,
        )
        for name in done
    }

    resumer = FleetBuilder([make_machine(n) for n in names])
    results = resumer.build(output_dir=str(out), resume=True)

    assert sorted(resumer.resumed) == done
    assert sorted(m.name for _, m in results) == sorted(set(names) - set(done))
    assert resumer.build_errors == {}
    assert artifact_dirs(out) == sorted(names)
    assert staging_dirs(out) == []
    # journaled-complete machines were not rebuilt: bytes AND mtime equal
    for name in done:
        assert (
            (out / name / "model.pkl").read_bytes(),
            (out / name / "model.pkl").stat().st_mtime_ns,
        ) == before[name]
    final_state = BuildJournal.load(str(out)).machines()
    assert all(e["status"] == "built" for e in final_state.values())
    # contract parity with an uninterrupted build: same dir set, same
    # files per dir, every artifact loadable and servable
    uninterrupted = tmp_path / "uninterrupted"
    FleetBuilder([make_machine(n) for n in names]).build(
        output_dir=str(uninterrupted)
    )
    assert artifact_dirs(uninterrupted) == artifact_dirs(out)
    for name in names:
        assert sorted(os.listdir(out / name)) == sorted(
            os.listdir(uninterrupted / name)
        )
        model = serializer.load(str(out / name))
        assert model.aggregate_threshold_ is not None


def test_process_kill_site_fires_after_machine_completes(tmp_path):
    """The ``process_kill_after_n_machines`` site fires AFTER the Nth+1
    machine's artifact landed and was journaled — the journal is never
    behind the artifacts."""
    out = tmp_path / "out"
    machines = [make_machine(f"pk-{i}") for i in range(3)]
    with inject(
        FaultRule("process_kill_after_n_machines", after=1, times=None)
    ):
        with pytest.raises(SystemExit):
            FleetBuilder(machines).build(output_dir=str(out))
    done = artifact_dirs(out)
    assert len(done) >= 2  # the first pass-through + the firing machine
    state = BuildJournal.load(str(out)).machines()
    for name in done:
        assert state[name]["status"] == "built"
    resumer = FleetBuilder([make_machine(f"pk-{i}") for i in range(3)])
    resumer.build(output_dir=str(out), resume=True)
    assert sorted(resumer.resumed) == done
    assert artifact_dirs(out) == sorted(m.name for m in machines)


def test_resume_rebuilds_on_config_hash_mismatch(tmp_path):
    out = tmp_path / "out"
    FleetBuilder([make_machine("cfg-m")]).build(output_dir=str(out))
    mtime = (out / "cfg-m" / "model.pkl").stat().st_mtime_ns

    changed_model = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 2,  # config changed → hash changed
                }
            }
        }
    }
    resumer = FleetBuilder([make_machine("cfg-m", model=changed_model)])
    resumer.build(output_dir=str(out), resume=True)
    assert resumer.resumed == []
    assert (out / "cfg-m" / "model.pkl").stat().st_mtime_ns != mtime


def test_resume_rebuilds_corrupt_artifact(tmp_path):
    """A journal that says ``built`` is never trusted over the artifact:
    a truncated model.pkl fails the checksum and rebuilds."""
    out = tmp_path / "out"
    FleetBuilder([make_machine("cor-m")]).build(output_dir=str(out))
    model_path = out / "cor-m" / "model.pkl"
    model_path.write_bytes(model_path.read_bytes()[:10])
    assert not artifact_complete(str(out / "cor-m"))

    resumer = FleetBuilder([make_machine("cor-m")])
    results = resumer.build(output_dir=str(out), resume=True)
    assert resumer.resumed == []
    assert [m.name for _, m in results] == ["cor-m"]
    assert artifact_complete(str(out / "cor-m"))
    assert serializer.load(str(out / "cor-m")).aggregate_threshold_ is not None


def test_resumable_names_mirrors_builder_resume_filter(tmp_path):
    """Every process of a multi-host build must derive the same resume
    skip-set (one SPMD program): the read-only helper non-coordinators
    use has to agree exactly with the coordinator's builder filter."""
    from gordo_tpu.parallel.journal import resumable_names

    out = tmp_path / "out"
    names = [f"mh-{i}" for i in range(3)]
    FleetBuilder([make_machine(n) for n in names[:2]]).build(output_dir=str(out))

    machines = [make_machine(n) for n in names]
    helper_view = resumable_names(str(out), machines)
    resumer = FleetBuilder(machines)
    resumer.build(output_dir=str(out), resume=True)
    assert sorted(helper_view) == sorted(resumer.resumed) == names[:2]


# -- bucket degradation ---------------------------------------------------


def test_resource_exhausted_bisects_and_isolates_poison_member(tmp_path):
    """An injected per-bucket RESOURCE_EXHAUSTED completes the build via
    bisection: the poisonous machine is isolated out of the fleet path
    and rebuilt sequentially; healthy machines never notice."""
    out = tmp_path / "out"
    machines = [
        make_machine("good-a"),
        make_machine("poison-x"),
        make_machine("good-b"),
    ]
    builder = FleetBuilder(machines)
    with inject(FaultRule("device_program", match="poison-*", times=None)):
        results = builder.build(output_dir=str(out))

    assert builder.build_errors == {}
    assert sorted(m.name for _, m in results) == ["good-a", "good-b", "poison-x"]
    assert set(builder.degraded) == {"poison-x"}
    assert builder.robustness["sequential_degraded"] == 1
    assert builder.robustness["bucket_bisects"] >= 1
    # trainer-internal splits are attributed to the machines that rode
    # through them, so artifact metadata agrees with the fleet counters
    by_name = {m.name: m for _, m in results}
    assert (
        by_name["good-a"].metadata.build_metadata.robustness.bucket_bisects >= 1
    )
    assert artifact_dirs(out) == ["good-a", "good-b", "poison-x"]
    for _, machine in results:
        loaded = serializer.load(str(out / machine.name))
        assert loaded.aggregate_threshold_ is not None


def test_over_packed_bucket_resolves_by_splitting():
    """A device error that stops reproducing once the bucket is smaller
    (the over-packed-HBM case) resolves purely by bisection — every
    machine still builds on the fleet path, nothing degrades."""
    from gordo_tpu.parallel.fleet import FleetTrainer

    machines = [make_machine(f"pack-{i}") for i in range(4)]
    builder = FleetBuilder(machines)
    trainer = builder.trainer
    big_bucket_failures = {"n": 0}
    real = FleetTrainer._train_bucket

    def oom_on_big_buckets(self, spec, n_padded, bucket, config, m_padded=None):
        if len(bucket) > 2:
            big_bucket_failures["n"] += 1
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory (injected)")
        return real(self, spec, n_padded, bucket, config, m_padded=m_padded)

    FleetTrainer._train_bucket = oom_on_big_buckets
    try:
        results = builder.build()
    finally:
        FleetTrainer._train_bucket = real

    assert big_bucket_failures["n"] >= 1
    assert builder.build_errors == {} and builder.degraded == {}
    assert sorted(m.name for _, m in results) == [m.name for m in machines]
    assert builder.robustness["bucket_bisects"] >= 1


# -- data-plane retry -----------------------------------------------------


def test_data_fetch_retries_through_transient_failures(tmp_path):
    out = tmp_path / "out"
    machines = [make_machine("flaky-m"), make_machine("steady-m")]
    builder = FleetBuilder(machines, data_retries=2, data_backoff=0)
    with inject(FaultRule("data_fetch", match="flaky-*", times=2)):
        results = builder.build(output_dir=str(out))

    assert builder.build_errors == {}
    assert sorted(m.name for _, m in results) == ["flaky-m", "steady-m"]
    assert builder.robustness["data_fetch_retries"] == 2
    by_name = {m.name: m for _, m in results}
    flaky_meta = by_name["flaky-m"].metadata.build_metadata.robustness
    assert flaky_meta.data_fetch_retries == 2
    steady_meta = by_name["steady-m"].metadata.build_metadata.robustness
    assert steady_meta.data_fetch_retries == 0
    # the counters ride into the dumped artifact metadata
    meta = serializer.load_metadata(str(out / "flaky-m"))
    assert (
        meta["metadata"]["build_metadata"]["robustness"]["data_fetch_retries"]
        == 2
    )


def test_data_fetch_exhaustion_fails_only_that_machine():
    machines = [make_machine("dead-m"), make_machine("live-m")]
    builder = FleetBuilder(machines, data_retries=1, data_backoff=0)
    with inject(FaultRule("data_fetch", match="dead-*", times=None)):
        results = builder.build()
    assert [m.name for _, m in results] == ["live-m"]
    assert set(builder.build_errors) == {"dead-m"}
    assert isinstance(builder.build_errors["dead-m"], faults.FaultInjected)


# -- atomic dumps ---------------------------------------------------------


def test_dump_fault_leaves_no_partial_artifact(tmp_path):
    """A failure mid-dump (after files staged, before the rename) must
    leave NOTHING at the artifact path — no staging dir, no half-written
    model.pkl a resume or the serving store could load."""
    out = tmp_path / "out"
    machines = [make_machine("dump-ok"), make_machine("dump-bad")]
    builder = FleetBuilder(machines)
    with inject(
        FaultRule("dump_artifact", match="dump-bad", times=None, exc=OSError)
    ):
        results = builder.build(output_dir=str(out))
    assert [m.name for _, m in results] == ["dump-ok"]
    assert set(builder.build_errors) == {"dump-bad"}
    assert artifact_dirs(out) == ["dump-ok"]
    assert staging_dirs(out) == []
    state = BuildJournal.load(str(out)).machines()
    assert state["dump-bad"]["status"] == "failed"


def test_serving_store_ignores_journal_and_staging_dirs(tmp_path):
    out = tmp_path / "out"
    FleetBuilder([make_machine("served-m")]).build(output_dir=str(out))
    assert (out / JOURNAL_FILE).is_file()
    (out / ".leftover.tmp-123abc").mkdir()  # as a killed builder leaves it
    (out / ".leftover.tmp-123abc" / "model.pkl").write_bytes(b"partial")

    from gordo_tpu.server.fleet_store import RevisionFleet

    assert RevisionFleet(str(out)).warm() == ["served-m"]


# -- journal + staging plumbing ------------------------------------------


class TestBuildJournal:
    def test_record_and_load_round_trip(self, tmp_path):
        journal = BuildJournal(str(tmp_path))
        journal.record("m-1", "planned", config_hash="abc")
        journal.record("m-1", "built")
        journal.record("m-2", "failed", error="ValueError('boom')")
        loaded = BuildJournal.load(str(tmp_path))
        assert loaded.get("m-1") == {"status": "built", "config_hash": "abc"}
        assert loaded.get("m-2")["error"] == "ValueError('boom')"

    def test_corrupt_journal_starts_fresh(self, tmp_path):
        (tmp_path / JOURNAL_FILE).write_text("{not json")
        assert BuildJournal.load(str(tmp_path)).machines() == {}

    def test_unknown_status_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            BuildJournal(str(tmp_path)).record("m", "half-done")

    def test_event_overlay_is_durable_and_compacts(self, tmp_path):
        """Per-machine record(flush=True) appends O(1) event lines that
        load() applies over the base; flush() compacts them away."""
        journal = BuildJournal(str(tmp_path))
        for i in range(20):
            journal.record(f"m-{i}", "planned", flush=True)
        assert os.path.isfile(journal.events_path)
        assert len(BuildJournal.load(str(tmp_path)).machines()) == 20

        journal.flush()
        assert not os.path.exists(journal.events_path)
        import json

        with open(journal.path) as f:
            assert len(json.load(f)["machines"]) == 20
        assert len(BuildJournal.load(str(tmp_path)).machines()) == 20

    def test_torn_event_tail_is_tolerated(self, tmp_path):
        journal = BuildJournal(str(tmp_path))
        journal.record("m-ok", "built", flush=True)
        with open(journal.events_path, "a") as f:
            f.write('{"name": "m-torn", "status": "bu')  # kill mid-append
        loaded = BuildJournal.load(str(tmp_path))
        assert loaded.get("m-ok")["status"] == "built"
        assert loaded.get("m-torn") is None

    def test_clean_staging_dirs_spares_artifacts(self, tmp_path):
        (tmp_path / "real-model").mkdir()
        (tmp_path / ".dead.tmp-1").mkdir()
        (tmp_path / ".dead2.tmp-xyz").mkdir()
        removed = clean_staging_dirs(str(tmp_path), min_age_seconds=0)
        assert sorted(removed) == [".dead.tmp-1", ".dead2.tmp-xyz"]
        assert (tmp_path / "real-model").is_dir()
        assert clean_staging_dirs(str(tmp_path / "missing")) == []

    def test_clean_staging_dirs_spares_live_builders_fresh_dirs(self, tmp_path):
        """On a shared volume a FRESH staging dir may be another live
        builder's in-flight dump — the default sweep must spare it."""
        import os as _os
        import time as _time

        fresh = tmp_path / ".inflight.tmp-2"
        fresh.mkdir()
        old = tmp_path / ".orphan.tmp-3"
        old.mkdir()
        hours_ago = _time.time() - 7200
        _os.utime(old, (hours_ago, hours_ago))
        removed = clean_staging_dirs(str(tmp_path))
        assert removed == [".orphan.tmp-3"]
        assert fresh.is_dir()


# -- prometheus export ----------------------------------------------------


def test_robustness_counters_exported_to_prometheus(tmp_path):
    from prometheus_client import REGISTRY

    machines = [make_machine("prom-flaky")]
    builder = FleetBuilder(machines, data_retries=1, data_backoff=0)
    with inject(FaultRule("data_fetch", match="prom-*", times=1)):
        builder.build()
    assert builder.robustness["data_fetch_retries"] == 1
    value = REGISTRY.get_sample_value(
        "gordo_fleet_build_data_fetch_retries_total",
        {"project": "fault-test"},
    )
    assert value is not None and value >= 1
