"""
Fleet-build telemetry acceptance: the span stream covers every build
phase with compile time attributed separately from run time, per-member
training summaries land in BuildMetadata and Prometheus, and the
``build_status.json`` surface shows live progress mid-build (exercised
through the fault-injection kill site) and renders through the
``build-status`` CLI.
"""

import json
import os

import pytest

from gordo_tpu import serializer, telemetry
from gordo_tpu.machine import Machine
from gordo_tpu.parallel import FleetBuilder
from gordo_tpu.utils import faults
from gordo_tpu.utils.faults import FaultRule, inject

pytestmark = pytest.mark.observability

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": 1,
            }
        }
    }
}

#: the pipeline phases the ISSUE's acceptance criterion names: plan →
#: fetch → stage → CV → final fit → dump must all appear as spans
REQUIRED_PHASES = {
    "plan",
    "data_fetch",
    "stage",
    "cv_train",
    "final_fit",
    "dump",
}


def make_machine(name, tags=("t1", "t2")):
    return Machine.from_config(
        {
            "name": name,
            "model": MODEL,
            "dataset": {**DATASET, "tag_list": list(tags)},
        },
        project_name="telemetry-test",
    )


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def read_trace(output_dir):
    path = os.path.join(output_dir, telemetry.progress.BUILD_TRACE_FILE)
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_span_stream_covers_every_phase_and_attributes_compile(tmp_path):
    """One CPU fleet build emits spans for every pipeline phase, device
    programs carry bucket attribution (member count, shape, bytes), and
    a second build of the same fleet shows the SAME program signatures
    as steady-state runs — first-call compile attributed separately."""
    telemetry.reset_seen_programs()
    machines = [make_machine("sp-a"), make_machine("sp-b")]
    out = tmp_path / "out"
    builder = FleetBuilder(machines)
    results = builder.build(output_dir=str(out))
    assert len(results) == 2

    spans = read_trace(str(out))
    phases = {
        s["attributes"]["phase"]
        for s in spans
        if s["name"] == "build_phase"
    }
    assert REQUIRED_PHASES <= phases

    # the whole build is one trace, rooted at fleet_build
    roots = [s for s in spans if s["name"] == "fleet_build"]
    assert len(roots) == 1
    assert len({s["context"]["trace_id"] for s in spans}) == 1

    programs = [s for s in spans if s["name"] == "device_program"]
    assert programs, "device programs must be traced"
    for span in programs:
        attrs = span["attributes"]
        assert attrs["program"]
        assert attrs["members"] >= 1
        assert attrs["shape"].startswith("(")
        assert attrs.get("bytes", 0) > 0 or attrs["program"].endswith(
            "predict"
        )
    # compile-vs-run attribution within one build: the FIRST occurrence
    # of each (program, stacked-shape) signature is the compile, every
    # later one a steady-state run. (Under the test mesh the CV and
    # final-fit buckets pad to the same stacked shape, so the final fit
    # is already a cache hit — exactly the signal this layer exists for.)
    seen_signatures = set()
    for span in programs:
        signature = (
            span["attributes"]["program"],
            span["attributes"]["shape"],
        )
        assert span["attributes"]["compile"] == (
            signature not in seen_signatures
        )
        seen_signatures.add(signature)
    assert any(s["attributes"]["compile"] for s in programs)

    # per-member training summaries: events in the trace AND metadata
    trained = [s for s in spans if s["name"] == "member_trained"]
    assert sorted(s["attributes"]["machine"] for s in trained) == [
        "sp-a",
        "sp-b",
    ]
    for _, machine in results:
        training = machine.metadata.build_metadata.model.training
        assert training.final_loss is not None
        assert training.best_loss <= training.final_loss or (
            training.best_loss is not None
        )
        assert training.epochs_run == 1 and training.epochs_configured == 1
        assert training.early_stop_epoch is None
    # ... and in the dumped artifact metadata
    meta = serializer.load_metadata(str(out / "sp-a"))
    summary = meta["metadata"]["build_metadata"]["model"]["training"]
    assert summary["epochs_run"] == 1
    assert summary["final_loss"] is not None

    # second build, same fleet: identical program signatures are now
    # cache hits — compile=False runs, separately attributed
    out2 = tmp_path / "out2"
    FleetBuilder([make_machine("sp-a"), make_machine("sp-b")]).build(
        output_dir=str(out2)
    )
    programs2 = [
        s for s in read_trace(str(out2)) if s["name"] == "device_program"
    ]
    assert programs2 and all(
        not s["attributes"]["compile"] for s in programs2
    )


def test_prometheus_build_metrics_exported(tmp_path):
    from prometheus_client import REGISTRY

    telemetry.reset_seen_programs()
    builder = FleetBuilder([make_machine("pm-a")])
    builder.build(output_dir=str(tmp_path / "out"))

    def sample(name, labels):
        return REGISTRY.get_sample_value(name, labels)

    for phase in REQUIRED_PHASES:
        count = sample(
            "gordo_fleet_build_phase_duration_seconds_count",
            {"project": "telemetry-test", "phase": phase},
        )
        assert count and count >= 1, phase
    assert (
        sample(
            "gordo_fleet_member_final_loss_count",
            {"project": "telemetry-test"},
        )
        >= 1
    )
    assert (
        sample(
            "gordo_fleet_build_machines_completed",
            {"project": "telemetry-test"},
        )
        >= 1
    )
    # at least one program compiled for this project's shapes
    compile_count = sum(
        s.value
        for metric in REGISTRY.collect()
        if metric.name == "gordo_fleet_compile_duration_seconds"
        for s in metric.samples
        if s.name.endswith("_count")
        and s.labels.get("project") == "telemetry-test"
    )
    assert compile_count >= 1


def test_build_status_shows_live_progress_mid_build_and_after_kill(
    tmp_path, monkeypatch
):
    """The acceptance drill: a process death mid-dump (the existing
    ``process_kill_after_n_machines`` site) leaves a ``build_status.json``
    still in state ``running`` whose completed count already includes
    every machine journaled before the kill — with the heartbeat
    throttle at 0 the status is never behind the journal — and the
    ``build-status`` CLI renders it."""
    from click.testing import CliRunner

    monkeypatch.setenv(telemetry.HEARTBEAT_ENV, "0")

    from gordo_tpu.cli.cli import gordo_tpu_cli
    from gordo_tpu.parallel.journal import BuildJournal

    out = tmp_path / "out"
    names = [f"ks-{i}" for i in range(3)]
    with inject(
        FaultRule("process_kill_after_n_machines", after=1, times=None)
    ):
        with pytest.raises(SystemExit):
            FleetBuilder([make_machine(n) for n in names]).build(
                output_dir=str(out)
            )

    doc = telemetry.load_status(str(out))
    assert doc is not None
    assert doc["state"] == "running"  # the kill outran finish()
    journaled_built = [
        name
        for name, entry in BuildJournal.load(str(out)).machines().items()
        if entry["status"] == "built"
    ]
    assert len(journaled_built) >= 2
    assert doc["machines"]["completed"] >= len(journaled_built)
    assert doc["machines"]["total"] == 3
    assert doc["phases"]["dump"]["status"] == "running"

    rendered = telemetry.render_status(doc)
    assert "running" in rendered and "/3 done" in rendered

    runner = CliRunner()
    result = runner.invoke(gordo_tpu_cli, ["build-status", str(out)])
    assert result.exit_code == 0
    assert "running" in result.output
    raw = runner.invoke(
        gordo_tpu_cli, ["build-status", str(out), "--as-json"]
    )
    assert json.loads(raw.output)["state"] == "running"

    # resume completes the fleet and the status reflects it
    resumer = FleetBuilder([make_machine(n) for n in names])
    resumer.build(output_dir=str(out), resume=True)
    doc = telemetry.load_status(str(out))
    assert doc["state"] == "complete"
    assert doc["machines"]["resumed"] == len(resumer.resumed)
    assert (
        doc["machines"]["completed"] + doc["machines"]["resumed"]
        == doc["machines"]["total"]
    )


def test_failed_machines_counted_and_status_completes(tmp_path):
    out = tmp_path / "out"
    machines = [make_machine("ok-m"), make_machine("dead-m")]
    builder = FleetBuilder(machines, data_retries=0, data_backoff=0)
    with inject(FaultRule("data_fetch", match="dead-*", times=None)):
        results = builder.build(output_dir=str(out))
    assert [m.name for _, m in results] == ["ok-m"]
    doc = telemetry.load_status(str(out))
    assert doc["state"] == "complete"
    assert doc["machines"]["failed"] == 1
    assert doc["machines"]["completed"] == 1
    spans = read_trace(str(out))
    failed_events = [s for s in spans if s["name"] == "machine_failed"]
    assert [s["attributes"]["machine"] for s in failed_events] == ["dead-m"]


def test_telemetry_off_leaves_no_trace_files(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.TELEMETRY_ENV, "0")
    out = tmp_path / "out"
    builder = FleetBuilder([make_machine("off-m")])
    results = builder.build(output_dir=str(out))
    assert len(results) == 1
    assert telemetry.load_status(str(out)) is None
    assert not (out / telemetry.progress.BUILD_TRACE_FILE).exists()
    # the artifact contract is untouched
    assert serializer.load_metadata(str(out / "off-m"))


def test_serving_store_ignores_telemetry_files(tmp_path):
    """build_status.json / build_trace.jsonl are builder droppings: the
    model listing and the serving store must never mistake them for
    artifacts, and revision cleanup must treat a directory holding only
    them as empty."""
    out = tmp_path / "out"
    FleetBuilder([make_machine("srv-m")]).build(output_dir=str(out))
    assert (out / "build_status.json").is_file()
    assert (out / "build_trace.jsonl").is_file()
    assert serializer.list_model_dirs(str(out)) == ["srv-m"]
    from gordo_tpu.server.fleet_store import RevisionFleet

    assert RevisionFleet(str(out)).warm() == ["srv-m"]
    assert serializer.is_builder_dropping("build_status.json")
    assert serializer.is_builder_dropping("build_trace.jsonl")
