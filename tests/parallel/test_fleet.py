import numpy as np
import pytest

from gordo_tpu.models.factories import feedforward_symmetric
from gordo_tpu.models.training import FitConfig, fit_single
from gordo_tpu.parallel import FleetMember, FleetResult, FleetTrainer, make_mesh
from gordo_tpu.parallel.fleet import _round_up_pow2

SPEC = feedforward_symmetric(3, dims=(6, 3), funcs=("tanh", "tanh"))
CONFIG = FitConfig(epochs=3, batch_size=16, shuffle=False)


def _member(name, n, seed):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3).astype(np.float32)
    return FleetMember(name=name, spec=SPEC, X=X, y=X.copy(), seed=seed)


def test_round_up_pow2():
    assert _round_up_pow2(100, 16) == 128
    assert _round_up_pow2(5, 16) == 16
    assert _round_up_pow2(128, 16) == 128
    assert _round_up_pow2(129, 16) == 256


def test_fleet_trains_ragged_members():
    """Members of different lengths in one bucket, all trained at once."""
    members = [_member(f"m{i}", n, i) for i, n in enumerate([50, 80, 100, 128])]
    trainer = FleetTrainer()
    results = trainer.train(members, CONFIG)
    assert [r.name for r in results] == ["m0", "m1", "m2", "m3"]
    for r in results:
        assert len(r.history.history["loss"]) == 3
        assert np.isfinite(r.history.history["loss"]).all()


def test_fleet_matches_single_model_training():
    """A fleet member must train to the same params as the single path when
    shapes align (same seed, same data, no padding difference)."""
    rng = np.random.RandomState(0)
    X = rng.rand(64, 3).astype(np.float32)  # 64 = already a pow2 multiple
    member = FleetMember(name="m", spec=SPEC, X=X, y=X.copy(), seed=7)
    fleet_result = FleetTrainer().train([member], CONFIG)[0]

    single_params, single_history = fit_single(SPEC, X, X.copy(), CONFIG, seed=7)
    import jax

    for fleet_leaf, single_leaf in zip(
        jax.tree_util.tree_leaves(fleet_result.params),
        jax.tree_util.tree_leaves(jax.device_get(single_params)),
    ):
        np.testing.assert_allclose(fleet_leaf, single_leaf, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(
        fleet_result.history.history["loss"],
        single_history.history["loss"],
        rtol=2e-4,
    )


def test_fleet_member_isolation():
    """A member's result must not depend on which other members share the
    fleet (same seed => same params)."""
    alone = FleetTrainer().train([_member("m", 64, 5)], CONFIG)[0]
    crowded = FleetTrainer().train(
        [_member("m", 64, 5)] + [_member(f"x{i}", 64, 50 + i) for i in range(3)],
        CONFIG,
    )[0]
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(alone.params),
        jax.tree_util.tree_leaves(crowded.params),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fleet_sharded_over_mesh():
    """8-device CPU mesh: the model axis shards without changing results."""
    import jax

    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.shape == (8, 1)
    members = [_member(f"m{i}", 64, i) for i in range(8)]
    results = FleetTrainer(mesh=mesh).train(members, CONFIG)
    baseline = FleetTrainer(mesh=make_mesh(jax.devices()[:1])).train(members, CONFIG)
    for sharded, single_dev in zip(results, baseline):
        for a, b in zip(
            jax.tree_util.tree_leaves(sharded.params),
            jax.tree_util.tree_leaves(single_dev.params),
        ):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_fleet_data_axis_mesh():
    """models × data 2D mesh compiles and runs (GSPMD inserts collectives)."""
    mesh = make_mesh(data_parallelism=2)
    assert mesh.devices.shape == (4, 2)
    members = [_member(f"m{i}", 64, i) for i in range(4)]
    results = FleetTrainer(mesh=mesh).train(members, CONFIG)
    assert all(np.isfinite(r.history.history["loss"]).all() for r in results)


def test_mismatched_lengths_raise():
    with pytest.raises(ValueError):
        FleetMember(name="bad", spec=SPEC, X=np.zeros((10, 3)), y=np.zeros((9, 3)))


def test_non_pow2_data_axis_padding():
    """lcm padding: data axis 3 with batch 32 must not break batch reshape
    (regression for n_padded bumped to a non-multiple of batch_size)."""
    import jax

    mesh = make_mesh(jax.devices()[:6], data_parallelism=3)
    members = [_member(f"m{i}", 20, i) for i in range(2)]
    results = FleetTrainer(mesh=mesh).train(
        members, FitConfig(epochs=1, batch_size=32, shuffle=False)
    )
    assert all(np.isfinite(r.history.history["loss"]).all() for r in results)


def test_val_weights_without_train_weights():
    rng = np.random.RandomState(0)
    X = rng.rand(64, 3).astype(np.float32)
    val_mask = np.zeros(64, np.float32)
    val_mask[-16:] = 1.0
    member = FleetMember(
        name="m", spec=SPEC, X=X, y=X.copy(), val_weights=val_mask, seed=1
    )
    result = FleetTrainer().train([member], FitConfig(epochs=2, batch_size=16))[0]
    assert "val_loss" in result.history.history
    assert np.isfinite(result.history.history["val_loss"]).all()


def test_no_val_member_has_no_val_history():
    member = _member("m", 64, 2)
    result = FleetTrainer().train(
        [member], FitConfig(epochs=2, batch_size=16, validation_split=0.0)
    )[0]
    assert "val_loss" not in result.history.history


def test_host_prng_keys_bit_equal_jax():
    """host_prng_keys must match jax.random.PRNGKey bit-for-bit (the fleet
    staging path builds keys host-side to avoid per-member device round
    trips; any divergence would silently desync fleet vs fit_single RNG)."""
    import jax

    from gordo_tpu.parallel.fleet import host_prng_keys

    seeds = [0, 1, 7, 42, 2**31 - 1, 2**32 + 5, -1, -1234567]
    keys = host_prng_keys(seeds)
    for seed, key in zip(seeds, keys):
        expected = np.asarray(jax.random.PRNGKey(seed))
        np.testing.assert_array_equal(key, expected, err_msg=f"seed={seed}")


def test_fleet_retries_diverged_members():
    """Members with non-finite final loss are re-vmapped with a fresh seed
    (the chip-level analog of the reference DAG's pod retryStrategy)."""
    from unittest import mock

    from gordo_tpu.models.factories import feedforward_hourglass
    from gordo_tpu.models.training import FitConfig

    spec = feedforward_hourglass(4)
    X = np.random.RandomState(0).rand(32, 4).astype(np.float32)
    members = [
        FleetMember(name=f"m{i}", spec=spec, X=X, y=X, seed=i) for i in range(3)
    ]
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)
    trainer = FleetTrainer()

    real = trainer._train_once(members, config)
    poisoned = [
        FleetResult(
            name=r.name,
            params=r.params,
            history=r.history,
            seed=r.seed,
        )
        for r in real
    ]
    poisoned[1].history.history["loss"] = [float("nan"), float("nan")]

    calls = []
    original = trainer._train_once

    def fake_train_once(ms, cfg):
        calls.append([m.name for m in ms])
        if len(calls) == 1:
            return poisoned
        return original(ms, cfg)

    with mock.patch.object(trainer, "_train_once", side_effect=fake_train_once):
        results = trainer.train(members, config)

    assert calls[0] == ["m0", "m1", "m2"]
    assert calls[1] == ["m1"]  # only the diverged member retried
    assert np.isfinite(results[1].history.history["loss"][-1])
    # retry reseeded: params differ from an identically-seeded fresh train
    assert results[1].name == "m1"
    # the retry is auditable: FleetResult records the reseed and count,
    # and the history params carry them into build metadata
    assert results[1].retries == 1
    assert results[1].seed == members[1].seed + 7919
    assert results[1].history.params["fleet_retry"] == {
        "retries": 1,
        "seed": members[1].seed + 7919,
    }
    # untouched members record their original seed and zero retries
    assert results[0].retries == 0 and results[0].seed == members[0].seed
    assert "fleet_retry" not in results[0].history.params


class TestFetchToHost:
    """Coalesced device→host fetch: values must round-trip exactly for
    any leaf count — including past _FLAT_CONCAT_MAX_LEAVES, where the
    coalescing proceeds in chunks rather than reverting to per-leaf
    transfers (the largest fleets are exactly where per-leaf round trips
    hurt most)."""

    def _tree(self, n_leaves, dtype=np.float32):
        import jax

        rng = np.random.RandomState(0)
        return {
            f"leaf_{i}": jax.device_put(
                rng.standard_normal((3, i % 5 + 1)).astype(dtype)
            )
            for i in range(n_leaves)
        }

    @pytest.mark.parametrize("n_leaves", [2, 7, 300])
    def test_round_trips_exactly(self, n_leaves):
        from gordo_tpu.parallel.fleet import fetch_to_host

        tree = self._tree(n_leaves)
        host = fetch_to_host(tree)
        assert set(host) == set(tree)
        for key, device_leaf in tree.items():
            np.testing.assert_array_equal(host[key], np.asarray(device_leaf))
            assert isinstance(host[key], np.ndarray)

    def test_mixed_dtypes_past_chunk_cap(self):
        import jax

        from gordo_tpu.parallel.fleet import _FLAT_CONCAT_MAX_LEAVES, fetch_to_host

        n = _FLAT_CONCAT_MAX_LEAVES + 20
        tree = {
            **{f"f{i}": jax.device_put(np.full((2,), i, np.float32)) for i in range(n)},
            **{f"i{i}": jax.device_put(np.full((3,), -i, np.int32)) for i in range(40)},
        }
        host = fetch_to_host(tree)
        for i in range(n):
            np.testing.assert_array_equal(host[f"f{i}"], np.full((2,), i, np.float32))
        for i in range(40):
            np.testing.assert_array_equal(host[f"i{i}"], np.full((3,), -i, np.int32))

    def test_leaves_are_independent_copies(self):
        """Slicing out of the coalesced buffer must copy — a view would
        pin the whole transfer buffer for the life of any one leaf."""
        from gordo_tpu.parallel.fleet import fetch_to_host

        host = fetch_to_host(self._tree(6))
        leaf = host["leaf_0"]
        assert leaf.base is None, "leaf is a view into the coalesced buffer"
