"""
Real multi-process ``jax.distributed`` execution: two local CPU processes
join a coordinator, run the CLI ``build-fleet`` path through
``_maybe_init_distributed`` (cli/cli.py) over the global 2-device mesh,
and only the coordinator writes artifacts — which must match a
single-process build of the same config.

This is the in-CI stand-in for a 2-host TPU slice: same
coordinator/process-id wiring the workflow template injects
(JAX_COORDINATOR_ADDRESS / JAX_PROCESS_COUNT / JAX_PROCESS_INDEX), same
SPMD program, ICI/DCN collectives replaced by the CPU backend's transport.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow

CONFIG = """
project_name: dist-test
machines:
  - name: dist-machine-a
    project_name: dist-test
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        encoding_layers: 1
        epochs: 2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [dist-tag-1, dist-tag-2]
  - name: dist-machine-b
    project_name: dist-test
    model:
      gordo_tpu.models.JaxAutoEncoder:
        kind: feedforward_hourglass
        encoding_layers: 1
        epochs: 2
    dataset:
      type: RandomDataset
      train_start_date: "2020-01-01T00:00:00+00:00"
      train_end_date: "2020-01-02T00:00:00+00:00"
      tag_list: [dist-tag-3, dist-tag-4]
"""

# Worker: force the CPU backend *before* any JAX backend initializes (the
# axon TPU plugin would otherwise grab the platform), then run the real
# CLI command in-process so _maybe_init_distributed handles the
# coordinator handshake exactly as a fleet-builder pod would.
WORKER = textwrap.dedent(
    """
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")

    from click.testing import CliRunner

    from gordo_tpu.cli.cli import build_fleet

    config_path, output_dir = sys.argv[1], sys.argv[2]
    result = CliRunner().invoke(
        build_fleet, [config_path, output_dir], catch_exceptions=False
    )
    print(result.output)
    sys.exit(result.exit_code)
    """
)


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _run_fleet_processes(tmp_path, config_path, n_processes=2, timeout=420):
    port = _free_port()
    out_dirs = []
    procs = []
    logs = []
    for rank in range(n_processes):
        out_dir = tmp_path / f"out-rank{rank}"
        out_dirs.append(out_dir)
        env = {
            **os.environ,
            "JAX_PROCESS_COUNT": str(n_processes),
            "JAX_PROCESS_INDEX": str(rank),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            # the conftest's 8-device flag would give 16 global devices;
            # keep it simple: one CPU device per process
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        }
        log = open(tmp_path / f"rank{rank}.log", "w")
        logs.append(log)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", WORKER, str(config_path), str(out_dir)],
                env=env,
                stdout=log,
                stderr=subprocess.STDOUT,
            )
        )
    codes = [proc.wait(timeout=timeout) for proc in procs]
    for log in logs:
        log.close()
    if any(codes):
        for rank in range(n_processes):
            print(f"--- rank {rank} log ---")
            print((tmp_path / f"rank{rank}.log").read_text()[-3000:])
    return codes, out_dirs


def test_two_process_build_fleet_matches_single_process(tmp_path):
    config_path = tmp_path / "machines.yaml"
    config_path.write_text(CONFIG)

    codes, out_dirs = _run_fleet_processes(tmp_path, config_path)
    assert codes == [0, 0]

    # Only the coordinator (process 0) writes artifacts.
    assert (out_dirs[0] / "dist-machine-a" / "model.pkl").exists()
    assert (out_dirs[0] / "dist-machine-b" / "model.pkl").exists()
    assert not out_dirs[1].exists()

    # Single-process ground truth, same config.
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import build_fleet

    single_dir = tmp_path / "single"
    result = CliRunner().invoke(
        build_fleet, [str(config_path), str(single_dir)], catch_exceptions=False
    )
    assert result.exit_code == 0

    # The distributed run must produce the same models: compare predictions
    # on a fixed probe (training is seeded; the model axis shards across
    # processes without changing any per-model math).
    from gordo_tpu import serializer

    probe = np.random.RandomState(0).rand(16, 2).astype(np.float32)
    for name in ("dist-machine-a", "dist-machine-b"):
        dist_model = serializer.load(str(out_dirs[0] / name))
        single_model = serializer.load(str(single_dir / name))
        np.testing.assert_allclose(
            dist_model.predict(probe),
            single_model.predict(probe),
            rtol=1e-5,
            atol=1e-6,
        )
