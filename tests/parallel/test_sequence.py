"""Time-axis (sequence) parallelism: halo-exchange windowed scoring."""

import jax
import numpy as np
import pytest

from gordo_tpu.models.factories import feedforward_hourglass, lstm_model
from gordo_tpu.models.nn import init_fn_for
from gordo_tpu.models.training import predict_fn
from gordo_tpu.ops.windows import sliding_windows
from gordo_tpu.parallel.sequence import (
    ring_windowed_anomaly_scores,
    ring_windowed_predict,
)
from jax.sharding import Mesh

#: ring-sequence LSTM compiles are minute-scale on CPU hosts: runs in
#: the dedicated `parallel` CI job, outside the tier-1 budget.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def seq_mesh():
    dev = jax.devices()
    return Mesh(np.array(dev).reshape(len(dev)), ("data",))


def _lstm_setup(n_features=3, lookback=12, lookahead=0):
    spec = lstm_model(n_features, lookback_window=lookback)
    params = init_fn_for(spec)(jax.random.PRNGKey(0), spec)
    return spec, params


@pytest.mark.parametrize("lookahead", [0, 1])
@pytest.mark.parametrize("n", [200, 203])  # exact and ragged chunking
def test_ring_predict_matches_single_device(seq_mesh, n, lookahead):
    lookback = 12
    spec, params = _lstm_setup(lookback=lookback, lookahead=lookahead)
    X = np.random.RandomState(0).rand(n, 3).astype(np.float32)
    fn = predict_fn(spec)

    expected = np.asarray(fn(params, sliding_windows(X, lookback, lookahead)))
    got = ring_windowed_predict(
        fn, params, X, lookback, lookahead, mesh=seq_mesh
    )
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_ring_predict_short_chunks_still_correct(seq_mesh):
    # chunk < halo forces the chunk-floor path
    lookback = 40
    spec, params = _lstm_setup(lookback=lookback)
    X = np.random.RandomState(1).rand(90, 3).astype(np.float32)
    fn = predict_fn(spec)
    expected = np.asarray(fn(params, sliding_windows(X, lookback, 0)))
    got = ring_windowed_predict(fn, params, X, lookback, 0, mesh=seq_mesh)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_ring_predict_too_short_raises(seq_mesh):
    spec, params = _lstm_setup(lookback=12)
    X = np.random.RandomState(2).rand(5, 3).astype(np.float32)
    with pytest.raises(ValueError, match="too short"):
        ring_windowed_predict(predict_fn(spec), params, X, 12, 0, mesh=seq_mesh)


def test_ring_anomaly_scores_align_targets(seq_mesh):
    lookback = 8
    spec, params = _lstm_setup(lookback=lookback)
    X = np.random.RandomState(3).rand(120, 3).astype(np.float32)
    fn = predict_fn(spec)
    scores = ring_windowed_anomaly_scores(
        fn, params, X, None, lookback, 0, mesh=seq_mesh
    )
    pred = np.asarray(fn(params, sliding_windows(X, lookback, 0)))
    expected = (pred - X[lookback - 1 :]) ** 2
    np.testing.assert_allclose(scores, expected, rtol=1e-5, atol=1e-6)


def test_ring_rejects_multiaxis_mesh():
    dev = jax.devices()
    if len(dev) < 4:
        pytest.skip("needs >=4 devices")
    mesh = Mesh(np.array(dev[:4]).reshape(2, 2), ("models", "data"))
    spec, params = _lstm_setup(lookback=4)
    X = np.random.RandomState(4).rand(64, 3).astype(np.float32)
    with pytest.raises(ValueError, match="axis 'models' has size 2"):
        ring_windowed_predict(predict_fn(spec), params, X, 4, 0, mesh=mesh)


def test_lstm_estimator_routes_long_series_through_ring(monkeypatch):
    """The product call site: JaxLSTMBaseEstimator.predict takes the ring
    (time-sharded) path past the row threshold, with identical output."""
    import gordo_tpu.parallel.sequence as sequence
    from gordo_tpu.models.estimators import JaxLSTMAutoEncoder

    rng = np.random.RandomState(0)
    train = rng.rand(64, 3).astype(np.float32)
    est = JaxLSTMAutoEncoder(
        kind="lstm_model", lookback_window=4, epochs=1, batch_size=16
    )
    est.fit(train, train)

    series = rng.rand(400, 3).astype(np.float32)
    monkeypatch.setenv(sequence.RING_PREDICT_ROWS_ENV, "0")  # ring disabled
    direct = est.predict(series)
    monkeypatch.setenv(sequence.RING_PREDICT_ROWS_ENV, "300")  # 400 > 300: ring on
    calls = []
    original = sequence.ring_windowed_predict

    def spy(*args, **kwargs):
        calls.append(1)
        return original(*args, **kwargs)

    monkeypatch.setattr(sequence, "ring_windowed_predict", spy)
    ringed = est.predict(series)

    assert calls, "long-series predict did not route through the ring path"
    assert ringed.shape == direct.shape
    np.testing.assert_allclose(ringed, direct, rtol=1e-4, atol=1e-5)


def test_lstm_estimator_short_series_stays_on_window_path(monkeypatch):
    import gordo_tpu.parallel.sequence as sequence
    from gordo_tpu.models.estimators import JaxLSTMAutoEncoder

    rng = np.random.RandomState(1)
    train = rng.rand(64, 2).astype(np.float32)
    est = JaxLSTMAutoEncoder(
        kind="lstm_model", lookback_window=4, epochs=1, batch_size=16
    )
    est.fit(train, train)
    monkeypatch.setenv(sequence.RING_PREDICT_ROWS_ENV, "1000")

    def boom(*args, **kwargs):
        raise AssertionError("ring path must not trigger below threshold")

    monkeypatch.setattr(sequence, "ring_windowed_predict", boom)
    out = est.predict(rng.rand(50, 2).astype(np.float32))
    assert out.shape[0] == 50 - 3  # lookback offset
