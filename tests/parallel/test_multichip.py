"""
Multichip dryrun invariant as a pytest guard: a fresh process forced
onto 8 virtual host devices (``--xla_force_host_platform_device_count=8``,
the CI stand-in for an 8-chip slice) must train a sharded fleet to the
SAME params and losses as a 1-device mesh of the same process.

The in-process suite (tests/parallel/test_fleet.py) covers this under
the conftest's virtual mesh; this subprocess variant pins the XLA flag
explicitly so the ``MULTICHIP_r*.json`` dryrun invariant stays guarded
even if the conftest bootstrap changes.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.planner

SCRIPT = textwrap.dedent(
    """
    import json
    import os

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from gordo_tpu.models.factories import feedforward_symmetric
    from gordo_tpu.models.training import FitConfig
    from gordo_tpu.parallel import FleetMember, FleetTrainer, make_mesh

    assert len(jax.devices()) == 8, jax.devices()

    spec = feedforward_symmetric(3, dims=(6, 3), funcs=("tanh", "tanh"))
    config = FitConfig(epochs=2, batch_size=16, shuffle=False)

    def members():
        out = []
        for i in range(4):
            rng = np.random.RandomState(i)
            X = rng.rand(64, 3).astype(np.float32)
            out.append(
                FleetMember(name=f"m{i}", spec=spec, X=X, y=X.copy(), seed=i)
            )
        return out

    sharded_mesh = make_mesh()
    assert sharded_mesh.devices.shape == (8, 1)
    sharded = FleetTrainer(mesh=sharded_mesh).train(members(), config)
    single = FleetTrainer(mesh=make_mesh(jax.devices()[:1])).train(
        members(), config
    )

    max_param_delta = 0.0
    max_loss_delta = 0.0
    for a, b in zip(sharded, single):
        for la, lb in zip(
            jax.tree_util.tree_leaves(a.params),
            jax.tree_util.tree_leaves(b.params),
        ):
            max_param_delta = max(
                max_param_delta, float(np.abs(np.asarray(la) - np.asarray(lb)).max())
            )
        max_loss_delta = max(
            max_loss_delta,
            float(
                np.abs(
                    np.asarray(a.history.history["loss"])
                    - np.asarray(b.history.history["loss"])
                ).max()
            ),
        )
    print(
        "MULTICHIP_RESULT "
        + json.dumps(
            {
                "n_devices": len(jax.devices()),
                "mesh": list(sharded_mesh.devices.shape),
                "models": len(sharded),
                "max_param_delta": max_param_delta,
                "max_loss_delta": max_loss_delta,
            }
        )
    )
    """
)


def test_sharded_build_matches_single_device_in_forced_8_device_process():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, "-c", SCRIPT],
        env=env,
        capture_output=True,
        text=True,
        timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    line = next(
        l for l in proc.stdout.splitlines() if l.startswith("MULTICHIP_RESULT ")
    )
    result = json.loads(line.split(" ", 1)[1])
    assert result["n_devices"] == 8
    assert result["mesh"] == [8, 1]
    assert result["models"] == 4
    # float32 pipeline: sharded placement must not change the math
    assert result["max_param_delta"] < 5e-5
    assert result["max_loss_delta"] < 5e-5
