"""
Planner ↔ trainer/builder integration: the packed strategy must not
change member numerics for unchanged buckets, a build persists its
FleetPlan + journal hash, ``plan_only`` is deterministic, and a plan
replays end to end through ``--plan-from`` + ``--resume`` (only unbuilt
members are replanned after a mid-build kill).
"""

import json
import os

import numpy as np
import pytest

from gordo_tpu import serializer, telemetry
from gordo_tpu.machine import Machine
from gordo_tpu.models.factories import feedforward_symmetric
from gordo_tpu.models.training import FitConfig
from gordo_tpu.parallel import FleetBuilder, FleetMember, FleetTrainer
from gordo_tpu.parallel.journal import BuildJournal
from gordo_tpu.planner import PLAN_FILE, FleetPlan
from gordo_tpu.utils import faults
from gordo_tpu.utils.faults import FaultRule, inject

pytestmark = pytest.mark.planner

SPEC = feedforward_symmetric(3, dims=(6, 3), funcs=("tanh", "tanh"))
CONFIG = FitConfig(epochs=3, batch_size=16, shuffle=False)

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
}

MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "gordo_tpu.models.JaxAutoEncoder": {
                "kind": "feedforward_hourglass",
                "encoding_layers": 1,
                "epochs": 2,
            }
        }
    }
}


def make_machine(name, tags=("t1", "t2")):
    return Machine.from_config(
        {
            "name": name,
            "model": MODEL,
            "dataset": {**DATASET, "tag_list": list(tags)},
        },
        project_name="plan-test",
    )


def _member(name, n, seed):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 3).astype(np.float32)
    return FleetMember(name=name, spec=SPEC, X=X, y=X.copy(), seed=seed)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def test_packed_matches_naive_numerics_for_unchanged_buckets():
    """Members whose pad target is the same under both strategies train
    to IDENTICAL params — repacking neighbors must never leak into a
    member's numerics (the acceptance criterion's no-divergence half)."""
    import jax

    members = [_member(f"big{i}", 128, i) for i in range(4)] + [
        _member(f"small{i}", 40, 100 + i) for i in range(2)
    ]
    naive = {
        r.name: r
        for r in FleetTrainer(plan_strategy="naive").train(members, CONFIG)
    }
    packed = {
        r.name: r
        for r in FleetTrainer(plan_strategy="packed").train(members, CONFIG)
    }
    assert sorted(naive) == sorted(packed)
    # n=128 sits on BOTH ladders (pow2 and the 1.25 geometric rung set),
    # so those members' padded shape is unchanged: exact same training.
    for name in ("big0", "big1", "big2", "big3"):
        for a, b in zip(
            jax.tree_util.tree_leaves(naive[name].params),
            jax.tree_util.tree_leaves(packed[name].params),
        ):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # merged members (40 → a different rung than pow2 64) still converge
    for name in ("small0", "small1"):
        assert np.isfinite(packed[name].history.history["loss"]).all()


def _split_bin_plan(members):
    """A packed plan whose HBM cap forces sibling bins (2 members each)
    sharing an m_padded rung — the shape the m_padded fixes guard."""
    from gordo_tpu import planner

    cost_model = planner.CostModel()
    per_member = cost_model.predict_hbm_bytes(
        SPEC, 1, 128, CONFIG.batch_size
    )
    buckets = planner.plan_train_buckets(
        members,
        CONFIG,
        strategy="packed",
        cost_model=cost_model,
        hbm_cap=int(2.5 * per_member),
    )
    assert all(b.m_padded is not None for b in buckets)  # the premise
    return planner.build_plan_doc(
        [(CONFIG, buckets)],
        "packed",
        (1, 1),
        None,
        planner.config_fingerprint([m.name for m in members]),
    )


def test_planned_m_padded_bucket_still_bisects_on_oom(monkeypatch):
    """The OOM recovery ladder must shrink the member axis: a bucket
    whose PLANNED m_padded rung over-sizes device memory bisects into
    halves that drop the rung (padding a half back up to the planned
    shape would re-OOM identically, forever)."""
    calls = []
    real = FleetTrainer._train_bucket

    def oom_at_planned_rung(self, spec, n_padded, bucket, config, m_padded=None):
        calls.append((len(bucket), m_padded))
        if m_padded is not None:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory (injected)")
        return real(self, spec, n_padded, bucket, config, m_padded=m_padded)

    monkeypatch.setattr(FleetTrainer, "_train_bucket", oom_at_planned_rung)
    members = [_member(f"mp{i}", 128, i) for i in range(4)]
    results = FleetTrainer(
        plan_strategy="packed", fleet_plan=_split_bin_plan(members)
    ).train(members, CONFIG)
    assert all(r.error is None for r in results)
    assert any(m_padded is not None for _, m_padded in calls)  # rung tried
    full = max(n for n, _ in calls)
    assert all(
        m_padded is None for n, m_padded in calls if n < full
    )  # every bisected half dropped the floor


def test_planned_m_padded_bucket_skips_block_diagonal_packing(monkeypatch):
    """Sibling HBM-split buckets rely on the shared member rung for
    their one-compile contract; the block-diagonal packed program has no
    member-axis floor, so those buckets must take the plain path."""
    packed_calls = []
    real_packed = FleetTrainer._train_bucket_packed

    def spy(self, spec, n_padded, bucket, config, g):
        packed_calls.append(len(bucket))
        return real_packed(self, spec, n_padded, bucket, config, g)

    monkeypatch.setattr(FleetTrainer, "_train_bucket_packed", spy)
    members = [_member(f"bp{i}", 128, i) for i in range(4)]
    results = FleetTrainer(
        plan_strategy="packed",
        packing=2,
        fleet_plan=_split_bin_plan(members),
    ).train(members, CONFIG)
    assert all(r.error is None for r in results)
    assert packed_calls == []


def test_builder_packed_persists_plan_journal_and_accuracy(tmp_path):
    """A packed build drops fleet_plan.json beside the artifacts, the
    journal records the plan hash, and the trace carries the plan +
    predicted-vs-actual accuracy events."""
    telemetry.reset_seen_programs()
    out = tmp_path / "out"
    machines = [
        make_machine("pl-a"),
        make_machine("pl-b"),
        make_machine("pl-c", tags=("t1", "t2", "t3")),
    ]
    builder = FleetBuilder(machines, plan_strategy="packed")
    results = builder.build(output_dir=str(out))
    assert len(results) == 3
    for _, machine in results:
        assert serializer.load(str(out / machine.name)) is not None

    plan = FleetPlan.load(str(out / PLAN_FILE))
    assert plan.strategy == "packed"
    assert plan.covers(["pl-a", "pl-b", "pl-c"])
    assert plan.totals["members"] == 3

    journal_plan = BuildJournal.load(str(out)).plan()
    assert journal_plan == {"plan_hash": plan.plan_hash, "strategy": "packed"}

    with open(out / telemetry.progress.BUILD_TRACE_FILE) as f:
        spans = [json.loads(line) for line in f]
    planned = [s for s in spans if s["name"] == "fleet_plan"]
    assert len(planned) == 1
    assert planned[0]["attributes"]["plan_hash"] == plan.plan_hash
    assert planned[0]["attributes"]["replayed"] is False
    accuracy = [s for s in spans if s["name"] == "fleet_plan_accuracy"]
    assert len(accuracy) == 1
    attrs = accuracy[0]["attributes"]
    assert attrs["predicted_compiles"] == plan.totals["compiles"]
    assert attrs["actual_fit_s"] >= 0.0
    # the bucket_plan phase is part of the traced build
    phases = {
        s["attributes"]["phase"] for s in spans if s["name"] == "build_phase"
    }
    assert "bucket_plan" in phases


def test_plan_only_is_deterministic(tmp_path):
    """Same machines + cost table => byte-identical plan JSON (what
    `gordo-tpu plan` prints and the journal hash is derived from)."""
    machines = lambda: [make_machine("det-a"), make_machine("det-b")]  # noqa: E731
    first = FleetBuilder(machines(), plan_strategy="packed").plan_only()
    second = FleetBuilder(machines(), plan_strategy="packed").plan_only()
    assert first.to_json() == second.to_json()
    assert first.plan_hash == second.plan_hash
    assert first.totals["members"] == 2
    # and it round-trips through the file the CLI writes
    path = str(tmp_path / "plan.json")
    first.save(path)
    assert FleetPlan.load(path).to_json() == first.to_json()


def test_plan_from_replays_across_kill_and_resume(tmp_path):
    """The acceptance path: emit a plan, build from it, die after one
    machine, resume FROM THE SAME PLAN — journaled machines are not
    rebuilt, only unbuilt members are (re)planned, and their planned pad
    targets survive the resume."""
    out = tmp_path / "out"
    names = [f"rp-{i}" for i in range(4)]
    plan = FleetBuilder(
        [make_machine(n) for n in names], plan_strategy="packed"
    ).plan_only()
    assert plan.covers(names)

    # the first two artifact dumps land; every later one dies mid-write
    # (SystemExit, like the process_kill site's exit during dump)
    with inject(FaultRule("dump_artifact", after=2, times=None, exc=SystemExit)):
        with pytest.raises(SystemExit):
            FleetBuilder(
                [make_machine(n) for n in names],
                plan_strategy="packed",
                fleet_plan=plan,
            ).build(output_dir=str(out))

    journal = BuildJournal.load(str(out))
    done = sorted(
        n for n, e in journal.machines().items() if e["status"] == "built"
    )
    assert done and len(done) < len(names)
    assert journal.plan()["plan_hash"] == plan.plan_hash

    before = {n: (out / n / "model.pkl").stat().st_mtime_ns for n in done}
    resumer = FleetBuilder(
        [make_machine(n) for n in names],
        plan_strategy="packed",
        fleet_plan=plan,
    )
    results = resumer.build(output_dir=str(out), resume=True)
    assert sorted(resumer.resumed) == done
    assert sorted(m.name for _, m in results) == sorted(set(names) - set(done))
    # resumed artifacts untouched: their members were never replanned
    for name in done:
        assert (out / name / "model.pkl").stat().st_mtime_ns == before[name]
    # the journal still records the replayed plan's identity
    assert BuildJournal.load(str(out)).plan()["plan_hash"] == plan.plan_hash
    for name in names:
        assert serializer.load(str(out / name)) is not None
    # the resumed build replayed the same plan: every unbuilt member's
    # bucket (and pad target) came from the original document
    trainer_plan = resumer.trainer.fleet_plan
    assert trainer_plan is not None
    assert trainer_plan.plan_hash == plan.plan_hash


def test_replayed_plan_strategy_covers_live_packed_members(
    tmp_path, monkeypatch
):
    """`build-fleet --plan-from <packed plan>` with no --plan-strategy:
    the plan's strategy must ride onto the trainer, so CV fold members
    and plan-uncovered members pack with the strategy the operator
    opted into — not silently naive while the journal says packed."""
    import gordo_tpu.parallel.fleet as fleet_mod

    strategies_seen = []
    real = fleet_mod.plan_train_buckets

    def spy(members, config, strategy=None, **kwargs):
        strategies_seen.append(strategy)
        return real(members, config, strategy=strategy, **kwargs)

    monkeypatch.setattr(fleet_mod, "plan_train_buckets", spy)
    machines = [make_machine("st-a"), make_machine("st-b")]
    plan = FleetBuilder(machines, plan_strategy="packed").plan_only()
    builder = FleetBuilder(
        [make_machine("st-a"), make_machine("st-b")], fleet_plan=plan
    )
    builder.build(output_dir=str(tmp_path / "out"))
    assert strategies_seen and all(s == "packed" for s in strategies_seen)
    # the switch does not outlive the build on the (builder-owned) trainer
    assert builder.trainer.plan_strategy is None


def test_fresh_build_replans_when_no_plan_given(tmp_path):
    """Without --plan-from, each build computes (and persists) its own
    plan; a trainer reused across builds must not leak the previous
    fleet's plan into the next build."""
    out_a = tmp_path / "a"
    out_b = tmp_path / "b"
    trainer = FleetTrainer(plan_strategy="naive")
    FleetBuilder([make_machine("fr-a")], trainer=trainer).build(
        output_dir=str(out_a)
    )
    plan_a = FleetPlan.load(str(out_a / PLAN_FILE))
    assert plan_a.covers(["fr-a"])
    FleetBuilder([make_machine("fr-b")], trainer=trainer).build(
        output_dir=str(out_b)
    )
    plan_b = FleetPlan.load(str(out_b / PLAN_FILE))
    assert plan_b.covers(["fr-b"])
    assert not plan_b.covers(["fr-a"])
