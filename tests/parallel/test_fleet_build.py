import numpy as np
import pandas as pd
import pytest

from gordo_tpu import serializer
from gordo_tpu.machine import Machine
from gordo_tpu.parallel import FleetBuilder, fleet_build

DATASET = {
    "type": "RandomDataset",
    "train_start_date": "2020-01-01T00:00:00+00:00",
    "train_end_date": "2020-01-05T00:00:00+00:00",
}

DETECTOR_MODEL = {
    "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
        "base_estimator": {
            "sklearn.pipeline.Pipeline": {
                "steps": [
                    "sklearn.preprocessing.MinMaxScaler",
                    {
                        "gordo_tpu.models.JaxAutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "encoding_layers": 1,
                            "epochs": 2,
                        }
                    },
                ]
            }
        }
    }
}


def make_machine(name, tags, model=None):
    return Machine.from_config(
        {
            "name": name,
            "model": model or DETECTOR_MODEL,
            "dataset": {**DATASET, "tag_list": tags},
        },
        project_name="fleet-test",
    )


def test_fleet_build_detectors(tmp_path):
    # two machines share an architecture bucket (same tag count), one differs
    machines = [
        make_machine("m-a", ["t1", "t2", "t3"]),
        make_machine("m-b", ["t4", "t5", "t6"]),
        make_machine("m-c", ["t7", "t8"]),
    ]
    results = fleet_build(machines, output_dir=str(tmp_path))
    assert len(results) == 3
    for model, machine in results:
        assert hasattr(model, "anomaly")
        assert model.aggregate_threshold_ is not None
        assert len(model.feature_thresholds_) == len(
            machine.dataset.tag_list
        )
        bm = machine.metadata.build_metadata
        assert bm.model.model_offset == 0
        scores = bm.model.cross_validation.scores
        n_tags = len(machine.dataset.tag_list)
        assert len(scores) == 4 * (n_tags + 1)
        assert {"fold-mean", "fold-std", "fold-1", "fold-2", "fold-3"} <= set(
            scores["explained-variance-score"]
        )
        # artifacts on disk, loadable, servable
        loaded = serializer.load(str(tmp_path / machine.name))
        X, y = machine.dataset.get_data()
        frame = loaded.anomaly(X, y)
        assert len(frame) == len(X)


def test_fleet_build_matches_model_builder_thresholds():
    """Fleet CV must produce the same thresholds as the sequential
    ModelBuilder path for the same machine."""
    from gordo_tpu.builder import ModelBuilder

    machine = make_machine("parity", ["t1", "t2"])
    fleet_model, _ = fleet_build([make_machine("parity", ["t1", "t2"])])[0]
    seq_model, _ = ModelBuilder(machine).build()
    np.testing.assert_allclose(
        fleet_model.feature_thresholds_.values.astype(float),
        seq_model.feature_thresholds_.values.astype(float),
        rtol=0.2,
    )
    np.testing.assert_allclose(
        fleet_model.aggregate_threshold_, seq_model.aggregate_threshold_, rtol=0.2
    )


def test_fleet_build_lstm():
    model_def = {
        "gordo_tpu.models.JaxLSTMAutoEncoder": {
            "kind": "lstm_symmetric",
            "dims": [4],
            "funcs": ["tanh"],
            "lookback_window": 4,
            "epochs": 1,
        }
    }
    results = fleet_build([make_machine("lstm-m", ["t1", "t2"], model=model_def)])
    model, machine = results[0]
    assert machine.metadata.build_metadata.model.model_offset == 3
    X, _ = machine.dataset.get_data()
    assert len(model.predict(X)) == len(X) - 3


def test_fleet_build_fallback_for_non_jax_models():
    model_def = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": "sklearn.linear_model.LinearRegression"
        }
    }
    results = fleet_build([make_machine("sk-m", ["t1", "t2"], model=model_def)])
    model, machine = results[0]
    assert model.aggregate_threshold_ is not None
    assert machine.metadata.build_metadata.model.model_training_duration_sec > 0


def test_cross_val_only_mode():
    machine = Machine.from_config(
        {
            "name": "cv-only",
            "model": DETECTOR_MODEL,
            "dataset": {**DATASET, "tag_list": ["t1", "t2"]},
            "evaluation": {"cv_mode": "cross_val_only"},
        },
        project_name="fleet-test",
    )
    model, built = fleet_build([machine])[0]
    assert built.metadata.build_metadata.model.cross_validation.scores
    assert built.metadata.build_metadata.model.model_training_duration_sec == 0.0


def test_fleet_kfcv_matches_sequential():
    """KFCV thresholds: fleet chronological stitching must track the
    sequential path (same folds, same smoothing order)."""
    from gordo_tpu.builder import ModelBuilder

    model_def = {
        "gordo_tpu.models.anomaly.diff.DiffBasedKFCVAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 2,
                }
            },
            "window": 12,
        }
    }
    fleet_model, _ = fleet_build(
        [make_machine("kfcv-m", ["t1", "t2"], model=model_def)]
    )[0]
    seq_model, _ = ModelBuilder(
        make_machine("kfcv-m", ["t1", "t2"], model=model_def)
    ).build()
    np.testing.assert_allclose(
        fleet_model.aggregate_threshold_, seq_model.aggregate_threshold_, rtol=0.35
    )
    np.testing.assert_allclose(
        np.asarray(fleet_model.feature_thresholds_, dtype=float),
        np.asarray(seq_model.feature_thresholds_, dtype=float),
        rtol=0.35,
    )


def test_smoothed_threshold_metadata_present():
    model_def = {
        "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.JaxAutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "encoding_layers": 1,
                    "epochs": 1,
                }
            },
            "window": 12,
        }
    }
    model, _ = fleet_build([make_machine("sm-m", ["t1", "t2"], model=model_def)])[0]
    meta = model.get_metadata()
    assert "smooth-feature-thresholds-per-fold" in meta
    assert "smooth-aggregate-thresholds-per-fold" in meta


def test_fleet_build_fail_fast_false_continues(tmp_path):
    """One machine's data failure must not stop the fleet (the reference
    DAG runs failFast:false — argo-workflow.yml.template)."""
    good = make_machine("good-machine", ["tag-1", "tag-2"])
    # n_samples_threshold above the row count forces InsufficientDataError
    bad = Machine.from_config(
        {
            "name": "bad-machine",
            "model": DETECTOR_MODEL,
            "dataset": {
                **DATASET,
                "tag_list": ["tag-1", "tag-2"],
                "n_samples_threshold": 10_000_000,
            },
        },
        project_name="fleet-test",
    )
    builder = FleetBuilder([good, bad])
    results = builder.build(output_dir=str(tmp_path))
    assert [m.name for _, m in results] == ["good-machine"]
    assert set(builder.build_errors) == {"bad-machine"}
    from gordo_tpu.dataset.exceptions import InsufficientDataError

    assert isinstance(builder.build_errors["bad-machine"], InsufficientDataError)
    # good machine's artifacts still landed
    assert (tmp_path / "good-machine" / "model.pkl").exists()
    assert not (tmp_path / "bad-machine").exists()


def test_try_call_propagates_shutdown_signals():
    """_try_call's broad capture exists for failFast:false semantics
    only — interpreter shutdown (Ctrl-C, SystemExit/injected kill) must
    propagate, never become a per-machine build error."""
    from gordo_tpu.parallel.fleet_build import _try_call

    def raise_(exc):
        raise exc

    with pytest.raises(KeyboardInterrupt):
        _try_call(raise_, KeyboardInterrupt())
    with pytest.raises(SystemExit):
        _try_call(raise_, SystemExit(137))
    captured = _try_call(raise_, RuntimeError("per-machine"))
    assert isinstance(captured, RuntimeError)
    assert _try_call(lambda: None) is None


def test_fleet_build_fail_fast_true_raises_fleet_build_error():
    """fail_fast=True surfaces the first FleetBuildError instead of
    recording it: here a windowed (LSTM) model with scattered KFold CV
    folds, which have no clean window mapping."""
    from gordo_tpu.parallel.fleet_build import FleetBuildError

    machine = Machine.from_config(
        {
            "name": "ff-lstm",
            "model": {
                "gordo_tpu.models.JaxLSTMAutoEncoder": {
                    "kind": "lstm_symmetric",
                    "dims": [4],
                    "funcs": ["tanh"],
                    "lookback_window": 4,
                    "epochs": 1,
                }
            },
            "dataset": {**DATASET, "tag_list": ["t1", "t2"]},
            "evaluation": {
                "cv": {
                    "sklearn.model_selection.KFold": {
                        "n_splits": 3,
                        "shuffle": True,
                        "random_state": 0,
                    }
                }
            },
        },
        project_name="fleet-test",
    )
    with pytest.raises(FleetBuildError):
        FleetBuilder([machine], fail_fast=True).build()
    # failFast:false records the same failure instead of raising
    builder = FleetBuilder([machine])
    assert builder.build() == []
    assert isinstance(builder.build_errors["ff-lstm"], FleetBuildError)


def test_final_fit_divergence_retry_counts_into_metadata(monkeypatch):
    """FleetTrainer.train's diverged-member reseed retry must surface in
    the built machine's BuildMetadata robustness counters."""
    from gordo_tpu.parallel import FleetTrainer

    machine = make_machine("retry-meta", ["t1", "t2"])
    builder = FleetBuilder([machine])
    real = FleetTrainer._train_once
    state = {"poisoned": False}

    def poison_first_final_fit(self, members, config):
        results = real(self, members, config)
        # poison exactly one result once: the final-fit members carry the
        # machine name itself (CV fold members are name::foldN)
        if not state["poisoned"] and any(r.name == "retry-meta" for r in results):
            state["poisoned"] = True
            for r in results:
                if r.name == "retry-meta":
                    r.history.history["loss"] = [float("nan")]
        return results

    monkeypatch.setattr(FleetTrainer, "_train_once", poison_first_final_fit)
    results = builder.build()
    assert len(results) == 1
    _, built = results[0]
    robustness = built.metadata.build_metadata.robustness
    assert robustness.fleet_retries == 1
    assert builder.robustness["fleet_retries"] == 1
    estimator = results[0][0].base_estimator.steps[-1][1]
    assert np.isfinite(estimator._history.history["loss"][-1])


def test_fleet_build_fail_fast_true_raises():
    bad = Machine.from_config(
        {
            "name": "bad-machine",
            "model": DETECTOR_MODEL,
            "dataset": {
                **DATASET,
                "tag_list": ["tag-1"],
                "n_samples_threshold": 10_000_000,
            },
        },
        project_name="fleet-test",
    )
    from gordo_tpu.dataset.exceptions import InsufficientDataError

    with pytest.raises(InsufficientDataError):
        FleetBuilder([bad], fail_fast=True).build()


def test_fleet_build_register_failure_not_dumped(tmp_path, monkeypatch):
    """A machine that fails at the register step must not leave artifacts
    in output_dir (its build is an error, not a product)."""
    from gordo_tpu.builder.build_model import ModelBuilder

    good = make_machine("reg-good", ["t1", "t2"])
    doomed = make_machine("reg-doomed", ["t3", "t4"])
    register_dir = tmp_path / "register"
    output_dir = tmp_path / "out"

    original_register = ModelBuilder.register

    def failing_register(self, model, machine, register_directory):
        if machine.name == "reg-doomed":
            raise OSError("disk full")
        return original_register(self, model, machine, register_directory)

    monkeypatch.setattr(ModelBuilder, "register", failing_register)
    builder = FleetBuilder([good, doomed])
    results = builder.build(
        output_dir=str(output_dir), model_register_dir=str(register_dir)
    )
    assert [m.name for _, m in results] == ["reg-good"]
    assert set(builder.build_errors) == {"reg-doomed"}
    assert (output_dir / "reg-good" / "model.pkl").exists()
    assert not (output_dir / "reg-doomed").exists()


def test_cv_chunking_by_bytes_preserves_order():
    from gordo_tpu.parallel.fleet_build import _chunk_by_bytes
    from gordo_tpu.parallel import FleetMember
    from gordo_tpu.models.factories import feedforward_hourglass

    spec = feedforward_hourglass(4)
    members = [
        FleetMember(name=f"c{i}", spec=spec,
                    X=(X := np.zeros((50, 4), np.float32)), y=X, seed=i)
        for i in range(7)
    ]
    items = [(f"plan{i}", i % 3) for i in range(7)]
    per_member = members[0].X.nbytes  # y aliased -> not double-counted
    chunks = _chunk_by_bytes(members, items, budget=per_member * 3)
    assert [len(ms) for ms, _ in chunks] == [3, 3, 1]
    flat_items = [it for _, its in chunks for it in its]
    assert flat_items == items  # order preserved across chunk boundaries
    # a budget smaller than one member still yields 1-member chunks
    tiny = _chunk_by_bytes(members, items, budget=1)
    assert [len(ms) for ms, _ in tiny] == [1] * 7


def test_cv_chunk_split_retry_isolates_bad_machine(monkeypatch):
    """A fold bucket that fails as a whole must split-retry down to the
    bad machine: the healthy machines' CV still completes."""
    from gordo_tpu.parallel import FleetBuilder, FleetTrainer

    machines = [make_machine(f"split-{i}", ["t1", "t2"]) for i in range(3)]
    builder = FleetBuilder(machines)
    real_train = builder.trainer.train
    calls = {"n": 0}

    def flaky_train(members, config, **kwargs):
        calls["n"] += 1
        # fail any chunk containing the bad machine AND another member —
        # forcing the halving retry to isolate it
        names = [m.name for m in members]
        bad = [n for n in names if n.startswith("split-1")]
        if bad and len(names) > 1:
            raise RuntimeError("chunk-level failure")
        if bad:
            raise RuntimeError("bad machine alone")
        return real_train(members, config, **kwargs)

    monkeypatch.setattr(builder.trainer, "train", flaky_train)
    results = builder.build()
    names = {m.name for _, m in results}
    assert names == {"split-0", "split-2"}
    assert set(builder.build_errors) == {"split-1"}
    assert calls["n"] > 3  # the halving retry actually recursed


class TestRollingMinMax:
    """FleetBuilder._rolling_min_max replaced the per-(machine, fold)
    pandas rolling(w).min().max() threshold statistic; parity with the
    pandas expression is the contract (reference diff.py:196-212)."""

    @pytest.mark.parametrize("window", [1, 6, 144])
    @pytest.mark.parametrize("n", [4, 6, 150, 400])
    def test_series_parity(self, window, n):
        rng = np.random.RandomState(window * 1000 + n)
        values = rng.rand(n)
        expected = pd.Series(values).rolling(window).min().max()
        actual = FleetBuilder._rolling_min_max(values, window)
        if np.isnan(expected):
            assert np.isnan(actual)
        else:
            assert actual == pytest.approx(expected, rel=1e-12)

    @pytest.mark.parametrize("window", [6, 30])
    def test_frame_parity(self, window):
        rng = np.random.RandomState(7)
        values = rng.rand(200, 4)
        expected = pd.DataFrame(values).rolling(window).min().max().to_numpy()
        actual = FleetBuilder._rolling_min_max(values, window)
        np.testing.assert_allclose(actual, expected, rtol=1e-12)

    def test_nan_windows_skipped_like_pandas(self):
        values = np.array([1.0, 2.0, np.nan, 4.0, 5.0, 6.0, 7.0, 8.0])
        expected = pd.Series(values).rolling(3).min().max()
        actual = FleetBuilder._rolling_min_max(values, 3)
        assert actual == pytest.approx(expected)

    def test_all_nan_returns_nan(self):
        assert np.isnan(FleetBuilder._rolling_min_max(np.full(10, np.nan), 3))
